"""Headline benchmark: fused SDDMM+SpMM GFLOP/s per chip at R=128.

Prints ONE JSON line: {"metric": ..., "value": N, "unit": ..., "vs_baseline": N}.

Mirrors the reference's primary entry point `bench_erdos_renyi`
(`/root/reference/bench_erdos_renyi.cpp`) + `benchmark_algorithm`
(`/root/reference/benchmark_dist.cpp:117-149`): Graph500-style R-mat input,
fused SDDMM->SpMM pairs, throughput = 2*nnz*2*R*trials / elapsed.

Baseline denominator: the only absolute figure recoverable from the reference
repo is the weak-scaling point ~6.47 GFLOP/s (15d_sparse fused, 256 Cori-KNL
ranks; ipdps_chart_generator.ipynb cell 10, see BASELINE.md). vs_baseline is
value / 6.47 — i.e. this chip vs. a 256-rank Cori KNL job on the recoverable
number.
"""

import json
import os
import sys
import time


def main() -> None:
    import jax

    from distributed_sddmm_tpu.common import MatMode
    from distributed_sddmm_tpu.parallel.dense_shift_15d import DenseShift15D
    from distributed_sddmm_tpu.utils.coo import HostCOO

    log_m = int(os.environ.get("BENCH_LOG_M", "16"))
    nnz_per_row = int(os.environ.get("BENCH_NNZ_PER_ROW", "32"))
    R = int(os.environ.get("BENCH_R", "128"))
    trials = int(os.environ.get("BENCH_TRIALS", "5"))
    kernel_name = os.environ.get("BENCH_KERNEL", "auto")

    from distributed_sddmm_tpu.ops import get_kernel

    kernel = get_kernel(kernel_name)

    S = HostCOO.rmat(log_m=log_m, edge_factor=nnz_per_row, seed=0)
    n_dev = jax.device_count()
    c = 1
    alg = DenseShift15D(S, R=R, c=c, fusion_approach=2, kernel=kernel)

    import jax.numpy as jnp

    A = alg.dummy_initialize(MatMode.A)
    B = alg.like_b_matrix(0.01)
    s_vals = alg.like_s_values(1.0)

    # Trials are CHAINED (each consumes the previous output, scaled to keep
    # magnitudes finite) inside ONE jitted fori_loop ending in a scalar host
    # fetch. Rationale: on async/tunneled backends block_until_ready alone
    # does not force execution, independent same-input calls could be elided,
    # and per-call dispatch latency through a remote tunnel would otherwise
    # dominate the measurement; a single compiled data-dependent chain plus
    # one fetch times exactly the device work.
    pair = alg.fused_program(s_vals, MatMode.A)

    from functools import partial

    @partial(jax.jit, static_argnums=2)
    def chain(A_t, B, n):
        def body(_, A_t):
            out, _ = pair(A_t, B)
            return A_t + out * 1e-12
        return jax.lax.fori_loop(0, n, body, A_t)

    # Warmup / compile both trip counts.
    float(chain(A, B, 1).sum())
    float(chain(A, B, 1 + trials).sum())
    t0 = time.perf_counter()
    float(chain(A, B, 1).sum())
    t_one = time.perf_counter() - t0
    t0 = time.perf_counter()
    float(chain(A, B, 1 + trials).sum())
    elapsed = (time.perf_counter() - t0) - t_one

    # Reference throughput formula (`benchmark_dist.cpp:147-149`).
    flops = 2.0 * S.nnz * 2.0 * R * trials
    gflops = flops / elapsed / 1e9
    gflops_per_chip = gflops / n_dev

    baseline = 6.47  # GFLOP/s, see module docstring
    print(
        json.dumps(
            {
                "metric": f"fused SDDMM+SpMM GFLOP/s/chip (R-mat 2^{log_m}, "
                f"nnz/row={nnz_per_row}, R={R}, {kernel.name} kernel, "
                f"{n_dev} chip(s))",
                "value": round(gflops_per_chip, 3),
                "unit": "GFLOP/s/chip",
                "vs_baseline": round(gflops_per_chip / baseline, 3),
            }
        )
    )


if __name__ == "__main__":
    sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
    main()
