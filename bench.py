"""Headline benchmark: fused SDDMM+SpMM GFLOP/s per chip at R=128.

Prints ONE JSON line: {"metric": ..., "value": N, "unit": ..., "vs_baseline": N}.

Mirrors the reference's primary entry point `bench_erdos_renyi`
(`/root/reference/bench_erdos_renyi.cpp`) + `benchmark_algorithm`
(`/root/reference/benchmark_dist.cpp:117-149`): Graph500-style R-mat input,
fused SDDMM->SpMM pairs, throughput = 2*nnz*2*R*trials / elapsed.

Resilience: the TPU in this environment is reached through an experimental
tunnel whose backend init is flaky (it can raise UNAVAILABLE or hang
outright, including mid-run). A crash or hang in-process would leave the
driver with no number at all, so this script is split in two:

* orchestrator (default): launches the measurement as a ``--worker``
  subprocess with a hard timeout, retries the TPU attempt with backoff, and
  if the TPU never produces a result falls back to a CPU-backend run so a
  real (if slower) number always exists. Terminal failure still exits 0 with
  a JSON error record rather than a stack trace.
* worker (``--worker``): the actual chained-trial measurement. Trials are
  data-dependently chained inside one jitted fori_loop ending in a scalar
  host fetch, because on the tunneled backend ``block_until_ready`` alone
  does not force execution and per-dispatch latency would otherwise dominate.

Baseline denominator: the only absolute figure recoverable from the reference
repo is the weak-scaling point ~6.47 GFLOP/s (15d_sparse fused, 256 Cori-KNL
ranks; ipdps_chart_generator.ipynb cell 10, see BASELINE.md). vs_baseline is
value / 6.47 — i.e. this chip vs. a 256-rank Cori KNL job on the recoverable
number.
"""

import json
import os
import signal
import subprocess
import sys
import time

BASELINE_GFLOPS = 6.47  # see module docstring


def make_headline_chain(prog, n: int):
    """The chained-trials headline program for one trip count: the full
    fused shard_map program applied ``n`` times with a data dependence
    between passes. Every device buffer is an ARGUMENT (not a closure
    capture) so the identical computation can be AOT-compiled in an
    offline process and loaded here (`scripts/aot_compile_bench.py`)."""
    import jax

    @jax.jit
    def chain(A_t, B, *targs):
        def body(_, A_t):
            out, _mid = prog(A_t, B, *targs)
            return A_t + out * 1e-12

        return jax.lax.fori_loop(0, n, body, A_t)

    return chain


def build_headline(kernel, devices=None):
    """Construct the headline benchmark's strategy and operands (shared
    with the offline AOT compiler, which retargets the mesh afterwards).
    Returns (alg, prog, A, B, targs)."""
    from distributed_sddmm_tpu.common import MatMode
    from distributed_sddmm_tpu.parallel.dense_shift_15d import DenseShift15D
    from distributed_sddmm_tpu.utils.coo import HostCOO

    log_m = int(os.environ.get("BENCH_LOG_M", "16"))
    nnz_per_row = int(os.environ.get("BENCH_NNZ_PER_ROW", "32"))
    R = int(os.environ.get("BENCH_R", "128"))

    S = HostCOO.rmat(log_m=log_m, edge_factor=nnz_per_row, seed=0)
    alg = DenseShift15D(S, R=R, c=1, fusion_approach=2, kernel=kernel,
                        devices=devices)
    A = alg.dummy_initialize(MatMode.A)
    B = alg.like_b_matrix(0.01)
    s_vals = alg.like_s_values(1.0)
    prog = alg._program("fused", use_st=False)
    targs = alg._tile_args(alg.S_tiles, s_vals)
    return alg, prog, A, B, targs


def worker() -> None:
    """The measurement itself; runs in a subprocess under the orchestrator."""
    if os.environ.get("BENCH_PLATFORM", "") == "cpu":
        from distributed_sddmm_tpu.utils.platform import force_cpu_platform

        force_cpu_platform()

    import jax

    from distributed_sddmm_tpu.ops import get_kernel

    log_m = int(os.environ.get("BENCH_LOG_M", "16"))
    nnz_per_row = int(os.environ.get("BENCH_NNZ_PER_ROW", "32"))
    R = int(os.environ.get("BENCH_R", "128"))
    trials = int(os.environ.get("BENCH_TRIALS", "5"))
    kernel_name = os.environ.get("BENCH_KERNEL", "auto")

    kernel = get_kernel(kernel_name)

    n_dev = jax.device_count()
    alg, prog, A, B, targs = build_headline(kernel)
    nnz = alg.S_tiles.nnz

    # Pre-serialized AOT executables (offline compile — Mosaic or flat XLA
    # depending on the rung's kernel) when the orchestrator validated loads
    # on this backend; on-device jit otherwise or on ANY failure along the
    # AOT path.
    chains = None
    used_aot = False
    aot_dir = os.environ.get("BENCH_AOT_DIR", "")
    # The offline compiler targets ONE topology device; a multi-chip mesh
    # would need matching shardings it doesn't build. The probe validated
    # this backend, but only the single-device case.
    if aot_dir and n_dev == 1:
        try:
            from distributed_sddmm_tpu.bench import aot

            # The offline compiler lowers with the same positional args the
            # jitted chain takes, so the loaded callables are drop-ins.
            chains = aot.load_chain_pair(aot_dir, "headline", trials,
                                         jax.devices()[0])
            # Probe one real execution NOW: runtime incompatibilities must
            # degrade to on-device compile, not kill the attempt.
            float(chains[1](A, B, *targs).sum())
            used_aot = True
        except Exception as e:  # noqa: BLE001 — fall back to on-device jit
            print(f"[bench-worker] AOT path failed ({type(e).__name__}: "
                  f"{e}); compiling on-device", file=sys.stderr)
            chains = None
    if chains is None:
        chains = {n: make_headline_chain(prog, n) for n in (1, 1 + trials)}

    def run(n):
        return float(chains[n](A, B, *targs).sum())

    # Warmup / compile both trip counts, then time by difference so the
    # constant per-fetch overhead cancels.
    run(1)
    run(1 + trials)
    t0 = time.perf_counter()
    run(1)
    t_one = time.perf_counter() - t0
    t0 = time.perf_counter()
    run(1 + trials)
    t_full = time.perf_counter() - t0
    elapsed = t_full - t_one
    if elapsed <= 0:
        # Difference timing can go negative under dispatch noise at tiny
        # sizes; fall back to assuming uniform per-iteration cost.
        elapsed = t_full * trials / (1 + trials)

    # Reference throughput formula (`benchmark_dist.cpp:147-149`).
    flops = 2.0 * nnz * 2.0 * R * trials
    gflops = flops / elapsed / 1e9
    gflops_per_chip = gflops / n_dev

    rec = {
        "metric": f"fused SDDMM+SpMM GFLOP/s/chip (R-mat 2^{log_m}, "
        f"nnz/row={nnz_per_row}, R={R}, {kernel.name} kernel, "
        f"{n_dev} {jax.default_backend()} chip(s))",
        "value": round(gflops_per_chip, 3),
        "unit": "GFLOP/s/chip",
        "vs_baseline": round(gflops_per_chip / BASELINE_GFLOPS, 3),
        "backend": jax.default_backend(),
    }
    if used_aot:
        rec["aot"] = True
    print(json.dumps(rec))


def _headline_pallas_records() -> list:
    """Pallas records from KERNELS_TPU.jsonl matching the headline
    (logM, nnz/row, R) config, malformed lines skipped."""
    path = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                        "KERNELS_TPU.jsonl")
    want = (
        int(os.environ.get("BENCH_LOG_M", "16")),
        int(os.environ.get("BENCH_NNZ_PER_ROW", "32")),
        int(os.environ.get("BENCH_R", "128")),
    )
    recs = []
    try:
        with open(path) as f:
            for line in f:
                try:
                    r = json.loads(line)
                except json.JSONDecodeError:
                    continue
                if not str(r.get("kernel", "")).startswith("pallas"):
                    continue
                if (r.get("logM"), r.get("npr"), r.get("R")) == want:
                    recs.append(r)
    except OSError:
        pass
    return recs


def _best_measured_env() -> dict | None:
    """Env overrides from the best Pallas record in KERNELS_TPU.jsonl for the
    headline config, so the sweep's tuning carries into the headline number.
    Returns None when no matching record exists (fresh checkout / pre-sweep)."""
    best = None
    for r in _headline_pallas_records():
        g = r.get("fused_pair_gflops")
        if g and (best is None or g > best.get("fused_pair_gflops", 0)):
            best = r
    if best is None or "bm" not in best:
        return None
    return {
        "DSDDMM_BLOCK_ROWS": str(best["bm"]),
        "DSDDMM_BLOCK_COLS": str(best["bn"]),
        "DSDDMM_CHUNK_GROUP": str(best.get("group", 1)),
        "DSDDMM_SCATTER_FORM": best.get("scatter_form", "bt"),
        "DSDDMM_CHUNK": str(best.get("chunk", 128)),
        "DSDDMM_BATCH_STEP": "1" if best.get("batch_step") else "0",
    }


_AOT_GATE = None


def _aot_gate():
    """The shared AOT-gate policy module, imported from its FILE — going
    through the package would execute distributed_sddmm_tpu/__init__ and
    pull jax into this deliberately backend-free orchestrator process."""
    global _AOT_GATE
    if _AOT_GATE is None:
        import importlib.util

        p = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                         "distributed_sddmm_tpu", "bench", "aot_gate.py")
        spec = importlib.util.spec_from_file_location("_aot_gate_file", p)
        mod = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(mod)
        _AOT_GATE = mod
    return _AOT_GATE


def _aot_validated(program: str | None = None) -> bool:
    """AOT_LOAD.json (scripts/aot_load_probe.py) recorded that re-homed
    executables load correctly on this backend. ``program`` gates on one
    probe program ("pallas_fused"/"xla_matmul") so one program's failure
    doesn't foreclose AOT mode for the other; no argument = ALL programs.
    Policy lives in aot_gate (shared with the sweep/apps/dist-gap)."""
    if os.environ.get("BENCH_NO_AOT", "") not in ("", "0"):
        return False
    gate = _aot_gate()
    rep = gate.load_verdict(
        os.path.join(os.path.dirname(os.path.abspath(__file__)),
                     "AOT_LOAD.json"))
    return gate.probe_validated(rep, program)


def _bench_code_hash() -> str:
    """Fingerprint of the sources that determine the headline program, so
    stale serialized executables are never timed as current code. Every
    package source is hashed — enumerating 'the files that matter' proved
    error-prone (ring/ablation/ingest code all shape the program), and
    over-invalidation only costs a ~3s local recompile."""
    import hashlib
    import pathlib

    here = pathlib.Path(os.path.dirname(os.path.abspath(__file__)))
    h = hashlib.sha256()
    files = [here / "bench.py", here / "scripts" / "aot_compile_bench.py"]
    files += sorted((here / "distributed_sddmm_tpu").rglob("*.py"))
    for f in files:
        h.update(f.read_bytes())
    return h.hexdigest()[:10]


def _maybe_aot_dir(env_extra: dict, timeout_s: float = 420.0) -> str | None:
    """Offline-compile the headline chain for this attempt's knobs and
    return the cache dir for BENCH_AOT_DIR — or None for on-device compile
    (not validated / compile failed / CPU rung). TPU rungs of BOTH kernels
    qualify — the Mosaic-outage rescue rung gets a flat XLA program."""
    # Kernel resolved from the MERGED env — the worker and the cache key
    # both see os.environ ∪ env_extra, and the gate must agree with them.
    merged_kernel = {**os.environ, **env_extra}.get("BENCH_KERNEL", "auto")
    if env_extra.get("BENCH_PLATFORM") == "cpu" or not _aot_validated(
            _aot_gate().probe_program(merged_kernel)):
        return None
    here = os.path.dirname(os.path.abspath(__file__))
    env = dict(os.environ)
    env.update(env_extra)
    # Knob names come from blocked.py's canonical dict (plus the BENCH_*
    # grid knobs) so a new kernel knob can't silently share cache dirs.
    from distributed_sddmm_tpu.ops.blocked import knob_env_defaults

    key_names = ("BENCH_LOG_M", "BENCH_NNZ_PER_ROW", "BENCH_R",
                 "BENCH_TRIALS", "BENCH_KERNEL") + tuple(
                     sorted(knob_env_defaults()))
    knobs = "_".join(
        f"{k.rsplit('_', 1)[-1]}{env.get(k, '')}" for k in key_names)
    out_dir = os.path.join(here, "artifacts", "aot_bench",
                           f"{knobs}_{_bench_code_hash()}")
    meta = os.path.join(out_dir, "meta.json")
    if os.path.exists(meta):
        try:
            with open(meta) as f:
                return out_dir if json.load(f).get("ok") else None
        except (OSError, json.JSONDecodeError):
            return None
    env["JAX_PLATFORMS"] = "cpu"

    def record_failure(reason: str):
        # Negative cache: a deterministic local compile failure must not
        # re-spend its timeout on every bench invocation.
        os.makedirs(out_dir, exist_ok=True)
        with open(meta, "w") as f:
            json.dump({"ok": False, "error": reason}, f)

    try:
        proc = subprocess.run(
            [sys.executable, os.path.join(here, "scripts",
                                          "aot_compile_bench.py"), out_dir],
            env=env, capture_output=True, text=True, timeout=timeout_s)
    except subprocess.TimeoutExpired:
        # One timeout is not a deterministic failure — it may be this
        # machine's load spike, or a capped remaining-window budget.
        # aot_gate.timeout_strike tombstones only after two strikes from
        # INDEPENDENT episodes (>=30 min apart; bench and dist_gap share
        # this cache dir, so same-spike strikes must not compound).
        print("[bench] AOT precompile timed out; on-device compile",
              file=sys.stderr)
        if _aot_gate().timeout_strike(out_dir,
                                      full_budget=timeout_s >= 420.0):
            record_failure(f"repeated timeouts ({timeout_s:.0f}s budget)")
        return None
    if proc.returncode != 0 or not os.path.exists(meta):
        tail = (proc.stderr or "").strip().splitlines()[-3:]
        print(f"[bench] AOT precompile failed (rc={proc.returncode}, {tail}); "
              "on-device compile", file=sys.stderr)
        if proc.returncode >= 0 and not os.path.exists(meta):
            # Negative rc = signal kill (transient); an existing meta is
            # the compiler's own verdict — never clobber it with ours.
            record_failure(f"rc={proc.returncode}: {tail}")
        return None
    return out_dir


def _run_attempt(env_extra: dict, timeout_s: float) -> dict | None:
    """Run one worker subprocess; return its JSON record or None.

    The worker runs in its own session so a timeout kills the whole process
    GROUP — the tunneled backend spawns helper processes that would otherwise
    inherit our pipes and keep ``communicate()`` blocked past the kill.
    """
    env = dict(os.environ)
    env.update(env_extra)
    proc = subprocess.Popen(
        [sys.executable, os.path.abspath(__file__), "--worker"],
        env=env,
        stdout=subprocess.PIPE,
        stderr=subprocess.PIPE,
        text=True,
        start_new_session=True,
    )
    try:
        stdout, stderr = proc.communicate(timeout=timeout_s)
    except subprocess.TimeoutExpired:
        try:
            os.killpg(proc.pid, signal.SIGKILL)
        except (ProcessLookupError, PermissionError):
            proc.kill()
        stderr = ""
        try:
            _, stderr = proc.communicate(timeout=10)
        except subprocess.TimeoutExpired:
            pass
        print(f"[bench] attempt timed out after {timeout_s:.0f}s", file=sys.stderr)
        for ln in (stderr or "").strip().splitlines()[-15:]:
            print(f"[bench]   {ln}", file=sys.stderr)
        return None
    for line in reversed((stdout or "").strip().splitlines()):
        try:
            rec = json.loads(line)
            if isinstance(rec, dict) and "value" in rec:
                return rec
        except json.JSONDecodeError:
            continue
    tail = (stderr or "").strip().splitlines()[-15:]
    print(
        f"[bench] attempt rc={proc.returncode}, no JSON record; stderr tail:",
        file=sys.stderr,
    )
    for ln in tail:
        print(f"[bench]   {ln}", file=sys.stderr)
    return None


def main() -> None:
    # Attempt schedule: TPU at chunk-group 4 then chunk-group 1 (the kernel
    # grid step batching is config-dependent; print the best TPU record),
    # with a retry, then a CPU fallback so the driver always records a real
    # measurement. Everything fits inside ONE total wall-clock budget with
    # the tail reserved for the CPU fallback — an external harness timeout
    # must never land before the fallback has had its chance.
    total = float(os.environ.get("BENCH_TOTAL_TIMEOUT", "2100"))
    backoff = float(os.environ.get("BENCH_BACKOFF", "20"))
    start = time.monotonic()
    # The queue's mid-round banking run sets this: a CPU record can never
    # be banked, so skipping the fallback rung hands its reserve to the
    # TPU rungs instead of burning health-window minutes on a throwaway.
    skip_cpu = os.environ.get("BENCH_SKIP_CPU_FALLBACK", "") not in ("", "0")
    cpu_reserve = 0.0 if skip_cpu else min(600.0, total / 3)
    tpu_budget = total - cpu_reserve

    cpu_env = {"BENCH_PLATFORM": "cpu", "BENCH_KERNEL": "xla"}
    tuned = _best_measured_env()
    attempts = [
        ({"DSDDMM_CHUNK_GROUP": "4"}, tpu_budget * 0.4, 0.0),
        ({"DSDDMM_CHUNK_GROUP": "1"}, tpu_budget * 0.3, 0.0),
        # TPU with the XLA kernel: survives outages of the separate Mosaic
        # (Pallas) compile service — slower kernel, same real chip.
        ({"BENCH_KERNEL": "xla"}, tpu_budget * 0.3 - backoff, backoff),
        (cpu_env, cpu_reserve, 0.0),
    ]
    # What the first fixed rung actually resolves to: its own env_extra over
    # whatever the parent process exported, over blocked.py's defaults —
    # read from blocked.py itself so the dedup can't drift from the knobs.
    from distributed_sddmm_tpu.ops.blocked import knob_env_defaults

    first_rung_effective = {**knob_env_defaults(), **attempts[0][0]}
    if tuned is not None and tuned != first_rung_effective:
        # Lead with the sweep's best (blocks, group, scatter) combination;
        # the fixed-group rungs stay as fallbacks (and as a regression check
        # that the tuned setting really is the fastest). When the best IS
        # what the first rung would run anyway, don't measure it twice.
        attempts.insert(0, (tuned, tpu_budget * 0.4, 0.0))
    best = None
    errors = 0
    for env_extra, timeout_s, backoff_s in attempts:
        if backoff_s and errors:
            time.sleep(backoff_s)
        remaining = total - (time.monotonic() - start)
        is_cpu = env_extra.get("BENCH_PLATFORM") == "cpu"
        if is_cpu and skip_cpu:
            continue
        if env_extra.get("BENCH_KERNEL") == "xla" and best is not None:
            continue  # the XLA rung is a Mosaic-outage rescue, never faster
        if not is_cpu:
            if best is not None and remaining < cpu_reserve + 120:
                break  # have a TPU record; don't risk the budget tail
            # Precompile the chain offline when AOT loads are validated —
            # the worker then spends the window measuring, not compiling.
            # Charged against the same budget: cap by what's left above
            # the fallback reserve and re-measure afterwards.
            aot_budget = remaining - cpu_reserve - 60
            if aot_budget > 30:
                aot_dir = _maybe_aot_dir(
                    env_extra, timeout_s=min(420.0, aot_budget))
                if aot_dir:
                    env_extra = {**env_extra, "BENCH_AOT_DIR": aot_dir}
                remaining = total - (time.monotonic() - start)
            # Never let a TPU attempt eat into the fallback reserve.
            timeout_s = min(timeout_s, remaining - cpu_reserve)
            if timeout_s < 30:
                continue
        else:
            if best is not None:
                break  # CPU fallback only matters when TPU never delivered
            timeout_s = min(timeout_s, max(remaining, 60.0))
        rec = _run_attempt(env_extra, timeout_s)
        if rec is not None:
            if is_cpu:
                mid = _midround_tpu_record()
                if mid is not None:
                    # The hardware DID answer this round, just not right
                    # now: the queue's healthy-window headline run is this
                    # round's real-TPU measurement of the same program.
                    mid["note"] = (
                        "TPU backend unavailable at bench time; value is "
                        "this round's committed mid-round real-TPU run "
                        "(artifacts/bench_midround/record.json); the "
                        f"live CPU fallback measured {rec['value']} "
                        f"{rec['unit']}"
                    )
                    best = mid
                    break
                rec["note"] = (
                    "TPU backend unavailable after retries; CPU fallback run"
                    + _committed_tpu_note()
                )
                best = rec
                break
            if best is None or rec["value"] > best["value"]:
                best = rec
        else:
            errors += 1
    if best is not None:
        # Stamped so a banked copy of this record can later prove it
        # measured these exact sources (see _midround_tpu_record).
        best.setdefault("code_hash", _bench_code_hash())
        print(json.dumps(best))
        return
    mid = _midround_tpu_record()
    if mid is not None:
        mid["note"] = (
            "all live bench attempts failed or timed out; value is this "
            "round's committed mid-round real-TPU run "
            "(artifacts/bench_midround/record.json)"
        )
        print(json.dumps(mid))
        return
    print(
        json.dumps(
            {
                "metric": "fused SDDMM+SpMM GFLOP/s/chip (all backends failed)",
                "value": 0.0,
                "unit": "GFLOP/s/chip",
                "vs_baseline": 0.0,
                "note": "TPU and CPU bench attempts all failed or timed out"
                + _committed_tpu_note(),
            }
        )
    )


def _midround_tpu_record(path: str | None = None) -> dict | None:
    """A banked headline record from a mid-round healthy window (written
    by the queue's headline step via --validate-midround). Lets a round
    whose health window closed before bench time still report the number
    the hardware produced. Valid only when the measuring backend was
    really the TPU AND the record's code_hash matches the CURRENT
    sources — a banked number must never masquerade as a measurement of
    code it didn't run (including a previous round's record surviving in
    artifacts/)."""
    if path is None:
        path = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                            "artifacts", "bench_midround", "record.json")
    try:
        with open(path) as f:
            rec = json.loads(f.read().strip().splitlines()[-1])
    except (OSError, json.JSONDecodeError, IndexError):
        return None
    if rec.get("backend") != "tpu" or not rec.get("value", 0) > 0:
        return None
    if rec.get("code_hash") != _bench_code_hash():
        return None
    return rec


def _committed_tpu_note() -> str:
    """Pointer to the best committed real-hardware measurement at the
    HEADLINE config, so a tunnel-outage fallback record still cites the
    evidence that exists."""
    gs = [r.get("fused_pair_gflops") for r in _headline_pallas_records()]
    gs = [g for g in gs if g]
    if not gs:
        return ""
    return (
        f"; best committed real-TPU tile measurement at this config: "
        f"{max(gs):.1f} GFLOP/s fused pair (KERNELS_TPU.jsonl)"
    )


if __name__ == "__main__":
    sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
    if "--worker" in sys.argv:
        worker()
    elif "--validate-midround" in sys.argv:
        # Bankability check for the queue: ONE validator (shared with the
        # fallback reader) decides what counts as a real-TPU record.
        target = sys.argv[sys.argv.index("--validate-midround") + 1]
        sys.exit(0 if _midround_tpu_record(target) is not None else 1)
    else:
        main()
