"""Multi-chip TPU lowering evidence for the BLOCKED (Pallas) programs.

`run.py` censuses the XLA-kernel shard_map programs AOT-compiled for a real
v5e 2x4 topology; this companion does the same for the production kernel
path — the blocked chunk-list Pallas programs each strategy builds when its
kernel `is_blocked` (including their `check_vma=False` shard_map wrapping,
`dense_shift_15d.py`). The round-3 verdict flagged that the collective-
parity and async-permute claims only covered the flat XLA programs; this
closes that gap: same collectives table, now for the code path that would
actually run on a pod.

Strategy instances are constructed on a CPU mesh with the INTERPRET Pallas
kernel (tile ingest builds the chunk-list metadata); lowering then swaps in
the real Mosaic kernel (`interpret=False`, bf16) and retargets a topology
mesh, with every operand passed as a ShapeDtypeStruct. Compilation invokes
the real Mosaic/TPU compiler — no chips needed, but in this environment the
Mosaic compile can route through the tunnel, so callers should wrap this in
a timeout (the queue does).

Run from repo root: XLA_FLAGS=--xla_force_host_platform_device_count=8 \
    python artifacts/multichip_hlo/run_pallas.py
"""

from __future__ import annotations

import importlib.util
import json
import pathlib
import sys

sys.path.insert(0, str(pathlib.Path(__file__).resolve().parents[2]))

import jax

jax.config.update("jax_platforms", "cpu")

from jax.experimental import topologies

from distributed_sddmm_tpu.bench.harness import make_algorithm
from distributed_sddmm_tpu.common import MatMode
from distributed_sddmm_tpu.ops.pallas_kernels import PallasKernel
from distributed_sddmm_tpu.parallel.mesh import GridSpec, make_grid
from distributed_sddmm_tpu.utils.coo import HostCOO

HERE = pathlib.Path(__file__).parent

_spec = importlib.util.spec_from_file_location("mc_hlo_run", HERE / "run.py")
_run = importlib.util.module_from_spec(_spec)
# run.py's import side effects (jax.config cpu) are idempotent here; main()
# is not executed.
_spec.loader.exec_module(_run)
census, sds_like, TOPOLOGY = _run.census, _run.sds_like, _run.TOPOLOGY

# name -> (op, use_st, call-arg composer mirroring the public op methods'
# dense-arg order: fused_spmm/spmm_a/sddmm_a in each strategy module).
PLANS = {
    "15d_fusion2": (
        "fused", lambda alg, A, B, v: (A, B, *alg._tile_args(alg.S_tiles, v))),
    "15d_sparse": (
        "spmm", lambda alg, A, B, v: (B, *alg._spmm_args(alg.S_tiles, v))),
    "25d_dense_replicate": (
        "sddmm", lambda alg, A, B, v: (B, A, *alg._sddmm_args(alg.S_tiles, v))),
    "25d_sparse_replicate": (
        "spmm", lambda alg, A, B, v: (A, B, *alg._spmm_args(alg.S_tiles, v))),
}


def main() -> int:
    cpu = jax.devices()[:8]
    assert len(cpu) == 8, "need XLA_FLAGS=--xla_force_host_platform_device_count=8"
    topo = topologies.get_topology_desc(platform="tpu", topology_name=TOPOLOGY)

    S = HostCOO.rmat(log_m=10, edge_factor=8, seed=0)
    R, c = 32, 2
    report = {"topology": TOPOLOGY, "M": S.M, "nnz": S.nnz, "R": R, "c": c,
              "kernel": "pallas-bf16 blocked (check_vma=False shard_map)",
              "programs": {}}
    for name, (op, compose) in PLANS.items():
        alg = make_algorithm(
            name, S, R, c, devices=cpu,
            kernel=PallasKernel(precision="f32", interpret=True),
        )
        tiles = alg.S_tiles
        assert alg._use_blocked(tiles), f"{name}: tiles lack chunk metadata"
        A = alg.dummy_initialize(MatMode.A)
        B = alg.dummy_initialize(MatMode.B)
        vals = alg.like_s_values(1.0)
        call_args = compose(alg, A, B, vals)

        g = alg.grid
        tpu_grid = make_grid(g.nr, g.nc, g.nh, adjacency=g.adjacency,
                             devices=list(topo.devices))
        alg.grid = GridSpec(mesh=tpu_grid.mesh, nr=g.nr, nc=g.nc, nh=g.nh,
                            adjacency=g.adjacency)
        alg.kernel = PallasKernel(precision="bf16", interpret=False)
        alg._programs.clear()
        prog = alg._program(op, False)
        mesh = alg.grid.mesh

        args = tuple(sds_like(a, mesh) for a in call_args)
        compiled = prog.lower(*args).compile()
        hlo = compiled.as_text()
        mem = compiled.memory_analysis()
        entry = {
            "op": op,
            "collectives": census(hlo),
            "mosaic_custom_calls": hlo.count('custom_call_target="tpu_custom_call"'),
            "is_scheduled": "is_scheduled=true" in hlo,
        }
        if mem is not None:
            entry["memory"] = {
                "argument_bytes": int(getattr(mem, "argument_size_in_bytes", 0)),
                "output_bytes": int(getattr(mem, "output_size_in_bytes", 0)),
                "temp_bytes": int(getattr(mem, "temp_size_in_bytes", 0)),
            }
        report["programs"][name] = entry
        print(name, json.dumps(entry["collectives"]),
              f"mosaic_calls={entry['mosaic_custom_calls']}", flush=True)

    (HERE / "report_pallas.json").write_text(json.dumps(report, indent=2))
    print(f"wrote {HERE / 'report_pallas.json'}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
