"""Multi-chip TPU lowering evidence: AOT-compile the distributed programs
for a REAL v5e 2x4 (8-chip) topology and census the result.

`__graft_entry__.dryrun_multichip` proves numerics on a CPU mesh; this
artifact proves the same shard_map programs compile and schedule for actual
TPU hardware (`jax.experimental.topologies` — no chips needed): which
collectives each algorithm lowers to (the MPI-primitive parity table of
SURVEY.md section 2), whether ring permutes become async start/done pairs,
and the compiler's per-device memory figures.

Strategies are constructed on a CPU mesh (tile ingest needs real buffers);
lowering then retargets a topology mesh of the same shape, with tile
operands passed as ShapeDtypeStructs. XLA local kernels only — Pallas
kernels compile through a separate Mosaic service exercised by the kernel
sweep instead.

Run from repo root: XLA_FLAGS=--xla_force_host_platform_device_count=8 \
    python artifacts/multichip_hlo/run.py
"""

from __future__ import annotations

import json
import pathlib
import re
import sys

sys.path.insert(0, str(pathlib.Path(__file__).resolve().parents[2]))

import jax

jax.config.update("jax_platforms", "cpu")

import numpy as np
from jax.experimental import topologies

from distributed_sddmm_tpu.bench.harness import make_algorithm
from distributed_sddmm_tpu.parallel.mesh import GridSpec, make_grid
from distributed_sddmm_tpu.utils.coo import HostCOO

HERE = pathlib.Path(__file__).parent
TOPOLOGY = "v5e:2x4"

COLLECTIVES = (
    "all-gather", "reduce-scatter", "all-reduce",
    "collective-permute-start", "collective-permute-done",
    "collective-permute",
)


def census(hlo: str) -> dict:
    counts = {}
    rest = hlo
    # Longest names first so e.g. -start doesn't count into the plain name;
    # `name(` only occurs at op applications (operand references carry a
    # `.N` suffix instead of the open paren).
    for name in COLLECTIVES:
        n = len(re.findall(rf"{re.escape(name)}\(", rest))
        counts[name] = n
        rest = rest.replace(f"{name}(", "<counted>(")
    return counts


def sds_like(x, mesh):
    sharding = jax.sharding.NamedSharding(mesh, x.sharding.spec)
    return jax.ShapeDtypeStruct(x.shape, x.dtype, sharding=sharding)


def main() -> int:
    cpu = jax.devices()[:8]
    assert len(cpu) == 8, "need XLA_FLAGS=--xla_force_host_platform_device_count=8"
    topo = topologies.get_topology_desc(platform="tpu", topology_name=TOPOLOGY)

    S = HostCOO.rmat(log_m=10, edge_factor=8, seed=0)
    R, c = 32, 2
    plans = {
        "15d_fusion2": ("fused", False),
        "15d_sparse": ("spmm", False),
        "25d_dense_replicate": ("sddmm", True),
        "25d_sparse_replicate": ("spmm", True),
    }
    report = {"topology": TOPOLOGY, "M": S.M, "nnz": S.nnz, "R": R, "c": c,
              "programs": {}}
    for name, (op, use_st) in plans.items():
        alg = make_algorithm(name, S, R, c, devices=cpu)
        g = alg.grid
        tpu_grid = make_grid(g.nr, g.nc, g.nh, adjacency=g.adjacency,
                             devices=list(topo.devices))
        # Retarget program construction at the TPU topology mesh.
        alg.grid = GridSpec(mesh=tpu_grid.mesh, nr=g.nr, nc=g.nc, nh=g.nh,
                            adjacency=g.adjacency)
        alg._programs.clear()
        prog = alg._program(op, use_st)
        mesh = alg.grid.mesh

        tiles = alg.ST_tiles if use_st else alg.S_tiles
        dense = alg.dummy_initialize  # noqa: F841 — shapes via dense_shape
        import jax.numpy as jnp

        def dense_sds(mode):
            spec = alg.a_spec if mode == "A" else alg.b_spec
            from distributed_sddmm_tpu.common import MatMode

            shape = alg.dense_shape(MatMode.A if mode == "A" else MatMode.B)
            return jax.ShapeDtypeStruct(
                shape, jnp.float32,
                sharding=jax.sharding.NamedSharding(mesh, spec),
            )

        vals = sds_like(tiles.mask if hasattr(tiles, "mask") else tiles.rows, mesh)
        if hasattr(tiles, "mask_owned"):
            vals = sds_like(tiles.mask_owned, mesh)
        t_args = tuple(
            sds_like(a, mesh)
            for a in (tiles.rows, tiles.cols)
        )
        mask_sds = sds_like(tiles.mask, mesh)

        if name == "15d_fusion2":
            args = (dense_sds("A"), dense_sds("B"), *t_args, mask_sds)
        elif name == "15d_sparse":
            args = (dense_sds("B"), *t_args, vals)
        elif name == "25d_dense_replicate":
            args = (dense_sds("B"), dense_sds("A"), *t_args, mask_sds, mask_sds)
        else:  # 25d_sparse_replicate spmm: (a_role, b_role, rows, cols, vals)
            args = (dense_sds("A"), dense_sds("B"), *t_args, vals)

        compiled = prog.lower(*args).compile()
        hlo = compiled.as_text()
        mem = compiled.memory_analysis()
        entry = {
            "op": op,
            "collectives": census(hlo),
            "is_scheduled": "is_scheduled=true" in hlo,
        }
        if mem is not None:
            entry["memory"] = {
                "argument_bytes": int(getattr(mem, "argument_size_in_bytes", 0)),
                "output_bytes": int(getattr(mem, "output_size_in_bytes", 0)),
                "temp_bytes": int(getattr(mem, "temp_size_in_bytes", 0)),
            }
        report["programs"][name] = entry
        print(name, json.dumps(entry["collectives"]), flush=True)

    (HERE / "report.json").write_text(json.dumps(report, indent=2))
    print(f"wrote {HERE / 'report.json'}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
