"""Application + heatmap benchmark records on the 8-device CPU test mesh.

The distributed-structure complement to APPS_TPU.jsonl (which carries the
single-chip hardware numbers): ALS-CG and GAT app benchmarks plus the
R-sweep heatmap run through the full multi-device shard_map programs —
every collective real — on the virtual CPU mesh, then rendered by the chart
pipeline. Absolute times are not hardware-meaningful (single host core);
the artifact evidences the app paths end-to-end at p=8 and feeds
`tools/charts.py` (reference `benchmark_dist.cpp:88-163`,
`bench_heatmap.cpp:33-35`).

Run from repo root:
    XLA_FLAGS=--xla_force_host_platform_device_count=8 \
    python artifacts/cpu_mesh/run.py
"""

from __future__ import annotations

import pathlib
import sys

sys.path.insert(0, str(pathlib.Path(__file__).resolve().parents[2]))

import jax

jax.config.update("jax_platforms", "cpu")

from distributed_sddmm_tpu.bench.cli import main as bench_main
from distributed_sddmm_tpu.tools.charts import main as charts_main

HERE = pathlib.Path(__file__).parent
RECORDS = HERE / "records.jsonl"

RECORDS.unlink(missing_ok=True)

# Applications (reference app selection, `benchmark_dist.cpp:88-100`).
for app in ("als", "gat"):
    rc = bench_main([
        "er", "10", "8", "15d_fusion2", "16", "2",
        "--app", app, "--trials", "2", "--kernel", "xla",
        "-o", str(RECORDS),
    ])
    assert rc == 0, app

# Heatmap R-sweep over two contrasting strategies
# (`bench_heatmap.cpp:33-35`, scaled to the single-core host).
rc = bench_main([
    "heatmap", "10", "8", "2", "--alg", "15d_fusion2",
    "--r-values", "32", "64", "128", "--trials", "2", "--kernel", "xla",
    "-o", str(RECORDS),
])
assert rc == 0
rc = bench_main([
    "heatmap", "10", "8", "2", "--alg", "25d_sparse_replicate",
    "--r-values", "32", "64", "128", "--trials", "2", "--kernel", "xla",
    "-o", str(RECORDS),
])
assert rc == 0

rc = charts_main([str(RECORDS), "-o", str(HERE / "charts")])
assert rc == 0
print("cpu_mesh bench artifact complete", flush=True)
