"""Real-graph workflow chain artifact (reference `bench_file.cpp` +
`random_permute.cpp:42-57`): synthetic power-law graph -> native .mtx write
-> `permute` -> `file` bench of every algorithm on the 8-device CPU mesh
with region breakdown -> chart render. Run from repo root:

    XLA_FLAGS=--xla_force_host_platform_device_count=8 \
    python artifacts/realgraph/run.py
"""
import pathlib
import sys

sys.path.insert(0, str(pathlib.Path(__file__).resolve().parents[2]))

import jax

jax.config.update("jax_platforms", "cpu")

HERE = pathlib.Path(__file__).parent
RECORDS = HERE / "records.jsonl"

from distributed_sddmm_tpu.bench.cli import main as bench_main
from distributed_sddmm_tpu.tools.charts import main as charts_main
from distributed_sddmm_tpu.utils.coo import HostCOO

# 1. Generate an R-mat graph (power-law, the reference's synthetic stand-in
#    for uk-2002/twitter7-style graphs) and write it through the native IO.
mtx = HERE / "rmat14.mtx"
S = HostCOO.rmat(log_m=14, edge_factor=16, seed=7)
S.save_mtx(str(mtx))
print(f"wrote {mtx} ({S.M}x{S.N}, nnz={S.nnz})", flush=True)

# 2. Random row/col permutation (load-balance preprocessing,
#    `random_permute.cpp:42-57`).
rc = bench_main(["permute", str(mtx), "--seed", "1",
                 "-o", str(HERE / "rmat14-permuted.mtx")])
assert rc == 0

# 3. File benchmark: all five algorithm configs, fused, with region
#    breakdown, on the permuted graph.
RECORDS.unlink(missing_ok=True)
rc = bench_main([
    "file", str(HERE / "rmat14-permuted.mtx"), "all", "32", "2",
    "--kernel", "xla", "--trials", "3", "--breakdown",
    "-o", str(RECORDS),
])
assert rc == 0

# 4. Render the throughput + breakdown charts and the winner table.
rc = charts_main([str(RECORDS), "-o", str(HERE / "charts")])
assert rc == 0
print("chain complete", flush=True)
