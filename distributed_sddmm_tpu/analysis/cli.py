"""``bench lint`` / ``bench env``: the analyzer's CLI surface.

Exit contract (the repo's standard one, shared with ``bench gate`` and
``tracereport``): **0** clean (every finding tagged or baselined),
**2** new findings, **3** usage/config error (unknown checker id,
unreadable baseline) — a CI hook can distinguish "the tree regressed"
from "the lint invocation is broken".

This module's stdout IS its product (finding listings, the env table),
so it sits on the bare-print allowlist like the other CLI modules.
"""

from __future__ import annotations

import argparse
import json
import pathlib
import sys
from typing import Optional

EXIT_CLEAN = 0
EXIT_FINDINGS = 2
EXIT_USAGE = 3


def build_lint_parser(p: Optional[argparse.ArgumentParser] = None):
    if p is None:
        p = argparse.ArgumentParser(
            prog="bench lint",
            description="repo-wide invariant analyzer (analysis/)",
        )
    p.add_argument(
        "--checker", action="append", default=None, metavar="ID",
        help="run only this checker (repeatable; default: all)",
    )
    p.add_argument(
        "--baseline", default=None, metavar="PATH",
        help="baseline file (default: the committed LINT_BASELINE.json "
        "when scanning this checkout; 'none' disables)",
    )
    p.add_argument(
        "--root", default=None, metavar="DIR",
        help="scan root (default: this checkout; repo-wide consistency "
        "passes only run on the checkout itself)",
    )
    p.add_argument("--json", action="store_true",
                   help="machine-readable findings")
    p.add_argument(
        "--write-baseline", action="store_true",
        help="rewrite the baseline from the current new findings "
        "(exits 0; review the diff like any other)",
    )
    p.add_argument("--list", action="store_true",
                   help="list registered checkers and exit")
    return p


def run_lint(args) -> int:
    from distributed_sddmm_tpu import analysis
    from distributed_sddmm_tpu.analysis import baseline as bl

    if args.list:
        for cid, checker in sorted(analysis.CHECKERS.items()):
            print(f"{cid:<20} {checker.description}")
        return EXIT_CLEAN

    root = pathlib.Path(args.root).resolve() if args.root else None
    scanning_repo = root is None or root == analysis.repo_root()
    baseline_path = None
    if args.baseline and args.baseline != "none":
        baseline_path = pathlib.Path(args.baseline)
    elif args.baseline is None and scanning_repo:
        baseline_path = bl.default_baseline_path()

    # Usage errors (exit 3) surface BEFORE the multi-second repo walk:
    # a misconfigured CI invocation fails instantly, not after the scan.
    baseline_doc = None
    if baseline_path is not None and not args.write_baseline:
        try:
            baseline_doc = bl.load_baseline(baseline_path)
        except ValueError as e:
            print(f"bench lint: {e}", file=sys.stderr)
            return EXIT_USAGE

    try:
        findings = analysis.run(root=root, checkers=args.checker)
    except KeyError as e:
        print(f"bench lint: {e.args[0]}", file=sys.stderr)
        return EXIT_USAGE

    if args.write_baseline:
        out = baseline_path or (
            (root or analysis.repo_root()) / bl.BASELINE_NAME
        )
        keep = ()
        if args.checker and out.exists():
            # Partial regeneration: a --checker X run only re-baselines
            # X's debt; every other checker's committed entries survive
            # verbatim (deleting them would make the next FULL run fail
            # on suppressions nobody decided to drop).
            try:
                prior = bl.load_baseline(out)
            except ValueError as e:
                print(f"bench lint: {e}", file=sys.stderr)
                return EXIT_USAGE
            selected = set(args.checker)
            keep = [e for e in prior.get("findings", ())
                    if e.get("checker") not in selected]
        doc = bl.write_baseline(out, findings, keep=keep)
        print(f"wrote {out} ({len(doc['findings'])} finding(s)"
              + (f", {len(keep)} kept from unselected checkers" if keep
                 else "") + ")")
        return EXIT_CLEAN

    stale = []
    if baseline_doc is not None:
        # Scoped to the selected checkers: a partial run must not call
        # the unselected checkers' entries stale (see apply_baseline).
        stale = analysis.apply_baseline(
            findings, baseline_doc, checkers=args.checker
        )["stale"]

    new = [f for f in findings if f.state == "new"]
    if args.json:
        print(json.dumps({
            "new": len(new),
            "tagged": sum(f.state == "tagged" for f in findings),
            "baselined": sum(f.state == "baselined" for f in findings),
            "stale_baseline_entries": stale,
            "findings": [f.to_dict() for f in findings],
        }, indent=1))
    else:
        for f in new:
            print(f.render())
        counts = (
            f"{len(new)} new, "
            f"{sum(f.state == 'tagged' for f in findings)} tagged, "
            f"{sum(f.state == 'baselined' for f in findings)} baselined"
        )
        print(f"lint: {counts}")
        if stale:
            print(
                f"lint: note — {len(stale)} stale baseline entr"
                f"{'y' if len(stale) == 1 else 'ies'} (debt paid; drop "
                "with --write-baseline)"
            )
    return EXIT_FINDINGS if new else EXIT_CLEAN


def build_env_parser(p: Optional[argparse.ArgumentParser] = None):
    if p is None:
        p = argparse.ArgumentParser(
            prog="bench env",
            description="the DSDDMM_* env-knob registry (utils/envreg.py)",
        )
    fmt = p.add_mutually_exclusive_group()
    fmt.add_argument("--json", action="store_true")
    fmt.add_argument(
        "--markdown", action="store_true",
        help="emit the README block (paste between the envreg markers)",
    )
    p.add_argument(
        "--scope", choices=("runtime", "test"), default=None,
        help="filter by knob scope (default: all for the table, "
        "runtime for --markdown)",
    )
    return p


def run_env(args) -> int:
    from distributed_sddmm_tpu.utils import envreg

    if args.json:
        print(json.dumps(envreg.to_records(scope=args.scope), indent=1))
    elif args.markdown:
        # Scope threads through (--scope test audits the test knobs);
        # the default runtime block is the one the README commits and
        # the env-knob checker verifies.
        if args.scope in (None, "runtime"):
            print(envreg.README_BEGIN)
            print(envreg.render_markdown())
            print(envreg.README_END)
        else:
            print(envreg.render_markdown(scope=args.scope))
    else:
        print(envreg.render_table(scope=args.scope))
    return EXIT_CLEAN


def main(argv=None) -> int:
    """Standalone entry (``python -m distributed_sddmm_tpu.analysis.cli``)
    — same surface as ``bench lint`` for jax-free CI hooks."""
    ap = argparse.ArgumentParser(prog="analysis")
    sub = ap.add_subparsers(dest="cmd", required=True)
    build_lint_parser(sub.add_parser("lint"))
    build_env_parser(sub.add_parser("env"))
    args = ap.parse_args(argv)
    return run_lint(args) if args.cmd == "lint" else run_env(args)


if __name__ == "__main__":
    sys.exit(main())
