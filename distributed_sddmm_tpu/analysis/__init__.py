"""Repo-wide invariant analyzer: pluggable AST checkers, one tag scanner.

Nine PRs of hand-enforced discipline — structured logging, the one-clock
rule, atomic artifact writes, the single cache-key grammar, exported
counters, lock-guarded module state, trace purity, declared env knobs —
machine-checked before multi-controller code multiplies the ways to
violate them. The framework replaces the three ad-hoc regex lints that
grew inside ``tests/test_obs_lint.py`` (each with its own divergent
tag-comment parser) with:

* :mod:`~distributed_sddmm_tpu.analysis.core` — file walker (artifact
  outputs excluded), per-checker visitor registry, ONE tag-comment
  scanner for the whole suppression vocabulary, finding records with
  ``file:line`` + checker id + suppression state;
* :mod:`~distributed_sddmm_tpu.analysis.baseline` — committed JSON
  baseline (``LINT_BASELINE.json``): pre-existing findings don't block
  CI, new ones fail loud; entries are content-hashed so line drift does
  not invalidate them;
* :mod:`~distributed_sddmm_tpu.analysis.checkers` — the discipline
  checkers (the three migrated ``test_obs_lint`` lints plus
  atomic-write, env-knob, lock-discipline, key-grammar, trace-purity);
* :mod:`~distributed_sddmm_tpu.analysis.cli` — ``bench lint`` /
  ``bench env`` surface with the repo's 0/2/3 exit contract.

This package deliberately imports neither jax nor strategy code — the
analyzer must run in subprocess CI hooks and offline tooling the same
way ``programs/keys.py`` must (module doc there). The only runtime
imports are data tables (``utils.envreg``), themselves jax-free.
"""

from distributed_sddmm_tpu.analysis.core import (
    CHECKERS,
    Checker,
    Finding,
    SourceFile,
    parse_tags,
    repo_root,
    run,
)
from distributed_sddmm_tpu.analysis import checkers as _checkers  # noqa: F401 — registers
from distributed_sddmm_tpu.analysis.baseline import (
    apply_baseline,
    default_baseline_path,
    fingerprint,
    load_baseline,
    write_baseline,
)


def run_repo(checkers=None, baseline="auto"):
    """Run checkers over this checkout with the committed baseline
    applied — the call the ``tests/test_obs_lint.py`` thin wrappers and
    CI make. ``baseline`` may be a path, None (no suppression) or
    ``"auto"`` (the committed ``LINT_BASELINE.json`` when present)."""
    findings = run(checkers=checkers)
    if baseline == "auto":
        baseline = default_baseline_path()
    if baseline is not None:
        apply_baseline(findings, load_baseline(baseline),
                       checkers=checkers)
    return findings


__all__ = [
    "CHECKERS", "Checker", "Finding", "SourceFile", "parse_tags",
    "repo_root", "run", "run_repo", "apply_baseline", "fingerprint",
    "load_baseline", "write_baseline", "default_baseline_path",
]
