"""Analyzer core: file walker, tag scanner, checker registry, findings.

Three design rules, learned from the ad-hoc lints this replaces:

1. **One tag scanner.** The bare-print and export-completeness lints
   each grew a private regex for their opt-out comment (``# cli-output``
   vs ``# not-exported``) and the two had already drifted (one matched
   anywhere in the line, one only outside docstrings). Here a single
   tokenizer pass extracts every ``#`` comment once and parses the whole
   tag vocabulary out of it; checkers declare which tags suppress them
   and the core applies suppression uniformly over the *statement's*
   full line range (a tag on any physical line of a multi-line call
   counts, where the line-based regexes silently missed continuations).

2. **AST, not regex.** Findings anchor to real nodes: a ``print`` in a
   docstring or a key-grammar prefix in prose can no longer
   false-positive, and multi-line calls can no longer false-negative.

3. **The walker never scans artifact output.** ``artifacts/`` holds the
   runstore, program store, checkpoints and committed TPU-run records —
   generated trees that may contain thousands of files (and .py run
   scripts whose discipline is the TPU pod's, not this package's).
"""

from __future__ import annotations

import ast
import dataclasses
import pathlib
import re
import tokenize
from typing import Callable, Iterable, Iterator, Optional

# --------------------------------------------------------------------- #
# Tags: the unified suppression vocabulary
# --------------------------------------------------------------------- #

#: Every tag comment the analyzer understands, with the discipline it
#: opts out of. ``lock`` is parametric (``# lock: <name>`` names the
#: lock the surrounding code holds by construction).
TAG_VOCABULARY = {
    "cli-output": "deliberate stdout product line (bare-print)",
    "wall-clock-ok": "deliberate raw clock read (monotonic-clock)",
    "not-exported": "GLOBAL counter deliberately off /metrics "
                    "(export-completeness)",
    "non-atomic-ok": "deliberate raw write: stream/append/lock file "
                     "(atomic-write)",
    "env-ok": "deliberate unregistered env access (env-knob)",
    "lock": "module state guarded by the named lock at a coarser "
            "granularity (lock-discipline)",
    "unlocked-ok": "deliberately unguarded module-state write "
                   "(lock-discipline)",
    "key-grammar-ok": "deliberate key-shaped string outside "
                      "programs/keys.py (key-grammar)",
    "trace-impure-ok": "deliberate impurity in a traced body "
                       "(trace-purity)",
    "raw-collective-ok": "deliberate raw lax collective outside the "
                         "parallel/loops.py policy-aware wrappers "
                         "(raw-collective)",
    "no-trace-ctx": "deliberate fleet/ post_json without trace "
                    "headers (trace-propagation)",
}

_TAG_RES = {
    name: re.compile(
        rf"\b{re.escape(name)}\b" if name != "lock"
        else r"\block:\s*([A-Za-z_][\w.]*)"
    )
    for name in TAG_VOCABULARY
}


@dataclasses.dataclass(frozen=True)
class Tag:
    name: str
    arg: Optional[str] = None  # the lock name for ``lock:``


def parse_tags(comment: str) -> list[Tag]:
    """All tags in one ``#`` comment's text. A comment may carry several
    (``# lock: _registry_lock  # not-exported``) and prose after a tag
    (``# wall-clock-ok — the calibration pair``) is fine."""
    tags = []
    for name, rx in _TAG_RES.items():
        m = rx.search(comment)
        if m:
            tags.append(Tag(name, m.group(1) if m.groups() else None))
    return tags


def scan_tags(text: str) -> dict[int, list[Tag]]:
    """Line number -> tags, from ONE tokenizer pass over the file. Falls
    back to a line regex when the file fails to tokenize (the AST parse
    will report the syntax error; suppression accuracy is moot then)."""
    out: dict[int, list[Tag]] = {}
    try:
        import io

        for tok in tokenize.generate_tokens(io.StringIO(text).readline):
            if tok.type == tokenize.COMMENT:
                tags = parse_tags(tok.string)
                if tags:
                    out.setdefault(tok.start[0], []).extend(tags)
    except (tokenize.TokenError, IndentationError, SyntaxError):
        for ln, line in enumerate(text.splitlines(), 1):
            if "#" in line:
                tags = parse_tags(line[line.index("#"):])
                if tags:
                    out.setdefault(ln, []).extend(tags)
    return out


# --------------------------------------------------------------------- #
# Source files and findings
# --------------------------------------------------------------------- #


class SourceFile:
    """One parsed source file: text, AST (with parent links), tag map."""

    def __init__(self, path: pathlib.Path, rel: str):
        self.path = path
        self.rel = rel  # posix path relative to the scan root
        self.text = path.read_text(errors="replace")
        self.lines = self.text.splitlines()
        self.parse_error: Optional[SyntaxError] = None
        self._tree: Optional[ast.AST] = None
        self._tags: Optional[dict[int, list[Tag]]] = None

    @property
    def tree(self) -> Optional[ast.AST]:
        if self._tree is None and self.parse_error is None:
            try:
                self._tree = ast.parse(self.text)
            except SyntaxError as e:
                self.parse_error = e
                return None
            for node in ast.walk(self._tree):
                for child in ast.iter_child_nodes(node):
                    child._dsddmm_parent = node  # type: ignore[attr-defined]
        return self._tree

    @property
    def tags(self) -> dict[int, list[Tag]]:
        if self._tags is None:
            self._tags = scan_tags(self.text)
        return self._tags

    def tags_in_range(self, lo: int, hi: int) -> list[Tag]:
        """Tags on any physical line of [lo, hi] — the statement span,
        so a tag on the closing line of a multi-line call counts — plus
        standalone comment lines immediately ABOVE the statement (the
        natural place for a tag with a because-clause too long for a
        trailing comment)."""
        out = []
        ln = lo - 1
        while ln >= 1 and self.line(ln).strip().startswith("#"):
            out.extend(self.tags.get(ln, ()))
            ln -= 1
        for ln in range(lo, hi + 1):
            out.extend(self.tags.get(ln, ()))
        return out

    def parents(self, node: ast.AST) -> Iterator[ast.AST]:
        while True:
            node = getattr(node, "_dsddmm_parent", None)
            if node is None:
                return
            yield node

    def line(self, ln: int) -> str:
        return self.lines[ln - 1] if 0 < ln <= len(self.lines) else ""


@dataclasses.dataclass
class Finding:
    """One checker hit. ``state`` is ``new`` (fails the gate),
    ``tagged`` (suppressed at the site) or ``baselined`` (suppressed by
    the committed baseline)."""

    checker: str
    path: str  # scan-root-relative posix path
    line: int
    message: str
    snippet: str = ""
    state: str = "new"
    tag: Optional[str] = None

    def location(self) -> str:
        return f"{self.path}:{self.line}"

    def render(self) -> str:
        loc = f"{self.location()}: [{self.checker}] {self.message}"
        return f"{loc}\n    {self.snippet.strip()[:90]}" if self.snippet else loc

    def to_dict(self) -> dict:
        return dataclasses.asdict(self)


# --------------------------------------------------------------------- #
# Checker registry
# --------------------------------------------------------------------- #


class Checker:
    """One invariant. Subclasses set ``id``/``description``, the tags
    that suppress them, and override :meth:`check` (per file) and/or
    :meth:`finish` (one repo-wide pass after every file, for
    cross-file consistency like stale-declaration detection)."""

    id: str = ""
    description: str = ""
    #: Tag names that mark a finding of this checker deliberate.
    suppress_tags: tuple[str, ...] = ()

    def select(self, src: SourceFile) -> bool:
        """Which files this checker reads (default: all walked)."""
        return True

    def check(self, src: SourceFile, ctx: "Analysis") -> Iterable[Finding]:
        return ()

    def finish(self, ctx: "Analysis") -> Iterable[Finding]:
        return ()

    # -- helpers shared by the concrete checkers ----------------------- #

    def finding(self, src: SourceFile, node: ast.AST, message: str) -> Finding:
        ln = getattr(node, "lineno", 1)
        f = Finding(self.id, src.rel, ln, message, snippet=src.line(ln))
        # The node rides along (non-dataclass attr) so the core can
        # check suppression tags over the statement's full line span.
        f._node = node  # type: ignore[attr-defined]
        return f


CHECKERS: dict[str, Checker] = {}


def register(cls: type) -> type:
    inst = cls()
    if not inst.id:
        raise ValueError(f"checker {cls.__name__} has no id")
    if inst.id in CHECKERS:
        raise ValueError(f"duplicate checker id {inst.id!r}")
    CHECKERS[inst.id] = inst
    return cls


# --------------------------------------------------------------------- #
# AST utilities
# --------------------------------------------------------------------- #


def dotted(node: ast.AST) -> Optional[str]:
    """``a.b.c`` for Name/Attribute chains, else None."""
    parts = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


def call_name(call: ast.Call) -> Optional[str]:
    return dotted(call.func)


def str_const(node: ast.AST) -> Optional[str]:
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return node.value
    return None


def node_span(node: ast.AST) -> tuple[int, int]:
    lo = getattr(node, "lineno", 1)
    hi = getattr(node, "end_lineno", lo) or lo
    return lo, hi


# --------------------------------------------------------------------- #
# The walker and the run loop
# --------------------------------------------------------------------- #

#: Directory names the walker never descends into. ``artifacts`` is the
#: load-bearing one: runstore/program-store/checkpoint/flightrec output
#: lands there (plus committed TPU-run scripts that are not part of this
#: package's lint surface).
EXCLUDE_DIRS = {
    "artifacts", "__pycache__", ".git", ".venv", "node_modules",
    ".pytest_cache",
}

#: Excluded only as REPO-ROOT directories: packaging output (``build/``,
#: ``dist/``) and the native C++ tree live at the checkout root, while
#: ``distributed_sddmm_tpu/dist/`` (the multi-host subsystem, PR 14) is
#: real package source the checkers must scan. Anchored to
#: :func:`repo_root`, NOT the scan root — ``--root
#: distributed_sddmm_tpu`` must see the same files a repo-root scan
#: sees for that subtree.
EXCLUDE_TOP_DIRS = {"native", "build", "dist"}


def repo_root() -> pathlib.Path:
    return pathlib.Path(__file__).resolve().parents[2]


def iter_source_paths(root: pathlib.Path) -> Iterator[pathlib.Path]:
    excluded_roots = {repo_root() / name for name in EXCLUDE_TOP_DIRS}
    for path in sorted(root.rglob("*.py")):
        rel_parts = path.relative_to(root).parts
        if any(part in EXCLUDE_DIRS for part in rel_parts[:-1]):
            continue
        if any(parent in excluded_roots
               for parent in path.resolve().parents):
            continue
        yield path


class Analysis:
    """One run's context: the scan root, whether it IS this checkout
    (repo-wide consistency passes — stale counters, README agreement —
    only make sense there, not on seeded fixture trees), and per-checker
    scratch space for :meth:`Checker.finish`."""

    def __init__(self, root: pathlib.Path):
        self.root = root
        self.is_repo = (root == repo_root())
        self.files: list[SourceFile] = []
        self.scratch: dict[str, dict] = {}

    def scratch_for(self, checker_id: str) -> dict:
        return self.scratch.setdefault(checker_id, {})


def _apply_tags(checker: Checker, src: SourceFile, finding: Finding,
                node: Optional[ast.AST]) -> Finding:
    lo, hi = node_span(node) if node is not None else (finding.line,
                                                      finding.line)
    for tag in src.tags_in_range(lo, hi):
        if tag.name in checker.suppress_tags:
            finding.state = "tagged"
            finding.tag = tag.name if tag.arg is None else (
                f"{tag.name}: {tag.arg}"
            )
            break
    return finding


def run(root: Optional[pathlib.Path] = None,
        checkers: Optional[Iterable[str]] = None) -> list[Finding]:
    """Walk ``root`` (default: this checkout) and run the selected
    checkers (default: all registered). Returns every finding —
    including tagged ones, so ``--json`` output shows the full picture;
    only ``state == "new"`` findings fail the gate."""
    # Imported for side effect when core is used directly: the concrete
    # checkers register on import.
    from distributed_sddmm_tpu.analysis import checkers as _impl  # noqa: F401

    # Resolve: is_repo must hold for ANY spelling of this checkout's
    # path (relative, symlinked) or the repo-wide finish() passes would
    # silently skip.
    root = (pathlib.Path(root).resolve() if root is not None
            else repo_root())
    # Dedupe, order-preserving: a repeated --checker flag must not run
    # a checker twice (double findings, ordinal-shifted fingerprints).
    ids = (list(dict.fromkeys(checkers)) if checkers is not None
           else list(CHECKERS))
    unknown = [i for i in ids if i not in CHECKERS]
    if unknown:
        raise KeyError(
            f"unknown checker id(s) {unknown}; known: {sorted(CHECKERS)}"
        )
    ctx = Analysis(root)
    findings: list[Finding] = []
    for path in iter_source_paths(root):
        src = SourceFile(path, path.relative_to(root).as_posix())
        ctx.files.append(src)
        selected = [CHECKERS[i] for i in ids if CHECKERS[i].select(src)]
        if not selected:
            continue
        if src.tree is None:  # SyntaxError: one framework finding
            e = src.parse_error
            findings.append(Finding(
                "parse", src.rel, e.lineno or 1,
                f"file does not parse: {e.msg}",
            ))
            continue
        for checker in selected:
            for f in checker.check(src, ctx):
                node = getattr(f, "_node", None)
                findings.append(_apply_tags(checker, src, f, node))
    for i in ids:
        findings.extend(CHECKERS[i].finish(ctx))
    findings.sort(key=lambda f: (f.checker, f.path, f.line))
    return findings
