"""The discipline checkers.

Eight disciplines, ten checker ids (the three lints migrated from
``tests/test_obs_lint.py`` count as one group there):

====================  ================================================
id                    invariant
====================  ================================================
``bare-print``        diagnostics go through ``obs.log``; only CLI
                      modules / ``# cli-output`` lines print
``monotonic-clock``   serve/ and obs/ read ``obs.clock``, never raw
                      ``time.*``; ``time.time()`` package-wide must be
                      ``clock.epoch()`` (one calibration pair)
``export-completeness``  every ``GLOBAL.add`` name is declared in
                      ``httpexp.KNOWN_GLOBAL_COUNTERS`` (and no
                      declaration is stale)
``atomic-write``      artifact writes route through ``utils/atomic``
                      (temp-file + ``os.replace``); streams/appends/
                      lock files carry ``# non-atomic-ok``
``env-knob``          every ``DSDDMM_*`` access names a knob declared
                      in ``utils/envreg.py``; registry and README
                      table agree; no stale registrations
``lock-discipline``   module-level mutable containers in obs/ and
                      serve/ are written under a ``with <lock>`` block
                      (or in a ``*_locked`` function, or annotated)
``key-grammar``       ``plan:``/``serve:``/``bench:`` cache keys are
                      built ONLY by ``programs/keys.py`` builders
``trace-purity``      no wall-clock / ``random`` / GLOBAL-counter
                      mutation inside jit- or Pallas-traced bodies
``raw-collective``    ``lax.all_gather`` / ``lax.ppermute`` /
                      ``lax.psum_scatter`` only through the
                      policy-aware ``parallel/loops.py`` wrappers
                      (or tagged ``# raw-collective-ok``)
``trace-propagation`` every ``post_json`` under ``fleet/`` forwards
                      trace headers (a ``headers=`` argument) so the
                      fleet request tree never silently loses a hop
                      (or tagged ``# no-trace-ctx``)
====================  ================================================

Every checker is a pure AST pass (regex only inside comments); the
suppression vocabulary lives in ``core.TAG_VOCABULARY`` and is parsed
by the one shared scanner — the divergent per-lint tag regexes this
replaces are the bug this PR retires.
"""

from __future__ import annotations

import ast
import pathlib
from typing import Iterable, Optional

from distributed_sddmm_tpu.analysis.core import (
    Analysis,
    Checker,
    Finding,
    SourceFile,
    call_name,
    dotted,
    node_span,
    register,
    repo_root,
    str_const,
)

PKG = "distributed_sddmm_tpu/"


def in_pkg(src: SourceFile) -> bool:
    return src.rel.startswith(PKG)


def pkg_rel(src: SourceFile) -> str:
    return src.rel[len(PKG):]


# --------------------------------------------------------------------- #
# 1. bare-print (migrated from tests/test_obs_lint.py)
# --------------------------------------------------------------------- #


@register
class BarePrintChecker(Checker):
    id = "bare-print"
    description = ("bare print( in library code — use obs.log, or tag "
                   "deliberate CLI output '# cli-output'")
    suppress_tags = ("cli-output",)

    #: Modules whose stdout IS the product (argparse CLIs, table
    #: printers) — the allowlist the old lint carried, plus the lint
    #: CLI itself.
    ALLOWLIST = {
        "bench/cli.py",        # bench subcommands print JSON records
        "bench/kernels.py",    # kernel-sweep table printer
        "tools/costmodel.py",  # cost-model CLI
        "tools/charts.py",     # chart CLI
        "tools/tracereport.py",  # trace-report CLI
        "analysis/cli.py",     # the lint/env CLI: findings ARE stdout
    }

    def select(self, src):
        return in_pkg(src) and pkg_rel(src) not in self.ALLOWLIST

    def check(self, src, ctx):
        for node in ast.walk(src.tree):
            if (isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Name)
                    and node.func.id == "print"):
                yield self.finding(
                    src, node,
                    "bare print( — route diagnostics through "
                    "distributed_sddmm_tpu.obs.log",
                )


# --------------------------------------------------------------------- #
# 2. monotonic-clock (migrated)
# --------------------------------------------------------------------- #


@register
class MonotonicClockChecker(Checker):
    id = "monotonic-clock"
    description = ("raw time.* clock read where obs.clock (the one "
                   "calibrated pair) is required")
    suppress_tags = ("wall-clock-ok",)

    #: The clock module IS the abstraction.
    ALLOWLIST = {"obs/clock.py"}
    #: Full discipline (no raw clock at all) inside the span layers.
    SPAN_SUBPACKAGES = ("serve/", "obs/")
    RAW_CLOCKS = {"time.time", "time.perf_counter", "time.monotonic"}

    def select(self, src):
        return in_pkg(src) and pkg_rel(src) not in self.ALLOWLIST

    def check(self, src, ctx):
        rel = pkg_rel(src)
        span_path = rel.startswith(self.SPAN_SUBPACKAGES)
        for node in ast.walk(src.tree):
            if not isinstance(node, ast.Call):
                continue
            name = call_name(node)
            if name not in self.RAW_CLOCKS:
                continue
            if span_path:
                yield self.finding(
                    src, node,
                    f"raw {name}() in a serve/obs span path — read "
                    "obs.clock (now()/epoch()) so timestamps stay "
                    "calibrated and mergeable",
                )
            elif name == "time.time":
                # Package-wide: epoch stamps come from clock.epoch()
                # so created-at metadata shares the process's one
                # calibration pair (perf_counter stays free outside
                # the span layers — bench timing is local by design).
                yield self.finding(
                    src, node,
                    "time.time() outside obs/clock — use "
                    "obs.clock.epoch() for epoch stamps",
                )


# --------------------------------------------------------------------- #
# 3. export-completeness (migrated)
# --------------------------------------------------------------------- #


def _counter_add_name(call: ast.Call) -> Optional[ast.AST]:
    """The name-argument node of a ``GLOBAL.add(...)`` /
    ``_global_counters().add(...)`` bump, else None."""
    fn = call.func
    if not (isinstance(fn, ast.Attribute) and fn.attr == "add"):
        return None
    owner = fn.value
    owner_name = dotted(owner)
    owned = (
        # GLOBAL.add / metrics.GLOBAL.add / obs_metrics.GLOBAL.add —
        # the counter registry is always bound as ``GLOBAL``.
        (owner_name is not None
         and (owner_name == "GLOBAL" or owner_name.endswith(".GLOBAL")))
        or (isinstance(owner, ast.Call)
            and call_name(owner) == "_global_counters")
    )
    if not owned or not call.args:
        return None
    return call.args[0]


def known_global_counters(root: Optional[pathlib.Path] = None) -> set:
    """Statically extract ``KNOWN_GLOBAL_COUNTERS`` keys from
    ``obs/httpexp.py`` — no package import, so the analyzer stays
    importable in jax-free subprocesses."""
    path = (root or repo_root()) / PKG / "obs" / "httpexp.py"
    if not path.exists():
        return set()
    tree = ast.parse(path.read_text())
    for node in tree.body:
        targets = []
        if isinstance(node, ast.Assign):
            targets = node.targets
        elif isinstance(node, ast.AnnAssign) and node.target is not None:
            targets = [node.target]
        for t in targets:
            if (isinstance(t, ast.Name)
                    and t.id == "KNOWN_GLOBAL_COUNTERS"
                    and isinstance(node.value, ast.Dict)):
                return {str_const(k) for k in node.value.keys
                        if str_const(k) is not None}
    return set()


@register
class ExportCompletenessChecker(Checker):
    id = "export-completeness"
    description = ("GLOBAL counter missing from the /metrics exposition "
                   "(httpexp.KNOWN_GLOBAL_COUNTERS), or stale declaration")
    suppress_tags = ("not-exported",)

    def select(self, src):
        return in_pkg(src)

    def check(self, src, ctx):
        scratch = ctx.scratch_for(self.id)
        seen = scratch.setdefault("seen", set())
        known = scratch.get("known")
        if known is None:
            # The SCANNED tree's declarations (a --root worktree's own
            # httpexp.py), not the running checkout's; a tree without
            # one (fixture trees) has an empty known set, so every
            # bump fires.
            known = scratch["known"] = known_global_counters(ctx.root)
        for node in ast.walk(src.tree):
            if not isinstance(node, ast.Call):
                continue
            arg = _counter_add_name(node)
            if arg is None:
                continue
            name = str_const(arg)
            if name is None:
                yield self.finding(
                    src, node,
                    "GLOBAL.add with a non-literal counter name — the "
                    "exposition cannot verify it is scraped",
                )
                continue
            seen.add(name)
            if name not in known:
                yield self.finding(
                    src, node,
                    f"GLOBAL counter {name!r} not declared in "
                    "obs.httpexp.KNOWN_GLOBAL_COUNTERS — it will never "
                    "appear on /metrics",
                )

    def finish(self, ctx):
        if not ctx.is_repo:
            return
        scratch = ctx.scratch_for(self.id)
        seen = scratch.get("seen", set())
        known = scratch.get("known", known_global_counters(ctx.root))
        if not seen:
            yield Finding(
                self.id, PKG + "obs/httpexp.py", 1,
                "checker matched no GLOBAL.add sites at all — the "
                "visitor rotted",
            )
            return
        # Reverse direction: a declared-but-never-bumped counter is a
        # stale declaration (renamed counter keeps scraping a frozen 0).
        for name in sorted(known - seen):
            yield Finding(
                self.id, PKG + "obs/httpexp.py", 1,
                f"KNOWN_GLOBAL_COUNTERS declares {name!r} but no "
                "GLOBAL.add site bumps it (stale declaration)",
            )


# --------------------------------------------------------------------- #
# 4. atomic-write
# --------------------------------------------------------------------- #


@register
class AtomicWriteChecker(Checker):
    id = "atomic-write"
    description = ("raw file write — route artifact writes through "
                   "utils/atomic (or tag streams '# non-atomic-ok')")
    suppress_tags = ("non-atomic-ok",)

    #: The one implementation of the temp-file + os.replace dance.
    ALLOWLIST = {"utils/atomic.py"}
    WRITE_MODES = set("wax+")

    def select(self, src):
        return in_pkg(src) and pkg_rel(src) not in self.ALLOWLIST

    def _open_mode(self, call: ast.Call) -> Optional[str]:
        if not (isinstance(call.func, ast.Name)
                and call.func.id == "open"):
            return None
        mode = None
        if len(call.args) >= 2:
            mode = str_const(call.args[1])
        for kw in call.keywords:
            if kw.arg == "mode":
                mode = str_const(kw.value)
        return mode

    def check(self, src, ctx):
        for node in ast.walk(src.tree):
            if not isinstance(node, ast.Call):
                continue
            mode = self._open_mode(node)
            if mode is not None and set(mode) & self.WRITE_MODES:
                yield self.finding(
                    src, node,
                    f"raw open(..., {mode!r}) — a kill mid-write leaves "
                    "a torn file; use utils.atomic (atomic_write_text/"
                    "json/bytes)",
                )
                continue
            name = call_name(node)
            if name == "json.dump":
                yield self.finding(
                    src, node,
                    "json.dump to a raw handle — use "
                    "utils.atomic.atomic_write_json",
                )
                continue
            if (isinstance(node.func, ast.Attribute)
                    and node.func.attr in ("write_text", "write_bytes")):
                yield self.finding(
                    src, node,
                    f".{node.func.attr}() — a kill mid-write leaves a "
                    "torn file; use utils.atomic",
                )


# --------------------------------------------------------------------- #
# 5. env-knob
# --------------------------------------------------------------------- #


def _env_access_name(node: ast.AST) -> Optional[ast.AST]:
    """The name-expression node of an ``os.environ`` access, else None:
    ``os.environ.get/pop/setdefault(K, ...)``, ``os.getenv(K, ...)``,
    ``os.environ[K]`` (read, write or del)."""
    if isinstance(node, ast.Call):
        name = call_name(node)
        if name in ("os.environ.get", "os.environ.pop",
                    "os.environ.setdefault", "os.getenv") and node.args:
            return node.args[0]
        return None
    if isinstance(node, ast.Subscript):
        if dotted(node.value) == "os.environ":
            return node.slice
    return None


def registered_knobs(root: pathlib.Path) -> Optional[set]:
    """Statically extract declared knob names from a tree's
    ``utils/envreg.py`` (first string argument of each ``_K``/``Knob``
    call). None when the tree has no registry file."""
    path = root / PKG / "utils" / "envreg.py"
    if not path.exists():
        return None
    names = set()
    for node in ast.walk(ast.parse(path.read_text())):
        if (isinstance(node, ast.Call)
                and dotted(node.func) in ("_K", "Knob") and node.args):
            name = str_const(node.args[0])
            if name is not None:
                names.add(name)
    return names


@register
class EnvKnobChecker(Checker):
    id = "env-knob"
    description = ("DSDDMM_* env access not declared in utils/envreg.py "
                   "(or stale registration / README table drift)")
    suppress_tags = ("env-ok",)
    PREFIX = "DSDDMM_"

    # Scope: everything walked — the package, scripts/, tests/ and the
    # root entry points all reach for knobs.

    def _registry(self, ctx) -> set:
        """Declared knob names — from the SCANNED tree's envreg.py when
        it has one (a --root worktree validates against its own
        registry, statically extracted), else the running checkout's
        (fixture trees reference real knobs)."""
        scratch = ctx.scratch_for(self.id)
        if "knobs" not in scratch:
            names = registered_knobs(ctx.root)
            if names is None:
                from distributed_sddmm_tpu.utils import envreg

                names = set(envreg.KNOBS)
            scratch["knobs"] = names
        return scratch["knobs"]

    def check(self, src, ctx):
        if in_pkg(src) and pkg_rel(src) == "utils/envreg.py":
            return
        knobs = self._registry(ctx)
        seen = ctx.scratch_for(self.id).setdefault("seen", set())
        for node in ast.walk(src.tree):
            arg = _env_access_name(node)
            if arg is None:
                continue
            name = str_const(arg)
            if name is None or not name.startswith(self.PREFIX):
                continue
            seen.add(name)
            if name not in knobs:
                yield self.finding(
                    src, node,
                    f"env knob {name!r} is not declared in "
                    "utils/envreg.py — register it (name, type, "
                    "default, doc) so `bench env` and the README table "
                    "stay complete",
                )

    def finish(self, ctx):
        if not ctx.is_repo:
            return
        from distributed_sddmm_tpu.utils import envreg

        knobs = self._registry(ctx)
        seen = ctx.scratch_for(self.id).get("seen", set())
        envreg_rel = PKG + "utils/envreg.py"
        for name in sorted(set(knobs) - seen):
            yield Finding(
                self.id, envreg_rel, envreg.declaration_line(name) or 1,
                f"registered knob {name!r} has no os.environ access "
                "site anywhere in the repo (stale registration)",
            )
        # README table agreement: the committed block between the
        # envreg markers must be exactly what the registry renders.
        readme = ctx.root / "README.md"
        if not readme.exists():
            return
        text = readme.read_text()
        begin, end = envreg.README_BEGIN, envreg.README_END
        if begin not in text or end not in text:
            yield Finding(
                self.id, "README.md", 1,
                f"README is missing the env-knob table markers "
                f"({begin} / {end}) — regenerate with "
                "`bench env --markdown`",
            )
            return
        block = text.split(begin, 1)[1].split(end, 1)[0].strip()
        want = envreg.render_markdown().strip()
        if block != want:
            line = text[: text.index(begin)].count("\n") + 1
            yield Finding(
                self.id, "README.md", line,
                "README env-knob table does not match utils/envreg.py "
                "— regenerate the block with `bench env --markdown`",
            )


# --------------------------------------------------------------------- #
# 6. lock-discipline
# --------------------------------------------------------------------- #


@register
class LockDisciplineChecker(Checker):
    id = "lock-discipline"
    description = ("module-level mutable container written outside a "
                   "`with <lock>` block in obs/ or serve/")
    suppress_tags = ("lock", "unlocked-ok")

    SCOPES = ("obs/", "serve/")
    CONTAINER_CALLS = {
        "dict", "list", "set", "defaultdict", "collections.defaultdict",
        "OrderedDict", "collections.OrderedDict", "deque",
        "collections.deque", "Counter", "collections.Counter",
    }
    MUTATORS = {
        "append", "add", "update", "pop", "popitem", "clear", "extend",
        "insert", "remove", "discard", "setdefault", "appendleft",
        "popleft", "rotate",
    }

    def select(self, src):
        return in_pkg(src) and pkg_rel(src).startswith(self.SCOPES)

    def _module_containers(self, src) -> set:
        names = set()
        for stmt in src.tree.body:
            targets, value = [], None
            if isinstance(stmt, ast.Assign):
                targets, value = stmt.targets, stmt.value
            elif isinstance(stmt, ast.AnnAssign) and stmt.value is not None:
                targets, value = [stmt.target], stmt.value
            if value is None:
                continue
            mutable = isinstance(value, (
                ast.Dict, ast.List, ast.Set, ast.DictComp, ast.ListComp,
                ast.SetComp,
            )) or (isinstance(value, ast.Call)
                   and call_name(value) in self.CONTAINER_CALLS)
            if not mutable:
                continue
            for t in targets:
                if isinstance(t, ast.Name):
                    names.add(t.id)
        return names

    def _is_locked(self, src, node) -> bool:
        """Held-lock heuristic: an enclosing ``with`` whose context
        expression mentions a lock (``with self._lock:``, ``with
        _registry_lock:``, ``with store._flock():``) or an enclosing
        function named ``*_locked`` (the repo's convention for
        called-with-lock-held helpers)."""
        for anc in src.parents(node):
            if isinstance(anc, (ast.FunctionDef, ast.AsyncFunctionDef)):
                if anc.name.endswith("_locked"):
                    return True
            if isinstance(anc, (ast.With, ast.AsyncWith)):
                for item in anc.items:
                    if "lock" in ast.unparse(item.context_expr).lower():
                        return True
        return False

    def _mutations(self, tree, containers):
        for node in ast.walk(tree):
            if isinstance(node, (ast.Assign, ast.AugAssign)):
                targets = (node.targets if isinstance(node, ast.Assign)
                           else [node.target])
                for t in targets:
                    if (isinstance(t, ast.Subscript)
                            and isinstance(t.value, ast.Name)
                            and t.value.id in containers):
                        yield node, t.value.id
            elif isinstance(node, ast.Delete):
                for t in node.targets:
                    if (isinstance(t, ast.Subscript)
                            and isinstance(t.value, ast.Name)
                            and t.value.id in containers):
                        yield node, t.value.id
            elif isinstance(node, ast.Call):
                fn = node.func
                if (isinstance(fn, ast.Attribute)
                        and fn.attr in self.MUTATORS
                        and isinstance(fn.value, ast.Name)
                        and fn.value.id in containers):
                    yield node, fn.value.id

    def check(self, src, ctx):
        containers = self._module_containers(src)
        if not containers:
            return
        for node, name in self._mutations(src.tree, containers):
            # Module-level statements run at import, single-threaded by
            # the import lock — only function-scope writes race.
            if not any(isinstance(a, (ast.FunctionDef,
                                      ast.AsyncFunctionDef))
                       for a in src.parents(node)):
                continue
            if self._is_locked(src, node):
                continue
            yield self.finding(
                src, node,
                f"module-level container {name!r} written outside a "
                "`with <lock>` block — a concurrent scrape/serve thread "
                "can observe a torn update; hold the module's lock or "
                "annotate `# lock: <name>` / `# unlocked-ok`",
            )


# --------------------------------------------------------------------- #
# 7. key-grammar
# --------------------------------------------------------------------- #


@register
class KeyGrammarChecker(Checker):
    id = "key-grammar"
    description = ("cache-key-shaped string built outside "
                   "programs/keys.py builders")
    suppress_tags = ("key-grammar-ok",)

    #: The one key grammar module (module doc there: three look-alike
    #: builders diverging is exactly what PR 6 unified).
    ALLOWLIST = {"programs/keys.py"}
    PREFIXES = ("plan:", "serve:", "bench:")
    FAMILIES = {"plan", "serve", "bench"}
    #: Span/event names share the prefixes (``serve:batch``) but real
    #: keys are many-segment — require >= this many literal colons.
    MIN_COLONS = 3

    def select(self, src):
        return in_pkg(src) and pkg_rel(src) not in self.ALLOWLIST

    def _flag(self, src, node, how):
        return self.finding(
            src, node,
            f"{how} builds a {'/'.join(self.PREFIXES)} cache key "
            "outside programs/keys.py — use the builders "
            "(plan_program_key/serve_program_key/bench_aot_key) so the "
            "one grammar cannot silently fork",
        )

    def check(self, src, ctx):
        for node in ast.walk(src.tree):
            if isinstance(node, ast.JoinedStr):
                lits = [v.value for v in node.values
                        if isinstance(v, ast.Constant)
                        and isinstance(v.value, str)]
                if not lits or not lits[0].startswith(self.PREFIXES):
                    continue
                if sum(s.count(":") for s in lits) >= self.MIN_COLONS:
                    yield self._flag(src, node, "f-string")
            elif isinstance(node, ast.Call):
                fn = node.func
                # ":".join(("plan", ...))
                if (isinstance(fn, ast.Attribute) and fn.attr == "join"
                        and str_const(fn.value) == ":" and node.args):
                    arg = node.args[0]
                    if isinstance(arg, (ast.Tuple, ast.List)) and arg.elts:
                        if str_const(arg.elts[0]) in self.FAMILIES:
                            yield self._flag(src, node, '":".join')
                # "plan:{}:{}...".format(...)
                elif (isinstance(fn, ast.Attribute)
                      and fn.attr == "format"):
                    s = str_const(fn.value)
                    if (s and s.startswith(self.PREFIXES)
                            and s.count(":") >= self.MIN_COLONS):
                        yield self._flag(src, node, "str.format")
            elif isinstance(node, ast.BinOp) and isinstance(node.op, ast.Mod):
                s = str_const(node.left)
                if (s and s.startswith(self.PREFIXES)
                        and s.count(":") >= self.MIN_COLONS):
                    yield self._flag(src, node, "%-format")


# --------------------------------------------------------------------- #
# 8. raw-collective
# --------------------------------------------------------------------- #


@register
class RawCollectiveChecker(Checker):
    id = "raw-collective"
    description = ("raw lax collective outside the parallel/loops.py "
                   "policy-aware wrappers (abl_all_gather / abl_ppermute "
                   "/ abl_psum_scatter)")
    suppress_tags = ("raw-collective-ok",)

    #: The wrappers themselves — the ONE place the raw collectives (and
    #: the wire-precision boundary casts around them) may live.
    ALLOWLIST = {"parallel/loops.py"}
    #: The three collectives the wrappers own. ``pmax``/``psum`` stay
    #: out: they carry scalar/row-stat payloads the wire policy keeps
    #: exact by contract, so raw use is not a policy bypass.
    COLLECTIVES = {
        "lax.all_gather", "lax.ppermute", "lax.psum_scatter",
        "jax.lax.all_gather", "jax.lax.ppermute", "jax.lax.psum_scatter",
    }

    def select(self, src):
        return in_pkg(src) and pkg_rel(src) not in self.ALLOWLIST

    def check(self, src, ctx):
        for node in ast.walk(src.tree):
            if not isinstance(node, ast.Call):
                continue
            name = call_name(node)
            if name not in self.COLLECTIVES:
                continue
            yield self.finding(
                src, node,
                f"raw {name}( outside parallel/loops.py — route through "
                "the abl_* wrappers so the collective honors the "
                "ablation mode AND the wire-precision policy (or tag a "
                "deliberate off-policy collective '# raw-collective-ok')",
            )


# --------------------------------------------------------------------- #
# 9. trace-propagation
# --------------------------------------------------------------------- #


@register
class TracePropagationChecker(Checker):
    id = "trace-propagation"
    description = ("fleet/ post_json without a headers= argument — the "
                   "hop drops the X-DSDDMM-Trace context (or tag "
                   "deliberate context-free calls '# no-trace-ctx')")
    suppress_tags = ("no-trace-ctx",)

    #: Only the fleet tier routes requests on behalf of a fleet trace
    #: context; obs/ and bench CLI probes (health polls, the load
    #: generator's client) mint or carry their own.
    SCOPES = ("fleet/",)
    POSTERS = ("post_json",)

    def select(self, src):
        return in_pkg(src) and pkg_rel(src).startswith(self.SCOPES)

    def check(self, src, ctx):
        for node in ast.walk(src.tree):
            if not isinstance(node, ast.Call):
                continue
            name = call_name(node)
            if name is None or name.split(".")[-1] not in self.POSTERS:
                continue
            if any(kw.arg == "headers" for kw in node.keywords):
                continue
            yield self.finding(
                src, node,
                "post_json under fleet/ without headers= — the request "
                "leaves the process with no X-DSDDMM-Trace context, so "
                "the replica's spans can never re-join the fleet "
                "request tree; pass encode_fleet_ctx(...) headers or "
                "tag a deliberate context-free call '# no-trace-ctx'",
            )


# --------------------------------------------------------------------- #
# 10. trace-purity
# --------------------------------------------------------------------- #


@register
class TracePurityChecker(Checker):
    id = "trace-purity"
    description = ("wall-clock / random / GLOBAL-counter mutation "
                   "inside a jit- or Pallas-traced function body")
    suppress_tags = ("trace-impure-ok",)

    JIT_NAMES = {"jit", "jax.jit"}
    PALLAS_SUFFIX = "pallas_call"
    IMPURE_CALLS = {
        "time.time", "time.perf_counter", "time.monotonic",
        "time.time_ns", "clock.now", "clock.epoch", "obs_clock.now",
        "obs_clock.epoch",
    }
    RANDOM_ROOTS = ("random.", "np.random.", "numpy.random.")

    def select(self, src):
        return in_pkg(src)

    # -- traced-root discovery ----------------------------------------- #

    def _is_jit_decorator(self, dec: ast.AST) -> bool:
        name = dotted(dec)
        if name in self.JIT_NAMES:
            return True
        if isinstance(dec, ast.Call):
            name = call_name(dec)
            if name in self.JIT_NAMES:
                return True
            # @partial(jax.jit, static_argnums=...)
            if (name in ("partial", "functools.partial") and dec.args
                    and dotted(dec.args[0]) in self.JIT_NAMES):
                return True
        return False

    def _traced_defs(self, src) -> list:
        defs_by_name: dict[str, list] = {}
        for node in ast.walk(src.tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                defs_by_name.setdefault(node.name, []).append(node)

        roots: list = []
        for node in ast.walk(src.tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                if any(self._is_jit_decorator(d)
                       for d in node.decorator_list):
                    roots.append(node)
            elif isinstance(node, ast.Call):
                name = call_name(node)
                traced_call = name in self.JIT_NAMES or (
                    name is not None and name.endswith(self.PALLAS_SUFFIX)
                )
                if not traced_call:
                    continue
                # Any Name referenced in the call's arguments that
                # resolves to a function def in this module is traced
                # (covers jax.jit(make), shard_map(prog, ...) inside
                # jit, pl.pallas_call(kernel_body, ...)).
                for arg in list(node.args) + [kw.value
                                              for kw in node.keywords]:
                    for sub in ast.walk(arg):
                        if isinstance(sub, ast.Name):
                            roots.extend(defs_by_name.get(sub.id, ()))

        # Same-module reachability: a traced body calling a local
        # helper by name traces the helper too.
        traced, queue = [], list(dict.fromkeys(roots))
        seen_ids = set()
        while queue:
            fn = queue.pop()
            if id(fn) in seen_ids:
                continue
            seen_ids.add(id(fn))
            traced.append(fn)
            for sub in ast.walk(fn):
                if (isinstance(sub, ast.Call)
                        and isinstance(sub.func, ast.Name)):
                    queue.extend(defs_by_name.get(sub.func.id, ()))
        return traced

    # -- impurity scan -------------------------------------------------- #

    def check(self, src, ctx):
        reported = set()
        for fn in self._traced_defs(src):
            for node in ast.walk(fn):
                if id(node) in reported:
                    continue
                msg = self._impurity(node)
                if msg:
                    reported.add(id(node))
                    yield self.finding(
                        src, node,
                        f"{msg} inside traced function {fn.name!r} — "
                        "it bakes one trace-time value into the "
                        "compiled program (or silently no-ops per "
                        "call); hoist it out or tag "
                        "'# trace-impure-ok'",
                    )

    def _impurity(self, node: ast.AST) -> Optional[str]:
        if isinstance(node, ast.Call):
            name = call_name(node)
            if name in self.IMPURE_CALLS:
                return f"wall-clock read {name}()"
            if name and name.startswith(self.RANDOM_ROOTS):
                return f"host RNG call {name}()"
            if _counter_add_name(node) is not None:
                return "GLOBAL counter mutation"
        elif isinstance(node, (ast.Assign, ast.AugAssign)):
            targets = (node.targets if isinstance(node, ast.Assign)
                       else [node.target])
            for t in targets:
                base = t
                while isinstance(base, (ast.Attribute, ast.Subscript)):
                    base = base.value
                if (isinstance(base, ast.Name) and base.id == "GLOBAL"
                        and base is not t):
                    return "GLOBAL counter mutation"
        return None
