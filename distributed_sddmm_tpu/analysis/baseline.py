"""Committed finding baseline: pre-existing debt doesn't block CI.

The baseline is a JSON list of finding fingerprints. A fingerprint is
``sha256(checker:path:normalized-line-text:ordinal)`` —
content-addressed, not line-numbered, so adding a function above a
baselined site does not invalidate the entry, while *editing the
flagged line itself* does (the edit is exactly the moment the debt
should be repaid or the entry consciously re-baselined). The ordinal
counts byte-identical duplicates in line order, so baselining one
``print('x')`` never covers a second identical one added later;
snippet-less findings (repo-wide ``finish()`` facts) hash the message
instead, so two stale declarations never alias.

Workflow (README "Static analysis"):

* ``bench lint`` — committed tree must exit 0: every finding is either
  tagged at the site or in ``LINT_BASELINE.json``.
* a new violation → exit 2, CI fails loud.
* ``bench lint --write-baseline`` — regenerate the file after a
  deliberate decision to carry new debt (reviewed like any diff).

Stale entries (fingerprints no current finding matches — the debt was
paid) are reported by ``bench lint`` as a note and dropped on the next
``--write-baseline``; they never affect the exit code.
"""

from __future__ import annotations

import hashlib
import json
import pathlib
from typing import Iterable, Optional

from distributed_sddmm_tpu.analysis.core import Finding, repo_root

SCHEMA_VERSION = 1

#: The committed baseline, beside the other root-level committed JSON
#: records (BENCH_r0*.json and friends).
BASELINE_NAME = "LINT_BASELINE.json"


def default_baseline_path() -> Optional[pathlib.Path]:
    p = repo_root() / BASELINE_NAME
    return p if p.exists() else None


def fingerprint(f: Finding, ordinal: int = 0) -> str:
    """Content-addressed identity of one finding (see module doc).

    Snippet-less findings (the ``finish()`` cross-file passes anchor
    whole-repo facts at a file, not a line) fall back to the message so
    two distinct stale declarations never share one fingerprint, and
    ``ordinal`` distinguishes byte-identical duplicate lines in one
    file — baselining the first ``print('x')`` must not silently cover
    a second one added later."""
    norm = " ".join(f.snippet.split()) or f.message
    body = f"{f.checker}:{f.path}:{norm}:{ordinal}"
    return hashlib.sha256(body.encode()).hexdigest()[:16]


def fingerprints(findings: Iterable[Finding]) -> list[str]:
    """Fingerprints aligned with ``findings``, ordinals assigned to
    duplicates in line order (stable across unrelated edits: the first
    occurrence is always ordinal 0)."""
    findings = list(findings)
    counts: dict[tuple, int] = {}
    out = []
    seen: dict[int, str] = {}
    for f in sorted(findings, key=lambda f: (f.checker, f.path, f.line)):
        key = (f.checker, f.path, " ".join(f.snippet.split()) or f.message)
        n = counts.get(key, 0)
        counts[key] = n + 1
        seen[id(f)] = fingerprint(f, n)
    for f in findings:
        out.append(seen[id(f)])
    return out


def load_baseline(path) -> dict:
    """Parse a baseline file. Raises ValueError on schema mismatch or
    unparseable JSON — the CLI maps that to exit 3 (usage/config error,
    not a lint verdict)."""
    path = pathlib.Path(path)
    try:
        doc = json.loads(path.read_text())
    except (OSError, json.JSONDecodeError) as e:
        raise ValueError(f"unreadable baseline {path}: {e}") from e
    if not isinstance(doc, dict) or doc.get("schema") != SCHEMA_VERSION:
        raise ValueError(
            f"baseline {path} has schema {doc.get('schema')!r}, "
            f"expected {SCHEMA_VERSION}"
        )
    return doc


def apply_baseline(findings: Iterable[Finding], baseline: Optional[dict],
                   checkers: Optional[Iterable[str]] = None) -> dict:
    """Mark findings whose fingerprint is baselined. Returns
    ``{"matched": [...], "stale": [...]}`` — stale entries are baseline
    rows no current finding matches (paid-off debt). ``checkers``
    scopes the comparison to a partial run's selection: entries for
    checkers that did not run are out of scope, NOT stale — a
    ``--checker X`` run must never report another checker's live
    suppressions as paid-off debt."""
    findings = list(findings)
    if not baseline:
        return {"matched": [], "stale": []}
    selected = set(checkers) if checkers is not None else None
    entries = {
        e["fingerprint"]: e for e in baseline.get("findings", ())
        if selected is None or e.get("checker") in selected
    }
    matched = set()
    for f, fp in zip(findings, fingerprints(findings)):
        if f.state != "new":
            continue
        if fp in entries:
            f.state = "baselined"
            matched.add(fp)
    return {
        "matched": sorted(matched),
        "stale": [e for fp, e in sorted(entries.items())
                  if fp not in matched],
    }


def write_baseline(path, findings: Iterable[Finding],
                   keep: Iterable[dict] = ()) -> dict:
    """Write the current ``new`` findings as the baseline (atomic —
    the analyzer holds itself to its own atomic-write discipline).
    ``keep`` carries prior entries to preserve verbatim — a partial
    ``--checker X --write-baseline`` run passes the unselected
    checkers' existing entries so regenerating one checker's debt
    never deletes another's."""
    from distributed_sddmm_tpu.utils.atomic import atomic_write_json

    findings = list(findings)
    rows = [
        {
            "fingerprint": fp,
            "checker": f.checker,
            "path": f.path,
            "line": f.line,
            "snippet": " ".join(f.snippet.split())[:90],
        }
        for f, fp in zip(findings, fingerprints(findings))
        if f.state == "new"
    ]
    rows.extend(keep)
    rows.sort(key=lambda e: (e.get("checker", ""), e.get("path", ""),
                             e.get("line", 0)))
    doc = {"schema": SCHEMA_VERSION, "findings": rows}
    atomic_write_json(path, doc)
    return doc
