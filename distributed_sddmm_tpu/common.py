"""Shared enums and small utilities.

Mirrors the role of the reference's ``common.h`` / ``common.cpp``
(`/root/reference/common.h:21-33`, `common.cpp:16-27`): kernel-mode and
matrix-mode enums plus integer helpers. The reference's ``BufferPair``
double-buffer and MPI datatype registration have no equivalent here — XLA
double-buffers ``ppermute`` internally and sharded ``jax.Array``s need no wire
types.
"""

from __future__ import annotations

import enum


class KernelMode(enum.Enum):
    """The four distributed-op modes (reference `sparse_kernels.h:13`).

    * ``SDDMM_A`` — ``out_vals = S_vals * (A @ B^T sampled at pattern(S))``
    * ``SPMM_A``  — ``A += S @ B``
    * ``SPMM_B``  — ``B += S^T @ A``
    * ``SDDMM_B`` — SDDMM computed against the transposed representation
      (values returned in S^T's canonical nonzero order).
    """

    SDDMM_A = "sddmmA"
    SPMM_A = "spmmA"
    SPMM_B = "spmmB"
    SDDMM_B = "sddmmB"


class MatMode(enum.Enum):
    """Which dense matrix plays the output role (reference `common.h:21`)."""

    A = "Amat"
    B = "Bmat"


def p_mod(num: int, denom: int) -> int:
    """Positive modulus (reference `common.cpp:16-18`)."""
    return ((num % denom) + denom) % denom


def divide_round_up(num: int, denom: int) -> int:
    """Ceiling division (reference `common.cpp:24-27`)."""
    return -(-num // denom)
