"""Structured attention-mask generators: block-sparse patterns as HostCOO.

SDDMM ⊙ masked-softmax → SpMM *is* sparse attention, and the mask IS
the sparse matrix: every generator here returns a unit-valued
:class:`~distributed_sddmm_tpu.utils.coo.HostCOO` whose pattern is the
attention mask (``vals == 1`` at attended positions — the ``gate != 0``
indicator the softmax kernels read; callers may rescale values to carry
per-edge logit weights or temperature). Three families, the structured
regimes the codegen band selector must degenerate gracefully on
(ROADMAP item 5 / NeutronSparse-style structure routing):

* :func:`sliding_window` — each token attends to its ±w neighborhood
  (near-uniform nnz/row: the anti-power-law stress case for banding);
* :func:`bigbird` — sliding window ∪ global tokens (attend/attended
  everywhere) ∪ seeded random links, the BigBird recipe;
* :func:`graph_mask` — the pattern of an existing sparse matrix (the
  GAT adjacency path: attention over graph edges).

:func:`from_spec` parses the ``--mask`` CLI grammar
(``window:8``, ``bigbird:w=8,g=2,r=2``, ``graph``) so bench records can
carry the mask as one printable config axis.

Import discipline: numpy + HostCOO only (no jax) — mask construction is
host-side ingest work, usable from offline tooling.
"""

from __future__ import annotations

import numpy as np

from distributed_sddmm_tpu.utils.coo import HostCOO


def _dedup(rows: np.ndarray, cols: np.ndarray, n: int) -> HostCOO:
    key = rows.astype(np.int64) * n + cols.astype(np.int64)
    key = np.unique(key)
    return HostCOO(
        rows=key // n, cols=key % n, vals=np.ones(key.size), M=n, N=n
    )


def sliding_window(n: int, window: int = 8) -> HostCOO:
    """Each row ``i`` attends to columns ``[i-window, i+window]``
    (clipped at the edges), diagonal included — near-uniform
    ``2*window+1`` nnz/row."""
    if window < 0:
        raise ValueError(f"window must be >= 0, got {window}")
    offs = np.arange(-window, window + 1, dtype=np.int64)
    rows = np.repeat(np.arange(n, dtype=np.int64), offs.size)
    cols = rows + np.tile(offs, n)
    keep = (cols >= 0) & (cols < n)
    return HostCOO(
        rows=rows[keep], cols=cols[keep], vals=np.ones(int(keep.sum())),
        M=n, N=n,
    )


def bigbird(
    n: int,
    window: int = 8,
    n_global: int = 2,
    n_random: int = 2,
    seed: int = 0,
) -> HostCOO:
    """BigBird-style mask: sliding window ∪ ``n_global`` global tokens
    (their full rows AND columns) ∪ ``n_random`` seeded random columns
    per row. Deduplicated union; deterministic for a given seed."""
    base = sliding_window(n, window)
    parts_r = [base.rows]
    parts_c = [base.cols]
    if n_global:
        g = np.arange(min(n_global, n), dtype=np.int64)
        full = np.arange(n, dtype=np.int64)
        # Global rows: g attends everywhere; global cols: everyone
        # attends g.
        parts_r += [np.repeat(g, n), np.repeat(full, g.size)]
        parts_c += [np.tile(full, g.size), np.tile(g, n)]
    if n_random:
        rng = np.random.default_rng(seed)
        rr = np.repeat(np.arange(n, dtype=np.int64), n_random)
        rc = rng.integers(0, n, size=n * n_random).astype(np.int64)
        parts_r.append(rr)
        parts_c.append(rc)
    return _dedup(np.concatenate(parts_r), np.concatenate(parts_c), n)


def graph_mask(S: HostCOO) -> HostCOO:
    """Attention mask from an existing sparse pattern (the GAT path:
    attend over graph edges). Unit values; duplicate edges collapse."""
    n = max(S.M, S.N)
    return _dedup(S.rows, S.cols, n)


def from_spec(
    spec: str,
    n: int,
    graph: HostCOO | None = None,
    seed: int = 0,
) -> HostCOO:
    """Parse one ``--mask`` spec into a mask matrix over ``n`` tokens.

    Grammar (printable, colon-free after the family tag — the spec rides
    into bench records and the runstore config axes verbatim):

    * ``window:<w>`` — :func:`sliding_window` with half-width ``w``;
    * ``bigbird:w=<w>,g=<g>,r=<r>`` — :func:`bigbird` (all keys
      optional, defaults ``w=8,g=2,r=2``);
    * ``graph`` — :func:`graph_mask` over ``graph`` (the benchmark's
      generated/loaded matrix; required).
    """
    fam, _, rest = spec.partition(":")
    if fam == "window":
        return sliding_window(n, _int_param(spec, "window", rest, "w", 8))
    if fam == "bigbird":
        kw = {"w": 8, "g": 2, "r": 2}
        for part in filter(None, rest.split(",")):
            k, _, v = part.partition("=")
            if k not in kw:
                raise ValueError(
                    f"unknown bigbird key {k!r} in mask spec {spec!r}"
                )
            kw[k] = int(v)
        return bigbird(
            n, window=kw["w"], n_global=kw["g"], n_random=kw["r"], seed=seed
        )
    if fam == "graph":
        if graph is None:
            raise ValueError("mask spec 'graph' needs a source matrix")
        return graph_mask(graph)
    if fam == "topk":
        raise ValueError(
            f"mask spec {spec!r} is request-time dynamic (the kept "
            "positions depend on the computed scores) — it has no static "
            "mask matrix; serve it through a dynamic-mask workload "
            "(parse_dynamic_spec)"
        )
    raise ValueError(
        f"unknown mask spec {spec!r}; expected window:<w>, "
        "bigbird:w=..,g=..,r=.., graph, or topk:<k>"
    )


# --------------------------------------------------------------------- #
# Request-time dynamic mask specs (PR 20, ``dynstruct/``)
# --------------------------------------------------------------------- #

#: The families a dynamic-mask serving workload resolves per request —
#: parameterized window narrowing and score top-k. Both are *runtime*
#: program inputs of a capacity-sized program, never trace constants.
DYNAMIC_FAMILIES = ("window", "topk")


def _int_param(spec: str, fam: str, rest: str, key: str, default) -> int:
    """One strict integer parameter: ``fam:<v>`` or ``fam:key=<v>``;
    unknown keys and non-integers error in the SLOSpec style."""
    rest = rest.strip()
    if not rest:
        if default is None:
            raise ValueError(
                f"mask spec {spec!r} needs a value "
                f"({fam}:<{key}> or {fam}:{key}=<{key}>)"
            )
        return int(default)
    if "=" in rest:
        k, _, v = rest.partition("=")
        if k != key:
            raise ValueError(
                f"unknown {fam} key {k!r} in mask spec {spec!r}"
            )
        rest = v
    try:
        return int(rest)
    except ValueError:
        raise ValueError(
            f"mask spec {spec!r}: {key} must be an integer, got {rest!r}"
        ) from None


def parse_dynamic_spec(
    spec: str,
    w_max: int | None = None,
    k_max: int | None = None,
) -> tuple[str, int]:
    """Parse one per-request dynamic mask spec -> ``(kind, param)``.

    Grammar: ``window:<w>`` / ``window:w=<w>`` (attend to the ±w
    neighborhood, ``w >= 0``) and ``topk:<k>`` / ``topk:k=<k>`` (keep
    the k highest-scoring in-capacity positions, ``k >= 1``; ties at
    the threshold are all kept — deterministic, order-free). ``w_max``
    / ``k_max`` bound the parameters to the serving program's capacity:
    a request can narrow its mask at runtime but never widen past what
    the compiled program gathered.
    """
    fam, _, rest = spec.partition(":")
    if fam == "window":
        w = _int_param(spec, "window", rest, "w", None)
        if w < 0:
            raise ValueError(f"mask spec {spec!r}: w must be >= 0")
        if w_max is not None and w > w_max:
            raise ValueError(
                f"mask spec {spec!r}: w exceeds the serving capacity "
                f"w_max={w_max}"
            )
        return "window", w
    if fam == "topk":
        k = _int_param(spec, "topk", rest, "k", None)
        if k < 1:
            raise ValueError(f"mask spec {spec!r}: k must be >= 1")
        if k_max is not None and k > k_max:
            raise ValueError(
                f"mask spec {spec!r}: k exceeds the serving capacity "
                f"k_max={k_max}"
            )
        return "topk", k
    raise ValueError(
        f"unknown dynamic mask spec {spec!r}; expected one of "
        f"{[f + ':<n>' for f in DYNAMIC_FAMILIES]}"
    )


def format_dynamic_spec(kind: str, param: int) -> str:
    """Canonical printable form of a dynamic mask: round-trips through
    :func:`parse_dynamic_spec` (the form records and payloads carry)."""
    if kind not in DYNAMIC_FAMILIES:
        raise ValueError(
            f"unknown dynamic mask kind {kind!r}; expected one of "
            f"{DYNAMIC_FAMILIES}"
        )
    return f"{kind}:{int(param)}"
