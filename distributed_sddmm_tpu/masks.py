"""Structured attention-mask generators: block-sparse patterns as HostCOO.

SDDMM ⊙ masked-softmax → SpMM *is* sparse attention, and the mask IS
the sparse matrix: every generator here returns a unit-valued
:class:`~distributed_sddmm_tpu.utils.coo.HostCOO` whose pattern is the
attention mask (``vals == 1`` at attended positions — the ``gate != 0``
indicator the softmax kernels read; callers may rescale values to carry
per-edge logit weights or temperature). Three families, the structured
regimes the codegen band selector must degenerate gracefully on
(ROADMAP item 5 / NeutronSparse-style structure routing):

* :func:`sliding_window` — each token attends to its ±w neighborhood
  (near-uniform nnz/row: the anti-power-law stress case for banding);
* :func:`bigbird` — sliding window ∪ global tokens (attend/attended
  everywhere) ∪ seeded random links, the BigBird recipe;
* :func:`graph_mask` — the pattern of an existing sparse matrix (the
  GAT adjacency path: attention over graph edges).

:func:`from_spec` parses the ``--mask`` CLI grammar
(``window:8``, ``bigbird:w=8,g=2,r=2``, ``graph``) so bench records can
carry the mask as one printable config axis.

Import discipline: numpy + HostCOO only (no jax) — mask construction is
host-side ingest work, usable from offline tooling.
"""

from __future__ import annotations

import numpy as np

from distributed_sddmm_tpu.utils.coo import HostCOO


def _dedup(rows: np.ndarray, cols: np.ndarray, n: int) -> HostCOO:
    key = rows.astype(np.int64) * n + cols.astype(np.int64)
    key = np.unique(key)
    return HostCOO(
        rows=key // n, cols=key % n, vals=np.ones(key.size), M=n, N=n
    )


def sliding_window(n: int, window: int = 8) -> HostCOO:
    """Each row ``i`` attends to columns ``[i-window, i+window]``
    (clipped at the edges), diagonal included — near-uniform
    ``2*window+1`` nnz/row."""
    if window < 0:
        raise ValueError(f"window must be >= 0, got {window}")
    offs = np.arange(-window, window + 1, dtype=np.int64)
    rows = np.repeat(np.arange(n, dtype=np.int64), offs.size)
    cols = rows + np.tile(offs, n)
    keep = (cols >= 0) & (cols < n)
    return HostCOO(
        rows=rows[keep], cols=cols[keep], vals=np.ones(int(keep.sum())),
        M=n, N=n,
    )


def bigbird(
    n: int,
    window: int = 8,
    n_global: int = 2,
    n_random: int = 2,
    seed: int = 0,
) -> HostCOO:
    """BigBird-style mask: sliding window ∪ ``n_global`` global tokens
    (their full rows AND columns) ∪ ``n_random`` seeded random columns
    per row. Deduplicated union; deterministic for a given seed."""
    base = sliding_window(n, window)
    parts_r = [base.rows]
    parts_c = [base.cols]
    if n_global:
        g = np.arange(min(n_global, n), dtype=np.int64)
        full = np.arange(n, dtype=np.int64)
        # Global rows: g attends everywhere; global cols: everyone
        # attends g.
        parts_r += [np.repeat(g, n), np.repeat(full, g.size)]
        parts_c += [np.tile(full, g.size), np.tile(g, n)]
    if n_random:
        rng = np.random.default_rng(seed)
        rr = np.repeat(np.arange(n, dtype=np.int64), n_random)
        rc = rng.integers(0, n, size=n * n_random).astype(np.int64)
        parts_r.append(rr)
        parts_c.append(rc)
    return _dedup(np.concatenate(parts_r), np.concatenate(parts_c), n)


def graph_mask(S: HostCOO) -> HostCOO:
    """Attention mask from an existing sparse pattern (the GAT path:
    attend over graph edges). Unit values; duplicate edges collapse."""
    n = max(S.M, S.N)
    return _dedup(S.rows, S.cols, n)


def from_spec(
    spec: str,
    n: int,
    graph: HostCOO | None = None,
    seed: int = 0,
) -> HostCOO:
    """Parse one ``--mask`` spec into a mask matrix over ``n`` tokens.

    Grammar (printable, colon-free after the family tag — the spec rides
    into bench records and the runstore config axes verbatim):

    * ``window:<w>`` — :func:`sliding_window` with half-width ``w``;
    * ``bigbird:w=<w>,g=<g>,r=<r>`` — :func:`bigbird` (all keys
      optional, defaults ``w=8,g=2,r=2``);
    * ``graph`` — :func:`graph_mask` over ``graph`` (the benchmark's
      generated/loaded matrix; required).
    """
    fam, _, rest = spec.partition(":")
    if fam == "window":
        return sliding_window(n, int(rest or "8"))
    if fam == "bigbird":
        kw = {"w": 8, "g": 2, "r": 2}
        for part in filter(None, rest.split(",")):
            k, _, v = part.partition("=")
            if k not in kw:
                raise ValueError(
                    f"unknown bigbird key {k!r} in mask spec {spec!r}"
                )
            kw[k] = int(v)
        return bigbird(
            n, window=kw["w"], n_global=kw["g"], n_random=kw["r"], seed=seed
        )
    if fam == "graph":
        if graph is None:
            raise ValueError("mask spec 'graph' needs a source matrix")
        return graph_mask(graph)
    raise ValueError(
        f"unknown mask spec {spec!r}; expected window:<w>, "
        "bigbird:w=..,g=..,r=.., or graph"
    )
