"""TPU-native distributed SDDMM / SpMM framework.

A ground-up JAX/XLA/Pallas re-design of the capabilities of
PASSIONLab/distributed_sddmm ("Half-and-Half"): communication-avoiding 1.5D /
2.5D distributed algorithms for SpMM (sparse x tall-skinny dense) and SDDMM
(sampled dense-dense matmul), two SDDMM->SpMM fusion strategies, a pluggable
local-kernel boundary, and the ALS-CG / GAT driver applications.

Where the reference uses MPI communicators (FlexibleGrid.hpp), this framework
uses a named 3-D `jax.sharding.Mesh`; where it ring-shifts buffers with
`MPI_Sendrecv` / `MPI_Isend` (distributed_sparse.h:351-361, SpmatLocal.hpp:200-259),
this framework uses `jax.lax.ppermute` inside `shard_map`; replication /
reduction (`MPI_Allgather` / `MPI_Reduce_scatter`) become `lax.all_gather` /
`lax.psum_scatter` over named mesh axes.
"""

from distributed_sddmm_tpu.common import KernelMode, MatMode

__version__ = "0.1.0"

__all__ = ["KernelMode", "MatMode", "__version__"]
