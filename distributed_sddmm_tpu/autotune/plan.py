"""The Plan record and the ``get_plan`` selection entry point.

Selection order, fastest knowledge first:

1. **Cache hit** — a stored plan for this exact fingerprint returns
   immediately: zero measured trials, zero strategy builds, well under a
   second.
2. **Warm start** — committed sweep/heatmap records seed a candidate
   (verified for legality against the current mesh before being trusted).
3. **Cost model** — candidates enumerated, HBM-guarded, and ranked by the
   analytic models.
4. **Measurement** (``mode="measure"`` or ``mode="auto"`` with the sparse
   matrix available) — the top-ranked few candidates run short trials
   through the bench harness under per-trial timeouts; the measured winner
   takes the plan. Every measurement failure mode degrades to step 3's
   ranking — a dead backend can cost selection quality, never a hang or an
   exception.

The chosen plan is stored back under the fingerprint key, so the next
process with the same problem, mesh, backend and code generation takes
path 1.
"""

from __future__ import annotations

import dataclasses
from typing import Callable, Optional

from distributed_sddmm_tpu.autotune import cache as cache_mod
from distributed_sddmm_tpu.autotune import candidates as cand_mod
from distributed_sddmm_tpu.autotune import measure as measure_mod
from distributed_sddmm_tpu.autotune.cache import PlanCache
from distributed_sddmm_tpu.autotune.candidates import Candidate
from distributed_sddmm_tpu.autotune.fingerprint import (
    Problem, machine_signature, make_fingerprint,
)

MODES = ("auto", "model", "measure")


@dataclasses.dataclass
class Plan:
    """A selected execution configuration for one fingerprinted problem."""

    algorithm: str
    c: int
    kernel: str = "xla"
    block: tuple | None = None
    gather_budget: int | None = None
    #: Codegen kernel-variant id (``codegen/variants.py``); None = the
    #: generic kernel. Optional field — pre-PR-9 cached plans load with
    #: None, and a plan carrying an unknown variant generation falls
    #: back to the generic kernel at build time.
    variant: str | None = None
    #: Wire-precision comm dtype (``parallel/wire.py``); None = the f32
    #: identity wire. Optional field — pre-PR-15 cached plans load with
    #: None and build byte-identical strategies.
    wire: str | None = None
    source: str = "model"            # model | measured | seed
    predicted_ms: float | None = None
    measured_gflops: float | None = None
    fingerprint_key: str = ""

    def to_dict(self) -> dict:
        d = dataclasses.asdict(self)
        d["block"] = list(self.block) if self.block else None
        return d

    @classmethod
    def from_dict(cls, d: dict) -> "Plan":
        block = d.get("block")
        return cls(
            algorithm=d["algorithm"],
            c=int(d["c"]),
            kernel=d.get("kernel", "xla"),
            block=tuple(block) if block else None,
            gather_budget=d.get("gather_budget"),
            variant=d.get("variant"),
            wire=d.get("wire"),
            source=d.get("source", "model"),
            predicted_ms=d.get("predicted_ms"),
            measured_gflops=d.get("measured_gflops"),
            fingerprint_key=d.get("fingerprint_key", ""),
        )

    def candidate(self) -> Candidate:
        return Candidate(
            algorithm=self.algorithm, c=self.c, kernel=self.kernel,
            block=self.block, gather_budget=self.gather_budget,
            variant=self.variant, wire=self.wire,
        )

    def make_kernel(self):
        return measure_mod._build_kernel(self.candidate())

    def instantiate(self, S, R: int, devices=None, program_store=None, **kw):
        """Build the planned strategy for a concrete sparse matrix through
        the harness factory (same five magic strings). ``R`` is passed
        explicitly — plans are selected per problem and do not carry the
        problem with them.

        When the persistent program store is active (``programs/``;
        ``program_store`` overrides, ``DSDDMM_PROGRAMS=0`` vetoes), the
        strategy is bound to it under this plan's fingerprint key: every
        shard_map program the strategy compiles is then recalled from
        ``artifacts/programs/`` when a previous process already built it,
        and persisted when not — the zero-live-compile warm start the
        plan cache gives selection, extended to compilation."""
        from distributed_sddmm_tpu.bench.harness import make_algorithm

        with measure_mod.block_knobs(self.candidate()):
            alg = make_algorithm(
                self.algorithm, S, R=R, c=self.c,
                kernel=self.make_kernel(), devices=devices,
                wire=self.wire, **kw
            )
        if self.fingerprint_key:
            from distributed_sddmm_tpu import programs

            programs.bind_strategy(
                alg, self.fingerprint_key, store=program_store,
                content_key=programs.matrix_content_key(S),
            )
        return alg


def _seed_candidate(
    problem: Problem, p: int, backend: str, kernels: tuple[str, ...],
) -> Optional[Candidate]:
    """A legality-checked candidate from committed offline records.

    Only a matching *winner* record (algorithm + c actually measured on
    this problem shape) seeds a candidate; the kernel-family records can
    refine its kernel choice but never fabricate an algorithm/c on their
    own — without a winner match, the cost model's ranking stands (it
    already weighs kernel families through their measured rates).
    """
    seed = cache_mod.seed_winner_plan(problem, p)
    if seed is None:
        return None
    algorithm, c = seed.get("algorithm"), seed.get("c")
    kernel = cache_mod.seed_kernel_family(problem, backend)
    kernel = kernel if kernel in kernels else "xla"
    if algorithm not in cand_mod.ALGORITHM_MODELS:
        return None
    if c not in cand_mod.legal_c_values(algorithm, p, problem.R):
        return None
    cand = Candidate(algorithm=algorithm, c=int(c), kernel=kernel)
    return cand_mod.hbm_guard(problem, cand, p)


def get_plan(
    problem: Problem,
    devices=None,
    S=None,
    *,
    mode: str = "auto",
    cache: Optional[PlanCache] = None,
    machine=None,
    top_k: int = 3,
    trials: int = 2,
    warmup: int = 1,
    timeout_s: float = 120.0,
    retries: int = 1,
    backoff_s: float = 2.0,
    jitter: float = 0.25,
    max_elapsed_s: float = 900.0,
    trial_fn: Optional[Callable] = None,
) -> Plan:
    """Select (or recall) the execution plan for a fingerprinted problem.

    ``mode``: ``"model"`` never measures; ``"measure"`` requires ``S`` and
    measures the top-``top_k`` model-ranked candidates; ``"auto"``
    measures only when ``S`` is provided. All modes hit the cache first
    and store their result.

    ``trial_fn`` (tests, alternative backends) replaces the harness trial:
    ``trial_fn(S, problem, candidate, trials, warmup) -> record``.
    """
    if mode not in MODES:
        raise ValueError(f"mode must be one of {MODES}")
    if mode == "measure" and S is None:
        raise ValueError("mode='measure' needs the sparse matrix S")

    from distributed_sddmm_tpu.obs import metrics as obs_metrics
    from distributed_sddmm_tpu.obs import trace as obs_trace

    p, backend, kernels = machine_signature(devices)
    fp = make_fingerprint(problem, p, backend, kernels)
    cache = cache if cache is not None else PlanCache()

    hit = cache.load(fp.key)
    if hit is not None:
        # An explicit measure request upgrades a cached model/seed guess:
        # serving it would make '--plan-mode measure' a silent no-op
        # forever after any model-mode call warmed the key. Measured
        # plans always serve (zero-trial hits are the point).
        if not (mode == "measure" and hit.get("source") != "measured"):
            obs_metrics.GLOBAL.add("plan_cache_hits")
            obs_trace.event(
                "plan_cache_hit", key=fp.key,
                algorithm=hit.get("algorithm"), c=hit.get("c"),
                source=hit.get("source"),
            )
            return Plan.from_dict(hit)
    obs_metrics.GLOBAL.add("plan_cache_misses")

    cands = cand_mod.enumerate_candidates(problem, p, kernels)
    if not cands:
        raise ValueError(
            f"no constructible algorithm configuration for {problem} "
            f"on p={p} (check R divisibility constraints)"
        )
    ranked = cand_mod.rank_candidates(problem, cands, p, machine)

    seed = _seed_candidate(problem, p, backend, kernels)
    seeded_first = ranked
    if seed is not None:
        seeded_first = [cs for cs in ranked if cs[0] == seed]
        seeded_first += [cs for cs in ranked if cs[0] != seed]
        if not seeded_first or seeded_first[0][0] != seed:
            # Seed survived legality but not enumeration (e.g. guard
            # rewrote it) — score it explicitly and lead with it.
            seeded_first = [
                (seed, cand_mod.model_cost(problem, seed, p, machine))
            ] + ranked

    measured: list = []
    if mode == "measure" or (mode == "auto" and S is not None):
        short_list = [cand for cand, _ in seeded_first[:top_k]]
        measured = measure_mod.measure_candidates(
            S, problem, short_list,
            trials=trials, warmup=warmup, timeout_s=timeout_s,
            retries=retries, backoff_s=backoff_s, jitter=jitter,
            max_elapsed_s=max_elapsed_s, trial_fn=trial_fn,
        )

    if measured:
        best_cand, rec = measured[0]
        plan = Plan(
            algorithm=best_cand.algorithm, c=best_cand.c,
            kernel=best_cand.kernel, block=best_cand.block,
            gather_budget=best_cand.gather_budget,
            variant=best_cand.variant,
            wire=best_cand.wire,
            source="measured",
            predicted_ms=_predicted_ms(problem, best_cand, p, machine),
            measured_gflops=rec.get("overall_throughput"),
            fingerprint_key=fp.key,
        )
    else:
        best_cand, cost = seeded_first[0]
        plan = Plan(
            algorithm=best_cand.algorithm, c=best_cand.c,
            kernel=best_cand.kernel, block=best_cand.block,
            gather_budget=best_cand.gather_budget,
            variant=best_cand.variant,
            wire=best_cand.wire,
            source="seed" if seed is not None and best_cand == seed else "model",
            predicted_ms=cost * 1e3,
            fingerprint_key=fp.key,
        )

    obs_trace.event(
        "plan_selected", key=fp.key, algorithm=plan.algorithm, c=plan.c,
        kernel=plan.kernel, source=plan.source,
        measured=len(measured),
    )
    cache.store(fp.key, plan.to_dict())
    return plan


def _predicted_ms(problem, cand, p, machine) -> float:
    return cand_mod.model_cost(problem, cand, p, machine) * 1e3
