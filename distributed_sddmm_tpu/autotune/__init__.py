"""Autotune subsystem: problem-fingerprinted plan selection.

The paper's central artifact is a winner map — which of the five algorithm
configurations (1.5D dense/sparse shift, 2.5D Cannon dense/sparse, plus
fusion strategy) wins at a given (M, nnz/row, R, p, c). This package turns
that knowledge — analytic (``tools/costmodel.py``), measured offline
(``KERNELS_TPU.jsonl``, ``artifacts/cpu_mesh``), or measured on demand —
into automatic plan selection at run time, following the auto-tuning
pattern of communication-avoiding frameworks (Bharadwaj et al., IPDPS
2022; replication-factor selection after Koanantakool et al.'s 2.5D work).

Layout:

* :mod:`.fingerprint` — canonical problem signature + stable cache key
* :mod:`.candidates`  — legal candidate-plan enumeration, cost-model
  ranking, HBM-footprint guards (heavy corners route to the chunked XLA
  kernel instead of OOMing)
* :mod:`.measure`     — short measured trials with per-trial timeout and
  retry-with-backoff; degrades to cost-model ranking, never hangs
* :mod:`.cache`       — versioned, atomically-written JSON plan cache
  under ``artifacts/plan_cache/``, warm-started from committed sweep and
  heatmap records
* :mod:`.plan`        — the :class:`Plan` record and :func:`get_plan`
  entry point

Entry points::

    from distributed_sddmm_tpu.autotune import Problem, get_plan
    plan = get_plan(Problem.from_coo(S, R))    # model-ranked, cached
    alg = plan.instantiate(S, R=R)             # a DistributedSparse

or ``--algorithm auto`` on the bench CLI.
"""

from distributed_sddmm_tpu.autotune.candidates import Candidate, enumerate_candidates
from distributed_sddmm_tpu.autotune.cache import PlanCache, SCHEMA_VERSION
from distributed_sddmm_tpu.autotune.fingerprint import Problem, make_fingerprint
from distributed_sddmm_tpu.autotune.plan import Plan, get_plan

__all__ = [
    "Candidate",
    "Plan",
    "PlanCache",
    "Problem",
    "SCHEMA_VERSION",
    "enumerate_candidates",
    "get_plan",
    "make_fingerprint",
]
