"""Short measured trials for candidate plans, with timeout and backoff.

The measurement path exists because the analytic models are first-order:
when the backend is healthy, a few short trials through the existing
``bench/harness.py`` timing path beat any model. But the tunneled TPU
backend is *not* always healthy — round 5's sweep log is a string of
"attempt timed out after 600s" entries — so every trial runs under a
per-trial timeout with retry-and-exponential-backoff, and a candidate
whose trials all fail is simply dropped. When every candidate drops, the
caller (``plan.get_plan``) falls back to cost-model ranking: a flaky
backend degrades selection quality, it never hangs or raises.

Timeouts run through the shared resilience utility
(``resilience.retry.call_with_timeout``): a daemon-thread join bound that
works from ANY thread — the SIGALRM path this replaced could only arm on
the main thread, so worker-thread autotuning ran unbounded. Backoff
between retries carries proportional jitter (fixed steps re-synchronize
workers that failed together) and a max-elapsed cap (a dead backend fails
fast instead of compounding exponential sleeps). The trial function is
injectable (``trial_fn``) so tests simulate timeouts and count
invocations without ever touching a backend.
"""

from __future__ import annotations

import contextlib
import time
from typing import Callable, Optional

from distributed_sddmm_tpu.autotune.candidates import Candidate
from distributed_sddmm_tpu.autotune.fingerprint import Problem
from distributed_sddmm_tpu.resilience.retry import Backoff, CallTimeout, call_with_timeout


class MeasureTimeout(CallTimeout):
    """One measured trial exceeded its wall-clock budget."""


def _build_kernel(cand: Candidate):
    """The kernel instance a candidate names (chunked XLA = budget
    override; Pallas block config applied via :func:`block_knobs`;
    codegen variant id -> the banked specialized kernel, falling back
    to the generic Pallas kernel when the id's variant generation is
    unknown to this code)."""
    from distributed_sddmm_tpu.ops.kernels import XlaKernel, get_kernel

    if cand.kernel == "xla":
        return XlaKernel(gather_budget=cand.gather_budget)
    if cand.variant:
        from distributed_sddmm_tpu import codegen
        from distributed_sddmm_tpu.obs import log as obs_log

        try:
            return codegen.make_banked_kernel(cand.variant)
        except ValueError as e:
            obs_log.warn(
                "codegen",
                "unknown kernel variant; generic pallas fallback",
                variant=cand.variant, error=str(e),
            )
            from distributed_sddmm_tpu.obs import metrics as obs_metrics

            obs_metrics.GLOBAL.add("codegen_generic_fallbacks")
    return get_kernel(cand.kernel)


@contextlib.contextmanager
def block_knobs(cand: Candidate):
    """Apply a candidate's Pallas block config while its strategy is
    BUILT (the blocked tile chunk lists bake geometry at ingest).

    The knob defaults live as module attributes of ``ops.blocked``,
    initialized from env at first import — so a per-candidate config must
    rebind the module attributes; mutating the env vars here would be a
    silent no-op (the snapshot already happened)."""
    if cand.kernel != "pallas" or cand.block is None:
        yield
        return
    from distributed_sddmm_tpu.ops import blocked

    saved = (blocked.DEFAULT_BLOCK_ROWS, blocked.DEFAULT_BLOCK_COLS)
    blocked.DEFAULT_BLOCK_ROWS, blocked.DEFAULT_BLOCK_COLS = cand.block
    try:
        yield
    finally:
        blocked.DEFAULT_BLOCK_ROWS, blocked.DEFAULT_BLOCK_COLS = saved


def default_trial(
    S, problem: Problem, cand: Candidate, trials: int, warmup: int
) -> dict:
    """One short measured run through the bench harness timing path.
    Returns the harness record (``overall_throughput`` in GFLOP/s)."""
    from distributed_sddmm_tpu.bench.harness import benchmark_algorithm
    from distributed_sddmm_tpu.obs import store as obs_store

    # A candidate trial is a probe, not a run: keep it out of the run
    # store (it would share the real run's fingerprint key AND config
    # axes, silently skewing the regression gate's rolling baseline).
    with obs_store.suppressed(), block_knobs(cand):
        return benchmark_algorithm(
            S,
            cand.algorithm,
            None,
            fused=True,
            R=problem.R,
            c=cand.c,
            trials=trials,
            warmup=warmup,
            kernel=_build_kernel(cand),
            wire=cand.wire,
        )


def measure_candidates(
    S,
    problem: Problem,
    cands: list[Candidate],
    *,
    trials: int = 2,
    warmup: int = 1,
    timeout_s: float = 120.0,
    retries: int = 1,
    backoff_s: float = 2.0,
    jitter: float = 0.25,
    max_elapsed_s: float = 900.0,
    trial_fn: Optional[Callable] = None,
    sleep: Callable[[float], None] = time.sleep,
    monotonic: Callable[[], float] = time.monotonic,
    rng=None,
) -> list[tuple[Candidate, dict]]:
    """Measure each candidate; return the (candidate, record) pairs that
    produced a number, fastest-first by measured throughput.

    Per candidate: up to ``retries + 1`` attempts, each under ``timeout_s``
    wall-clock, with ``backoff_s * 2**attempt * (1 + U(0, jitter))`` sleeps
    between (a flaky tunnel often recovers within one backoff window; the
    jitter keeps a fleet of workers that timed out together from re-arriving
    together). ``max_elapsed_s`` caps the whole candidate's attempt budget
    — a dead backend fails fast instead of serializing 600s hangs across
    the whole candidate list. Construction errors (divisibility, kernel
    availability) drop the candidate immediately — retrying a deterministic
    failure wastes budget.

    Every trial attempt runs under an ``autotune:trial`` trace span
    (algorithm/c/kernel/attempt, plus the measured throughput or the
    failure), so a traced run makes plan selection explainable: the
    report shows which candidates were tried, how long each took, and
    why losers lost.
    """
    from distributed_sddmm_tpu.obs import log, metrics, trace

    run = trial_fn or default_trial
    out = []
    for cand in cands:
        backoff = Backoff(
            base_s=backoff_s, jitter=jitter, max_delay_s=float("inf"),
            max_elapsed_s=max_elapsed_s, rng=rng,
        )
        t_start = monotonic()
        last_err = None
        for attempt in range(retries + 1):
            with trace.span(
                "autotune:trial", algorithm=cand.algorithm, c=cand.c,
                kernel=cand.kernel, attempt=attempt,
            ) as sp:
                try:
                    rec = call_with_timeout(
                        lambda: run(S, problem, cand, trials, warmup),
                        timeout_s, label=f"trial:{cand.algorithm}",
                    )
                    sp.set(gflops=rec.get("overall_throughput"))
                    out.append((cand, rec))
                    last_err = None
                    break
                except ValueError as e:
                    sp.set(failed=f"{type(e).__name__}")
                    last_err = e
                    break  # unconstructible; enumeration bug or stale seed
                except Exception as e:  # noqa: BLE001 — failure = drop+note
                    sp.set(failed=f"{type(e).__name__}")
                    last_err = e
            if last_err is not None and attempt < retries:
                d = backoff.delay(attempt)
                if not backoff.budget_left(monotonic() - t_start, d):
                    break  # elapsed cap: fail this candidate fast
                metrics.GLOBAL.add("autotune_trial_retries")
                sleep(d)
        if last_err is not None:
            # The degradation (candidate dropped, possibly down to pure
            # cost-model ranking) must be observable, not silent.
            metrics.GLOBAL.add("autotune_candidates_dropped")
            trace.event(
                "autotune_candidate_dropped", algorithm=cand.algorithm,
                c=cand.c, kernel=cand.kernel,
                error=type(last_err).__name__,
            )
            log.warn(
                "autotune",
                f"dropped {cand.algorithm} c={cand.c} kernel={cand.kernel}",
                error=f"{type(last_err).__name__}: {last_err}",
            )
    out.sort(
        key=lambda cr: cr[1].get("overall_throughput", 0.0), reverse=True
    )
    return out
