"""Versioned, atomically-written JSON plan cache + warm-start seeding.

One file per fingerprint key under ``artifacts/plan_cache/`` — atomic
writes (temp file + ``os.replace``) mean a reader can never observe a
half-written plan, and per-key files mean concurrent tuners of different
problems never contend. Every file carries ``schema_version``; a bump
invalidates old entries (they read as misses and are overwritten on the
next store). Corrupt or truncated files — a killed process, a full disk —
also read as misses: the cache is a pure accelerator, never a source of
errors.

Warm start: before the first measurement a cold cache consults the repo's
committed knowledge — ``KERNELS_TPU.jsonl`` (which kernel family wins a
grid point on real TPU) and the heatmap-style records under
``artifacts/cpu_mesh`` (which algorithm/c wins a problem shape on the
8-device mesh). A matching record yields a seed plan dict (source
``"seed"``) that selection verifies for legality before trusting.
"""

from __future__ import annotations

import json
import math
import os
import pathlib

from distributed_sddmm_tpu.autotune.fingerprint import Problem
from distributed_sddmm_tpu.utils.atomic import atomic_write_json

_REPO = pathlib.Path(__file__).resolve().parents[2]

#: Plan-record schema generation. Bump on any incompatible change to the
#: stored plan dict; old entries then read as misses.
SCHEMA_VERSION = 1

DEFAULT_CACHE_DIR = _REPO / "artifacts" / "plan_cache"


def default_cache_dir() -> pathlib.Path:
    """``DSDDMM_PLAN_CACHE`` env override, else the repo artifact dir —
    read per call so tests and CI can redirect without reimporting."""
    env = os.environ.get("DSDDMM_PLAN_CACHE")
    return pathlib.Path(env) if env else DEFAULT_CACHE_DIR


class PlanCache:
    """File-per-key JSON plan store with corrupt/stale recovery."""

    def __init__(self, root: str | os.PathLike | None = None):
        self.root = pathlib.Path(root) if root is not None else default_cache_dir()

    def _path(self, key: str) -> pathlib.Path:
        return self.root / f"{key}.json"

    def load(self, key: str) -> dict | None:
        """The stored plan dict, or None on miss / corruption / version
        mismatch. Never raises for file-content reasons."""
        try:
            raw = self._path(key).read_text()
        except OSError:
            return None
        try:
            rec = json.loads(raw)
        except json.JSONDecodeError:
            return None
        if not isinstance(rec, dict):
            return None
        if rec.get("schema_version") != SCHEMA_VERSION:
            return None
        if rec.get("fingerprint_key") not in (None, key):
            return None  # renamed/copied file; do not serve a foreign plan
        return rec

    def store(self, key: str, plan_dict: dict) -> None:
        """Atomic write (shared ``utils.atomic`` helper): a concurrent
        reader sees the old entry or the new one, never a prefix. The
        helper's fault hook can corrupt the landed file — ``load`` then
        reads it as a miss, the recovery the corruption tests pin."""
        rec = dict(plan_dict)
        rec["schema_version"] = SCHEMA_VERSION
        rec["fingerprint_key"] = key
        atomic_write_json(self._path(key), rec)

    def invalidate(self, key: str) -> None:
        try:
            os.unlink(self._path(key))
        except OSError:
            pass


# --------------------------------------------------------------------- #
# Warm-start seeding from committed offline knowledge
# --------------------------------------------------------------------- #


def _read_jsonl(path: pathlib.Path) -> list[dict]:
    try:
        lines = path.read_text().splitlines()
    except OSError:
        return []
    out = []
    for line in lines:
        try:
            rec = json.loads(line)
        except json.JSONDecodeError:
            continue
        if isinstance(rec, dict):
            out.append(rec)
    return out


def _log2_bucket(x: float) -> int:
    return max(int(round(math.log2(max(x, 1)))), 0)


def seed_kernel_family(
    problem: Problem,
    backend: str,
    path: str | os.PathLike | None = None,
) -> str | None:
    """Best-measured kernel family at the nearest swept grid point
    (KERNELS_TPU.jsonl rows are keyed (logM, npr, R) on a real chip, so
    they only inform TPU backends)."""
    if backend != "tpu":
        return None
    p = pathlib.Path(path) if path is not None else _REPO / "KERNELS_TPU.jsonl"
    want = (_log2_bucket(problem.M), problem.npr_bucket, problem.R)
    best: tuple[float, str] | None = None
    for rec in _read_jsonl(p):
        if rec.get("skipped"):
            continue
        key = (rec.get("logM"), rec.get("npr"), rec.get("R"))
        if key != want:
            continue
        g = rec.get("fused_pair_gflops")
        fam = str(rec.get("kernel", "")).split("-")[0]
        if g and fam and (best is None or g > best[0]):
            best = (g, fam)
    return best[1] if best else None


def seed_winner_plan(
    problem: Problem,
    p: int,
    path: str | os.PathLike | None = None,
) -> dict | None:
    """Winning (algorithm, c) from committed heatmap-style records whose
    problem shape and mesh size match (exact M/N/p, nnz/row and R within
    the same power-of-two bucket). Returns a partial plan dict or None."""
    rp = (
        pathlib.Path(path)
        if path is not None
        else _REPO / "artifacts" / "cpu_mesh" / "records.jsonl"
    )
    best: tuple[float, dict] | None = None
    for rec in _read_jsonl(rp):
        info = rec.get("alg_info") or {}
        if rec.get("app", "vanilla") != "vanilla" or not rec.get("fused", False):
            continue
        if info.get("m") != problem.M or info.get("n") != problem.N:
            continue
        if info.get("p") != p:
            continue
        nnz = info.get("nnz") or 0
        if _log2_bucket(nnz / max(problem.M, 1)) != _log2_bucket(
            problem.nnz_per_row
        ):
            continue
        if _log2_bucket(rec.get("R", 0)) != _log2_bucket(problem.R):
            continue
        g = rec.get("overall_throughput", 0.0)
        if g and (best is None or g > best[0]):
            best = (
                g,
                {
                    "algorithm": rec.get("algorithm"),
                    "c": rec.get("c"),
                    "source": "seed",
                    "seed_evidence": {
                        "file": str(rp),
                        "overall_throughput": g,
                    },
                },
            )
    return best[1] if best else None
