"""Canonical problem fingerprints for plan-cache lookup.

A fingerprint is the complete set of inputs that determine which plan is
best: the problem (M, N, nnz, nnz/row bucket, R, dtype), the machine
(mesh shape, backend, which kernel families are available), and the code
generation (a hash of the program-shaping package sources). Two processes
given the same inputs MUST produce the same key — the cache-hit fast path
and cross-restart reuse both depend on it — so the key is a SHA-256 of the
canonical-JSON field dict, never ``hash()`` (randomized per process) or
``repr()`` of anything with unstable ordering.

The nnz/row term is bucketed to the nearest power of two: sparsity-regime
boundaries in the winner map are octave-scale (the reference sweeps
nnz/row in {8, 32, 128}), and exact-nnz keys would make every R-mat seed a
cold miss. M, N and nnz stay exact — tile geometry and the HBM guards
depend on them exactly.

This module deliberately imports neither jax nor the strategy code:
fingerprints must be computable in a subprocess (stability tests) and in
tooling without pulling up a backend. The machine terms are plain
arguments; callers with a live backend use :func:`machine_signature`.
"""

from __future__ import annotations

import dataclasses
import functools
import hashlib
import json
import pathlib

from distributed_sddmm_tpu.utils import buckets
from distributed_sddmm_tpu.utils.buckets import pow2_bucket

_PKG = pathlib.Path(__file__).resolve().parents[1]

#: Fingerprint field-schema generation. Bump when the field set or any
#: bucketing rule changes so stale cache entries cannot alias new keys.
FINGERPRINT_VERSION = 1


@dataclasses.dataclass(frozen=True)
class Problem:
    """The tuning-relevant description of one SDDMM+SpMM workload."""

    M: int
    N: int
    nnz: int
    R: int
    dtype: str = "float32"

    @classmethod
    def from_coo(cls, S, R: int, dtype: str = "float32") -> "Problem":
        """Build from a :class:`~distributed_sddmm_tpu.utils.coo.HostCOO`."""
        return cls(M=int(S.M), N=int(S.N), nnz=int(S.nnz), R=int(R),
                   dtype=dtype)

    @property
    def nnz_per_row(self) -> float:
        return self.nnz / max(self.M, 1)

    @property
    def npr_bucket(self) -> int:
        """nnz/row rounded to the nearest power of two (>= 1) — the
        SHARED rule (``utils/buckets.pow2_bucket``) the serve ladder
        and the codegen band selector also use, so plans, serving and
        kernel banding bucket identically."""
        return pow2_bucket(self.nnz_per_row)


@functools.lru_cache(maxsize=1)
def code_hash() -> str:
    """Hash of the program-shaping sources (``ops`` + ``parallel`` +
    ``codegen``).

    A plan measured under one code generation must not claim validity under
    another — ring structure, tile ingest, kernel lowering and the codegen
    variant geometry all shape the programs a plan names (``codegen/``
    joined in PR 9: a banked-geometry change invalidates plans that chose a
    variant). Autotune's own modules (and models/bench/tools) are excluded
    on purpose: editing selection logic or apps does not change what a
    (algorithm, c, kernel) plan executes, and including them would
    cold-start the cache on every subsystem tweak.
    """
    h = hashlib.sha256()
    for sub in ("ops", "parallel", "codegen"):
        for f in sorted((_PKG / sub).glob("*.py")):
            h.update(f.name.encode())
            h.update(f.read_bytes())
    return h.hexdigest()[:12]


@functools.lru_cache(maxsize=1)
def models_code_hash() -> str:
    """Hash of the ``models/`` sources. The jit-chained app programs
    (``cgStep``, ``gatLayer``) bake the CG vector algebra / layer math
    into the executable on top of the strategy programs, so their store
    entries must be invalidated by a ``models/`` edit even though
    :func:`code_hash` (ops/ + parallel/ only, the plan-validity scope)
    deliberately is not."""
    h = hashlib.sha256()
    for f in sorted((_PKG / "models").glob("*.py")):
        h.update(f.name.encode())
        h.update(f.read_bytes())
    return h.hexdigest()[:12]


@functools.lru_cache(maxsize=1)
def serve_code_hash() -> str:
    """The serving analog of :func:`code_hash`: warm serving programs
    (fold-in solve, node scoring) are shaped by ``serve/workloads.py``,
    not by ops/ or parallel/, so the serving-program cache keys on the
    ``serve/`` sources instead."""
    h = hashlib.sha256()
    for f in sorted((_PKG / "serve").glob("*.py")):
        h.update(f.name.encode())
        h.update(f.read_bytes())
    return h.hexdigest()[:12]


def serve_program_key(
    workload: str, batch_bucket: int, inner_bucket: int, r, backend: str,
) -> str:
    """Cache key for one serving bucket cell. The grammar now lives in
    ``programs/keys.py`` beside every other compiled-program key (PR 6
    unified the three look-alike builders); this compat re-export keeps
    the historical import path working."""
    from distributed_sddmm_tpu.programs import keys as program_keys

    return program_keys.serve_program_key(
        workload, batch_bucket, inner_bucket, r, backend,
        code=serve_code_hash(),
    )


@dataclasses.dataclass(frozen=True)
class Fingerprint:
    """Canonical signature + stable key. ``fields`` is the exact dict the
    key hashes; it is stored alongside cached plans so a cache file is
    self-describing."""

    fields: tuple  # canonical (name, value) pairs, fixed order
    key: str

    def as_dict(self) -> dict:
        return dict(self.fields)


def machine_signature(devices=None) -> tuple[int, str, tuple[str, ...]]:
    """(p, backend, available kernel families) for the live jax runtime.

    The only function here that touches jax — callers without a backend
    (subprocess key checks, offline tooling) pass the terms explicitly to
    :func:`make_fingerprint`.
    """
    import jax

    if devices is None:
        devices = jax.devices()
    backend = devices[0].platform
    kernels = ("pallas", "xla") if backend == "tpu" else ("xla",)
    return len(devices), backend, kernels


def make_fingerprint(
    problem: Problem,
    p: int,
    backend: str,
    kernels: tuple[str, ...] = ("xla",),
    code: str | None = None,
    capacity_bucket: bool = False,
) -> Fingerprint:
    """Build the canonical fingerprint for (problem, machine, code).

    ``capacity_bucket=True`` (PR 20, ``dynstruct/``) fingerprints the
    problem at its pow2 capacity rung instead of its exact nnz, and
    stamps a mode marker: every pattern whose nnz lands in the same rung
    shares a fingerprint — the plan-reuse granularity of a bucketed
    build, whose compiled programs are sized to the rung, not the
    pattern. Default off keeps every field (and hence every existing
    plan key) byte-identical, and the marker means a bucketed
    fingerprint can never collide with an exact one.
    """
    fields = (
        ("fingerprint_version", FINGERPRINT_VERSION),
        ("M", problem.M),
        ("N", problem.N),
        ("nnz", buckets.pow2_at_least(problem.nnz)
         if capacity_bucket else problem.nnz),
        ("npr_bucket", problem.npr_bucket),
        ("R", problem.R),
        ("dtype", problem.dtype),
        ("p", int(p)),
        ("backend", str(backend)),
        ("kernels", tuple(sorted(kernels))),
        ("code_hash", code if code is not None else code_hash()),
    )
    if capacity_bucket:
        fields += (("capacity_mode", "pow2"),)
    blob = json.dumps(
        [[k, list(v) if isinstance(v, tuple) else v] for k, v in fields],
        separators=(",", ":"),
    )
    key = hashlib.sha256(blob.encode()).hexdigest()[:16]
    return Fingerprint(fields=fields, key=key)
