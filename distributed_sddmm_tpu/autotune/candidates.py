"""Candidate-plan enumeration, cost-model ranking, HBM-footprint guards.

A candidate names everything the harness needs to build a strategy:
algorithm (which also fixes fusion strategy and r_split), replication
factor c, kernel family, an optional Pallas block config, and an optional
gather budget that forces the chunked XLA kernel. Enumeration applies the
same legality constraints the strategy constructors enforce (c | p;
square p/c and R divisibility for the 2.5D grids; (p/c) | R for
sparse-shift) so every emitted candidate is constructible.

Two pruning layers follow enumeration:

* **HBM guard** (:func:`hbm_guard`): estimates the per-device footprint of
  the dominant allocations. A candidate whose *kernel intermediates*
  (the XLA gather/scatter [nnz, R] arrays) blow the budget is not dropped
  — it is routed to the chunked XLA kernel (``gather_budget`` set below
  the tile footprint), which is exactly how the reference grid's heavy
  corner (logM=16, nnz/row=128, R=512) becomes runnable. Only candidates
  whose *resident* state (dense operands + tiles) cannot fit are pruned.
* **Cost model** (:func:`rank_candidates`): orders survivors by the
  analytic pair time from ``tools/costmodel.py`` (1.5D models from the
  reference notebook; 2.5D extensions). The model is first-order — it
  picks what to *measure first* and is the final arbiter only when
  measurement is unavailable.
"""

from __future__ import annotations

import dataclasses
import math

from distributed_sddmm_tpu.autotune.fingerprint import Problem
from distributed_sddmm_tpu.tools import costmodel

#: The five named algorithm configurations (bench/harness.py factory keys)
#: mapped to their analytic cost model. r_split is implied: sparse-shift
#: and both 2.5D strategies split R, the dense-shift fusions do not.
#: fusion2 leads: rank_candidates' sort is stable, so on modeled-cost ties
#: the headline single-ring-pass fusion wins enumeration order.
ALGORITHM_MODELS = {
    "15d_fusion2": "15d_fusion2",
    "15d_fusion1": "15d_fusion1",
    "15d_sparse": "15d_sparse",
    "25d_dense_replicate": "25d_dense",
    "25d_sparse_replicate": "25d_sparse",
}

#: Pallas block configs worth trying, best-measured first
#: (KERNELS_TPU.jsonl: (512, 512) wins the headline point at 73.3 vs 38.4
#: for (256, 512)). None = the env-default knobs.
PALLAS_BLOCKS = (None, (512, 512), (256, 512))

#: Default per-device memory budget for the footprint guard, in bytes.
#: v5e-ish HBM (16 GiB) with headroom for XLA workspace and the program
#: itself. CPU test meshes share the bound — it only ever *tightens*
#: selection, and an 8-device host mesh splits one host's RAM anyway.
DEFAULT_HBM_BYTES = 12 * (1 << 30)

_DTYPE_BYTES = {"float32": 4, "bfloat16": 2, "float16": 2, "float64": 8}


@dataclasses.dataclass(frozen=True)
class Candidate:
    """One constructible plan shape (pre-selection)."""

    algorithm: str
    c: int
    kernel: str = "xla"              # "xla" | "pallas"
    block: tuple | None = None       # Pallas (block_rows, block_cols)
    gather_budget: int | None = None  # set => chunked XLA kernel forced
    variant: str | None = None       # codegen kernel-variant id (pallas)
    #: Wire-precision comm dtype (``parallel/wire.py``): None/f32 = the
    #: identity wire, "bf16" = bf16 gather/ring payloads with f32
    #: accumulation. A plan axis exactly like ``variant``: it changes
    #: the traced program (and its store key) without changing any
    #: argument shape.
    wire: str | None = None

    @property
    def chunked(self) -> bool:
        return self.gather_budget is not None

    @property
    def r_split(self) -> bool:
        return self.algorithm in (
            "15d_sparse", "25d_dense_replicate", "25d_sparse_replicate"
        )


def legal_c_values(algorithm: str, p: int, R: int) -> list[int]:
    """Replication factors the named algorithm's constructor would accept
    at (p, R) — one place that mirrors every constructor's checks."""
    out = []
    for c in range(1, p + 1):
        if p % c:
            continue
        if algorithm in ("15d_fusion1", "15d_fusion2"):
            out.append(c)
        elif algorithm == "15d_sparse":
            if R % (p // c) == 0:
                out.append(c)
        else:  # 2.5D grids
            s = math.isqrt(p // c)
            if s * s * c != p:
                continue
            if algorithm == "25d_dense_replicate" and R % s == 0:
                out.append(c)
            elif algorithm == "25d_sparse_replicate" and R % (s * c) == 0:
                out.append(c)
    return out


def _resident_bytes(problem: Problem, cand: Candidate, p: int) -> float:
    """Per-device bytes that stay allocated for the life of the strategy:
    both dense operands (the stationary one replicated c-fold for the
    dense-replicating strategies) plus the padded tile structure (rows,
    cols, mask, vals ~ 4 words per nonzero, S and S^T both resident)."""
    b = _DTYPE_BYTES.get(problem.dtype, 4)
    dense = (problem.M + problem.N) * problem.R * b / p
    if cand.algorithm in ("15d_fusion1", "15d_fusion2"):
        dense += (problem.M * problem.R * b / p) * (cand.c - 1)
    elif cand.algorithm == "25d_dense_replicate":
        dense *= cand.c
    tiles = 2 * problem.nnz * 4 * 4 / p
    if cand.algorithm == "25d_sparse_replicate":
        tiles *= cand.c
    return dense + tiles


def _xla_intermediate_elems(problem: Problem, cand: Candidate, p: int) -> float:
    """Elements of the largest [local_nnz, R_local] intermediate the
    un-chunked XLA kernel materializes per ring step (gather product /
    scatter contributions). Local nnz follows the block-row tiling: nnz/p
    scaled by the stationary replication. R_local is the resident feature
    width, which each r_split strategy divides differently: sparse-shift
    splits R over the full shift axis p/c, the 2.5D grids only over
    sqrt(p/c) (dense-replicating, cols axis) or sqrt(p/c)*c (sparse-
    replicating, cols x layers fiber)."""
    local_nnz = problem.nnz / p
    r_div = 1
    if cand.algorithm in ("15d_fusion1", "15d_fusion2"):
        local_nnz *= cand.c
    elif cand.algorithm == "15d_sparse":
        r_div = max(p // cand.c, 1)
    elif cand.algorithm == "25d_dense_replicate":
        local_nnz *= cand.c  # tiles live on the s x s grid: nnz/(s*s)
        r_div = max(math.isqrt(p // cand.c), 1)
    elif cand.algorithm == "25d_sparse_replicate":
        local_nnz *= cand.c
        r_div = max(math.isqrt(p // cand.c) * cand.c, 1)
    return local_nnz * max(problem.R / r_div, 1)


def hbm_guard(
    problem: Problem,
    cand: Candidate,
    p: int,
    budget_bytes: int = DEFAULT_HBM_BYTES,
) -> Candidate | None:
    """Route or prune one candidate against the memory budget.

    Returns the candidate (possibly rewritten onto the chunked XLA kernel)
    or None when no rewrite can make it fit. Never returns a candidate
    whose un-chunked XLA intermediates exceed the budget — the OOM corner
    must be impossible to *select*, not merely unlikely.
    """
    b = _DTYPE_BYTES.get(problem.dtype, 4)
    resident = _resident_bytes(problem, cand, p)
    if resident > budget_bytes:
        return None
    if cand.kernel != "xla":
        return cand
    headroom = budget_bytes - resident
    inter = _xla_intermediate_elems(problem, cand, p)
    # Gather + scatter intermediates live simultaneously in the fused pass.
    if 2 * inter * b <= headroom:
        return cand
    # Chunk the kernel: budget the scan segment so one segment's
    # intermediates use at most half the headroom (elements, not bytes —
    # XLA_GATHER_BUDGET is an element count).
    seg_budget = int(headroom / (4 * b))
    if seg_budget < problem.R:  # cannot fit even one nonzero's row
        return None
    return dataclasses.replace(cand, gather_budget=seg_budget)


def enumerate_candidates(
    problem: Problem,
    p: int,
    kernels: tuple[str, ...] = ("xla",),
    budget_bytes: int = DEFAULT_HBM_BYTES,
) -> list[Candidate]:
    """All constructible, memory-safe candidates for (problem, machine)."""
    from distributed_sddmm_tpu.codegen import variant_from_id, variant_ids_for

    out = []
    for algorithm in ALGORITHM_MODELS:
        for c in legal_c_values(algorithm, p, problem.R):
            for kernel in kernels:
                blocks = PALLAS_BLOCKS if kernel == "pallas" else (None,)
                for block in blocks:
                    cand = Candidate(
                        algorithm=algorithm, c=c, kernel=kernel, block=block
                    )
                    cand = hbm_guard(problem, cand, p, budget_bytes)
                    if cand is not None:
                        out.append(cand)
                if kernel == "pallas":
                    # Codegen-specialized variants register beside the
                    # generic Pallas candidates (band geometry rides in
                    # the variant id, not the block knobs) and face the
                    # same guards and cost-model ranking. The replicated
                    # 2.5D layout cannot bank (build_replicated_tiles
                    # falls back to the generic encoding), so a BANKED
                    # candidate there would win on a discount it can
                    # never realize and stamp a variant id onto a
                    # byte-identical-to-generic run; non-banked R-regime
                    # variants still apply.
                    for vid in variant_ids_for(problem):
                        if (
                            algorithm == "25d_sparse_replicate"
                            and variant_from_id(vid).banked
                        ):
                            continue
                        cand = Candidate(
                            algorithm=algorithm, c=c, kernel=kernel,
                            variant=vid,
                        )
                        cand = hbm_guard(problem, cand, p, budget_bytes)
                        if cand is not None:
                            out.append(cand)
    # Wire-precision axis: every survivor also enumerates as a
    # bf16-wire twin — but only for float32 problems (the boundary
    # casts only touch f32 payloads; on a reduced-precision model the
    # wire is already narrow, so a bf16-wire candidate would claim a
    # discount it cannot realize). The twin's modeled cost earns
    # exactly the per-algorithm byte discount ``costmodel.pair_bytes``
    # can realize (sparse-shift's int32 index traffic and the
    # accumulator legs stay full-width).
    if problem.dtype == "float32":
        out.extend(dataclasses.replace(cand, wire="bf16") for cand in list(out))
    return out


def model_cost(
    problem: Problem,
    cand: Candidate,
    p: int,
    machine: costmodel.Machine | None = None,
) -> float:
    """Analytic seconds per fused pair for one candidate.

    The kernel family adjusts the compute rate: when the sweep records
    carry measured rates for both families, their ratio at the nearest
    grid point scales the model's flops term (the collective terms are
    kernel-independent). The chunked kernel is charged a small sequential
    overhead so an un-chunked sibling of equal volume outranks it.
    """
    if machine is None:
        machine = costmodel.Machine()
    rate = costmodel.measured_flops_rate(cand.kernel) or machine.flops_rate
    m = costmodel.Machine(
        ici_words_per_s=machine.ici_words_per_s,
        alpha_s=machine.alpha_s,
        flops_rate=rate,
    )
    t = costmodel.pair_time(
        ALGORITHM_MODELS[cand.algorithm],
        problem.M, problem.N, problem.R, problem.nnz, p, cand.c, m,
        wire=cand.wire,
    )
    if cand.chunked:
        t *= 1.1
    if cand.variant:
        from distributed_sddmm_tpu.codegen import variant_cost_factor

        # Banked variants are charged by estimated padded-lane overhead
        # relative to the generic encoding (a discount on skewed
        # problems, a penalty when banking cannot help) — the same
        # first-order role as the chunked kernel's 1.1x.
        t *= variant_cost_factor(problem, cand.variant)
    return t


def rank_candidates(
    problem: Problem,
    cands: list[Candidate],
    p: int,
    machine: costmodel.Machine | None = None,
) -> list[tuple[Candidate, float]]:
    """(candidate, modeled seconds) sorted fastest-first."""
    scored = [(cand, model_cost(problem, cand, p, machine)) for cand in cands]
    scored.sort(key=lambda cs: cs[1])
    return scored


def rank_candidates_realized(
    problem: Problem,
    cands: list[Candidate],
    p: int,
    machine: costmodel.Machine | None = None,
    realized: dict | None = None,
) -> list[tuple[Candidate, float]]:
    """Rank with the incumbent's REALIZED serving data folded in — the
    closed-loop tuner's ordering (``tuner/retune.py``).

    ``realized`` describes what actually ran: ``{"variant": <id or
    None>, "padded_lane_frac": <counted gauge>}``. When the realized
    encoding was GENERIC and its counted pad gauge is known, the
    ranking stops trusting the cost model's pad *estimate* where
    ground truth exists: every Pallas candidate is re-charged an
    absolute ``(1 + waste)`` pad overhead — the **realized** gauge for
    generic-encoding candidates, the model's estimate for banked ones
    (their realized number is unknown until measured). Banked variants
    then outrank generic exactly when their estimated waste undercuts
    the waste the replica is demonstrably paying — which is the trigger
    condition that started the re-tune. Orders what to MEASURE first,
    like :func:`rank_candidates`; trials remain the arbiter.
    """
    scored = rank_candidates(problem, cands, p, machine)
    frac = (realized or {}).get("padded_lane_frac")
    if frac is None or (realized or {}).get("variant") is not None:
        # No gauge, or a banked incumbent: the realized data describes
        # an encoding the estimates cannot be re-anchored against.
        return scored
    from distributed_sddmm_tpu.codegen import variants as cg_variants

    out = []
    for cand, t in scored:
        if cand.kernel == "pallas":
            base = t / variant_cost_factor_of(problem, cand)
            if cand.variant:
                waste = cg_variants.estimated_pad_frac(problem, banked=True)
            else:
                waste = float(frac)
            t = base * (1.0 + waste)
        out.append((cand, t))
    out.sort(key=lambda cs: cs[1])
    return out


def variant_cost_factor_of(problem: Problem, cand: Candidate) -> float:
    """The pad-estimate factor :func:`model_cost` already charged a
    candidate (1.0 for non-variant candidates) — what realized
    re-ranking divides back out before re-charging."""
    if not cand.variant:
        return 1.0
    from distributed_sddmm_tpu.codegen import variant_cost_factor

    return variant_cost_factor(problem, cand.variant)
