"""Persistent run store: every bench run becomes a queryable document.

PR 3 made one run legible (spans, per-op counters, a manifest); this
module makes runs *comparable*. Each benchmark run is persisted as one
JSON document under ``artifacts/runstore/runs/<run_id>.json``, joined
from four sources the harness already produces:

* the bench **record** (``bench/harness.py`` schema — alg_info,
  elapsed, throughput, per-op ``metrics``, ``anomalies``),
* the **trace aggregate** (``tools/tracereport.aggregate`` per-phase
  table incl. the comm-vs-costmodel column) when tracing was on,
* the run **manifest** (versions/backend/devices/git rev),
* the problem **fingerprint** (``autotune/fingerprint.py``) plus the
  code hash and backend, which together form the index key regression
  comparisons match on: two runs are comparable when problem, machine
  and program-shaping code all agree.

An ``index.json`` summary (one row per run) makes ``bench history``
O(1 file); it is derivative state — :meth:`RunStore.rebuild_index`
regenerates it from the run docs, and a corrupt index is rebuilt on
read rather than trusted. All writes go through ``utils/atomic.py``
(a reader sees old or new content, never a prefix; the resilience
layer's write-fault hook applies).

Activation mirrors the tracer: the bench CLI enables the store for
benchmark-producing subcommands (``--no-runstore`` opts out), the
``DSDDMM_RUNSTORE`` env var enables it programmatically (``1`` → the
default root, a path → that root, ``0``/``off`` → disabled), and
library callers that invoke ``benchmark_algorithm`` directly see no
store unless they ask — tests must not silt up ``artifacts/``.
"""

from __future__ import annotations

import contextlib
import json
import os
import pathlib
import threading

from distributed_sddmm_tpu.obs import clock
from distributed_sddmm_tpu.utils.atomic import atomic_write_json

#: Run-document schema generation; readers skip docs they cannot read.
SCHEMA_VERSION = 1

_REPO = pathlib.Path(__file__).resolve().parents[2]
DEFAULT_ROOT = _REPO / "artifacts" / "runstore"

#: Index-row fields lifted out of each run doc (``bench history`` shows
#: these without opening per-run files).
_INDEX_FIELDS = (
    "run_id", "created_epoch", "key", "backend", "code_hash",
    "algorithm", "app", "R", "c", "fused", "kernel", "kernel_variant",
    # Attention records (`--app attention`) only; None elsewhere. The
    # mask spec is a config axis: a sliding-window run must never pool
    # into a BigBird (or SDDMM) baseline.
    "mask",
    "elapsed", "overall_throughput", "source", "anomaly_count",
    # Serving records (`bench serve`) only; None elsewhere.
    "latency_p99_ms", "shed_count",
    # Program-store cold-start cost: in-process compiles this run paid
    # (0 for a fully disk-warmed run; None for pre-PR 6 records).
    "live_compiles",
    # PR 7 serving telemetry: percentiles from the mergeable fixed-
    # bucket request histogram plus the SLO error-budget burn rate.
    # None on every earlier doc — readers must treat absence as
    # "not measured", never as a verdict.
    "hist_p50_ms", "hist_p95_ms", "hist_p99_ms", "burn_rate",
    # Pod identity (PR 14): controller-process count and this record's
    # process slot. Absent on pre-pod docs; the config-axis matcher
    # normalizes absence to single-process (1) so history stays
    # comparable while future multi-host records never pool into
    # single-process baselines.
    "num_processes", "process_index",
    # Wire precision (PR 15): the realized collective payload policy
    # ("f32"/"bf16") and the run's total counted comm bytes (summed
    # over per-op metrics; None on pre-PR-15 docs and metric-less
    # records — "not measured", never a verdict).
    "wire", "comm_bytes",
    # Dynamic structure (PR 20): zero-retrace structure rebinds this
    # run performed (None on pre-PR-20 docs — "not measured").
    "dynstruct_rebinds",
)

#: Configuration axes (beyond the fingerprint key) two runs must share
#: to be regression-comparable: the fingerprint pins (problem, machine,
#: code) but one problem legitimately runs under many configurations —
#: a heatmap sweep benchmarks every algorithm at every R cell — and
#: pooling a 2.5D Cannon run into a 1.5D-fused baseline would gate on
#: an apples-to-oranges delta.
# ``kernel_variant`` joined in PR 9 — a banked-variant run must not
# pool into the generic kernel's baseline (both directions would poison
# the noise bands); pre-PR-9 docs carry None, which matches every other
# None-variant run, so history stays comparable. ``mask`` joined with
# the attention app (PR 13): the ``app`` axis already keeps attention
# runs out of SDDMM baselines, and the mask spec keeps the mask
# families apart from each other; non-attention docs carry None, which
# matches None.
# ``num_processes`` joined in PR 14: a pod record's timings include DCN
# collectives a single-controller run never pays — pooling either way
# would poison the noise bands. Pre-pod docs carry None, which the
# matcher normalizes to 1 (single-process) so existing history keeps
# comparing.
# ``wire`` joined in PR 15: a bf16-wire run moves half the collective
# bytes of an f32 run of the same problem — pooling either way would
# poison the bands. Pre-PR-15 docs carry None, which the matcher
# normalizes to "f32" (the identity wire every old run realized).
_CONFIG_AXES = (
    "algorithm", "app", "c", "fused", "kernel", "kernel_variant", "mask",
    "num_processes", "wire",
)


def _axis_value(row: dict, axis: str):
    """Config-axis value with absence normalization: ``num_processes``
    None (every pre-PR-14 row) means single-process; ``wire`` None
    (every pre-PR-15 row) means the f32 identity wire."""
    v = row.get(axis)
    if axis == "num_processes" and v is None:
        return 1
    if axis == "wire" and v is None:
        return "f32"
    return v


class RunStore:
    """One directory of run documents plus a derived summary index."""

    def __init__(self, root: str | os.PathLike | None = None):
        self.root = pathlib.Path(root) if root else DEFAULT_ROOT
        self.runs_dir = self.root / "runs"
        self.index_path = self.root / "index.json"
        self._lock = threading.Lock()

    # ------------------------------------------------------------------ #
    # Document I/O
    # ------------------------------------------------------------------ #

    def put(self, doc: dict) -> pathlib.Path:
        """Persist one run document and update the index atomically.

        ``doc`` must carry ``run_id``; ``schema``/``created_epoch`` are
        filled in when absent. Re-putting a run_id overwrites (a rerun
        under the same explicit id is one logical run).
        """
        run_id = doc.get("run_id")
        if not run_id:
            raise ValueError("run doc needs a run_id")
        doc.setdefault("schema", SCHEMA_VERSION)
        doc.setdefault("created_epoch", clock.epoch())
        path = self.runs_dir / f"{_safe_id(run_id)}.json"
        with self._lock, self._flock():
            atomic_write_json(path, doc)
            index = self._read_index()
            if index is _CORRUPT:
                # Recover the other rows from the run docs on disk
                # before appending ours — a torn index must not cost
                # the whole history.
                index = self._rebuild_index_locked()
            index = [r for r in index if r.get("run_id") != run_id]
            index.append(_index_row(doc))
            index.sort(key=lambda r: (r.get("created_epoch") or 0, r["run_id"]))
            atomic_write_json(self.index_path, index)
        return path

    @contextlib.contextmanager
    def _flock(self):
        """Advisory cross-PROCESS lock around the index read-modify-
        write: the threading.Lock covers one process, but two parallel
        bench invocations auto-ingesting into the same store would
        otherwise each read-append-write index.json and drop the
        other's row. Best-effort: no fcntl (non-POSIX) → in-process
        lock only."""
        try:
            import fcntl
        except ImportError:
            yield
            return
        self.root.mkdir(parents=True, exist_ok=True)
        # non-atomic-ok: flock target — the file's CONTENT is never read.
        with open(self.root / ".lock", "w") as fh:
            fcntl.flock(fh, fcntl.LOCK_EX)
            try:
                yield
            finally:
                fcntl.flock(fh, fcntl.LOCK_UN)

    def get(self, run_id: str) -> dict | None:
        """Load one run document (None when absent or unreadable)."""
        path = self.runs_dir / f"{_safe_id(run_id)}.json"
        try:
            doc = json.loads(path.read_text())
        except (OSError, json.JSONDecodeError):
            return None
        return doc if isinstance(doc, dict) else None

    def index(self) -> list[dict]:
        """Summary rows, oldest first; rebuilt from run docs when the
        index file is missing or corrupt (derived state is never load-
        bearing)."""
        with self._lock:
            rows = self._read_index()
            if rows is _CORRUPT:
                return self._rebuild_index_locked()
            return rows

    def rebuild_index(self) -> list[dict]:
        """Regenerate index.json from the run documents on disk."""
        with self._lock:
            return self._rebuild_index_locked()

    def _read_index(self):
        try:
            rows = json.loads(self.index_path.read_text())
        except FileNotFoundError:
            return []
        except (OSError, json.JSONDecodeError):
            return _CORRUPT
        if not isinstance(rows, list):
            return _CORRUPT
        return [r for r in rows if isinstance(r, dict) and r.get("run_id")]

    def _rebuild_index_locked(self) -> list[dict]:
        rows = []
        for f in sorted(self.runs_dir.glob("*.json")):
            try:
                doc = json.loads(f.read_text())
            except (OSError, json.JSONDecodeError):
                continue  # torn write — the doc, not the store, is lost
            if isinstance(doc, dict) and doc.get("run_id"):
                rows.append(_index_row(doc))
        rows.sort(key=lambda r: (r.get("created_epoch") or 0, r["run_id"]))
        atomic_write_json(self.index_path, rows)
        return rows

    # ------------------------------------------------------------------ #
    # Queries the regression gate runs on
    # ------------------------------------------------------------------ #

    def history(
        self, key: str | None = None, backend: str | None = None,
        limit: int | None = None,
    ) -> list[dict]:
        """Index rows, newest LAST, optionally filtered to one
        fingerprint key and/or backend; ``limit`` keeps the newest N."""
        rows = self.index()
        if key:
            rows = [r for r in rows if r.get("key") == key]
        if backend:
            rows = [r for r in rows if r.get("backend") == backend]
        if limit is not None and limit >= 0:
            rows = rows[-limit:] if limit else []
        return rows

    def matching(self, doc: dict, limit: int = 5) -> list[dict]:
        """The newest ``limit`` run DOCUMENTS comparable to ``doc`` —
        same index key (problem fingerprint + code hash + backend) AND
        same configuration axes (algorithm, app, c, fused, kernel) —
        excluding ``doc`` itself: the rolling baseline population for
        ``bench gate``."""
        key = doc.get("key")
        if not key:
            return []
        cfg = _index_row(doc)
        rows = [
            r for r in self.history(key=key, backend=doc.get("backend"))
            if r.get("run_id") != doc.get("run_id")
            and all(_axis_value(r, a) == _axis_value(cfg, a)
                    for a in _CONFIG_AXES)
        ]
        docs = [self.get(r["run_id"]) for r in rows[-limit:]]
        return [d for d in docs if d]

    def resolve(self, spec: str) -> dict | None:
        """Resolve a CLI run spec to a document: an exact run_id, a
        unique run_id prefix, ``latest``, or ``latest~N`` (N runs back).
        Returns None when nothing matches; raises ValueError when a
        prefix is ambiguous (the caller's error message must steer the
        user toward a longer prefix, not claim the run does not exist)."""
        if spec.startswith("latest"):
            back = 0
            if spec != "latest":
                try:
                    back = int(spec.split("~", 1)[1])
                except (IndexError, ValueError):
                    return None
            rows = self.index()
            if back >= len(rows):
                return None
            return self.get(rows[-1 - back]["run_id"])
        doc = self.get(spec)
        if doc is not None:
            return doc
        hits = [r for r in self.index() if r["run_id"].startswith(spec)]
        if len(hits) == 1:
            return self.get(hits[0]["run_id"])
        if len(hits) > 1:
            sample = ", ".join(r["run_id"] for r in hits[:4])
            raise ValueError(
                f"run spec {spec!r} is ambiguous ({len(hits)} matches: "
                f"{sample}{', ...' if len(hits) > 4 else ''}); use a "
                "longer prefix"
            )
        return None

    # ------------------------------------------------------------------ #
    # The join: bench record -> run document
    # ------------------------------------------------------------------ #

    def ingest_record(self, record: dict, source: str = "bench") -> dict:
        """Build + persist the run document for one bench record.

        Joins the record with the trace aggregate and manifest (when the
        record names a trace) and stamps the fingerprint/code-hash/
        backend index key. Every record is its own run: a traced sweep
        stamps ONE tracer run_id into every record it emits, so ids are
        uniquified with a ``-N`` suffix here rather than letting later
        sweep cells overwrite earlier ones. Returns the stored document.
        """
        doc = build_run_doc(record, source=source)
        base = doc["run_id"]
        n = 1
        while self.get(doc["run_id"]) is not None:
            n += 1
            doc["run_id"] = f"{base}-{n}"
        self.put(doc)
        return doc

    def ingest_prebuilt(self, doc: dict) -> dict:
        """Persist an already-joined document (backfill path)."""
        doc.setdefault("created_epoch", clock.epoch())
        self.put(doc)
        return doc


#: Sentinel distinguishing "no index yet" from "index unreadable".
_CORRUPT = object()


def _safe_id(run_id: str) -> str:
    """Run ids become file names; keep them path-safe (no separators,
    no hidden/relative-looking leading dots)."""
    safe = "".join(c if (c.isalnum() or c in "-_.") else "_" for c in run_id)
    return safe.lstrip(".") or "run"


def _total_comm_bytes(rec: dict):
    """Total counted comm bytes across the record's per-op metrics —
    None (not 0) when no op reported the field, so pre-PR-15 docs read
    as "not measured" rather than "moved nothing"."""
    vals = [
        m.get("comm_bytes")
        for m in (rec.get("metrics") or {}).values()
        if isinstance(m, dict) and m.get("comm_bytes") is not None
    ]
    return sum(vals) if vals else None


def _index_row(doc: dict) -> dict:
    rec = doc.get("record") or {}
    anomalies = (doc.get("anomalies") or {}).get("anomalies", [])
    row = {
        "run_id": doc.get("run_id"),
        "created_epoch": doc.get("created_epoch"),
        "key": doc.get("key"),
        "backend": doc.get("backend"),
        "code_hash": doc.get("code_hash"),
        "algorithm": rec.get("algorithm"),
        "app": rec.get("app"),
        "R": rec.get("R"),
        "c": rec.get("c"),
        "fused": rec.get("fused"),
        "kernel": rec.get("kernel"),
        "kernel_variant": rec.get("kernel_variant"),
        "mask": rec.get("mask"),
        "elapsed": rec.get("elapsed"),
        "overall_throughput": rec.get("overall_throughput"),
        "source": doc.get("source"),
        "anomaly_count": sum(a.get("count", 1) for a in anomalies),
        "latency_p99_ms": (rec.get("latency_ms") or {}).get("p99"),
        "shed_count": rec.get("shed_count"),
        "hist_p50_ms": (rec.get("latency_hist_ms") or {}).get("p50"),
        "hist_p95_ms": (rec.get("latency_hist_ms") or {}).get("p95"),
        "hist_p99_ms": (rec.get("latency_hist_ms") or {}).get("p99"),
        "burn_rate": rec.get("burn_rate"),
        "num_processes": rec.get("num_processes"),
        "process_index": rec.get("process_index"),
        "wire": rec.get("wire"),
        "comm_bytes": _total_comm_bytes(rec),
        # Offline records carry the GLOBAL counter delta; serving
        # records the engine's own ladder attribution.
        "live_compiles": (
            (rec.get("program_store") or {}).get("live_compiles")
            if rec.get("program_store") is not None
            else (rec.get("engine") or {}).get("live_compiles")
        ),
        "dynstruct_rebinds": (
            (rec.get("dynstruct") or {}).get("dynstruct_rebinds")
            if rec.get("dynstruct") is not None
            else None
        ),
    }
    return {k: row[k] for k in _INDEX_FIELDS}


def _live_backend() -> str | None:
    """The already-initialized jax backend, never initializing one (the
    same discipline as ``obs/manifest.py``)."""
    import sys

    jax = sys.modules.get("jax")
    if jax is None:
        return None
    try:
        backends = getattr(jax._src.xla_bridge, "_backends", None)
        if backends:
            return jax.default_backend()
    except Exception:  # noqa: BLE001 — best-effort, like the manifest
        pass
    return None


def _fingerprint_for(record: dict, backend: str | None) -> dict:
    """Fingerprint fields + key for a bench record, via the autotune
    fingerprint so plan cache and run store agree on what "same problem
    on same machine under same code" means."""
    from distributed_sddmm_tpu.autotune import fingerprint as fp

    info = record.get("alg_info") or {}
    problem = fp.Problem(
        M=int(info.get("m") or 0), N=int(info.get("n") or 0),
        nnz=int(info.get("nnz") or 0), R=int(record.get("R") or 0),
    )
    backend = backend or "unknown"
    kernels = ("pallas", "xla") if backend == "tpu" else ("xla",)
    made = fp.make_fingerprint(
        problem, p=int(info.get("p") or 0), backend=backend, kernels=kernels,
    )
    return {"fingerprint": made.as_dict(), "key": made.key,
            "code_hash": fp.code_hash(), "backend": backend}


def build_run_doc(record: dict, source: str = "bench") -> dict:
    """The join, without persistence (testable on synthetic records)."""
    doc = {
        "schema": SCHEMA_VERSION,
        "run_id": record.get("run_id") or _fallback_run_id(),
        "created_epoch": clock.epoch(),
        "source": source,
        "record": record,
        "anomalies": record.get("anomalies"),
    }
    backend = None
    trace_path = record.get("trace_path")
    if trace_path:
        from distributed_sddmm_tpu.tools import tracereport

        try:
            # Attach the per-phase aggregate only when this trace holds
            # exactly one bench span: a sweep shares one trace file
            # across its records (spans emit on close, so record k sees
            # k closed bench spans), and aggregating the whole file
            # would charge earlier cells' phases to this record. The
            # record's own `metrics` remain the per-record fallback the
            # regression compare uses. The pre-count streams the raw
            # lines instead of JSON-parsing the whole (growing) file
            # for every sweep cell — only the single-bench case pays
            # for a full parse.
            if _count_bench_spans(trace_path, stop_after=2) <= 1:
                tr = tracereport.load_trace(trace_path, strict=False)
                agg = tracereport.aggregate(tr)
                doc["phases"] = agg.get("phases")
                doc["trace_events"] = agg.get("events")
                doc["strategy"] = agg.get("strategy")
        except (OSError, ValueError):
            pass  # a torn trace must not lose the run record itself
        manifest = tracereport.load_manifest(trace_path)
        if manifest:
            doc["manifest"] = {
                k: manifest.get(k)
                for k in ("jax_version", "jaxlib_version", "backend",
                          "device_count", "device_kind", "git_rev",
                          "git_dirty", "env")
            }
            # The manifest saw the live backend at run time — more
            # authoritative than a post-hoc module probe.
            backend = manifest.get("backend")
    # Fingerprint once, after the backend source is decided.
    doc.update(_fingerprint_for(record, backend or _live_backend()))
    return doc


def _count_bench_spans(trace_path, stop_after: int = 2) -> int:
    """Cheap streaming count of closed ``bench`` spans in a trace file
    (substring match on the raw lines — json.dumps emits the literal
    ``"name": "bench"``), bailing at ``stop_after``. A false positive
    merely skips the optional phase enrichment; it can never corrupt a
    run document."""
    n = 0
    with open(trace_path) as fh:
        for line in fh:
            if '"name": "bench"' in line:
                n += 1
                if n >= stop_after:
                    break
    return n


def _fallback_run_id() -> str:
    """Untraced runs still need a unique id to live in the store — the
    tracer's grammar, so trace files and store docs stay visually and
    prefix-wise interchangeable."""
    from distributed_sddmm_tpu.obs.trace import _make_run_id

    return _make_run_id()


# --------------------------------------------------------------------- #
# Backfill: the committed round 1–5 trajectory becomes store history
# --------------------------------------------------------------------- #

#: ``parsed.metric`` shape of the historical headline records, e.g.
#: "fused SDDMM+SpMM GFLOP/s/chip (R-mat 2^16, nnz/row=32, R=128,
#:  pallas-bf16 kernel, 1 tpu chip(s))".
_METRIC_RE = (
    r"R-mat 2\^(?P<logm>\d+), nnz/row=(?P<npr>\d+), R=(?P<R>\d+), "
    r"(?P<kernel>[\w.-]+) kernel, (?P<p>\d+) (?P<backend>\w+) chip"
)


def _doc_from_headline(run_id: str, parsed: dict, source: str,
                       rc=None, epoch: float = 0.0) -> dict:
    """One run document from a BENCH_r0x ``parsed`` headline (or the
    mid-round banked record, same schema). ``epoch`` is a tiny
    deterministic ordinal (round number), NOT the ingest time: history
    sorts by ``created_epoch``, and backfilled rounds must sort *before*
    every live run — `resolve("latest")` returning a years-old record
    because it was ingested a second ago would break compare/gate."""
    import re

    from distributed_sddmm_tpu.autotune import fingerprint as fp

    record = {
        "app": "vanilla",
        "overall_throughput": parsed.get("value"),
        "unit": parsed.get("unit"),
        "metric": parsed.get("metric"),
        "vs_baseline": parsed.get("vs_baseline"),
        "note": parsed.get("note"),
        "rc": rc,
    }
    doc = {
        "schema": SCHEMA_VERSION,
        "run_id": run_id,
        "created_epoch": epoch,
        "source": source,
        "record": record,
        "key": None,
        "backend": parsed.get("backend"),
        # The historical code generation, NOT today's: a backfilled run
        # must never alias a live run's index key — its numbers would
        # poison the rolling baseline the gate compares against.
        "code_hash": parsed.get("code_hash", "historical"),
    }
    m = re.search(_METRIC_RE, str(parsed.get("metric", "")))
    if m:
        M = 1 << int(m.group("logm"))
        backend = parsed.get("backend") or m.group("backend")
        problem = fp.Problem(M=M, N=M, nnz=M * int(m.group("npr")),
                             R=int(m.group("R")))
        made = fp.make_fingerprint(
            problem, p=int(m.group("p")), backend=backend,
            kernels=("pallas", "xla") if backend == "tpu" else ("xla",),
            code=doc["code_hash"],
        )
        doc.update({"fingerprint": made.as_dict(), "key": made.key,
                    "backend": backend})
        record["R"] = problem.R
        record["alg_info"] = {"m": M, "n": M, "nnz": problem.nnz,
                              "p": int(m.group("p"))}
        record["kernel"] = m.group("kernel")
    return doc


def backfill_historical(store: RunStore, root=None) -> list[dict]:
    """Ingest the committed round 1–5 records — BENCH_r0*.json,
    MULTICHIP_r0*.json, and the banked mid-round TPU measurement — so
    ``bench history`` opens with the repo's real trajectory instead of
    an empty store. Idempotent: run ids are derived from file names, so
    re-running overwrites in place. Returns the ingested documents."""
    root = pathlib.Path(root) if root else _REPO

    def _round(stem: str) -> float:
        digits = "".join(c for c in stem if c.isdigit())
        return float(digits) if digits else 0.0

    docs = []
    for f in sorted(root.glob("BENCH_r0*.json")):
        try:
            rec = json.loads(f.read_text())
        except (OSError, json.JSONDecodeError):
            continue
        parsed = rec.get("parsed") or {}
        doc = _doc_from_headline(
            f"backfill-{f.stem.lower()}", parsed, source=f.name,
            rc=rec.get("rc"), epoch=_round(f.stem),
        )
        docs.append(store.ingest_prebuilt(doc))
    mid = root / "artifacts" / "bench_midround" / "record.json"
    try:
        parsed = json.loads(mid.read_text())
        docs.append(store.ingest_prebuilt(_doc_from_headline(
            "backfill-bench-midround-r05", parsed,
            source="artifacts/bench_midround/record.json",
            epoch=5.5,  # mid-round 5, between r05 and any live run
        )))
    except (OSError, json.JSONDecodeError):
        pass
    for f in sorted(root.glob("MULTICHIP_r0*.json")):
        try:
            rec = json.loads(f.read_text())
        except (OSError, json.JSONDecodeError):
            continue
        doc = {
            "schema": SCHEMA_VERSION,
            "run_id": f"backfill-{f.stem.lower()}",
            # Ordinal epoch (round + small offset): sorts with its
            # round, always before live runs (see _doc_from_headline).
            "created_epoch": _round(f.stem) + 0.25,
            "source": f.name,
            "key": None,
            "backend": None,
            "code_hash": "historical",
            "record": {
                "app": "multichip",
                "n_devices": rec.get("n_devices"),
                "ok": rec.get("ok"),
                "skipped": rec.get("skipped"),
                "rc": rec.get("rc"),
            },
        }
        docs.append(store.ingest_prebuilt(doc))
    return docs


# --------------------------------------------------------------------- #
# Module-level activation (the bench harness's auto-write hook)
# --------------------------------------------------------------------- #

_active: RunStore | None = None
_env_checked = False
_registry_lock = threading.Lock()
_suppress_count = 0


@contextlib.contextmanager
def suppressed():
    """Hide the active store for the duration of the block —
    process-wide, not thread-local, because the suppressed work may
    hop to a worker thread (autotune trials run under the thread-based
    timeout). Used by autotune's candidate measurement: those short
    probes flow through ``benchmark_algorithm`` but are not *runs*, and
    persisting them would pollute history and skew the gate's rolling
    baseline with 2-trial compile-heavy records."""
    global _suppress_count
    with _registry_lock:
        _suppress_count += 1
    try:
        yield
    finally:
        with _registry_lock:
            _suppress_count -= 1


def parse_env_spec(spec: str | None) -> tuple[bool, str | None]:
    """One grammar for ``DSDDMM_RUNSTORE``, shared by :func:`active` and
    the bench CLI: returns ``(enabled, root)`` where ``0/off/false/no``
    disables, ``1/on/true/yes``/empty selects the default root, and any
    other value is a root path. Empty/unset counts as *enabled with the
    default root* — the caller decides whether unset means "on by
    default" (CLI bench runs) or "off" (library use, via :func:`active`
    which only enables on a non-empty spec)."""
    spec = spec or ""
    low = spec.lower()
    if low in ("0", "off", "false", "no"):
        return False, None
    if not spec or low in ("1", "on", "true", "yes"):
        return True, None
    return True, spec


def enable(root: str | os.PathLike | None = None) -> RunStore:
    """Activate the process-wide store (idempotent; an active store
    wins, mirroring the tracer's semantics)."""
    global _active, _env_checked
    with _registry_lock:
        _env_checked = True
        if _active is None:
            _active = RunStore(root)
        return _active


def disable() -> None:
    global _active, _env_checked
    with _registry_lock:
        _active = None
        _env_checked = True


def active() -> RunStore | None:
    """The active store, activating from ``DSDDMM_RUNSTORE`` on first
    query (``1``/``on`` → default root, a path → that root, ``0``/
    ``off``/unset → None)."""
    global _active, _env_checked
    if _suppress_count:
        return None
    if _env_checked:
        return _active
    with _registry_lock:
        if not _env_checked:
            _env_checked = True
            spec = os.environ.get("DSDDMM_RUNSTORE", "")
            enabled, root = parse_env_spec(spec)
            if spec and enabled:
                _active = RunStore(root)
    return _active
