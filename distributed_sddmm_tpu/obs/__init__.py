"""Observability layer: structured tracing, metrics, logging, profiling.

The paper's whole argument is a communication/computation accounting story
(1.5D vs 2.5D shift/replication tradeoffs), so the repro needs more than
wall-clock: this package attributes time to shift steps, collectives,
local kernels, retries and host transfers, and counts the communication
volume each strategy's layout math implies — the same per-phase breakdown
Bharadwaj et al. (IPDPS 2022) use to validate their cost model.

Modules (each importable on its own; none touches a JAX backend at import
time, so platform pinning still works):

* :mod:`~distributed_sddmm_tpu.obs.trace` — process-wide tracer with
  nested spans and thread-safe JSONL emission
  (``DSDDMM_TRACE`` / ``--trace``; near-zero overhead when disabled).
* :mod:`~distributed_sddmm_tpu.obs.metrics` — thread-safe counters: the
  per-strategy op registry that replaced the ad-hoc ``total_time`` dict
  (kernel time separated from retry/fault overhead, comm words and FLOPs
  from the strategies' layout math), plus a process-wide event counter.
* :mod:`~distributed_sddmm_tpu.obs.log` — structured stderr logger
  (level via ``DSDDMM_LOG``) replacing stray ``print`` diagnostics.
* :mod:`~distributed_sddmm_tpu.obs.profiler` — optional ``jax.profiler``
  capture + named ``TraceAnnotation``s around compiled programs.
* :mod:`~distributed_sddmm_tpu.obs.manifest` — one run manifest per
  traced run (versions, device kind, mesh, git rev, fault config).

The cross-run half (PR 4) closes the loop:

* :mod:`~distributed_sddmm_tpu.obs.store` — persistent run store under
  ``artifacts/runstore/`` (one doc per run, indexed by problem
  fingerprint + code hash + backend; the bench CLI writes it
  automatically, ``DSDDMM_RUNSTORE`` for programmatic use).
* :mod:`~distributed_sddmm_tpu.obs.regress` — per-phase deltas between
  runs / rolling baselines, noise-aware verdicts, the CI ``bench gate``
  exit-code contract, roofline + comm-model attribution columns.
* :mod:`~distributed_sddmm_tpu.obs.watchdog` — in-run anomaly monitor
  (EWMA step-time spikes/drift, repair storms, comm-vs-costmodel
  mismatch) via ``DSDDMM_WATCHDOG=warn|strict``; anomalies land as
  trace events and an ``anomalies`` summary in the bench record.
* :mod:`~distributed_sddmm_tpu.obs.report` — self-contained HTML
  dashboard (``bench report-html``): history, trends, latest compare.

The request-level / multi-process half (PR 7):

* :mod:`~distributed_sddmm_tpu.obs.clock` — THE clock module: one
  calibrated monotonic/wall pair per process (a lint forbids raw
  ``time.*`` clock reads in ``serve/`` and ``obs/`` span paths).
* :mod:`~distributed_sddmm_tpu.obs.tracemerge` — offset-aligned merge
  of per-process trace shards (``bench trace-merge``); shards align on
  each ``begin`` record's ``t0_epoch`` calibration header.
* :mod:`~distributed_sddmm_tpu.obs.telemetry` — mergeable fixed-bucket
  latency histograms, the SLO error-budget burn rate, and the sampler
  thread behind ``bench serve --telemetry`` / ``bench top``.

The live operational half (PR 8):

* :mod:`~distributed_sddmm_tpu.obs.httpexp` — zero-dependency stdlib
  HTTP admin server: Prometheus ``/metrics`` text exposition (GLOBAL
  counters, per-op registry, queue/latency-histogram families),
  ``/healthz``/``/readyz`` liveness + SLO-burn readiness, and the
  ``/debug/requests`` recent-timeline ring (``bench serve
  --admin-port``; ``bench top --serve`` exporter mode).
* :mod:`~distributed_sddmm_tpu.obs.flightrec` — anomaly-triggered
  flight recorder: the tracer's in-memory span ring plus metrics/
  telemetry snapshots dumped to ``artifacts/flightrec/<run_id>/``
  whenever the watchdog fires (``--flightrec`` /
  ``DSDDMM_FLIGHTREC``); the dump path is stamped into the anomaly
  trace event and the bench record.
* :mod:`~distributed_sddmm_tpu.obs.traceexport` — Chrome trace-event
  export (``bench trace-export``): any schema-valid trace, merged
  multi-shard included, as Perfetto-openable JSON with one lane per
  shard/thread and request chains drawn as cross-thread flows.

The trace reader/report side lives in ``tools/tracereport.py``
(``python -m distributed_sddmm_tpu.bench report-trace <trace.jsonl>``),
including the serving request-chain reconstruction
(``tracereport.request_chains``).
"""

from distributed_sddmm_tpu.obs import (
    clock, flightrec, httpexp, log, manifest, metrics, profiler, regress,
    report, store, telemetry, trace, traceexport, tracemerge, watchdog,
)

__all__ = [
    "clock", "trace", "tracemerge", "traceexport", "metrics", "telemetry",
    "log", "profiler", "manifest", "store", "regress", "watchdog",
    "report", "httpexp", "flightrec",
]
