"""Chrome trace-event export: open any trace in Perfetto.

``obs/trace.py`` writes a private JSONL schema; this module converts
any schema-valid trace — including ``bench trace-merge`` outputs whose
records carry ``shard``/``pid`` tags — into the Chrome trace-event JSON
format (the ``{"traceEvents": [...]}`` array flavor) that
https://ui.perfetto.dev and ``chrome://tracing`` open directly.

Mapping:

* one Chrome **process lane per shard** (a single-process trace is one
  lane named after its run_id), one **thread lane per source thread**,
  both announced with ``process_name``/``thread_name`` metadata events;
* **spans become B/E pairs** on the merged (offset-calibrated)
  monotonic timeline, attrs riding along as ``args``. Ties at equal
  timestamps are ordered by nesting depth (E closes deepest-first, B
  opens shallowest-first) so viewers reconstruct the exact span tree;
* **events become instants** (``ph:"i"``), except the request-scoped
  ``serve:enqueue``/``serve:reply``/``serve:shed`` events, which become
  1µs marker slices (``ph:"X"``) — Chrome *flow* events bind to
  enclosing slices, and an instant cannot anchor a flow;
* **request chains become flows**: for every request with an enqueue
  event, a ``serve:batch`` span listing it, and a reply event, a
  ``s``/``t``/``f`` flow triple (one disjoint flow id per request)
  stitches enqueue → batch → reply across threads — the same joins
  ``tools/tracereport.request_chains`` verifies, drawn as arrows;
* **fleet links become cross-process flows**: every record the merge
  pass re-parented across shards (``attrs.fleet_parent`` — a router
  attempt's replica-side enqueue, a side-thread hedge/audit attempt
  under its request span) gets its own arrow from the parent span's
  lane to the linked record's lane, so a fleet request reads as one
  tree spanning the router's process and every replica it touched.

CLI: ``python -m distributed_sddmm_tpu.bench trace-export TRACE.jsonl
[-o OUT.json]`` (exit 2 on a schema-invalid trace, like report-trace).
"""

from __future__ import annotations

import json
import pathlib

from distributed_sddmm_tpu.tools import tracereport
from distributed_sddmm_tpu.utils.atomic import atomic_write_text

#: Trace events exported as 1µs marker slices instead of instants so
#: request flows have slices to bind to.
_MARKER_EVENTS = ("serve:enqueue", "serve:reply", "serve:shed")
_MARKER_DUR_US = 1.0


def _us(t_s: float) -> float:
    return round(t_s * 1e6, 3)


class _Lanes:
    """shard → Chrome pid, (shard, raw tid) → Chrome tid, plus the
    metadata events announcing both."""

    def __init__(self, begin: dict | None):
        self._pids: dict = {}
        self._tids: dict = {}
        self.meta: list[dict] = []
        self._begin = begin or {}
        # Merged traces pre-declare their shards (keeps lane order
        # deterministic: shard meta order, not record order).
        for meta in self._begin.get("shards") or ():
            self.pid(meta.get("run_id"), os_pid=meta.get("pid"))

    def pid(self, shard, os_pid=None) -> int:
        if shard not in self._pids:
            p = len(self._pids) + 1
            self._pids[shard] = p
            label = shard or self._begin.get("run_id") or "trace"
            if os_pid is None and shard is None:
                os_pid = self._begin.get("pid")
            if os_pid is not None:
                label = f"{label} (pid {os_pid})"
            self.meta.append({
                "name": "process_name", "ph": "M", "pid": p,
                "args": {"name": f"shard {label}"},
            })
            self.meta.append({
                "name": "process_sort_index", "ph": "M", "pid": p,
                "args": {"sort_index": p},
            })
        return self._pids[shard]

    def tid(self, shard, raw_tid) -> int:
        key = (shard, raw_tid)
        if key not in self._tids:
            pid = self.pid(shard)
            t = sum(1 for (s, _r) in self._tids if s == shard) + 1
            self._tids[key] = (pid, t)
            self.meta.append({
                "name": "thread_name", "ph": "M", "pid": pid, "tid": t,
                "args": {"name": f"thread {raw_tid}"},
            })
        return self._tids[key][1]


def _span_depths(spans: list[dict]) -> dict:
    """span id → nesting depth (root = 0), from parent links."""
    parent = {sp["id"]: sp.get("parent") for sp in spans}
    depths: dict = {}

    def depth(i):
        if i in depths:
            return depths[i]
        seen = []
        d = 0
        node = i
        while node is not None and node not in depths:
            seen.append(node)
            node = parent.get(node)
            d += 1
            if d > len(parent) + 1:  # cycle guard: malformed parent
                break
        base = depths.get(node, -1)
        for off, n in enumerate(reversed(seen), 1):
            depths[n] = base + off
        return depths[i]

    for sp in spans:
        depth(sp["id"])
    return depths


def _request_flows(trace: dict, lanes: _Lanes) -> list[dict]:
    """One ``s``/``t``/``f`` flow triple per fully-joined request."""
    enq: dict = {}
    rep: dict = {}
    for ev in trace["events"]:
        req = ev["attrs"].get("req")
        if req is None:
            continue
        key = tracereport.req_key(ev, req)
        if ev["name"] == "serve:enqueue":
            enq[key] = ev
        elif ev["name"] == "serve:reply":
            rep[key] = ev
    batch: dict = {}
    for sp in trace["spans"]:
        if sp["name"] != "serve:batch":
            continue
        for req in sp["attrs"].get("req_ids") or ():
            batch[tracereport.req_key(sp, req)] = sp
    flows = []
    for fid, key in enumerate(sorted(enq, key=str), 1):
        e, b, r = enq[key], batch.get(key), rep.get(key)
        if b is None or r is None:
            continue
        common = {"name": "request", "cat": "request", "id": fid,
                  "args": {"req": e["attrs"]["req"], "shard": key[0]}}
        flows.append({
            **common, "ph": "s",
            "pid": lanes.pid(e.get("shard")),
            "tid": lanes.tid(e.get("shard"), e["tid"]),
            "ts": _us(e["t"]) + _MARKER_DUR_US / 2,
        })
        flows.append({
            **common, "ph": "t",
            "pid": lanes.pid(b.get("shard")),
            "tid": lanes.tid(b.get("shard"), b["tid"]),
            "ts": round(_us(b["t0"]) + max(
                _us(b["t1"]) - _us(b["t0"]), _MARKER_DUR_US) / 2, 3),
        })
        flows.append({
            **common, "ph": "f", "bp": "e",
            "pid": lanes.pid(r.get("shard")),
            "tid": lanes.tid(r.get("shard"), r["tid"]),
            "ts": _us(r["t"]) + _MARKER_DUR_US / 2,
        })
    return flows


#: Flow-id offset keeping fleet arrows disjoint from request flows.
_FLEET_FLOW_BASE = 10_000_000


def _slice_mid_us(t0_s: float, t1_s: float) -> float:
    """A timestamp strictly inside a slice, for flow binding."""
    return round((_us(t0_s) + _us(t1_s)) / 2, 3)


def _fleet_flows(trace: dict, lanes: _Lanes) -> list[dict]:
    """One ``s``/``f`` flow pair per cross-process fleet link.

    The merge pass re-parents a record onto its causal parent in
    another shard (or thread) and records the merged id as
    ``attrs.fleet_parent``; each such re-parented record — the
    replica's enqueue marker under the router's attempt span, a
    side-thread hedge/audit attempt under its request span — gets an
    arrow from the parent span's lane. Records whose in-process parent
    survived the merge (``serve:reply`` under ``serve:batch``) keep
    their nesting and need no arrow.
    """
    span_by_id = {sp["id"]: sp for sp in trace["spans"]}
    linked = [
        rec for rec in trace["spans"] + trace["events"]
        if isinstance(rec.get("attrs"), dict)
        and rec["attrs"].get("fleet_parent") is not None
        and rec.get("parent") == rec["attrs"]["fleet_parent"]
        and (rec["type"] == "span" or rec["name"] in _MARKER_EVENTS)
    ]
    flows = []
    fid = _FLEET_FLOW_BASE
    for rec in sorted(linked, key=lambda r: r["id"]):
        parent = span_by_id.get(rec["attrs"]["fleet_parent"])
        if parent is None:
            continue
        fid += 1
        common = {
            "name": "fleet", "cat": "fleet", "id": fid,
            "args": {"fleet_req": rec["attrs"].get("fleet_req"),
                     "to": rec["name"]},
        }
        if rec["type"] == "span":
            ts = _slice_mid_us(rec["t0"], rec["t1"])
        else:
            ts = _us(rec["t"]) + _MARKER_DUR_US / 2
        # The arrow starts just inside the parent slice's opening edge
        # (a slice midpoint could land AFTER the child record — e.g. a
        # long attempt span whose replica enqueued early — and Chrome
        # flows must run forward in time); it still binds to the
        # parent slice, and never past the child's anchor.
        flows.append({
            **common, "ph": "s",
            "pid": lanes.pid(parent.get("shard")),
            "tid": lanes.tid(parent.get("shard"), parent["tid"]),
            "ts": min(_us(parent["t0"]) + 1, ts),
        })
        flows.append({
            **common, "ph": "f", "bp": "e",
            "pid": lanes.pid(rec.get("shard")),
            "tid": lanes.tid(rec.get("shard"), rec["tid"]),
            "ts": ts,
        })
    return flows


def to_chrome(trace: dict) -> dict:
    """A ``tracereport.load_trace`` dict → Chrome trace-event JSON."""
    begin = trace.get("begin") or {}
    lanes = _Lanes(begin)
    depths = _span_depths(trace["spans"])
    out: list = []

    for sp in trace["spans"]:
        pid = lanes.pid(sp.get("shard"))
        tid = lanes.tid(sp.get("shard"), sp["tid"])
        d = depths.get(sp["id"], 0)
        # Ties at one timestamp: E before B (close the old span before
        # opening the next), E deepest-first, B shallowest-first.
        out.append(((_us(sp["t0"]), 2, d), {
            "name": sp["name"], "cat": "span", "ph": "B",
            "pid": pid, "tid": tid, "ts": _us(sp["t0"]),
            "args": sp.get("attrs") or {},
        }))
        out.append(((_us(sp["t1"]), 0, -d), {
            "ph": "E", "pid": pid, "tid": tid, "ts": _us(sp["t1"]),
        }))
    for ev in trace["events"]:
        pid = lanes.pid(ev.get("shard"))
        tid = lanes.tid(ev.get("shard"), ev["tid"])
        if ev["name"] in _MARKER_EVENTS:
            out.append(((_us(ev["t"]), 2, 0), {
                "name": ev["name"], "cat": "request", "ph": "X",
                "pid": pid, "tid": tid, "ts": _us(ev["t"]),
                "dur": _MARKER_DUR_US, "args": ev.get("attrs") or {},
            }))
        else:
            out.append(((_us(ev["t"]), 2, 0), {
                "name": ev["name"], "cat": "event", "ph": "i", "s": "t",
                "pid": pid, "tid": tid, "ts": _us(ev["t"]),
                "args": ev.get("attrs") or {},
            }))
    for fl in _request_flows(trace, lanes):
        out.append(((fl["ts"], 1, 0), fl))
    for fl in _fleet_flows(trace, lanes):
        out.append(((fl["ts"], 1, 0), fl))

    out.sort(key=lambda pair: pair[0])
    events = lanes.meta + [rec for _key, rec in out]
    n_flows = sum(1 for e in events
                  if e.get("ph") == "s" and e.get("cat") == "request")
    n_fleet = sum(1 for e in events
                  if e.get("ph") == "s" and e.get("cat") == "fleet")
    return {
        "traceEvents": events,
        "displayTimeUnit": "ms",
        "metadata": {
            "exporter": "distributed_sddmm_tpu trace-export",
            "run_id": begin.get("run_id"),
            "t0_epoch": begin.get("t0_epoch"),
            "shards": [m.get("run_id") for m in begin.get("shards") or ()],
            "spans": len(trace["spans"]),
            "events": len(trace["events"]),
            "request_flows": n_flows,
            "fleet_flows": n_fleet,
        },
    }


def write_chrome(trace_path, out_path=None, strict: bool = True):
    """Load + validate ``trace_path``, write its Chrome JSON.

    Returns ``(out_path, chrome_dict)``. Default output sits next to
    the trace: ``<stem>.chrome.json``. Raises ``ValueError`` on a
    schema-invalid trace when ``strict`` (the CLI maps that to exit 2).
    """
    trace = tracereport.load_trace(trace_path, strict=strict)
    chrome = to_chrome(trace)
    if out_path is None:
        p = pathlib.Path(trace_path)
        out_path = p.with_name(p.stem + ".chrome.json")
    out_path = pathlib.Path(out_path)
    # Atomic: Perfetto rejects truncated JSON with an opaque error — a
    # kill mid-export must leave the old file or none, never a prefix.
    atomic_write_text(out_path, json.dumps(chrome, default=str))
    return out_path, chrome
