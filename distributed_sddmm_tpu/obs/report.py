"""Static HTML dashboard over the run store.

``bench report-html`` renders one **self-contained** HTML file — no
external assets, charts embedded as base64 PNGs — so it can be attached
to a CI artifact, mailed, or opened from a scp'd checkout without a
server. Sections:

* **Run history** — every stored run (backfilled rounds included),
  newest last, with backend, headline throughput, and anomaly counts.
* **Per-phase trends** — seconds/call per phase and headline GFLOP/s
  across the runs sharing the dashboard's focus fingerprint key (the
  most recent key by default): the "did PR N bend this curve" figure.
* **Latest compare** — the most recent run against its rolling
  baseline, straight from :func:`obs.regress.compare`, with verdict
  coloring and the comm/FLOP attribution columns.

Chart rendering reuses ``tools/charts.py`` (matplotlib). When
matplotlib is unavailable the dashboard degrades to tables only — the
numbers, not the pictures, are the contract.
"""

from __future__ import annotations

import base64
import html
import io
import pathlib
import time

from distributed_sddmm_tpu.obs import regress
from distributed_sddmm_tpu.utils.atomic import atomic_write_text

_CSS = """
body { font-family: -apple-system, 'Segoe UI', Roboto, sans-serif;
       margin: 2em auto; max-width: 72em; color: #222; }
h1, h2 { font-weight: 600; }
h1 { font-size: 1.4em; } h2 { font-size: 1.1em; margin-top: 2em; }
table { border-collapse: collapse; font-size: 0.82em; width: 100%; }
th, td { padding: 3px 8px; text-align: right; border-bottom: 1px solid #eee; }
th { background: #f6f6f6; position: sticky; top: 0; }
td.l, th.l { text-align: left; font-family: ui-monospace, monospace; }
tr.regression td { background: #fdecea; }
tr.improvement td { background: #eaf7ed; }
tr.missing td, tr.new td { background: #fff8e1; }
.meta { color: #777; font-size: 0.8em; }
.verdict-ok { color: #1a7f37; font-weight: 600; }
.verdict-regression { color: #c0392b; font-weight: 600; }
.verdict-improvement { color: #1a7f37; font-weight: 600; }
.verdict-no_data { color: #b8860b; font-weight: 600; }
img { max-width: 100%; }
"""


def _esc(v) -> str:
    return html.escape("-" if v is None else str(v))


def _fmt(v, nd: int = 3) -> str:
    if v is None:
        return "-"
    if isinstance(v, float):
        return f"{v:.{nd}f}"
    return str(v)


def _chart_png(draw) -> str | None:
    """Run ``draw(ax)`` on a fresh figure, return a data-URI PNG (None
    when matplotlib is absent or nothing was drawn)."""
    try:
        import matplotlib

        matplotlib.use("Agg")
        import matplotlib.pyplot as plt
    except ImportError:
        return None
    fig, ax = plt.subplots(figsize=(9.5, 4.0))
    try:
        if draw(ax) is False:
            return None
        fig.tight_layout()
        buf = io.BytesIO()
        fig.savefig(buf, format="png", dpi=120)
    finally:
        plt.close(fig)
    return "data:image/png;base64," + base64.b64encode(buf.getvalue()).decode()


def _history_table(rows: list[dict]) -> str:
    cells = [
        "<table><tr><th class=l>run_id</th><th class=l>source</th>"
        "<th class=l>algorithm</th><th>app</th><th>R</th><th>c</th>"
        "<th class=l>variant</th>"
        "<th>backend</th><th>elapsed&nbsp;s</th><th>GFLOP/s</th>"
        "<th>cold&nbsp;compiles</th>"
        "<th>p99&nbsp;ms</th><th>burn</th>"
        "<th>anomalies</th><th class=l>key</th></tr>"
    ]
    for r in rows:
        anom = r.get("anomaly_count", 0)
        burn = r.get("burn_rate")
        style = (
            ' class="regression"'
            if anom or (burn is not None and burn > 1.0) else ""
        )
        live = r.get("live_compiles")
        p99 = r.get("hist_p99_ms")
        if p99 is None:
            p99 = r.get("latency_p99_ms")
        cells.append(
            f"<tr{style}><td class=l>{_esc(r.get('run_id'))}</td>"
            f"<td class=l>{_esc(r.get('source'))}</td>"
            f"<td class=l>{_esc(r.get('algorithm'))}</td>"
            f"<td>{_esc(r.get('app'))}</td><td>{_esc(r.get('R'))}</td>"
            f"<td>{_esc(r.get('c'))}</td>"
            f"<td class=l>{_esc(r.get('kernel_variant') or '-')}</td>"
            f"<td>{_esc(r.get('backend'))}</td>"
            f"<td>{_fmt(r.get('elapsed'))}</td>"
            f"<td>{_fmt(r.get('overall_throughput'))}</td>"
            f"<td>{'-' if live is None else int(live)}</td>"
            f"<td>{_fmt(p99, 1)}</td>"
            f"<td>{_fmt(burn, 2)}</td>"
            f"<td>{anom or ''}</td>"
            f"<td class=l>{_esc((r.get('key') or '')[:16])}</td></tr>"
        )
    cells.append("</table>")
    return "".join(cells)


def _flight_record_rows(store, rows: list[dict]) -> list[tuple]:
    """(run_id, kind, op, count, snapshot_path) for every anomaly group
    of every run that recorded any — ``snapshot_path`` present when the
    flight recorder was armed (PR 8), letting the dashboard jump from
    an anomaly row straight to the span-ring dump that explains it."""
    out = []
    for r in rows:
        if not r.get("anomaly_count"):
            continue
        doc = store.get(r["run_id"])
        anom = ((doc or {}).get("record") or {}).get("anomalies") or {}
        for g in anom.get("anomalies") or ():
            out.append((
                r["run_id"], g.get("kind"), g.get("op"),
                g.get("count", 1),
                (g.get("first") or {}).get("snapshot_path"),
            ))
    return out


def _flight_table(rows: list[tuple]) -> str:
    cells = [
        "<table><tr><th class=l>run_id</th><th class=l>anomaly</th>"
        "<th class=l>op</th><th>count</th>"
        "<th class=l>flight record</th></tr>"
    ]
    for run_id, kind, op, count, path in rows:
        link = (
            f'<a href="file://{_esc(path)}">{_esc(path)}</a>'
            if path else "-"
        )
        cells.append(
            f'<tr class="regression"><td class=l>{_esc(run_id)}</td>'
            f"<td class=l>{_esc(kind)}</td><td class=l>{_esc(op)}</td>"
            f"<td>{count}</td><td class=l>{link}</td></tr>"
        )
    cells.append("</table>")
    return "".join(cells)


def _compare_table(report: dict) -> str:
    cells = [
        "<table><tr><th class=l>phase</th><th>calls</th>"
        "<th>t/call base</th><th>t/call new</th><th>Δ%</th>"
        "<th>GF/s base</th><th>GF/s new</th><th>Mwords/call</th>"
        "<th>MB/call</th>"
        "<th>words/model</th><th>verdict</th><th>blame</th></tr>"
    ]
    for name, row in report["phases"].items():
        v = row["verdict"]
        a, b = row.get("a"), row.get("b")
        if v in ("missing", "new"):
            cells.append(
                f'<tr class="{v}"><td class=l>{_esc(name)}</td>'
                + "<td>-</td>" * 9
                + f"<td>{v}</td><td></td></tr>"
            )
            continue
        mwords = b["comm_words"] / b["calls"] / 1e6 if b["calls"] else 0.0
        # Wire-dtype-aware volume (PR 15); None on pre-PR-15 docs —
        # rendered as '-' (not measured), never as zero traffic.
        mbytes = (
            b["comm_bytes"] / b["calls"] / 1e6
            if b["calls"] and b.get("comm_bytes") is not None else None
        )
        cells.append(
            f'<tr class="{v if v != "ok" else ""}">'
            f"<td class=l>{_esc(name)}</td><td>{b['calls']}</td>"
            f"<td>{_fmt(row.get('baseline_median_t_call'), 6)}</td>"
            f"<td>{_fmt(b['t_call'], 6)}</td>"
            f"<td>{_fmt(row.get('delta_pct'), 1)}</td>"
            f"<td>{_fmt(a.get('gflops'))}</td><td>{_fmt(b.get('gflops'))}</td>"
            f"<td>{_fmt(mwords)}</td>"
            f"<td>{_fmt(mbytes)}</td>"
            f"<td>{_fmt(b.get('model_ratio'))}</td>"
            f"<td>{v}</td><td>{_esc(row.get('attribution', ''))}</td></tr>"
        )
    cells.append("</table>")
    return "".join(cells)


def _latency_series(store, rows: list[dict]) -> dict:
    """Serving latency trend (ms) across every stored ``bench serve``
    run in ``rows`` — p50/p99 plus the shed count scaled into view via
    its own series label. Rows without ``latency_ms`` (offline runs)
    contribute nothing, so the panel only renders when serving history
    exists."""
    series: dict[str, list] = {}
    for x, r in enumerate(rows):
        if r.get("latency_p99_ms") is None:
            continue
        doc = store.get(r["run_id"])
        lat = ((doc or {}).get("record") or {}).get("latency_ms") or {}
        for pct in ("p50", "p99"):
            if lat.get(pct) is not None:
                series.setdefault(f"latency {pct} (ms)", []).append(
                    (x, lat[pct])
                )
    return series


def _burn_series(rows: list[dict]) -> dict:
    """SLO error-budget burn-rate trend (index-only: the burn rate is a
    PR-7 index column). Pre-PR-7 rows carry None and contribute
    nothing — the panel renders only when measured history exists."""
    series: dict[str, list] = {}
    for x, r in enumerate(rows):
        if r.get("burn_rate") is not None:
            series.setdefault("error-budget burn rate", []).append(
                (x, r["burn_rate"])
            )
    return series


def _attention_rows(store, rows: list[dict]) -> list[tuple]:
    """(run_id, mask, R, fused, throughput, hbm fused/unfused/savings)
    for every attention run (``app == "attention"``); the HBM columns
    come from the run doc's counted ``attention_hbm`` record."""
    out = []
    for r in rows:
        if r.get("app") != "attention":
            continue
        doc = store.get(r["run_id"]) or {}
        hbm = (doc.get("record") or {}).get("attention_hbm") or {}
        out.append((
            r.get("run_id"), r.get("mask"), r.get("R"), r.get("fused"),
            r.get("overall_throughput"), hbm.get("fused_bytes"),
            hbm.get("unfused_bytes"), hbm.get("savings_frac"),
        ))
    return out


def _attention_table(rows: list[tuple]) -> str:
    head = (
        "<tr><th class=l>run</th><th class=l>mask</th><th>R</th>"
        "<th>fused</th><th>GFLOP/s</th><th>HBM fused</th>"
        "<th>HBM unfused</th><th>HBM cut</th></tr>"
    )
    body = []
    for run, mask, R, fused, gf, fb, ub, sf in rows:
        body.append(
            f"<tr><td class=l>{_esc((run or '')[:24])}</td>"
            f"<td class=l>{_esc(mask or '-')}</td><td>{_fmt(R, 0)}</td>"
            f"<td>{'yes' if fused else 'no'}</td><td>{_fmt(gf)}</td>"
            f"<td>{_fmt(fb, 0)}</td><td>{_fmt(ub, 0)}</td>"
            f"<td>{_fmt(sf * 100, 1) + '%' if sf is not None else '-'}"
            f"</td></tr>"
        )
    return f"<table>{head}{''.join(body)}</table>"


def _fleet_rows(store, rows: list[dict]) -> list[tuple]:
    """(run_id, replicas, chaos, availability, ok, shed+deferred,
    losses, mismatches, replacement live compiles, worst tenant burn)
    for every ``bench fleet`` run — the serving-fleet ops panel."""
    out = []
    for r in rows:
        doc = store.get(r["run_id"]) or {}
        rec = doc.get("record") or {}
        fleet = rec.get("fleet") or {}
        if fleet.get("availability") is None:
            continue
        worst = None
        for name, cell in (rec.get("tenant") or {}).items():
            b = cell.get("burn_rate")
            if b is not None and (worst is None or b > worst[1]):
                worst = (name, b)
        out.append((
            r.get("run_id"), fleet.get("replicas"), fleet.get("chaos"),
            fleet.get("availability"), fleet.get("ok"),
            (fleet.get("shed_with_retry") or 0)
            + (fleet.get("deferred") or 0),
            fleet.get("losses"), fleet.get("mismatches"),
            fleet.get("replacement_live_compiles"), worst,
        ))
    return out


def _fleet_table(rows: list[tuple]) -> str:
    head = (
        "<tr><th class=l>run</th><th>replicas</th><th class=l>chaos</th>"
        "<th>availability</th><th>ok</th><th>shed/deferred</th>"
        "<th>losses</th><th>mismatches</th><th>respawn compiles</th>"
        "<th class=l>worst tenant burn</th></tr>"
    )
    body = []
    for run, n, chaos, avail, ok, shed, losses, mism, rlc, worst in rows:
        body.append(
            f"<tr><td class=l>{_esc((run or '')[:24])}</td>"
            f"<td>{_fmt(n, 0)}</td><td class=l>{_esc(chaos or '-')}</td>"
            f"<td>{_fmt(avail * 100, 2) + '%' if avail is not None else '-'}"
            f"</td><td>{_fmt(ok, 0)}</td><td>{_fmt(shed, 0)}</td>"
            f"<td>{_fmt(losses, 0)}</td><td>{_fmt(mism, 0)}</td>"
            f"<td>{_fmt(rlc, 0)}</td>"
            f"<td class=l>{_esc(f'{worst[0]} ({worst[1]:.2f}x)') if worst else '-'}"
            f"</td></tr>"
        )
    return f"<table>{head}{''.join(body)}</table>"


def _trend_series(store, rows: list[dict]) -> tuple[dict, dict]:
    """(per-phase t/call series, headline series) across ``rows``."""
    per_phase: dict[str, list] = {}
    headline: dict[str, list] = {"GFLOP/s": []}
    for x, r in enumerate(rows):
        if r.get("overall_throughput"):
            headline["GFLOP/s"].append((x, r["overall_throughput"]))
        doc = store.get(r["run_id"])
        if not doc:
            continue
        for name, ph in regress.phase_stats(doc).items():
            per_phase.setdefault(name, []).append((x, ph["t_call"]))
    return per_phase, headline


def build_html(
    store,
    out_path: str | pathlib.Path | None = None,
    limit: int = 100,
    key: str | None = None,
    threshold: float = 0.15,
) -> pathlib.Path:
    """Render the dashboard; returns the written path (default
    ``<store root>/report.html``)."""
    from distributed_sddmm_tpu.tools import charts

    out_path = pathlib.Path(out_path) if out_path else store.root / "report.html"
    all_rows = store.history(limit=limit)
    # Focus key for trends/compare: the most recent run's key unless
    # pinned — trends across different problems would be meaningless.
    if key is None:
        for r in reversed(all_rows):
            if r.get("key"):
                key = r["key"]
                break
    focus_rows = [r for r in all_rows if key and r.get("key") == key]

    sections = [
        "<h1>distributed_sddmm_tpu run history</h1>",
        f'<p class=meta>store: {_esc(store.root)} · generated '
        f'{time.strftime("%Y-%m-%d %H:%M:%S")} · {len(all_rows)} runs shown'
        f" · focus key: {_esc((key or '')[:16])}</p>",
        "<h2>Runs</h2>", _history_table(all_rows),
    ]

    flights = _flight_record_rows(store, all_rows)
    if flights:
        sections += [
            "<h2>Anomalies &amp; flight records</h2>",
            "<p class=meta>Watchdog anomalies per run; when the flight "
            "recorder was armed, each links to the span-ring snapshot "
            "written at the moment it fired.</p>",
            _flight_table(flights),
        ]

    per_phase, headline = _trend_series(store, focus_rows)
    png = _chart_png(lambda ax: charts.trend_chart(ax, per_phase))
    if png:
        sections += ["<h2>Per-phase seconds/call (focus key)</h2>",
                     f'<img src="{png}" alt="per-phase trend">']
    png = _chart_png(
        lambda ax: charts.trend_chart(
            ax, headline, ylabel="GFLOP/s", logy=False)
    )
    if png:
        sections += ["<h2>Headline throughput (focus key)</h2>",
                     f'<img src="{png}" alt="throughput trend">']

    attn = _attention_rows(store, all_rows)
    if attn:
        sections += [
            "<h2>Sparse attention (all attention runs)</h2>",
            "<p class=meta>Fused SDDMM → masked-softmax → SpMM runs per "
            "mask family; the HBM columns are the counted program-I/O "
            "traffic of the fused pair vs the three-program unfused "
            "sequence.</p>",
            _attention_table(attn),
        ]

    lat_series = _latency_series(store, all_rows)
    png = _chart_png(
        lambda ax: charts.trend_chart(
            ax, lat_series, ylabel="latency (ms)", logy=False)
    )
    if png:
        sections += ["<h2>Serving latency trend (all serve runs)</h2>",
                     f'<img src="{png}" alt="serving latency trend">']

    burn_series = _burn_series(all_rows)
    png = _chart_png(
        lambda ax: charts.trend_chart(
            ax, burn_series, ylabel="burn rate (x budget)", logy=False)
    )
    if png:
        sections += [
            "<h2>SLO error-budget burn rate (all serve runs)</h2>",
            "<p class=meta>1.0 = burning exactly at budget; above the "
            "line the SLO will be violated if the window holds.</p>",
            f'<img src="{png}" alt="burn rate trend">',
        ]

    fleet = _fleet_rows(store, all_rows)
    if fleet:
        sections += [
            "<h2>Serving fleet (all fleet runs)</h2>",
            "<p class=meta>Replica pool behind the front router: "
            "availability = (answered + shed-with-retry + deferred) / "
            "offered through the chaos window; mismatches compare every "
            "reply bit-for-bit against the single-engine oracle; "
            "respawn compiles must be 0 (warm-start from the shared "
            "program store).</p>",
            _fleet_table(fleet),
        ]

    if len(focus_rows) >= 2:
        newest = store.get(focus_rows[-1]["run_id"])
        baseline = store.matching(newest, limit=5) if newest else []
        if newest and baseline:
            rep = regress.compare(
                newest, baseline_docs=baseline, threshold=threshold
            )
            sections += [
                f"<h2>Latest compare — verdict "
                f'<span class="verdict-{rep["verdict"]}">'
                f'{rep["verdict"]}</span></h2>',
                f"<p class=meta>{_esc(rep['run_a'])} → "
                f"{_esc(rep['run_b'])} (baseline n={rep['baseline_n']}, "
                f"threshold ±{threshold * 100:.0f}%)</p>",
                _compare_table(rep),
            ]

    doc = (
        "<!doctype html><html><head><meta charset='utf-8'>"
        "<title>distributed_sddmm_tpu runs</title>"
        f"<style>{_CSS}</style></head><body>"
        + "".join(sections)
        + "</body></html>"
    )
    # Atomic: a dashboard refresh must never serve a half-written page.
    atomic_write_text(out_path, doc)
    return out_path
