"""Anomaly-triggered flight recorder: dump the last N spans on alarm.

The watchdog (``obs/watchdog.py``) *detects* anomalies — EWMA step-time
spikes, repair storms, comm mismatch, queue runaway — but until now it
only recorded THAT something fired, never the context needed to explain
it after the fact. This module is the black box: the tracer keeps a
bounded in-memory ring of recent spans/events (``obs.trace.arm_ring``),
and when the watchdog fires while a recorder is armed, the ring plus
metrics/telemetry snapshots (and, when profiling is armed, a short
``jax.profiler`` capture window) are dumped to::

    artifacts/flightrec/<run_id>/<seq>-<kind>.json

The dump path is stamped into the anomaly's trace event and into the
bench record's ``anomalies`` summary (``snapshot_path``), so
``report-html`` and post-mortems can jump from "p99 regressed at 14:03"
straight to the spans surrounding the spike.

Design constraints:

* **Never in the hot path.** Disabled (the default) the only cost is
  the watchdog's existing anomaly path checking one module-level
  ``None``. Armed, the ring tap is one deque append per emitted record.
* **Never fails the run.** ``dump()`` swallows everything; a failed
  dump returns None and the anomaly proceeds exactly as before.
* **Bounded.** ``max_dumps`` caps files per process (an anomaly storm
  must not fill the disk with identical snapshots); the ring caps
  memory.

Activation mirrors the tracer/watchdog pattern: ``DSDDMM_FLIGHTREC``
(``1``/``on`` → the default directory, ``0``/``off`` → disabled, any
other value → a directory) or the bench CLI's ``--flightrec`` flag, or
programmatic :func:`enable`.
"""

from __future__ import annotations

import os
import pathlib
import threading
from typing import Callable, Optional

from distributed_sddmm_tpu.obs import clock
from distributed_sddmm_tpu.obs import log as obs_log
from distributed_sddmm_tpu.obs import metrics as obs_metrics
from distributed_sddmm_tpu.obs import trace as obs_trace

_REPO = pathlib.Path(__file__).resolve().parents[2]
DEFAULT_FLIGHTREC_DIR = _REPO / "artifacts" / "flightrec"

SCHEMA_VERSION = 1


class FlightRecorder:
    """One process's armed black box."""

    def __init__(
        self,
        out_dir=None,
        ring_capacity: int = 512,
        max_dumps: int = 16,
        profile_window_s: float = 0.0,
        run_id: Optional[str] = None,
    ):
        self.out_root = (
            pathlib.Path(out_dir) if out_dir else DEFAULT_FLIGHTREC_DIR
        )
        #: Whether WE armed the ring (vs. tapping one an AdminServer or
        #: caller already armed) — module-level :func:`disable` only
        #: disarms what the recorder armed, mirroring
        #: ``AdminServer.stop``'s guard in the other direction.
        self._armed_ring = obs_trace.ring() is None
        self.ring = obs_trace.arm_ring(ring_capacity)
        self.max_dumps = int(max_dumps)
        #: >0 arms the short ``jax.profiler`` window per dump (the CLI
        #: sets this only when ``--profile`` is also armed — capture has
        #: real overhead and needs an operator opt-in).
        self.profile_window_s = float(profile_window_s)
        # The ring arm may have installed the run's (memory) tracer, so
        # run_id() is authoritative after it.
        self.run_id = run_id or obs_trace.run_id() or obs_trace._make_run_id()
        self.out_dir = self.out_root / self.run_id
        self._lock = threading.Lock()
        self.dumps = 0
        #: File-name sequence — monotonic and never refunded, unlike the
        #: ``dumps`` budget: a failed dump gives its budget slot back,
        #: but reusing its seq could overwrite a concurrent successful
        #: dump's file (and the snapshot_path already stamped for it).
        self._seq = 0
        #: Paths written this session, in firing order.
        self.paths: list[str] = []
        #: Named snapshot callables merged into every dump (the serve
        #: CLI registers the engine's telemetry snapshot; offline runs
        #: get GLOBAL metrics regardless).
        self._sources: dict[str, Callable[[], dict]] = {}

    def register_source(self, name: str, fn: Callable[[], dict]) -> None:
        """Attach a snapshot source (called per dump; exceptions are
        recorded as the source's value, never raised)."""
        with self._lock:
            self._sources[name] = fn

    # ------------------------------------------------------------------ #

    def dump(self, kind: str, op: str, attrs: dict) -> Optional[str]:
        """Write one flight record for an anomaly; returns its path or
        None (budget exhausted / write failed). Never raises. A failed
        write refunds its budget slot — a persistent serialization or
        disk error must not silently exhaust ``max_dumps``."""
        try:
            return self._dump(kind, op, attrs)
        except Exception as e:  # noqa: BLE001 — the run goes on
            with self._lock:
                self.dumps = max(0, self.dumps - 1)
            obs_log.warn("flightrec", "dump failed",
                         kind=kind, error=f"{type(e).__name__}: {e}")
            return None

    def _dump(self, kind: str, op: str, attrs: dict) -> Optional[str]:
        with self._lock:
            if self.dumps >= self.max_dumps:
                return None
            self.dumps += 1
            seq = self._seq
            self._seq += 1
            sources = dict(self._sources)
        path = self.out_dir / f"{seq:03d}-{kind}.json"
        record = {
            "schema": SCHEMA_VERSION,
            "run_id": self.run_id,
            "seq": seq,
            "t_epoch": clock.epoch(),
            "anomaly": {"kind": kind, "op": op, "attrs": dict(attrs)},
            "ring": self.ring.records(),
            "ring_seen": self.ring.appended,
            "metrics": {"global": obs_metrics.GLOBAL.snapshot()},
        }
        for name, fn in sources.items():
            try:
                record.setdefault("sources", {})[name] = fn()
            except Exception as e:  # noqa: BLE001 — recorded, not raised
                record.setdefault("sources", {})[name] = {
                    "error": f"{type(e).__name__}: {e}"
                }
        if self.profile_window_s > 0:
            from distributed_sddmm_tpu.obs import profiler

            logdir = str(self.out_dir / f"{seq:03d}-profile")
            # Non-blocking: the watchdog fires from the dispatch path;
            # the capture window rides a daemon thread and lands (or
            # not — best effort) after the dump file does.
            started = profiler.capture_window(
                logdir, duration_s=self.profile_window_s, block=False
            )
            record["profile"] = {"logdir": logdir, "started": started}
        from distributed_sddmm_tpu.utils.atomic import atomic_write_json

        # default=str: the ring holds attrs exactly as emitted, and the
        # tracer's own serializer stringifies non-JSON values (Paths,
        # numpy scalars) — the dump must accept anything the ring can.
        atomic_write_json(path, record, default=str)
        with self._lock:
            self.paths.append(str(path))
        obs_metrics.GLOBAL.add("flightrec_dumps")
        obs_log.warn("flightrec", "anomaly snapshot written",
                     kind=kind, op=op, path=str(path))
        return str(path)


# --------------------------------------------------------------------- #
# Module-level activation (env + CLI), watchdog/tracer-style
# --------------------------------------------------------------------- #

_active: Optional[FlightRecorder] = None
_env_checked = False
_registry_lock = threading.Lock()


def parse_env_spec(spec: str | None) -> tuple[bool, pathlib.Path | None]:
    """``DSDDMM_FLIGHTREC`` grammar, matching the telemetry/runstore
    one: 0/off/false/no disables, 1/on/true/yes selects the default
    directory, any other value is a directory."""
    spec = spec or ""
    low = spec.lower()
    if low in ("", "0", "off", "false", "no"):
        return False, None
    if low in ("1", "on", "true", "yes"):
        return True, None
    return True, pathlib.Path(spec)


def enable(out_dir=None, **knobs) -> FlightRecorder:
    """Arm a process-wide flight recorder (replaces any previous one —
    the dump budget and ring are per-session)."""
    global _active, _env_checked
    with _registry_lock:
        _env_checked = True
        _active = FlightRecorder(out_dir=out_dir, **knobs)
        return _active


def disable() -> None:
    global _active, _env_checked
    with _registry_lock:
        fr = _active
        _active = None
        _env_checked = True
    # Disarm only a ring the recorder armed itself: an AdminServer (or
    # test) that armed it first still owns it — yanking it here would
    # break /debug/requests and, when the memory-only tracer was the
    # only tracer, silently stop span emission for the whole process.
    if fr is not None and fr._armed_ring:
        obs_trace.disarm_ring()


def active() -> Optional[FlightRecorder]:
    """The armed recorder, activating from ``DSDDMM_FLIGHTREC`` on
    first query (the watchdog calls this on every anomaly)."""
    global _active, _env_checked
    if _env_checked:
        return _active
    with _registry_lock:
        if not _env_checked:
            _env_checked = True
            enabled, root = parse_env_spec(os.environ.get("DSDDMM_FLIGHTREC"))
            if enabled:
                _active = FlightRecorder(out_dir=root)
    return _active
