"""Offset-aligned merge of per-process trace shards into one trace.

A multi-process run (the serve smoke's workers, ``tests/_mp_worker.py``,
open item 1's multi-host pods) produces one JSONL shard per process —
each with its own monotonic origin, so their ``t0``/``t1``/``t``
timestamps are not comparable. Every shard's ``begin`` record carries
the clock-calibration header the tracer has always written
(``t0_epoch``: the wall-clock reading of the monotonic origin), and the
merge aligns on it:

* the earliest shard's ``t0_epoch`` becomes the merged origin;
* every other shard's records shift by ``(its t0_epoch - base)`` —
  monotonic-duration accuracy within a shard is preserved exactly, and
  cross-shard ordering is accurate to wall-clock-sync accuracy (NTP on
  one host: sub-millisecond; good enough to order batches, not kernels);
* span/event ids are renumbered into disjoint ranges (each process
  counts from 1) with ``parent`` links rewritten, and every record is
  tagged with its ``shard`` (source run_id) and ``pid``;
* the output is one schema-valid trace (``tools/tracereport`` validates
  every record on load and again after the merge), time-sorted, with a
  ``begin`` whose ``shards`` list records each source's run_id, pid,
  epoch and applied offset.

CLI: ``python -m distributed_sddmm_tpu.bench trace-merge SPEC... [-o
OUT]`` where a SPEC is a shard file, a shard directory, or an explicit
``PATH.jsonl`` stem (merged with its sibling ``PATH.shards/``
directory, the layout ``obs/trace.py`` reroutes worker processes into).
"""

from __future__ import annotations

import hashlib
import json
import pathlib

from distributed_sddmm_tpu.tools import tracereport
from distributed_sddmm_tpu.utils.atomic import atomic_write_lines


def _is_merged_output(path: pathlib.Path) -> bool:
    """True when the file's begin record is itself a merge product
    (carries a ``shards`` list). Globbed spec expansion skips these so
    re-running ``trace-merge`` over a directory that already holds a
    prior merged output doesn't double-count every span."""
    try:
        with open(path) as fh:
            rec = json.loads(fh.readline())
    except (OSError, ValueError):
        return False
    return (isinstance(rec, dict) and rec.get("type") == "begin"
            and "shards" in rec)


def discover(spec) -> list[pathlib.Path]:
    """Shard files for one CLI spec: a directory (every ``*.jsonl``
    inside), a ``PATH.jsonl`` stem (itself + ``PATH.shards/*.jsonl``),
    or a single file. Prior merged outputs found by globbing are
    excluded; a merged trace named explicitly is kept as given."""
    p = pathlib.Path(spec)
    if p.is_dir():
        out = [f for f in sorted(p.glob("*.jsonl"))
               if not _is_merged_output(f)]
        if not out:
            raise FileNotFoundError(f"no *.jsonl shards in {p}")
        return out
    out = [p] if p.exists() else []
    shards = p.with_suffix(".shards")
    if p.suffix == ".jsonl" and shards.is_dir():
        out += [f for f in sorted(shards.glob("*.jsonl"))
                if not _is_merged_output(f)]
    if not out:
        raise FileNotFoundError(f"no trace shards at {spec}")
    return out


def merge(paths, strict: bool = True) -> dict:
    """Merge shard files into ``{"begin", "spans", "events", "errors"}``
    (the ``tracereport.load_trace`` shape, plus ``begin["shards"]``).

    Raises ``ValueError`` when ``strict`` and any shard fails schema
    validation, or when no shard contributes a ``begin`` record.
    """
    loaded, errors = [], []
    for path in paths:
        tr = tracereport.load_trace(path, strict=strict)
        errors.extend(f"{path}: {e}" for e in tr["errors"])
        if tr["begin"] is None:
            errors.append(f"{path}: no begin record; shard skipped")
            continue
        loaded.append((pathlib.Path(path), tr))
    if not loaded:
        raise ValueError(
            "no mergeable shards: " + "; ".join(errors[:5]) if errors
            else "no mergeable shards"
        )

    base_epoch = min(
        float(tr["begin"].get("t0_epoch") or 0.0) for _, tr in loaded
    )
    spans, events, shards_meta = [], [], []
    #: (source run_id, original span id) -> merged span id: the lookup
    #: the cross-process parent rewrite below resolves fleet links with.
    spanmap: dict = {}
    id_base = 0
    for path, tr in loaded:
        b = tr["begin"]
        off = float(b.get("t0_epoch") or base_epoch) - base_epoch
        rid, pid = b.get("run_id"), b.get("pid")
        max_id = 0
        for sp in tr["spans"]:
            sp = dict(sp)
            max_id = max(max_id, int(sp["id"]))
            spanmap[(rid, int(sp["id"]))] = int(sp["id"]) + id_base
            sp["id"] = int(sp["id"]) + id_base
            if sp.get("parent") is not None:
                sp["parent"] = int(sp["parent"]) + id_base
            sp["t0"] = round(sp["t0"] + off, 9)
            sp["t1"] = round(sp["t1"] + off, 9)
            sp["shard"] = rid
            if pid is not None:
                sp["pid"] = pid
            spans.append(sp)
        for ev in tr["events"]:
            ev = dict(ev)
            max_id = max(max_id, int(ev["id"]))
            ev["id"] = int(ev["id"]) + id_base
            if ev.get("parent") is not None:
                ev["parent"] = int(ev["parent"]) + id_base
            ev["t"] = round(ev["t"] + off, 9)
            # serve:reply embeds precise trace-relative stamps alongside
            # the emission-time `t`; they live in the same timebase and
            # must shift with it or merged chains land in the source
            # shard's timeline.
            attrs = ev.get("attrs")
            if isinstance(attrs, dict):
                shifted = {
                    k: round(attrs[k] + off, 9)
                    for k in ("t_enqueue", "t_reply")
                    if isinstance(attrs.get(k), (int, float))
                }
                if shifted:
                    ev["attrs"] = {**attrs, **shifted}
            ev["shard"] = rid
            if pid is not None:
                ev["pid"] = pid
            events.append(ev)
        shards_meta.append({
            "run_id": rid, "pid": pid,
            "t0_epoch": b.get("t0_epoch"), "offset_s": round(off, 9),
            "path": str(path),
            "spans": len(tr["spans"]), "events": len(tr["events"]),
        })
        id_base += max_id

    # Second pass — cross-process causality. A record carrying a
    # ``fleet_span`` attr names its causal parent span in another shard
    # (``fleet_shard``, the router's run_id; absent = its own shard —
    # the router's side-thread attempt spans). The merged id is
    # published as ``attrs.fleet_parent`` on every linked record, and a
    # record with no in-process parent (the replica's enqueue event,
    # hedge/audit attempts on parentless side threads) is re-parented
    # onto it — one causally-connected tree per fleet request, without
    # disturbing in-process nesting where it exists (``serve:reply``
    # stays under its ``serve:batch`` span).
    fleet_links = 0
    for rec in spans + events:
        attrs = rec.get("attrs")
        if not isinstance(attrs, dict):
            continue
        fspan = attrs.get("fleet_span")
        if fspan is None:
            continue
        try:
            key = ((attrs.get("fleet_shard") or rec.get("shard")),
                   int(fspan))
        except (TypeError, ValueError):
            continue
        target = spanmap.get(key)
        if target is None:
            continue
        rec["attrs"] = {**attrs, "fleet_parent": target}
        if rec.get("parent") is None:
            rec["parent"] = target
        fleet_links += 1

    spans.sort(key=lambda r: r["t0"])
    events.sort(key=lambda r: r["t"])
    digest = hashlib.sha256(
        "|".join(str(s["run_id"]) for s in shards_meta).encode()
    ).hexdigest()[:10]
    begin = {
        "type": "begin",
        "schema": tracereport.SUPPORTED_SCHEMA,
        "run_id": f"merged-{digest}",
        "t0_epoch": base_epoch,
        "shards": shards_meta,
        "fleet_links": fleet_links,
    }
    return {"begin": begin, "spans": spans, "events": events,
            "errors": errors}


def write_merged(paths, out_path=None, strict: bool = True):
    """Merge ``paths`` and write one time-sorted JSONL trace.

    Returns ``(out_path, merged)``. Default output:
    ``<first shard's directory>/<merged run_id>.jsonl``. Every written
    record is re-validated — a merge that produced an invalid record is
    a bug and raises rather than persisting garbage.
    """
    merged = merge(paths, strict=strict)
    records = sorted(
        merged["spans"] + merged["events"],
        key=lambda r: r["t0"] if r["type"] == "span" else r["t"],
    )
    for rec in [merged["begin"]] + records:
        errs = tracereport.validate_record(rec)
        if errs:
            raise ValueError(f"merge produced an invalid record: {errs}")
    if out_path is None:
        out_path = (
            pathlib.Path(paths[0]).parent / f"{merged['begin']['run_id']}.jsonl"
        )
    out_path = pathlib.Path(out_path)
    # Atomic + streaming: a merged trace is a one-shot artifact — a
    # reader (or a re-run globbing for shards) must never see a
    # half-written file — and multi-shard serving traces are large, so
    # records serialize one at a time instead of joining into one
    # in-memory payload.
    atomic_write_lines(
        out_path,
        (json.dumps(rec, default=str)
         for rec in [merged["begin"], *records]),
    )
    return out_path, merged
