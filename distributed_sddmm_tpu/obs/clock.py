"""The one clock module: monotonic now(), wall epoch(), calibration.

Every duration and timeline stamp in the serving and observability
layers reads :func:`now` (``time.perf_counter`` — monotonic, immune to
NTP steps and wall-clock adjustments); every piece of *metadata* that
must be meaningful across processes and reboots reads :func:`epoch`
(``time.time``). The split matters because the two clocks drift: a span
whose ``t0`` came from one and ``t1`` from the other can report a
negative duration across an NTP correction, and a multi-process trace
whose shards mixed them cannot be offset-aligned.

:func:`calibration` returns the pair ``(perf_origin, epoch_origin)``
captured together — the perf_counter↔wall-clock anchor the tracer
writes into every trace's ``begin`` record and ``bench trace-merge``
uses to offset-align shards from different processes: two shards'
monotonic timelines become comparable by shifting each by its own
``epoch_origin`` relative to the earliest shard's.

``tests/test_obs_lint.py`` enforces the discipline: raw
``time.time()``/``time.perf_counter()`` calls are forbidden in
``serve/`` and ``obs/`` outside this module (a line tagged
``# wall-clock-ok`` opts out for the rare legitimate exception).
"""

from __future__ import annotations

import time

#: Captured together at import: the perf_counter↔epoch anchor. The pair
#: is the process's clock calibration — ``epoch_for`` maps any
#: perf_counter value to an (approximate) wall-clock time through it.
PERF_ORIGIN = time.perf_counter()  # wall-clock-ok — the calibration pair
EPOCH_ORIGIN = time.time()  # wall-clock-ok — the calibration pair


def now() -> float:
    """Monotonic seconds (``time.perf_counter``): durations, timelines,
    deadlines. Comparable only within this process."""
    return time.perf_counter()  # wall-clock-ok — this IS the clock module


def epoch() -> float:
    """Wall-clock seconds since the Unix epoch (``time.time``):
    created-at metadata, cross-process alignment. Never subtract two of
    these for a duration — NTP can step between them."""
    return time.time()  # wall-clock-ok — this IS the clock module


def calibration() -> dict:
    """The process's perf_counter↔epoch anchor pair, JSON-ready."""
    return {"perf_origin": PERF_ORIGIN, "epoch_origin": EPOCH_ORIGIN}


def epoch_for(perf_t: float) -> float:
    """Approximate wall-clock time of a perf_counter stamp, through the
    import-time calibration (good to clock-drift accuracy — fine for
    aligning traces, not for billing)."""
    return EPOCH_ORIGIN + (perf_t - PERF_ORIGIN)
