"""Process-wide structured tracer: nested spans, thread-safe JSONL.

One tracer per process, activated by the ``DSDDMM_TRACE`` environment
variable (``1`` → the default ``artifacts/traces/<run_id>.jsonl``; any
other value is used as the output path, a directory landing the default
file name inside it), by the bench CLI's ``--trace`` flag, or
programmatically via :func:`enable`.

Design constraints, in order:

1. **Near-zero overhead when disabled.** :func:`span` and :func:`event`
   check one module-level boolean and return a shared no-op object —
   no allocation, no lock, no clock read. Strategy dispatch calls these
   on every compiled-program call; the disabled path must cost
   nanoseconds (pinned by a test).
2. **Thread-safe emission.** Retry workers, autotune trials and the
   checkpoint writer all emit from non-main threads; records are
   serialized under one lock and written as complete lines, so a trace
   is valid JSONL even under concurrency. Span *nesting* is tracked
   per-thread (thread-local stack) — a worker thread's spans parent to
   that thread's enclosing span, never to another thread's.
3. **Monotonic timestamps.** ``t0``/``t1`` are ``time.perf_counter``
   offsets from the tracer's start; the begin record carries the epoch
   time of that origin so tools can reconstruct wall-clock — and so
   ``bench trace-merge`` can offset-align shards written by different
   processes onto one timeline (each process's ``t0_epoch`` is its
   shard's clock-calibration header).
4. **One process, one file.** A trace file is owned by exactly one
   process. Directory specs embed the run_id (which embeds the pid) in
   the file name, so concurrent processes never collide; an *explicit*
   ``PATH.jsonl`` spec that another live process already owns reroutes
   this process's writes into the sibling shard directory
   ``PATH.shards/<run_id>.jsonl`` instead of truncating or interleaving.
   Enabling with an explicit file also exports ``DSDDMM_TRACE`` =
   ``PATH.shards`` to child processes, so workers a traced run spawns
   (serve smoke, ``tests/_mp_worker.py``) write per-process shards by
   default; ``bench trace-merge PATH.jsonl`` stitches the stem file and
   its shards back into one trace.

Besides the JSONL file there is one optional in-memory sink: the
**span ring** (:func:`arm_ring`), a bounded deque of the most recent
emitted records. The flight recorder (``obs/flightrec.py``) dumps it
when the watchdog fires, and the admin server's ``/debug/requests``
endpoint reconstructs recent request timelines from it. Arming the
ring with no file tracer active installs a *memory-only* tracer
(``path is None``) so spans and events still flow — ``enabled()``
becomes true but ``trace_path()`` stays None, and nothing touches the
filesystem.

Record schema (one JSON object per line, ``schema`` = SCHEMA_VERSION):

* ``{"type": "begin", "schema": 1, "run_id": .., "t0_epoch": ..,
  "pid": ..}`` — first line of every trace; ``t0_epoch`` is the
  wall-clock time of the monotonic origin (the shard-alignment anchor).
* ``{"type": "span", "name": .., "id": .., "parent": .., "tid": ..,
  "t0": .., "t1": .., "dur_s": .., "attrs": {..}}`` — emitted when
  the span *closes* (children therefore appear before their parent;
  readers reconstruct nesting from ``parent``).
* ``{"type": "event", "name": .., "id": .., "parent": .., "tid": ..,
  "t": .., "attrs": {..}}`` — instantaneous (fault fired, retry,
  guard repair, checkpoint, cache hit, log mirror).

``tools/tracereport.py`` is the schema's reader and validator.
"""

from __future__ import annotations

import collections
import errno
import json
import os
import pathlib
import threading
import time
from typing import Optional

from distributed_sddmm_tpu.obs import clock

#: Trace record schema generation; readers reject records they cannot
#: interpret. Bump on any incompatible change.
SCHEMA_VERSION = 1

_REPO = pathlib.Path(__file__).resolve().parents[2]
DEFAULT_TRACE_DIR = _REPO / "artifacts" / "traces"

# Module-level fast path: `_active is None` means every hook is a no-op.
_active: Optional["Tracer"] = None
_env_checked = False
_registry_lock = threading.Lock()
#: (previous DSDDMM_TRACE value, exported?) — enable() exports the shard
#: directory to children; disable() restores the inherited value.
_env_export: tuple[Optional[str], bool] = (None, False)
#: The directory child processes of this traced run shard into.
_shard_dir: Optional[str] = None
#: Optional bounded in-memory sink of emitted records (flight recorder
#: ring + admin /debug/requests source); None = disarmed.
_ring: Optional["SpanRing"] = None


class SpanRing:
    """Bounded ring of the most recent emitted trace records.

    Thread-safe; holds the record dicts exactly as emitted (spans close
    before they land here, so the ring is the last ``capacity`` *completed*
    spans and events — an in-flight span is not visible until it exits).
    """

    def __init__(self, capacity: int = 512):
        self.capacity = int(capacity)
        self._lock = threading.Lock()
        self._buf: collections.deque = collections.deque(maxlen=self.capacity)
        #: Total records ever appended (rotation-aware: ``appended -
        #: len(records())`` is how many the ring has already forgotten).
        self.appended = 0

    def append(self, rec: dict) -> None:
        with self._lock:
            self._buf.append(rec)
            self.appended += 1

    def records(self) -> list[dict]:
        with self._lock:
            return list(self._buf)

    def clear(self) -> None:
        with self._lock:
            self._buf.clear()


def _make_run_id() -> str:
    return (
        time.strftime("%Y%m%d-%H%M%S")
        + f"-{os.getpid()}-{int.from_bytes(os.urandom(2), 'big'):04x}"
    )


class _NoopSpan:
    """Shared do-nothing span: the disabled-tracer return value."""

    __slots__ = ()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False

    def set(self, **attrs) -> None:
        pass


NOOP_SPAN = _NoopSpan()


class Span:
    """A live span; emitted as one JSONL record when it closes."""

    __slots__ = ("tracer", "name", "attrs", "id", "parent", "tid", "_t0")

    def __init__(self, tracer: "Tracer", name: str, attrs: dict):
        self.tracer = tracer
        self.name = name
        self.attrs = attrs

    def set(self, **attrs) -> None:
        """Attach attributes mid-span (e.g. kernel vs overhead splits
        known only after the wrapped call returns)."""
        self.attrs.update(attrs)

    def __enter__(self) -> "Span":
        tr = self.tracer
        self.id = tr.next_id()
        self.tid = threading.get_ident()
        stack = tr.stack()
        self.parent = stack[-1] if stack else None
        stack.append(self.id)
        self._t0 = clock.now()
        return self

    def __exit__(self, *exc) -> bool:
        t1 = clock.now()
        tr = self.tracer
        stack = tr.stack()
        if stack and stack[-1] == self.id:
            stack.pop()
        if exc and exc[0] is not None:
            self.attrs.setdefault("error", exc[0].__name__)
        tr.emit({
            "type": "span",
            "name": self.name,
            "id": self.id,
            "parent": self.parent,
            "tid": self.tid,
            "t0": round(self._t0 - tr.t0, 9),
            "t1": round(t1 - tr.t0, 9),
            "dur_s": round(t1 - self._t0, 9),
            "attrs": self.attrs,
        })
        return False


class Tracer:
    """JSONL-emitting tracer bound to one output file.

    ``path=None`` is the memory-only mode :func:`arm_ring` installs when
    no file tracer is active: spans and events flow (into the ring), but
    nothing touches the filesystem and ``trace_path()`` stays None.
    """

    def __init__(self, path: Optional[pathlib.Path], run_id: str):
        self.path = path
        self.run_id = run_id
        self.t0 = clock.now()
        self._lock = threading.Lock()
        self._ids = 0
        self._local = threading.local()
        if path is None:
            self._fh = None
        else:
            path.parent.mkdir(parents=True, exist_ok=True)
            # Truncate: one trace per file (re-running with the same
            # explicit --trace PATH.jsonl must not merge runs — the
            # reader would double-count). Default/directory specs embed
            # the run_id in the file name, and an explicit file another
            # LIVE process owns was already rerouted into the shard
            # directory by _resolve_path, so two running processes never
            # share a file.
            # non-atomic-ok: streaming JSONL — the tracer appends for
            # the life of the run; readers tolerate a torn tail line.
            self._fh = open(path, "w", buffering=1)  # line-buffered
        # t0_epoch is the wall-clock reading of the monotonic origin —
        # the shard's clock-calibration header trace-merge aligns on.
        self.emit({
            "type": "begin",
            "schema": SCHEMA_VERSION,
            "run_id": run_id,
            "t0_epoch": clock.epoch(),
            "pid": os.getpid(),
        })

    def next_id(self) -> int:
        with self._lock:
            self._ids += 1
            return self._ids

    def stack(self) -> list:
        st = getattr(self._local, "stack", None)
        if st is None:
            st = self._local.stack = []
        return st

    def emit(self, record: dict) -> None:
        ring = _ring
        if ring is not None:
            ring.append(record)
        if self._fh is None:
            return
        line = json.dumps(record, default=str)
        with self._lock:
            if self._fh.closed:
                return
            self._fh.write(line + "\n")

    def current_span_id(self) -> Optional[int]:
        st = self.stack()
        return st[-1] if st else None

    def close(self) -> None:
        if self._fh is None:
            return
        with self._lock:
            if not self._fh.closed:
                self._fh.flush()
                self._fh.close()


# --------------------------------------------------------------------- #
# Module-level API — what the rest of the framework calls.
# --------------------------------------------------------------------- #


#: DSDDMM_TRACE values meaning "on at the default location" (not a
#: path). Shared with dist/run.py's shard-dir resolution so the two
#: can never disagree about what counts as a path spec.
FLAG_VALUES = ("1", "on", "true", "yes")


def _env_activate() -> None:
    global _env_checked
    with _registry_lock:
        if _env_checked:
            return
        _env_checked = True
        spec = os.environ.get("DSDDMM_TRACE")
        if spec:
            _enable_locked(None if spec in FLAG_VALUES else spec)


def _owning_pid(path: pathlib.Path) -> Optional[int]:
    """The pid in an existing trace file's begin record, or None."""
    try:
        with open(path) as fh:
            rec = json.loads(fh.readline())
    except (OSError, ValueError):
        return None
    if isinstance(rec, dict) and rec.get("type") == "begin":
        pid = rec.get("pid")
        return pid if isinstance(pid, int) else None
    return None


def _pid_alive(pid: int) -> bool:
    try:
        os.kill(pid, 0)
    except OSError as e:
        return e.errno == errno.EPERM  # exists, not ours to signal
    return True


def shard_dir_for(path) -> pathlib.Path:
    """The shard directory belonging to an explicit ``PATH.jsonl`` trace
    stem: sibling ``PATH.shards/`` (worker processes of the run write
    their per-process shards there; ``bench trace-merge PATH.jsonl``
    stitches stem + shards)."""
    return pathlib.Path(path).with_suffix(".shards")


def _resolve_path(spec, run_id: str) -> pathlib.Path:
    if spec is None:
        return DEFAULT_TRACE_DIR / f"{run_id}.jsonl"
    p = pathlib.Path(spec)
    if p.suffix != ".jsonl":  # treat as a directory
        return p / f"{run_id}.jsonl"
    # Explicit file: if another LIVE process already owns it (a parent
    # that exported this spec to us, or a sibling launched with the same
    # flag), become a shard instead of truncating/interleaving its file.
    owner = _owning_pid(p)
    if owner is not None and owner != os.getpid() and _pid_alive(owner):
        return shard_dir_for(p) / f"{run_id}.jsonl"
    return p


def _export_child_spec(spec, resolved: pathlib.Path) -> None:
    """Point child processes at the shard directory for this trace.

    Directory/default specs already isolate per process (run_id in the
    file name) — children share the directory. An explicit ``.jsonl``
    file exports its sibling ``.shards`` directory, so workers a traced
    run spawns write shards instead of fighting over one file. The
    inherited ``DSDDMM_TRACE`` value is restored by :func:`disable`.
    """
    global _env_export, _shard_dir
    if spec is None:
        child = str(DEFAULT_TRACE_DIR)
    else:
        p = pathlib.Path(spec)
        child = str(shard_dir_for(p) if p.suffix == ".jsonl" else p)
    if resolved.parent != pathlib.Path(child) and resolved.suffix == ".jsonl" \
            and resolved.parent.name.endswith(".shards"):
        # We ourselves were rerouted into a shard dir: share it.
        child = str(resolved.parent)
    _env_export = (os.environ.get("DSDDMM_TRACE"), True)
    _shard_dir = child
    os.environ["DSDDMM_TRACE"] = child


def _enable_locked(spec=None, run_id: Optional[str] = None) -> "Tracer":
    global _active
    if _active is not None:
        return _active
    rid = run_id or _make_run_id()
    path = _resolve_path(spec, rid)
    _active = Tracer(path, rid)
    _export_child_spec(spec, path)
    return _active


def enable(path=None, run_id: Optional[str] = None) -> "Tracer":
    """Activate tracing (idempotent — an already-active tracer wins).

    ``path``: explicit ``.jsonl`` file, a directory, or None for
    ``artifacts/traces/<run_id>.jsonl``. Also writes the run manifest
    next to the trace (best-effort)."""
    global _env_checked
    with _registry_lock:
        _env_checked = True
        tr = _enable_locked(path, run_id)
    from distributed_sddmm_tpu.obs import manifest

    manifest.write_for_trace(tr)
    return tr


def disable() -> None:
    """Close and deactivate the tracer (tests; end-of-run flush).
    Restores the ``DSDDMM_TRACE`` value :func:`enable` exported for
    child processes, and disarms the span ring — ``disable()`` is the
    full reset the test fixtures rely on."""
    global _active, _env_checked, _env_export, _shard_dir, _ring
    with _registry_lock:
        if _active is not None:
            _active.close()
        _active = None
        _ring = None
        _env_checked = True
        prev, exported = _env_export
        if exported:
            if prev is None:
                os.environ.pop("DSDDMM_TRACE", None)
            else:
                os.environ["DSDDMM_TRACE"] = prev
        _env_export = (None, False)
        _shard_dir = None


def arm_ring(capacity: int = 512) -> SpanRing:
    """Attach (or return) the in-memory span ring.

    With a file tracer already active the ring simply taps its emit
    stream; with no tracer a **memory-only** tracer is installed so
    spans/events flow at all (``enabled()`` turns true, ``trace_path()``
    stays None). Arm AFTER enabling file tracing when you want both —
    ``enable()`` is idempotent and will not upgrade a memory tracer to
    a file one. Idempotent: an armed ring is returned as-is (capacity
    of the first arm wins)."""
    global _ring, _active
    if not _env_checked:
        _env_activate()  # a DSDDMM_TRACE file spec must win over memory
    with _registry_lock:
        if _ring is None:
            _ring = SpanRing(capacity)
        if _active is None:
            _active = Tracer(None, _make_run_id())
        return _ring


def disarm_ring() -> None:
    """Detach the span ring; a memory-only tracer installed by
    :func:`arm_ring` is deactivated too (a file tracer is untouched)."""
    global _ring, _active
    with _registry_lock:
        _ring = None
        if _active is not None and _active.path is None:
            _active = None


def ring() -> Optional[SpanRing]:
    """The armed span ring, or None."""
    return _ring


def shard_dir() -> Optional[str]:
    """The directory child processes of this traced run write shards
    into (the exported ``DSDDMM_TRACE``), or None when not tracing."""
    return _shard_dir if _active is not None else None


def tracer() -> Optional["Tracer"]:
    """The active tracer, activating from ``DSDDMM_TRACE`` on first query."""
    if not _env_checked:
        _env_activate()
    return _active


def enabled() -> bool:
    if not _env_checked:
        _env_activate()
    return _active is not None


def run_id() -> Optional[str]:
    tr = tracer()
    return tr.run_id if tr else None


def rel_time(t_perf: float) -> Optional[float]:
    """A ``clock.now()`` stamp as a trace-relative time (the unit span
    ``t0``/``t1`` and event ``t`` use), or None when not tracing. Lets
    emitters embed *precise* externally-captured stamps in event attrs —
    an event's own ``t`` is its emission time, which can lag the moment
    it describes by a thread-scheduling delay."""
    tr = tracer()
    return round(t_perf - tr.t0, 9) if tr else None


def trace_path() -> Optional[str]:
    tr = tracer()
    return str(tr.path) if tr is not None and tr.path is not None else None


# --------------------------------------------------------------------- #
# Fleet trace context — the cross-process propagation format.
# --------------------------------------------------------------------- #

#: HTTP header carrying fleet trace context on ``POST /submit``.
TRACE_HEADER = "X-DSDDMM-Trace"

#: Header format generation; decoders ignore versions they don't know.
TRACE_HEADER_VERSION = "v1"

#: Context fields, in wire order. ``req`` is the fleet-level request id
#: (always present, minted by the router even when tracing is off so
#: replica logs stay correlatable), ``shard`` the router's trace run_id,
#: ``span`` the router-side attempt span id the replica's records should
#: parent to, ``kind`` the attempt kind (primary/hedge/audit/arbitrate),
#: ``ord`` the failover ordinal of the attempt.
_CTX_FIELDS = ("req", "shard", "span", "kind", "ord")
_CTX_INT_FIELDS = ("span", "ord")


def encode_fleet_ctx(ctx: dict) -> str:
    """Serialize a fleet trace context to the ``X-DSDDMM-Trace`` wire
    value: ``v1;req=..;shard=..;span=..;kind=..;ord=..`` (fields with a
    None value are omitted; unknown keys are dropped)."""
    parts = [TRACE_HEADER_VERSION]
    for key in _CTX_FIELDS:
        val = ctx.get(key)
        if val is None:
            continue
        parts.append(f"{key}={val}")
    return ";".join(parts)


def decode_fleet_ctx(value) -> Optional[dict]:
    """Parse an ``X-DSDDMM-Trace`` header value back into a context
    dict, or None for a missing/garbage/unknown-version value. Integer
    fields (``span``, ``ord``) are coerced; a field that fails to parse
    is dropped rather than poisoning the rest (partial context is still
    useful for correlation)."""
    if not value or not isinstance(value, str):
        return None
    parts = value.strip().split(";")
    if not parts or parts[0] != TRACE_HEADER_VERSION:
        return None
    ctx: dict = {}
    for part in parts[1:]:
        key, sep, raw = part.partition("=")
        if not sep or key not in _CTX_FIELDS or not raw:
            continue
        if key in _CTX_INT_FIELDS:
            try:
                ctx[key] = int(raw)
            except ValueError:
                continue
        else:
            ctx[key] = raw
    return ctx if ctx.get("req") else None


def find_shard(directory, pid: int) -> Optional[str]:
    """The trace shard in ``directory`` whose begin record was written
    by ``pid``, or None. The fleet manager uses this to harvest a
    replica's shard at reap/quarantine time — the shard file name embeds
    the replica's run_id (which embeds its pid), but the begin record is
    the authoritative owner stamp."""
    d = pathlib.Path(directory)
    if not d.is_dir():
        return None
    for path in sorted(d.glob("*.jsonl")):
        if _owning_pid(path) == pid:
            return str(path)
    return None


def span(name: str, **attrs):
    """A context manager timing a nested region; no-op when disabled.

    Usage::

        with trace.span("fusedSpMM", alg="15d_fusion2", R=128) as sp:
            out = run()
            sp.set(kernel_s=...)   # attrs added before the span closes
    """
    tr = tracer()
    if tr is None:
        return NOOP_SPAN
    return Span(tr, name, attrs)


def event(name: str, **attrs) -> None:
    """Emit an instantaneous event under the current thread's span."""
    tr = tracer()
    if tr is None:
        return
    tr.emit({
        "type": "event",
        "name": name,
        "id": tr.next_id(),
        "parent": tr.current_span_id(),
        "tid": threading.get_ident(),
        "t": round(clock.now() - tr.t0, 9),
        "attrs": attrs,
    })
