"""In-run anomaly watchdog: drift, storms, and comm-model disagreement.

The store/regress half of the obs layer compares *finished* runs; this
module watches a run **while it executes**. Three anomaly families, each
chosen because it has bitten this repo's own rounds:

* **step_time_spike / step_time_drift** — an EWMA per op at the
  ``parallel/base.py::_timed`` choke point (and per ALS alternating
  step / GAT layer via the app hooks). A single dispatch far above the
  moving average is a spike (preempted chip, paging, a retry storm
  upstream); a moving average that creeps above its own early baseline
  is drift (the round-5 ALS dispatch-gap failure mode — each step a
  little slower, invisible until the run ends). Mid-run jit recompiles
  surface as spikes *by design*: on a dispatch-dominated backend a
  retrace storm is precisely the anomaly worth catching early.
* **repair_storm** — guard repairs + exec retries per dispatch window.
  Individually each repair is a healed transient; a *rate* of them is a
  persistently sick backend that retry is merely hiding.
* **comm_mismatch** — the strategy's counted per-device comm words
  against ``tools/costmodel.pair_words`` for its declared model (the
  1.5D/2.5D volumes of Bharadwaj et al., arXiv:2203.07673). Layout math
  and analytic model are maintained independently; disagreement beyond
  tolerance means one of them drifted, and the run's accounting — the
  paper's whole argument — can no longer be trusted.
* **xla_flop_mismatch** — the same independence argument one level
  down: analytic per-op FLOPs against XLA's own ``cost_analysis`` of
  the compiled executables (captured by the program store). Counted
  exceeding compiled means the analytic accounting drifted; compiled
  exceeding counted by the waste factor means padding/layout exploded.

Every anomaly is recorded on the watchdog (for the end-of-run
``anomalies`` summary the bench record carries), emitted as an
``anomaly`` trace event when tracing, and counted in the global
metrics. Modes (``DSDDMM_WATCHDOG`` or :func:`enable`):

* ``warn`` (also ``1``/``on``) — observe and report only; numerical
  results are untouched by construction (the watchdog only ever reads
  timings and counters).
* ``strict`` — additionally raise :class:`WatchdogAlarm` (a
  :class:`~distributed_sddmm_tpu.resilience.guards.NumericalFault`)
  after recording, which hands the anomaly to the resilience ladder:
  ALS answers with a damped restart and ultimately the serial
  fallback, exactly as it would a tripped output guard.

Disabled (the default) every hook is one module-level ``None`` check —
the same budget discipline as the tracer.
"""

from __future__ import annotations

import os
import statistics
import threading
from typing import Optional

from distributed_sddmm_tpu.obs import log as obs_log
from distributed_sddmm_tpu.obs import metrics as obs_metrics
from distributed_sddmm_tpu.obs import trace as obs_trace
from distributed_sddmm_tpu.resilience.guards import NumericalFault


class WatchdogAlarm(NumericalFault):
    """An in-run anomaly escalated under ``DSDDMM_WATCHDOG=strict`` —
    typed as a NumericalFault so the existing degradation ladder
    (retry / damped restart / serial fallback) owns the response."""


class Watchdog:
    """Anomaly state for one process-wide monitoring session."""

    def __init__(
        self,
        mode: str = "warn",
        spike_factor: float = 3.0,
        min_abs_s: float = 5e-3,
        drift_factor: float = 2.0,
        min_samples: int = 5,
        ewma_alpha: float = 0.2,
        storm_window: int = 20,
        storm_rate: float = 0.25,
        comm_rtol: float = 0.25,
        queue_frac: float = 0.75,
        queue_patience: int = 5,
        xla_rtol: float = 0.25,
        xla_waste_factor: float = 32.0,
    ):
        if mode not in ("warn", "strict"):
            raise ValueError(f"watchdog mode {mode!r}; expected warn|strict")
        self.mode = mode
        self.spike_factor = spike_factor
        self.min_abs_s = min_abs_s
        self.drift_factor = drift_factor
        self.min_samples = min_samples
        self.ewma_alpha = ewma_alpha
        self.storm_window = storm_window
        self.storm_rate = storm_rate
        self.comm_rtol = comm_rtol
        self.queue_frac = queue_frac
        self.queue_patience = queue_patience
        self.xla_rtol = xla_rtol
        self.xla_waste_factor = xla_waste_factor
        self._queue_streak = 0
        self._queue_flagged = False

        #: Every anomaly, in firing order (the bench harness slices this
        #: by cursor, the same pattern as FaultPlan.events).
        self.events: list[dict] = []
        self._lock = threading.Lock()
        # {op: {"count", "ewma", "warmup" (first-window samples),
        #  "baseline" (their MEDIAN — robust: the first dispatch of a
        #  jitted program is a compile, and a mean would fold that
        #  outlier into "normal", blinding the detector for the rest of
        #  a short run)}}
        self._ops: dict[str, dict] = {}
        self._drift_flagged: set[str] = set()
        self._comm_checked: dict[tuple, bool] = {}
        self._dispatches = 0
        self._storm_mark: dict[str, float] = {}

    # ------------------------------------------------------------------ #
    # Anomaly plumbing
    # ------------------------------------------------------------------ #

    def _anomaly(self, kind: str, op: str, **attrs) -> str:
        """Record + emit one anomaly; returns its description. Never
        raises — strict-mode escalation happens in :meth:`_escalate`
        AFTER every co-detected anomaly of the observation has been
        emitted (a raise mid-emission would permanently swallow a drift
        or storm detected on the same dispatch as a spike).

        An armed flight recorder (``obs/flightrec.py``) dumps its span
        ring + snapshots FIRST — before the anomaly trace event — so
        the dump's ring ends at the spans that *preceded* the anomaly,
        and ``snapshot_path`` can ride both the trace event and the
        bench record's ``anomalies`` summary."""
        from distributed_sddmm_tpu.obs import flightrec

        fr = flightrec.active()
        if fr is not None:
            snapshot_path = fr.dump(kind, op, attrs)
            if snapshot_path:
                attrs = {**attrs, "snapshot_path": snapshot_path}
        ev = {"kind": kind, "op": op, **attrs}
        with self._lock:
            self.events.append(ev)
        obs_metrics.GLOBAL.add("watchdog_anomalies")
        obs_trace.event("anomaly", kind=kind, op=op, **attrs)
        obs_log.warn("watchdog", f"{kind} on {op}",
                     **{k: _fmt(v) for k, v in attrs.items()})
        return f"{kind} on {op} ({attrs})"

    def _escalate(self, descriptions: list[str]) -> None:
        if descriptions and self.mode == "strict":
            raise WatchdogAlarm("watchdog: " + "; ".join(descriptions))

    # ------------------------------------------------------------------ #
    # Step-time EWMA (dispatch choke point + app loops)
    # ------------------------------------------------------------------ #

    def observe(self, op: str, dur_s: float) -> None:
        """Feed one timed region (a ``_timed`` dispatch, an ALS
        alternating step, a GAT layer). Spike/drift checks run against
        the op's own history — cross-op scales never mix."""
        spike = drift = None
        with self._lock:
            # Storm accounting first, unconditionally: it is op-
            # independent, and skipping it on warmup dispatches would
            # let a window boundary slide — the next boundary would
            # then divide a multi-window repair delta by one window.
            self._dispatches += 1
            storm = self._storm_check_locked()
            st = self._ops.get(op)
            if st is None:
                st = self._ops[op] = {
                    "count": 0, "ewma": 0.0, "warmup": [], "baseline": 0.0,
                }
            if st["count"] < self.min_samples:
                # Warmup: no spike/drift verdicts; the first window's
                # MEDIAN defines normal (robust to the compile-on-
                # first-dispatch outlier).
                st["count"] += 1
                st["warmup"].append(dur_s)
                if st["count"] == self.min_samples:
                    st["baseline"] = st["ewma"] = statistics.median(
                        st["warmup"]
                    )
            else:
                ewma = st["ewma"]
                if (
                    dur_s > self.spike_factor * ewma
                    and dur_s - ewma > self.min_abs_s
                ):
                    spike = (dur_s, ewma)
                st["ewma"] = ewma = (
                    (1 - self.ewma_alpha) * ewma + self.ewma_alpha * dur_s
                )
                st["count"] += 1
                baseline = st["baseline"]
                if (
                    op not in self._drift_flagged
                    and st["count"] > 2 * self.min_samples
                    and ewma > self.drift_factor * baseline
                    and ewma - baseline > self.min_abs_s
                ):
                    self._drift_flagged.add(op)
                    drift = (ewma, baseline)
        # Anomaly emission (and strict-mode raising) happens outside the
        # state lock — trace/log hooks must never run under it.
        fired = []
        if spike:
            fired.append(self._anomaly(
                "step_time_spike", op,
                dur_s=round(spike[0], 6), ewma_s=round(spike[1], 6),
                factor=round(spike[0] / max(spike[1], 1e-12), 2),
            ))
        if drift:
            fired.append(self._anomaly(
                "step_time_drift", op,
                ewma_s=round(drift[0], 6), baseline_s=round(drift[1], 6),
                factor=round(drift[0] / max(drift[1], 1e-12), 2),
            ))
        if storm:
            fired.append(self._anomaly("repair_storm", "*", **storm))
        self._escalate(fired)

    def _storm_check_locked(self) -> dict | None:
        """Every ``storm_window`` dispatches, compare the global repair/
        retry counters against the previous mark; a rate above
        ``storm_rate`` per dispatch is a storm."""
        if self._dispatches % self.storm_window:
            return None
        snap = obs_metrics.GLOBAL.snapshot()
        repairs = snap.get("guard_repairs", 0.0) + snap.get("exec_retries", 0.0)
        prev = self._storm_mark.get("repairs", None)
        self._storm_mark["repairs"] = repairs
        if prev is None:
            return None
        rate = (repairs - prev) / self.storm_window
        if rate > self.storm_rate:
            return {
                "repairs_in_window": repairs - prev,
                "window": self.storm_window,
                "rate": round(rate, 3),
            }
        return None

    # ------------------------------------------------------------------ #
    # Queue-depth runaway (the serving layer's anomaly)
    # ------------------------------------------------------------------ #

    def observe_queue(self, depth: int, capacity: int) -> None:
        """Feed one serving-queue depth sample (``serve/engine.py`` calls
        this per admission). A depth that sits at or above
        ``queue_frac * capacity`` for ``queue_patience`` consecutive
        samples is a **queue_runaway**: arrivals persistently outpace
        drain, so latency is already unbounded-trending and shedding is
        imminent — the open-loop failure mode a single spike check
        misses. One anomaly per runaway episode: the streak re-arms only
        after depth falls back below the line."""
        fired = None
        with self._lock:
            if capacity <= 0:
                return
            if depth >= self.queue_frac * capacity:
                self._queue_streak += 1
                if (
                    not self._queue_flagged
                    and self._queue_streak >= self.queue_patience
                ):
                    self._queue_flagged = True
                    fired = (depth, self._queue_streak)
            else:
                self._queue_streak = 0
                self._queue_flagged = False
        if fired:
            self._escalate([self._anomaly(
                "queue_runaway", "serve",
                depth=fired[0], capacity=capacity,
                frac=round(fired[0] / capacity, 3), streak=fired[1],
            )])

    # ------------------------------------------------------------------ #
    # Comm-volume vs cost model
    # ------------------------------------------------------------------ #

    #: Ops the analytic model predicts exactly: whole fused SDDMM+SpMM
    #: pairs (incl. the B-mode cost aliases). Single ops (sddmmA, ...)
    #: still pay full replication for half the flops, and GAT layers run
    #: at per-layer R — the model column would be wrong, not the layout
    #: math, so those are excluded here exactly as in tools/tracereport.
    _COMM_CHECK_OPS = ("fusedSpMM", "fusedSpMMB", "cgStep", "cgStepB")

    def check_comm(
        self, strategy, op: str, counted_words: float, pairs: float = 1.0,
    ) -> None:
        """Counted per-device words for one call of ``op`` against the
        analytic prediction for the strategy's declared cost model.
        Static per (strategy geometry, op, R, pairs) — checked once per
        key, so the per-dispatch cost after the first call is one dict
        hit."""
        if op not in self._COMM_CHECK_OPS:
            return
        model_name = getattr(strategy, "cost_model_name", None)
        frac = obs_metrics.OP_PAIRS.get(op)
        if model_name is None or frac is None or strategy.S_tiles is None:
            return
        # The full geometry belongs in the memo key: model_words depends
        # on (M_pad, N_pad, p, c), and a c-sweep instantiates the same
        # algorithm_name at several geometries in one process.
        key = (
            strategy.algorithm_name, model_name, op,
            strategy.M_pad, strategy.N_pad, strategy.p, strategy.c,
            strategy.R, pairs,
        )
        with self._lock:
            if key in self._comm_checked:
                return
            self._comm_checked[key] = True
        from distributed_sddmm_tpu.tools import costmodel

        try:
            model_words = costmodel.pair_words(
                model_name, strategy.M_pad, strategy.N_pad, strategy.R,
                strategy.S_tiles.nnz, strategy.p, strategy.c,
            ) * frac * pairs
        except ValueError:
            return
        if model_words <= 0:
            if counted_words > 0:
                self._escalate([self._anomaly(
                    "comm_mismatch", op, counted_words=counted_words,
                    model_words=0.0, ratio=None,
                )])
            return
        ratio = counted_words / model_words
        if abs(ratio - 1.0) > self.comm_rtol:
            self._escalate([self._anomaly(
                "comm_mismatch", op,
                counted_words=counted_words,
                model_words=model_words,
                ratio=round(ratio, 4),
                model=model_name,
            )])

    def observe_dispatch(
        self, strategy, op: str, dur_s: float,
        counted_words: float = 0.0, pairs: float = 1.0,
        cost_op: str | None = None,
    ) -> None:
        """The ``_timed`` hook: step-time EWMA plus the one-time comm
        check, in one call."""
        self.check_comm(strategy, cost_op or op, counted_words, pairs)
        self.observe(op, dur_s)

    # ------------------------------------------------------------------ #
    # Analytic-vs-XLA FLOP agreement (the program store's cost capture)
    # ------------------------------------------------------------------ #

    def check_xla_costs(self, metrics: dict, xla_ops: dict) -> None:
        """Counted analytic FLOPs/call per op against XLA's own
        ``cost_analysis`` numbers for the op's compiled programs
        (``programs.xla_cost_summary`` builds ``xla_ops``).

        Two one-sided bands, because the two counts measure different
        things: XLA charges the COMPILED program (padding, masking and
        fusion included) while the analytic count is useful work only,
        so ``xla >= counted`` is normal. ``counted > xla * (1 +
        xla_rtol)`` means the executable does *less* arithmetic than
        the useful work we claim — the analytic accounting drifted;
        ``xla > counted * xla_waste_factor`` means padding/layout blew
        the compiled FLOPs up pathologically. Anomalies are recorded
        (``xla_flop_mismatch``) but never escalated: this runs at
        record-assembly time, where the resilience ladder has nothing
        left to degrade to.
        """
        for op, cost in (xla_ops or {}).items():
            m = metrics.get(op) or {}
            calls, flops = m.get("calls") or 0, m.get("flops") or 0.0
            xla = cost.get("flops_per_call") or 0.0
            if not (calls and flops and xla):
                continue
            counted = flops / calls
            ratio = counted / xla
            if counted > xla * (1.0 + self.xla_rtol):
                self._anomaly(
                    "xla_flop_mismatch", op, direction="counted_exceeds_xla",
                    counted_flops=counted, xla_flops=xla,
                    ratio=round(ratio, 4),
                )
            elif xla > counted * self.xla_waste_factor:
                self._anomaly(
                    "xla_flop_mismatch", op, direction="xla_waste",
                    counted_flops=counted, xla_flops=xla,
                    ratio=round(ratio, 4),
                )

    # ------------------------------------------------------------------ #
    # End-of-run summary
    # ------------------------------------------------------------------ #

    def summary(self, since: int = 0) -> dict:
        """Aggregate anomalies recorded after cursor ``since`` (the bench
        harness snapshots ``len(events)`` per record): grouped by
        (kind, op) with a count and the first occurrence's detail.
        ``snapshots`` lists every flight-record path the window's
        anomalies produced, in firing order (``report-html`` links
        them; the per-group ``first`` carries its own
        ``snapshot_path`` too)."""
        with self._lock:
            events = list(self.events[since:])
        grouped: dict[tuple, dict] = {}
        snapshots: list[str] = []
        for ev in events:
            k = (ev["kind"], ev["op"])
            g = grouped.get(k)
            if g is None:
                g = grouped[k] = {
                    "kind": ev["kind"], "op": ev["op"], "count": 0,
                    "first": {a: v for a, v in ev.items()
                              if a not in ("kind", "op")},
                }
            g["count"] += 1
            if ev.get("snapshot_path"):
                snapshots.append(ev["snapshot_path"])
        out = {
            "mode": self.mode,
            "total": len(events),
            "anomalies": [grouped[k] for k in sorted(grouped)],
        }
        if snapshots:
            out["snapshots"] = snapshots
        return out


def _fmt(v):
    return round(v, 6) if isinstance(v, float) else v


# --------------------------------------------------------------------- #
# Module-level activation (env + CLI), tracer-style
# --------------------------------------------------------------------- #

_active: Optional[Watchdog] = None
_env_checked = False
_registry_lock = threading.Lock()


def enable(mode: str = "warn", **knobs) -> Watchdog:
    """Activate a process-wide watchdog (replaces any previous one —
    monitoring state is per-session, not cumulative across enables)."""
    global _active, _env_checked
    with _registry_lock:
        _env_checked = True
        _active = Watchdog(mode=mode, **knobs)
        return _active


def disable() -> None:
    global _active, _env_checked
    with _registry_lock:
        _active = None
        _env_checked = True


def active() -> Optional[Watchdog]:
    """The active watchdog, activating from ``DSDDMM_WATCHDOG`` on first
    query (``warn``/``1``/``on`` → warn, ``strict`` → strict, other /
    unset → disabled)."""
    global _active, _env_checked
    if _env_checked:
        return _active
    with _registry_lock:
        if not _env_checked:
            _env_checked = True
            spec = os.environ.get("DSDDMM_WATCHDOG", "").lower()
            if spec in ("warn", "1", "on", "true", "yes"):
                _active = Watchdog(mode="warn")
            elif spec == "strict":
                _active = Watchdog(mode="strict")
    return _active
