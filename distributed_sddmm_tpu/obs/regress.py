"""Cross-run regression analysis: per-phase deltas, verdicts, gate.

Consumes run documents from :mod:`obs.store` and answers the question
every PR needs answered mechanically: *did this change make a phase
slower, and if so, is it compute, comm, or overhead?*

Design points:

* **Per-phase, not per-run.** A run's headline GFLOP/s can hide a 2x
  cgStep regression behind a faster warmup; the unit of comparison is
  the phase table (trace aggregate when the run was traced, per-op
  ``metrics`` otherwise — both normalize to the same row shape).
* **Noise-aware verdicts.** Single-shot diffs flag noise as regression
  and absorb regressions into noise. The comparison metric is seconds
  per call; against a rolling baseline of the last K matching runs the
  band is ``median * (1 ± threshold)`` widened by a robust spread
  estimate (1.4826·MAD ≈ σ), so a machine with jittery timings widens
  its own bands instead of tripping the gate.
* **Roofline context.** Each row carries achieved GFLOP/s
  (counted FLOPs / kernel seconds) and counted-vs-modeled comm words
  (``tools/costmodel.pair_words`` through the trace aggregate), so a
  regression is *attributed*: overhead growth (retries/faults), comm
  drift, or compute slowdown — the first-order split the 1.5D/2.5D
  cost-model argument needs.
* **Machine-readable gate.** :func:`gate` returns a stable exit code —
  0 pass, 2 regression, 3 insufficient data — and a JSON-able report;
  CI fails on nonzero, exactly like a test.

Comparability is enforced by the caller handing in documents with the
same store index key (problem fingerprint + code hash + backend);
:func:`compare` itself only warns when keys differ — cross-key diffs
are legitimate for "what did this code change cost" questions.
"""

from __future__ import annotations

import statistics

#: Gate exit codes (stable contract for CI).
GATE_PASS = 0
GATE_REGRESSION = 2
GATE_NO_DATA = 3

#: Phases that exist for bookkeeping, not performance (the bench span
#: wraps the whole run; comparing it double-counts its children).
_SKIP_PHASES = ("bench",)

#: Zero-tolerance axes: any nonzero ``t_call`` regresses, no noise
#: band, no baseline required. ``fleet:audit_mismatch`` counts
#: byzantine replies; ``fleet:trace_coverage`` carries the UNCOVERED
#: fraction of delivered requests whose trace chain failed to
#: reconstruct (1 - coverage).
_HARD_AXES = ("fleet:audit_mismatch", "fleet:trace_coverage")


def _optional_axis(name: str) -> bool:
    """Axes that only exist when optional telemetry ran (SLO burn rate
    needs an SLO spec; XLA cost needs the program store; time-to-adapt
    needs the background tuner to have promoted). Their absence in the
    judged run is "not measured", never a gate failure. The
    ``serve:burn_rate`` prefix covers the per-tenant sub-axes
    (``serve:burn_rate:<tenant>``) — a run without that tenant declared
    simply did not measure it; ``fleet:`` axes exist only for ``bench
    fleet`` records."""
    return (
        name.startswith("xla:")
        or name.startswith("tuner:")
        or name.startswith("comm:")
        or name.startswith("fleet:")
        or name.startswith("serve:burn_rate")
        or name.startswith("dynstruct:")
    )


def phase_stats(doc: dict) -> dict[str, dict]:
    """Normalize one run document to ``{phase: row}``.

    The row NAMESPACE is the bench record's per-op ``metrics`` — every
    run (traced or not, first-of-sweep or not) carries it, so two docs
    always compare over the same phase set; a verdict of "missing" then
    means work actually vanished, never that one doc happened to have a
    trace aggregate attached and the other did not. The trace aggregate
    (``doc["phases"]``), when present, only ENRICHES matching ops with
    the cost-model column (app-level spans like ``als:step`` stay out
    of the comparison). Docs with no record metrics at all (synthetic /
    trace-only) fall back to the trace aggregate wholesale. Row shape::

        {calls, total_s, kernel_s, overhead_s, retries,
         comm_words, flops, t_call, gflops, model_words?, model_ratio?}
    """
    trace_phases = doc.get("phases") or {}
    metrics = (doc.get("record") or {}).get("metrics") or {}
    if metrics:
        phases = {}
        for op, m in metrics.items():
            row = {
                "calls": m.get("calls", 0),
                "total_s": m.get("kernel_s", 0.0) + m.get("overhead_s", 0.0),
                "kernel_s": m.get("kernel_s", 0.0),
                "overhead_s": m.get("overhead_s", 0.0),
                "retries": m.get("retries", 0),
                "comm_words": m.get("comm_words", 0.0),
                "comm_bytes": m.get("comm_bytes"),
                "flops": m.get("flops", 0.0),
            }
            tp = trace_phases.get(op)
            if tp and tp.get("model_words") is not None:
                row["model_words"] = tp["model_words"]
            phases[op] = row
    else:
        phases = trace_phases
    out = {}
    for name, ph in phases.items():
        if name in _SKIP_PHASES:
            continue
        calls = ph.get("calls", 0)
        if not calls:
            continue
        kernel_s = ph.get("kernel_s", 0.0)
        row = {
            "calls": int(calls),
            "total_s": ph.get("total_s", kernel_s + ph.get("overhead_s", 0.0)),
            "kernel_s": kernel_s,
            "overhead_s": ph.get("overhead_s", 0.0),
            "retries": int(ph.get("retries", 0)),
            "comm_words": ph.get("comm_words", 0.0),
            "comm_bytes": ph.get("comm_bytes"),
            "flops": ph.get("flops", 0.0),
        }
        row["t_call"] = row["total_s"] / calls
        row["gflops"] = (
            row["flops"] / kernel_s / 1e9 if kernel_s > 0 else None
        )
        if ph.get("model_words") is not None:
            row["model_words"] = ph["model_words"]
            row["model_ratio"] = (
                ph.get("model_ratio")
                if ph.get("model_ratio") is not None
                else (row["comm_words"] / ph["model_words"]
                      if ph["model_words"] else None)
            )
        out[name] = row
    out.update(_serving_rows(doc))
    out.update(_xla_rows(doc))
    out.update(_tuner_rows(doc))
    out.update(_comm_bytes_rows(doc))
    out.update(_fleet_rows(doc))
    out.update(_dynstruct_rows(doc))
    return out


def _pseudo_row(calls: int, value: float) -> dict:
    """A phase row carrying one scalar-per-request quantity in its
    ``t_call`` slot (seconds for latency axes, a plain rate for
    shed_rate) — the band/verdict machinery then applies unchanged."""
    return {
        "calls": int(calls), "total_s": value * calls,
        "kernel_s": value * calls, "overhead_s": 0.0, "retries": 0,
        "comm_words": 0.0, "comm_bytes": None, "flops": 0.0,
        "t_call": value, "gflops": None,
    }


def _serving_rows(doc: dict) -> dict[str, dict]:
    """The serving verdict axes (``bench serve`` records): tail latency
    percentiles as pseudo-phases (``t_call`` = the percentile in
    seconds), the shed rate, and — since PR 7 — the SLO error-budget
    burn rate. Offline records have none of these fields and contribute
    no rows, so serving and kernel docs never produce spurious
    "missing" verdicts against each other only when the config axes
    differ — which the store's ``app=serve-*`` axis already
    guarantees."""
    rec = doc.get("record") or {}
    lat = rec.get("latency_ms") or {}
    requests = rec.get("requests") or 0
    if not requests:
        return {}
    rows = {}
    for pct in (50, 99):
        v = lat.get(f"p{pct}")
        if v is not None:
            rows[f"serve:latency_p{pct}"] = _pseudo_row(requests, v / 1e3)
    if rec.get("shed_rate") is not None and lat:
        rows["serve:shed_rate"] = _pseudo_row(
            requests, float(rec["shed_rate"])
        )
    if rec.get("burn_rate") is not None:
        # Burn rate regresses like a latency: higher = burning budget
        # faster. Pre-PR-7 docs lack the field and simply lack the axis
        # (an OPTIONAL axis — see compare()'s not-measured verdict).
        rows["serve:burn_rate"] = _pseudo_row(
            requests, float(rec["burn_rate"])
        )
    for tname, cell in sorted((rec.get("tenant") or {}).items()):
        # Multi-tenant QoS (PR 16): each tenant with its own SLO gets
        # its own burn-rate axis, so one tenant's budget burning inside
        # a healthy aggregate still regresses. OPTIONAL like the
        # fleet-wide axis (startswith in _optional_axis).
        if cell.get("burn_rate") is not None:
            rows[f"serve:burn_rate:{tname}"] = _pseudo_row(
                max(int(cell.get("requests") or 0), 1),
                float(cell["burn_rate"]),
            )
    return rows


def _fleet_rows(doc: dict) -> dict[str, dict]:
    """The fleet verdict axis (``bench fleet`` records):
    ``fleet:availability`` as a pseudo-phase whose ``t_call`` is the
    UNAVAILABLE fraction ``max(1 - availability, 0.01)`` — the gate's
    higher-is-worse convention, floored so a perfect baseline does not
    make every subsequent run read as an infinite regression. OPTIONAL
    in compare(): only fleet records carry the field."""
    fleet = (doc.get("record") or {}).get("fleet") or {}
    avail = fleet.get("availability")
    if avail is None:
        return {}
    offered = max(int(fleet.get("offered") or 0), 1)
    rows = {
        "fleet:availability": _pseudo_row(
            offered, max(1.0 - float(avail), 0.01)
        ),
    }
    if fleet.get("audit_mismatches") is not None:
        # HARD axis (compare() special-cases it ahead of the band
        # machinery): replies are bit-identical by construction, so a
        # single cross-replica mismatch is a byzantine event — any
        # nonzero count regresses, no threshold, no baseline band.
        rows["fleet:audit_mismatch"] = _pseudo_row(
            offered, float(fleet["audit_mismatches"])
        )
    trace = fleet.get("trace") or {}
    if trace.get("coverage") is not None:
        # HARD axis (PR 19): every DELIVERED reply must reconstruct a
        # complete causal chain in the merged fleet trace (router
        # request span → winning attempt, duration agreeing with the
        # router's own recorded latency within 1 ms → replica
        # enqueue/batch/reply). ``t_call`` is the UNCOVERED fraction
        # ``1 - coverage`` so any nonzero value regresses — a dropped
        # span is lost observability, no threshold, no baseline band.
        rows["fleet:trace_coverage"] = _pseudo_row(
            max(int(trace.get("delivered") or 0), 1),
            max(1.0 - float(trace["coverage"]), 0.0),
        )
    hedges = int(fleet.get("hedges") or 0)
    if hedges > 0:
        # A RISING hedge-win rate means primaries increasingly miss the
        # p95-derived hedge deadline — tail degradation the latency
        # percentiles can hide when the hedge keeps rescuing it. Higher
        # = worse matches the gate convention directly.
        rows["fleet:hedge_win_rate"] = _pseudo_row(
            hedges,
            max(float(fleet.get("hedge_wins") or 0) / hedges, 0.01),
        )
    return rows


def _dynstruct_rows(doc: dict) -> dict[str, dict]:
    """The dynamic-structure verdict axis (PR 20):
    ``dynstruct:rebind`` as a pseudo-phase whose ``t_call`` is the
    retrace rate per structure change — ``retraces / changes``, floored
    at 0.01 so an all-fit baseline (the whole point of dynstruct) does
    not turn the first legitimate spill into an infinite regression.
    OPTIONAL in compare(): only records that actually churned structure
    (``record["dynstruct"]`` with nonzero changes) carry the axis;
    pre-PR-20 and static docs are "not measured", never a verdict."""
    dyn = (doc.get("record") or {}).get("dynstruct") or {}
    changes = int(dyn.get("dynstruct_rebinds") or 0) + int(
        dyn.get("dynstruct_bucket_spills") or 0
    )
    if not changes:
        return {}
    retraces = float(dyn.get("structure_retraces") or 0)
    return {
        "dynstruct:rebind": _pseudo_row(
            changes, max(retraces / changes, 0.01)
        ),
    }


def _xla_rows(doc: dict) -> dict[str, dict]:
    """Analytic-vs-XLA FLOP agreement axes: one pseudo-phase per op
    whose compiled programs reported a cost analysis, ``t_call`` =
    counted/XLA FLOP ratio. The gate judges the ratio's *stability*
    run over run — the two counts measure different things (useful vs
    compiled work) so the interesting signal is drift, not closeness
    to 1. Docs without ``xla_cost`` (store disabled, pre-PR-7) have no
    rows; the axes are OPTIONAL in compare()."""
    rec = doc.get("record") or {}
    metrics = rec.get("metrics") or {}
    ops = (rec.get("xla_cost") or {}).get("ops") or {}
    rows = {}
    for op, cost in ops.items():
        m = metrics.get(op) or {}
        calls, flops = m.get("calls") or 0, m.get("flops") or 0.0
        xla = cost.get("flops_per_call") or 0.0
        if calls and flops and xla:
            rows[f"xla:{op}_flops"] = _pseudo_row(
                calls, (flops / calls) / xla
            )
    return rows


def _comm_bytes_rows(doc: dict) -> dict[str, dict]:
    """Wire-volume axes (PR 15): one pseudo-phase per op that counted
    ``comm_bytes``, ``t_call`` = bytes per call. The gate judges the
    realized wire volume's stability — a bf16-wire run's ~2x drop
    reads as an improvement, a policy that silently stopped realizing
    its discount as a regression. OPTIONAL in compare(): pre-PR-15
    docs lack the field entirely and read as "not-measured", never a
    failure."""
    metrics = (doc.get("record") or {}).get("metrics") or {}
    rows = {}
    for op, m in metrics.items():
        calls, nbytes = m.get("calls") or 0, m.get("comm_bytes")
        if calls and nbytes:
            rows[f"comm:{op}_bytes"] = _pseudo_row(calls, nbytes / calls)
    return rows


def _tuner_rows(doc: dict) -> dict[str, dict]:
    """The closed-loop tuner's verdict axis: ``tuner:time_to_adapt``,
    the seconds from trigger detection to challenger promotion
    (``bench serve --tuner`` records carry ``time_to_adapt_s``). An
    adaptation that got slower run over run means the loop itself
    regressed — detection lag, measurement budget, or shadow
    throughput. OPTIONAL in compare(): records without a promotion
    (tuner off, or nothing to adapt to) lack the axis entirely."""
    rec = doc.get("record") or {}
    v = rec.get("time_to_adapt_s")
    if v is None:
        return {}
    promos = len(((rec.get("tuner") or {}).get("promotions")) or []) or 1
    return {"tuner:time_to_adapt": _pseudo_row(promos, float(v))}


def _band(t_calls: list[float], threshold: float) -> tuple[float, float, float]:
    """(median, lo, hi) noise band for a phase's baseline seconds/call.

    The relative threshold sets the floor; with >= 3 baseline runs a
    robust spread estimate (1.4826·MAD) widens it — a noisy machine's
    own history is the best available noise model."""
    med = statistics.median(t_calls)
    slack = threshold * med
    if len(t_calls) >= 3:
        mad = statistics.median(abs(t - med) for t in t_calls)
        slack = max(slack, 3.0 * 1.4826 * mad)
    return med, med - slack, med + slack


def _attribute(base: dict, new: dict) -> str:
    """First-order blame for a slower phase: overhead (retry/fault wall),
    comm (counted volume or model agreement moved), or compute (the
    kernel itself). Same altitude as the cost model — a hint for where
    to look, not a proof."""
    d_total = new["t_call"] - base["t_call"]
    d_overhead = (
        new["overhead_s"] / new["calls"] - base["overhead_s"] / base["calls"]
    )
    if d_total > 0 and d_overhead >= 0.5 * d_total:
        return "overhead"
    base_w = base["comm_words"] / base["calls"] if base["calls"] else 0.0
    new_w = new["comm_words"] / new["calls"] if new["calls"] else 0.0
    if base_w > 0 and abs(new_w - base_w) > 0.1 * base_w:
        return "comm"
    r_a, r_b = base.get("model_ratio"), new.get("model_ratio")
    if r_a is not None and r_b is not None and abs(r_b - r_a) > 0.1:
        return "comm"
    return "compute"


def compare(
    doc_b: dict,
    doc_a: dict | None = None,
    baseline_docs: list[dict] | None = None,
    threshold: float = 0.15,
) -> dict:
    """Per-phase comparison of run ``doc_b`` against run ``doc_a`` and/or
    a rolling baseline.

    ``baseline_docs`` (defaulting to ``[doc_a]``) supplies the
    seconds-per-call population the noise band is computed from;
    ``doc_a`` (defaulting to the newest baseline doc) supplies the
    reference row shown in the delta columns. Returns a JSON-able report
    with per-phase verdicts in {regression, improvement, ok, missing,
    new} and an overall verdict.
    """
    if baseline_docs is None:
        baseline_docs = [doc_a] if doc_a is not None else []
    if doc_a is None:
        if not baseline_docs:
            raise ValueError("compare needs doc_a and/or baseline_docs")
        doc_a = baseline_docs[-1]

    stats_a = phase_stats(doc_a)
    stats_b = phase_stats(doc_b)
    baseline_stats = [phase_stats(d) for d in baseline_docs] or [stats_a]

    phases: dict[str, dict] = {}
    regressions, improvements, missing, new_phases = [], [], [], []
    for name in sorted(set(stats_a) | set(stats_b)):
        a, b = stats_a.get(name), stats_b.get(name)
        if name in _HARD_AXES and b is not None:
            # Zero-tolerance hard axes: the band machinery would let a
            # "stable" nonzero value pass — but one byzantine reply (or
            # one delivered request whose trace chain failed to
            # reconstruct) is one too many, baseline or no baseline.
            bad = b["t_call"] > 0
            if bad:
                verdict = "regression"
                regressions.append(name)
            elif a is None:
                verdict = "new"
                new_phases.append(name)
            else:
                verdict = "ok"
            row = {"a": a, "b": b, "verdict": verdict, "hard_axis": True}
            if bad:
                row["attribution"] = "fleet"
            phases[name] = row
            continue
        if b is None:
            if _optional_axis(name):
                # Optional instrumentation axes (burn rate, XLA cost)
                # appear only when their telemetry ran; absent is
                # "not measured", not "work vanished" — pre-PR-7 docs
                # and store-disabled runs must not gate-fail on them.
                phases[name] = {"a": a, "b": None,
                                "verdict": "not-measured"}
                continue
            missing.append(name)
            phases[name] = {"a": a, "b": None, "verdict": "missing"}
            continue
        if a is None:
            new_phases.append(name)
            phases[name] = {"a": None, "b": b, "verdict": "new"}
            continue
        t_calls = [s[name]["t_call"] for s in baseline_stats if name in s]
        med, lo, hi = _band(t_calls or [a["t_call"]], threshold)
        if b["t_call"] > hi:
            verdict = "regression"
            regressions.append(name)
        elif b["t_call"] < lo:
            verdict = "improvement"
            improvements.append(name)
        else:
            verdict = "ok"
        row = {
            "a": a,
            "b": b,
            "baseline_median_t_call": med,
            "band": [lo, hi],
            "baseline_n": len(t_calls),
            "delta_pct": (
                (b["t_call"] - med) / med * 100.0 if med > 0 else None
            ),
            "verdict": verdict,
        }
        if verdict == "regression":
            if name.startswith("serve:"):
                # Serving axes carry no comm/overhead split to blame;
                # the axis itself names what went bad.
                row["attribution"] = "serving"
            elif name.startswith("fleet:"):
                # Availability moved: a replica-lifecycle or routing
                # problem, not a kernel one.
                row["attribution"] = "fleet"
            elif name.startswith("xla:"):
                # Agreement drifted: either the analytic count or the
                # compiled program changed — the axis IS the blame.
                row["attribution"] = "xla-cost"
            elif name.startswith("tuner:"):
                # The adaptation loop itself slowed down (detection →
                # promotion wall); no comm/compute split exists.
                row["attribution"] = "tuner"
            else:
                base_row = dict(a)
                base_row["t_call"] = med
                row["attribution"] = _attribute(base_row, b)
        phases[name] = row

    overall = "ok"
    if regressions or missing:
        overall = "regression"
    elif improvements:
        overall = "improvement"
    return {
        "run_a": doc_a.get("run_id"),
        "run_b": doc_b.get("run_id"),
        "key_a": doc_a.get("key"),
        "key_b": doc_b.get("key"),
        "comparable": doc_a.get("key") == doc_b.get("key"),
        "baseline_n": len(baseline_docs),
        "threshold": threshold,
        "phases": phases,
        "regressions": regressions,
        "improvements": improvements,
        "missing": missing,
        "new": new_phases,
        "verdict": overall,
    }


def gate(
    store,
    doc: dict,
    k: int = 5,
    threshold: float = 0.15,
    min_runs: int = 1,
    baseline_doc: dict | None = None,
) -> tuple[int, dict]:
    """CI gate: compare ``doc`` against an explicit baseline run or the
    rolling baseline of the last ``k`` store runs matching its index key
    (same problem fingerprint, code hash, backend).

    Returns ``(exit_code, report)``: 0 pass (improvements pass too),
    2 on any phase regression or vanished phase, 3 when fewer than
    ``min_runs`` comparable baseline runs exist (CI treats that as
    "cannot judge", distinct from "judged bad").
    """
    if baseline_doc is not None:
        baseline = [baseline_doc]
    else:
        baseline = store.matching(doc, limit=k)
    if len(baseline) < max(min_runs, 1):
        return GATE_NO_DATA, {
            "verdict": "no_data",
            "run_b": doc.get("run_id"),
            "key_b": doc.get("key"),
            "baseline_n": len(baseline),
            "min_runs": min_runs,
            "exit_code": GATE_NO_DATA,
        }
    report = compare(doc, baseline_docs=baseline, threshold=threshold)
    code = GATE_REGRESSION if report["verdict"] == "regression" else GATE_PASS
    report["exit_code"] = code
    return code, report


# --------------------------------------------------------------------- #
# Rendering (the human half of `bench compare` / `bench gate`)
# --------------------------------------------------------------------- #


def _num(v, spec: str, width: int) -> str:
    """Right-aligned number or a '-' placeholder; ``spec`` is a full
    format spec (sign/precision/type), padded to ``width``."""
    if v is None:
        return " " * (width - 1) + "-"
    return f"{format(v, spec):>{width}}"


def render_compare(report: dict) -> str:
    """Fixed-width per-phase delta table with comm/FLOP attribution."""
    lines = [
        f"compare {report.get('run_a')} -> {report.get('run_b')} "
        f"(baseline n={report.get('baseline_n')}, "
        f"threshold ±{report.get('threshold', 0) * 100:.0f}%)",
    ]
    if not report.get("comparable", True):
        lines.append(
            "NOTE: runs have different fingerprint keys (problem, code or "
            "backend changed) — deltas mix causes"
        )
    header = (
        f"{'phase':<16} {'calls':>5} {'t/call A':>10} {'t/call B':>10} "
        f"{'Δ%':>7} {'GF/s A':>8} {'GF/s B':>8} {'Mw/call':>9} "
        f"{'words/model':>11} {'verdict':>11} {'blame':>9}"
    )
    lines += [header, "-" * len(header)]
    for name, row in report["phases"].items():
        a, b = row.get("a"), row.get("b")
        if row["verdict"] in ("missing", "new", "not-measured"):
            src = a if b is None else b
            dash = " ".join(
                "-".rjust(w) for w in (10, 10, 7, 8, 8, 9, 11)
            )
            lines.append(
                f"{name:<16} {src['calls']:>5} {dash} "
                f"{row['verdict']:>11}"
            )
            continue
        med = row.get("baseline_median_t_call")
        mwords = b["comm_words"] / b["calls"] / 1e6 if b["calls"] else 0.0
        lines.append(
            f"{name:<16} {b['calls']:>5} "
            f"{_num(med, '.6f', 10)} {_num(b['t_call'], '.6f', 10)} "
            f"{_num(row.get('delta_pct'), '+.1f', 7)} "
            f"{_num(a.get('gflops'), '.3f', 8)} "
            f"{_num(b.get('gflops'), '.3f', 8)} "
            f"{mwords:>9.3f} "
            f"{_num(b.get('model_ratio'), '.3f', 11)} "
            f"{row['verdict']:>11} {row.get('attribution', ''):>9}"
        )
    lines.append(f"verdict: {report['verdict']}")
    if report.get("regressions"):
        lines.append("regressions: " + ", ".join(report["regressions"]))
    if report.get("missing"):
        lines.append("missing phases: " + ", ".join(report["missing"]))
    if report.get("improvements"):
        lines.append("improvements: " + ", ".join(report["improvements"]))
    return "\n".join(lines)


def render_history(rows: list[dict]) -> str:
    """``bench history`` table: one line per stored run, oldest first."""
    header = (
        f"{'run_id':<28} {'source':<9} {'alg':<20} {'app':<7} {'R':>5} "
        f"{'backend':<8} {'elapsed':>9} {'GFLOP/s':>9} {'anom':>4}  key"
    )
    lines = [header, "-" * len(header)]
    for r in rows:
        lines.append(
            f"{str(r.get('run_id'))[:28]:<28} {str(r.get('source', ''))[:9]:<9} "
            f"{str(r.get('algorithm', '') or '-')[:20]:<20} "
            f"{str(r.get('app', '') or '-')[:7]:<7} "
            f"{str(r.get('R', '') or '-'):>5} "
            f"{str(r.get('backend', '') or '-')[:8]:<8} "
            f"{_num(r.get('elapsed'), '.3f', 9)} "
            f"{_num(r.get('overall_throughput'), '.3f', 9)} "
            f"{r.get('anomaly_count', 0):>4}  {str(r.get('key') or '-')[:16]}"
        )
    return "\n".join(lines)
