"""Structured stderr logger replacing stray ``print`` diagnostics.

One line per record: ``[component] message key=value ...`` on stderr —
the same surface the ad-hoc prints used, so operators lose nothing —
with a level gate (``DSDDMM_LOG`` = ``debug`` | ``info`` | ``warn`` |
``error``, default ``info``) and, when tracing is active, a mirrored
``log`` event in the trace so diagnostics land next to the spans they
explain.

CLI-facing *output* (bench JSON lines, verify tables, chart paths) is
NOT logging and stays on ``print``/stdout; the print-lint test
(``tests/test_obs_lint.py``) enforces the boundary.
"""

from __future__ import annotations

import os
import sys
import threading

from distributed_sddmm_tpu.obs import trace

LEVELS = {"debug": 10, "info": 20, "warn": 30, "warning": 30, "error": 40}

_write_lock = threading.Lock()


def threshold() -> int:
    """Current level gate, read from ``DSDDMM_LOG`` per call (tests and
    long-lived processes can change it without reimporting)."""
    name = os.environ.get("DSDDMM_LOG", "info").lower()
    return LEVELS.get(name, 20)


def log(level: str, component: str, msg: str, **fields) -> None:
    lv = LEVELS.get(level, 20)
    if lv < threshold():
        return
    parts = [f"[{component}] {msg}"]
    parts += [f"{k}={v}" for k, v in fields.items()]
    line = " ".join(parts)
    with _write_lock:
        sys.stderr.write(line + "\n")
    if trace.enabled():
        attrs = {"level": level, "component": component, "msg": msg}
        for k, v in fields.items():
            # "name" is trace.event's own positional (the event name);
            # a log field by that name must not shadow it.
            attrs[k if k != "name" else "name_"] = v
        trace.event("log", **attrs)


def debug(component: str, msg: str, **fields) -> None:
    log("debug", component, msg, **fields)


def info(component: str, msg: str, **fields) -> None:
    log("info", component, msg, **fields)


def warn(component: str, msg: str, **fields) -> None:
    log("warn", component, msg, **fields)


def error(component: str, msg: str, **fields) -> None:
    log("error", component, msg, **fields)
