"""Optional ``jax.profiler`` integration: capture + named annotations.

Three pieces, all no-ops unless explicitly armed:

* :func:`capture` / :func:`maybe_capture` — a context manager around a
  whole run that starts a ``jax.profiler`` trace into a log directory
  (TensorBoard/XProf-readable). Armed by the bench CLI's ``--profile
  DIR`` flag or the ``DSDDMM_PROFILE=DIR`` env var.
* :func:`capture_window` — a bounded capture (a fraction of a second,
  not a run): start a trace, hold it for ``duration_s``, stop. This is
  the flight recorder's hook — when the watchdog fires an anomaly with
  ``--profile`` armed, a short window catches the device timeline
  *around* the anomaly without paying whole-run capture overhead.
  Refuses (returns False) while another capture is active — two
  concurrent ``jax.profiler`` sessions is an error in jax itself.
* :func:`annotate` — a named ``jax.profiler.TraceAnnotation`` wrapped
  around each compiled-program dispatch (``cgStep``, ``gatLayer``, the
  sddmm/spmm/fused programs) so device timelines carry the framework's
  op names. Only constructed while a capture is active
  (:func:`active`), so the hot path pays one boolean check otherwise.

Everything degrades gracefully: a jax without the profiler API (or a
backend that refuses to start one — :func:`capture_available` probes
without side effects) logs a warning and runs untraced — profiling must
never take down a run.
"""

from __future__ import annotations

import contextlib
import threading
import time  # time.sleep only; clocks go through obs.clock

from distributed_sddmm_tpu.obs import log

_capturing = False


def active() -> bool:
    """True while a profiler capture is running (annotations worth it)."""
    return _capturing


def annotate(name: str):
    """A ``TraceAnnotation(name)`` while capturing, else a null context."""
    if not _capturing:
        return contextlib.nullcontext()
    try:
        import jax.profiler

        return jax.profiler.TraceAnnotation(name)
    except Exception:  # noqa: BLE001 — profiling is best-effort
        return contextlib.nullcontext()


@contextlib.contextmanager
def capture(logdir: str):
    """Run the block under a ``jax.profiler`` trace into ``logdir``."""
    global _capturing
    started = False
    try:
        import jax.profiler

        jax.profiler.start_trace(logdir)
        started = True
        log.info("profiler", "jax.profiler capture started", logdir=logdir)
    except Exception as e:  # noqa: BLE001 — run unprofiled, never die
        log.warn("profiler", "could not start jax.profiler capture",
                 error=f"{type(e).__name__}: {e}")
    _capturing = started
    try:
        yield
    finally:
        _capturing = False
        if started:
            try:
                import jax.profiler

                jax.profiler.stop_trace()
                log.info("profiler", "jax.profiler capture written",
                         logdir=logdir)
            except Exception as e:  # noqa: BLE001
                log.warn("profiler", "jax.profiler stop_trace failed",
                         error=f"{type(e).__name__}: {e}")


def maybe_capture(logdir: str | None = None):
    """``capture(logdir)`` when a directory is given (CLI flag) or set in
    ``DSDDMM_PROFILE``; a null context otherwise."""
    import os

    target = logdir or os.environ.get("DSDDMM_PROFILE")
    if not target:
        return contextlib.nullcontext()
    return capture(target)


def capture_available() -> bool:
    """True when this jax exposes the start/stop trace API (no capture
    is started — a pure probe, safe on any backend)."""
    try:
        import jax.profiler

        return (
            hasattr(jax.profiler, "start_trace")
            and hasattr(jax.profiler, "stop_trace")
        )
    except Exception:  # noqa: BLE001 — absence is a normal answer
        return False


def capture_window(
    logdir: str, duration_s: float = 0.25, block: bool = True,
) -> bool:
    """Capture a short ``jax.profiler`` window into ``logdir``.

    Returns True when a window was attempted (profiler API present and
    no capture already active), False otherwise — the graceful no-op
    contract the flight recorder relies on. ``block=False`` runs the
    window on a daemon thread so an anomaly hook never stalls the
    dispatch path it fired from; the capture that actually lands is
    still best-effort (a backend refusing to start one logs and moves
    on, exactly like :func:`capture`).
    """
    if _capturing or not capture_available():
        return False

    def _window():
        with capture(logdir):
            if active():  # start_trace may still have refused
                time.sleep(duration_s)

    if block:
        _window()
        return True
    threading.Thread(
        target=_window, daemon=True, name="profiler-window"
    ).start()
    return True
