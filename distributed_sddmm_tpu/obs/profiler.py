"""Optional ``jax.profiler`` integration: capture + named annotations.

Two pieces, both no-ops unless explicitly armed:

* :func:`capture` / :func:`maybe_capture` — a context manager around a
  whole run that starts a ``jax.profiler`` trace into a log directory
  (TensorBoard/XProf-readable). Armed by the bench CLI's ``--profile
  DIR`` flag or the ``DSDDMM_PROFILE=DIR`` env var.
* :func:`annotate` — a named ``jax.profiler.TraceAnnotation`` wrapped
  around each compiled-program dispatch (``cgStep``, ``gatLayer``, the
  sddmm/spmm/fused programs) so device timelines carry the framework's
  op names. Only constructed while a capture is active
  (:func:`active`), so the hot path pays one boolean check otherwise.

Everything degrades gracefully: a jax without the profiler API (or a
backend that refuses to start one) logs a warning and runs untraced —
profiling must never take down a run.
"""

from __future__ import annotations

import contextlib

from distributed_sddmm_tpu.obs import log

_capturing = False


def active() -> bool:
    """True while a profiler capture is running (annotations worth it)."""
    return _capturing


def annotate(name: str):
    """A ``TraceAnnotation(name)`` while capturing, else a null context."""
    if not _capturing:
        return contextlib.nullcontext()
    try:
        import jax.profiler

        return jax.profiler.TraceAnnotation(name)
    except Exception:  # noqa: BLE001 — profiling is best-effort
        return contextlib.nullcontext()


@contextlib.contextmanager
def capture(logdir: str):
    """Run the block under a ``jax.profiler`` trace into ``logdir``."""
    global _capturing
    started = False
    try:
        import jax.profiler

        jax.profiler.start_trace(logdir)
        started = True
        log.info("profiler", "jax.profiler capture started", logdir=logdir)
    except Exception as e:  # noqa: BLE001 — run unprofiled, never die
        log.warn("profiler", "could not start jax.profiler capture",
                 error=f"{type(e).__name__}: {e}")
    _capturing = started
    try:
        yield
    finally:
        _capturing = False
        if started:
            try:
                import jax.profiler

                jax.profiler.stop_trace()
                log.info("profiler", "jax.profiler capture written",
                         logdir=logdir)
            except Exception as e:  # noqa: BLE001
                log.warn("profiler", "jax.profiler stop_trace failed",
                         error=f"{type(e).__name__}: {e}")


def maybe_capture(logdir: str | None = None):
    """``capture(logdir)`` when a directory is given (CLI flag) or set in
    ``DSDDMM_PROFILE``; a null context otherwise."""
    import os

    target = logdir or os.environ.get("DSDDMM_PROFILE")
    if not target:
        return contextlib.nullcontext()
    return capture(target)
