"""Thread-safe counters: the per-op metrics registry and global events.

Two registries, two jobs:

* :class:`OpMetrics` — one per strategy instance, replacing the ad-hoc
  ``total_time``/``call_count`` defaultdicts that ``parallel/base.py``
  kept around ``_timed``. The old dicts had two defects this class
  exists to fix: they were mutated without a lock while
  ``resilience/retry.py`` ran calls on worker threads, and retry
  attempts double-counted into kernel time (a healed transient fault
  inflated the op's "kernel" seconds by the whole backoff+retry wall).
  Every record now carries **kernel_s** (the successful attempt only)
  and **overhead_s** (everything `_resilient_call` added: failed
  attempts, backoff sleeps, fault hooks, guard checks) separately,
  plus per-op retries, communication words and FLOPs.
* :data:`GLOBAL` — a process-wide :class:`Counters` for cross-cutting
  events (faults fired, exec retries, guard repairs, plan-cache
  hits/misses, checkpoints saved/loaded; since PR 6 also the program
  store's ``program_store_hits`` / ``program_store_misses`` /
  ``live_compiles`` — disk-recalled vs in-process-compiled programs,
  the cold-start cost the runstore's compile column surfaces). Cheap
  enough to bump unconditionally; snapshot lands in bench records and
  smoke reports.

Communication/FLOP accounting conventions (matching
``tools/costmodel.py`` so counted volume is directly comparable to the
analytic predictions):

* ``comm_words`` are **per-device words** — the same unit the cost
  model's ``pair_words`` predicts (and the notebook's models before it).
  Only collectives the model counts contribute (``in_model`` entries of
  the strategy's ``comm_profile``); the SpMM reduce-scatter the notebook
  folds out of its comparison is tracked separately as
  ``comm_words_extra``. Words count ELEMENTS and are wire-dtype
  independent — ``comm_bytes`` (PR 15) is the dtype-aware volume under
  the strategy's wire policy (``costmodel.pair_bytes``); at the f32
  identity wire ``comm_bytes == 4 * comm_words`` exactly, so
  ``comm_words`` is simply the byte count re-expressed at 4 B/element
  and pre-PR-15 gate history keeps comparing.
* ``flops`` are **global useful FLOPs**: ``4 * nnz * R`` per fused
  SDDMM+SpMM pair, ``2 * nnz * R`` per single op — the bench harness's
  throughput convention (`benchmark_dist.cpp:147-149`).
"""

from __future__ import annotations

import collections
import threading


class Counters:
    """Named float counters behind one lock (add/get/snapshot/clear)."""

    def __init__(self):
        self._lock = threading.Lock()
        self._vals: dict[str, float] = {}

    def add(self, name: str, value: float = 1.0) -> None:
        with self._lock:
            self._vals[name] = self._vals.get(name, 0.0) + value

    def get(self, name: str, default: float = 0.0) -> float:
        with self._lock:
            return self._vals.get(name, default)

    def snapshot(self) -> dict:
        with self._lock:
            return dict(self._vals)

    def clear(self) -> None:
        with self._lock:
            self._vals.clear()


#: Process-wide event counters (faults_fired, exec_retries,
#: guard_repairs, plan_cache_hits, checkpoints_saved, ...).
GLOBAL = Counters()

_FIELDS = (
    "calls", "kernel_s", "overhead_s", "retries",
    "comm_words", "comm_bytes", "comm_words_extra", "flops",
)


class OpMetrics:
    """Per-op accumulators for one strategy instance (thread-safe)."""

    def __init__(self):
        self._lock = threading.Lock()
        self._ops: dict[str, dict] = {}
        #: Per-op gauge values (set, not accumulated): structural facts
        #: like ``padded_lane_frac`` that describe what the op runs
        #: OVER rather than what one dispatch did. Kept across
        #: :meth:`clear` — a timer reset does not rebuild tiles.
        self._gauges: dict[str, dict] = {}

    def note(self, op: str, **gauges) -> None:
        """Set per-op gauges (e.g. ``padded_lane_frac``). Last write
        wins; values surface in :meth:`to_dict` alongside the op's
        counters once the op has dispatched (a noted-but-never-run op
        stays out of records and scrapes — strategies note every op
        their tiles COULD serve at build time)."""
        with self._lock:
            self._gauges.setdefault(op, {}).update(gauges)

    def gauges(self) -> dict:
        """Snapshot of every noted per-op gauge, INCLUDING ops that have
        never dispatched — unlike :meth:`to_dict`, which hides them.
        The closed-loop tuner mines structural gauges here
        (``padded_lane_frac`` exists from tile build, long before the
        first strategy dispatch of a serving replica)."""
        with self._lock:
            return {op: dict(g) for op, g in self._gauges.items()}

    def record(
        self,
        op: str,
        kernel_s: float,
        overhead_s: float = 0.0,
        retries: int = 0,
        comm_words: float = 0.0,
        comm_bytes: float = 0.0,
        comm_words_extra: float = 0.0,
        flops: float = 0.0,
        calls: int = 1,
    ) -> None:
        with self._lock:
            rec = self._ops.get(op)
            if rec is None:
                rec = self._ops[op] = dict.fromkeys(_FIELDS, 0.0)
            rec["calls"] += calls
            rec["kernel_s"] += kernel_s
            rec["overhead_s"] += overhead_s
            rec["retries"] += retries
            rec["comm_words"] += comm_words
            rec["comm_bytes"] += comm_bytes
            rec["comm_words_extra"] += comm_words_extra
            rec["flops"] += flops

    # ------------------------------------------------------------------ #
    # Views
    # ------------------------------------------------------------------ #

    def time_view(self):
        """``{op: kernel seconds}`` — the ``json_perf_statistics`` shape.
        Retry/fault overhead is deliberately NOT in here; see
        :meth:`to_dict` for the full attribution."""
        with self._lock:
            return collections.defaultdict(
                float, {k: v["kernel_s"] for k, v in self._ops.items()}
            )

    def wall_view(self):
        """``{op: kernel + overhead seconds}`` — the unit the old
        ``total_time`` dict measured (whole ``_timed`` wall)."""
        with self._lock:
            return collections.defaultdict(
                float,
                {k: v["kernel_s"] + v["overhead_s"] for k, v in self._ops.items()},
            )

    def calls_view(self):
        with self._lock:
            return collections.defaultdict(
                int, {k: int(v["calls"]) for k, v in self._ops.items()}
            )

    def to_dict(self) -> dict:
        """Full per-op attribution, JSON-ready (sorted, rounded).
        Noted gauges merge into their op's dict; gauge-only ops (noted
        at tile build but never dispatched) are omitted so records and
        scrapes list only ops that actually ran."""
        with self._lock:
            out = {}
            for op in sorted(self._ops):
                rec = self._ops[op]
                out[op] = {
                    "calls": int(rec["calls"]),
                    "kernel_s": round(rec["kernel_s"], 9),
                    "overhead_s": round(rec["overhead_s"], 9),
                    "retries": int(rec["retries"]),
                    "comm_words": rec["comm_words"],
                    "comm_bytes": rec["comm_bytes"],
                    "comm_words_extra": rec["comm_words_extra"],
                    "flops": rec["flops"],
                    **self._gauges.get(op, {}),
                }
            return out

    def clear(self) -> None:
        with self._lock:
            self._ops.clear()


# --------------------------------------------------------------------- #
# Op-shape conventions shared by the dispatch choke point and the
# report tool: how many fused pairs one logical call represents, and
# the FLOP charge per op family.
# --------------------------------------------------------------------- #

#: Fraction of a fused SDDMM+SpMM pair each cost-op name represents
#: (``gatLayer`` is per-head — the caller scales by ``num_heads``).
#: ``fusedSpMMB``/``cgStepB`` are cost-op aliases: B-mode fused
#: dispatches keep their public counter name but charge the transposed
#: layout (``_timed``'s ``_comm_op`` hint).
OP_PAIRS = {
    "fusedSpMM": 1.0,
    "fusedSpMMB": 1.0,
    # Fused block-sparse attention: one SDDMM + one SpMM pass (the
    # masked-softmax epilogue between them is O(nnz) VPU work, charged
    # as zero model FLOPs like every other elementwise stage).
    "fusedAttn": 1.0,
    "fusedAttnB": 1.0,
    "cgStep": 1.0,
    "cgStepB": 1.0,
    "gatLayer": 1.0,
    "sddmmA": 0.5,
    "sddmmB": 0.5,
    "spmmA": 0.5,
    "spmmB": 0.5,
}


def op_flops(op: str, nnz: int, R: int, pairs: float = 1.0) -> float:
    """Global useful FLOPs for one call: 4*nnz*R per fused pair
    (2*nnz*R per single op via the 0.5 pair fraction)."""
    frac = OP_PAIRS.get(op)
    if frac is None:
        return 0.0
    return 4.0 * nnz * R * frac * pairs
