"""Per-run manifest: the environment a trace was captured in.

A trace without its environment is unreproducible noise — the manifest
records, once per run, everything needed to interpret (and re-run) the
numbers: jax/jaxlib versions, backend and device kind, device count,
git revision, the resilience/observability env knobs, and any extras
the caller supplies (mesh shape, plan fingerprint, bench config).

Written atomically next to the trace file as
``<run_id>.manifest.json``. Collection is strictly best-effort and
**never initializes a JAX backend**: device info is only read when a
backend is already up (platform pinning in scripts/tests must keep
working). Git provenance tolerates detached HEADs and non-git
checkouts: ``git_rev`` records ``"unknown"`` (never raises) when no
revision is resolvable, and ``git_dirty`` flags uncommitted changes
(None when unknowable).
"""

from __future__ import annotations

import os
import pathlib
import subprocess
import sys

from distributed_sddmm_tpu.obs import clock
from distributed_sddmm_tpu.utils.atomic import atomic_write_json

#: Manifest schema generation (validated by tools/tracereport.py).
SCHEMA_VERSION = 1

_REPO = pathlib.Path(__file__).resolve().parents[2]

#: Env knobs worth snapshotting — the resilience/obs configuration that
#: shaped the run's behavior.
_ENV_KEYS = (
    "DSDDMM_TRACE", "DSDDMM_LOG", "DSDDMM_PROFILE",
    "DSDDMM_FAULTS", "DSDDMM_GUARDS", "DSDDMM_GUARD_MODE",
    "DSDDMM_EXEC_RETRIES", "DSDDMM_EXEC_TIMEOUT",
    "DSDDMM_PLAN_CACHE", "DSDDMM_CHECKPOINT_DIR",
    "DSDDMM_WATCHDOG", "DSDDMM_RUNSTORE",
    "DSDDMM_DIST_COORDINATOR", "DSDDMM_DIST_NPROCS",
    "DSDDMM_DIST_PROC_ID",
    "JAX_PLATFORMS", "XLA_FLAGS",
)


_git_info_cache: dict = {}


def _git_info(cwd=None) -> dict:
    """``{"git_rev", "git_dirty"}``, memoized per directory — a traced
    sweep refreshes the manifest once per bench record and must not
    fork git each time.

    Never raises: a detached HEAD still resolves through ``rev-parse
    HEAD``; a non-git checkout (tarball export, bind-mounted subdir) or
    a missing git binary records ``git_rev: "unknown"`` with
    ``git_dirty: None`` — an explicit "provenance unavailable" marker a
    run-store consumer can filter on, instead of a crash or a silent
    null that reads like a bug."""
    cwd = pathlib.Path(cwd) if cwd is not None else _REPO
    key = str(cwd)
    if key not in _git_info_cache:
        rev, dirty = "unknown", None
        try:
            out = subprocess.run(
                ["git", "rev-parse", "HEAD"],
                cwd=cwd, capture_output=True, text=True, timeout=5,
            )
            if out.returncode == 0 and out.stdout.strip():
                rev = out.stdout.strip()
                st = subprocess.run(
                    ["git", "status", "--porcelain"],
                    cwd=cwd, capture_output=True, text=True, timeout=5,
                )
                if st.returncode == 0:
                    dirty = bool(st.stdout.strip())
        except (OSError, subprocess.SubprocessError):
            pass
        # unlocked-ok: idempotent memo — racing threads compute and
        # store the same value; dict item assignment is atomic under
        # the GIL and a double subprocess probe is harmless.
        _git_info_cache[key] = {"git_rev": rev, "git_dirty": dirty}
    return _git_info_cache[key]


def _jax_info() -> dict:
    """Version/device facts, without ever triggering backend init."""
    info: dict = {}
    jax = sys.modules.get("jax")
    if jax is None:
        return info
    info["jax_version"] = getattr(jax, "__version__", None)
    jaxlib = sys.modules.get("jaxlib")
    if jaxlib is not None:
        info["jaxlib_version"] = getattr(jaxlib, "version", None) and getattr(
            jaxlib.version, "__version__", None
        )
    try:
        # Only report devices if a backend already exists; creating one
        # here could pin the wrong platform before the caller's setup.
        backends = getattr(jax._src.xla_bridge, "_backends", None)
        if backends:
            devs = jax.devices()
            info["backend"] = jax.default_backend()
            info["device_count"] = len(devs)
            info["device_kind"] = devs[0].device_kind if devs else None
    except Exception:  # noqa: BLE001 — manifest is best-effort
        pass
    return info


def _dist_info() -> dict:
    """Pod identity (num_processes / process_index / coordinator) via
    ``dist.init.pod_info`` — which shares this module's never-initialize
    discipline: a live multi-process backend is authoritative, launcher
    env labels apply otherwise, and nothing boots a backend. Multi-host
    records must never pool into single-process baselines, so these
    fields ride every manifest (and the run-store index)."""
    try:
        from distributed_sddmm_tpu.dist.init import pod_info

        # The ONE record shape (PodContext.record_fields): coordinator
        # only when present, so single-controller manifests keep the
        # pre-PR-14 schema and can never drift from bench records.
        return pod_info().record_fields()
    except Exception:  # noqa: BLE001 — manifest is best-effort
        return {}


def build(run_id: str, extra: dict | None = None) -> dict:
    m = {
        "schema": SCHEMA_VERSION,
        "run_id": run_id,
        "created_epoch": clock.epoch(),
        "python": sys.version.split()[0],
        "platform": sys.platform,
        "argv": sys.argv,
        **_git_info(),
        "env": {k: os.environ[k] for k in _ENV_KEYS if k in os.environ},
    }
    m.update(_jax_info())
    m.update(_dist_info())
    if extra:
        m["extra"] = extra
    return m


def manifest_path_for(trace_path: str | os.PathLike) -> pathlib.Path:
    p = pathlib.Path(trace_path)
    return p.with_name(p.stem + ".manifest.json")


def write_for_trace(tracer, extra: dict | None = None) -> pathlib.Path | None:
    """Write (or refresh) the manifest next to ``tracer``'s trace file.

    Refreshes are cheap and idempotent, and once a manifest has been
    written WITH device facts (i.e. after backend init) further
    extras-free refreshes are skipped — a traced sweep calls this once
    per bench record and nothing in it can change anymore. A memory-only
    tracer (``trace.arm_ring`` with no file tracer; ``path is None``)
    has nowhere to put a manifest and returns None."""
    if tracer is None or tracer.path is None:
        return None
    path = manifest_path_for(tracer.path)
    if extra is None and getattr(tracer, "_manifest_final", False):
        return path
    m = build(tracer.run_id, extra)
    atomic_write_json(path, m)
    if "backend" in m:
        tracer._manifest_final = True
    return path
