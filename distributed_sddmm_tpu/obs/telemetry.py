"""Live serving telemetry: mergeable histograms + a sampler thread.

The run store and regression gate judge *finished* runs; this module is
the during-the-run view (the ROADMAP's "heavy traffic" operations half):

* :class:`LatencyHistogram` — a **fixed-bucket** latency histogram.
  Fixed bounds are the whole point: two histograms from two processes
  (or two sampling windows) merge by element-wise count addition, which
  is associative and commutative — the property multi-host aggregation
  and `bench trace-merge` need, and the property sample-list percentiles
  do not have without shipping every sample. Percentiles come back as
  bucket upper bounds (nearest-rank over the cumulative counts), so a
  merged p99 is conservative by at most one bucket's width.
* :class:`TelemetrySampler` — a daemon thread that snapshots a serving
  engine every ``interval_s``: queue depth/occupancy, shed/degrade/error
  counters, the request histogram, program-store hit rates, and the SLO
  error-budget burn rate, appended as JSONL to
  ``artifacts/telemetry/<run_id>.jsonl`` (``DSDDMM_TELEMETRY`` or
  ``bench serve --telemetry`` relocate/enable it). One snapshot is one
  self-contained line — ``bench top`` tails the newest file and renders
  the live view, and a crashed process leaves every completed line
  readable.
* **Burn rate** — the SRE error-budget framing: for a latency target
  ``pXX_ms=L`` the budget is the ``(100-XX)%`` of requests allowed over
  ``L``; ``burn_rate = observed_bad_fraction / budget_fraction``. 1.0
  means burning exactly at budget; >1 means the SLO will be violated if
  the window is representative. The worst axis wins. ``bench gate``
  regresses the recorded burn rate as a serving verdict axis.

Clock discipline: everything here reads ``obs.clock`` (the lint in
``tests/test_obs_lint.py`` forbids raw ``time.*`` calls in ``obs/``).
"""

from __future__ import annotations

import json
import os
import pathlib
import threading

from distributed_sddmm_tpu.obs import clock

_REPO = pathlib.Path(__file__).resolve().parents[2]
DEFAULT_TELEMETRY_DIR = _REPO / "artifacts" / "telemetry"

#: Fixed histogram bucket upper bounds in milliseconds (log-ish 1-2-5
#: ladder, 0.25 ms .. 30 s) plus an implicit overflow bucket. FIXED so
#: histograms from any two processes of any run merge; changing these
#: is a schema change (readers check the bounds match before merging).
BUCKET_BOUNDS_MS: tuple[float, ...] = (
    0.25, 0.5, 1.0, 2.0, 5.0, 10.0, 20.0, 50.0, 100.0, 250.0,
    500.0, 1000.0, 2500.0, 5000.0, 10000.0, 30000.0,
)


class LatencyHistogram:
    """Fixed-bucket counts; ``merge`` is associative + commutative."""

    __slots__ = ("bounds_ms", "counts")

    def __init__(self, bounds_ms: tuple[float, ...] = BUCKET_BOUNDS_MS,
                 counts: list[int] | None = None):
        self.bounds_ms = tuple(float(b) for b in bounds_ms)
        n = len(self.bounds_ms) + 1  # +1: overflow bucket
        if counts is None:
            counts = [0] * n
        if len(counts) != n:
            raise ValueError(
                f"histogram needs {n} counts for {n - 1} bounds, "
                f"got {len(counts)}"
            )
        self.counts = [int(c) for c in counts]

    # -- feeding ------------------------------------------------------- #

    def add(self, latency_ms: float) -> None:
        for i, bound in enumerate(self.bounds_ms):
            if latency_ms <= bound:
                self.counts[i] += 1
                return
        self.counts[-1] += 1  # overflow

    # -- algebra ------------------------------------------------------- #

    @property
    def total(self) -> int:
        return sum(self.counts)

    def merge(self, other: "LatencyHistogram") -> "LatencyHistogram":
        """A NEW histogram holding both operands' counts. Raises on a
        bounds mismatch — silently merging different bucketings would
        produce a histogram that means nothing."""
        if self.bounds_ms != other.bounds_ms:
            raise ValueError("cannot merge histograms with different bounds")
        return LatencyHistogram(
            self.bounds_ms,
            [a + b for a, b in zip(self.counts, other.counts)],
        )

    def __eq__(self, other) -> bool:
        return (
            isinstance(other, LatencyHistogram)
            and self.bounds_ms == other.bounds_ms
            and self.counts == other.counts
        )

    # -- reading ------------------------------------------------------- #

    def quantile_ms(self, pct: float) -> float | None:
        """Nearest-rank percentile as a bucket upper bound (None when
        empty). Overflow-bucket hits report the last finite bound — a
        floor, flagged by the caller comparing against ``total``."""
        total = self.total
        if total == 0:
            return None
        rank = max(1, int(pct / 100.0 * total + 0.999999))
        cum = 0
        for i, c in enumerate(self.counts):
            cum += c
            if cum >= rank:
                return self.bounds_ms[min(i, len(self.bounds_ms) - 1)]
        return self.bounds_ms[-1]

    def fraction_above(self, threshold_ms: float) -> float:
        """Fraction of observations in buckets that lie entirely above
        ``threshold_ms`` (a lower bound on the true fraction: the bucket
        straddling the threshold is not charged)."""
        total = self.total
        if total == 0:
            return 0.0
        # The overflow bucket's lower bound is the last finite bound;
        # past that the bucket straddles the threshold and is not
        # charged, like any other straddling bucket.
        above = self.counts[-1] if self.bounds_ms[-1] >= threshold_ms else 0
        for i, bound in enumerate(self.bounds_ms):
            lower = self.bounds_ms[i - 1] if i else 0.0
            if lower >= threshold_ms:
                above += self.counts[i]
        return above / total

    def percentiles_ms(self, pcts=(50, 95, 99)) -> dict:
        out = {}
        for pct in pcts:
            v = self.quantile_ms(pct)
            if v is not None:
                out[f"p{pct}"] = v
        return out

    # -- (de)serialization --------------------------------------------- #

    def to_dict(self) -> dict:
        return {"bounds_ms": list(self.bounds_ms), "counts": list(self.counts)}

    @classmethod
    def from_dict(cls, d: dict | None) -> "LatencyHistogram | None":
        if not isinstance(d, dict):
            return None
        try:
            return cls(tuple(d["bounds_ms"]), list(d["counts"]))
        except (KeyError, TypeError, ValueError):
            return None


def merge_histograms(dicts) -> LatencyHistogram | None:
    """Merge serialized histograms (e.g. one per trace shard / telemetry
    stream); unreadable or bounds-mismatched inputs are skipped."""
    out = None
    for d in dicts:
        h = d if isinstance(d, LatencyHistogram) else \
            LatencyHistogram.from_dict(d)
        if h is None:
            continue
        if out is None:
            # Copy: with a single LatencyHistogram input the result must
            # not alias the caller's object.
            out = LatencyHistogram(h.bounds_ms, h.counts)
        else:
            try:
                out = out.merge(h)  # merge() already returns a new one
            except ValueError:
                continue
    return out


# --------------------------------------------------------------------- #
# The sampler thread (one per serving engine)
# --------------------------------------------------------------------- #


def parse_env_spec(spec: str | None) -> tuple[bool, pathlib.Path | None]:
    """``DSDDMM_TELEMETRY`` grammar, matching the run store's: 0/off/
    false/no disables, 1/on/true/yes/empty selects the default dir, any
    other value is a directory."""
    spec = spec or ""
    low = spec.lower()
    if low in ("", "0", "off", "false", "no"):
        return False, None
    if low in ("1", "on", "true", "yes"):
        return True, None
    return True, pathlib.Path(spec)


def engine_snapshot(engine, slo=None, run_id: str | None = None) -> dict:
    """One telemetry-style snapshot of a serving engine, JSON-ready.

    ``engine`` needs ``.queue`` (``depth()``, ``max_depth``,
    ``submitted_count``), ``.stats()`` and ``.recorder`` (a
    :class:`~distributed_sddmm_tpu.serve.slo.LatencyRecorder`); ``slo``
    (optional) adds the burn-rate field. This is THE snapshot shape —
    the sampler appends it as JSONL lines, the admin server's
    ``/snapshot`` endpoint serves it live, and ``bench top`` renders
    either source through the same :func:`render_top`.
    """
    q = engine.queue
    summary = engine.recorder.summary()
    depth = q.depth()
    snap = {
        "schema": 1,
        "run_id": run_id,
        "t_epoch": clock.epoch(),
        "queue_depth": depth,
        "queue_capacity": q.max_depth,
        "depth_frac": round(depth / q.max_depth, 4) if q.max_depth else 0.0,
        "submitted": q.submitted_count,
        "requests": summary.get("requests", 0),
        "completed": summary.get("completed", 0),
        "errors": summary.get("errors", 0),
        "shed": summary.get("shed_count", 0),
        "degraded": summary.get("degraded_count", 0),
        "latency_hist": summary.get("request_hist"),
        "latency_hist_ms": summary.get("latency_hist_ms"),
        "batch_occupancy": (summary.get("batch_occupancy") or {}).get("mean"),
    }
    # mean*count from the SAME summary instant as the histogram above —
    # the /metrics exposition's histogram ``_sum``; deriving it from a
    # second summary() call would let requests complete in between and
    # ship a self-inconsistent _sum/_count pair in one scrape.
    lat = summary.get("latency_ms") or {}
    if lat.get("mean") is not None:
        snap["latency_sum_ms"] = lat["mean"] * summary.get("completed", 0)
    try:
        stats = engine.stats()
    except Exception:  # noqa: BLE001 — telemetry never fails serving
        stats = {}
    snap["program_store"] = {
        k: stats.get(k)
        for k in ("cache_hits", "cache_misses", "disk_hits", "live_compiles")
        if stats.get(k) is not None
    }
    if slo is not None:
        snap["burn_rate"] = slo.burn_rate(summary)
    # Bucket ladders — the router's structure-aware admission signal: a
    # request routes to a replica whose warm ladder fits its inner size.
    try:
        snap["buckets"] = {
            "batch": list(engine.batch_buckets),
            "inner": list(engine.workload.inner_buckets),
        }
    except Exception:  # noqa: BLE001 — telemetry never fails serving
        pass
    # Per-tenant QoS view: live queue depths from the weighted-fair
    # scheduler plus the recorder's per-tenant breakdown (when any
    # named tenant has shown up).
    q_tenants = getattr(q, "tenants", None) or {}
    if set(q_tenants) - {"default"} and hasattr(q, "tenant_depths"):
        tenant_view: dict[str, dict] = {}
        depths = q.tenant_depths()
        shed = dict(getattr(q, "tenant_shed", {}))
        sub = dict(getattr(q, "tenant_submitted", {}))
        rec_tenants = summary.get("tenant") or {}
        for name, spec in q_tenants.items():
            cell = {
                "depth": depths.get(name, 0),
                "submitted": sub.get(name, 0),
                "queue_shed": shed.get(name, 0),
                "weight": spec.weight,
            }
            cell.update(rec_tenants.get(name, {}))
            t_slo = getattr(spec, "slo", None)
            if t_slo is not None and name in rec_tenants:
                cell["burn_rate"] = t_slo.burn_rate(rec_tenants[name])
            tenant_view[name] = cell
        snap["tenant"] = tenant_view
    tuner = getattr(engine, "tuner", None)
    if tuner is not None:
        # The closed-loop tuner's live state (state machine phase,
        # promotions, time-to-adapt) — `bench top` and /snapshot show
        # a replica that is mid-shadow or freshly adapted.
        try:
            snap["tuner"] = tuner.snapshot()
        except Exception:  # noqa: BLE001 — telemetry never fails serving
            pass
    return snap


class TelemetrySampler:
    """Periodic engine snapshots appended as JSONL.

    Engine/slo requirements are :func:`engine_snapshot`'s. The thread
    is a daemon and every snapshot is one complete line, so a dying
    process costs at most the in-flight line.
    """

    def __init__(self, engine, interval_s: float = 0.5, out_dir=None,
                 slo=None, run_id: str | None = None):
        from distributed_sddmm_tpu.obs import trace as obs_trace

        self.engine = engine
        self.interval_s = float(interval_s)
        self.slo = slo
        rid = run_id or obs_trace.run_id()
        if rid is None:
            from distributed_sddmm_tpu.obs.trace import _make_run_id

            rid = _make_run_id()
        self.run_id = rid
        out_dir = pathlib.Path(out_dir) if out_dir else DEFAULT_TELEMETRY_DIR
        self.path = out_dir / f"{rid}.jsonl"
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None
        self.samples = 0

    # -- one snapshot --------------------------------------------------- #

    def snapshot(self) -> dict:
        return engine_snapshot(self.engine, slo=self.slo, run_id=self.run_id)

    # -- lifecycle ------------------------------------------------------ #

    def start(self) -> "TelemetrySampler":
        if self._thread is not None:
            raise RuntimeError("sampler already started")
        self.path.parent.mkdir(parents=True, exist_ok=True)
        self._stop.clear()
        self._thread = threading.Thread(
            target=self._run, daemon=True, name="telemetry-sampler"
        )
        self._thread.start()
        return self

    def stop(self, timeout_s: float = 5.0) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout_s)
            self._thread = None
        self._emit()  # final snapshot: the end-of-run state always lands

    def __enter__(self) -> "TelemetrySampler":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.stop()

    def _run(self) -> None:
        while not self._stop.wait(self.interval_s):
            self._emit()

    def _emit(self) -> None:
        try:
            line = json.dumps(self.snapshot(), default=str)
            # non-atomic-ok: append-only snapshot stream (bench top
            # tails it live; a torn tail line is skipped by the reader).
            with open(self.path, "a") as fh:
                fh.write(line + "\n")
            self.samples += 1
        except Exception:  # noqa: BLE001 — telemetry never fails serving
            pass


# --------------------------------------------------------------------- #
# `bench top` — the reader half
# --------------------------------------------------------------------- #


def read_snapshots(path) -> list[dict]:
    """All parseable snapshot lines of one telemetry file (torn final
    lines are skipped — the writer appends whole lines)."""
    out = []
    try:
        text = pathlib.Path(path).read_text()
    except OSError:
        return out
    for line in text.splitlines():
        if not line.strip():
            continue
        try:
            rec = json.loads(line)
        except ValueError:
            continue
        if isinstance(rec, dict):
            out.append(rec)
    return out


def newest_stream(root=None) -> pathlib.Path | None:
    """The most recently modified telemetry file under ``root``."""
    root = pathlib.Path(root) if root else DEFAULT_TELEMETRY_DIR
    try:
        files = sorted(root.glob("*.jsonl"), key=os.path.getmtime)
    except OSError:
        return None
    return files[-1] if files else None


def _render_top_fleet(snapshots: list[dict], cur: dict) -> str:
    """The ``bench top`` screen for a ROUTER snapshot (``/snapshot`` on
    a :class:`~distributed_sddmm_tpu.fleet.router.FleetRouter`'s admin
    port, tagged ``router: true``): per-replica health/breaker/depth
    table plus the routing, hedging and audit counters."""
    stats = cur.get("stats") or {}
    lines = [
        f"fleet router · sample {len(snapshots)} · "
        f"hedge {cur.get('hedge_delay_s')}s · "
        f"audit {cur.get('audit_frac')}",
        "",
        f"  {'replica':<10} {'ready':<6} {'breaker':<8} {'depth':>6} "
        f"{'burn':>6} {'strikes':>7}  buckets",
    ]
    for rep in cur.get("replicas") or []:
        state = "drain" if rep.get("draining") else (
            "yes" if rep.get("ready") else "no")
        lines.append(
            f"  {str(rep.get('name')):<10} {state:<6} "
            f"{str(rep.get('breaker', '-')):<8} "
            f"{100.0 * (rep.get('depth_frac') or 0.0):>5.0f}% "
            f"{rep.get('burn') if rep.get('burn') is not None else '-':>6} "
            f"{rep.get('strikes', 0):>7}  {rep.get('inner_buckets')}"
        )
    lines += [
        "",
        f"  routed    {stats.get('routed', 0):>6}   serial "
        f"{stats.get('serial_routed', 0)}   failovers "
        f"{stats.get('failovers', 0)}   decode_failovers "
        f"{stats.get('decode_failovers', 0)}",
        f"  hedges    {stats.get('hedges', 0):>6}   wins "
        f"{stats.get('hedge_wins', 0)}   audits {stats.get('audits', 0)}   "
        f"mismatches {stats.get('audit_mismatches', 0)}",
        f"  sheds     edge={stats.get('edge_sheds', 0)} "
        f"replica={stats.get('replica_sheds_seen', 0)}   breaker_opens "
        f"{stats.get('breaker_opens', 0)}   quarantines "
        f"{stats.get('quarantines', 0)}",
    ]
    mgr = cur.get("manager") or {}
    if mgr:
        # describe() ships replicas as the full dict list — the top
        # line wants the count, not the blob.
        lines.append(
            "  manager   "
            + "   ".join(
                f"{k}={len(mgr[k]) if isinstance(mgr[k], list) else mgr[k]}"
                for k in ("replicas", "spawns", "losses", "quarantines",
                          "trace_shards")
                if mgr.get(k) is not None
            )
        )
    return "\n".join(lines)


def render_top(snapshots: list[dict]) -> str:
    """The ``bench top`` screen: latest snapshot + short-window rates.

    Renders the engine view for replica snapshots and the fleet view
    (replica table + routing counters) when the snapshot came from a
    front router's admin port."""
    if not snapshots:
        return "no telemetry samples yet"
    cur = snapshots[-1]
    if cur.get("router"):
        return _render_top_fleet(snapshots, cur)
    lines = [
        f"run {cur.get('run_id')} · sample {len(snapshots)} · "
        f"t={cur.get('t_epoch')}",
        "",
        f"  queue     {cur.get('queue_depth', 0):>6} / "
        f"{cur.get('queue_capacity', 0)} "
        f"({100.0 * (cur.get('depth_frac') or 0.0):.0f}% full)",
        f"  requests  {cur.get('requests', 0):>6}   completed "
        f"{cur.get('completed', 0)}   errors {cur.get('errors', 0)}   "
        f"shed {cur.get('shed', 0)}   degraded {cur.get('degraded', 0)}",
    ]
    hist = LatencyHistogram.from_dict(cur.get("latency_hist"))
    if hist is not None and hist.total:
        p = hist.percentiles_ms()
        lines.append(
            f"  latency   p50 {p.get('p50', 0):>8.2f} ms   "
            f"p95 {p.get('p95', 0):>8.2f} ms   "
            f"p99 {p.get('p99', 0):>8.2f} ms   (n={hist.total})"
        )
    burn = cur.get("burn_rate")
    if burn is not None:
        state = "OVER BUDGET" if burn > 1.0 else "within budget"
        lines.append(f"  slo burn  {burn:>8.3f}x  ({state})")
    ps = cur.get("program_store") or {}
    if ps:
        lines.append(
            "  programs  "
            + "   ".join(f"{k}={v}" for k, v in sorted(ps.items()))
        )
    tun = cur.get("tuner")
    if tun:
        adapt = tun.get("time_to_adapt_s")
        lines.append(
            f"  tuner     {tun.get('state', '?'):<8} "
            f"promotions={tun.get('promotions', 0)} "
            f"rejects={tun.get('rejects', 0)}"
            + (f"  adapted in {adapt:.2f}s" if adapt is not None else "")
        )
    occ = cur.get("batch_occupancy")
    if occ is not None:
        lines.append(f"  occupancy {occ:>8.3f} mean batch fill")
    if len(snapshots) >= 2:
        prev = snapshots[-2]
        dt = (cur.get("t_epoch") or 0) - (prev.get("t_epoch") or 0)
        if dt > 0:
            dc = (cur.get("completed") or 0) - (prev.get("completed") or 0)
            ds = (cur.get("shed") or 0) - (prev.get("shed") or 0)
            lines.append(
                f"  window    {dc / dt:.1f} req/s served, "
                f"{ds / dt:.1f} req/s shed (last {dt:.1f}s)"
            )
    return "\n".join(lines)
