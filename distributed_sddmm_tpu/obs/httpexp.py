"""Zero-dependency HTTP admin surface: /metrics, health, debug ring.

The rest of the obs stack records to files read *after* a run; this
module is the live pull surface — the piece a fleet scheduler, a
Prometheus scraper, or an on-call human hits while the process is still
serving. Stdlib only (``http.server``), one daemon thread, bound to
loopback by default.

Endpoints (:class:`AdminServer`):

* ``/metrics`` — Prometheus text exposition (format 0.0.4): every
  :data:`~distributed_sddmm_tpu.obs.metrics.GLOBAL` counter (the
  export-completeness lint in ``tests/test_obs_lint.py`` pins that new
  counters cannot silently vanish from scrape — see
  :data:`KNOWN_GLOBAL_COUNTERS`), the per-op :class:`OpMetrics`
  registry, serving queue depth/occupancy gauges, program-store hit
  counters, the SLO burn-rate gauge, and the PR-7
  :class:`~distributed_sddmm_tpu.obs.telemetry.LatencyHistogram` as a
  proper cumulative-bucket Prometheus histogram (``_bucket{le=..}`` /
  ``_count`` / ``_sum``).
* ``/healthz`` — liveness: 200 while the engine's runner thread is
  alive (or always, in exporter mode), 503 once it died.
* ``/readyz`` — readiness: 200 only while the runner is alive, the
  warm program ladder is compiled, AND the SLO error-budget burn rate
  is at or under ``burn_threshold`` — the signal a load balancer uses
  to pull a replica that is still up but no longer meeting its SLO.
* ``/debug/requests`` — recent request timelines reconstructed from
  the tracer's in-memory span ring (``obs.trace.arm_ring``; the server
  arms it on start) through ``tools/tracereport.request_chains`` —
  the last N enqueue→batch→reply chains with their segment splits.
* ``/snapshot`` — the :func:`~distributed_sddmm_tpu.obs.telemetry.
  engine_snapshot` JSON (``bench top --admin-port`` reads this).
* ``POST /submit`` — request ingestion (only when a ``submit_fn`` is
  injected — ``bench serve --serve-http`` replica mode): JSON
  ``{"payload": {...}, "tenant": "...", "serial": false}`` → the reply
  JSON, or 429 + ``Retry-After`` when admission control sheds (the
  ``ShedError.retry_after_s`` hint, end to end). The fleet router
  (``fleet/router.py``) fronts a pool of these.

Two sources, one exposition: a **live engine** (``bench serve
--admin-port``) scrapes the engine/recorder/queue directly; a
**snapshot function** (``bench top --serve`` — the standalone exporter
over a telemetry JSONL stream) maps the latest sampler snapshot into
the same metric families, so dashboards don't care which side wrote it.

Clock discipline: reads ``obs.clock`` only (lint-enforced).
"""

from __future__ import annotations

import inspect
import json
import math
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Callable, Optional
from urllib.parse import urlsplit

from distributed_sddmm_tpu.obs import clock
from distributed_sddmm_tpu.obs import log as obs_log
from distributed_sddmm_tpu.obs import metrics as obs_metrics
from distributed_sddmm_tpu.obs import trace as obs_trace
from distributed_sddmm_tpu.obs.telemetry import LatencyHistogram

#: Every GLOBAL counter the package increments, with scrape help text.
#: ``tests/test_obs_lint.py::test_global_counters_exported_to_metrics``
#: statically scans the package for ``GLOBAL.add("<name>")`` sites and
#: fails if a name is neither listed here nor tagged ``# not-exported``
#: at the call site — a new counter cannot silently vanish from scrape.
KNOWN_GLOBAL_COUNTERS: dict = {
    "faults_fired": "injected faults fired (resilience/faults.py)",
    "exec_retries": "dispatch retries across offline + serving paths",
    "guard_repairs": "NaN/Inf outputs repaired by guards",
    "checkpoints_saved": "checkpoint steps persisted",
    "checkpoints_loaded": "checkpoint steps restored",
    "plan_cache_hits": "autotune plan-cache hits",
    "plan_cache_misses": "autotune plan-cache misses",
    "autotune_trial_retries": "autotune measured-trial retries",
    "autotune_candidates_dropped": "autotune candidates pruned pre-trial",
    "watchdog_anomalies": "anomalies recorded by the in-run watchdog",
    "program_store_hits": "AOT program store disk hits",
    "program_store_misses": "AOT program store misses",
    "live_compiles": "in-process compiles (cold-start cost)",
    "codegen_variants_built": "specialized banked kernel encodings built",
    "codegen_generic_fallbacks":
        "kernel-variant requests that fell back to the generic encoding",
    "serve_shed": "requests shed by admission control",
    "serve_degraded_batches": "serving batches degraded to the serial rung",
    "flightrec_dumps": "flight-recorder snapshots written",
    "tuner_scans": "closed-loop tuner signal-mining cycles",
    "tuner_signals": "re-tune trigger signals mined (tuner/signals.py)",
    "tuner_retunes": "off-path re-measurement cycles run by the tuner",
    "tuner_shadow_replays":
        "mirrored request groups replayed on a challenger ladder",
    "tuner_shadow_mismatches":
        "shadow replies that diverged from the incumbent (blocks promotion)",
    "tuner_promotions": "challenger ladders hot-swapped into serving",
    "tuner_rejects": "challengers abandoned (mismatch, stale, or no better)",
    "fleet_hedges": "hedged (duplicate) submits fired by the fleet router",
    "fleet_hedge_wins": "hedged submits whose backup reply won the race",
    "fleet_audit_mismatches":
        "cross-replica reply comparisons that disagreed bit-for-bit",
    "fleet_breaker_opens": "per-replica circuit breakers tripped open",
    "fleet_quarantines": "replicas quarantined for autopsy (byzantine/gray)",
    "dynstruct_rebinds":
        "structure changes bound into live programs with zero retraces",
    "dynstruct_bucket_spills":
        "structure changes that outgrew a capacity rung (full rebuild)",
    "structure_retraces":
        "program retraces forced by a structure change (the spill cost)",
}

#: Exposition metric-name prefix.
PREFIX = "dsddmm"


def _json_default(o):
    """JSON fallback for numpy payloads/replies crossing the wire: array
    ``tolist()`` / scalar ``item()`` keep int64 and float values exact
    (JSON numbers round-trip Python ints losslessly and floats via
    shortest-repr), so a decoded payload re-normalized by the workload's
    ``clamp`` is bit-identical to the original."""
    tolist = getattr(o, "tolist", None)
    if callable(tolist):
        return tolist()
    item = getattr(o, "item", None)
    if callable(item):
        return item()
    return str(o)


def _fmt_value(v) -> str:
    """A Prometheus sample value: floats rendered plainly, NaN allowed."""
    if isinstance(v, float):
        if math.isnan(v):
            return "NaN"
        if v == int(v) and abs(v) < 1e15:
            return str(int(v))
        return repr(v)
    return str(v)


def _escape_label(v: str) -> str:
    return str(v).replace("\\", r"\\").replace('"', r"\"").replace("\n", r"\n")


class Exposition:
    """Prometheus text-format builder (one HELP/TYPE per family)."""

    def __init__(self):
        #: family -> (type, help, [(labels_dict_or_None, value), ...])
        self._fams: dict[str, tuple[str, str, list]] = {}
        self._order: list[str] = []

    def _add(self, name, kind, help_text, labels, value):
        fam = self._fams.get(name)
        if fam is None:
            fam = self._fams[name] = (kind, help_text, [])
            self._order.append(name)
        fam[2].append((labels, value))

    def counter(self, name, value, help_text="", labels=None):
        self._add(name, "counter", help_text, labels, value)

    def gauge(self, name, value, help_text="", labels=None):
        self._add(name, "gauge", help_text, labels, value)

    def histogram_ms(self, name, hist: LatencyHistogram, sum_ms=None,
                     help_text=""):
        """A cumulative-bucket histogram from a fixed-bucket
        :class:`LatencyHistogram` (buckets are already disjoint counts;
        Prometheus wants cumulative ``le`` buckets + ``+Inf``)."""
        fam = self._fams.get(name)
        if fam is None:
            fam = self._fams[name] = ("histogram", help_text, [])
            self._order.append(name)
        cum = 0
        for bound, count in zip(hist.bounds_ms, hist.counts):
            cum += count
            fam[2].append(({"le": _fmt_value(float(bound))}, cum))
        total = hist.total
        fam[2].append(({"le": "+Inf"}, total))
        fam[2].append(("_count", total))
        # _sum is required by the format; the fixed-bucket histogram
        # does not track it, so the caller passes the recorder's
        # mean*count estimate (NaN when unknown — legal in the format).
        fam[2].append(("_sum", float("nan") if sum_ms is None else sum_ms))

    def render(self) -> str:
        lines = []
        for name in self._order:
            kind, help_text, samples = self._fams[name]
            if help_text:
                lines.append(f"# HELP {name} {help_text}")
            lines.append(f"# TYPE {name} {kind}")
            for labels, value in samples:
                if labels == "_count":
                    lines.append(f"{name}_count {_fmt_value(value)}")
                elif labels == "_sum":
                    lines.append(f"{name}_sum {_fmt_value(value)}")
                elif kind == "histogram":
                    lines.append(
                        f'{name}_bucket{{le="{labels["le"]}"}} '
                        f"{_fmt_value(value)}"
                    )
                elif labels:
                    lab = ",".join(
                        f'{k}="{_escape_label(v)}"'
                        for k, v in sorted(labels.items())
                    )
                    lines.append(f"{name}{{{lab}}} {_fmt_value(value)}")
                else:
                    lines.append(f"{name} {_fmt_value(value)}")
        return "\n".join(lines) + "\n"


# --------------------------------------------------------------------- #
# Metric sources -> exposition
# --------------------------------------------------------------------- #


def _expose_global(expo: Exposition) -> None:
    """Every known GLOBAL counter (0 when never bumped — Prometheus
    counters should exist from the first scrape). Only declared names
    are rendered: the lint keeps the declaration list complete, and a
    counter deliberately tagged ``# not-exported`` must actually stay
    off the scrape surface."""
    snap = obs_metrics.GLOBAL.snapshot()
    for name, help_text in KNOWN_GLOBAL_COUNTERS.items():
        expo.counter(f"{PREFIX}_{name}_total", snap.get(name, 0.0),
                     help_text)


_OP_FIELDS = (
    ("calls", f"{PREFIX}_op_calls_total", "dispatches per op"),
    ("kernel_s", f"{PREFIX}_op_kernel_seconds_total",
     "successful-attempt kernel seconds per op"),
    ("overhead_s", f"{PREFIX}_op_overhead_seconds_total",
     "retry/fault/guard overhead seconds per op"),
    ("retries", f"{PREFIX}_op_retries_total", "retries per op"),
    ("comm_words", f"{PREFIX}_op_comm_words_total",
     "counted per-device communication words per op"),
    ("comm_bytes", f"{PREFIX}_op_comm_bytes_total",
     "counted per-device communication bytes per op (wire-dtype aware)"),
    ("flops", f"{PREFIX}_op_flops_total", "analytic useful FLOPs per op"),
)


def _expose_op_metrics(expo: Exposition, op_metrics) -> None:
    ops = op_metrics.to_dict()
    for field, metric, help_text in _OP_FIELDS:
        for op, rec in ops.items():
            expo.counter(metric, rec[field], help_text, labels={"op": op})
    for op, rec in ops.items():
        if "padded_lane_frac" in rec:
            expo.gauge(
                f"{PREFIX}_op_padded_lane_frac", rec["padded_lane_frac"],
                "inert pad-lane fraction of the op's chunk-list encoding",
                labels={"op": op},
            )


def _expose_engine(expo: Exposition, engine, slo=None) -> None:
    """Live-engine mode: one ``engine_snapshot`` rendered through the
    exporter mapping — ONE family set for both sources, so the live and
    ``bench top --serve`` expositions cannot drift apart — plus the
    engine-only extras a telemetry snapshot line does not carry."""
    from distributed_sddmm_tpu.obs.telemetry import engine_snapshot

    snap = engine_snapshot(engine, slo=slo)
    _expose_snapshot(expo, snap)
    stats = engine.stats()
    expo.counter(f"{PREFIX}_served_requests_total", stats.get("served", 0),
                 "requests answered by the runner")
    expo.counter(f"{PREFIX}_degraded_batches_total",
                 stats.get("degraded_batches", 0),
                 "batches that fell to the serial rung")


def _expose_snapshot(expo: Exposition, snap: dict, sum_ms=None) -> None:
    """One telemetry snapshot dict (``engine_snapshot``'s shape, live
    or re-read from the sampler stream) mapped onto the metric
    families. The histogram's ``_sum`` comes from the snapshot's own
    ``latency_sum_ms`` (computed off the same summary instant as the
    buckets) unless the caller overrides it."""
    expo.gauge(f"{PREFIX}_queue_depth", snap.get("queue_depth", 0),
               "serving queue depth")
    expo.gauge(f"{PREFIX}_queue_capacity", snap.get("queue_capacity", 0),
               "admission bound (requests shed beyond it)")
    if snap.get("batch_occupancy") is not None:
        expo.gauge(f"{PREFIX}_batch_occupancy_mean",
                   snap["batch_occupancy"],
                   "mean micro-batch fill fraction")
    expo.counter(f"{PREFIX}_requests_submitted_total",
                 snap.get("submitted", 0), "requests admitted past the queue")
    for field, metric in (
        ("completed", "requests_completed_total"),
        ("errors", "requests_errors_total"),
        ("shed", "requests_shed_total"),
        ("degraded", "requests_degraded_total"),
    ):
        expo.counter(f"{PREFIX}_{metric}", snap.get(field, 0),
                     f"recorder {field}")
    for field in ("cache_hits", "cache_misses", "disk_hits",
                  "live_compiles"):
        v = (snap.get("program_store") or {}).get(field)
        if v is not None:
            expo.counter(f"{PREFIX}_program_{field}_total", v,
                         f"engine program-cache {field}")
    hist = LatencyHistogram.from_dict(snap.get("latency_hist")) \
        or LatencyHistogram()
    if sum_ms is None:
        sum_ms = snap.get("latency_sum_ms")
    expo.histogram_ms(f"{PREFIX}_request_latency_ms", hist, sum_ms=sum_ms,
                      help_text="end-to-end request latency (ms)")
    if snap.get("burn_rate") is not None:
        expo.gauge(f"{PREFIX}_slo_burn_rate", snap["burn_rate"],
                   "worst-axis error-budget burn rate (1.0 = at budget)")


# --------------------------------------------------------------------- #
# The admin server
# --------------------------------------------------------------------- #


class AdminServer:
    """The operational HTTP surface for one process.

    Construct with a live ``engine`` (``bench serve --admin-port``) or a
    ``snapshot_fn`` returning the latest telemetry snapshot dict
    (``bench top --serve`` exporter mode); ``op_metrics`` (an
    :class:`~distributed_sddmm_tpu.obs.metrics.OpMetrics`) adds the
    per-op families, ``slo`` the burn-rate gauge and the readiness burn
    check. ``port=0`` binds an ephemeral port (read :attr:`port` after
    :meth:`start`). Binds loopback by default — this is an *admin*
    surface, not a public API.
    """

    def __init__(
        self,
        engine=None,
        op_metrics=None,
        slo=None,
        snapshot_fn: Optional[Callable[[], Optional[dict]]] = None,
        host: str = "127.0.0.1",
        port: int = 0,
        burn_threshold: float = 1.0,
        ring_capacity: int = 512,
        debug_requests_limit: int = 64,
        submit_fn: Optional[Callable] = None,
        chaos_fn: Optional[Callable[[dict], dict]] = None,
        debug_fn: Optional[Callable[[], dict]] = None,
    ):
        self.engine = engine
        self.op_metrics = op_metrics
        self.slo = slo
        self.snapshot_fn = snapshot_fn
        #: ``submit_fn(payload, tenant=..., serial=..., timeout_s=...)``
        #: → reply dict. None keeps the server read-only (no /submit).
        #: A submit_fn that also accepts ``trace_ctx=`` receives the
        #: decoded ``X-DSDDMM-Trace`` fleet context (probed once here —
        #: existing submit_fns without the kwarg keep working unchanged).
        self.submit_fn = submit_fn
        self._submit_accepts_trace = False
        if submit_fn is not None:
            try:
                params = inspect.signature(submit_fn).parameters.values()
                self._submit_accepts_trace = any(
                    p.kind is inspect.Parameter.VAR_KEYWORD
                    or p.name == "trace_ctx"
                    for p in params
                )
            except (TypeError, ValueError):
                pass
        #: ``debug_fn()`` → dict served at ``/debug/requests`` instead of
        #: the span-ring reconstruction — the fleet router injects its
        #: live fleet request chains here.
        self.debug_fn = debug_fn
        #: ``chaos_fn(body)`` → ack dict, serving ``POST /chaos`` — the
        #: runtime arming hook chaos drills use to install a fault plan
        #: in an already-running replica (env knobs cannot change after
        #: spawn). None (the default) keeps the endpoint 404.
        self.chaos_fn = chaos_fn
        self.host = host
        self.port = int(port)
        self.burn_threshold = float(burn_threshold)
        self.ring_capacity = int(ring_capacity)
        self.debug_requests_limit = int(debug_requests_limit)
        self._httpd: Optional[ThreadingHTTPServer] = None
        self._thread: Optional[threading.Thread] = None
        self._armed_ring = False
        self.scrapes = 0

    # -- rendering ------------------------------------------------------ #

    def metrics_text(self) -> str:
        expo = Exposition()
        _expose_global(expo)
        if self.op_metrics is not None:
            _expose_op_metrics(expo, self.op_metrics)
        if self.engine is not None:
            _expose_engine(expo, self.engine, slo=self.slo)
        elif self.snapshot_fn is not None:
            snap = self.snapshot_fn()
            if snap:
                _expose_snapshot(expo, snap)
        expo.gauge(f"{PREFIX}_admin_scrapes", self.scrapes,
                   "scrapes served by this admin server")
        return expo.render()

    def snapshot(self) -> Optional[dict]:
        """The telemetry-style JSON the ``/snapshot`` endpoint serves."""
        if self.engine is not None:
            from distributed_sddmm_tpu.obs.telemetry import engine_snapshot

            return engine_snapshot(self.engine, slo=self.slo,
                                   run_id=obs_trace.run_id())
        if self.snapshot_fn is not None:
            return self.snapshot_fn()
        return None

    def health(self) -> tuple[int, dict]:
        """Liveness: the runner thread is the engine's beating heart.

        An engine that has not been started yet is still *alive* — the
        admin server deliberately comes up before warmup so readiness
        can report the compile window honestly, and a liveness prober
        that saw 503 there would kill the replica mid-warmup. Only a
        runner that started and then died is down."""
        if self.engine is None:
            return 200, {"ok": True, "mode": "exporter"}
        started = bool(getattr(self.engine, "ever_started", True))
        alive = self.engine.runner_alive() or not started
        return (200 if alive else 503), {
            "ok": alive, "runner_alive": self.engine.runner_alive(),
            "started": started,
        }

    def readiness(self) -> tuple[int, dict]:
        """Readiness: alive AND warm AND within SLO error budget."""
        checks: dict = {}
        if self.engine is not None:
            checks["runner_alive"] = self.engine.runner_alive()
            checks["warm"] = bool(getattr(self.engine, "warmed", False))
            if self.slo is not None:
                burn = self.slo.burn_rate(self.engine.recorder.summary())
                checks["burn_rate"] = burn
                checks["slo_burn_ok"] = (
                    burn is None or burn <= self.burn_threshold
                )
        elif self.snapshot_fn is not None:
            snap = self.snapshot_fn()
            checks["snapshot_available"] = snap is not None
            if snap is not None and snap.get("burn_rate") is not None:
                checks["burn_rate"] = snap["burn_rate"]
                checks["slo_burn_ok"] = (
                    snap["burn_rate"] <= self.burn_threshold
                )
        ready = all(
            v for k, v in checks.items() if isinstance(v, bool)
        ) if checks else True
        return (200 if ready else 503), {"ready": ready, "checks": checks}

    def debug_requests(self) -> dict:
        """Recent request timelines from the tracer's span ring."""
        from distributed_sddmm_tpu.tools import tracereport

        ring = obs_trace.ring()
        if ring is None:
            return {"error": "span ring not armed", "requests": []}
        recs = ring.records()
        pseudo = {
            "begin": None,
            "spans": [r for r in recs if r.get("type") == "span"],
            "events": [r for r in recs if r.get("type") == "event"],
            "errors": [],
        }
        chains = tracereport.request_chains(pseudo)
        rows = sorted(
            chains["requests"].values(),
            key=lambda ch: ch.get("t_reply") or ch.get("t_enqueue") or 0.0,
        )[-self.debug_requests_limit:]
        return {
            "ring_records": len(recs),
            "ring_seen": ring.appended,
            "complete": chains["complete"],
            "incomplete": chains["incomplete"],
            "inconsistent": chains["inconsistent"],
            "shed": chains["shed"],
            "requests": rows,
        }

    # -- lifecycle ------------------------------------------------------ #

    def start(self) -> "AdminServer":
        if self._httpd is not None:
            raise RuntimeError("admin server already started")
        # /debug/requests source; remember whether WE armed it so stop()
        # can put the process back exactly as found.
        self._armed_ring = obs_trace.ring() is None
        obs_trace.arm_ring(self.ring_capacity)
        admin = self

        class _Handler(BaseHTTPRequestHandler):
            server_version = "dsddmm-admin/1"
            protocol_version = "HTTP/1.1"

            def _guarded(self, route):
                try:
                    route(self)
                except BrokenPipeError:
                    pass
                except Exception as e:  # noqa: BLE001 — 500, never die
                    try:
                        body = f"internal error: {type(e).__name__}: {e}"
                        self.send_response(500)
                        payload = body.encode()
                        self.send_header("Content-Type", "text/plain")
                        self.send_header("Content-Length", str(len(payload)))
                        self.end_headers()
                        self.wfile.write(payload)
                    except Exception:  # noqa: BLE001
                        pass

            def do_GET(self):  # noqa: N802 — http.server API
                self._guarded(admin._route)

            def do_POST(self):  # noqa: N802 — http.server API
                self._guarded(admin._route_post)

            def log_message(self, fmt, *args):  # silence stderr chatter
                obs_log.debug("admin", fmt % args)

        self._httpd = ThreadingHTTPServer((self.host, self.port), _Handler)
        self._httpd.daemon_threads = True
        self.port = self._httpd.server_address[1]
        self._thread = threading.Thread(
            target=self._httpd.serve_forever, kwargs={"poll_interval": 0.2},
            daemon=True, name=f"admin-{self.port}",
        )
        self._thread.start()
        obs_log.info("admin", "serving",
                     url=f"http://{self.host}:{self.port}")
        return self

    def stop(self) -> None:
        if self._httpd is None:
            return
        self._httpd.shutdown()
        self._httpd.server_close()
        self._httpd = None
        if self._thread is not None:
            self._thread.join(5.0)
            self._thread = None
        if getattr(self, "_armed_ring", False):
            from distributed_sddmm_tpu.obs import flightrec

            # Disarm only what we armed — and never yank the ring out
            # from under an armed flight recorder. Without this, a
            # stopped admin server would leave a memory-only tracer
            # enabled() for the rest of the process.
            if flightrec.active() is None:
                obs_trace.disarm_ring()
            self._armed_ring = False

    def __enter__(self) -> "AdminServer":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.stop()

    # -- routing -------------------------------------------------------- #

    def _route(self, handler: BaseHTTPRequestHandler) -> None:
        path = urlsplit(handler.path).path.rstrip("/") or "/"
        if path == "/metrics":
            self.scrapes += 1
            self._send(handler, 200, self.metrics_text(),
                       "text/plain; version=0.0.4; charset=utf-8")
        elif path == "/healthz":
            code, body = self.health()
            self._send_json(handler, code, body)
        elif path == "/readyz":
            code, body = self.readiness()
            self._send_json(handler, code, body)
        elif path == "/debug/requests":
            if self.debug_fn is not None:
                self._send_json(handler, 200, self.debug_fn())
            else:
                self._send_json(handler, 200, self.debug_requests())
        elif path == "/snapshot":
            snap = self.snapshot()
            if snap is None:
                self._send_json(handler, 404,
                                {"error": "no snapshot source"})
            else:
                self._send_json(handler, 200, snap)
        elif path == "/":
            endpoints = ["/metrics", "/healthz", "/readyz",
                         "/debug/requests", "/snapshot"]
            if self.submit_fn is not None:
                endpoints.append("POST /submit")
            if self.chaos_fn is not None:
                endpoints.append("POST /chaos")
            self._send_json(handler, 200, {
                "endpoints": endpoints,
                "t_epoch": clock.epoch(),
            })
        else:
            self._send(handler, 404, f"no such endpoint: {path}\n",
                       "text/plain")

    def _route_post(self, handler: BaseHTTPRequestHandler) -> None:
        from distributed_sddmm_tpu.serve.queue import ShedError

        path = urlsplit(handler.path).path.rstrip("/") or "/"
        if path == "/chaos" and self.chaos_fn is not None:
            self._route_chaos(handler)
            return
        if path != "/submit" or self.submit_fn is None:
            self._send(handler, 404, f"no such POST endpoint: {path}\n",
                       "text/plain")
            return
        length = int(handler.headers.get("Content-Length") or 0)
        raw = handler.rfile.read(length) if length else b""
        try:
            body = json.loads(raw.decode() or "{}")
        except (UnicodeDecodeError, json.JSONDecodeError) as e:
            self._send_json(handler, 400, {"error": f"bad JSON: {e}"})
            return
        payload = body.get("payload")
        if not isinstance(payload, dict):
            self._send_json(handler, 400,
                            {"error": "body.payload must be an object"})
            return
        tenant = str(body.get("tenant") or "default")
        serial = bool(body.get("serial"))
        timeout_s = float(body.get("timeout_s") or 30.0)
        kwargs = {"tenant": tenant, "serial": serial, "timeout_s": timeout_s}
        if self._submit_accepts_trace:
            kwargs["trace_ctx"] = obs_trace.decode_fleet_ctx(
                handler.headers.get(obs_trace.TRACE_HEADER)
            )
        try:
            reply = self.submit_fn(payload, **kwargs)
        except ShedError as e:
            # The backpressure hint crosses the process boundary as the
            # standard header; the fleet router forwards it verbatim.
            retry_s = float(getattr(e, "retry_after_s", 0.0) or 0.0)
            self._send_json(
                handler, 429,
                {"error": str(e), "shed": True, "retry_after_s": retry_s},
                extra_headers={"Retry-After": f"{retry_s:.3f}"},
            )
        except ValueError as e:
            self._send_json(handler, 400, {"error": str(e)})
        except Exception as e:  # noqa: BLE001 — typed 500, never die
            self._send_json(
                handler, 500,
                {"error": f"{type(e).__name__}: {e}"},
            )
        else:
            self._send_json(handler, 200, {"reply": reply, "tenant": tenant})

    def _route_chaos(self, handler: BaseHTTPRequestHandler) -> None:
        """``POST /chaos``: arm a fault plan in the running replica.
        Only wired up in chaos-enabled ``bench serve`` replicas; a
        malformed body is the caller's bug (400), a handler failure a
        typed 500 — arming never crashes the serving process."""
        length = int(handler.headers.get("Content-Length") or 0)
        raw = handler.rfile.read(length) if length else b""
        try:
            body = json.loads(raw.decode() or "{}")
        except (UnicodeDecodeError, json.JSONDecodeError) as e:
            self._send_json(handler, 400, {"error": f"bad JSON: {e}"})
            return
        try:
            ack = self.chaos_fn(body)
        except ValueError as e:
            self._send_json(handler, 400, {"error": str(e)})
        except Exception as e:  # noqa: BLE001 — typed 500, never die
            self._send_json(
                handler, 500, {"error": f"{type(e).__name__}: {e}"},
            )
        else:
            self._send_json(handler, 200, ack or {"armed": True})

    @staticmethod
    def _send(handler, code: int, body: str, content_type: str,
              extra_headers: Optional[dict] = None) -> None:
        payload = body.encode()
        handler.send_response(code)
        handler.send_header("Content-Type", content_type)
        handler.send_header("Content-Length", str(len(payload)))
        for k, v in (extra_headers or {}).items():
            handler.send_header(k, v)
        handler.end_headers()
        handler.wfile.write(payload)

    @staticmethod
    def _send_json(handler, code: int, body: dict,
                   extra_headers: Optional[dict] = None) -> None:
        AdminServer._send(
            handler, code, json.dumps(body, default=_json_default) + "\n",
            "application/json", extra_headers=extra_headers,
        )


def fetch_json(host: str, port: int, path: str = "/snapshot",
               timeout_s: float = 2.0) -> dict:
    """GET a JSON endpoint off a local admin server (stdlib urllib —
    ``bench top --admin-port`` uses this). Raises ``OSError`` family on
    connection failure; callers fall back to the telemetry file."""
    import urllib.request

    url = f"http://{host}:{port}{path}"
    with urllib.request.urlopen(url, timeout=timeout_s) as resp:
        return json.loads(resp.read().decode())


def post_json(
    host: str, port: int, path: str, body: dict, timeout_s: float = 30.0,
    headers: Optional[dict] = None,
) -> tuple[int, dict, dict]:
    """POST JSON to a local admin/router server; returns ``(status,
    decoded_body, headers)``. HTTP error statuses (429/4xx/5xx) are
    returned, not raised — a shed IS a reply and its ``Retry-After``
    header is in the caller's contract. Connection-level failures
    (refused, reset, timeout) still raise the ``OSError`` family —
    that is how a router tells a dead replica from a shedding one.
    ``headers`` are merged over the Content-Type default — the fleet
    router passes the ``X-DSDDMM-Trace`` context this way."""
    import urllib.error
    import urllib.request

    url = f"http://{host}:{port}{path}"
    data = json.dumps(body, default=_json_default).encode()
    hdrs = {"Content-Type": "application/json"}
    if headers:
        hdrs.update(headers)
    req = urllib.request.Request(url, data=data, method="POST", headers=hdrs)
    try:
        with urllib.request.urlopen(req, timeout=timeout_s) as resp:
            return (resp.status, json.loads(resp.read().decode()),
                    dict(resp.headers))
    except urllib.error.HTTPError as e:
        raw = e.read()
        try:
            decoded = json.loads(raw.decode())
        except Exception:  # noqa: BLE001 — non-JSON error body
            decoded = {"error": raw.decode(errors="replace")}
        return e.code, decoded, dict(e.headers)
