"""External-competitor baseline: scipy CSR SpMM on the host CPU.

The reference benchmarked against PETSc ``MatMatMult`` on the same machines
with the same JSON schema and FLOP accounting
(`/root/reference/petsc_baseline/spmm_test.cpp:111-157`). PETSc does not
exist on a TPU host; the honest external competitor for a single chip's
host is scipy's native CSR SpMM (MKL-free SMSpMM in C). Same record schema:
``2 * R * nnz * iters`` FLOPs over wall time.
"""

from __future__ import annotations

import json
import time

import numpy as np

from distributed_sddmm_tpu.utils.coo import HostCOO


def run_baseline(
    S: HostCOO,
    R: int = 128,
    iters: int = 10,
    output_file: str | None = None,
) -> dict:
    """scipy CSR @ dense, accumulate semantics, PETSc-style accounting."""
    csr = S.to_scipy()
    rng = np.random.default_rng(0)
    B = rng.standard_normal((S.N, R))
    out = np.zeros((S.M, R))

    out += csr @ B  # warm caches
    t0 = time.perf_counter()
    for _ in range(iters):
        out += csr @ B
    elapsed = time.perf_counter() - t0

    record = {
        "baseline": "scipy-csr-spmm",
        "m": S.M, "n": S.N, "nnz": S.nnz, "r": R,
        "num_iterations": iters,
        "elapsed": elapsed,
        # `petsc_baseline/spmm_test.cpp:138-144` accounting.
        "overall_throughput": 2.0 * R * S.nnz * iters / elapsed / 1e9,
    }
    if output_file:
        # non-atomic-ok: append-only record stream (the -o contract).
        with open(output_file, "a") as f:
            f.write(json.dumps(record) + "\n")
    return record
