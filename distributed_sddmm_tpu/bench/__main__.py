from distributed_sddmm_tpu.bench.cli import main

raise SystemExit(main())
