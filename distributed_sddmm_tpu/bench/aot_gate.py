"""Shared policy for gating AOT-compile modes on the probe's verdict.

Single home for three decisions that bench.py, scripts/kernel_sweep.py,
scripts/dist_gap.py and scripts/tpu_apps.py previously each hand-rolled
(and let drift):

* which probe program vouches for a given kernel choice
  (`probe_program`),
* whether AOT_LOAD.json (written by scripts/aot_load_probe.py) validates
  re-homed loads for that program (`probe_validated`),
* when repeated AOT-precompile timeouts justify a permanent ok:false
  tombstone (`timeout_strike`).

Deliberately jax-free: the callers are orchestrator processes that must
not initialize any backend.

Reference analog: none — this is tunnel-environment engineering around
the remote Mosaic compile service (see bench/aot.py's module docstring).
"""

from __future__ import annotations

import json
import pathlib

from distributed_sddmm_tpu.obs import clock
from distributed_sddmm_tpu.utils.atomic import atomic_write_text

# Strikes closer together than this are treated as one load episode —
# a retry loop or a sibling script hitting the same machine-load spike
# minutes later is not independent evidence of a deterministic hang.
STRIKE_WINDOW_S = 1800.0

# Per-program probe-chain versions — THE single home (the probe script
# imports these). Bump a program's version when its chain changes: every
# gate then rejects that program's recorded verdict until the probe
# re-answers with the current chain, while sibling verdicts keep working.
# Entries recorded before per-program versioning carry no program_version
# field; those chains were version 1.
PROGRAM_VERSIONS = {
    "pallas_fused": 1,
    "xla_matmul": 2,  # v2: pinned to Precision.HIGHEST
}


def probe_program(kernel: str) -> str:
    """The aot_load_probe program whose verdict vouches for ``kernel``."""
    return "xla_matmul" if kernel == "xla" else "pallas_fused"


def _entry_current(name: str, entry: dict) -> bool:
    return entry.get("program_version", 1) == PROGRAM_VERSIONS.get(name)


def load_verdict(path: str | pathlib.Path) -> dict:
    """AOT_LOAD.json contents, or {} when absent/unreadable."""
    try:
        return json.loads(pathlib.Path(path).read_text())
    except (OSError, json.JSONDecodeError, ValueError):
        return {}


def probe_validated(rep: dict, program: str | None = None) -> bool:
    """Did the probe validate re-homed loads (for one program, or — with
    no argument — for ALL programs)? Multi-device backends never qualify
    (the offline compilers target one device), and a verdict earned by an
    older probe chain never qualifies — staleness must bind every gate,
    not only the queue's --check-stale pruning pass."""
    try:
        if int(rep.get("n_devices", 1)) != 1:
            return False
    except (TypeError, ValueError):
        return False
    progs = rep.get("programs") or {}
    if program is not None:
        entry = progs.get(program, {})
        return bool(entry.get("ok")) and _entry_current(program, entry)
    return bool(rep.get("ok")) and set(progs) >= set(PROGRAM_VERSIONS) and all(
        _entry_current(n, progs[n]) for n in PROGRAM_VERSIONS)


def timeout_strike(out_dir: str | pathlib.Path, *,
                   full_budget: bool = True) -> bool:
    """Record one AOT-precompile timeout strike against ``out_dir``.

    Returns True when the history now shows two strikes from independent
    load episodes (>= STRIKE_WINDOW_S apart) — only then should the
    caller write its permanent ok:false tombstone. A timeout under a
    capped budget (``full_budget=False``) neither counts nor is recorded:
    a healthy compile can exceed a ~30s remaining-window cap, so it is
    no evidence about this config at all.

    The strike file holds one epoch timestamp per line; tokens that are
    not plausible epochs (e.g. the pre-policy integer counters) are
    ignored rather than misread as 1970-era strikes.
    """
    out = pathlib.Path(out_dir)
    out.mkdir(parents=True, exist_ok=True)
    f = out / "timeouts"
    now = clock.epoch()
    times: list[float] = []
    try:
        for tok in f.read_text().split():
            try:
                v = float(tok)
            except ValueError:
                continue
            if v > 1e9:
                times.append(v)
    except OSError:
        pass
    if not full_budget:
        return False
    conclusive = any(now - t >= STRIKE_WINDOW_S for t in times)
    atomic_write_text(f, "\n".join(f"{t:.0f}" for t in [*times, now]))
    return conclusive
