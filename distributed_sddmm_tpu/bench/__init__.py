"""Benchmark harness: algorithm factory, trial loop, JSON result emission.

TPU-native counterpart of the reference's ``benchmark_dist.{hpp,cpp}`` and
its CLI drivers (``bench_erdos_renyi.cpp``, ``bench_file.cpp``,
``bench_heatmap.cpp``): one module + one argparse CLI
(``python -m distributed_sddmm_tpu.bench``) replace the four positional-argv
executables.
"""

from distributed_sddmm_tpu.bench.harness import (
    ALGORITHM_FACTORIES,
    benchmark_algorithm,
    make_algorithm,
)

__all__ = ["ALGORITHM_FACTORIES", "benchmark_algorithm", "make_algorithm"]
