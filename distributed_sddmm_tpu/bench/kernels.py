"""Single-device local-kernel microbenchmark.

The per-chip analog of the reference's ``local_kernel_benchmark``
(`/root/reference/local_kernel_benchmark.cpp:109-305`): sweep matrix size,
nnz/row and R over the local SDDMM / SpMM / fused kernels and print a
GFLOP/s table (`local_kernel_benchmark.cpp:264-267`). Where the reference
swept a hand COO loop vs an MKL CSR path, we sweep the XLA gather/segment-sum
kernel vs the Pallas one-hot MXU kernel.

Timing chains iterations data-dependently inside one jitted ``fori_loop``
ending in a host fetch — see bench.py for why (tunneled backends neither
block on ``block_until_ready`` nor pay dispatch per call otherwise).
"""

from __future__ import annotations

import json
import time
from functools import partial

import numpy as np

import jax
import jax.numpy as jnp

from distributed_sddmm_tpu.ops.blocked import CHUNK, DEFAULT_GROUP, build_blocked
from distributed_sddmm_tpu.ops.kernels import XlaKernel
from distributed_sddmm_tpu.utils.coo import HostCOO

# Reference sweep: logM 13-16, nnz/row 8-128, R 8-4096
# (`local_kernel_benchmark.cpp:276-280`). Default to a tractable subset.
DEFAULT_LOG_M = [13, 14, 15, 16]
DEFAULT_NNZ_PER_ROW = [8, 32, 128]
DEFAULT_R = [32, 128, 512]


def _chain_time(step_fn, state, trials: int) -> float:
    """Time ``trials`` data-dependent applications of ``step_fn``."""

    @partial(jax.jit, static_argnums=1)
    def chain(state, n):
        return jax.lax.fori_loop(0, n, lambda _, s: step_fn(s), state)

    def run(n):
        out = chain(state, n)
        # Host fetch forces the queue on tunneled backends.
        float(jnp.asarray(out[0]).sum())

    run(1)
    run(1 + trials)  # compile both trip counts
    t0 = time.perf_counter()
    run(1)
    t_one = time.perf_counter() - t0
    t0 = time.perf_counter()
    run(1 + trials)
    # Clamp: at tiny sizes the dispatch-noise difference can go negative.
    return max((time.perf_counter() - t0 - t_one) / trials, 1e-9)


def _bench_one(S: HostCOO, R: int, kernel_name: str, trials: int) -> dict:
    rng = np.random.default_rng(0)
    A = jnp.array(rng.standard_normal((S.M, R)), jnp.float32)
    B = jnp.array(rng.standard_normal((S.N, R)), jnp.float32)

    if kernel_name == "xla":
        kern = XlaKernel()
        rows = jnp.array(S.rows.astype(np.int32))
        cols = jnp.array(S.cols.astype(np.int32))
        vals = jnp.array(S.vals.astype(np.float32))

        # Each step must feed its output back into a DENSE operand — chaining
        # only the sparse values would leave the gather/dot loop-invariant
        # and XLA hoists it out of the timing loop.
        def sddmm_step(state):
            B, v = state
            out = kern.sddmm(rows, cols, v, A, B)
            return (B + out.sum() * 1e-30, v)

        def spmm_step(state):
            B, _ = state
            return (B + kern.spmm(rows, cols, vals, B, S.M)[: S.N] * 1e-12, _)

        t_sddmm = _chain_time(sddmm_step, (B, vals), trials)
        t_spmm = _chain_time(spmm_step, (B, vals), trials)
        t_fused = t_sddmm + t_spmm  # no fused XLA program
    else:
        from distributed_sddmm_tpu.ops.pallas_kernels import BlockedTile, PallasKernel

        precision = "bf16" if kernel_name == "pallas" else "f32"
        kern = PallasKernel(precision=precision)
        meta = build_blocked(
            1, np.zeros(S.nnz, np.int64), S.rows, S.cols, S.M, S.N,
            group=DEFAULT_GROUP,
        )
        blk = BlockedTile(
            lr=jnp.array(meta.lr[0]), lc=jnp.array(meta.lc[0]),
            meta=jnp.array(meta.meta[0]), bm=meta.bm, bn=meta.bn,
            gr_blocks=meta.gr_blocks, gc_blocks=meta.gc_blocks,
            group=meta.group,
        )
        vals_np = np.zeros(meta.n_chunks * CHUNK, np.float32)
        vals_np[meta.host_to_chunk] = S.vals
        vals = jnp.array(vals_np)

        def sddmm_step(state):
            B, v = state
            out = kern.sddmm_tile(blk, v, A, B)
            return (B + out.sum() * 1e-30, v)

        def spmm_step(state):
            B, _ = state
            return (B + kern.spmm_tile(blk, vals, B, S.M)[: S.N] * 1e-12, _)

        def fused_step(state):
            B, _ = state
            o, _mid = kern.fused_tile(blk, vals, A, B)
            return (B + o[: S.N] * 1e-12, _)

        t_sddmm = _chain_time(sddmm_step, (B, vals), trials)
        t_spmm = _chain_time(spmm_step, (B, vals), trials)
        t_fused = _chain_time(fused_step, (B, vals), trials)

    flops = 2.0 * S.nnz * R
    rec = {
        "M": S.M, "N": S.N, "nnz": S.nnz, "R": R, "kernel": kernel_name,
        "sddmm_ms": t_sddmm * 1e3, "spmm_ms": t_spmm * 1e3,
        "fused_pair_ms": t_fused * 1e3,
        "sddmm_gflops": flops / t_sddmm / 1e9,
        "spmm_gflops": flops / t_spmm / 1e9,
        "fused_pair_gflops": 2 * flops / t_fused / 1e9,
    }
    if kernel_name != "xla":
        # Record the active tuning knobs so the table is reproducible.
        rec.update(
            bm=meta.bm, bn=meta.bn, group=meta.group, chunk=CHUNK,
            scatter_form=kern.scatter_form, batch_step=kern.batch_step,
        )
    return rec


def run_kernel_benchmark(
    log_m_values=None,
    nnz_per_row_values=None,
    r_values=None,
    kernels=("xla", "pallas"),
    trials: int = 5,
    output_file: str | None = None,
) -> list:
    """Sweep and print the per-chip kernel table; returns all records."""
    log_m_values = log_m_values or DEFAULT_LOG_M
    nnz_per_row_values = nnz_per_row_values or DEFAULT_NNZ_PER_ROW
    r_values = r_values or DEFAULT_R

    header = (
        f"{'M':>9} {'nnz':>10} {'R':>5} {'kernel':>12} "
        f"{'SDDMM':>9} {'SpMM':>9} {'fused':>9}   (GFLOP/s)"
    )
    print(header)
    print("-" * len(header))
    records = []
    for log_m in log_m_values:
        for npr in nnz_per_row_values:
            S = HostCOO.rmat(log_m=log_m, edge_factor=npr, seed=0)
            S = S.with_values(
                np.random.default_rng(1).standard_normal(S.nnz)
            )
            for R in r_values:
                for kname in kernels:
                    rec = _bench_one(S, R, kname, trials)
                    records.append(rec)
                    print(
                        f"{rec['M']:>9} {rec['nnz']:>10} {rec['R']:>5} "
                        f"{rec['kernel']:>12} {rec['sddmm_gflops']:>9.2f} "
                        f"{rec['spmm_gflops']:>9.2f} "
                        f"{rec['fused_pair_gflops']:>9.2f}"
                    )
                    if output_file:
                        # non-atomic-ok: append-only record stream.
                        with open(output_file, "a") as f:
                            f.write(json.dumps(rec) + "\n")
    return records
