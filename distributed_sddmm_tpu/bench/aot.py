"""Ahead-of-time compile/serialize/load helpers for the kernel benchmarks.

On this environment's tunneled TPU backend, on-device Pallas compiles route
through a remote Mosaic service costing 2-12 minutes per distinct program —
the binding constraint on sweep breadth (see KERNELS_TPU.md). The Mosaic/
TPU compiler itself runs fine locally against a `jax.experimental.
topologies` AOT target (established by `scripts/preflight_kernels.py`), and
`scripts/aot_load_probe.py` tests whether such executables can be
deserialized onto the live chip. When that answer is yes, the sweep
pipeline uses this module to move every compile off-chip:

* `compile_chain_pair` (offline, CPU-pinned process): AOT-compile the
  chained-trials program — ``fori_loop(0, n, step)`` for n in
  {1, 1+trials}, the exact shape `bench.kernels._chain_time` jits — for
  one topology device, and serialize both executables to a directory.
* `load_chain_pair` + `chain_time_loaded` (on the TPU process): load the
  pair onto the real device and reproduce `_chain_time`'s timing protocol
  (warm both trip counts, time n=1, time n=1+trials, difference /
  trials).

The reference has no analog (its kernels are prebuilt library calls,
`sparse_kernels.cpp:94-121`); this is tunnel-environment engineering to
make the benchmark breadth of `local_kernel_benchmark.cpp:276-280`
affordable here.
"""

from __future__ import annotations

import pathlib
import pickle
import time

import jax
import jax.numpy as jnp


# (op, use_st) strategy programs each app touches — shared between the
# offline compiler (scripts/aot_compile_apps.py) and the injecting runner
# (scripts/tpu_apps.py) so the two can't drift. GAT is deliberately absent:
# its per-layer feature widths retrace, and the inject_program wrapper's
# jit fallback covers it.
APP_PROGRAM_KEYS = {
    "als": (("sddmm", False), ("sddmm", True), ("spmm", False),
            ("spmm", True), ("fused", False), ("fused", True)),
    "vanilla": (("fused", False),),
}


def _chain(step_fn, n: int):
    """The chained-trials program — must stay in lockstep with
    `bench.kernels._chain_time`'s jitted chain (same fori_loop shape), or
    AOT timings stop being comparable to on-device ones."""

    @jax.jit
    def chain(state):
        return jax.lax.fori_loop(0, n, lambda _, s: step_fn(s), state)

    return chain


def trip_counts(trials: int) -> tuple[int, int]:
    return (1, 1 + trials)


def _store_for(out_dir: str | pathlib.Path):
    """The program store bench AOT entries live in: the process-wide
    active store (``artifacts/programs/``, the PR 6 unification) when
    enabled, else a store rooted AT ``out_dir`` (tests and explicitly
    relocated caches). ``out_dir`` always contributes the key STEM — its
    basename already encodes the config/code-hash the offline compilers
    derive — so entries from different sweep configs cannot collide."""
    from distributed_sddmm_tpu import programs

    store = programs.active()
    return store if store is not None else programs.ProgramStore(out_dir)


def _aot_key(out_dir: str | pathlib.Path, name: str, n: int,
             backend: str) -> str:
    from distributed_sddmm_tpu.programs import bench_aot_key

    return bench_aot_key(pathlib.Path(out_dir).name, name, n, backend)


def save_executable(compiled, out_dir: str | pathlib.Path, name: str,
                    n: int, backend: str | None = None) -> None:
    """Persist one serialized executable into the program store under a
    ``bench:<dir-stem>:<name>:<n>`` key (the historical ``{name}_{n}.pkl``
    per-directory pickles became store entries in PR 6; `load_executable`
    still reads the legacy files as a fallback). ``backend`` is the
    TARGET platform — offline compilers pass their topology device's
    platform; default is the live backend."""
    if backend is None:
        from distributed_sddmm_tpu.programs.store import live_backend

        backend = live_backend()
    pathlib.Path(out_dir).mkdir(parents=True, exist_ok=True)
    store = _store_for(out_dir)
    if not store.save(_aot_key(out_dir, name, n, backend), compiled,
                      meta={"name": name, "n": n}, backend=backend):
        # This jax generation cannot serialize: keep the legacy pickle
        # format working rather than silently storing nothing.
        from jax.experimental import serialize_executable as se

        from distributed_sddmm_tpu.utils.atomic import atomic_write_bytes

        atomic_write_bytes(pathlib.Path(out_dir) / f"{name}_{n}.pkl",
                           pickle.dumps(se.serialize(compiled)))


def compile_chain_pair(step_fn, state, trials: int, device,
                       out_dir: str | pathlib.Path, name: str) -> dict:
    """AOT-compile ``step_fn``'s chain for both trip counts against
    ``device`` (a topology AOT device) and serialize to
    ``out_dir/{name}_{n}.pkl``. Returns {n: compile_seconds}."""
    sharding = jax.sharding.SingleDeviceSharding(device)

    def sds(x):
        x = jnp.asarray(x)
        return jax.ShapeDtypeStruct(x.shape, x.dtype, sharding=sharding)

    sds_state = jax.tree_util.tree_map(sds, state)
    times = {}
    for n in trip_counts(trials):
        t0 = time.monotonic()
        compiled = _chain(step_fn, n).lower(sds_state).compile()
        save_executable(compiled, out_dir, name, n,
                        backend=device.platform)
        times[n] = round(time.monotonic() - t0, 2)
    return times


def load_executable(out_dir: str | pathlib.Path, name: str, n: int, device):
    """Deserialize one saved executable onto ``device``: the program
    store first (PR 6 entries), then the legacy per-directory
    ``{name}_{n}.pkl`` pickle (pre-PR 6 caches stay loadable). Raises on
    any failure — callers fall back to the jitted path."""
    from distributed_sddmm_tpu import compat

    store = _store_for(out_dir)
    loaded = store.load(_aot_key(out_dir, name, n, device.platform),
                        device=device)
    if loaded is not None:
        return loaded
    serialized, in_tree, out_tree = pickle.loads(
        (pathlib.Path(out_dir) / f"{name}_{n}.pkl").read_bytes())
    return compat.deserialize_and_load(
        serialized, in_tree, out_tree, backend=device.client,
        execution_devices=[device])


def load_chain_pair(out_dir: str | pathlib.Path, name: str, trials: int,
                    device) -> dict:
    """Deserialize the chain pair onto ``device``. Returns {n: callable}.
    Raises on any load failure — callers fall back to on-device jit."""
    return {n: load_executable(out_dir, name, n, device)
            for n in trip_counts(trials)}


def timed_difference(run, trials: int) -> float:
    """`_chain_time`'s measurement protocol over an arbitrary ``run(n)``
    callable (which must BLOCK until the n-trip chain executed — end in a
    host fetch on tunneled backends): warm both trip counts, time each
    once, per-trial difference, clamped positive. The single home for this
    protocol — bench.py's worker keeps its own only because its negative-
    difference policy differs (uniform-cost estimate, documented there)."""
    run(1)
    run(1 + trials)
    t0 = time.perf_counter()
    run(1)
    t_one = time.perf_counter() - t0
    t0 = time.perf_counter()
    run(1 + trials)
    return max((time.perf_counter() - t0 - t_one) / trials, 1e-9)


def chain_time_loaded(loaded: dict, state, trials: int) -> float:
    """`timed_difference` over pre-loaded chain executables."""

    def run(n):
        out = loaded[n](state)
        # Host fetch forces execution on the tunneled backend.
        float(jnp.asarray(out[0]).sum())

    return timed_difference(run, trials)
