"""Communication/compute overlap experiment.

TPU-native analog of the reference's ``test_async_strategies``
(`/root/reference/test_async_strategies.cpp:14-103`), which asked whether
local compute can hide an ``MPI_Isend`` or RMA window. Here the question
is whether XLA's scheduler hides a ``ppermute`` ring hop behind per-step
matmul work — the property the shift algorithms' single-program ring loops
rely on (the reference achieved it by hand with ``BufferPair`` double
buffering, `common.h:49-93`).

Two complementary probes:

* **Measured** (:func:`run_overlap_experiment`): run p-1 ring steps over the
  mesh in one compiled program, twice — (a) "interleaved": each step
  computes on the resident block, then permutes (XLA may overlap the
  permute with the next step's compute); (b) "serialized": the same work
  with a data dependency forced between each compute and its following
  permute, denying overlap. The ratio of the two walltimes is the
  hidden-communication fraction. Caveat: the CPU test backend compiles only
  SYNCHRONOUS ``collective-permute`` (no start/done pairs), so the CPU-mesh
  ratio is ~1 by construction — a backend property, not a verdict on the
  algorithms.
* **Structural** (:func:`hlo_overlap_report`): AOT-compile the same program
  for a real TPU topology (``jax.experimental.topologies``, no chips
  needed) and inspect the scheduled HLO: on TPU the permute splits into
  ``collective-permute-start`` / ``-done`` and the latency-hiding scheduler
  places the per-step compute fusion INSIDE the window — the async
  double-buffered overlap the reference built by hand with ``BufferPair``
  (`common.h:49-93`). This is the property the shift algorithms rely on;
  no manual two-slot pipeline is needed on the XLA path.
"""

from __future__ import annotations

import json
import time
from functools import partial

import numpy as np

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P
from distributed_sddmm_tpu.compat import shard_map


def _program(p: int, steps_work: int, serialize: bool):
    perm = [(k, (k + 1) % p) for k in range(p)]

    def prog(X, W):
        def body(s, state):
            X, acc = state
            for _ in range(steps_work):
                acc = jnp.tanh(acc @ W)
            if serialize:
                # Data dependency: the permute input depends on the compute
                # result, so the collective cannot start early.
                X = X + acc[:1, :1] * 0
            # raw-collective-ok: standalone overlap microbenchmark ring
            # (not a strategy payload — wire policy does not apply).
            nxt = lax.ppermute(X, "ring", perm)
            return nxt, acc

        X, acc = lax.fori_loop(0, p - 1, body, (X, jnp.ones_like(X)))
        return acc + X

    return prog


def run_overlap_experiment(
    block: int = 1024,
    steps_work: int = 4,
    trials: int = 10,
    devices=None,
    output_file: str | None = None,
) -> dict:
    devices = devices if devices is not None else jax.devices()
    p = len(devices)
    mesh = Mesh(np.array(devices), ("ring",))
    spec = P("ring", None)

    rng = np.random.default_rng(0)
    X = jax.device_put(
        rng.standard_normal((block * p, block)).astype(np.float32),
        NamedSharding(mesh, spec),
    )
    W = jax.device_put(
        rng.standard_normal((block, block)).astype(np.float32),
        NamedSharding(mesh, P(None, None)),
    )

    results = {}
    for name, serialize in (("interleaved", False), ("serialized", True)):
        prog = shard_map(
            _program(p, steps_work, serialize),
            mesh=mesh, in_specs=(spec, P(None, None)), out_specs=spec,
        )

        @partial(jax.jit, static_argnums=2)
        def chain(X, W, n):
            return lax.fori_loop(0, n, lambda _, x: prog(x, W) * 1e-3, X)

        float(chain(X, W, 1).sum())
        float(chain(X, W, 1 + trials).sum())
        t0 = time.perf_counter(); float(chain(X, W, 1).sum())
        t_one = time.perf_counter() - t0
        t0 = time.perf_counter(); float(chain(X, W, 1 + trials).sum())
        # Clamp: dispatch noise can make the difference negative at tiny sizes.
        results[name] = max((time.perf_counter() - t0 - t_one) / trials, 1e-9)

    record = {
        "experiment": "comm-compute-overlap",
        "backend": jax.default_backend(),
        "p": p,
        "block": block,
        "steps_work": steps_work,
        "interleaved_ms": results["interleaved"] * 1e3,
        "serialized_ms": results["serialized"] * 1e3,
        "overlap_speedup": results["serialized"] / max(results["interleaved"], 1e-12),
    }
    if output_file:
        # non-atomic-ok: append-only record stream (the -o contract).
        with open(output_file, "a") as f:
            f.write(json.dumps(record) + "\n")
    return record


def hlo_overlap_report(
    p: int | None = None,
    block: int = 256,
    steps_work: int = 2,
    topology_name: str = "v5e:2x4",
    output_file: str | None = None,
) -> dict:
    """Structural overlap evidence from a scheduled TPU executable.

    AOT-compiles the interleaved ring program for ``topology_name`` (no
    hardware required) and reports, for the while-loop body, whether the
    scheduler placed compute between ``collective-permute-start`` and
    ``-done`` — i.e. whether the ring hop is hidden behind the local
    kernels, the reference's ``BufferPair`` property (`common.h:49-93`,
    `test_async_strategies.cpp:14-56`).
    """
    import re

    from jax.experimental import topologies

    topo = topologies.get_topology_desc(
        platform="tpu", topology_name=topology_name
    )
    devs = topo.devices
    if p is None:
        p = len(devs)  # default: the whole slice forms the ring
    if len(devs) < p:
        raise ValueError(
            f"topology {topology_name} has {len(devs)} < {p} chips"
        )
    mesh = Mesh(np.array(devs[:p]), ("ring",))
    spec = P("ring", None)
    xs = jax.ShapeDtypeStruct(
        (block * p, block), np.float32, sharding=NamedSharding(mesh, spec)
    )
    ws = jax.ShapeDtypeStruct(
        (block, block), np.float32, sharding=NamedSharding(mesh, P(None, None))
    )
    prog = jax.jit(
        shard_map(
            _program(p, steps_work, serialize=False),
            mesh=mesh, in_specs=(spec, P(None, None)), out_specs=spec,
        )
    )
    hlo = prog.lower(xs, ws).compile().as_text()

    record = {
        "experiment": "comm-compute-overlap-hlo",
        "topology": topology_name,
        "p": p,
        "block": block,
        "steps_work": steps_work,
        **scan_overlap_hlo(hlo),
    }
    if output_file:
        # non-atomic-ok: append-only record stream (the -o contract).
        with open(output_file, "a") as f:
            f.write(json.dumps(record) + "\n")
    return record


def scan_overlap_hlo(hlo: str) -> dict:
    """Structural overlap facts from one scheduled HLO text: whether the
    module is scheduled, how many ``collective-permute-start``/``-done``
    async pairs exist, and whether any computation places compute
    fusions/dots INSIDE a start→done window (the latency-hiding
    scheduler's signature — communication in flight behind the local
    kernel). Shared by the synthetic ring probe and the shift-strategy
    fusion probe below."""
    import re

    record = {
        "is_scheduled": "is_scheduled=true" in hlo,
        # Count op DEFINITIONS only — the matching done op's operand list
        # also contains the start op's name and must not double-count.
        "async_pairs": len(re.findall(r"collective-permute-start\(", hlo)),
        "loop_body_overlaps_compute": False,
    }
    # Scheduled order inside each computation: compute fusions/dots between
    # any start and its following done == overlap.
    for comp in re.split(r"\n(?=[%\w].*\{)", hlo):
        if "collective-permute-start(" not in comp:
            continue
        lines = comp.splitlines()
        open_start = None
        for i, ln in enumerate(lines):
            if "collective-permute-start(" in ln:
                open_start = i
            elif "collective-permute-done(" in ln and open_start is not None:
                inside = [
                    l for l in lines[open_start + 1 : i]
                    if re.search(r" fusion\(| dot\(|convolution", l)
                ]
                if inside:
                    record["loop_body_overlaps_compute"] = True
                open_start = None
    return record


def fusion_overlap_hlo_report(
    topology_name: str = "v5e:2x4",
    log_m: int = 8,
    edge_factor: int = 8,
    R: int = 16,
    c: int = 1,
    algorithm: str = "15d_fusion2",
    overlap: bool = True,
    unroll: bool = False,
    output_file: str | None = None,
) -> dict:
    """Structural overlap evidence for the ACTUAL shift-strategy fused
    program — the ``--fusion overlap`` acceptance gate.

    The strategy is constructed on the live (CPU test) mesh — tile
    ingest needs real buffers — then program construction is retargeted
    at a real TPU topology mesh of the same shape
    (``jax.experimental.topologies``, no chips needed; the
    ``artifacts/multichip_hlo`` pattern) and the fused SDDMM→SpMM
    program is AOT-compiled with ShapeDtypeStruct operands. The
    scheduled HLO is then scanned for ``collective-permute-start``/
    ``-done`` bracketing the per-step local kernel: ``async_pairs >= 1``
    with ``loop_body_overlaps_compute`` is the double-buffered
    local-kernel-overlap structure the reference built by hand with
    ``BufferPair``. Default ``unroll=False`` compiles the rolled ring so
    the evidence sits in an actual while-loop body.

    Environment note: on machines without TPU instance metadata, set
    ``TPU_SKIP_MDS_QUERY=1`` before first jax/libtpu init or the
    topology lookup stalls ~minutes in metadata retries.
    """
    from jax.experimental import topologies

    from distributed_sddmm_tpu.bench.harness import make_algorithm
    from distributed_sddmm_tpu.common import MatMode
    from distributed_sddmm_tpu.parallel.mesh import GridSpec, make_grid
    from distributed_sddmm_tpu.utils.coo import HostCOO

    devices = jax.devices()
    topo = topologies.get_topology_desc(
        platform="tpu", topology_name=topology_name
    )
    if len(topo.devices) < len(devices):
        raise ValueError(
            f"topology {topology_name} has {len(topo.devices)} < "
            f"{len(devices)} chips"
        )

    S = HostCOO.rmat(log_m=log_m, edge_factor=edge_factor, seed=0)
    alg = make_algorithm(
        algorithm, S, R, c, devices=devices, overlap=overlap, unroll=unroll
    )
    vals = alg.like_s_values(1.0)
    if algorithm == "15d_sparse":
        op, args = "spmm", (alg.dummy_initialize(MatMode.B),
                            *alg._spmm_args(alg.S_tiles, vals))
    else:
        op = "fused" if alg.fusion_approach == 2 else "fused_twopass"
        args = (alg.dummy_initialize(MatMode.A),
                alg.dummy_initialize(MatMode.B),
                *alg._tile_args(alg.S_tiles, vals))

    # Retarget program construction at the TPU topology mesh; operands
    # become ShapeDtypeStructs sharded over it.
    g = alg.grid
    tpu_grid = make_grid(g.nr, g.nc, g.nh, adjacency=g.adjacency,
                         devices=list(topo.devices)[: alg.p])
    alg.grid = GridSpec(mesh=tpu_grid.mesh, nr=g.nr, nc=g.nc, nh=g.nh,
                        adjacency=g.adjacency)
    alg._programs.clear()
    mesh = alg.grid.mesh

    def sds_like(x):
        return jax.ShapeDtypeStruct(
            x.shape, x.dtype,
            sharding=jax.sharding.NamedSharding(mesh, x.sharding.spec),
        )

    prog = alg._program(op, use_st=False)
    hlo = prog.lower(*(sds_like(a) for a in args)).compile().as_text()

    record = {
        "experiment": "fusion-overlap-hlo",
        "topology": topology_name,
        "algorithm": algorithm,
        "fusion": "overlap" if overlap else "sequential",
        "op": op,
        "p": alg.p,
        "c": c,
        "M": S.M,
        "nnz": S.nnz,
        "R": R,
        "unrolled": bool(unroll),
        **scan_overlap_hlo(hlo),
    }
    if output_file:
        # non-atomic-ok: append-only record stream (the -o contract).
        with open(output_file, "a") as f:
            f.write(json.dumps(record) + "\n")
    return record
