"""Communication/compute overlap experiment.

TPU-native analog of the reference's ``test_async_strategies``
(`/root/reference/test_async_strategies.cpp:14-103`), which asked whether
local compute can hide an ``MPI_Isend`` or RMA window. Here the question
is whether XLA's scheduler hides a ``ppermute`` ring hop behind per-step
matmul work — the property the shift algorithms' single-program ring loops
rely on (the reference achieved it by hand with ``BufferPair`` double
buffering, `common.h:49-93`).

Method: run p-1 ring steps over the mesh in one compiled program, twice —
(a) "interleaved": each step computes on the resident block, then permutes
(XLA may overlap the permute with the next step's compute); (b) "serialized":
the same work with a data dependency forced between each compute and its
following permute, denying overlap. The ratio of the two walltimes is the
hidden-communication fraction. On one device the permutes are no-ops and the
ratio is ~1; run on a real multi-chip mesh (or the CPU test mesh) for signal.
"""

from __future__ import annotations

import json
import time
from functools import partial

import numpy as np

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P
from jax import shard_map


def _program(p: int, steps_work: int, serialize: bool):
    perm = [(k, (k + 1) % p) for k in range(p)]

    def prog(X, W):
        def body(s, state):
            X, acc = state
            for _ in range(steps_work):
                acc = jnp.tanh(acc @ W)
            if serialize:
                # Data dependency: the permute input depends on the compute
                # result, so the collective cannot start early.
                X = X + acc[:1, :1] * 0
            nxt = lax.ppermute(X, "ring", perm)
            return nxt, acc

        X, acc = lax.fori_loop(0, p - 1, body, (X, jnp.ones_like(X)))
        return acc + X

    return prog


def run_overlap_experiment(
    block: int = 1024,
    steps_work: int = 4,
    trials: int = 10,
    devices=None,
    output_file: str | None = None,
) -> dict:
    devices = devices if devices is not None else jax.devices()
    p = len(devices)
    mesh = Mesh(np.array(devices), ("ring",))
    spec = P("ring", None)

    rng = np.random.default_rng(0)
    X = jax.device_put(
        rng.standard_normal((block * p, block)).astype(np.float32),
        NamedSharding(mesh, spec),
    )
    W = jax.device_put(
        rng.standard_normal((block, block)).astype(np.float32),
        NamedSharding(mesh, P(None, None)),
    )

    results = {}
    for name, serialize in (("interleaved", False), ("serialized", True)):
        prog = shard_map(
            _program(p, steps_work, serialize),
            mesh=mesh, in_specs=(spec, P(None, None)), out_specs=spec,
        )

        @partial(jax.jit, static_argnums=2)
        def chain(X, W, n):
            return lax.fori_loop(0, n, lambda _, x: prog(x, W) * 1e-3, X)

        float(chain(X, W, 1).sum())
        float(chain(X, W, 1 + trials).sum())
        t0 = time.perf_counter(); float(chain(X, W, 1).sum())
        t_one = time.perf_counter() - t0
        t0 = time.perf_counter(); float(chain(X, W, 1 + trials).sum())
        # Clamp: dispatch noise can make the difference negative at tiny sizes.
        results[name] = max((time.perf_counter() - t0 - t_one) / trials, 1e-9)

    record = {
        "experiment": "comm-compute-overlap",
        "p": p,
        "block": block,
        "steps_work": steps_work,
        "interleaved_ms": results["interleaved"] * 1e3,
        "serialized_ms": results["serialized"] * 1e3,
        "overlap_speedup": results["serialized"] / max(results["interleaved"], 1e-12),
    }
    if output_file:
        with open(output_file, "a") as f:
            f.write(json.dumps(record) + "\n")
    return record
