"""Unified benchmark CLI: ``python -m distributed_sddmm_tpu.bench <cmd>``.

Replaces the reference's positional-argv executables with argparse
subcommands:

* ``er``      — R-mat / Erdos-Renyi synthetic benchmark
  (`/root/reference/bench_erdos_renyi.cpp:19-118`)
* ``file``    — matrix-market file benchmark
  (`/root/reference/bench_file.cpp:19-101`)
* ``heatmap`` — R-sweep for the winner heatmap
  (`/root/reference/bench_heatmap.cpp:19-107`)
* ``permute`` — random row/col permutation of a .mtx file
  (`/root/reference/random_permute.cpp:19-59`)
* ``verify``  — fingerprint cross-check of all algorithms
  (`/root/reference/scratch.cpp:26-76`)
* ``kernels`` — single-device local-kernel sweep
  (`/root/reference/local_kernel_benchmark.cpp:109-305`)
* ``overlap`` — comm/compute overlap experiment
  (`/root/reference/test_async_strategies.cpp:14-103`)
* ``baseline`` — external-competitor host SpMM baseline
  (`/root/reference/petsc_baseline/spmm_test.cpp:111-157`)
* ``serve``   — online serving load test (no reference analog): warm
  engine + dynamic micro-batching + open-loop Poisson arrivals with
  SLO-gated latency (``distributed_sddmm_tpu/serve/``)

Cross-run observability subcommands (no reference analog — the obs
layer's store/regress/report half):

* ``history``     — list the persistent run store (``obs/store.py``)
* ``compare``     — per-phase delta table between two stored runs
* ``gate``        — CI regression gate vs a rolling baseline
  (exit 0 pass / 2 regression / 3 insufficient data)
* ``backfill``    — ingest the committed round 1–5 BENCH/MULTICHIP
  records into the store
* ``report-html`` — self-contained HTML dashboard (``obs/report.py``)
* ``report-trace``— per-phase aggregate of one trace file
* ``trace-merge`` — offset-align and merge per-process trace shards
  into one schema-valid trace (``obs/tracemerge.py``)
* ``trace-export``— convert any schema-valid trace (merged multi-shard
  included) to Chrome trace-event JSON openable in Perfetto, request
  chains drawn as cross-thread flows (``obs/traceexport.py``)
* ``top``         — live serving telemetry view over the sampler's
  JSONL stream or a live ``--admin-port`` endpoint; ``--serve``
  re-exports a telemetry stream as /metrics (``obs/telemetry.py``,
  ``obs/httpexp.py``)
* ``lint``        — repo-wide invariant analyzer: the discipline
  checkers over the package with the committed baseline applied
  (``analysis/``; exit 0 clean / 2 new findings / 3 usage error)
* ``env``         — the declared ``DSDDMM_*`` env-knob table
  (``utils/envreg.py``; ``--markdown`` regenerates the README block)

Benchmark-producing subcommands (``er``/``file``/``heatmap``) persist
every record into the run store automatically (``--no-runstore`` opts
out) and accept ``--watchdog warn|strict`` for in-run anomaly
monitoring (``obs/watchdog.py``).
"""

from __future__ import annotations

import argparse
import json
import os
import sys

from distributed_sddmm_tpu.bench import harness
from distributed_sddmm_tpu.bench.harness import (
    ALGORITHM_FACTORIES,
    benchmark_algorithm,
)
from distributed_sddmm_tpu.utils.coo import HostCOO

# `bench_erdos_renyi.cpp:50-115`: "15d" runs both fusion strategies, "25d"
# runs both replication strategies.
ALG_GROUPS = {
    "15d": ["15d_fusion1", "15d_fusion2", "15d_sparse"],
    "25d": ["25d_dense_replicate", "25d_sparse_replicate"],
    "all": list(ALGORITHM_FACTORIES),
}

# `bench_heatmap.cpp:33-35`.
HEATMAP_R_VALUES = [64, 128, 192, 256, 320, 384, 448]


def _resolve_algs(name: str) -> list[str]:
    if name == "auto":
        # Autotuned: the plan (algorithm, c, kernel) is selected per
        # (matrix, R) in _run_configs through the autotune subsystem.
        return ["auto"]
    if name in ALG_GROUPS:
        return ALG_GROUPS[name]
    if name in ALGORITHM_FACTORIES:
        return [name]
    raise SystemExit(
        f"unknown algorithm {name!r}; expected one of "
        f"{sorted(ALGORITHM_FACTORIES) + sorted(ALG_GROUPS) + ['auto']}"
    )


def _get_kernel(name: str, variant: str | None = None):
    from distributed_sddmm_tpu.ops import get_kernel

    if variant:
        from distributed_sddmm_tpu.codegen import make_banked_kernel

        if name not in ("pallas", "auto"):
            raise SystemExit("--kernel-variant requires the pallas kernel")
        return make_banked_kernel(variant)
    return get_kernel(name)


def _run_configs(S, alg_names, args, r_values=None):
    breakdown = getattr(args, "breakdown", False)
    if breakdown and (args.app != "vanilla" or args.fused != "yes"):
        # Raise here, not inside the loop: the per-config ValueError catch
        # below is for divisibility skips and would silently swallow this
        # usage error, "succeeding" with zero records.
        raise SystemExit(
            "--breakdown requires --app vanilla and --fused yes "
            "(it attributes the fusedSpMM op)"
        )
    records = []
    for alg in alg_names:
        for R in r_values or [args.R]:
            plan = None
            if alg == "auto":
                # Autotuned path: fingerprint the problem, recall or select
                # a plan (algorithm + c + kernel); the positional c and
                # --kernel are superseded by the plan's choices.
                from distributed_sddmm_tpu.autotune import Problem, get_plan

                mode = getattr(args, "plan_mode", "model")
                plan = get_plan(
                    Problem.from_coo(S, R),
                    S=S if mode in ("auto", "measure") else None,
                    mode=mode,
                )
                run_alg, run_c, kernel = plan.algorithm, plan.c, plan.make_kernel()
                print(
                    f"plan[{plan.source}] {run_alg} c={run_c} "
                    f"kernel={plan.kernel}"
                    + (f" variant={plan.variant}" if plan.variant else "")
                    + (f" wire={plan.wire}" if plan.wire else "")
                    + (" (chunked)" if plan.gather_budget else ""),
                    file=sys.stderr,
                )
            else:
                run_alg, run_c, kernel = alg, args.c, _get_kernel(
                    args.kernel, getattr(args, "kernel_variant", None)
                )
            for fused in ([True, False] if args.fused == "both" else [args.fused == "yes"]):
                # The plan's Pallas block config applies at strategy BUILD
                # (tile ingest bakes the geometry), so the whole benchmark
                # call runs under the plan's knobs — otherwise the record
                # would claim a block config that never ran.
                if plan is not None:
                    from distributed_sddmm_tpu.autotune.measure import block_knobs

                    knobs = block_knobs(plan.candidate())
                else:
                    import contextlib

                    knobs = contextlib.nullcontext()
                try:
                    with knobs:
                        rec = benchmark_algorithm(
                            S,
                            run_alg,
                            args.output_file,
                            fused=fused,
                            R=R,
                            c=run_c,
                            app=args.app,
                            trials=args.trials,
                            warmup=args.warmup,
                            kernel=kernel,
                            breakdown=getattr(args, "breakdown", False),
                            extra_info={"plan": plan.to_dict()} if plan else None,
                            checkpoint_dir=getattr(args, "checkpoint_dir", None),
                            checkpoint_every=getattr(args, "checkpoint_every", 1),
                            resume=getattr(args, "resume", False),
                            overlap=getattr(args, "fusion", None) == "overlap",
                            mask=(
                                getattr(args, "mask", None)
                                if args.app == "attention" else None
                            ),
                            # Plan-routed runs realize the plan's own
                            # comm_dtype axis; explicit algorithms take
                            # the CLI policy.
                            wire=(plan.wire if plan is not None
                                  else getattr(args, "wire", None)),
                        )
                except ValueError as e:
                    # Divisibility constraints differ per algorithm
                    # (reference exits; the sweep driver skips instead).
                    print(f"skip {run_alg} R={R} c={run_c}: {e}", file=sys.stderr)
                    continue
                records.append(rec)
                print(
                    json.dumps(
                        {
                            "algorithm": run_alg,
                            "R": R,
                            "c": run_c,
                            "fused": fused,
                            "elapsed": round(rec["elapsed"], 4),
                            "GFLOPs": round(rec["overall_throughput"], 3),
                        }
                    )
                )
    return records


def _add_common(p: argparse.ArgumentParser) -> None:
    p.add_argument(
        "--app", default="vanilla",
        choices=["vanilla", "gat", "als", "attention"],
    )
    p.add_argument(
        "--mask", default="window:16", metavar="SPEC",
        help="with --app attention: the block-sparse mask family — "
        "window:<w> (sliding window), bigbird:w=..,g=..,r=.. "
        "(window + global + random), or graph (the generated/loaded "
        "matrix's pattern, the GAT adjacency path); the benchmark "
        "matrix becomes the mask and the spec rides into records as a "
        "gate config axis (distributed_sddmm_tpu/masks.py)",
    )
    p.add_argument("--trials", type=int, default=5)
    p.add_argument("--warmup", type=int, default=1)
    p.add_argument("--kernel", default="auto", help="xla | pallas | auto")
    p.add_argument(
        "--kernel-variant", default=None, metavar="VID",
        help="force a codegen kernel-variant id (e.g. v1.rb32.rm) on the "
        "pallas kernel; plans select one automatically via --algorithm auto",
    )
    p.add_argument(
        "--plan-mode", default="model", choices=["model", "auto", "measure"],
        help="with an 'auto' algorithm: 'model' selects by cost model / "
        "cache only (fast, no trial runs); 'measure' times the top "
        "candidates first; 'auto' measures when possible",
    )
    p.add_argument(
        "--wire", default=None, choices=["f32", "bf16"],
        help="wire-precision policy for the distributed collectives "
        "(parallel/wire.py): 'bf16' halves gather/ring payload bytes "
        "with f32 accumulation everywhere; 'f32' (and the default, "
        "absent DSDDMM_WIRE) is the bit-identical identity wire. With "
        "--algorithm auto the plan's comm_dtype axis supersedes this; "
        "gated structurally by WIRE_HLO.json",
    )
    p.add_argument("--fused", default="yes", choices=["yes", "no", "both"])
    p.add_argument(
        "--fusion", default="sequential", choices=["sequential", "overlap"],
        help="ring-loop build for the 1.5D shift strategies: 'sequential' "
        "(kernel then ppermute per tile) or 'overlap' (double-buffered "
        "local kernel overlap — the next tile's ppermute is issued before "
        "the current tile's kernel, the reference's BufferPair strategy); "
        "bit-identical results, gated structurally by "
        "`bench overlap --fusion-hlo`",
    )
    p.add_argument(
        "--breakdown", action="store_true",
        help="add {Replication, Propagation, Computation} region attribution "
        "to perf_stats (collective-ablation timing; run on a standard "
        "backend, e.g. the CPU test mesh)",
    )
    p.add_argument("-o", "--output-file", default=None, help="append JSON records here")
    p.add_argument(
        "--faults", default=None, metavar="SPEC",
        help="activate a fault-injection plan: inline JSON spec list (or "
        "{'seed','specs'} dict) or @/path/to/plan.json; equivalent to the "
        "DSDDMM_FAULTS env var (see resilience/faults.py)",
    )
    p.add_argument(
        "--checkpoint-dir", default=None, metavar="DIR",
        help="persist app state (ALS factors) under DIR atomically",
    )
    p.add_argument(
        "--checkpoint-every", type=int, default=1, metavar="N",
        help="checkpoint every N alternating steps (with --checkpoint-dir)",
    )
    p.add_argument(
        "--resume", action="store_true",
        help="resume from the newest valid checkpoint in --checkpoint-dir "
        "instead of step 0 (corrupt checkpoints scan back; none = fresh)",
    )
    p.add_argument(
        "--trace", nargs="?", const="1", default=None, metavar="PATH",
        help="emit a structured JSONL trace (spans, comm counters, "
        "resilience events) + run manifest; PATH is a .jsonl file or a "
        "directory, default artifacts/traces/<run_id>.jsonl "
        "(equivalent to DSDDMM_TRACE)",
    )
    p.add_argument(
        "--profile", default=None, metavar="LOGDIR",
        help="capture a jax.profiler trace into LOGDIR "
        "(TensorBoard-readable) with named annotations per compiled "
        "program (equivalent to DSDDMM_PROFILE)",
    )
    p.add_argument(
        "--watchdog", default=None, choices=["warn", "strict"],
        help="in-run anomaly monitor: EWMA step-time spikes/drift, "
        "repair storms, comm-vs-costmodel mismatch; 'warn' reports "
        "(anomaly trace events + an 'anomalies' record field), 'strict' "
        "escalates through the resilience ladder (equivalent to "
        "DSDDMM_WATCHDOG)",
    )
    p.add_argument(
        "--flightrec", nargs="?", const="1", default=None, metavar="DIR",
        help="arm the anomaly flight recorder: the tracer keeps an "
        "in-memory ring of recent spans, and every watchdog anomaly "
        "dumps it (plus metrics/telemetry snapshots, plus a short "
        "jax.profiler window when --profile is also armed) to "
        "artifacts/flightrec/<run_id>/; DIR relocates (equivalent to "
        "DSDDMM_FLIGHTREC)",
    )
    p.add_argument(
        "--no-runstore", action="store_true",
        help="do not persist this run into the run store "
        "(artifacts/runstore); DSDDMM_RUNSTORE relocates or disables it",
    )


def build_parser() -> argparse.ArgumentParser:
    """The CLI's argument parser (importable for parse-only validation,
    e.g. the pod runner's --dry-run)."""
    ap = argparse.ArgumentParser(prog="distributed_sddmm_tpu.bench", description=__doc__)
    sub = ap.add_subparsers(dest="cmd", required=True)

    er = sub.add_parser("er", help="synthetic R-mat benchmark")
    er.add_argument("log_m", type=int, help="log2 of matrix side")
    er.add_argument("edge_factor", type=int, help="average nnz per row")
    er.add_argument("alg", help="algorithm name or group (15d | 25d | all)")
    er.add_argument("R", type=int)
    er.add_argument("c", type=int)
    _add_common(er)

    fi = sub.add_parser("file", help="matrix-market file benchmark")
    fi.add_argument("path", help=".mtx file")
    fi.add_argument("alg")
    fi.add_argument("R", type=int)
    fi.add_argument("c", type=int)
    fi.add_argument("--permute", action="store_true", help="random row/col permutation first")
    _add_common(fi)

    hm = sub.add_parser("heatmap", help="R-value sweep on one synthetic matrix")
    hm.add_argument("log_m", type=int)
    hm.add_argument("edge_factor", type=int)
    hm.add_argument("c", type=int)
    hm.add_argument("--alg", default="all")
    hm.add_argument("--r-values", type=int, nargs="+", default=HEATMAP_R_VALUES)
    _add_common(hm)
    hm.set_defaults(R=None)

    pm = sub.add_parser("permute", help="randomly permute a .mtx file")
    pm.add_argument("path")
    pm.add_argument("--seed", type=int, default=0)
    pm.add_argument("-o", "--output-file", default=None, help="default <in>-permuted.mtx")

    kn = sub.add_parser("kernels", help="single-device local-kernel sweep")
    kn.add_argument("--log-m", type=int, nargs="+", default=None)
    kn.add_argument("--nnz-per-row", type=int, nargs="+", default=None)
    kn.add_argument("--r-values", type=int, nargs="+", default=None)
    kn.add_argument("--kernels", nargs="+", default=["xla", "pallas"])
    kn.add_argument("--trials", type=int, default=5)
    kn.add_argument("-o", "--output-file", default=None)

    ov = sub.add_parser("overlap", help="comm/compute overlap experiment")
    ov.add_argument("--block", type=int, default=1024)
    ov.add_argument("--steps-work", type=int, default=4)
    ov.add_argument("--trials", type=int, default=10)
    ov.add_argument(
        "--hlo-topology", default=None, metavar="NAME",
        help="also AOT-compile for this TPU topology (e.g. v5e:2x4) and "
        "report the structural start/compute/done overlap evidence",
    )
    ov.add_argument(
        "--fusion-hlo", default=None, metavar="TOPOLOGY", nargs="?",
        const="v5e:2x4",
        help="AOT-compile the 1.5D dense-shift fused program (with "
        "--fusion overlap's double-buffered build) for a TPU topology "
        "and report whether collective-permute-start/done bracket the "
        "per-step local kernel — the --fusion overlap structural gate "
        "(set TPU_SKIP_MDS_QUERY=1 on machines without TPU metadata)",
    )
    ov.add_argument(
        "--fusion-mode", default="overlap",
        choices=["overlap", "sequential"],
        help="which ring-loop build --fusion-hlo compiles (default "
        "overlap; 'sequential' probes the baseline build for comparison)",
    )
    ov.add_argument("-o", "--output-file", default=None)

    bl = sub.add_parser("baseline", help="external host-CPU SpMM baseline")
    bl.add_argument("log_m", type=int)
    bl.add_argument("edge_factor", type=int)
    bl.add_argument("R", type=int)
    bl.add_argument("--iters", type=int, default=10)
    bl.add_argument("-o", "--output-file", default=None)

    sv = sub.add_parser(
        "serve",
        help="online serving load test: warm engine (autotune-planned "
        "strategy), dynamic micro-batching, open-loop Poisson arrivals, "
        "SLO-gated latency report (serve/); the record persists to the "
        "run store so `bench gate` regresses p99/shed-rate",
    )
    sv.add_argument("--app", default="als",
                    choices=["als", "gat", "attention"])
    sv.add_argument(
        "--mask", default="window:16", metavar="SPEC",
        help="with --app attention: block-sparse mask family for the "
        "warm context (window:<w> | bigbird:w=..,g=..,r=.. | graph — "
        "graph uses the generated R-mat's pattern)",
    )
    sv.add_argument(
        "--window", type=int, default=None, metavar="W",
        help="with --app attention: per-request sliding-window "
        "half-width (default DSDDMM_ATTN_SERVE_WINDOW)",
    )
    sv.add_argument("--log-m", type=int, default=8, help="log2 matrix side")
    sv.add_argument("--edge-factor", type=int, default=8)
    sv.add_argument("--R", type=int, default=16)
    sv.add_argument("--duration", type=float, default=10.0,
                    metavar="SECONDS", help="load-generation window")
    sv.add_argument("--rate", type=float, default=30.0, metavar="HZ",
                    help="offered Poisson arrival rate (requests/s)")
    sv.add_argument("--max-batch", type=int, default=8)
    sv.add_argument("--max-depth", type=int, default=64,
                    help="admission bound; beyond it requests shed")
    sv.add_argument("--max-wait-ms", type=float, default=5.0,
                    help="micro-batch linger after the first arrival")
    sv.add_argument("--k", type=int, default=10, help="ALS top-k size")
    sv.add_argument("--train-steps", type=int, default=2,
                    help="ALS warm-model alternating steps before serving")
    sv.add_argument("--seed", type=int, default=0)
    sv.add_argument("--oracle-every", type=int, default=8,
                    help="oracle-check every Nth request (0 disables)")
    sv.add_argument(
        "--slo", default=None, metavar="SPEC",
        help="SLO spec 'p99_ms=250,err_rate=0.01' (default DSDDMM_SLO); "
        "violations exit 2",
    )
    sv.add_argument(
        "--plan-mode", default="model", choices=["model", "auto", "measure"],
    )
    sv.add_argument("-o", "--output-file", default=None,
                    help="append the JSON record here")
    sv.add_argument(
        "--faults", default=None, metavar="SPEC",
        help="fault plan: JSON spec, @path, or the comma shorthand "
        "('delay,nan' expands to probabilistic faults at the execute/"
        "output sites); the engine must shed/degrade, never crash",
    )
    sv.add_argument("--trace", nargs="?", const="1", default=None,
                    metavar="PATH")
    sv.add_argument(
        "--telemetry", nargs="?", const="1", default=None, metavar="DIR",
        help="sample live telemetry (queue depth, latency histogram, "
        "shed/degrade, program-store hits, SLO burn rate) to "
        "artifacts/telemetry/<run_id>.jsonl every --telemetry-interval "
        "seconds; DIR relocates (equivalent to DSDDMM_TELEMETRY); "
        "watch it live with `bench top`",
    )
    sv.add_argument("--telemetry-interval", type=float, default=0.5,
                    metavar="SECONDS")
    sv.add_argument("--profile", default=None, metavar="LOGDIR")
    sv.add_argument("--watchdog", default=None, choices=["warn", "strict"])
    sv.add_argument(
        "--admin-port", type=int, default=None, metavar="PORT",
        help="serve the live operational surface on 127.0.0.1:PORT "
        "(0 = ephemeral): Prometheus /metrics, /healthz + /readyz "
        "(503 once the SLO error budget burns past 1x), "
        "/debug/requests recent-timeline ring, /snapshot for "
        "`bench top --admin-port` (obs/httpexp.py); the record gains "
        "an admin_port field",
    )
    sv.add_argument(
        "--flightrec", nargs="?", const="1", default=None, metavar="DIR",
        help="arm the anomaly flight recorder (see the offline "
        "subcommands' --flightrec; equivalent to DSDDMM_FLIGHTREC)",
    )
    sv.add_argument(
        "--tuner", action="store_true",
        help="run the background closed-loop tuner against the live "
        "engine (tuner/): mine trigger gauges, re-measure candidates "
        "off the request path, shadow-validate and hot-swap a winning "
        "kernel variant mid-load; the record gains tuner/"
        "time_to_adapt_s fields and `bench gate` regresses the new "
        "tuner:time_to_adapt axis (equivalent to DSDDMM_TUNER; "
        "DSDDMM_TUNER_* knobs pace it)",
    )
    sv.add_argument("--no-runstore", action="store_true")
    sv.add_argument(
        "--serve-http", action="store_true",
        help="replica mode: instead of generating load, accept requests "
        "over the admin server's POST /submit until SIGTERM, then drain "
        "and print the serving record as the last stdout line (the "
        "fleet manager's replica contract; implies --admin-port 0 when "
        "unset)",
    )
    sv.add_argument(
        "--tenants", default=None, metavar="SPEC",
        help="multi-tenant QoS classes 'name[:weight[:slo]];...' (e.g. "
        "'premium:3:p99_ms=250;batch:1'): weighted-fair dequeue across "
        "classes, per-tenant shed counters and burn-rate gate axes "
        "(default DSDDMM_TENANTS)",
    )

    fl = sub.add_parser(
        "fleet",
        help="serving-fleet harness: spawn N `bench serve --serve-http` "
        "replicas behind the front router (fleet/), drive an open-loop "
        "HTTP load with a multi-tenant mix, optionally inject a seeded "
        "chaos schedule (--chaos 'wedge:r1@0.3/1s;corrupt@0.6;kill@0.8'),"
        " and pin that replies stay bit-identical to a single-engine "
        "oracle while availability holds above --availability-floor and "
        "every gray fault is detected (breaker open / quarantine) within "
        "--detect-deadline; the record lands in the run store with "
        "fleet:availability / fleet:audit_mismatch / per-tenant "
        "serve:burn_rate gate axes",
    )
    fl.add_argument("--replicas", type=int, default=None, metavar="N",
                    help="serve-role replica count (default "
                    "DSDDMM_FLEET_REPLICAS or 2)")
    fl.add_argument("--chaos", default=None, metavar="SCHEDULE",
                    help="seeded deterministic chaos schedule "
                    "(resilience/chaos.py grammar): ';'-separated "
                    "kind[:target]@frac[/duration][:param] actions, e.g. "
                    "'kill@0.5;wedge:r1@0.3/1s;partition:r0@0.6/0.5s;"
                    "slow:r2@0.4:80ms;corrupt:r1@0.7'; 'kill-replica' "
                    "stays as sugar for 'kill@0.5' (default DSDDMM_CHAOS "
                    "or none)")
    fl.add_argument("--audit-frac", type=float, default=None,
                    metavar="FRAC",
                    help="fraction of routed requests re-executed on a "
                    "second replica and compared bit-for-bit before "
                    "delivery (default DSDDMM_FLEET_AUDIT_FRAC or 0; "
                    "chaos schedules with a corrupt action default to "
                    "1.0 so the byzantine replica cannot leak bytes)")
    fl.add_argument("--hedge", default=None, metavar="DELAY",
                    help="hedged requests: after this many seconds "
                    "without a primary reply ('on' = p95-derived), "
                    "re-submit to a second replica and take the first "
                    "answer (default DSDDMM_FLEET_HEDGE or off)")
    fl.add_argument("--detect-deadline", type=float, default=5.0,
                    metavar="SECONDS",
                    help="each injected gray fault must show its "
                    "detection signal (wedge/partition -> breaker open, "
                    "corrupt -> quarantine) within this window or the "
                    "drill exits 1")
    fl.add_argument("--app", default="als", choices=["als", "gat"])
    fl.add_argument("--log-m", type=int, default=6)
    fl.add_argument("--edge-factor", type=int, default=4)
    fl.add_argument("--R", type=int, default=8)
    fl.add_argument("--k", type=int, default=5)
    fl.add_argument("--train-steps", type=int, default=1)
    fl.add_argument("--duration", type=float, default=6.0,
                    metavar="SECONDS")
    fl.add_argument("--rate", type=float, default=20.0, metavar="HZ")
    fl.add_argument("--max-batch", type=int, default=4)
    fl.add_argument("--max-depth", type=int, default=32)
    fl.add_argument("--max-wait-ms", type=float, default=5.0)
    fl.add_argument(
        "--tenants", default="premium:3:p99_ms=2000;batch:1",
        metavar="SPEC",
        help="tenant mix for the generated load (same grammar as serve "
        "--tenants)",
    )
    fl.add_argument("--slo", default=None, metavar="SPEC")
    fl.add_argument("--availability-floor", type=float, default=0.95,
                    help="minimum (answered + shed-with-retry)/offered "
                    "fraction; below it the harness exits 3")
    fl.add_argument("--seed", type=int, default=0)
    fl.add_argument("--ready-timeout", type=float, default=300.0,
                    metavar="SECONDS",
                    help="warmup budget for the replica pool")
    fl.add_argument("-o", "--output-file", default=None)
    fl.add_argument("--no-runstore", action="store_true")

    tn = sub.add_parser(
        "tune",
        help="offline closed-loop re-tune of one problem: mine the "
        "runstore's realized history for the fingerprint, re-rank and "
        "re-measure candidates (full plan space — algorithm and c "
        "included, unlike the live tuner's hot-swappable subset), and "
        "store the winner into the plan cache for the next warmup "
        "(tuner/retune.py)",
    )
    tn.add_argument("log_m", type=int, help="log2 of matrix side")
    tn.add_argument("edge_factor", type=int, help="average nnz per row")
    tn.add_argument("R", type=int)
    tn.add_argument(
        "--trial", default="auto", choices=["auto", "counted", "wall"],
        help="trial mode: wall-clock harness runs, deterministic "
        "counted padded-lane trials, or auto (wall on TPU else counted)",
    )
    tn.add_argument("--trials", type=int, default=1)
    tn.add_argument("--timeout", type=float, default=60.0,
                    help="per-trial wall-clock cap in seconds")
    tn.add_argument("--budget", type=float, default=120.0,
                    help="whole-retune elapsed cap in seconds")
    tn.add_argument("--top-k", type=int, default=3)
    tn.add_argument(
        "--dry-run", action="store_true",
        help="report the challenger without writing the plan cache",
    )
    tn.add_argument("--json", action="store_true")
    tn.add_argument(
        "--store", default=None, metavar="DIR",
        help="run-store root mined for realized history (default "
        "artifacts/runstore, or DSDDMM_RUNSTORE)",
    )

    vf = sub.add_parser("verify", help="fingerprint cross-check of algorithms")
    vf.add_argument("--log-m", type=int, default=8)
    vf.add_argument("--edge-factor", type=int, default=8)
    vf.add_argument("--R", type=int, default=16)
    vf.add_argument("--c", type=int, default=1)
    vf.add_argument("--alg", default="all")
    vf.add_argument("--kernel", default="xla")

    rt = sub.add_parser(
        "report-trace",
        help="aggregate a JSONL trace into a per-phase table + comm-volume"
        " vs cost-model comparison (tools/tracereport.py); exits nonzero "
        "on schema violations unless --no-strict",
    )
    rt.add_argument("trace", help="path to a <run_id>.jsonl trace")
    rt.add_argument("--json", action="store_true")
    rt.add_argument("--no-strict", action="store_true")

    tm = sub.add_parser(
        "trace-merge",
        help="offset-align and merge per-process trace shards into one "
        "schema-valid trace (each shard's begin record carries its "
        "perf_counter<->wall-clock origin; the earliest becomes the "
        "merged timeline's zero); exits 2 on unmergeable/invalid shards",
    )
    tm.add_argument(
        "shards", nargs="+",
        help="shard files, shard directories, or a PATH.jsonl stem "
        "(merged with its sibling PATH.shards/ directory)",
    )
    tm.add_argument("-o", "--output-file", default=None,
                    help="default <first shard dir>/<merged id>.jsonl")
    tm.add_argument("--no-strict", action="store_true",
                    help="tolerate (and drop) malformed shard lines")

    te = sub.add_parser(
        "trace-export",
        help="convert a schema-valid trace (merged multi-shard traces "
        "included) to Chrome trace-event JSON openable in Perfetto / "
        "chrome://tracing: one process lane per shard, one thread lane "
        "per thread, spans as B/E pairs on the calibrated clock, and "
        "flow arrows stitching each request's enqueue->batch->reply "
        "chain across threads; exits 2 on an invalid trace",
    )
    te.add_argument("trace", help="path to a <run_id>.jsonl trace")
    te.add_argument("-o", "--output-file", default=None,
                    help="default <trace stem>.chrome.json")
    te.add_argument("--no-strict", action="store_true",
                    help="tolerate (and drop) malformed trace lines")

    tp = sub.add_parser(
        "top",
        help="live serving telemetry view: queue depth, histogram "
        "percentiles, shed/degrade counters, program-store hit rates, "
        "SLO burn rate — over the sampler stream `bench serve "
        "--telemetry` writes to artifacts/telemetry/, or live off a "
        "`bench serve --admin-port` endpoint",
    )
    tp.add_argument(
        "path", nargs="?", default=None,
        help="telemetry .jsonl stream (default: the newest one under "
        "artifacts/telemetry/ or $DSDDMM_TELEMETRY); a missing "
        "explicit path exits 2",
    )
    tp.add_argument(
        "--watch", type=float, default=0.0, metavar="SECONDS",
        help="refresh every N seconds until interrupted (0 = one shot)",
    )
    tp.add_argument(
        "--admin-port", type=int, default=None, metavar="PORT",
        help="read the live /snapshot endpoint of a `bench serve "
        "--admin-port` engine instead of a telemetry file; falls back "
        "to the telemetry stream when the endpoint is unreachable",
    )
    tp.add_argument("--admin-host", default="127.0.0.1", metavar="HOST")
    tp.add_argument(
        "--serve", type=int, default=None, metavar="PORT", dest="serve_port",
        help="standalone exporter: serve Prometheus /metrics (+ "
        "/snapshot, /healthz, /readyz) rendered from the telemetry "
        "stream on 127.0.0.1:PORT (0 = ephemeral) until interrupted — "
        "the admin surface for runs that only wrote --telemetry",
    )

    from distributed_sddmm_tpu.analysis import cli as analysis_cli

    analysis_cli.build_lint_parser(sub.add_parser(
        "lint",
        help="repo-wide invariant analyzer: the six discipline "
        "checkers over the package (analysis/); exit 0 clean, 2 new "
        "findings, 3 usage error",
    ))
    analysis_cli.build_env_parser(sub.add_parser(
        "env",
        help="the DSDDMM_* env-knob registry table (utils/envreg.py); "
        "--markdown regenerates the README block",
    ))

    def _store_arg(p):
        p.add_argument(
            "--store", default=None, metavar="DIR",
            help="run-store root (default artifacts/runstore, or "
            "DSDDMM_RUNSTORE)",
        )

    hi = sub.add_parser("history", help="list the persistent run store")
    _store_arg(hi)
    hi.add_argument("--key", default=None,
                    help="filter to one fingerprint key")
    hi.add_argument("--backend", default=None)
    hi.add_argument("--limit", type=int, default=None, metavar="N",
                    help="newest N runs")
    hi.add_argument("--json", action="store_true")

    cp = sub.add_parser(
        "compare",
        help="per-phase delta table between two stored runs "
        "(run ids, unique prefixes, or latest / latest~N)",
    )
    _store_arg(cp)
    cp.add_argument("run_a")
    cp.add_argument("run_b")
    cp.add_argument("--threshold", type=float, default=0.15,
                    help="relative noise band (default 0.15)")
    cp.add_argument("--json", action="store_true")

    ga = sub.add_parser(
        "gate",
        help="CI regression gate: compare a run against a rolling "
        "baseline of the last K matching runs; exit 0 pass, 2 "
        "regression, 3 insufficient baseline data",
    )
    _store_arg(ga)
    ga.add_argument("run", help="run id / prefix / latest[~N] to judge")
    ga.add_argument("--against", default=None, metavar="RUN",
                    help="explicit baseline run instead of the rolling "
                    "baseline")
    ga.add_argument("--last", type=int, default=5, metavar="K",
                    help="rolling-baseline population (default 5)")
    ga.add_argument("--min-runs", type=int, default=1,
                    help="fewer matching baseline runs than this exits 3")
    ga.add_argument("--threshold", type=float, default=0.15)
    ga.add_argument("--json", action="store_true")

    bf = sub.add_parser(
        "backfill",
        help="ingest the committed historical records (BENCH_r0*.json, "
        "MULTICHIP_r0*.json, artifacts/bench_midround) into the run store",
    )
    _store_arg(bf)
    bf.add_argument("--root", default=None, metavar="DIR",
                    help="repo root to scan (default: this checkout)")

    rh = sub.add_parser(
        "report-html",
        help="self-contained HTML dashboard: run history, per-phase "
        "trends, latest compare",
    )
    _store_arg(rh)
    rh.add_argument("-o", "--output-file", default=None,
                    help="default <store>/report.html")
    rh.add_argument("--limit", type=int, default=100)
    rh.add_argument("--key", default=None,
                    help="focus fingerprint key for trends/compare "
                    "(default: the newest run's)")
    rh.add_argument("--threshold", type=float, default=0.15)
    return ap


def _run_store(args):
    from distributed_sddmm_tpu.obs import store as obs_store

    if getattr(args, "store", None):
        return obs_store.RunStore(args.store)
    return obs_store.active() or obs_store.RunStore()


def _resolve_run(store, spec: str):
    try:
        doc = store.resolve(spec)
    except ValueError as e:  # ambiguous prefix — say so, with candidates
        raise SystemExit(str(e))
    if doc is None:
        raise SystemExit(
            f"no stored run matches {spec!r} (try 'history'; specs are "
            "run ids, unique prefixes, or latest / latest~N)"
        )
    return doc


def _dispatch_store(args) -> int:
    """The run-store subcommands (no benchmark execution, no backend)."""
    from distributed_sddmm_tpu.obs import regress

    store = _run_store(args)

    if args.cmd == "history":
        rows = store.history(
            key=args.key, backend=args.backend, limit=args.limit
        )
        if args.json:
            print(json.dumps(rows, indent=1))
        else:
            print(regress.render_history(rows))  # cli-output
        return 0

    if args.cmd == "compare":
        a = _resolve_run(store, args.run_a)
        b = _resolve_run(store, args.run_b)
        report = regress.compare(b, doc_a=a, threshold=args.threshold)
        if args.json:
            print(json.dumps(report, indent=1))
        else:
            print(regress.render_compare(report))  # cli-output
        return 0

    if args.cmd == "gate":
        doc = _resolve_run(store, args.run)
        baseline = (
            _resolve_run(store, args.against) if args.against else None
        )
        code, report = regress.gate(
            store, doc, k=args.last, threshold=args.threshold,
            min_runs=args.min_runs, baseline_doc=baseline,
        )
        if args.json:
            print(json.dumps(report, indent=1))
        else:
            if report.get("phases"):
                print(regress.render_compare(report))  # cli-output
            print(f"gate: {report['verdict']} (exit {code})")  # cli-output
        return code

    if args.cmd == "backfill":
        from distributed_sddmm_tpu.obs.store import backfill_historical

        docs = backfill_historical(store, root=args.root)
        print(  # cli-output
            f"backfilled {len(docs)} historical record(s) into {store.root}"
        )
        for d in docs:
            print(f"  {d['run_id']:<32} <- {d.get('source')}")  # cli-output
        return 0

    if args.cmd == "report-html":
        from distributed_sddmm_tpu.obs import report as obs_report

        path = obs_report.build_html(
            store, out_path=args.output_file, limit=args.limit,
            key=args.key, threshold=args.threshold,
        )
        print(f"wrote {path}")  # cli-output
        return 0

    raise AssertionError(args.cmd)


#: Subcommands that execute benchmarks and therefore feed the run store.
_BENCH_CMDS = ("er", "file", "heatmap", "serve", "fleet")


def main(argv=None) -> int:
    args = build_parser().parse_args(argv)

    if args.cmd in ("lint", "env"):
        from distributed_sddmm_tpu.analysis import cli as analysis_cli

        return (analysis_cli.run_lint(args) if args.cmd == "lint"
                else analysis_cli.run_env(args))

    if args.cmd == "report-trace":
        from distributed_sddmm_tpu.tools import tracereport

        sub_argv = [args.trace]
        if args.json:
            sub_argv.append("--json")
        if args.no_strict:
            sub_argv.append("--no-strict")
        return tracereport.main(sub_argv)

    if args.cmd == "trace-merge":
        return _dispatch_trace_merge(args)

    if args.cmd == "trace-export":
        return _dispatch_trace_export(args)

    if args.cmd == "top":
        return _dispatch_top(args)

    if args.cmd in ("history", "compare", "gate", "backfill", "report-html"):
        return _dispatch_store(args)

    if args.cmd == "tune":
        return _dispatch_tune(args)

    if getattr(args, "watchdog", None):
        from distributed_sddmm_tpu.obs import watchdog as obs_watchdog

        obs_watchdog.enable(args.watchdog)
        print(f"[watchdog] {args.watchdog} mode", file=sys.stderr)

    if args.cmd in _BENCH_CMDS:
        from distributed_sddmm_tpu.obs import store as obs_store

        if getattr(args, "no_runstore", False):
            # Explicit opt-out must beat the env var: the harness's
            # store.active() would otherwise self-activate from a
            # non-empty DSDDMM_RUNSTORE despite the flag.
            obs_store.disable()
        else:
            # Records persist into the store automatically;
            # DSDDMM_RUNSTORE can relocate (a path) or veto (0/off)
            # this default — one grammar, shared with store.active().
            enabled, root = obs_store.parse_env_spec(
                os.environ.get("DSDDMM_RUNSTORE")
            )
            if enabled:
                obs_store.enable(root)

    if getattr(args, "faults", None):
        from distributed_sddmm_tpu.resilience import FaultPlan, faults

        faults.install(FaultPlan.from_spec(args.faults))
        print("[faults] plan installed from --faults", file=sys.stderr)

    if getattr(args, "trace", None):
        from distributed_sddmm_tpu.obs import trace as obs_trace

        tr = obs_trace.enable(None if args.trace == "1" else args.trace)
        print(f"[trace] writing {tr.path}", file=sys.stderr)

    flightrec_armed = bool(getattr(args, "flightrec", None))
    if flightrec_armed:
        # AFTER --trace: the ring must tap the file tracer when both
        # are armed, not install a memory-only one first.
        from distributed_sddmm_tpu.obs import flightrec as obs_flightrec

        fr = obs_flightrec.enable(
            None if args.flightrec == "1" else args.flightrec,
            profile_window_s=0.25 if getattr(args, "profile", None) else 0.0,
        )
        print(f"[flightrec] armed -> {fr.out_dir}", file=sys.stderr)

    if getattr(args, "profile", None):
        if flightrec_armed:
            # jax.profiler supports one capture at a time: a whole-run
            # capture would make every anomaly window refuse. With the
            # flight recorder armed, --profile means per-anomaly
            # capture windows (dumped next to each flight record), not
            # a whole-run trace.
            print("[profile] flight recorder armed: capturing short "
                  "per-anomaly windows instead of the whole run",
                  file=sys.stderr)
            return _dispatch(args)
        from distributed_sddmm_tpu.obs import profiler as obs_profiler

        with obs_profiler.capture(args.profile):
            return _dispatch(args)
    return _dispatch(args)


def _dispatch_trace_merge(args) -> int:
    """``bench trace-merge``: discover shards, offset-align, write one
    merged trace, re-validate it. Exit 0 valid, 2 unmergeable."""
    from distributed_sddmm_tpu.obs import tracemerge
    from distributed_sddmm_tpu.tools import tracereport

    strict = not args.no_strict
    paths: list = []
    try:
        for spec in args.shards:
            for p in tracemerge.discover(spec):
                if p not in paths:
                    paths.append(p)
        out, merged = tracemerge.write_merged(
            paths, args.output_file, strict=strict
        )
        # Round-trip: the merged file must satisfy the same reader
        # contract any single-process trace does.
        tracereport.load_trace(out, strict=True)
    except (FileNotFoundError, ValueError) as e:
        print(f"trace-merge failed: {e}", file=sys.stderr)
        return 2
    print(json.dumps({
        "merged": str(out),
        "run_id": merged["begin"]["run_id"],
        "shards": len(merged["begin"]["shards"]),
        "spans": len(merged["spans"]),
        "events": len(merged["events"]),
        "skipped_lines": len(merged["errors"]),
    }))
    return 0


def _dispatch_trace_export(args) -> int:
    """``bench trace-export``: one schema-valid trace -> Chrome
    trace-event JSON. Exit 0 written, 2 invalid/unreadable."""
    from distributed_sddmm_tpu.obs import traceexport

    try:
        out, chrome = traceexport.write_chrome(
            args.trace, args.output_file, strict=not args.no_strict
        )
    except (OSError, ValueError) as e:
        print(f"trace-export failed: {e}", file=sys.stderr)
        return 2
    print(json.dumps({"exported": str(out), **chrome["metadata"]}))
    return 0


def _top_source(args):
    """Resolve the ``bench top`` snapshot source.

    Returns ``(read_fn, label)`` where ``read_fn()`` yields the
    snapshot list to render, or raises SystemExit(2) for an explicitly
    named telemetry file that does not exist (a one-line error, not a
    traceback)."""
    import pathlib as _pathlib

    from distributed_sddmm_tpu.obs import telemetry

    if args.admin_port is not None:
        from distributed_sddmm_tpu.obs import httpexp

        def read_live():
            snap = httpexp.fetch_json(
                args.admin_host, args.admin_port, "/snapshot"
            )
            return [snap] if snap else []

        try:
            read_live()  # probe once; unreachable -> fall back to files
            return read_live, (
                f"admin {args.admin_host}:{args.admin_port}"
            )
        except (OSError, ValueError) as e:  # incl. a non-JSON body
            print(
                f"[top] admin endpoint {args.admin_host}:"
                f"{args.admin_port} unreachable ({e}); falling back to "
                "the telemetry stream", file=sys.stderr,
            )

    path = args.path
    if path is not None:
        if not _pathlib.Path(path).exists():
            # One-line contract: a typo'd path must not scroll a
            # traceback past the operator.
            print(f"bench top: no telemetry file at {path}",
                  file=sys.stderr)
            raise SystemExit(2)
    else:
        _enabled, root = telemetry.parse_env_spec(
            os.environ.get("DSDDMM_TELEMETRY")
        )
        path = telemetry.newest_stream(root)
        if path is None:
            print("no telemetry streams found (run `bench serve "
                  "--telemetry` first)", file=sys.stderr)
            raise SystemExit(1)
    return (lambda: telemetry.read_snapshots(path)), str(path)


def _dispatch_top(args) -> int:
    """``bench top``: render the newest telemetry snapshot(s) from a
    file or a live admin endpoint; --watch refreshes until interrupted;
    --serve re-exports the stream as a /metrics endpoint."""
    import time as _time

    from distributed_sddmm_tpu.obs import telemetry

    try:
        read_fn, label = _top_source(args)
    except SystemExit as e:
        return int(e.code or 0)

    if args.serve_port is not None:
        from distributed_sddmm_tpu.obs import httpexp

        def latest():
            snaps = read_fn()
            return snaps[-1] if snaps else None

        server = httpexp.AdminServer(
            snapshot_fn=latest, port=args.serve_port
        )
        server.start()
        print(f"[top] exporting {label} on "
              f"http://127.0.0.1:{server.port}/metrics", file=sys.stderr)
        try:
            while True:
                _time.sleep(1.0)
        except KeyboardInterrupt:
            return 0
        finally:
            server.stop()

    while True:
        try:
            snaps = read_fn()
        except (OSError, ValueError) as e:
            # A live source can vanish mid-watch (serve exited, file
            # unlinked) or answer mid-shutdown garbage (truncated JSON
            # is a ValueError): one line, never a traceback. A watch
            # loop keeps polling — the endpoint may come back.
            print(f"bench top: snapshot source unavailable ({e})",
                  file=sys.stderr)
            if not args.watch:
                return 1
            snaps = []
        if args.watch:
            print("\x1b[2J\x1b[H", end="")  # clear screen between frames
        print(telemetry.render_top(snaps))
        if not args.watch:
            return 0
        try:
            _time.sleep(args.watch)
        except KeyboardInterrupt:
            return 0


def _dispatch_tune(args) -> int:
    """``bench tune``: one offline closed-loop pass for one problem.

    Loads the incumbent plan (cache hit or cost-model selection — the
    same path a warmup takes), mines the runstore's realized history
    for this fingerprint, re-ranks + re-measures the FULL candidate
    space, and stores a winning challenger back into the plan cache so
    the next replica warms straight onto it. Exit 0 either way — "the
    incumbent stands" is a successful tune."""
    from distributed_sddmm_tpu.autotune import Problem, get_plan
    from distributed_sddmm_tpu.autotune.cache import PlanCache
    from distributed_sddmm_tpu.autotune.plan import Plan
    from distributed_sddmm_tpu.tuner import TunerConfig
    # Import the tuner submodules directly (the package deliberately
    # does not re-export the retune() function — it would shadow the
    # `tuner.retune` submodule attribute).
    import distributed_sddmm_tpu.tuner.retune as tuner_retune
    import distributed_sddmm_tpu.tuner.signals as tuner_signals

    S = HostCOO.rmat(log_m=args.log_m, edge_factor=args.edge_factor, seed=0)
    problem = Problem.from_coo(S, args.R)
    if args.dry_run:
        # get_plan stores its selection on a cache miss; a dry run must
        # leave the real cache byte-untouched — serve a genuine hit,
        # else select against a throwaway cache.
        from distributed_sddmm_tpu.autotune.fingerprint import (
            machine_signature, make_fingerprint,
        )

        p_, backend_, kernels_ = machine_signature()
        hit = PlanCache().load(
            make_fingerprint(problem, p_, backend_, kernels_).key
        )
        if hit is not None:
            incumbent = Plan.from_dict(hit)
        else:
            import tempfile

            with tempfile.TemporaryDirectory() as _td:
                incumbent = get_plan(
                    problem, mode="model", cache=PlanCache(_td)
                )
    else:
        incumbent = get_plan(problem, mode="model")

    # _run_store falls back to the default root (artifacts/runstore /
    # DSDDMM_RUNSTORE) when --store is absent — the documented mining
    # source; an empty or missing store simply yields no signals.
    store = _run_store(args)
    signals = tuner_signals.mine_runstore(
        store, incumbent.fingerprint_key, problem, incumbent.predicted_ms,
    )

    # ONE trial-selection rule (TunerConfig.trial_fn): an explicit
    # --trial wall forces harness trials even off-TPU.
    trial_fn = TunerConfig(trial=args.trial).trial_fn()
    challenger = tuner_retune.retune(
        problem, incumbent, S,
        top_k=args.top_k, trials=args.trials, timeout_s=args.timeout,
        max_elapsed_s=args.budget, trial_fn=trial_fn,
    )
    promoted = False
    if challenger is not None and not args.dry_run:
        PlanCache().store(challenger.fingerprint_key, challenger.to_dict())
        promoted = True
    report = {
        "fingerprint_key": incumbent.fingerprint_key,
        "signals": [s.to_dict() for s in signals],
        "incumbent": incumbent.to_dict(),
        "challenger": challenger.to_dict() if challenger else None,
        "promoted": promoted,
        "dry_run": bool(args.dry_run),
    }
    if args.json:
        print(json.dumps(report, indent=1))
    else:
        inc, ch = incumbent, challenger
        print(  # cli-output
            f"incumbent  {inc.algorithm} c={inc.c} kernel={inc.kernel}"
            + (f" variant={inc.variant}" if inc.variant else "")
            + f" [{inc.source}]"
        )
        if ch is None:
            print("challenger none — incumbent stands")  # cli-output
        else:
            print(  # cli-output
                f"challenger {ch.algorithm} c={ch.c} kernel={ch.kernel}"
                + (f" variant={ch.variant}" if ch.variant else "")
                + f" measured={ch.measured_gflops:.3f} GFLOP/s"
                + (" -> plan cache" if promoted else " (dry run)")
            )
    return 0


def _dispatch_serve(args) -> int:
    """``bench serve``: build a warm engine, drive it open-loop, report
    + persist the serving record. Exit 0 on a clean run, 1 on any
    incorrect (oracle-mismatched) reply, 2 on SLO violation — faults
    and shedding are expected operating conditions, not failures."""
    from distributed_sddmm_tpu.obs import trace as obs_trace
    from distributed_sddmm_tpu.obs import watchdog as obs_watchdog
    from distributed_sddmm_tpu.resilience import faults
    from distributed_sddmm_tpu.serve import (
        SLOSpec, build_als_engine, build_attention_engine,
        build_gat_engine, parse_tenants, run_load, tenants_from_env,
    )

    S = HostCOO.rmat(log_m=args.log_m, edge_factor=args.edge_factor, seed=0)
    if args.app == "attention":
        S = _maybe_mask(S, args)
    slo = SLOSpec.parse(args.slo) if args.slo else SLOSpec.from_env()
    tenants = (parse_tenants(args.tenants) if args.tenants
               else tenants_from_env())
    serve_http = bool(getattr(args, "serve_http", False))
    if serve_http and args.admin_port is None:
        args.admin_port = 0  # replica mode NEEDS the ingestion surface
    engine_kw = dict(
        max_batch=args.max_batch, max_depth=args.max_depth,
        max_wait_ms=args.max_wait_ms, tenants=tenants,
    )
    # XLA-cost cursor: warmup + serving programs resolved from here on
    # feed the record's analytic-vs-XLA cross-check.
    from distributed_sddmm_tpu import programs as programs_mod

    _cost_cursor = programs_mod.cost_log_len()
    print(f"[serve] building warm {args.app} engine "
          f"(2^{args.log_m} matrix, R={args.R})", file=sys.stderr)
    if args.app == "als":
        eng = build_als_engine(
            S, R=args.R, train_steps=args.train_steps, k=args.k,
            plan_mode=args.plan_mode, **engine_kw,
        )
    elif args.app == "attention":
        eng = build_attention_engine(
            S, R=args.R, window=args.window, plan_mode=args.plan_mode,
            seed=args.seed, **engine_kw,
        )
    else:
        eng = build_gat_engine(
            S, R=args.R, plan_mode=args.plan_mode, **engine_kw,
        )
    model = eng.workload.model
    d_ops = model.d_ops
    plan = getattr(model, "plan", None)

    # Same cursor discipline as benchmark_algorithm: the record carries
    # only the faults/anomalies of the SERVING window, not warmup's.
    _fault_plan = faults.active()
    _events_before = len(_fault_plan.events) if _fault_plan else 0
    _watchdog = obs_watchdog.active()
    _anomalies_before = len(_watchdog.events) if _watchdog else 0
    d_ops.reset_performance_timers()

    # Live telemetry: a sampler thread snapshotting the engine to
    # artifacts/telemetry/<run_id>.jsonl for `bench top` and post-hoc
    # burn-rate forensics (--telemetry / DSDDMM_TELEMETRY).
    from distributed_sddmm_tpu.obs import telemetry as obs_telemetry

    sampler = None
    tel_spec = args.telemetry or os.environ.get("DSDDMM_TELEMETRY")
    tel_enabled, tel_root = obs_telemetry.parse_env_spec(tel_spec)
    if tel_enabled:
        sampler = obs_telemetry.TelemetrySampler(
            eng, interval_s=args.telemetry_interval, out_dir=tel_root,
            slo=slo,
        )

    # Live operational surface (obs/httpexp.py): started BEFORE warmup
    # so /readyz honestly reports not-ready while the ladder compiles.
    admin = None
    if args.admin_port is not None:
        from distributed_sddmm_tpu.obs import httpexp

        submit_fn = None
        if serve_http:
            def submit_fn(payload, tenant="default", serial=False,
                          timeout_s=30.0, trace_ctx=None):
                # Wire decode is the workload's own clamp (np.asarray
                # normalizes the JSON lists back to the exact dtypes),
                # so an HTTP-submitted payload takes the IDENTICAL
                # path an in-process one does — bit-identical replies.
                if serial:
                    return eng.workload.serial(eng.workload.clamp(payload))
                req = eng.submit(payload, tenant=tenant,
                                 trace_ctx=trace_ctx)
                # Reply accounting is the CLIENT's job (run_load does it
                # in-process); over HTTP that client is this boundary —
                # without it a replica's drained record reads 0 completed
                # and the fleet's per-tenant burn axes go dark.
                try:
                    reply = req.result(timeout_s=timeout_s)
                except Exception:
                    eng.recorder.record_error(tenant)
                    raise
                eng.recorder.record_reply(req)
                return reply

        chaos_fn = None
        if serve_http:
            def chaos_fn(body):
                # Runtime chaos arming (resilience/chaos.ChaosEngine's
                # corrupt action): install a fault plan in THIS running
                # process — env knobs cannot change after spawn. The
                # drill sets guard_mode=repair so a NaN-corrupted reply
                # is repaired to finite-but-WRONG bytes that only the
                # router's cross-replica audit can catch (raise-mode
                # would degrade to the serial oracle and recompute the
                # right answer, defeating the byzantine scenario).
                from distributed_sddmm_tpu.resilience import (
                    faults as res_faults,
                )

                spec = body.get("faults")
                if not isinstance(spec, (dict, list, str)):
                    raise ValueError("body.faults must be a plan spec")
                plan = res_faults.FaultPlan.from_spec(spec)
                res_faults.install(plan)
                mode = body.get("guard_mode")
                if mode is not None:
                    if mode not in ("raise", "repair"):
                        raise ValueError(f"bad guard_mode: {mode!r}")
                    os.environ["DSDDMM_GUARD_MODE"] = str(mode)
                return {"armed": True, "specs": len(plan.specs),
                        "seed": plan.seed, "guard_mode": mode}

        admin = httpexp.AdminServer(
            engine=eng, op_metrics=d_ops.metrics, slo=slo,
            port=args.admin_port, submit_fn=submit_fn,
            chaos_fn=chaos_fn,
        )
        admin.start()
        print(f"[admin] serving http://127.0.0.1:{admin.port} "
              "(/metrics /healthz /readyz /debug/requests /snapshot"
              + (" POST:/submit" if submit_fn else "") + ")",
              file=sys.stderr)

    # An armed flight recorder gets the engine's telemetry snapshot as
    # a dump source — an anomaly record then carries the queue/latency
    # state of the moment it fired.
    from distributed_sddmm_tpu.obs import flightrec as obs_flightrec

    _fr = obs_flightrec.active()
    if _fr is not None:
        _fr.register_source(
            "engine", lambda: obs_telemetry.engine_snapshot(eng, slo=slo)
        )

    # Closed-loop background tuner (--tuner / DSDDMM_TUNER): started
    # once the ladder is warm, paced by the DSDDMM_TUNER_* knobs.
    tuner = None
    tuner_wanted = args.tuner or os.environ.get(
        "DSDDMM_TUNER", ""
    ).lower() in ("1", "on", "true", "yes")
    try:
        eng.start()  # compile-ahead warmup of the whole bucket ladder
        if tuner_wanted:
            from distributed_sddmm_tpu.tuner import BackgroundTuner

            tuner = BackgroundTuner(eng).start()
            print("[tuner] background tuner armed "
                  f"(interval {tuner.config.interval_s}s)", file=sys.stderr)
        if sampler is not None:
            sampler.start()
            print(f"[telemetry] sampling to {sampler.path}",
                  file=sys.stderr)
        if serve_http:
            summary = _serve_until_signal(eng, slo, tenants)
        else:
            summary = run_load(
                eng, duration_s=args.duration, rate_hz=args.rate,
                seed=args.seed, oracle_every=args.oracle_every, slo=slo,
                tenants=tenants,
            )
    finally:
        if tuner is not None:
            tuner.stop()
        if sampler is not None:
            sampler.stop()
        eng.stop()
        if admin is not None:
            admin.stop()

    record = {
        "app": f"serve-{args.app}",
        "algorithm": plan.algorithm if plan else d_ops.algorithm_name,
        "mask": args.mask if args.app == "attention" else None,
        "R": args.R,
        "c": plan.c if plan else d_ops.c,
        "fused": True,
        "kernel": getattr(d_ops.kernel, "name", type(d_ops.kernel).__name__),
        "kernel_variant": eng.workload.kernel_variant,
        # Pod identity (runstore index + gate config axis) — serving
        # records must split across pod shapes like offline ones.
        **harness.pod_record_fields(),
        "num_trials": summary["completed"],
        "elapsed": summary["duration_s"],
        "overall_throughput": None,
        "alg_info": d_ops.json_algorithm_info(),
        "metrics": d_ops.metrics.to_dict(),
        "engine": eng.stats(),
        "serve_config": {
            "rate_hz": args.rate, "duration_s": args.duration,
            "max_batch": args.max_batch, "max_depth": args.max_depth,
            "max_wait_ms": args.max_wait_ms,
            "batch_buckets": list(eng.batch_buckets),
            "inner_buckets": list(eng.workload.inner_buckets),
            "tenants": args.tenants or os.environ.get("DSDDMM_TENANTS"),
            "serve_http": serve_http,
        },
        **summary,
    }
    if plan is not None:
        record["plan"] = plan.to_dict()
    if tuner is not None:
        # The closed-loop fields (MIGRATING): the tuner summary with
        # its promotions list, and time_to_adapt_s lifted to the top
        # level — the `tuner:time_to_adapt` gate axis reads it there.
        record["tuner"] = tuner.summary()
        record["time_to_adapt_s"] = tuner.time_to_adapt_s
    if sampler is not None:
        record["telemetry_path"] = str(sampler.path)
    if admin is not None:
        record["admin_port"] = admin.port
        record["admin_scrapes"] = admin.scrapes
    if _fr is not None:
        record["flightrec_dir"] = str(_fr.out_dir)
    # Analytic-vs-XLA FLOP cross-check over the engine's resolved
    # programs (strategy ops only — serve fold-in programs have no
    # analytic model to disagree with).
    _xla_cost = programs_mod.xla_cost_summary(
        record["metrics"], since=_cost_cursor
    )
    if _xla_cost:
        record["xla_cost"] = _xla_cost
        if _watchdog is not None:
            _watchdog.check_xla_costs(record["metrics"], _xla_cost["ops"])
    if obs_trace.enabled():
        record["run_id"] = obs_trace.run_id()
        record["trace_path"] = obs_trace.trace_path()
        from distributed_sddmm_tpu.obs import manifest as obs_manifest

        obs_manifest.write_for_trace(obs_trace.tracer())
    if _fault_plan is not None:
        record["faults_fired"] = [
            {"site": s, "kind": k, "call": n}
            for s, k, n in _fault_plan.events[_events_before:]
        ]
    if _watchdog is not None:
        record["anomalies"] = _watchdog.summary(since=_anomalies_before)

    if serve_http:
        # Replica contract (fleet/manager.py): the FULL record is the
        # last stdout JSON line — the manager collects it at reap time.
        print(json.dumps(record))
    else:
        print(json.dumps({
            "app": record["app"], "algorithm": record["algorithm"],
            "requests": summary["requests"],
            "completed": summary["completed"],
            "throughput_rps": summary["throughput_rps"],
            "latency_ms": summary["latency_ms"],
            "batch_occupancy": summary.get("batch_occupancy"),
            "shed_count": summary["shed_count"],
            "degraded_count": summary["degraded_count"],
            "oracle_checked": summary["oracle_checked"],
            "oracle_failures": summary["oracle_failures"],
            "slo_violations": summary["slo_violations"],
            "burn_rate": summary.get("burn_rate"),
            "latency_hist_ms": summary.get("latency_hist_ms"),
            "tenant": summary.get("tenant"),
        }))
    if args.output_file:
        # non-atomic-ok: append-only record stream (the -o contract).
        with open(args.output_file, "a") as f:
            f.write(json.dumps(record) + "\n")

    from distributed_sddmm_tpu.obs import store as obs_store

    run_store = obs_store.active()
    if run_store is not None:
        try:
            doc = run_store.ingest_record(record, source="serve")
            print(f"[serve] runstore doc {doc['run_id']}", file=sys.stderr)
        except Exception as e:  # noqa: BLE001 — never fail the run
            print(f"[serve] runstore ingest failed: {e}", file=sys.stderr)

    if serve_http:
        # Replica mode: the record carries any violations; the fleet
        # harness (not this process's exit code) judges them — a
        # drained replica must read as a clean exit to its manager.
        return 0
    if summary["oracle_failures"]:
        return 1
    if summary["slo_violations"]:
        return 2
    return 0


def _serve_until_signal(eng, slo, tenants) -> dict:
    """Replica mode: park until SIGTERM/SIGINT, then drain the queue and
    summarize — the serving half of the record comes entirely from the
    recorder (there is no local load generator to measure throughput
    against; requests arrived over POST /submit)."""
    import signal
    import threading

    from distributed_sddmm_tpu.obs import clock
    from distributed_sddmm_tpu.serve.slo import attach_tenant_slo

    done = threading.Event()

    def _handler(signum, frame):  # noqa: ARG001 — signal API
        done.set()

    old_term = signal.signal(signal.SIGTERM, _handler)
    old_int = signal.signal(signal.SIGINT, _handler)
    print("[serve] replica mode: accepting POST /submit until SIGTERM",
          file=sys.stderr)
    t0 = clock.now()
    try:
        done.wait()
    finally:
        signal.signal(signal.SIGTERM, old_term)
        signal.signal(signal.SIGINT, old_int)
    # Drain before summarizing: admission closes, queued requests
    # finish and reach the recorder (the caller's eng.stop() is then a
    # no-op on the already-joined runner).
    eng.stop(drain=True)
    elapsed = clock.now() - t0
    summary = eng.recorder.summary()
    completed = summary["completed"]
    summary.update({
        "duration_s": round(elapsed, 3),
        "offered": eng.queue.submitted_count + summary["shed_count"],
        "submitted": eng.queue.submitted_count,
        "throughput_rps": round(completed / elapsed, 3)
        if elapsed > 0 else 0.0,
        "oracle_checked": 0,
        "oracle_failures": 0,
    })
    summary["slo"] = slo.to_dict()
    summary["slo_violations"] = slo.check(summary)
    summary["burn_rate"] = slo.burn_rate(summary)
    attach_tenant_slo(summary, tenants)
    return summary


def _dispatch_fleet(args) -> int:
    """``bench fleet``: spawn N ``bench serve --serve-http`` replicas
    behind the front router, drive an open-loop multi-tenant HTTP load,
    optionally SIGKILL a replica at the load midpoint, and judge the
    fleet the way the single-engine harness judges one engine:

    * every 200 reply must be bit-identical (post-JSON) to the
      single-engine oracle's ``execute_now`` answer for that payload;
    * a killed replica's in-flight work must be re-admitted (router
      failover) or shed WITH a Retry-After hint — never silently lost;
    * the respawned replacement must warm-start from the shared
      ProgramStore: 0 request-path live compiles;
    * every injected GRAY fault must be *detected* within
      ``--detect-deadline``: a wedge or partition by a breaker-open on
      the victim, a corrupt by a quarantine verdict;
    * availability = (answered + shed-with-retry + client-deferred) /
      offered must hold above ``--availability-floor``.

    Exit 0 clean; 1 on a wrong/lost reply, a cold respawn, or a missed
    gray-fault detection; 3 on an availability-floor breach. Sheds and
    failovers are expected operating conditions, not failures.
    """
    import dataclasses
    import threading
    import time as _time

    import numpy as np

    from distributed_sddmm_tpu import programs as programs_mod
    from distributed_sddmm_tpu.fleet import (
        FleetManager, FleetRouter, ScalerConfig,
    )
    from distributed_sddmm_tpu.obs import trace as obs_trace
    from distributed_sddmm_tpu.obs.httpexp import _json_default, post_json
    from distributed_sddmm_tpu.obs.telemetry import LatencyHistogram
    from distributed_sddmm_tpu.resilience.chaos import ChaosEngine, ChaosSchedule
    from distributed_sddmm_tpu.serve import (
        SLOSpec, build_als_engine, build_gat_engine, parse_tenants,
    )
    from distributed_sddmm_tpu.serve.slo import attach_tenant_slo

    # Fleet-wide tracing (PR 19): the global --trace already armed the
    # tracer in main(); DSDDMM_FLEET_TRACE arms it for fleet runs
    # specifically (1/on, or an explicit trace path). Either way the
    # tracer exports DSDDMM_TRACE to the replicas spawned below, so
    # every replica writes its own shard — harvested by the manager at
    # reap/quarantine time and merged into one causal tree after the
    # load window.
    fleet_trace_spec = (os.environ.get("DSDDMM_FLEET_TRACE") or "").strip()
    if (fleet_trace_spec.lower() not in ("", "0", "off", "false", "no")
            and not obs_trace.enabled()):
        _tr = obs_trace.enable(
            None if fleet_trace_spec.lower() in ("1", "on", "true", "yes")
            else fleet_trace_spec
        )
        print(f"[fleet] tracing -> {_tr.path}", file=sys.stderr)

    n_replicas = (
        args.replicas if args.replicas is not None
        else int(os.environ.get("DSDDMM_FLEET_REPLICAS") or "2")
    )
    chaos_spec = (args.chaos if args.chaos is not None
                  else os.environ.get("DSDDMM_CHAOS") or "")
    schedule = ChaosSchedule.parse(chaos_spec, seed=args.seed)
    # A schedule with a corrupt action defaults the audit on full: the
    # drill's contract is that a byzantine replica cannot leak a single
    # wrong reply, which needs every routed request audited pre-delivery.
    has_corrupt = any(a.kind == "corrupt" for a in schedule.actions)
    audit_frac = (args.audit_frac if args.audit_frac is not None
                  else (1.0 if has_corrupt else None))
    hedge_delay = None
    if args.hedge is not None:
        from distributed_sddmm_tpu.fleet.router import DEFAULT_HEDGE_FLOOR_S

        h = args.hedge.strip().lower()
        if h in ("", "0", "off", "false", "no"):
            hedge_delay = 0.0
        elif h in ("1", "on", "true", "yes"):
            hedge_delay = DEFAULT_HEDGE_FLOOR_S
        else:
            hedge_delay = float(h)
    tenants = parse_tenants(args.tenants)
    slo = SLOSpec.parse(args.slo) if args.slo else SLOSpec.from_env()

    # The warm-start substrate: replicas inherit DSDDMM_PROGRAMS through
    # their environment, so the oracle's warmup below populates the SAME
    # store every replica (and every respawn) resolves its ladder from.
    if programs_mod.active() is None:
        import tempfile

        store_root = tempfile.mkdtemp(prefix="dsddmm-fleet-programs-")
        programs_mod.enable(store_root)
        os.environ["DSDDMM_PROGRAMS"] = store_root
        print(f"[fleet] shared program store at {store_root}",
              file=sys.stderr)

    # -- single-engine oracle (and store pre-warmer) -------------------- #
    S = HostCOO.rmat(log_m=args.log_m, edge_factor=args.edge_factor, seed=0)
    engine_kw = dict(
        max_batch=args.max_batch, max_depth=args.max_depth,
        max_wait_ms=args.max_wait_ms,
    )
    print(f"[fleet] building oracle {args.app} engine "
          f"(2^{args.log_m} matrix, R={args.R})", file=sys.stderr)
    if args.app == "als":
        oracle = build_als_engine(
            S, R=args.R, train_steps=args.train_steps, k=args.k,
            plan_mode="model", **engine_kw,
        )
    else:
        oracle = build_gat_engine(S, R=args.R, plan_mode="model",
                                  **engine_kw)
    oracle.warmup()

    # -- precomputed load plan ------------------------------------------ #
    rng_arr = np.random.default_rng(args.seed)
    gaps = rng_arr.exponential(
        1.0 / max(args.rate, 1e-9),
        size=max(1, int(args.duration * args.rate * 3)),
    )
    t_arrivals = np.cumsum(gaps)
    t_arrivals = [float(t) for t in t_arrivals[t_arrivals < args.duration]]
    rng_pay = np.random.default_rng(args.seed + 1)
    payloads = [oracle.workload.sample_payload(rng_pay) for _ in t_arrivals]
    tenant_names = sorted(tenants) if tenants else ["default"]
    if tenants:
        w = np.array([tenants[t].weight for t in tenant_names], float)
        probs = w / w.sum()
    else:
        probs = np.ones(1)
    rng_t = np.random.default_rng(args.seed + 2)
    assigned = [
        tenant_names[int(rng_t.choice(len(tenant_names), p=probs))]
        for _ in t_arrivals
    ]
    # Oracle answers, JSON-round-tripped the same way an HTTP reply is —
    # the comparison must see both sides through the identical wire
    # encoding. One payload per call: batching-determinism makes the
    # grouping irrelevant, and it sidesteps any batch-bucket clamp.
    oracle_replies = [
        json.loads(json.dumps(oracle.execute_now([p])[0],
                              default=_json_default))
        for p in payloads
    ]
    print(f"[fleet] oracle precomputed {len(oracle_replies)} replies",
          file=sys.stderr)

    # -- the fleet ------------------------------------------------------ #
    def replica_argv(name, port, role):  # noqa: ARG001 — manager contract
        argv = [
            sys.executable, "-m", "distributed_sddmm_tpu.bench", "serve",
            "--serve-http", "--admin-port", str(port), "--no-runstore",
            "--app", args.app, "--log-m", str(args.log_m),
            "--edge-factor", str(args.edge_factor), "--R", str(args.R),
            "--k", str(args.k), "--train-steps", str(args.train_steps),
            "--max-batch", str(args.max_batch),
            "--max-depth", str(args.max_depth),
            "--max-wait-ms", str(args.max_wait_ms),
            "--seed", str(args.seed), "--oracle-every", "0",
        ]
        if args.tenants:
            argv += ["--tenants", args.tenants]
        if args.slo:
            argv += ["--slo", args.slo]
        return argv

    # No live canary here: the chaos harness owns the replica count, and
    # a background tuner's CPU burn would only add latency noise to the
    # availability measurement. fleet/manager tests cover the canary.
    manager = FleetManager(replica_argv, tuner_canary=False)
    for _ in range(n_replicas):
        manager.spawn(role="serve")
    print(f"[fleet] warming {n_replicas} replicas "
          f"(budget {args.ready_timeout:.0f}s)...", file=sys.stderr)

    router = None
    chaos_engine = None
    results: list = [None] * len(t_arrivals)
    router_stats: dict = {}
    topology: dict = {}
    chaos_events: list = []
    breaker_events: list = []
    quarantine_log: list = []
    chaos_t0 = 0.0
    elapsed = 0.0
    try:
        if not manager.wait_ready(args.ready_timeout):
            print("[fleet] replica pool failed to become ready",
                  file=sys.stderr)
            return 1
        router_kw: dict = {"poll_interval_s": 0.2}
        if audit_frac is not None:
            router_kw["audit_frac"] = audit_frac
        if hedge_delay is not None:
            router_kw["hedge_delay_s"] = hedge_delay
        router = FleetRouter(manager, **router_kw).start()
        print(f"[fleet] router at http://127.0.0.1:{router.port}",
              file=sys.stderr)
        from distributed_sddmm_tpu.obs import flightrec as obs_flightrec

        _fr = obs_flightrec.active()
        if _fr is not None:
            # The router as a flight-recorder source: an anomaly dump
            # then carries the fleet topology (breaker states, depths,
            # quarantines) and routing counters of the moment it fired,
            # the same way serve dumps the engine snapshot.
            _fr.register_source("fleet", lambda: {
                "topology": router.topology(),
                "stats": dict(router.stats),
            })
        if schedule:
            chaos_engine = ChaosEngine(
                schedule, manager, router, duration_s=args.duration,
                ready_timeout_s=args.ready_timeout,
            )
            print(f"[fleet] chaos schedule: {schedule.normalized} "
                  f"(seed {schedule.seed})", file=sys.stderr)

        lock = threading.Lock()
        backoff_until = [0.0]

        def _fire(i):
            body = {"payload": payloads[i], "tenant": assigned[i],
                    "timeout_s": 30.0}
            try:
                code, decoded, headers = post_json(
                    "127.0.0.1", router.port, "/submit", body,
                    timeout_s=60.0,
                )
            except OSError as e:
                results[i] = ("error", f"{type(e).__name__}: {e}")
                return
            if code == 200:
                results[i] = ("ok", decoded.get("reply"))
            elif code == 429:
                hint = headers.get("Retry-After")
                if hint is None:
                    hint = decoded.get("retry_after_s")
                try:
                    hint_f = float(hint)
                except (TypeError, ValueError):
                    hint_f = None
                if hint_f:
                    # Honor the hint (satellite of run_load's
                    # honor_retry_after): later arrivals inside the
                    # window defer instead of piling on.
                    with lock:
                        backoff_until[0] = max(
                            backoff_until[0], _time.monotonic() + hint_f,
                        )
                results[i] = ("shed", hint_f)
            else:
                results[i] = (
                    "error", f"HTTP {code}: {decoded.get('error', decoded)}"
                )

        threads = []
        t0 = _time.monotonic()
        if chaos_engine is not None:
            # The engine's clock starts with the load clock: schedule
            # fractions are fractions of THIS load window.
            chaos_engine.start()
            chaos_t0 = chaos_engine._t0
        for i, t_arr in enumerate(t_arrivals):
            delay = t0 + t_arr - _time.monotonic()
            if delay > 0:
                _time.sleep(delay)
            with lock:
                wait = backoff_until[0] - _time.monotonic()
            if wait > 0:
                results[i] = ("deferred", round(wait, 3))
                continue
            th = threading.Thread(target=_fire, args=(i,), daemon=True)
            th.start()
            threads.append(th)
        for th in threads:
            th.join(90.0)
        elapsed = _time.monotonic() - t0
        if chaos_engine is not None:
            # Wait out any in-flight kill heal before reading verdicts:
            # the warm-respawn judgment needs the replacement's record.
            chaos_engine.close(join_timeout_s=args.ready_timeout)
            chaos_events = list(chaos_engine.events)
        router_stats = dict(router.stats)
        topology = router.topology()
        breaker_events = list(router.breaker_events)
        quarantine_log = list(manager.quarantine_log)
    finally:
        if chaos_engine is not None:
            chaos_engine.close()
        if router is not None:
            router.stop()
        manager.stop_all()

    # -- fleet trace collection + chain reconstruction ------------------ #
    # The router's own shard plus every replica shard the manager
    # harvested merge into one causally-connected trace; the chain
    # reconstruction over it is the run's trace-coverage verdict
    # (`fleet:trace_coverage` hard gate axis: every DELIVERED reply
    # must reconstruct a complete router→attempt→replica chain, the
    # winning attempt's span agreeing with the router's recorded
    # latency within 1 ms).
    trace_info = None
    if obs_trace.enabled():
        from distributed_sddmm_tpu.obs import tracemerge
        from distributed_sddmm_tpu.tools import tracereport

        try:
            shard_paths = list(dict.fromkeys(
                [str(obs_trace.trace_path())]
                + [str(s["path"]) for s in manager.trace_shards]
            ))
            # strict=False: a SIGKILLed replica can tear its final
            # shard line mid-write; the merged output is re-serialised
            # from the records that DID validate, so it stays
            # schema-valid for `report-trace`.
            merged_path, merged = tracemerge.write_merged(
                shard_paths, strict=False
            )
            chains = tracereport.fleet_request_chains(merged)
            trace_info = {
                "coverage": chains["coverage"],
                "requests": len(chains["requests"]),
                "delivered": chains["delivered"],
                "complete": chains["complete"],
                "failed": chains["failed"],
                "hedged": chains["hedged"],
                "audited": chains["audited"],
                "shards": len(merged["begin"].get("shards") or ()),
                "fleet_links": merged["begin"].get("fleet_links", 0),
                "merged_path": str(merged_path),
            }
            print(f"[fleet] merged trace {merged_path} "
                  f"({trace_info['shards']} shards, "
                  f"{trace_info['fleet_links']} cross-process links, "
                  f"coverage {trace_info['coverage']:.3f})",
                  file=sys.stderr)
        except Exception as e:  # noqa: BLE001 — tracing never fails the run
            print(f"[fleet] trace merge failed: {e}", file=sys.stderr)
            trace_info = {"error": f"{type(e).__name__}: {e}"}

    # -- judgment ------------------------------------------------------- #
    counts = {"ok": 0, "shed": 0, "deferred": 0, "error": 0, "lost": 0}
    shed_with_retry = 0
    n_mismatch = 0
    mismatch_examples = []
    client_tenant: dict[str, dict] = {}
    for i, res in enumerate(results):
        cell = client_tenant.setdefault(
            assigned[i],
            {"ok": 0, "shed": 0, "deferred": 0, "error": 0, "lost": 0},
        )
        kind = res[0] if res is not None else "lost"
        counts[kind] += 1
        cell[kind] += 1
        if kind == "shed" and res[1]:
            shed_with_retry += 1
        if kind == "ok" and res[1] != oracle_replies[i]:
            n_mismatch += 1
            if len(mismatch_examples) < 5:
                mismatch_examples.append(
                    {"request": i, "tenant": assigned[i]}
                )
    offered = len(t_arrivals)
    availability = (
        (counts["ok"] + shed_with_retry + counts["deferred"]) / offered
        if offered else 1.0
    )

    # Replacement warm-start: the replica living under the killed name
    # at stop time IS the respawn (generation >= 1); its drained record
    # carries the compile attribution.
    killed_names = [ev["target"] for ev in chaos_events
                    if ev["kind"] == "kill" and not ev.get("skipped")]
    killed_name = killed_names[0] if killed_names else None
    replacement = (manager.get(killed_name)
                   if killed_name is not None else None)
    repl_engine = ((replacement.record or {}).get("engine") or {}
                   if replacement is not None and replacement.generation >= 1
                   else {})
    repl_live_compiles = repl_engine.get("live_compiles")

    # -- gray-fault detection judge ------------------------------------- #
    # Every injected gray fault must show its detection signal within
    # --detect-deadline of firing: wedge/partition → a breaker-open on
    # the victim, corrupt → a quarantine verdict on the victim. Kill is
    # a CRASH fault (detected by construction — the connection dies);
    # slow is a latency fault the hedge absorbs rather than detects.
    detection = []
    for ev in chaos_events:
        if ev.get("skipped") or ev["kind"] not in (
                "wedge", "partition", "corrupt"):
            continue
        t_fire_abs = chaos_t0 + ev["t_s"]
        t_limit = t_fire_abs + args.detect_deadline
        if ev["kind"] in ("wedge", "partition"):
            hits = [b for b in breaker_events
                    if b["name"] == ev["target"] and b["state"] == "open"
                    and t_fire_abs <= b["t"] <= t_limit]
            signal_name = "breaker_open"
        else:
            hits = [q for q in quarantine_log
                    if q["name"] == ev["target"]
                    and t_fire_abs <= q["t"] <= t_limit]
            signal_name = "quarantine"
        detection.append({
            "kind": ev["kind"], "target": ev["target"],
            "signal": signal_name, "detected": bool(hits),
            "t_fire_s": ev["t_s"],
            "t_detect_s": (round(hits[0]["t"] - chaos_t0, 3)
                           if hits else None),
        })
    detection_ok = all(d["detected"] for d in detection)

    # -- fleet-wide + per-tenant rollups from the drained records ------- #
    fleet_hist = None
    tot = {"completed": 0, "errors": 0, "shed_count": 0}
    tenant_agg: dict[str, dict] = {}
    for rec in manager.records:
        h = LatencyHistogram.from_dict(rec.get("request_hist"))
        if h is not None:
            fleet_hist = h if fleet_hist is None else fleet_hist.merge(h)
        for k in tot:
            tot[k] += int(rec.get(k) or 0)
        for name, cell in (rec.get("tenant") or {}).items():
            a = tenant_agg.setdefault(name, {
                "requests": 0, "completed": 0, "errors": 0,
                "shed_count": 0, "_hist": None,
            })
            for k in ("requests", "completed", "errors", "shed_count"):
                a[k] += int(cell.get(k) or 0)
            th = LatencyHistogram.from_dict(cell.get("request_hist"))
            if th is not None:
                a["_hist"] = (th if a["_hist"] is None
                              else a["_hist"].merge(th))
    t_req = sum(tot.values())
    fleet_summary = {
        **tot,
        "err_rate": tot["errors"] / t_req if t_req else 0.0,
        "shed_rate": tot["shed_count"] / t_req if t_req else 0.0,
    }
    if fleet_hist is not None and fleet_hist.total:
        fleet_summary["request_hist"] = fleet_hist.to_dict()
        fleet_summary["latency_hist_ms"] = fleet_hist.percentiles_ms()
    tenant_table = {}
    for name, a in sorted(tenant_agg.items()):
        n_req = a["requests"]
        entry = {k: a[k] for k in
                 ("requests", "completed", "errors", "shed_count")}
        entry["err_rate"] = a["errors"] / n_req if n_req else 0.0
        entry["shed_rate"] = a["shed_count"] / n_req if n_req else 0.0
        if a["_hist"] is not None and a["_hist"].total:
            entry["request_hist"] = a["_hist"].to_dict()
            entry["latency_hist_ms"] = a["_hist"].percentiles_ms()
        tenant_table[name] = entry
    tenant_wrap = {"tenant": tenant_table}
    attach_tenant_slo(tenant_wrap, tenants)

    model = oracle.workload.model
    d_ops = model.d_ops
    plan = getattr(model, "plan", None)
    record = {
        "app": f"fleet-{args.app}",
        "algorithm": plan.algorithm if plan else d_ops.algorithm_name,
        "R": args.R,
        "c": plan.c if plan else d_ops.c,
        "fused": True,
        "kernel": getattr(d_ops.kernel, "name",
                          type(d_ops.kernel).__name__),
        "kernel_variant": oracle.workload.kernel_variant,
        **harness.pod_record_fields(),
        "num_trials": counts["ok"],
        "elapsed": round(elapsed, 3),
        "overall_throughput": None,
        "requests": offered,
        "throughput_rps": (round(counts["ok"] / elapsed, 3)
                           if elapsed > 0 else 0.0),
        **fleet_summary,
        "slo": slo.to_dict(),
        "slo_violations": slo.check(fleet_summary),
        "burn_rate": slo.burn_rate(fleet_summary),
        "tenant": tenant_wrap.get("tenant"),
        "fleet": {
            "replicas": n_replicas,
            "chaos": schedule.normalized,
            "chaos_seed": schedule.seed,
            "availability": round(availability, 4),
            "availability_floor": args.availability_floor,
            "offered": offered,
            "ok": counts["ok"],
            "shed_with_retry": shed_with_retry,
            "shed_no_hint": counts["shed"] - shed_with_retry,
            "deferred": counts["deferred"],
            "errors": counts["error"],
            "lost": counts["lost"],
            "oracle_checked": counts["ok"],
            "mismatches": n_mismatch,
            "mismatch_examples": mismatch_examples,
            "killed": killed_name,
            "killed_names": killed_names,
            "spawns": manager.spawns,
            "losses": manager.losses,
            "quarantines": manager.quarantines,
            "records_collected": len(manager.records),
            "replacement_live_compiles": repl_live_compiles,
            "replacement_disk_hits": repl_engine.get("disk_hits"),
            "hedges": router_stats.get("hedges", 0),
            "hedge_wins": router_stats.get("hedge_wins", 0),
            "audits": router_stats.get("audits", 0),
            "audit_mismatches": router_stats.get("audit_mismatches", 0),
            "breaker_opens": router_stats.get("breaker_opens", 0),
            "chaos_events": chaos_events,
            "breaker_events": [
                {**b, "t_s": round(b["t"] - chaos_t0, 3)}
                for b in breaker_events
            ] if chaos_t0 else breaker_events,
            "quarantine_log": [
                {**q, "t_s": round(q["t"] - chaos_t0, 3)}
                for q in quarantine_log
            ] if chaos_t0 else quarantine_log,
            "detection": detection,
            "detection_ok": detection_ok,
            "detect_deadline_s": args.detect_deadline,
            "router": router_stats,
            "topology": topology,
            "scaler_config": dataclasses.asdict(ScalerConfig.from_env()),
            "tenant_client": client_tenant,
        },
        "serve_config": {
            "rate_hz": args.rate, "duration_s": args.duration,
            "max_batch": args.max_batch, "max_depth": args.max_depth,
            "max_wait_ms": args.max_wait_ms,
            "tenants": args.tenants,
        },
    }
    if plan is not None:
        record["plan"] = plan.to_dict()
    if trace_info is not None:
        record["fleet"]["trace"] = trace_info
    if obs_trace.enabled():
        record["run_id"] = obs_trace.run_id()
        record["trace_path"] = obs_trace.trace_path()

    print(json.dumps({
        "app": record["app"],
        "replicas": n_replicas,
        "chaos": schedule.normalized,
        "offered": offered,
        "ok": counts["ok"],
        "shed_with_retry": shed_with_retry,
        "deferred": counts["deferred"],
        "errors": counts["error"],
        "lost": counts["lost"],
        "mismatches": n_mismatch,
        "availability": record["fleet"]["availability"],
        "replacement_live_compiles": repl_live_compiles,
        "quarantines": manager.quarantines,
        "audit_mismatches": router_stats.get("audit_mismatches", 0),
        "breaker_opens": router_stats.get("breaker_opens", 0),
        "hedges": router_stats.get("hedges", 0),
        "detection_ok": detection_ok,
        "burn_rate": record["burn_rate"],
        "trace_coverage": (trace_info or {}).get("coverage"),
        "router": router_stats,
    }))
    if args.output_file:
        # non-atomic-ok: append-only record stream (the -o contract).
        with open(args.output_file, "a") as f:
            f.write(json.dumps(record) + "\n")

    from distributed_sddmm_tpu.obs import store as obs_store

    run_store = obs_store.active()
    if run_store is not None:
        try:
            doc = run_store.ingest_record(record, source="fleet")
            print(f"[fleet] runstore doc {doc['run_id']}", file=sys.stderr)
        except Exception as e:  # noqa: BLE001 — never fail the run
            print(f"[fleet] runstore ingest failed: {e}", file=sys.stderr)

    if n_mismatch or counts["lost"]:
        return 1
    if killed_name is not None and (repl_live_compiles is None
                                    or repl_live_compiles > 0):
        # The respawn either never came back with a record or it
        # compiled on the request path — both break the warm-start
        # contract the fleet's capacity math depends on.
        return 1
    if not detection_ok:
        # An injected gray fault went undetected past its deadline: the
        # detectors (breaker, audit) are the thing under test here.
        print("[fleet] gray-fault detection FAILED: "
              + json.dumps(detection), file=sys.stderr)
        return 1
    if availability < args.availability_floor:
        return 3
    return 0


def _maybe_mask(S, args):
    """With ``--app attention`` the benchmark matrix IS the mask: build
    it from the --mask spec over the generated/loaded matrix's token
    count (``graph`` keeps the matrix's own pattern — the GAT adjacency
    path)."""
    if getattr(args, "app", None) != "attention":
        return S
    from distributed_sddmm_tpu import masks

    return masks.from_spec(args.mask, n=max(S.M, S.N), graph=S)


def _dispatch(args) -> int:
    if args.cmd == "serve":
        return _dispatch_serve(args)

    if args.cmd == "fleet":
        return _dispatch_fleet(args)

    if args.cmd == "er":
        S = HostCOO.rmat(log_m=args.log_m, edge_factor=args.edge_factor, seed=0)
        _run_configs(_maybe_mask(S, args), _resolve_algs(args.alg), args)
        return 0

    if args.cmd == "file":
        S = HostCOO.load_mtx(args.path)
        if args.permute:
            S = S.random_permuted(seed=0)
        _run_configs(_maybe_mask(S, args), _resolve_algs(args.alg), args)
        return 0

    if args.cmd == "heatmap":
        S = HostCOO.rmat(log_m=args.log_m, edge_factor=args.edge_factor, seed=0)
        _run_configs(_maybe_mask(S, args), _resolve_algs(args.alg), args,
                     r_values=args.r_values)
        return 0

    if args.cmd == "permute":
        out = args.output_file or args.path.replace(".mtx", "-permuted.mtx")
        S = HostCOO.load_mtx(args.path).random_permuted(seed=args.seed)
        S.save_mtx(out)
        print(f"wrote {out} ({S.M}x{S.N}, nnz={S.nnz})")
        return 0

    if args.cmd == "kernels":
        from distributed_sddmm_tpu.bench.kernels import run_kernel_benchmark

        run_kernel_benchmark(
            log_m_values=args.log_m,
            nnz_per_row_values=args.nnz_per_row,
            r_values=args.r_values,
            kernels=args.kernels,
            trials=args.trials,
            output_file=args.output_file,
        )
        return 0

    if args.cmd == "overlap":
        from distributed_sddmm_tpu.bench.overlap import (
            fusion_overlap_hlo_report, hlo_overlap_report,
            run_overlap_experiment,
        )

        rec = run_overlap_experiment(
            block=args.block, steps_work=args.steps_work, trials=args.trials,
            output_file=args.output_file,
        )
        print(json.dumps(rec))
        if args.hlo_topology:
            rec = hlo_overlap_report(
                topology_name=args.hlo_topology,
                block=args.block, steps_work=args.steps_work,
                output_file=args.output_file,
            )
            print(json.dumps(rec))
        if args.fusion_hlo:
            rec = fusion_overlap_hlo_report(
                topology_name=args.fusion_hlo,
                overlap=args.fusion_mode == "overlap",
                output_file=args.output_file,
            )
            print(json.dumps(rec))
        return 0

    if args.cmd == "baseline":
        from distributed_sddmm_tpu.bench.baseline import run_baseline

        S = HostCOO.rmat(log_m=args.log_m, edge_factor=args.edge_factor, seed=0)
        rec = run_baseline(
            S, R=args.R, iters=args.iters, output_file=args.output_file
        )
        print(json.dumps(rec))
        return 0

    if args.cmd == "verify":
        from distributed_sddmm_tpu.utils.verify import verify_algorithms

        ok = verify_algorithms(
            log_m=args.log_m,
            edge_factor=args.edge_factor,
            R=args.R,
            c=args.c,
            alg_names=_resolve_algs(args.alg),
            kernel=_get_kernel(args.kernel),
            verbose=True,
        )
        return 0 if ok else 1

    raise AssertionError(args.cmd)


if __name__ == "__main__":
    sys.exit(main())
