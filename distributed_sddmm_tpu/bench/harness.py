"""String-keyed algorithm factory + timed trial loop + JSON records.

Mirrors the reference's ``benchmark_algorithm``
(`/root/reference/benchmark_dist.cpp:26-163`):

* the same five algorithm configurations behind the same magic strings
  (`benchmark_dist.cpp:45-82`),
* app selection ``{vanilla, gat, als}`` (`benchmark_dist.cpp:88-100`),
* a fixed-trial loop (default 5, `benchmark_dist.cpp:117-141`),
* throughput ``2*nnz*2*R*trials / elapsed`` GFLOP/s
  (`benchmark_dist.cpp:147-149`),
* one JSON record appended per run to the output file
  (`benchmark_dist.cpp:151-163`).

Deviation by design: one **untimed warmup iteration** precedes the timed
loop so that XLA compilation (which the reference's ahead-of-time C++ build
has no analog of) is excluded from steady-state throughput. Pass
``warmup=0`` to time cold-start instead.
"""

from __future__ import annotations

import json
import time
from typing import Callable, Optional

import jax

from distributed_sddmm_tpu.common import KernelMode, MatMode
from distributed_sddmm_tpu.models.als import DistributedALS
from distributed_sddmm_tpu.models.gat import GAT, GATLayer
from distributed_sddmm_tpu.parallel.base import (
    DistributedSparse, realized_kernel_variant,
)
from distributed_sddmm_tpu.parallel.cannon_dense_25d import CannonDense25D
from distributed_sddmm_tpu.parallel.cannon_sparse_25d import CannonSparse25D
from distributed_sddmm_tpu.parallel.dense_shift_15d import DenseShift15D
from distributed_sddmm_tpu.parallel.sparse_shift_15d import SparseShift15D
from distributed_sddmm_tpu.utils.coo import HostCOO
from distributed_sddmm_tpu.utils.platform import force_fetch

# The five named configurations of `benchmark_dist.cpp:45-82`.
ALGORITHM_FACTORIES: dict[str, Callable[..., DistributedSparse]] = {
    "15d_fusion1": lambda S, R, c, **kw: DenseShift15D(
        S, R=R, c=c, fusion_approach=1, **kw
    ),
    "15d_fusion2": lambda S, R, c, **kw: DenseShift15D(
        S, R=R, c=c, fusion_approach=2, **kw
    ),
    "15d_sparse": lambda S, R, c, **kw: SparseShift15D(S, R=R, c=c, **kw),
    "25d_dense_replicate": lambda S, R, c, **kw: CannonDense25D(S, R=R, c=c, **kw),
    "25d_sparse_replicate": lambda S, R, c, **kw: CannonSparse25D(S, R=R, c=c, **kw),
}

# Reference GAT benchmark spec: 256 -> (256 x 4) -> (256 x 4) -> (256 x 6)
# (`benchmark_dist.cpp:90-92`).
GAT_REFERENCE_LAYERS = [(256, 256, 4), (1024, 256, 4), (1536, 256, 6)]


#: Strategies with a double-buffered local-kernel-overlap program
#: variant (``--fusion overlap``): the 1.5D shift family. The 2.5D
#: Cannon strategies have no overlap build — requesting one is a
#: configuration error the sweep driver's skip logic reports.
OVERLAP_CAPABLE = ("15d_fusion1", "15d_fusion2", "15d_sparse")

#: Strategies with a fused block-sparse attention program (``--app
#: attention``): the 1.5D DENSE-shift pair only. The softmax row
#: denominator needs every logit of its row before any SpMM
#: contribution flows, which the dense-shift layout satisfies between
#: its two ring passes; the sparse-shift and Cannon layouts move the
#: values/structure with the ring, so the denominator cannot ride the
#: traveling accumulator — same gating pattern as ``--fusion overlap``.
ATTENTION_CAPABLE = ("15d_fusion1", "15d_fusion2")


def make_algorithm(
    name: str,
    S: HostCOO,
    R: int,
    c: int,
    kernel=None,
    devices=None,
    overlap: bool = False,
    attention: bool = False,
    wire=None,
    **kw,
) -> DistributedSparse:
    """Instantiate one of the five named algorithm configurations.
    ``overlap=True`` selects the double-buffered local-kernel-overlap
    ring programs (shift strategies only); ``attention=True`` asserts
    the strategy can run the fused block-sparse attention pair;
    ``wire`` selects the wire-precision policy (``parallel/wire.py``;
    None = env default, i.e. the f32 identity wire)."""
    if wire is not None:
        kw["wire"] = wire
    if name not in ALGORITHM_FACTORIES:
        raise ValueError(
            f"unknown algorithm {name!r}; available: {sorted(ALGORITHM_FACTORIES)}"
        )
    if overlap:
        if name not in OVERLAP_CAPABLE:
            raise ValueError(
                f"fusion 'overlap' is implemented for the 1.5D shift "
                f"strategies {OVERLAP_CAPABLE}; {name} has no "
                "double-buffered variant"
            )
        kw["overlap"] = True
    if attention and name not in ATTENTION_CAPABLE:
        raise ValueError(
            f"fused attention is implemented for the 1.5D dense-shift "
            f"strategies {ATTENTION_CAPABLE}; {name} cannot carry the "
            "softmax row denominator on its traveling accumulator"
        )
    return ALGORITHM_FACTORIES[name](S, R, c, kernel=kernel, devices=devices, **kw)


def _gat_layers(R: int, num_layers: int = 3) -> list[GATLayer]:
    """GAT spec shaped like the reference's benchmark network but
    parameterized on R (features_per_head) so small test runs work: heads
    (4, 4, 6) as in `benchmark_dist.cpp:90-92`."""
    heads = [4, 4, 6][:num_layers]
    layers = []
    in_feat = R
    for h in heads:
        layers.append(GATLayer(input_features=in_feat, features_per_head=R, num_heads=h))
        in_feat = R * h
    return layers


def _run_vanilla(alg: DistributedSparse, fused: bool, trials: int, warmup: int):
    """The primary measured loop: ``fusedSpMM`` pairs or unfused
    sddmmA-then-spmmA (`benchmark_dist.cpp:117-141`)."""
    A = alg.dummy_initialize(MatMode.A)
    B = alg.dummy_initialize(MatMode.B)
    s_vals = alg.like_s_values(1.0)

    def one_trial():
        if fused:
            out, mid = alg.fused_spmm(A, B, s_vals, MatMode.A)
            return out, mid
        mid = alg.sddmm_a(A, B, s_vals)
        out = alg.spmm_a(A, B, mid)
        return out, mid

    for _ in range(warmup):
        force_fetch(one_trial())
    alg.reset_performance_timers()
    t0 = time.perf_counter()
    out = None
    for _ in range(trials):
        out = one_trial()
    # Host fetch, not block_until_ready: tunneled backends only execute the
    # queue on a transfer (see utils.platform.force_fetch).
    force_fetch(out)
    elapsed = time.perf_counter() - t0
    return elapsed, {}


def _array_bytes(*arrays) -> int:
    """Total bytes of device arrays (shape x itemsize — the unit one
    HBM read or write of the buffer costs)."""
    total = 0
    for a in arrays:
        total += int(a.size) * int(a.dtype.itemsize)
    return total


def _attention_hbm_bytes(alg, s_vals, A=None, B=None) -> dict:
    """Counted HBM traffic at the program I/O boundary, fused vs
    unfused (PR 9 counted-metric precedent: structural bytes, not
    wall-clock). Every compiled program reads its inputs from HBM and
    writes its outputs back once per dispatch; the unfused
    SDDMM → softmax → SpMM sequence is three programs, so the logits
    and weights round-trip through HBM between stages and the dense
    moving operand plus tile structure are re-read per stage. The fused
    program reads everything once and writes only (out, probs) — the
    strict cut the acceptance gate asserts. Pass the trial loop's
    ``A``/``B`` when they already exist; only shape/itemsize is read."""
    if A is None:
        A = alg.dummy_initialize(MatMode.A)
    if B is None:
        B = alg.dummy_initialize(MatMode.B)
    targs = alg._tile_args(alg.S_tiles, s_vals)
    dense_out = A  # output rides A's sharding/shape
    fused = _array_bytes(A, B, *targs) + _array_bytes(dense_out, s_vals)
    sddmm = _array_bytes(A, B, *targs) + _array_bytes(s_vals)
    softmax = _array_bytes(*targs, s_vals) + _array_bytes(s_vals)
    spmm = _array_bytes(B, *targs) + _array_bytes(dense_out)
    unfused = sddmm + softmax + spmm
    return {
        "fused_bytes": fused,
        "unfused_bytes": unfused,
        "savings_frac": 1.0 - fused / max(unfused, 1),
    }


def _run_attention(alg: DistributedSparse, fused: bool, trials: int,
                   warmup: int):
    """Fused block-sparse attention trials (or the three-program
    unfused baseline with ``fused=False``); the stats carry the counted
    HBM-traffic comparison either way."""
    A = alg.dummy_initialize(MatMode.A)
    B = alg.dummy_initialize(MatMode.B)
    s_vals = alg.like_s_values(1.0)

    def one_trial():
        if fused:
            return alg.fused_attention(A, B, s_vals)
        return alg.attention_unfused(A, B, s_vals)

    for _ in range(warmup):
        force_fetch(one_trial())
    alg.reset_performance_timers()
    t0 = time.perf_counter()
    out = None
    for _ in range(trials):
        out = one_trial()
    force_fetch(out)
    elapsed = time.perf_counter() - t0
    return elapsed, {
        "attention_hbm": _attention_hbm_bytes(alg, s_vals, A=A, B=B)
    }


def _run_gat(alg: DistributedSparse, trials: int, warmup: int, num_layers: int):
    gat = GAT(_gat_layers(alg.R, num_layers), alg)
    for _ in range(warmup):
        force_fetch(gat.forward())
    alg.reset_performance_timers()
    t0 = time.perf_counter()
    out = None
    for _ in range(trials):
        out = gat.forward()
    force_fetch(out)
    return time.perf_counter() - t0, {"gat_heads": [l.num_heads for l in gat.layers]}


def _run_als(
    alg: DistributedSparse,
    trials: int,
    warmup: int,
    cg_iters: int = 10,
    S: Optional[HostCOO] = None,
    checkpoint_dir: Optional[str] = None,
    checkpoint_every: int = 1,
    resume: bool = False,
):
    als = DistributedALS(alg, S_host=S)
    als.initialize_embeddings()
    if warmup:
        als.run_cg(1, cg_iters=cg_iters)  # compiles every program in the loop
        als.initialize_embeddings()
    store = None
    if checkpoint_dir:
        from distributed_sddmm_tpu.resilience import CheckpointStore

        store = CheckpointStore(checkpoint_dir)
    alg.reset_performance_timers()
    t0 = time.perf_counter()
    als.run_cg(
        trials, cg_iters=cg_iters,
        checkpoint=store, checkpoint_every=checkpoint_every, resume=resume,
    )
    force_fetch((als.A, als.B))
    elapsed = time.perf_counter() - t0
    stats = {"als_residual": als.compute_residual(), "cg_iters": cg_iters}
    if als.degraded:
        stats["als_degraded"] = als.degraded
    return elapsed, stats


def pod_record_fields() -> dict:
    """Pod identity for bench/serve records — ONE shape, owned by
    :meth:`dist.init.PodContext.record_fields` (the manifest resolves
    through the same method, so records and manifests cannot drift)."""
    from distributed_sddmm_tpu.dist.init import pod_info

    return pod_info().record_fields()


def benchmark_algorithm(
    S: HostCOO,
    algorithm_name: str,
    output_file: Optional[str],
    fused: bool,
    R: int,
    c: int,
    app: str = "vanilla",
    trials: int = 5,
    warmup: int = 1,
    kernel=None,
    devices=None,
    extra_info: Optional[dict] = None,
    breakdown: bool = False,
    post_build=None,
    checkpoint_dir: Optional[str] = None,
    checkpoint_every: int = 1,
    resume: bool = False,
    overlap: bool = False,
    mask: Optional[str] = None,
    wire=None,
) -> dict:
    """Run one benchmark configuration; append a JSON record to
    ``output_file`` (if given) and return it.

    Record schema follows `benchmark_dist.cpp:151-163`: ``alg_info`` (the
    reference's ``json_algorithm_info``), ``fused``, ``app``,
    ``overall_throughput`` in GFLOP/s, and per-op ``perf_stats`` (kernel
    seconds). Observability additions: ``metrics`` (the full per-op
    attribution — kernel vs retry/fault overhead, retries, comm words,
    FLOPs), and — when tracing is active — ``run_id`` and ``trace_path``
    tying the record to its trace + manifest.
    """
    from distributed_sddmm_tpu.obs import metrics as obs_metrics
    from distributed_sddmm_tpu.obs import trace as obs_trace
    from distributed_sddmm_tpu.obs import watchdog as obs_watchdog
    from distributed_sddmm_tpu.resilience import faults

    if app not in ("vanilla", "gat", "als", "attention"):
        raise ValueError(
            f"unknown app {app!r}; expected vanilla | gat | als | attention"
        )
    # Snapshot the plan's event cursor: the events list is cumulative and
    # process-wide, and a sweep emits many records — each must carry only
    # the faults that fired during ITS run.
    _fault_plan = faults.active()
    _events_before = len(_fault_plan.events) if _fault_plan is not None else 0
    # Same cursor discipline for the anomaly watchdog.
    _watchdog = obs_watchdog.active()
    _anomalies_before = len(_watchdog.events) if _watchdog is not None else 0
    if breakdown and (app != "vanilla" or not fused):
        # Fail before any measurement: the attribution times the fusedSpMM
        # op, so injecting it into unfused or gat/als records would mix ops
        # and units in one JSONL file.
        raise ValueError(
            "--breakdown requires app='vanilla' and fused=True (it "
            "attributes the fusedSpMM op)"
        )

    # Program-store attribution: the record carries how many programs
    # this run compiled live vs recalled from disk (GLOBAL counter
    # deltas — the runstore's cold-start column reads them).
    _prog_before = {
        k: obs_metrics.GLOBAL.get(k)
        for k in ("program_store_hits", "program_store_misses",
                  "live_compiles")
    }
    # Dynamic-structure attribution: rebind/spill/retrace deltas for
    # runs that churn the sparse pattern (dynstruct builds; zero for
    # static runs, and the record section still appears so the
    # ``dynstruct:`` gate axes have a denominator).
    _dyn_before = {
        k: obs_metrics.GLOBAL.get(k)
        for k in ("dynstruct_rebinds", "dynstruct_bucket_spills",
                  "structure_retraces")
    }
    # XLA-cost cursor: only programs THIS run resolved contribute to
    # its analytic-vs-XLA FLOP cross-check (a sweep's earlier cells
    # compiled at other geometries).
    from distributed_sddmm_tpu import programs as program_store_mod

    _cost_cursor = program_store_mod.cost_log_len()

    alg = make_algorithm(algorithm_name, S, R, c, kernel=kernel,
                         devices=devices, overlap=overlap,
                         attention=app == "attention", wire=wire)
    # Bind the strategy (and the app chains built on it) to the active
    # persistent program store under the problem fingerprint — the
    # strategy-config tag in the key keeps sweep cells apart. No active
    # store (tests, --no-store environments): the pre-PR 6 jit path.
    from distributed_sddmm_tpu import programs as program_store_mod

    if program_store_mod.active() is not None:
        from distributed_sddmm_tpu.autotune.fingerprint import (
            Problem, machine_signature, make_fingerprint,
        )

        _p, _backend, _kernels = machine_signature(devices)
        program_store_mod.bind_strategy(
            alg,
            make_fingerprint(Problem.from_coo(S, R), _p, _backend,
                             _kernels).key,
            content_key=program_store_mod.matrix_content_key(S),
        )
    if post_build is not None:
        # Hook for callers that prepare the strategy before any program
        # runs — e.g. tpu_apps injecting offline-AOT-compiled executables.
        post_build(alg)

    with obs_trace.span(
        "bench", algorithm=algorithm_name, app=app, R=R, c=c,
        fused=bool(fused), trials=trials,
    ):
        if app == "vanilla":
            elapsed, app_stats = _run_vanilla(alg, fused, trials, warmup)
        elif app == "attention":
            elapsed, app_stats = _run_attention(alg, fused, trials, warmup)
        elif app == "gat":
            elapsed, app_stats = _run_gat(alg, trials, warmup, num_layers=3)
        else:
            elapsed, app_stats = _run_als(
                alg, trials, warmup, S=S,
                checkpoint_dir=checkpoint_dir,
                checkpoint_every=checkpoint_every,
                resume=resume,
            )

    # SDDMM+SpMM pair = 2 ops x 2*nnz*R flops each (`benchmark_dist.cpp:147-149`).
    nnz = S.nnz
    throughput = 2.0 * nnz * 2.0 * alg.R * trials / max(elapsed, 1e-12) / 1e9

    perf_stats = alg.json_perf_statistics()
    if breakdown:
        # Region attribution via collective-ablated program variants
        # (reference region timers, `distributed_sparse.h:205-261`). The
        # breakdown REPLACES the whole-call counters: strategies whose
        # fused_spmm delegates to timed sddmm_a/spmm_a would otherwise
        # leave those (collective-inclusive) counters alongside the
        # ablated regions and double-count comm time into Computation.
        A = alg.dummy_initialize(MatMode.A)
        B = alg.dummy_initialize(MatMode.B)
        s_vals = alg.like_s_values(1.0)
        A, B = alg.initial_shift(A, B, KernelMode.SDDMM_A)
        perf_stats = alg.measure_breakdown(
            A, B, s_vals, op="fusedSpMM", trials=trials
        )

    record = {
        "algorithm": algorithm_name,
        "app": app,
        "R": alg.R,
        "c": c,
        "fused": bool(fused),
        "fusion": "overlap" if overlap else "sequential",
        # Attention runs only: the --mask spec (a runstore config axis —
        # mask families must not pool into each other's baselines).
        "mask": mask if app == "attention" else None,
        "num_trials": trials,
        "elapsed": elapsed,
        "overall_throughput": throughput,
        "kernel": getattr(alg.kernel, "name", type(alg.kernel).__name__),
        "kernel_variant": realized_kernel_variant(alg),
        # The REALIZED wire policy (a runstore config axis like
        # kernel_variant: a bf16-wire run must never pool into an f32
        # baseline). The label keeps role overrides distinguishable —
        # bf16 and bf16.reduce=bf16 are different numerics and must
        # not share a baseline. "f32" for default runs; pre-PR-15 docs
        # carry None, which the store's axis matcher normalizes to f32.
        "wire": alg.wire.label,
        # Pod identity: the runstore indexes these and gates on
        # num_processes, so a future multi-host record can never pool
        # into a single-process baseline.
        **pod_record_fields(),
        "alg_info": alg.json_algorithm_info(),
        "perf_stats": perf_stats,
        "metrics": alg.metrics.to_dict(),
        "program_store": {
            k: obs_metrics.GLOBAL.get(k) - v
            for k, v in _prog_before.items()
        },
        "dynstruct": {
            k: obs_metrics.GLOBAL.get(k) - v
            for k, v in _dyn_before.items()
        },
        **app_stats,
        **(extra_info or {}),
    }
    # Analytic-vs-XLA FLOP cross-check: XLA's own cost_analysis numbers
    # for the programs this run resolved, joined per op. The watchdog
    # flags beyond-band disagreement; the run store turns the ratio
    # into a gate axis (xla:<op>_flops).
    _xla_cost = program_store_mod.xla_cost_summary(
        record["metrics"], since=_cost_cursor
    )
    if _xla_cost:
        record["xla_cost"] = _xla_cost
        if _watchdog is not None:
            _watchdog.check_xla_costs(record["metrics"], _xla_cost["ops"])
    if obs_trace.enabled():
        record["run_id"] = obs_trace.run_id()
        record["trace_path"] = obs_trace.trace_path()
        # Refresh the manifest now that the backend is certainly up —
        # the copy written at enable() time may predate backend init and
        # so lack device facts (manifest collection never initializes a
        # backend itself).
        from distributed_sddmm_tpu.obs import manifest as obs_manifest

        obs_manifest.write_for_trace(obs_trace.tracer())
    if _fault_plan is not None:
        # A record produced under fault injection must say so — and which
        # faults actually fired — or sweep files silently mix poisoned and
        # clean measurements.
        record["faults_fired"] = [
            {"site": s, "kind": k, "call": n}
            for s, k, n in _fault_plan.events[_events_before:]
        ]
    if _watchdog is not None:
        # End-of-run anomaly summary — present (possibly empty) whenever
        # the watchdog ran, so a clean record under monitoring is
        # distinguishable from an unmonitored one.
        record["anomalies"] = _watchdog.summary(since=_anomalies_before)
    if output_file:
        # non-atomic-ok: append-only record stream (the -o contract).
        with open(output_file, "a") as f:
            f.write(json.dumps(record) + "\n")

    from distributed_sddmm_tpu.obs import store as obs_store

    run_store = obs_store.active()
    if run_store is not None:
        # Cross-run persistence is best-effort: a full disk or torn
        # index must cost the history entry, never the benchmark.
        try:
            run_store.ingest_record(record)
        except Exception as e:  # noqa: BLE001
            from distributed_sddmm_tpu.obs import log as obs_log

            obs_log.warn("store", "run-store ingest failed",
                         error=f"{type(e).__name__}: {e}")
    return record
