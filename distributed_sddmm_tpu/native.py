"""ctypes bindings for the native C++ host data layer (``native/``).

The TPU compute path is XLA/Pallas; the runtime *around* it — synthetic
graph generation, matrix-market IO, and the bucket sorts behind nonzero
redistribution — is native C++/OpenMP, matching the reference's
native-host architecture (CombBLAS IO + R-mat at
`/root/reference/SpmatLocal.hpp:467-533`, Alltoallv redistribution +
parallel sort at `SpmatLocal.hpp:389-462`).

The library is built lazily with the repo's ``native/Makefile`` on first
use; every entry point has a numpy fallback so the package works without a
toolchain (``available()`` reports which path is active, and the
``HNH_NO_NATIVE=1`` env var forces the fallback).
"""

from __future__ import annotations

import ctypes
import os
import pathlib
import subprocess
import threading

import numpy as np

_LIB_DIR = pathlib.Path(__file__).parent / "_native"
_LIB_PATH = _LIB_DIR / "libhnh_native.so"
_SRC_DIR = pathlib.Path(__file__).parent.parent / "native"

_lock = threading.Lock()
_lib = None
_tried = False

_i64p = np.ctypeslib.ndpointer(np.int64, flags="C_CONTIGUOUS")
_f64p = np.ctypeslib.ndpointer(np.float64, flags="C_CONTIGUOUS")


def _is_fresh() -> bool:
    src = _SRC_DIR / "hnh_native.cpp"
    try:
        return (
            _LIB_PATH.exists()
            and (not src.exists() or src.stat().st_mtime <= _LIB_PATH.stat().st_mtime)
        )
    except OSError:
        return False


def _try_build() -> bool:
    """Build (or rebuild a stale) library; concurrency-safe.

    Compiles to a per-process temp name and atomically renames into place,
    so parallel imports (pytest-xdist, multi-process launches) never dlopen
    a half-written .so or clobber each other's compile.
    """
    if _is_fresh():
        return True
    if not (_SRC_DIR / "Makefile").exists():
        return False
    tmp = _LIB_DIR / f"libhnh_native.build{os.getpid()}.so"
    try:
        subprocess.run(
            ["make", "-C", str(_SRC_DIR), f"OUT={tmp}"],
            check=True,
            capture_output=True,
            timeout=120,
        )
        os.replace(tmp, _LIB_PATH)
    except (subprocess.SubprocessError, OSError):
        tmp.unlink(missing_ok=True)
        return False
    return _LIB_PATH.exists()


def _load():
    global _lib, _tried
    with _lock:
        if _tried:
            return _lib
        _tried = True
        if os.environ.get("HNH_NO_NATIVE") == "1":
            return None
        # Always run make when the source tree is present: it is a no-op
        # for a fresh build and rebuilds stale binaries after source edits.
        if not _try_build() and not _LIB_PATH.exists():
            return None
        try:
            lib = ctypes.CDLL(str(_LIB_PATH))
        except OSError:
            return None
        lib.hnh_rmat.argtypes = [
            ctypes.c_int64, ctypes.c_int64,
            ctypes.c_double, ctypes.c_double, ctypes.c_double, ctypes.c_double,
            ctypes.c_uint64, _i64p, _i64p,
        ]
        lib.hnh_bucket_sort.argtypes = [
            _i64p, ctypes.c_int64, ctypes.c_int64, _i64p, _i64p,
        ]
        lib.hnh_bucket_sort.restype = ctypes.c_int
        lib.hnh_mtx_header.argtypes = [
            ctypes.c_char_p,
            ctypes.POINTER(ctypes.c_int64), ctypes.POINTER(ctypes.c_int64),
            ctypes.POINTER(ctypes.c_int64),
            ctypes.POINTER(ctypes.c_int), ctypes.POINTER(ctypes.c_int),
        ]
        lib.hnh_mtx_header.restype = ctypes.c_int
        lib.hnh_mtx_read.argtypes = [
            ctypes.c_char_p, ctypes.c_int64, ctypes.c_int, _i64p, _i64p, _f64p,
        ]
        lib.hnh_mtx_read.restype = ctypes.c_int64
        lib.hnh_mtx_write.argtypes = [
            ctypes.c_char_p, ctypes.c_int64, ctypes.c_int64, ctypes.c_int64,
            _i64p, _i64p, _f64p,
        ]
        lib.hnh_mtx_write.restype = ctypes.c_int64
        lib.hnh_parse_triplets.argtypes = [
            ctypes.c_char_p, ctypes.c_int64, ctypes.c_int, ctypes.c_int64,
            _i64p, _i64p, _f64p, ctypes.POINTER(ctypes.c_int64),
        ]
        lib.hnh_parse_triplets.restype = ctypes.c_int64
        lib.hnh_num_threads.restype = ctypes.c_int
        _lib = lib
        return _lib


def available() -> bool:
    return _load() is not None


# --------------------------------------------------------------------- #
# R-mat generation
# --------------------------------------------------------------------- #

def rmat_edges(log_m, n_edges, a, b, c, d, seed):
    """Generate R-mat edge endpoints; native when available.

    The native path uses counter-based splitmix64 streams (deterministic
    for a given seed, independent of thread count); the numpy fallback uses
    a different RNG, so cross-path runs agree statistically, not bitwise.
    """
    lib = _load()
    if lib is not None:
        rows = np.empty(n_edges, np.int64)
        cols = np.empty(n_edges, np.int64)
        lib.hnh_rmat(log_m, n_edges, a, b, c, d, np.uint64(seed), rows, cols)
        return rows, cols
    rng = np.random.default_rng(seed)
    rows = np.zeros(n_edges, dtype=np.int64)
    cols = np.zeros(n_edges, dtype=np.int64)
    ab = a + b
    top = b / ab if ab > 0 else 0.0
    bot = d / (c + d) if (c + d) > 0 else 0.0
    for _ in range(log_m):
        rbit = (rng.random(n_edges) >= ab).astype(np.int64)
        cprob = np.where(rbit == 0, top, bot)
        cbit = (rng.random(n_edges) < cprob).astype(np.int64)
        rows = (rows << 1) | rbit
        cols = (cols << 1) | cbit
    return rows, cols


# --------------------------------------------------------------------- #
# Stable bucket sort (the redistribution/chunking workhorse)
# --------------------------------------------------------------------- #

def bucket_sort(keys: np.ndarray, n_buckets: int):
    """Return ``(counts[n_buckets], order[n])`` = stable argsort by bucket.

    Equivalent to ``np.argsort(keys, kind="stable")`` +
    ``np.bincount(keys, minlength=n_buckets)`` but O(n) and parallel in the
    native path.
    """
    keys = np.ascontiguousarray(keys, dtype=np.int64)
    if keys.size and (keys.min() < 0 or keys.max() >= n_buckets):
        # Match the numpy path's behavior (np.bincount raises); the native
        # histogram would write out of bounds on bad keys.
        raise ValueError(
            f"bucket keys out of range [0, {n_buckets}): "
            f"min={keys.min()}, max={keys.max()}"
        )
    lib = _load()
    if lib is not None and keys.size:
        counts = np.empty(n_buckets, np.int64)
        order = np.empty(keys.size, np.int64)
        rc = lib.hnh_bucket_sort(keys, keys.size, n_buckets, counts, order)
        if rc == 0:
            return counts, order
        # Histogram allocation failed (astronomical n_buckets): fall through
        # to the numpy path rather than returning uninitialized buffers.
    order = np.argsort(keys, kind="stable")
    counts = np.bincount(keys, minlength=n_buckets).astype(np.int64)
    return counts, order


# --------------------------------------------------------------------- #
# Matrix-market IO
# --------------------------------------------------------------------- #

def _mtx_read_scipy(path: str):
    import scipy.io

    coo = scipy.io.mmread(path).tocoo()
    return (
        coo.row.astype(np.int64), coo.col.astype(np.int64),
        coo.data.astype(np.float64), int(coo.shape[0]), int(coo.shape[1]),
    )


def mtx_read(path: str):
    """Read a coordinate .mtx file -> (rows, cols, vals, M, N).

    Symmetric headers are expanded (mirror entries negated for
    skew-symmetric); complex/dense files fall back to the scipy reader."""
    lib = _load()
    if lib is None:
        return _mtx_read_scipy(path)
    M = ctypes.c_int64()
    N = ctypes.c_int64()
    nnz = ctypes.c_int64()
    sym = ctypes.c_int()
    pat = ctypes.c_int()
    rc = lib.hnh_mtx_header(
        path.encode(), ctypes.byref(M), ctypes.byref(N), ctypes.byref(nnz),
        ctypes.byref(sym), ctypes.byref(pat),
    )
    if rc in (-4, -6):  # dense 'array' / complex: not handled natively
        return _mtx_read_scipy(path)
    if rc != 0:
        raise IOError(f"failed to parse matrix-market header of {path} (rc={rc})")
    rows = np.empty(nnz.value, np.int64)
    cols = np.empty(nnz.value, np.int64)
    vals = np.empty(nnz.value, np.float64)
    got = lib.hnh_mtx_read(path.encode(), nnz.value, pat.value, rows, cols, vals)
    if got != nnz.value:
        raise IOError(f"{path}: expected {nnz.value} entries, parsed {got}")
    if sym.value:
        off = rows != cols
        mirror_r, mirror_c = cols[off], rows[off]
        mirror_v = -vals[off] if sym.value == 2 else vals[off]
        rows = np.concatenate([rows, mirror_r])
        cols = np.concatenate([cols, mirror_c])
        vals = np.concatenate([vals, mirror_v])
    return rows, cols, vals, M.value, N.value


def parse_triplets(buf: bytes, pattern: bool = False):
    """Parse an in-memory chunk of matrix-market data lines ->
    ``(rows_1based-1, cols-1, vals)`` — or None when the native layer is
    unavailable (the caller falls back to a numpy text reader).

    The ctypes call releases the GIL, which is what makes the
    partitioned loader's thread-pool chunk parse genuinely parallel;
    ``strtol``/``strtod`` produce the same correctly-rounded doubles as
    numpy's tokenizer, so the two paths are bit-identical on valid
    files — and strictness-identical on corrupt ones: a non-blank line
    that does not parse raises ``ValueError`` here exactly where
    ``np.loadtxt`` would in the fallback, instead of silently dropping
    entries.
    """
    import ctypes as _ct

    lib = _load()
    if lib is None:
        return None
    cap = buf.count(b"\n") + 1
    rows = np.empty(cap, np.int64)
    cols = np.empty(cap, np.int64)
    vals = np.empty(cap, np.float64)
    n_bad = _ct.c_int64(0)
    n = lib.hnh_parse_triplets(
        buf, len(buf), 1 if pattern else 0, cap, rows, cols, vals,
        _ct.byref(n_bad),
    )
    if n < 0:
        return None
    if n_bad.value:
        raise ValueError(
            f"{n_bad.value} malformed matrix-market data line(s) in chunk"
        )
    return rows[:n], cols[:n], vals[:n]


def mtx_write(path: str, rows, cols, vals, M: int, N: int) -> None:
    lib = _load()
    if lib is None:
        import scipy.io
        import scipy.sparse as sp

        scipy.io.mmwrite(
            path, sp.coo_matrix((vals, (rows, cols)), shape=(M, N))
        )
        return
    rows = np.ascontiguousarray(rows, np.int64)
    cols = np.ascontiguousarray(cols, np.int64)
    vals = np.ascontiguousarray(vals, np.float64)
    if lib.hnh_mtx_write(path.encode(), M, N, rows.size, rows, cols, vals) < 0:
        raise IOError(f"failed to write {path}")
