"""Pallas TPU kernels: one-hot MXU SDDMM / SpMM over blocked chunk lists.

TPUs have no vectorized random-row gather, so the classic SDDMM/SpMM inner
ops (gather A[row], gather B[col], scatter-add into out[row]) are re-cast as
dense matmuls against one-hot selector matrices built on the fly from the
chunk's indices:

    a_rT [R,128]   = A_T_block [R,BM] @ one_hotT [BM,128]      (gather)
    dots [1,128]   = sum_R (a_rT * b_rT) * s_vals              (VPU)
    acc  [R,BM]   += (b_rT * dots) [R,128] @ one_hotT^T        (scatter)

All matmuls are natural / B^T-form MXU contractions; the dense operands are
kept **feature-major** (``[R, rows]``) inside the kernel so no transposed
MXU loads are needed. One-hot selection in bfloat16 is exact (entries are
0/1); only the gathered dense values round to bf16, giving ~1e-3 relative
error in f32-land ("bf16" precision mode; "f32" mode skips the casts at
~4x the MXU cost).

The kernel grid is a 1-D walk over the tile's **active chunk list** (built
host-side by ``ops/blocked.py``): each step processes 128 nonzeros of one
(row_block, col_block) bucket; per-chunk packed metadata is scalar-prefetched
into SMEM and drives the BlockSpec index maps (which dense blocks to DMA)
plus the zero/flush conditionals of the output accumulator. Empty chunks
never run — load imbalance costs padding only inside a 128-lane chunk.

This is the TPU answer to the reference's ``StandardKernel`` hot loops: the
OpenMP COO dot loop (`/root/reference/sparse_kernels.cpp:44-55`) and MKL CSR
SpMM (`sparse_kernels.cpp:94-121`). It plugs into the same boundary
(`sparse_kernels.h:15-79` -> :class:`distributed_sddmm_tpu.ops.kernels.LocalKernel`)
and additionally exposes tile-level fused entry points the distributed
algorithms use for "local kernel overlap" fusion
(`15D_dense_shift.hpp:199-227`).
"""

from __future__ import annotations

import dataclasses
import functools
import os

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from distributed_sddmm_tpu import compat

from distributed_sddmm_tpu.ops import blocked
from distributed_sddmm_tpu.ops.blocked import (
    CHUNK, _GC_SHIFT, _GR_SHIFT, MAX_BLOCKS, unpack_meta,
)
from distributed_sddmm_tpu.ops.kernels import ATTN_NEG, XlaKernel


@jax.tree_util.register_dataclass
@dataclasses.dataclass
class BlockedTile:
    """Per-(device, tile) chunk-list view passed into the tile kernels.

    Array fields are the per-bucket slices of :class:`ops.blocked.BlockedMeta`
    uploaded to the device; static fields replicate its geometry.
    """

    lr: jax.Array        # [C, CHUNK] int32
    lc: jax.Array        # [C, CHUNK] int32
    meta: jax.Array      # [C] int32 packed (gr, gc, first, last)
    bm: int = dataclasses.field(metadata=dict(static=True), default=512)
    bn: int = dataclasses.field(metadata=dict(static=True), default=512)
    gr_blocks: int = dataclasses.field(metadata=dict(static=True), default=1)
    gc_blocks: int = dataclasses.field(metadata=dict(static=True), default=1)
    group: int = dataclasses.field(metadata=dict(static=True), default=1)

    @property
    def n_chunks(self) -> int:
        return self.lr.shape[0]

    @property
    def rows_pad(self) -> int:
        return self.gr_blocks * self.bm

    @property
    def cols_pad(self) -> int:
        return self.gc_blocks * self.bn


def _dotg(a, b, ca, cb):
    # f32 operands ask for true-f32 MXU passes; at DEFAULT precision the TPU
    # would silently round them through bf16.
    prec = jax.lax.Precision.HIGHEST if a.dtype == jnp.float32 else None
    return jax.lax.dot_general(
        a, b, (((ca,), (cb,)), ((), ())),
        preferred_element_type=jnp.float32, precision=prec,
    )


def _meta_gr(m, t):
    # Mask like _meta_gc: the arithmetic shift of an int32 word would
    # sign-extend gr >= 16384.
    return (m[t] >> _GR_SHIFT) & (MAX_BLOCKS - 1)


def _meta_gc(m, t):
    return (m[t] >> _GC_SHIFT) & (MAX_BLOCKS - 1)


def _gathered(dense_ref, loc_row):
    """Gather rows of a feature-major block via one-hot MXU.

    ``loc_row`` is ``[1, W]`` (W = CHUNK, or G*CHUNK on the step-batched
    path). Returns ``(one_hotT [block, W], rows_T [R, W])``."""
    ohT = (
        jax.lax.broadcasted_iota(
            jnp.int32, (dense_ref.shape[1], loc_row.shape[1]), 0
        )
        == loc_row
    ).astype(dense_ref.dtype)
    return ohT, _dotg(dense_ref[:], ohT, 1, 0)


def _scattered(scT, ohT_r, loc_row, bm, form):
    """Scatter-add contribution ``[R, W] -> [R, BM]`` via one-hot MXU
    (``W`` = CHUNK, or G*CHUNK on the step-batched path).

    ``form`` selects the contraction orientation: "bt" contracts the gather
    one-hot's lane axis (an A.B^T-shaped dot_general, reusing ``ohT_r``);
    "nt" builds the one-hot already transposed (lane axis = BM) from a
    sublane-relayouted index vector, so the MXU sees a natural A.B
    contraction and Mosaic never has to transpose a [BM, W] operand."""
    if form == "bt":
        return _dotg(scT, ohT_r, 1, 1)
    w = scT.shape[1]
    oh = (
        jax.lax.broadcasted_iota(jnp.int32, (w, bm), 1)
        == loc_row.reshape(w, 1)
    ).astype(scT.dtype)
    return _dotg(scT, oh, 1, 0)


def _lane_concat(ref, G):
    """[1, G, CHUNK] chunk-data block -> [1, G*CHUNK] along lanes."""
    if G == 1:
        return ref[0, 0:1]
    return jnp.concatenate([ref[0, j : j + 1] for j in range(G)], axis=1)


def _gathered_cols(bt_refs, lc_ref, G):
    """Per-sub-chunk moving-side gathers, lane-concatenated to [R, G*CHUNK]
    (each sub-chunk has its own bt window, so these cannot batch)."""
    if G == 1:
        return _gathered(bt_refs[0], lc_ref[0, 0:1])[1]
    return jnp.concatenate(
        [_gathered(bt_refs[j], lc_ref[0, j : j + 1])[1] for j in range(G)],
        axis=1,
    )


def _write_mid(mid_ref, dots, G):
    """Scatter the [1, G*CHUNK] dots row back into the [1, G, CHUNK] mid
    output block, sub-chunk by sub-chunk."""
    for j in range(G):
        mid_ref[0, j : j + 1] = dots[:, j * CHUNK : (j + 1) * CHUNK]


def _step_boundaries(meta_ref, acc_ref, t, G):
    """Step-batched zero/flush: the group alignment of ``build_blocked``
    puts every (bucket, gr) group on whole-step boundaries, so the zero
    flag can only sit on the step's FIRST chunk and the flush flag only on
    its LAST."""

    @pl.when((meta_ref[t * G] & 1) == 1)
    def _():
        acc_ref[:] = jnp.zeros_like(acc_ref)

    return ((meta_ref[t * G + G - 1] >> 1) & 1) == 1


def _make_fused_body_batched(G, form):
    def body(meta_ref, lr_ref, lc_ref, sv_ref, at_ref, *rest):
        bt_refs = rest[:G]
        out_ref, mid_ref, acc_ref = rest[G], rest[G + 1], rest[G + 2]
        t = pl.program_id(0)
        bm = out_ref.shape[1]
        last = _step_boundaries(meta_ref, acc_ref, t, G)
        lr_all = _lane_concat(lr_ref, G)
        ohT_all, a_rT = _gathered(at_ref, lr_all)
        b_rT = _gathered_cols(bt_refs, lc_ref, G)
        sv_all = _lane_concat(sv_ref, G)
        dots = jnp.sum(a_rT * b_rT, axis=0, keepdims=True) * sv_all
        _write_mid(mid_ref, dots, G)
        scT = (b_rT * dots).astype(at_ref.dtype)
        acc_ref[:] += _scattered(scT, ohT_all, lr_all, bm, form)

        @pl.when(last)
        def _():
            out_ref[:] = acc_ref[:]

    return body


def _make_fused_body_single(G, form):
    """Dense-short-row band body (codegen): every (bucket, gr) group is
    host-proven to span EXACTLY one grid step with no trailing pad
    steps (``codegen.banded._single_step_provable``), so the zero/flush
    conditionals and the VMEM accumulator carry vanish — each step
    writes its output window once, unconditionally. Same arithmetic as
    the batched body (the accumulator add was ``0 + x``)."""

    def body(meta_ref, lr_ref, lc_ref, sv_ref, at_ref, *rest):
        bt_refs = rest[:G]
        out_ref, mid_ref = rest[G], rest[G + 1]
        bm = out_ref.shape[1]
        lr_all = _lane_concat(lr_ref, G)
        ohT_all, a_rT = _gathered(at_ref, lr_all)
        b_rT = _gathered_cols(bt_refs, lc_ref, G)
        sv_all = _lane_concat(sv_ref, G)
        dots = jnp.sum(a_rT * b_rT, axis=0, keepdims=True) * sv_all
        _write_mid(mid_ref, dots, G)
        scT = (b_rT * dots).astype(at_ref.dtype)
        out_ref[:] = _scattered(scT, ohT_all, lr_all, bm, form)

    return body


def _make_spmm_body_single(G, form):
    """SpMM variant of :func:`_make_fused_body_single` (same single-step
    precondition, no accumulator scratch, no scalar conditionals)."""

    def body(meta_ref, lr_ref, lc_ref, sv_ref, *rest):
        bt_refs = rest[:G]
        out_ref = rest[G]
        bm = out_ref.shape[1]
        lr_all = _lane_concat(lr_ref, G)
        b_rT = _gathered_cols(bt_refs, lc_ref, G)
        sv_all = _lane_concat(sv_ref, G)
        scT = (b_rT * sv_all).astype(bt_refs[0].dtype)
        if form == "bt":
            ohT_all = (
                jax.lax.broadcasted_iota(jnp.int32, (bm, G * CHUNK), 0)
                == lr_all
            ).astype(scT.dtype)
        else:
            ohT_all = None
        out_ref[:] = _scattered(scT, ohT_all, lr_all, bm, form)

    return body


def _make_spmm_body_batched(G, form):
    def body(meta_ref, lr_ref, lc_ref, sv_ref, *rest):
        bt_refs = rest[:G]
        out_ref, acc_ref = rest[G], rest[G + 1]
        t = pl.program_id(0)
        bm = out_ref.shape[1]
        last = _step_boundaries(meta_ref, acc_ref, t, G)
        lr_all = _lane_concat(lr_ref, G)
        b_rT = _gathered_cols(bt_refs, lc_ref, G)
        sv_all = _lane_concat(sv_ref, G)
        scT = (b_rT * sv_all).astype(bt_refs[0].dtype)
        if form == "bt":
            ohT_all = (
                jax.lax.broadcasted_iota(
                    jnp.int32, (bm, G * CHUNK), 0
                )
                == lr_all
            ).astype(scT.dtype)
        else:
            ohT_all = None
        acc_ref[:] += _scattered(scT, ohT_all, lr_all, bm, form)

        @pl.when(last)
        def _():
            out_ref[:] = acc_ref[:]

    return body


def _make_sddmm_body_batched(G):
    def body(meta_ref, lr_ref, lc_ref, sv_ref, at_ref, *rest):
        bt_refs = rest[:G]
        mid_ref = rest[G]
        lr_all = _lane_concat(lr_ref, G)
        _, a_rT = _gathered(at_ref, lr_all)
        b_rT = _gathered_cols(bt_refs, lc_ref, G)
        sv_all = _lane_concat(sv_ref, G)
        dots = jnp.sum(a_rT * b_rT, axis=0, keepdims=True) * sv_all
        _write_mid(mid_ref, dots, G)

    return body


def _sub_boundaries(meta_ref, acc_ref, t, G, j):
    """Zero the accumulator at a first-of-row-block sub-chunk and return the
    flush predicate for a last-of-row-block one. With group > 1 the grid
    step never straddles a row-block boundary (``build_blocked``'s gr
    alignment), so the step's output window is valid for every sub-chunk."""
    w = meta_ref[t * G + j]

    @pl.when((w & 1) == 1)
    def _():
        acc_ref[:] = jnp.zeros_like(acc_ref)

    return ((w >> 1) & 1) == 1


def _make_fused_body(G, form):
    def body(meta_ref, lr_ref, lc_ref, sv_ref, at_ref, *rest):
        bt_refs = rest[:G]
        out_ref, mid_ref, acc_ref = rest[G], rest[G + 1], rest[G + 2]
        t = pl.program_id(0)
        bm = out_ref.shape[1]
        for j in range(G):
            last = _sub_boundaries(meta_ref, acc_ref, t, G, j)
            ohT_r, a_rT = _gathered(at_ref, lr_ref[0, j : j + 1])
            _, b_rT = _gathered(bt_refs[j], lc_ref[0, j : j + 1])
            dots = jnp.sum(a_rT * b_rT, axis=0, keepdims=True) * sv_ref[0, j : j + 1]
            mid_ref[0, j : j + 1] = dots
            scT = (b_rT * dots).astype(bt_refs[j].dtype)
            acc_ref[:] += _scattered(scT, ohT_r, lr_ref[0, j : j + 1], bm, form)

            @pl.when(last)
            def _():
                out_ref[:] = acc_ref[:]

    return body


def _make_sddmm_body(G):
    def body(meta_ref, lr_ref, lc_ref, sv_ref, at_ref, *rest):
        bt_refs = rest[:G]
        mid_ref = rest[G]
        for j in range(G):
            _, a_rT = _gathered(at_ref, lr_ref[0, j : j + 1])
            _, b_rT = _gathered(bt_refs[j], lc_ref[0, j : j + 1])
            mid_ref[0, j : j + 1] = (
                jnp.sum(a_rT * b_rT, axis=0, keepdims=True) * sv_ref[0, j : j + 1]
            )

    return body


def _make_spmm_body(G, form):
    def body(meta_ref, lr_ref, lc_ref, sv_ref, *rest):
        bt_refs = rest[:G]
        out_ref, acc_ref = rest[G], rest[G + 1]
        t = pl.program_id(0)
        bm = out_ref.shape[1]
        for j in range(G):
            last = _sub_boundaries(meta_ref, acc_ref, t, G, j)
            _, b_rT = _gathered(bt_refs[j], lc_ref[0, j : j + 1])
            if form == "bt":
                ohT_r = (
                    jax.lax.broadcasted_iota(jnp.int32, (bm, CHUNK), 0)
                    == lr_ref[0, j : j + 1]
                ).astype(bt_refs[j].dtype)
            else:
                ohT_r = None  # "nt" builds its one-hot inside _scattered
            scT = (b_rT * sv_ref[0, j : j + 1]).astype(bt_refs[j].dtype)
            acc_ref[:] += _scattered(scT, ohT_r, lr_ref[0, j : j + 1], bm, form)

            @pl.when(last)
            def _():
                out_ref[:] = acc_ref[:]

    return body


# ------------------------------------------------------------------ #
# Masked-softmax attention epilogue kernels (chunk-list layout).
#
# The SDDMM mid values ARE the sparse attention logits; these kernels
# turn them into row-stochastic weights between the SDDMM and SpMM
# stages without materializing any dense [rows, cols] intermediate.
# Two launches ride the SAME chunk-list metadata the pair kernels use:
#
# * ``attn_reduce`` — streaming per-row max + denominator over each
#   (bucket, row block) group's chunks: two (bm, 1) VMEM scratches
#   carry the running max ``m`` and the rescaled denominator
#   ``d ← d·exp(m_old − m_new) + Σ exp(z − m_new)`` (the online-softmax
#   recurrence), zeroed/flushed on the group's first/last flags exactly
#   like the pair accumulator. Bands whose metadata proves one grid
#   step per row-block group get the PROVABLY-ONE-PASS body: no
#   scratch, no flags — each step computes its window's stats from its
#   own lanes and writes them once, unconditionally (the epilogue
#   counterpart of the conditional-free single-step pair bodies).
# * ``attn_norm`` — a pure map: gather each lane's row stats from the
#   (bm, 1) blocks via the one-hot row selector and emit
#   ``exp(z − m) / d`` (0 at masked lanes, pads, and d == 0 rows).
#
# Everything is VPU work in the [bm, W] orientation (lane-axis chunk
# entries vs sublane-axis rows): sublane/lane reductions and broadcasts
# only — no transposes, no MXU passes, so Mosaic lowers it next to the
# pair kernels it fuses with. The mask indicator is ``gate != 0`` where
# ``gate`` is the ORIGINAL value vector (pad lanes carry 0 by the
# TileSet contract; a zero mask value means "masked out" — logits that
# are legitimately 0.0 stay in).
# ------------------------------------------------------------------ #


def _attn_sel(lr_all, gv, bm):
    """One-hot row selector [bm, W] and its mask-gated refinement."""
    ohT = (
        jax.lax.broadcasted_iota(jnp.int32, (bm, lr_all.shape[1]), 0)
        == lr_all
    )
    return ohT, ohT & (gv != 0)


def _attn_chunk_stats(sel, zv, m_prev):
    """Streaming update from one grid step's lanes: returns
    ``(m_new [bm, 1], csum [bm, 1])`` where ``csum`` sums
    ``exp(z − m_new)`` over the step's selected lanes per row."""
    neg = jnp.float32(ATTN_NEG)
    zb = jnp.where(sel, zv, neg)                      # [bm, W]
    m_new = jnp.maximum(m_prev, jnp.max(zb, axis=1, keepdims=True))
    e = jnp.where(sel, jnp.exp(zb - m_new), 0.0)
    return m_new, jnp.sum(e, axis=1, keepdims=True)


def _make_attn_reduce_body(G):
    def body(meta_ref, lr_ref, gv_ref, zv_ref, m_out, d_out, m_acc, d_acc):
        t = pl.program_id(0)
        bm = m_out.shape[0]

        @pl.when((meta_ref[t * G] & 1) == 1)
        def _():
            m_acc[:] = jnp.full_like(m_acc, jnp.float32(ATTN_NEG))
            d_acc[:] = jnp.zeros_like(d_acc)

        last = ((meta_ref[t * G + G - 1] >> 1) & 1) == 1
        lr_all = _lane_concat(lr_ref, G)
        _, sel = _attn_sel(lr_all, _lane_concat(gv_ref, G), bm)
        m_old = m_acc[:]
        m_new, csum = _attn_chunk_stats(sel, _lane_concat(zv_ref, G), m_old)
        d_acc[:] = d_acc[:] * jnp.exp(m_old - m_new) + csum
        m_acc[:] = m_new

        @pl.when(last)
        def _():
            m_out[:] = m_acc[:]
            d_out[:] = d_acc[:]

    return body


def _make_attn_reduce_body_single(G):
    """One-pass epilogue variant: the band's metadata proves every
    (bucket, row block) group spans exactly ONE grid step with no
    trailing pad chunks (``codegen.banded._single_step_provable``), so
    the running-stat scratch and the zero/flush conditionals vanish —
    each step derives its window's max/denominator from its own lanes
    and writes both outputs once, unconditionally."""

    def body(meta_ref, lr_ref, gv_ref, zv_ref, m_out, d_out):
        bm = m_out.shape[0]
        lr_all = _lane_concat(lr_ref, G)
        _, sel = _attn_sel(lr_all, _lane_concat(gv_ref, G), bm)
        m0 = jnp.full((bm, 1), jnp.float32(ATTN_NEG))
        m_new, csum = _attn_chunk_stats(sel, _lane_concat(zv_ref, G), m0)
        m_out[:] = m_new
        d_out[:] = csum

    return body


def _make_attn_norm_body(G):
    def body(meta_ref, lr_ref, gv_ref, zv_ref, m_ref, d_ref, p_out):
        bm = m_ref.shape[0]
        neg = jnp.float32(ATTN_NEG)
        lr_all = _lane_concat(lr_ref, G)
        gv = _lane_concat(gv_ref, G)
        zv = _lane_concat(zv_ref, G)
        ohT, _ = _attn_sel(lr_all, gv, bm)
        # Per-lane row-stat gather via the one-hot selector: each lane
        # belongs to exactly one row, so a masked sublane max/sum pulls
        # its m/d into lane orientation without any transpose.
        m_g = jnp.max(jnp.where(ohT, m_ref[:], neg), axis=0, keepdims=True)
        d_g = jnp.sum(jnp.where(ohT, d_ref[:], 0.0), axis=0, keepdims=True)
        ok = (gv != 0) & (d_g > 0)                         # [1, W]
        # exp on the select-guarded argument: a masked lane's raw
        # ``z − m`` can overflow to +inf before the select otherwise.
        e = jnp.exp(jnp.where(ok, zv - m_g, 0.0))
        p = jnp.where(ok, e / jnp.where(ok, d_g, 1.0), 0.0)
        _write_mid(p_out, p, G)

    return body


@functools.partial(
    jax.jit,
    static_argnames=("op", "bm", "gr_blocks", "group", "interpret",
                     "single_step"),
)
def _attn_call(
    meta, lr, gv, zv, m, d, op, bm, gr_blocks, group, interpret,
    single_step=False,
):
    """Launch one attention-epilogue kernel over a chunk list.

    ``gv`` is the ORIGINAL (mask) value vector and ``zv`` the SDDMM
    logits, both in chunk layout [C, CHUNK]; ``m``/``d`` are the merged
    (rows_pad, 1) row stats (``attn_norm`` only). Returns ``(m, d)``
    for ``attn_reduce``, the normalized chunk values for ``attn_norm``.
    """
    C = lr.shape[0]
    G = group
    if C % G:
        raise ValueError(f"chunk count {C} not a multiple of group {G}")
    steps = C // G
    lr3 = lr.reshape(steps, G, CHUNK)
    gv3 = gv.reshape(steps, G, CHUNK)
    zv3 = zv.reshape(steps, G, CHUNK)

    chunk_spec = pl.BlockSpec((1, G, CHUNK), lambda t, mm: (t, 0, 0))
    md_spec = pl.BlockSpec((bm, 1), lambda t, mm: (_meta_gr(mm, t * G), 0))
    md_shape = jax.ShapeDtypeStruct((gr_blocks * bm, 1), jnp.float32)

    if op == "attn_reduce":
        if single_step:
            body, scratch = _make_attn_reduce_body_single(G), []
        else:
            body = _make_attn_reduce_body(G)
            scratch = [pltpu.VMEM((bm, 1), jnp.float32),
                       pltpu.VMEM((bm, 1), jnp.float32)]
        in_specs = [chunk_spec, chunk_spec, chunk_spec]
        operands = (lr3, gv3, zv3)
        out_specs, out_shapes = [md_spec, md_spec], [md_shape, md_shape]
    elif op == "attn_norm":
        body, scratch = _make_attn_norm_body(G), []
        in_specs = [chunk_spec, chunk_spec, chunk_spec, md_spec, md_spec]
        operands = (lr3, gv3, zv3, m, d)
        out_specs = [chunk_spec]
        out_shapes = [jax.ShapeDtypeStruct((steps, G, CHUNK), jnp.float32)]
    else:
        raise ValueError(op)

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(steps,),
        in_specs=in_specs,
        out_specs=out_specs,
        scratch_shapes=scratch,
    )
    outs = pl.pallas_call(
        body,
        grid_spec=grid_spec,
        out_shape=out_shapes,
        compiler_params=compat.pallas_tpu_compiler_params(
            dimension_semantics=("arbitrary",)
        ),
        interpret=interpret,
    )(meta, *operands)
    return outs if op == "attn_reduce" else outs[0]


@functools.partial(
    jax.jit,
    static_argnames=(
        "op", "bm", "bn", "gr_blocks", "gc_blocks", "group", "interpret",
        "scatter_form", "batch_step", "single_step",
    ),
)
def _tile_call(
    meta, lr, lc, sv, at, bt, op, bm, bn, gr_blocks, gc_blocks, group,
    interpret, scatter_form="bt", batch_step=False, single_step=False,
):
    """Launch one chunk-list kernel. ``at``/``bt`` are feature-major padded
    dense operands [R, gr_blocks*bm] / [R, gc_blocks*bn]; ``sv`` is the
    chunk-layout values [C, CHUNK]. The grid walks ``group`` chunks per step
    (one semaphore round-trip and one chunk-data DMA amortized over G
    chunks); each sub-chunk gets its own bt window via a per-sub-chunk
    BlockSpec, while the at/out windows are shared (gr-aligned groups).
    Returns op-dependent outputs."""
    C = lr.shape[0]
    G = group
    if C % G:
        raise ValueError(f"chunk count {C} not a multiple of group {G}")
    steps = C // G
    R = bt.shape[0]
    lr3 = lr.reshape(steps, G, CHUNK)
    lc3 = lc.reshape(steps, G, CHUNK)
    sv3 = sv.reshape(steps, G, CHUNK)

    chunk_spec = pl.BlockSpec((1, G, CHUNK), lambda t, m: (t, 0, 0))
    at_spec = pl.BlockSpec((R, bm), lambda t, m: (0, _meta_gr(m, t * G)))
    bt_specs = [
        pl.BlockSpec((R, bn), (lambda j: lambda t, m: (0, _meta_gc(m, t * G + j)))(j))
        for j in range(G)
    ]
    out_spec = pl.BlockSpec((R, bm), lambda t, m: (0, _meta_gr(m, t * G)))
    out_shape = jax.ShapeDtypeStruct((R, gr_blocks * bm), jnp.float32)
    mid_shape = jax.ShapeDtypeStruct((steps, G, CHUNK), jnp.float32)

    if op == "fused":
        if single_step:
            body, scratch = _make_fused_body_single(G, scatter_form), []
        else:
            body = (
                _make_fused_body_batched if batch_step else _make_fused_body
            )(G, scatter_form)
            scratch = [pltpu.VMEM((R, bm), jnp.float32)]
        in_specs = [chunk_spec, chunk_spec, chunk_spec, at_spec, *bt_specs]
        operands = (lr3, lc3, sv3, at, *([bt] * G))
        out_specs, out_shapes = [out_spec, chunk_spec], [out_shape, mid_shape]
    elif op == "sddmm":
        body = (
            _make_sddmm_body_batched(G) if batch_step else _make_sddmm_body(G)
        )
        in_specs = [chunk_spec, chunk_spec, chunk_spec, at_spec, *bt_specs]
        operands = (lr3, lc3, sv3, at, *([bt] * G))
        out_specs, out_shapes, scratch = [chunk_spec], [mid_shape], []
    elif op == "spmm":
        if single_step:
            body, scratch = _make_spmm_body_single(G, scatter_form), []
        else:
            body = (
                _make_spmm_body_batched if batch_step else _make_spmm_body
            )(G, scatter_form)
            scratch = [pltpu.VMEM((R, bm), jnp.float32)]
        in_specs = [chunk_spec, chunk_spec, chunk_spec, *bt_specs]
        operands = (lr3, lc3, sv3, *([bt] * G))
        out_specs, out_shapes = [out_spec], [out_shape]
    else:
        raise ValueError(op)

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(steps,),
        in_specs=in_specs,
        out_specs=out_specs,
        scratch_shapes=scratch,
    )
    outs = pl.pallas_call(
        body,
        grid_spec=grid_spec,
        out_shape=out_shapes,
        compiler_params=compat.pallas_tpu_compiler_params(
            dimension_semantics=("arbitrary",)
        ),
        interpret=interpret,
    )(meta, *operands)
    return outs


def _flat_indices(geom, meta, lr, lc):
    """Device-side reconstruction of the chunk lanes' block-frame indices:
    ``rows`` address the ``at``/output frame, ``cols`` the ``bt`` frame
    (whatever the encoding's orientation)."""
    bm, bn = geom[0], geom[1]
    gr, gc, _, _ = unpack_meta(meta)
    rows = (gr[:, None] * bm + lr).reshape(-1)
    cols = (gc[:, None] * bn + lc).reshape(-1)
    return rows, cols


# Differentiable tile ops: forward runs the Mosaic kernel, backward runs XLA
# gather/segment-sum formulas over indices reconstructed from the chunk
# metadata. Pad lanes contribute nothing to dense cotangents because value
# vectors are zero there (the TileSet mask contract); their d_sv entries are
# don't-cares that the pad positions of value vectors absorb. The integer
# metadata arrays are explicit arguments with float0 cotangents (custom_vjp
# must not close over tracers); ``geom`` = (bm, bn, gr_blocks, gc_blocks,
# group, interpret, scatter_form, batch_step, single_step) rides in
# nondiff_argnums (``single_step`` selects the codegen direct-write body).


def _geom_call(geom, op, meta, lr, lc, sv, at, bt):
    bm, bn, grb, gcb, group, interpret, form, batch, single = geom
    return tuple(
        _tile_call(
            meta, lr, lc, sv, at, bt, op=op, bm=bm, bn=bn,
            gr_blocks=grb, gc_blocks=gcb, group=group, interpret=interpret,
            scatter_form=form, batch_step=batch, single_step=single,
        )
    )


def _int_zeros(*arrays):
    import numpy as onp

    return tuple(onp.zeros(a.shape, dtype=jax.dtypes.float0) for a in arrays)


def _seg_t(contrib, idx, n, dtype):
    """Scatter-add [nnz_flat, R] rows -> feature-major [R, n]."""
    return jax.ops.segment_sum(contrib, idx, num_segments=n).T.astype(dtype)


@functools.partial(jax.custom_vjp, nondiff_argnums=(0,))
def _sddmm_op(geom, meta, lr, lc, sv, at, bt):
    return _geom_call(geom, "sddmm", meta, lr, lc, sv, at, bt)[0]


def _sddmm_fwd(geom, meta, lr, lc, sv, at, bt):
    return _sddmm_op(geom, meta, lr, lc, sv, at, bt), (meta, lr, lc, sv, at, bt)


def _sddmm_bwd(geom, res, g):
    meta, lr, lc, sv, at, bt = res
    rows, cols = _flat_indices(geom, meta, lr, lc)
    a_g = at.T.astype(jnp.float32)[rows]
    b_g = bt.T.astype(jnp.float32)[cols]
    dots = jnp.sum(a_g * b_g, axis=-1)
    gf = g.reshape(-1).astype(jnp.float32)
    gs = (gf * sv.reshape(-1).astype(jnp.float32))[:, None]
    return _int_zeros(meta, lr, lc) + (
        (gf * dots).reshape(sv.shape).astype(sv.dtype),
        _seg_t(gs * b_g, rows, at.shape[1], at.dtype),
        _seg_t(gs * a_g, cols, bt.shape[1], bt.dtype),
    )


_sddmm_op.defvjp(_sddmm_fwd, _sddmm_bwd)


@functools.partial(jax.custom_vjp, nondiff_argnums=(0,))
def _spmm_op(geom, meta, lr, lc, sv, bt):
    return _geom_call(geom, "spmm", meta, lr, lc, sv, None, bt)[0]


def _spmm_fwd(geom, meta, lr, lc, sv, bt):
    return _spmm_op(geom, meta, lr, lc, sv, bt), (meta, lr, lc, sv, bt)


def _spmm_bwd(geom, res, g):
    meta, lr, lc, sv, bt = res
    rows, cols = _flat_indices(geom, meta, lr, lc)
    g_rows = g.T.astype(jnp.float32)[rows]
    b_g = bt.T.astype(jnp.float32)[cols]
    svf = sv.reshape(-1).astype(jnp.float32)[:, None]
    return _int_zeros(meta, lr, lc) + (
        jnp.sum(g_rows * b_g, axis=-1).reshape(sv.shape).astype(sv.dtype),
        _seg_t(svf * g_rows, cols, bt.shape[1], bt.dtype),
    )


_spmm_op.defvjp(_spmm_fwd, _spmm_bwd)


@functools.partial(jax.custom_vjp, nondiff_argnums=(0,))
def _fused_op(geom, meta, lr, lc, sv, at, bt):
    return _geom_call(geom, "fused", meta, lr, lc, sv, at, bt)


def _fused_fwd(geom, meta, lr, lc, sv, at, bt):
    outT, mid = _fused_op(geom, meta, lr, lc, sv, at, bt)
    return (outT, mid), (meta, lr, lc, sv, at, bt, mid)


def _fused_bwd(geom, res, cts):
    meta, lr, lc, sv, at, bt, mid = res
    g_out, g_mid = cts
    rows, cols = _flat_indices(geom, meta, lr, lc)
    a_g = at.T.astype(jnp.float32)[rows]
    b_g = bt.T.astype(jnp.float32)[cols]
    dots = jnp.sum(a_g * b_g, axis=-1)
    g_out_rows = g_out.T.astype(jnp.float32)[rows]
    # out = spmm(mid, bt) with mid = sv * dots: fold out's cotangent into mid's.
    g_mid_eff = g_mid.reshape(-1).astype(jnp.float32) + jnp.sum(
        g_out_rows * b_g, axis=-1
    )
    gs = (g_mid_eff * sv.reshape(-1).astype(jnp.float32))[:, None]
    midf = mid.reshape(-1).astype(jnp.float32)[:, None]
    return _int_zeros(meta, lr, lc) + (
        (g_mid_eff * dots).reshape(sv.shape).astype(sv.dtype),
        _seg_t(gs * b_g, rows, at.shape[1], at.dtype),
        _seg_t(gs * a_g + midf * g_out_rows, cols, bt.shape[1], bt.dtype),
    )


_fused_op.defvjp(_fused_fwd, _fused_bwd)


class PallasKernel:
    """TPU-native local kernel (one-hot MXU formulation).

    Implements the flat :class:`~distributed_sddmm_tpu.ops.kernels.LocalKernel`
    protocol by falling back to XLA formulas (so it is a drop-in anywhere),
    plus the blocked tile-level entry points ``sddmm_tile`` / ``spmm_tile`` /
    ``fused_tile`` that the distributed algorithms call when blocked
    metadata is available.

    ``precision``: "bf16" (exact one-hot selection, dense values rounded to
    bf16) or "f32" (full f32 MXU, ~4x slower). Default: bf16 on TPU, f32 in
    interpreter mode (CPU executors lack bf16 matmuls).
    ``interpret``: run in the Pallas interpreter (CPU test meshes).
    ``scatter_form``: "bt" (reuse the gather one-hot, A.B^T contraction) or
    "nt" (build a transposed one-hot, natural A.B contraction); identical
    numerics, different Mosaic lowering — ``scripts/tune_blocks.py`` probes
    which is faster on hardware. Env default: ``DSDDMM_SCATTER_FORM``.
    ``batch_step``: batch the stationary-side gather and the scatter across
    a grid step's G sub-chunks into single [.., G*CHUNK]-wide MXU ops
    (legal because group alignment pins a step inside one row-block
    window); identical numerics. Env default: ``DSDDMM_BATCH_STEP``.
    """

    is_blocked = True
    #: Codegen specialization id carried by subclasses
    #: (``codegen.kernel.BankedPallasKernel``); None = the generic
    #: one-shape-fits-all kernel. Rides into program-store keys
    #: (``parallel/base._program_cache_key``) and bench records.
    variant_id: str | None = None
    variant = None

    def __init__(
        self,
        precision: str | None = None,
        interpret: bool | None = None,
        scatter_form: str | None = None,
        batch_step: bool | None = None,
    ):
        if interpret is None:
            interpret = jax.default_backend() != "tpu"
        self.interpret = interpret
        if precision is None:
            precision = "f32" if interpret else "bf16"
        if precision not in ("bf16", "f32"):
            raise ValueError(f"precision must be 'bf16' or 'f32', got {precision!r}")
        if scatter_form is None:
            # Construction-time env read (docstring contract), falling back
            # to blocked.py's import-time snapshot — the one home for every
            # kernel-knob default.
            scatter_form = os.environ.get(
                "DSDDMM_SCATTER_FORM", blocked.DEFAULT_SCATTER_FORM)
        if scatter_form not in ("bt", "nt"):
            raise ValueError(f"scatter_form must be 'bt' or 'nt', got {scatter_form!r}")
        if batch_step is None:
            raw = os.environ.get("DSDDMM_BATCH_STEP")
            batch_step = (raw not in ("", "0")) if raw is not None \
                else blocked.DEFAULT_BATCH_STEP
        self.precision = precision
        self.scatter_form = scatter_form
        self.batch_step = bool(batch_step)
        self._xla = XlaKernel()
        self.name = f"pallas-{precision}"

    # -------------------- flat protocol (XLA fallback) ------------------- #

    def sddmm(self, rows, cols, vals, A, B):
        return self._xla.sddmm(rows, cols, vals, A, B)

    def spmm(self, rows, cols, vals, B, out_rows: int):
        return self._xla.spmm(rows, cols, vals, B, out_rows)

    # ----------------------- blocked tile protocol ----------------------- #

    def _mxu_dtype(self):
        return jnp.bfloat16 if self.precision == "bf16" else jnp.float32

    def prep(self, X: jax.Array, rows_pad: int) -> jax.Array:
        """[rows, R] -> padded feature-major [R, rows_pad] in MXU dtype.

        Use for both operands: pad the output-side/stationary one to
        ``blk.rows_pad`` (hoist out of ring loops) and the gathered/moving
        one to ``blk.cols_pad`` (per ring step)."""
        pad = rows_pad - X.shape[0]
        Xp = jnp.pad(X, ((0, pad), (0, 0))) if pad else X
        return Xp.T.astype(self._mxu_dtype())

    def _chunk_vals(self, blk: BlockedTile, vals: jax.Array) -> jax.Array:
        """Flat [C * CHUNK] values -> [C, CHUNK]: the flat layout IS the
        chunk layout (pad lanes hold zero by the TileSet mask contract)."""
        return vals.reshape(blk.n_chunks, CHUNK).astype(jnp.float32)

    def _unchunk(self, blk: BlockedTile, chunked: jax.Array, dtype) -> jax.Array:
        """Chunk layout [C, 1, CHUNK] -> flat [C * CHUNK]."""
        return chunked.reshape(-1).astype(dtype)

    def sddmm_tile(self, blk: BlockedTile, vals, A, B):
        """Tile-level SDDMM: returns flat [max_nnz] ``vals * dots``."""
        at = self.prep(A, blk.rows_pad)
        bt = self.prep(B, blk.cols_pad)
        return self.sddmm_tile_t(blk, vals, at, bt, vals.dtype)

    def _geom(self, blk: BlockedTile) -> tuple:
        return (
            blk.bm, blk.bn, blk.gr_blocks, blk.gc_blocks, blk.group,
            self.interpret, self.scatter_form, self.batch_step, False,
        )

    def sddmm_tile_t(self, blk: BlockedTile, vals, at, bt, out_dtype):
        """Feature-major variant (operands already via ``prep``)."""
        mid = _sddmm_op(
            self._geom(blk), blk.meta, blk.lr, blk.lc,
            self._chunk_vals(blk, vals), at, bt,
        )
        return self._unchunk(blk, mid, out_dtype)

    def spmm_tile(self, blk: BlockedTile, vals, B, out_rows: int):
        """Tile-level SpMM partial: returns [out_rows, R] dense."""
        bt = self.prep(B, blk.cols_pad)
        outT = self.spmm_tile_t(blk, vals, bt)
        return outT.T[:out_rows].astype(B.dtype)

    def spmm_tile_t(self, blk: BlockedTile, vals, bt):
        """Feature-major variant: returns padded [R, rows_pad] f32 partial."""
        return _spmm_op(
            self._geom(blk), blk.meta, blk.lr, blk.lc,
            self._chunk_vals(blk, vals), bt,
        )

    def fused_tile(self, blk: BlockedTile, vals, A, B):
        """SDDMM -> SpMM with shared gathers ("local kernel overlap").

        Returns ``(partial [A_rows, R], mid_flat [max_nnz])``."""
        at = self.prep(A, blk.rows_pad)
        bt = self.prep(B, blk.cols_pad)
        outT, mid = self.fused_tile_t(blk, vals, at, bt, vals.dtype)
        return outT.T[: A.shape[0]].astype(A.dtype), mid

    def fused_tile_t(self, blk: BlockedTile, vals, at, bt, out_dtype):
        outT, mid = _fused_op(
            self._geom(blk), blk.meta, blk.lr, blk.lc,
            self._chunk_vals(blk, vals), at, bt,
        )
        return outT, self._unchunk(blk, mid, out_dtype)

    # ---------------- masked-softmax attention epilogue ---------------- #

    def attn_stats_tile_t(self, blk: BlockedTile, gate_vals, logit_vals):
        """Per-row masked-softmax stats ``(m, d)``, each
        ``[rows_pad, 1]`` f32, for one blocked tile's chunk values
        (``gate_vals`` = the original mask values, ``logit_vals`` = the
        SDDMM output). Partial by construction — tiles/devices merge
        via :func:`ops.kernels.attn_merge_stats`."""
        return _attn_call(
            blk.meta, blk.lr,
            self._chunk_vals(blk, gate_vals),
            self._chunk_vals(blk, logit_vals),
            None, None, op="attn_reduce", bm=blk.bm,
            gr_blocks=blk.gr_blocks, group=blk.group,
            interpret=self.interpret,
        )

    def attn_norm_tile_t(self, blk: BlockedTile, gate_vals, logit_vals,
                         m, d, out_dtype):
        """Normalized attention weights (flat [max_nnz]) from the MERGED
        row stats."""
        probs = _attn_call(
            blk.meta, blk.lr,
            self._chunk_vals(blk, gate_vals),
            self._chunk_vals(blk, logit_vals),
            m, d, op="attn_norm", bm=blk.bm,
            gr_blocks=blk.gr_blocks, group=blk.group,
            interpret=self.interpret,
        )
        return self._unchunk(blk, probs, out_dtype)

    # Flat-protocol attention softmax (XLA fallback, like sddmm/spmm).

    def attn_stats(self, rows, gate, logits, out_rows: int):
        return self._xla.attn_stats(rows, gate, logits, out_rows)

    def attn_normalize(self, rows, gate, logits, m, d):
        return self._xla.attn_normalize(rows, gate, logits, m, d)

    def attn_softmax(self, rows, gate, logits, out_rows: int):
        return self._xla.attn_softmax(rows, gate, logits, out_rows)
