"""Pluggable local SDDMM / SpMM kernels (single device, one sparse tile).

This is the framework's counterpart of the reference's plugin boundary
``KernelImplementation`` (`/root/reference/sparse_kernels.h:15-79`): the
distributed algorithms are written against the :class:`LocalKernel` interface
and any implementation can be swapped in. Implementations:

* :class:`XlaKernel` — pure jax.numpy gather-dot SDDMM and segment-sum SpMM.
  Works on every backend (CPU test meshes included); XLA fuses the gather with
  the rowwise multiply-reduce. This replaces the reference's OpenMP COO loop
  (`sparse_kernels.cpp:13-57`) and MKL ``mkl_sparse_d_mm``
  (`sparse_kernels.cpp:94-121`).
* ``PallasKernel`` (``ops/pallas_kernels.py``) — blocked kernels for peak TPU
  throughput on row-sorted tiles.

Tile convention: a tile is a struct-of-arrays ``(rows, cols, vals)`` of static
length ``max_nnz``, padded with inert entries ``row = col = 0, val = 0``.
Zero-valued padding is harmless in both ops: SDDMM multiplies dots by the
input values (0 at pads) and SpMM scatters ``val * B[col]`` (0 contribution).
This mirrors the reference's own max_nnz double-buffering for in-flight
sparse shifts (`SpmatLocal.hpp:153-169`) — its solution to variable nnz is
already the static-shape solution XLA requires.
"""

from __future__ import annotations

from typing import Protocol

import jax
import jax.numpy as jnp


class LocalKernel(Protocol):
    """Local kernel plugin boundary (reference `sparse_kernels.h:15-79`)."""

    def sddmm(
        self,
        rows: jax.Array,
        cols: jax.Array,
        vals: jax.Array,
        A: jax.Array,
        B: jax.Array,
    ) -> jax.Array:
        """Return ``vals * rowwise_dot(A[rows], B[cols])``, shape [max_nnz]."""
        ...

    def spmm(
        self,
        rows: jax.Array,
        cols: jax.Array,
        vals: jax.Array,
        B: jax.Array,
        out_rows: int,
    ) -> jax.Array:
        """Return ``S_tile @ B`` as a dense [out_rows, R] array.

        Accumulate (beta=1) semantics are the caller's job: callers add the
        returned partial into their accumulation buffer, matching the
        reference's ``beta=1`` MKL call (`sparse_kernels.cpp:104-107`).
        """
        ...


class XlaKernel:
    """Gather-dot SDDMM + segment-sum SpMM in pure XLA ops."""

    name = "xla"

    def sddmm(self, rows, cols, vals, A, B):
        dots = jnp.sum(A[rows] * B[cols], axis=-1)
        return vals * dots.astype(vals.dtype)

    def spmm(self, rows, cols, vals, B, out_rows: int):
        contrib = vals[:, None] * B[cols]
        return jax.ops.segment_sum(contrib, rows, num_segments=out_rows)


_REGISTRY = {"xla": XlaKernel}


def get_kernel(name: str) -> LocalKernel:
    """Kernel factory; Pallas registers lazily to keep CPU imports light.

    ``"auto"`` picks Pallas on real TPU backends and XLA elsewhere (the
    Pallas interpreter is not an honest non-TPU fallback).
    """
    if name == "auto":
        import jax

        name = "pallas" if jax.default_backend() == "tpu" else "xla"
    if name == "pallas" and "pallas" not in _REGISTRY:
        try:
            from distributed_sddmm_tpu.ops.pallas_kernels import PallasKernel
        except ImportError as e:
            raise NotImplementedError(
                "the 'pallas' kernel is not available in this build"
            ) from e
        _REGISTRY["pallas"] = PallasKernel
    if name not in _REGISTRY:
        raise ValueError(f"unknown kernel {name!r}; available: {sorted(_REGISTRY)}")
    return _REGISTRY[name]()
