"""Pluggable local SDDMM / SpMM kernels (single device, one sparse tile).

This is the framework's counterpart of the reference's plugin boundary
``KernelImplementation`` (`/root/reference/sparse_kernels.h:15-79`): the
distributed algorithms are written against the :class:`LocalKernel` interface
and any implementation can be swapped in. Implementations:

* :class:`XlaKernel` — pure jax.numpy gather-dot SDDMM and segment-sum SpMM.
  Works on every backend (CPU test meshes included); XLA fuses the gather with
  the rowwise multiply-reduce. This replaces the reference's OpenMP COO loop
  (`sparse_kernels.cpp:13-57`) and MKL ``mkl_sparse_d_mm``
  (`sparse_kernels.cpp:94-121`).
* ``PallasKernel`` (``ops/pallas_kernels.py``) — blocked kernels for peak TPU
  throughput on row-sorted tiles.

Tile convention: a tile is a struct-of-arrays ``(rows, cols, vals)`` of static
length ``max_nnz``, padded with inert entries ``row = col = 0, val = 0``.
Zero-valued padding is harmless in both ops: SDDMM multiplies dots by the
input values (0 at pads) and SpMM scatters ``val * B[col]`` (0 contribution).
This mirrors the reference's own max_nnz double-buffering for in-flight
sparse shifts (`SpmatLocal.hpp:153-169`) — its solution to variable nnz is
already the static-shape solution XLA requires.
"""

from __future__ import annotations

import os
from typing import Protocol

import jax
import jax.numpy as jnp

# Element budget for the gathered/scattered [nnz, R] intermediates of the
# XLA kernel. Both ops materialize nnz*R-element arrays (A[rows]/B[cols]
# and the scatter contributions); past this budget they switch to a
# sequential scan over nnz segments so peak memory stays bounded — the
# reference grid's heavy corner (logM=16, nnz/row=128, R=512) needs
# ~17 GB per gather otherwise, more than a v5e chip's HBM. Shapes are
# static under jit, so this is a trace-time branch, not runtime control
# flow. The default (2^29 elements ≈ 2 GB f32 per intermediate) keeps the
# headline config (2^16 rows, nnz/row=32, R=128 → 2.7e8) on the fused
# single-pass path.
XLA_GATHER_BUDGET = int(os.environ.get("DSDDMM_XLA_GATHER_BUDGET", str(1 << 29)))


class LocalKernel(Protocol):
    """Local kernel plugin boundary (reference `sparse_kernels.h:15-79`)."""

    def sddmm(
        self,
        rows: jax.Array,
        cols: jax.Array,
        vals: jax.Array,
        A: jax.Array,
        B: jax.Array,
    ) -> jax.Array:
        """Return ``vals * rowwise_dot(A[rows], B[cols])``, shape [max_nnz]."""
        ...

    def spmm(
        self,
        rows: jax.Array,
        cols: jax.Array,
        vals: jax.Array,
        B: jax.Array,
        out_rows: int,
    ) -> jax.Array:
        """Return ``S_tile @ B`` as a dense [out_rows, R] array.

        Accumulate (beta=1) semantics are the caller's job: callers add the
        returned partial into their accumulation buffer, matching the
        reference's ``beta=1`` MKL call (`sparse_kernels.cpp:104-107`).
        """
        ...


class XlaKernel:
    """Gather-dot SDDMM + segment-sum SpMM in pure XLA ops.

    ``gather_budget`` overrides the module-level :data:`XLA_GATHER_BUDGET`
    for this instance — the autotuner's chunked-kernel candidate is exactly
    an ``XlaKernel`` with a budget below the tile's nnz*R footprint, which
    forces the sequential-scan path regardless of the env default.
    """

    name = "xla"

    def __init__(self, gather_budget: int | None = None):
        self._gather_budget = gather_budget

    @property
    def gather_budget(self) -> int:
        # Falls back to the module global at CALL time, so tests (and env
        # overrides applied after import) that rebind XLA_GATHER_BUDGET
        # still govern default-constructed kernels.
        if self._gather_budget is not None:
            return self._gather_budget
        return XLA_GATHER_BUDGET

    def sddmm(self, rows, cols, vals, A, B):
        n, r = rows.shape[0], A.shape[-1]
        budget = self.gather_budget
        if n * r <= budget:
            dots = jnp.sum(A[rows] * B[cols], axis=-1)
            return vals * dots.astype(vals.dtype)
        seg = max(1, budget // r)
        n_seg = -(-n // seg)
        pad = n_seg * seg - n
        rows_p = jnp.pad(rows, (0, pad)).reshape(n_seg, seg)
        cols_p = jnp.pad(cols, (0, pad)).reshape(n_seg, seg)
        dots = jax.lax.map(
            lambda rc: jnp.sum(A[rc[0]] * B[rc[1]], axis=-1), (rows_p, cols_p)
        ).reshape(-1)[:n]
        return vals * dots.astype(vals.dtype)

    def spmm(self, rows, cols, vals, B, out_rows: int):
        n, r = rows.shape[0], B.shape[-1]
        out_dtype = jnp.result_type(vals.dtype, B.dtype)
        budget = self.gather_budget
        if n * r <= budget:
            contrib = vals[:, None] * B[cols]
            return jax.ops.segment_sum(contrib, rows, num_segments=out_rows)
        seg = max(1, budget // r)
        n_seg = -(-n // seg)
        pad = n_seg * seg - n
        # Pad entries land at row 0 with val 0 — inert under accumulate,
        # exactly the tile padding convention documented above.
        rows_p = jnp.pad(rows, (0, pad)).reshape(n_seg, seg)
        cols_p = jnp.pad(cols, (0, pad)).reshape(n_seg, seg)
        vals_p = jnp.pad(vals, (0, pad)).reshape(n_seg, seg)

        def step(acc, rcv):
            rr, cc, vv = rcv
            return acc + jax.ops.segment_sum(
                vv[:, None] * B[cc], rr, num_segments=out_rows
            ), None

        out, _ = jax.lax.scan(
            step,
            jnp.zeros((out_rows, r), dtype=out_dtype),
            (rows_p, cols_p, vals_p),
        )
        return out


_REGISTRY = {"xla": XlaKernel}


def get_kernel(name: str) -> LocalKernel:
    """Kernel factory; Pallas registers lazily to keep CPU imports light.

    ``"auto"`` picks Pallas on real TPU backends and XLA elsewhere (the
    Pallas interpreter is not an honest non-TPU fallback).
    """
    if name == "auto":
        import jax

        name = "pallas" if jax.default_backend() == "tpu" else "xla"
    if name == "pallas" and "pallas" not in _REGISTRY:
        try:
            from distributed_sddmm_tpu.ops.pallas_kernels import PallasKernel
        except ImportError as e:
            raise NotImplementedError(
                "the 'pallas' kernel is not available in this build"
            ) from e
        _REGISTRY["pallas"] = PallasKernel
    if name not in _REGISTRY:
        raise ValueError(f"unknown kernel {name!r}; available: {sorted(_REGISTRY)}")
    return _REGISTRY[name]()
