"""Pluggable local SDDMM / SpMM kernels (single device, one sparse tile).

This is the framework's counterpart of the reference's plugin boundary
``KernelImplementation`` (`/root/reference/sparse_kernels.h:15-79`): the
distributed algorithms are written against the :class:`LocalKernel` interface
and any implementation can be swapped in. Implementations:

* :class:`XlaKernel` — pure jax.numpy gather-dot SDDMM and segment-sum SpMM.
  Works on every backend (CPU test meshes included); XLA fuses the gather with
  the rowwise multiply-reduce. This replaces the reference's OpenMP COO loop
  (`sparse_kernels.cpp:13-57`) and MKL ``mkl_sparse_d_mm``
  (`sparse_kernels.cpp:94-121`).
* ``PallasKernel`` (``ops/pallas_kernels.py``) — blocked kernels for peak TPU
  throughput on row-sorted tiles.

Tile convention: a tile is a struct-of-arrays ``(rows, cols, vals)`` of static
length ``max_nnz``, padded with inert entries ``row = col = 0, val = 0``.
Zero-valued padding is harmless in both ops: SDDMM multiplies dots by the
input values (0 at pads) and SpMM scatters ``val * B[col]`` (0 contribution).
This mirrors the reference's own max_nnz double-buffering for in-flight
sparse shifts (`SpmatLocal.hpp:153-169`) — its solution to variable nnz is
already the static-shape solution XLA requires.
"""

from __future__ import annotations

import os
from typing import Protocol

import jax
import jax.numpy as jnp

# Element budget for the gathered/scattered [nnz, R] intermediates of the
# XLA kernel. Both ops materialize nnz*R-element arrays (A[rows]/B[cols]
# and the scatter contributions); past this budget they switch to a
# sequential scan over nnz segments so peak memory stays bounded — the
# reference grid's heavy corner (logM=16, nnz/row=128, R=512) needs
# ~17 GB per gather otherwise, more than a v5e chip's HBM. Shapes are
# static under jit, so this is a trace-time branch, not runtime control
# flow. The default (2^29 elements ≈ 2 GB f32 per intermediate) keeps the
# headline config (2^16 rows, nnz/row=32, R=128 → 2.7e8) on the fused
# single-pass path.
XLA_GATHER_BUDGET = int(os.environ.get("DSDDMM_XLA_GATHER_BUDGET", str(1 << 29)))

# Element budget for the one-shot masked-softmax row statistics of the
# attention epilogue (``attn_stats``). Past it the stats switch to the
# streaming max/denominator scan (the classic online-softmax
# recurrence), which holds one [out_rows] running max and denominator
# instead of [nnz] temporaries. The value array is 1-D, so this budget
# is far larger than the gather budget's per-R accounting.
ATTN_STREAM_BUDGET = int(
    os.environ.get("DSDDMM_ATTN_STREAM_BUDGET", str(1 << 24))
)

#: Finite stand-in for -inf in the masked-softmax passes: segment maxima
#: over empty/masked rows must stay finite (``-inf - -inf`` would NaN the
#: streaming rescale ``exp(m_old - m_new)``), and ``exp(z - NEG)`` of a
#: real logit still overflows to +inf, which every consumer guards with
#: a select. All softmax implementations (XLA flat, Pallas chunk, f64
#: oracle) share this constant so fused/unfused paths stay bit-aligned.
ATTN_NEG = -1e30


class LocalKernel(Protocol):
    """Local kernel plugin boundary (reference `sparse_kernels.h:15-79`)."""

    def sddmm(
        self,
        rows: jax.Array,
        cols: jax.Array,
        vals: jax.Array,
        A: jax.Array,
        B: jax.Array,
    ) -> jax.Array:
        """Return ``vals * rowwise_dot(A[rows], B[cols])``, shape [max_nnz]."""
        ...

    def spmm(
        self,
        rows: jax.Array,
        cols: jax.Array,
        vals: jax.Array,
        B: jax.Array,
        out_rows: int,
    ) -> jax.Array:
        """Return ``S_tile @ B`` as a dense [out_rows, R] array.

        Accumulate (beta=1) semantics are the caller's job: callers add the
        returned partial into their accumulation buffer, matching the
        reference's ``beta=1`` MKL call (`sparse_kernels.cpp:104-107`).
        """
        ...


class XlaKernel:
    """Gather-dot SDDMM + segment-sum SpMM in pure XLA ops.

    ``gather_budget`` overrides the module-level :data:`XLA_GATHER_BUDGET`
    for this instance — the autotuner's chunked-kernel candidate is exactly
    an ``XlaKernel`` with a budget below the tile's nnz*R footprint, which
    forces the sequential-scan path regardless of the env default.
    """

    name = "xla"

    def __init__(self, gather_budget: int | None = None):
        self._gather_budget = gather_budget

    @property
    def gather_budget(self) -> int:
        # Falls back to the module global at CALL time, so tests (and env
        # overrides applied after import) that rebind XLA_GATHER_BUDGET
        # still govern default-constructed kernels.
        if self._gather_budget is not None:
            return self._gather_budget
        return XLA_GATHER_BUDGET

    def sddmm(self, rows, cols, vals, A, B):
        n, r = rows.shape[0], A.shape[-1]
        budget = self.gather_budget
        if n * r <= budget:
            dots = jnp.sum(A[rows] * B[cols], axis=-1)
            return vals * dots.astype(vals.dtype)
        seg = max(1, budget // r)
        n_seg = -(-n // seg)
        pad = n_seg * seg - n
        rows_p = jnp.pad(rows, (0, pad)).reshape(n_seg, seg)
        cols_p = jnp.pad(cols, (0, pad)).reshape(n_seg, seg)
        dots = jax.lax.map(
            lambda rc: jnp.sum(A[rc[0]] * B[rc[1]], axis=-1), (rows_p, cols_p)
        ).reshape(-1)[:n]
        return vals * dots.astype(vals.dtype)

    def spmm(self, rows, cols, vals, B, out_rows: int):
        n, r = rows.shape[0], B.shape[-1]
        out_dtype = jnp.result_type(vals.dtype, B.dtype)
        budget = self.gather_budget
        if n * r <= budget:
            contrib = vals[:, None] * B[cols]
            return jax.ops.segment_sum(contrib, rows, num_segments=out_rows)
        seg = max(1, budget // r)
        n_seg = -(-n // seg)
        pad = n_seg * seg - n
        # Pad entries land at row 0 with val 0 — inert under accumulate,
        # exactly the tile padding convention documented above.
        rows_p = jnp.pad(rows, (0, pad)).reshape(n_seg, seg)
        cols_p = jnp.pad(cols, (0, pad)).reshape(n_seg, seg)
        vals_p = jnp.pad(vals, (0, pad)).reshape(n_seg, seg)

        def step(acc, rcv):
            rr, cc, vv = rcv
            return acc + jax.ops.segment_sum(
                vv[:, None] * B[cc], rr, num_segments=out_rows
            ), None

        out, _ = jax.lax.scan(
            step,
            jnp.zeros((out_rows, r), dtype=out_dtype),
            (rows_p, cols_p, vals_p),
        )
        return out


    # ------------------------------------------------------------------ #
    # Masked-softmax attention epilogue (flat COO layout)
    #
    # SDDMM ⊙ masked-softmax → SpMM *is* block-sparse attention: the
    # SDDMM values are the row-sparse logits, and these passes turn them
    # into row-stochastic attention weights without ever materializing a
    # dense [rows, cols] logit matrix. The mask indicator is ``gate !=
    # 0`` — the tile value vector doubles as the mask (pad lanes carry
    # 0 by the TileSet contract, and a zero-valued mask entry means
    # "present in the pattern but masked out"), so fully masked rows
    # degrade to an all-zero output row, never NaN.
    # ------------------------------------------------------------------ #

    def attn_stats(self, rows, gate, logits, out_rows: int):
        """Per-row masked max and sum-of-exp: ``(m [out_rows],
        d [out_rows])`` with ``m = ATTN_NEG`` and ``d = 0`` for rows
        with no unmasked entries. Beyond :data:`ATTN_STREAM_BUDGET`
        elements the computation streams: a scan over fixed-size
        segments carries the running max and a rescaled denominator
        (``d ← d·exp(m_old − m_new) + Σ exp(z − m_new)``) — the online
        softmax recurrence, so peak memory is one segment plus two
        [out_rows] vectors."""
        n = rows.shape[0]
        dt = logits.dtype
        neg = jnp.asarray(ATTN_NEG, dt)

        def seg_stats(r, g, z, m_floor):
            zsafe = jnp.where(g != 0, z, neg)
            cm = jax.ops.segment_max(zsafe, r, num_segments=out_rows)
            cm = jnp.maximum(cm, neg)  # empty segments: -inf -> finite
            m_new = jnp.maximum(m_floor, cm)
            e = jnp.where(g != 0, jnp.exp(z - m_new[r]), jnp.asarray(0, dt))
            cs = jax.ops.segment_sum(e, r, num_segments=out_rows)
            return m_new, cs

        budget = ATTN_STREAM_BUDGET
        if n <= budget:
            m0 = jnp.full((out_rows,), neg, dt)
            return seg_stats(rows, gate, logits, m0)
        seg = max(1, budget)
        n_seg = -(-n // seg)
        pad = n_seg * seg - n
        rows_p = jnp.pad(rows, (0, pad)).reshape(n_seg, seg)
        gate_p = jnp.pad(gate, (0, pad)).reshape(n_seg, seg)  # pads gate=0
        z_p = jnp.pad(logits, (0, pad)).reshape(n_seg, seg)

        def step(carry, rgz):
            m_run, d_run = carry
            r, g, z = rgz
            m_new, cs = seg_stats(r, g, z, m_run)
            d_new = d_run * jnp.exp(m_run - m_new) + cs
            return (m_new, d_new), None

        init = (jnp.full((out_rows,), neg, dt), jnp.zeros((out_rows,), dt))
        (m, d), _ = jax.lax.scan(step, init, (rows_p, gate_p, z_p))
        return m, d

    def attn_normalize(self, rows, gate, logits, m, d):
        """Masked-softmax weights from the row stats: ``exp(z − m[row]) /
        d[row]`` at unmasked entries, exactly 0 at masked entries, pad
        lanes, and fully masked rows (``d == 0``)."""
        dt = logits.dtype
        sel = (gate != 0) & (d[rows] > 0)
        # exp on the selected-safe argument: an unmasked overflow
        # (z - ATTN_NEG) would manufacture inf before the select.
        e = jnp.exp(jnp.where(sel, logits - m[rows], jnp.asarray(0, dt)))
        return jnp.where(sel, e / jnp.where(sel, d[rows], 1.0), 0.0).astype(dt)

    def attn_softmax(self, rows, gate, logits, out_rows: int):
        """Row-wise masked softmax over flat COO values (stats +
        normalize in one call — the single-tile convenience form; the
        distributed programs call the two halves around their cross-
        device max/denominator merge)."""
        m, d = self.attn_stats(rows, gate, logits, out_rows)
        return self.attn_normalize(rows, gate, logits, m, d)


def attn_merge_stats(stats):
    """Combine per-partition masked-softmax row stats into one frame.

    ``stats`` is a sequence of ``(m, d)`` pairs over the SAME row frame
    (per tile, per band, or per device after a gather): the merged max
    is the elementwise maximum and each partial denominator is rescaled
    into it — the online-softmax merge rule. Empty partitions
    (``m == ATTN_NEG, d == 0``) are absorbed exactly: ``exp(m_b − m)``
    underflows to 0 against any real max and its ``d_b`` is 0 against
    another empty one.
    """
    import functools

    m = functools.reduce(jnp.maximum, [s[0] for s in stats])
    d = sum(s[1] * jnp.exp(s[0] - m) for s in stats)
    return m, d


_REGISTRY = {"xla": XlaKernel}


def get_kernel(name: str) -> LocalKernel:
    """Kernel factory; Pallas registers lazily to keep CPU imports light.

    ``"auto"`` picks Pallas on real TPU backends and XLA elsewhere (the
    Pallas interpreter is not an honest non-TPU fallback).
    """
    if name == "auto":
        import jax

        name = "pallas" if jax.default_backend() == "tpu" else "xla"
    if name == "pallas" and "pallas" not in _REGISTRY:
        try:
            from distributed_sddmm_tpu.ops.pallas_kernels import PallasKernel
        except ImportError as e:
            raise NotImplementedError(
                "the 'pallas' kernel is not available in this build"
            ) from e
        _REGISTRY["pallas"] = PallasKernel
    if name not in _REGISTRY:
        raise ValueError(f"unknown kernel {name!r}; available: {sorted(_REGISTRY)}")
    return _REGISTRY[name]()
