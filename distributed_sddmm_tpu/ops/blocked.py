"""Host-side 2-D block bucketing: flat COO tiles -> MXU chunk lists.

The TPU has no vectorized random gather, so the Pallas kernels
(``ops/pallas_kernels.py``) express SDDMM's A[row]/B[col] row gathers and
SpMM's row scatter as small dense matmuls with on-the-fly one-hot selector
matrices — MXU work instead of memory-system work. For that to pay off, each
matmul must touch only a small dense block, so every tile's nonzeros are
bucketed by ``(row_block, col_block)`` of size ``BM x BN`` and packed into
**chunks of 128** (one VPU lane row per nonzero).

The kernel then runs a 1-D grid over the chunk list; per-chunk scalar
metadata (which blocks to DMA, when to zero / flush the output accumulator)
is scalar-prefetched from SMEM. This mirrors how the reference tiles S into
block columns sized for cache (`/root/reference/SpmatLocal.hpp:541-563`) and
keeps max-size padded buffers for static shapes (`SpmatLocal.hpp:153-169`) —
here the padding target is the chunk grid instead of max_nnz.

Everything in this module is one-time numpy setup on the host, keyed off the
same ``scatter_index`` flat layout that ``parallel/sharding.build_tiles``
produces, so device-side relayout between the flat value layout and the
chunk layout is a cheap gather in both directions.
"""

from __future__ import annotations

import dataclasses
import os

import numpy as np

# Nonzeros per chunk. 128 = one VPU lane row per nonzero; 256 doubles the
# one-hot matmuls' N dimension (same FLOPs/nnz, fewer+larger MXU ops and
# half the per-sub-chunk fixed cost). Env-overridable for whole-process
# probes only (scripts/tune_blocks.py) — every module snapshots it at
# import, so it must never change inside a running process.
CHUNK = int(os.environ.get("DSDDMM_CHUNK", "128"))

# Chunks processed per Pallas grid step (see pallas_kernels._tile_call):
# amortizes the per-step semaphore/DMA fixed cost (scripts/tune_blocks.py
# probes this). Groups are gr-aligned, so larger values cost pad chunks in
# small row blocks. Env-overridable so benchmarks can compare group
# settings without code edits.
DEFAULT_GROUP = int(os.environ.get("DSDDMM_CHUNK_GROUP", "4"))

# Preferred dense block sizes for the one-hot kernels' (row, col) windows.
# Env-overridable for the same reason as DEFAULT_GROUP: bench.py applies the
# best (blocks, group, scatter form) combination measured in
# KERNELS_TPU.jsonl without code edits.
DEFAULT_BLOCK_ROWS = int(os.environ.get("DSDDMM_BLOCK_ROWS", "512"))
DEFAULT_BLOCK_COLS = int(os.environ.get("DSDDMM_BLOCK_COLS", "512"))

# Scatter contraction form ("bt"/"nt") and step batching for the Pallas
# kernels (consumed by ops/pallas_kernels.PallasKernel.__init__); defined
# here so every knob default lives in one module.
DEFAULT_SCATTER_FORM = os.environ.get("DSDDMM_SCATTER_FORM", "bt")
DEFAULT_BATCH_STEP = os.environ.get("DSDDMM_BATCH_STEP", "0") not in ("", "0")


def knob_env_defaults() -> dict:
    """The effective kernel-knob values as the env-var strings bench.py
    passes to its workers — the single source of truth for its
    tuned-vs-first-rung dedup. Values reflect this process's environment
    (each knob is env-overridable), falling back to the literals above."""
    return {
        "DSDDMM_BLOCK_ROWS": str(DEFAULT_BLOCK_ROWS),
        "DSDDMM_BLOCK_COLS": str(DEFAULT_BLOCK_COLS),
        "DSDDMM_CHUNK_GROUP": str(DEFAULT_GROUP),
        "DSDDMM_SCATTER_FORM": DEFAULT_SCATTER_FORM,
        "DSDDMM_CHUNK": str(CHUNK),
        "DSDDMM_BATCH_STEP": "1" if DEFAULT_BATCH_STEP else "0",
    }

# meta word packing: | gr (15 bits) | gc (15 bits) | last | first |
_GR_SHIFT = 17
_GC_SHIFT = 2
MAX_BLOCKS = 1 << 15


def pick_block(frame: int, preferred: int = 512) -> int:
    """Largest power-of-two block size <= preferred that the padded frame
    supports. Frames are padded to a multiple of the result, so any
    power-of-two works; smaller frames use one block."""
    b = preferred
    while b > CHUNK and b >= 2 * frame:
        b //= 2
    return b


def pad_frame(frame: int, block: int) -> int:
    return -(-frame // block) * block


@dataclasses.dataclass(frozen=True)
class BlockedMeta:
    """Host-side chunk-list encoding for every (device, tile) bucket.

    Arrays are indexed by flat bucket id ``b`` (device-major, tile-minor) —
    the same ordering as ``build_tiles``'s flat layout. The chunk layout IS
    the tile's flat nonzero layout: position ``b * C * CHUNK + chunk * CHUNK
    + lane`` holds one nonzero (or an inert pad), so value vectors need no
    relayout between the XLA and Pallas kernel paths.
    """

    lr: np.ndarray        # [NB, C, CHUNK] int32 — row within its row block
    lc: np.ndarray        # [NB, C, CHUNK] int32 — col within its col block
    meta: np.ndarray      # [NB, C] int32 — packed (gr, gc, first, last)
    host_to_chunk: np.ndarray  # [nnz] int64 — host nonzero -> absolute position
    pad_lane: np.ndarray  # [NB, C, CHUNK] bool — True at inert pad lanes
    bm: int               # row block size
    bn: int               # col block size
    gr_blocks: int        # row blocks per (padded) tile frame
    gc_blocks: int
    n_chunks: int         # C, padded axis-max chunks per bucket
    group: int = 1        # chunks per kernel grid step (gr-aligned groups)

    @property
    def rows_pad(self) -> int:
        return self.gr_blocks * self.bm

    @property
    def cols_pad(self) -> int:
        return self.gc_blocks * self.bn

    def global_rows(self) -> np.ndarray:
        """Tile-frame row index of every chunk lane, [NB, C, CHUNK] int32
        (pad lanes -> 0). Makes the chunk layout consumable by the flat
        gather/segment-sum kernels."""
        gr, _, _, _ = unpack_meta(self.meta)
        rows = gr[:, :, None] * self.bm + self.lr
        return np.where(self.pad_lane, 0, rows).astype(np.int32)

    def global_cols(self) -> np.ndarray:
        _, gc, _, _ = unpack_meta(self.meta)
        cols = gc[:, :, None] * self.bn + self.lc
        return np.where(self.pad_lane, 0, cols).astype(np.int32)


def padded_lane_count(meta) -> int:
    """Inert pad lanes in one chunk-list encoding (``BlockedMeta`` or
    codegen's ``BandedMeta``) — the counted waste metric the banked
    kernel variants exist to shrink."""
    return int(meta.pad_lane.sum())


def padded_lane_frac(meta) -> float:
    total = meta.pad_lane.size
    return float(meta.pad_lane.sum()) / total if total else 0.0


def pack_meta(gr, gc, first, last):
    return (
        (gr.astype(np.int64) << _GR_SHIFT)
        | (gc.astype(np.int64) << _GC_SHIFT)
        | (last.astype(np.int64) << 1)
        | first.astype(np.int64)
    ).astype(np.int32)


def build_blocked(
    n_buckets: int,
    bucket: np.ndarray,   # host nnz order -> flat (device, tile) bucket id
    local_r: np.ndarray,  # host nnz order, tile-local rows
    local_c: np.ndarray,
    tile_rows: int,
    tile_cols: int,
    block_rows: int | None = None,
    block_cols: int | None = None,
    group: int = 1,
) -> BlockedMeta:
    """Build the chunk-list encoding.

    Guarantees the kernels rely on:

    * chunks of one bucket are sorted by ``(gr, gc)``;
    * every row block ``gr`` of every bucket has >= 1 chunk (so the output
      accumulator is always zeroed and flushed, even for empty row blocks);
    * the ``first`` / ``last`` flags mark the boundary chunks of each
      bucket's ``gr`` group;
    * pad lanes carry ``lr = lc = 0`` and are flagged in ``pad_lane`` (value
      vectors must be zero there — ``build_tiles`` enforces this via the
      mask);
    * trailing bucket-pad chunks (to reach the shared C) have no flags set
      and gr = gr_blocks-1, gc = gc_blocks-1: they keep the kernel's output
      window pinned on the bucket's LAST (already flushed) row block. Pad
      chunks must never remap the output window — Pallas output buffers are
      write-only, so a remapped-but-unwritten window would flush stale VMEM
      over a correct block at grid end;
    * with ``group`` > 1, every bucket's ``gr`` group spans a multiple of
      ``group`` chunks and C is a multiple of ``group``, so a kernel grid
      step processing ``group`` consecutive chunks always stays inside one
      row-block window (the per-step output/stationary index maps read the
      step's first chunk). Group-pad chunks sit at the END of their gr
      group (appended to its last (gr, gc) pair) with all-pad lanes; since
      the first/last flags are derived from gr-group adjacency over the
      pad-EXPANDED chunk sequence, the ``last`` flag lands on the group's
      final chunk — a pad chunk when deficit padding was added. That is by
      design: the flush then happens at the group's true end (pads add
      nothing to the accumulator first), and a flag therefore does NOT
      imply the chunk carries real nonzeros.
    """
    if block_rows is None:
        block_rows = DEFAULT_BLOCK_ROWS
    if block_cols is None:
        block_cols = DEFAULT_BLOCK_COLS
    bm = pick_block(tile_rows, block_rows)
    bn = pick_block(tile_cols, block_cols)
    gr_blocks = max(-(-tile_rows // bm), 1)
    gc_blocks = max(-(-tile_cols // bn), 1)
    if gr_blocks > MAX_BLOCKS or gc_blocks > MAX_BLOCKS:
        raise ValueError(
            f"tile frame {tile_rows}x{tile_cols} exceeds the packed-meta "
            f"limit of {MAX_BLOCKS} blocks per axis"
        )

    nnz = local_r.size
    bucket = bucket.astype(np.int64)
    gr = (local_r // bm).astype(np.int64)
    gc = (local_c // bn).astype(np.int64)

    # Sort nonzeros by (bucket, gr, gc); stable keeps host order within.
    from distributed_sddmm_tpu import native

    key = (bucket * gr_blocks + gr) * gc_blocks + gc
    n_pairs = n_buckets * gr_blocks * gc_blocks
    pair_counts, order = native.bucket_sort(key, n_pairs)
    key_sorted = key[order]

    # Chunks per (bucket, gr, gc) pair.
    pair_chunks = -(-pair_counts // CHUNK)

    # Ensure >= 1 chunk for every (bucket, gr): give empty gr GROUPS one pad
    # chunk at gc = 0.
    group_chunks = pair_chunks.reshape(n_buckets, gr_blocks, gc_blocks)
    group_tot = group_chunks.sum(axis=2)
    need_pad_group = group_tot == 0
    pair_chunks = group_chunks.copy()
    pair_chunks[:, :, 0][need_pad_group] = 1
    if group > 1:
        # Pad every (bucket, gr) group to a multiple of `group` chunks so a
        # G-chunk grid step never straddles a row-block boundary; the pad
        # chunks ride on the group's last (gr, gc) pair, after its real
        # chunks.
        tot = pair_chunks.sum(axis=2)
        deficit = (-tot) % group
        pair_chunks[:, :, -1] += deficit
    pair_chunks = pair_chunks.reshape(-1)

    chunks_per_bucket = pair_chunks.reshape(n_buckets, -1).sum(axis=1)
    C = max(int(chunks_per_bucket.max(initial=0)), 1)
    C = -(-C // group) * group

    # Chunk start offset (within its bucket) for every pair.
    pair_chunk_start = np.zeros(n_pairs, dtype=np.int64)
    np.cumsum(pair_chunks[:-1], out=pair_chunk_start[1:])
    # pair_chunk_start counts from the global running total; rebase per bucket
    bucket_first_pair = (
        np.arange(n_buckets) * gr_blocks * gc_blocks
    )
    pair_chunk_start -= np.repeat(
        pair_chunk_start[bucket_first_pair], gr_blocks * gc_blocks
    )

    # Place each nonzero: chunk = pair's start + within//CHUNK, lane = within%CHUNK.
    pair_nnz_start = np.zeros(n_pairs, dtype=np.int64)
    np.cumsum(pair_counts[:-1], out=pair_nnz_start[1:])
    within = np.arange(nnz, dtype=np.int64) - pair_nnz_start[key_sorted]
    chunk_in_bucket = pair_chunk_start[key_sorted] + within // CHUNK
    lane = within % CHUNK
    pos_sorted = (bucket[order] * C + chunk_in_bucket) * CHUNK + lane

    total = n_buckets * C * CHUNK
    lr_flat = np.zeros(total, dtype=np.int32)
    lc_flat = np.zeros(total, dtype=np.int32)
    pad_lane = np.ones(total, dtype=bool)
    lr_flat[pos_sorted] = (local_r[order] % bm).astype(np.int32)
    lc_flat[pos_sorted] = (local_c[order] % bn).astype(np.int32)
    pad_lane[pos_sorted] = False

    host_to_chunk = np.empty(nnz, dtype=np.int64)
    host_to_chunk[order] = pos_sorted

    # Packed per-chunk metadata. Trailing bucket-pad chunks default to the
    # last (gr, gc) block with no flags, pinning the output window (see
    # docstring).
    meta = np.full(
        (n_buckets, C),
        int(pack_meta(
            np.int64(gr_blocks - 1), np.int64(gc_blocks - 1),
            np.int64(0), np.int64(0),
        )),
        dtype=np.int32,
    )
    pair_gr = (np.arange(n_pairs) // gc_blocks) % gr_blocks
    pair_gc = np.arange(n_pairs) % gc_blocks
    pair_bucket = np.arange(n_pairs) // (gr_blocks * gc_blocks)
    # Expand pairs to chunks; a bucket's chunks are consecutive and ordered
    # by (gr, gc), so positions within the bucket are just a running index.
    ch_bucket = np.repeat(pair_bucket, pair_chunks)
    ch_gr = np.repeat(pair_gr, pair_chunks)
    ch_gc = np.repeat(pair_gc, pair_chunks)
    bucket_chunk_offset = np.zeros(n_buckets, dtype=np.int64)
    np.cumsum(chunks_per_bucket[:-1], out=bucket_chunk_offset[1:])
    ch_pos = np.arange(ch_bucket.size, dtype=np.int64) - np.repeat(
        bucket_chunk_offset, chunks_per_bucket
    )
    # first/last chunk of each bucket's gr group (groups are contiguous).
    grp_key = ch_bucket * gr_blocks + ch_gr
    first = np.ones(ch_bucket.size, dtype=np.int64)
    first[1:] = grp_key[1:] != grp_key[:-1]
    last = np.ones(ch_bucket.size, dtype=np.int64)
    last[:-1] = grp_key[1:] != grp_key[:-1]
    meta[ch_bucket, ch_pos] = pack_meta(ch_gr, ch_gc, first, last)

    return BlockedMeta(
        lr=lr_flat.reshape(n_buckets, C, CHUNK),
        lc=lc_flat.reshape(n_buckets, C, CHUNK),
        meta=meta,
        host_to_chunk=host_to_chunk,
        pad_lane=pad_lane.reshape(n_buckets, C, CHUNK),
        bm=bm,
        bn=bn,
        gr_blocks=gr_blocks,
        gc_blocks=gc_blocks,
        n_chunks=C,
        group=group,
    )


def pad_chunk_count(meta: BlockedMeta, c_new: int) -> BlockedMeta:
    """Append trailing pad chunks to every bucket to reach ``c_new`` chunks.

    Used when the chunk-flat length must divide evenly (e.g. into fiber
    value slices). Pad chunks follow the window-pinning convention (last
    (gr, gc) block, no flags) and are all-pad lanes. ``c_new`` is rounded up
    to the encoding's group multiple."""
    C = meta.n_chunks
    c_new = -(-c_new // meta.group) * meta.group
    if c_new < C:
        raise ValueError(f"cannot shrink chunk count {C} -> {c_new}")
    if c_new == C:
        return meta
    nb = meta.lr.shape[0]
    extra = c_new - C
    pad_word = int(pack_meta(
        np.int64(meta.gr_blocks - 1), np.int64(meta.gc_blocks - 1),
        np.int64(0), np.int64(0),
    ))
    return dataclasses.replace(
        meta,
        lr=np.concatenate(
            [meta.lr, np.zeros((nb, extra, CHUNK), np.int32)], axis=1
        ),
        lc=np.concatenate(
            [meta.lc, np.zeros((nb, extra, CHUNK), np.int32)], axis=1
        ),
        meta=np.concatenate(
            [meta.meta, np.full((nb, extra), pad_word, np.int32)], axis=1
        ),
        pad_lane=np.concatenate(
            [meta.pad_lane, np.ones((nb, extra, CHUNK), bool)], axis=1
        ),
        host_to_chunk=(
            meta.host_to_chunk
            + (meta.host_to_chunk // (C * CHUNK)) * (extra * CHUNK)
        ),
        n_chunks=c_new,
    )


def unpack_meta(word):
    """Inverse of :func:`pack_meta` (numpy or jax arrays).

    gr is masked like gc: the word is int32, so an unmasked arithmetic shift
    would sign-extend gr >= 16384 into negative block indices."""
    gr = (word >> _GR_SHIFT) & (MAX_BLOCKS - 1)
    gc = (word >> _GC_SHIFT) & (MAX_BLOCKS - 1)
    last = (word >> 1) & 1
    first = word & 1
    return gr, gc, first, last
