from distributed_sddmm_tpu.ops.kernels import LocalKernel, XlaKernel, get_kernel

__all__ = ["LocalKernel", "XlaKernel", "get_kernel"]
