"""Online serving layer: request queue, micro-batching, warm engines.

Turns the offline apps into request/response services:

* :mod:`~distributed_sddmm_tpu.serve.queue` — bounded admission +
  dynamic micro-batching + backpressure (:class:`ShedError`).
* :mod:`~distributed_sddmm_tpu.serve.engine` — warm-model execution
  over a bucketed, compile-ahead program cache with the resilience
  ladder (retry → degrade-to-serial) around every dispatch.
* :mod:`~distributed_sddmm_tpu.serve.workloads` — the two paper apps as
  endpoints: ALS user fold-in + top-k recommendation, GAT node scoring.
* :mod:`~distributed_sddmm_tpu.serve.slo` — SLO specs (``DSDDMM_SLO``),
  latency/occupancy recording, and the open-loop Poisson load generator
  behind ``bench serve``.

The :func:`build_als_engine` / :func:`build_gat_engine` helpers are the
"zero to serving" path the CLI and smoke script use: autotune-plan the
strategy, warm the model, wrap it in an engine.
"""

from __future__ import annotations

from typing import Optional

from distributed_sddmm_tpu.serve.engine import ServingEngine
from distributed_sddmm_tpu.serve.queue import (
    DEFAULT_TENANT, Request, RequestError, RequestQueue, ShedError,
    TenantSpec,
)
from distributed_sddmm_tpu.serve.slo import (
    LatencyRecorder, SLOSpec, parse_tenants, percentile, run_load,
    tenants_from_env,
)
from distributed_sddmm_tpu.serve.workloads import (
    ALSFoldInTopK, AttentionTokenScore, GATNodeScore, ServingWorkload,
    bucket_for,
)

__all__ = [
    "ALSFoldInTopK", "AttentionTokenScore", "DEFAULT_TENANT", "GATNodeScore",
    "LatencyRecorder", "Request", "RequestError", "RequestQueue",
    "ServingEngine", "ServingWorkload", "ShedError", "SLOSpec", "TenantSpec",
    "bucket_for", "build_als_engine", "build_attention_engine",
    "build_gat_engine", "parse_tenants", "percentile", "run_load",
    "tenants_from_env",
]


def build_als_engine(
    S,
    R: int = 16,
    train_steps: int = 2,
    cg_iters: int = 5,
    k: int = 10,
    plan_mode: str = "model",
    devices=None,
    item_buckets=None,
    **engine_kw,
) -> ServingEngine:
    """Plan, train, and wrap a warm ALS fold-in endpoint.

    ``train_steps`` alternating steps warm the factor matrices (real
    deployments would restore a checkpoint instead; pass
    ``train_steps=0`` and load factors onto ``model`` yourself).
    """
    from distributed_sddmm_tpu.models.als import DistributedALS

    model = DistributedALS.from_plan(
        S, R, devices=devices, plan_mode=plan_mode
    )
    if train_steps:
        model.run_cg(train_steps, cg_iters=cg_iters)
    elif model.A is None:
        model.initialize_embeddings()
    kw = {"k": k}
    if item_buckets is not None:
        kw["item_buckets"] = tuple(item_buckets)
    workload = ALSFoldInTopK(model, **kw)
    return ServingEngine(workload, **engine_kw)


def build_attention_engine(
    S,
    R: int = 16,
    window: int | None = None,
    plan_mode: str = "model",
    devices=None,
    token_buckets=None,
    seed: int = 0,
    **engine_kw,
) -> ServingEngine:
    """Plan, run, and wrap a token-scoring attention endpoint.

    ``S`` is the block-sparse attention mask (see
    ``distributed_sddmm_tpu.masks``). The expensive whole-sequence half
    — ONE fused SDDMM → masked-softmax → SpMM dispatch over seeded
    context embeddings — runs here at build time through the
    autotune-planned 1.5D dense-shift strategy; its output rows become
    the cached context the per-request sliding-window scorer serves
    from. The per-request math is built exclusively from
    batch-dim-invariant ops, so replies are bit-identical across
    arrival order, batch composition, and padding.
    """
    import numpy as np

    from distributed_sddmm_tpu.autotune import Problem, get_plan
    from distributed_sddmm_tpu.bench.harness import ATTENTION_CAPABLE
    from distributed_sddmm_tpu.serve.workloads import AttentionTokenScore

    plan = get_plan(Problem.from_coo(S, R), mode=plan_mode)
    if plan.algorithm in ATTENTION_CAPABLE:
        alg = plan.instantiate(S, R=R, devices=devices)
    else:
        # The plan space includes layouts that cannot carry the softmax
        # row denominator (sparse-shift/Cannon); keep the plan's kernel
        # choice but pin the attention-capable dense-shift layout — and
        # restamp the plan with what actually runs: `algorithm` and `c`
        # are runstore config axes, so a record claiming the unpinned
        # layout would pool into the wrong gate baseline.
        import dataclasses

        from distributed_sddmm_tpu.parallel.dense_shift_15d import (
            DenseShift15D,
        )

        alg = DenseShift15D(
            S, R=R, c=1, fusion_approach=2, kernel=plan.make_kernel(),
            devices=devices,
        )
        plan = dataclasses.replace(
            plan, algorithm="15d_fusion2", c=1, source=f"{plan.source}-pinned"
        )
    rng = np.random.default_rng(seed)
    X = (rng.standard_normal((max(S.M, S.N), R)) / np.sqrt(R)).astype(
        np.float32
    )
    A = alg.put_a(X[: alg.M])
    B = alg.put_b(X[: alg.N])
    out, _ = alg.fused_attention(A, B, alg.like_s_values(1.0))
    context = alg.host_a(out)
    kw = {"window": window}
    if token_buckets is not None:
        kw["token_buckets"] = tuple(token_buckets)
    workload = AttentionTokenScore(context, d_ops=alg, **kw)
    # The serve CLI reads engine.workload.model.d_ops / .plan for its
    # record; this workload carries the strategy directly.
    workload.model = workload
    workload.plan = plan
    return ServingEngine(workload, **engine_kw)


def build_gat_engine(
    S,
    R: int = 16,
    num_layers: int = 2,
    plan_mode: str = "model",
    devices=None,
    node_buckets=None,
    **engine_kw,
) -> ServingEngine:
    """Plan, build, and wrap a warm GAT node-scoring endpoint (the
    forward pass runs once at workload construction; ``refresh()`` it
    after weight updates)."""
    from distributed_sddmm_tpu.bench.harness import _gat_layers
    from distributed_sddmm_tpu.models.gat import GAT

    model = GAT.from_plan(
        S, _gat_layers(R, num_layers), devices=devices, plan_mode=plan_mode
    )
    kw = {}
    if node_buckets is not None:
        kw["node_buckets"] = tuple(node_buckets)
    workload = GATNodeScore(model, **kw)
    return ServingEngine(workload, **engine_kw)
