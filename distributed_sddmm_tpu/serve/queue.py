"""Thread-safe request queue with dynamic micro-batching + backpressure.

The front door of the serving layer (`serve/engine.py` is the back):
client threads :meth:`~RequestQueue.submit` individual requests, the
engine's runner thread pulls *micro-batches* — up to ``max_batch``
requests, or whatever has arrived when ``max_wait_ms`` expires after the
first request of the batch, whichever comes first. That is the dynamic
batching bargain from the serving literature (and the same
amortize-setup-over-many-steps insight the offline kernels already
exploit via step-batching): one compiled-program dispatch serves many
requests, with a bounded latency tax on the first arrival.

Admission control is a hard depth bound: a full queue **sheds** new
requests with :class:`ShedError` carrying a ``retry_after_s`` hint
instead of growing without bound — queueing-theory 101 says an open-loop
arrival process above capacity turns an unbounded queue into unbounded
latency; shedding converts that into an explicit, client-visible signal
while requests already admitted still meet their latency target.

Every request carries its timeline (enqueue → admit → execute → reply
monotonic stamps, all read through ``obs.clock``); ``serve/slo.py``
turns those into the percentile histograms the SLO gate judges.

Multi-tenant QoS (PR 16): the queue holds one FIFO **per tenant class**
(:class:`TenantSpec` — a name, a scheduling weight, and an optional
per-tenant SLO spec the recorder judges burn against) and extracts
micro-batches by **stride scheduling**: each tenant carries a virtual
``pass`` advanced by ``1/weight`` per dequeued request, and the batcher
always drains the non-empty tenant with the smallest pass — over any
busy window tenants receive service in weight proportion, while a lone
tenant degenerates to the exact FIFO the single-tenant engine always
had. Admission stays one shared ``max_depth`` bound (a fleet router
does cross-replica isolation; inside one replica the bound is the
latency protection), but sheds and submissions are **counted per
tenant** so the serve record, telemetry snapshot and gate axes can
judge each class separately.

Trace context: the request id minted at :meth:`~RequestQueue.submit` is
the correlation key the whole serving path carries — the queue emits a
``serve:enqueue`` event per admission (and ``serve:shed`` per
rejection), the engine's batch spans list their member ``req_ids``, and
the per-request ``serve:reply`` event carries the full segment
decomposition, so ``tools/tracereport.request_chains`` can reconstruct
any request's enqueue→reply timeline from the trace alone.
"""

from __future__ import annotations

import collections
import dataclasses
import itertools
import threading
from typing import Any, Optional

from distributed_sddmm_tpu.obs import clock
from distributed_sddmm_tpu.obs import trace as obs_trace

#: The implicit tenant every un-labeled request belongs to.
DEFAULT_TENANT = "default"


@dataclasses.dataclass(frozen=True)
class TenantSpec:
    """One tenant class: a scheduling weight and (optionally) its own
    SLO. ``slo`` is opaque to the queue (an
    :class:`~distributed_sddmm_tpu.serve.slo.SLOSpec`; ``serve/slo.py``
    parses specs and computes per-tenant burn) — the queue only
    schedules and counts."""

    name: str
    weight: float = 1.0
    slo: Optional[object] = None

    def __post_init__(self):
        if not self.name or any(c in self.name for c in ":;,= \t"):
            raise ValueError(f"bad tenant name {self.name!r}")
        if not self.weight > 0:
            raise ValueError(
                f"tenant {self.name!r} weight must be > 0, "
                f"got {self.weight}"
            )


def _normalize_tenants(tenants) -> dict[str, TenantSpec]:
    if not tenants:
        return {DEFAULT_TENANT: TenantSpec(DEFAULT_TENANT)}
    if isinstance(tenants, dict):
        specs = list(tenants.values())
    else:
        specs = list(tenants)
    out = {}
    for spec in specs:
        if spec.name in out:
            raise ValueError(f"duplicate tenant {spec.name!r}")
        out[spec.name] = spec
    return out


class ShedError(RuntimeError):
    """Request rejected by admission control (queue at ``max_depth``).

    ``retry_after_s`` is the server's drain-time estimate for the current
    backlog — the value an HTTP front end would surface as a 429
    ``Retry-After`` header.
    """

    def __init__(self, msg: str, retry_after_s: float = 0.0):
        super().__init__(msg)
        self.retry_after_s = retry_after_s


class RequestError(RuntimeError):
    """The engine failed to produce a reply for this request (persistent
    fault after retries AND the serial fallback failed)."""


class Request:
    """One in-flight request: payload + reply slot + timeline stamps.

    The reply slot is a one-shot event; :meth:`result` blocks the caller
    until the engine delivers (or raises what the engine recorded).
    Timeline stamps are monotonic ``obs.clock.now()`` values filled in
    by the queue (``t_enqueue``), the batcher (``t_admit``), and the
    engine (``t_execute``, ``t_reply``); consecutive stamps bound the
    segments ``queue_s`` / ``batch_wait_s`` / ``execute_s``, which
    partition ``total_s`` exactly.
    """

    __slots__ = (
        "req_id", "payload", "tenant", "fleet", "t_enqueue", "t_admit",
        "t_execute", "t_reply", "degraded", "_done", "_value", "_error",
    )

    def __init__(self, req_id: int, payload: Any,
                 tenant: str = DEFAULT_TENANT,
                 fleet: Optional[dict] = None):
        self.req_id = req_id
        self.payload = payload
        self.tenant = tenant
        #: Decoded fleet trace context (``X-DSDDMM-Trace``) this request
        #: arrived with, or None for a direct (non-fleet) submission.
        self.fleet = fleet
        self.t_enqueue: float = 0.0
        self.t_admit: Optional[float] = None
        self.t_execute: Optional[float] = None
        self.t_reply: Optional[float] = None
        #: Set by the engine when this reply came off the serial fallback
        #: rung instead of the compiled program.
        self.degraded: bool = False
        self._done = threading.Event()
        self._value: Any = None
        self._error: Optional[BaseException] = None

    # -- engine side --------------------------------------------------- #

    def set_result(self, value: Any) -> None:
        self.t_reply = clock.now()
        self._value = value
        self._done.set()

    def set_error(self, err: BaseException) -> None:
        self.t_reply = clock.now()
        self._error = err
        self._done.set()

    # -- client side --------------------------------------------------- #

    def done(self) -> bool:
        return self._done.is_set()

    def result(self, timeout_s: Optional[float] = None) -> Any:
        """Block until the reply lands; raises the engine's recorded
        error, or ``TimeoutError`` if no reply arrives in time."""
        if not self._done.wait(timeout_s):
            raise TimeoutError(
                f"request {self.req_id} unanswered after {timeout_s}s"
            )
        if self._error is not None:
            raise self._error
        return self._value

    # -- timeline ------------------------------------------------------ #

    def stage_latencies_s(self) -> dict:
        """{queue, batch_wait, execute, total} seconds (None-safe:
        requests that were shed or errored mid-flight report what they
        have). ``queue_s`` (enqueue→admit), ``batch_wait_s``
        (admit→dispatch) and ``execute_s`` (dispatch→reply) partition
        ``total_s`` exactly — the invariant
        ``tools/tracereport.request_chains`` verifies per request."""
        out = {}
        if self.t_admit is not None:
            out["queue_s"] = self.t_admit - self.t_enqueue
        if self.t_admit is not None and self.t_execute is not None:
            out["batch_wait_s"] = self.t_execute - self.t_admit
        if self.t_execute is not None and self.t_reply is not None:
            out["execute_s"] = self.t_reply - self.t_execute
        if self.t_reply is not None:
            out["total_s"] = self.t_reply - self.t_enqueue
        return out


class RequestQueue:
    """Bounded FIFO with micro-batch extraction.

    ``max_depth`` bounds admission (excess submissions shed);
    ``max_batch``/``max_wait_ms`` shape the micro-batches
    :meth:`next_batch` hands the engine. ``drain_rate_hint`` (requests/s,
    updated by the engine from observed throughput) feeds the
    ``retry_after_s`` hint on shed. ``tenants`` (a list/dict of
    :class:`TenantSpec`) enables weighted-fair scheduling across tenant
    classes; omitted, the queue is the single implicit
    :data:`DEFAULT_TENANT` and behaves exactly as it always has.
    """

    def __init__(
        self,
        max_depth: int = 256,
        max_batch: int = 16,
        max_wait_ms: float = 5.0,
        tenants=None,
    ):
        if max_depth < 1 or max_batch < 1:
            raise ValueError("max_depth and max_batch must be >= 1")
        self.max_depth = int(max_depth)
        self.max_batch = int(max_batch)
        self.max_wait_ms = float(max_wait_ms)
        self.tenants: dict[str, TenantSpec] = _normalize_tenants(tenants)
        #: One FIFO per tenant class; total depth is what admission
        #: bounds.
        self._queues: dict[str, collections.deque[Request]] = {
            name: collections.deque() for name in self.tenants
        }
        #: Stride scheduling state: each dequeue advances the tenant's
        #: virtual pass by 1/weight; the batcher drains the non-empty
        #: tenant with the smallest pass.
        self._stride = {
            name: 1.0 / spec.weight for name, spec in self.tenants.items()
        }
        self._pass = {name: 0.0 for name in self.tenants}
        self._depth = 0
        self._lock = threading.Lock()
        self._not_empty = threading.Condition(self._lock)
        self._ids = itertools.count()
        self._closed = False
        self.shed_count = 0
        self.submitted_count = 0
        self.tenant_shed = {name: 0 for name in self.tenants}
        self.tenant_submitted = {name: 0 for name in self.tenants}
        #: Engine-maintained throughput estimate for retry_after hints.
        self.drain_rate_hint: float = 0.0

    # ------------------------------------------------------------------ #
    # Client side
    # ------------------------------------------------------------------ #

    def submit(self, payload: Any, tenant: str = DEFAULT_TENANT,
               trace_ctx: Optional[dict] = None) -> Request:
        """Admit one request (raises :class:`ShedError` when full, or
        ``RuntimeError`` after :meth:`close`). Admissions and sheds emit
        ``serve:enqueue`` / ``serve:shed`` trace events carrying the
        request id — the head of the request's trace chain. An unknown
        ``tenant`` raises ``ValueError`` — a typo'd class silently
        scheduled at default weight would defeat the QoS contract.
        ``trace_ctx`` is the decoded fleet context a router attached to
        this request; the enqueue event records it so the replica chain
        carries its fleet parent (``fleet_req``/``fleet_shard``/
        ``fleet_span``) into the merged trace."""
        if tenant not in self.tenants:
            raise ValueError(
                f"unknown tenant {tenant!r}; declared: "
                f"{sorted(self.tenants)}"
            )
        with self._lock:
            if self._closed:
                raise RuntimeError("queue is closed")
            if self._depth >= self.max_depth:
                self.shed_count += 1
                self.tenant_shed[tenant] += 1
                depth = self._depth
                rate = self.drain_rate_hint
                retry_after = (
                    depth / rate if rate > 0
                    else self.max_wait_ms / 1e3 * depth / self.max_batch
                )
                shed_id = next(self._ids)
            else:
                req = Request(next(self._ids), payload, tenant=tenant,
                              fleet=trace_ctx)
                req.t_enqueue = clock.now()
                q = self._queues[tenant]
                if not q:
                    # A tenant waking from idle must not replay the
                    # service it did not ask for: its pass catches up to
                    # the busiest tenants' floor instead of draining a
                    # backlog of virtual credit.
                    floor = min(
                        (self._pass[t] for t, d in self._queues.items()
                         if d), default=self._pass[tenant],
                    )
                    self._pass[tenant] = max(self._pass[tenant], floor)
                q.append(req)
                self._depth += 1
                self.submitted_count += 1
                self.tenant_submitted[tenant] += 1
                depth = self._depth
                self._not_empty.notify()
                shed_id = None
        fleet_attrs = {}
        if trace_ctx:
            fleet_attrs = {
                "fleet_req": trace_ctx.get("req"),
                "fleet_shard": trace_ctx.get("shard"),
                "fleet_span": trace_ctx.get("span"),
            }
        if shed_id is not None:
            obs_trace.event("serve:shed", req=shed_id, depth=depth,
                            tenant=tenant,
                            retry_after_s=round(retry_after, 6),
                            **fleet_attrs)
            raise ShedError(
                f"queue full ({depth}/{self.max_depth}); "
                f"retry after ~{retry_after:.3f}s",
                retry_after_s=retry_after,
            )
        if obs_trace.enabled():
            obs_trace.event("serve:enqueue", req=req.req_id, depth=depth,
                            tenant=tenant, **fleet_attrs)
        return req

    def depth(self) -> int:
        with self._lock:
            return self._depth

    def tenant_depths(self) -> dict[str, int]:
        """Live per-tenant backlog (telemetry snapshot field)."""
        with self._lock:
            return {name: len(q) for name, q in self._queues.items()}

    # ------------------------------------------------------------------ #
    # Engine side
    # ------------------------------------------------------------------ #

    def next_batch(self, timeout_s: Optional[float] = None) -> list[Request]:
        """Block for the next micro-batch.

        Returns as soon as ``max_batch`` requests are waiting, or
        ``max_wait_ms`` after the FIRST request of the batch arrived —
        the arrival of request #1 starts the clock, so a lone request
        pays at most ``max_wait_ms`` of batching latency. Returns ``[]``
        on ``timeout_s`` with nothing queued, or when closed and empty.

        Batch membership is stride-scheduled across tenant classes:
        each slot goes to the non-empty tenant with the smallest
        virtual pass (advanced by ``1/weight`` per dequeue), FIFO
        within a tenant — weighted-fair service over any busy window,
        exact FIFO with a single tenant.
        """
        deadline = (
            clock.now() + timeout_s if timeout_s is not None else None
        )
        with self._not_empty:
            while not self._depth:
                if self._closed:
                    return []
                remaining = None
                if deadline is not None:
                    remaining = deadline - clock.now()
                    if remaining <= 0:
                        return []
                self._not_empty.wait(remaining)
            # First arrival in hand: linger up to max_wait_ms for peers.
            batch_deadline = (
                min(q[0].t_enqueue for q in self._queues.values() if q)
                + self.max_wait_ms / 1e3
            )
            while (
                self._depth < self.max_batch
                and not self._closed
            ):
                linger = batch_deadline - clock.now()
                if linger <= 0:
                    break
                self._not_empty.wait(linger)
            batch = []
            while self._depth and len(batch) < self.max_batch:
                tenant = min(
                    (t for t, q in self._queues.items() if q),
                    key=lambda t: (self._pass[t], t),
                )
                batch.append(self._queues[tenant].popleft())
                self._pass[tenant] += self._stride[tenant]
                self._depth -= 1
        t_admit = clock.now()
        for req in batch:
            req.t_admit = t_admit
        return batch

    def close(self) -> None:
        """Stop admitting; wake any blocked :meth:`next_batch`. Requests
        already queued remain drainable."""
        with self._lock:
            self._closed = True
            self._not_empty.notify_all()

    @property
    def closed(self) -> bool:
        return self._closed
