"""The serving engine: warm model, bucketed program cache, runner thread.

One engine serves one workload (`serve/workloads.py`) from one warm
model. The execution contract:

* **Bucket ladder.** Every micro-batch is padded to a ``(batch_bucket,
  inner_bucket)`` cell from two small power-of-two ladders, so every
  request dispatches into an **already-jitted** program — the serving
  analog of the offline jit-chained ``cgStep``/``gatLayer`` paths: on a
  dispatch-dominated backend, a retrace on the hot path is the latency
  SLO's worst enemy. :meth:`warmup` compiles the whole ladder ahead of
  the first request (compile-ahead), and the program cache is keyed the
  way autotune fingerprints are (workload, bucket cell, R, backend,
  code hash) so a stale program can never serve a new code generation.
* **Determinism across batching.** A micro-batch is split into groups
  per inner bucket, each group padded with zero-masked rows; every
  program computes request rows independently. A request's reply is
  therefore a function of its payload alone — not of arrival order,
  micro-batch composition, or padding (pinned by ``tests/test_serve.py``).
* **Resilience ladder** (the same rungs ``parallel/base._resilient_call``
  gives offline dispatch): fault hooks fire at ``execute:serveBatch`` /
  ``output:serveBatch``, the call runs under a per-batch timeout with
  bounded retries, guarded outputs retry on NaN/Inf, and a persistently
  failing batch **degrades to the workload's host-serial fallback** per
  request — the engine sheds or degrades, it does not die.
* **Observability**: ``serve:batch`` spans + per-request reply events
  through ``obs.trace``, queue-depth/occupancy into the
  :class:`~distributed_sddmm_tpu.serve.slo.LatencyRecorder`, and the
  watchdog's ``queue_runaway`` hook on every admission.
"""

from __future__ import annotations

import os
import threading
from typing import Optional

from distributed_sddmm_tpu.obs import clock
from distributed_sddmm_tpu.obs import log as obs_log
from distributed_sddmm_tpu.obs import metrics as obs_metrics
from distributed_sddmm_tpu.obs import trace as obs_trace
from distributed_sddmm_tpu.obs import watchdog as obs_watchdog
from distributed_sddmm_tpu.resilience import faults
from distributed_sddmm_tpu.resilience.guards import NumericalFault
from distributed_sddmm_tpu.serve.queue import (
    DEFAULT_TENANT, Request, RequestError, RequestQueue,
)
from distributed_sddmm_tpu.serve.slo import LatencyRecorder
from distributed_sddmm_tpu.serve.workloads import ServingWorkload, bucket_for
from distributed_sddmm_tpu.utils.buckets import pow2_ladder


def _default_batch_buckets(max_batch: int) -> tuple[int, ...]:
    # The shared power-of-two ladder rule (utils/buckets.py) — the same
    # module the autotune fingerprint and codegen band thresholds use.
    return pow2_ladder(max_batch)


class ServingEngine:
    """Request/response execution over a warm model.

    ``exec_timeout_s``/``exec_retries`` bound one micro-batch dispatch
    (defaults from ``DSDDMM_SERVE_TIMEOUT`` / ``DSDDMM_SERVE_RETRIES``);
    after the retry budget the batch degrades to the workload's serial
    fallback instead of failing the requests.
    """

    #: Fault-injection site names (shared ``execute:*`` / ``output:*``
    #: namespaces with offline dispatch, so one fault spec covers both).
    OP = "serveBatch"

    def __init__(
        self,
        workload: ServingWorkload,
        max_batch: int = 8,
        max_depth: int = 64,
        max_wait_ms: float = 5.0,
        batch_buckets: Optional[tuple[int, ...]] = None,
        exec_timeout_s: Optional[float] = None,
        exec_retries: Optional[int] = None,
        recorder: Optional[LatencyRecorder] = None,
        program_store=None,
        tenants=None,
    ):
        self.workload = workload
        self.queue = RequestQueue(
            max_depth=max_depth, max_batch=max_batch, max_wait_ms=max_wait_ms,
            tenants=tenants,
        )
        self.batch_buckets = tuple(
            sorted(batch_buckets or _default_batch_buckets(max_batch))
        )
        self.exec_timeout_s = (
            float(os.environ.get("DSDDMM_SERVE_TIMEOUT", "0"))
            if exec_timeout_s is None else float(exec_timeout_s)
        )
        self.exec_retries = (
            int(os.environ.get("DSDDMM_SERVE_RETRIES", "1"))
            if exec_retries is None else int(exec_retries)
        )
        self.recorder = recorder if recorder is not None else LatencyRecorder()

        #: Persistent AOT program store (``programs/``): cold starts warm
        #: ladder cells from disk instead of compiling. ``program_store``
        #: overrides; the default follows ``programs.active()``
        #: (``DSDDMM_PROGRAMS`` env; None disables — in-process jit only).
        if program_store is None:
            from distributed_sddmm_tpu import programs

            program_store = programs.active()
        self.program_store = program_store

        self._programs: dict[str, object] = {}
        #: Fast path: (batch_bucket, inner_bucket) -> resolved program.
        #: The fingerprint-style key exists to pin the cache to a code
        #: generation at CONSTRUCTION; backend and serve_code_hash cannot
        #: change mid-process, so dispatch looks up by cell only.
        self._cell_programs: dict[tuple[int, int], object] = {}
        self._cache_lock = threading.Lock()
        self.cache_hits = 0
        self.cache_misses = 0
        #: Disk-vs-live compile attribution for this engine's ladder
        #: (``disk_hits`` counts programs deserialized from the store;
        #: ``live_compiles`` counts in-process compiles — the number a
        #: warmed cold start must hold at zero).
        self.disk_hits = 0
        self.live_compiles = 0
        self.served = 0
        self.degraded_batches = 0
        #: True once :meth:`warmup` has compiled the whole ladder — the
        #: admin server's ``/readyz`` warm check.
        self.warmed = False
        #: True once :meth:`start` has run: lets ``/healthz`` tell
        #: "not started yet" (alive, warming) from "runner died" (503).
        self.ever_started = False
        self._thread: Optional[threading.Thread] = None
        self._stop = threading.Event()
        #: Mirror hook (``tuner/shadow.py``): when attached, the runner
        #: hands every answered (non-degraded) group to it AFTER the
        #: replies are out the door — one bounded append on the request
        #: path, never a dispatch.
        self._mirror = None
        #: Challenger hot-swaps applied to this ladder (tuner
        #: promotions; ``stats()`` surfaces it).
        self.ladder_swaps = 0
        #: Structure changes bound into this engine (PR 20
        #: ``rebind_structure``; ``stats()`` surfaces it).
        self.structure_rebinds = 0
        #: Backref set by an attached ``BackgroundTuner`` (telemetry
        #: snapshots read tuner state through it; None = no tuner).
        self.tuner = None

    # ------------------------------------------------------------------ #
    # Warm program cache (autotune-fingerprint-style keys)
    # ------------------------------------------------------------------ #

    #: Sentinel: ``program_key``'s default is "the workload's current
    #: variant"; an explicit ``variant=None`` means the generic key.
    _WORKLOAD_VARIANT = object()

    def program_key(self, batch_bucket: int, inner_bucket: int,
                    sig: str | None = None,
                    variant=_WORKLOAD_VARIANT) -> str:
        """The ladder cell's program-store key. ``variant`` overrides
        the workload's realized kernel-variant segment — the tuner
        builds CHALLENGER keys this way, and the ``v<variant>`` segment
        (plus ``serve_code_hash``) is what guarantees a challenger
        entry can never alias the incumbent's, nor a stale generation's
        entry ever resolve (``programs/keys.py``)."""
        from distributed_sddmm_tpu.programs import keys as program_keys

        backend = "unknown"
        try:
            import jax

            backend = jax.default_backend()
        except Exception:  # noqa: BLE001 — key quality, not correctness
            pass
        if variant is ServingEngine._WORKLOAD_VARIANT:
            variant = getattr(self.workload, "kernel_variant", None)
        r = getattr(self.workload, "R", getattr(self.workload, "_F", 0))
        return program_keys.serve_program_key(
            self.workload.name, batch_bucket, inner_bucket, r, backend,
            params=self.workload.program_params(), sig=sig,
            variant=variant,
            # Realized wire policy of the warm model's strategy (PR
            # 15): bf16-wire ladder entries never alias f32's; None/
            # f32 appends nothing, keeping default keys byte-identical.
            wire=getattr(self.workload, "wire", None),
            # Capacity-bucket segment (PR 20): a dynamic-structure
            # workload's programs are sized to capacity rungs, not the
            # exact pattern — the rungs identify them. Static workloads
            # have no capacity_segment and append nothing (keys
            # byte-identical), so bucketed keys never alias exact ones.
            cap=getattr(self.workload, "capacity_segment", None),
            # Serving executables are per-process like plan programs:
            # on a pod each worker's ladder keys carry its dN.pK slot
            # (empty single-process — keys byte-identical to PR 5-13).
            dist=program_keys.dist_segment(),
        )

    def _note_resolve(self, source: str) -> None:
        with self._cache_lock:
            if source == "disk":
                self.disk_hits += 1
            else:
                self.live_compiles += 1

    def _program(self, batch_bucket: int, inner_bucket: int):
        cell = (batch_bucket, inner_bucket)
        with self._cache_lock:
            prog = self._cell_programs.get(cell)
            if prog is not None:
                self.cache_hits += 1
                return prog
            self.cache_misses += 1
        key = self.program_key(batch_bucket, inner_bucket)
        prog = self.workload.build_program(batch_bucket, inner_bucket)
        if self.program_store is not None:
            # Store-backed cell: the first call (warmup's, normally)
            # resolves against the persistent store — a cold start whose
            # keys a previous process warmed deserializes instead of
            # compiling (aval signature appended to the key so a program
            # compiled against another model's shapes can never answer).
            from distributed_sddmm_tpu.programs import StoredProgram

            prog = StoredProgram(
                prog,
                key_fn=lambda sig, bb=batch_bucket, ib=inner_bucket: (
                    self.program_key(bb, ib, sig=sig)
                ),
                store=self.program_store,
                meta={"workload": self.workload.name},
                on_resolve=self._note_resolve,
            )
        else:
            # No store: the cell build implies one in-process compile at
            # first dispatch; count it so cold-start cost stays visible.
            self._note_resolve("live")
        with self._cache_lock:
            prog = self._programs.setdefault(key, prog)
            self._cell_programs[cell] = prog
        return prog

    def warmup(self) -> int:
        """Compile-ahead: build and execute every ladder cell once (with
        an all-padding batch), so no live request ever pays a compile.
        Returns the number of programs warmed."""
        from distributed_sddmm_tpu.utils.platform import force_fetch

        n = 0
        with obs_trace.span(
            "serve:warmup", workload=self.workload.name,
            cells=len(self.batch_buckets) * len(self.workload.inner_buckets),
        ):
            for bb in self.batch_buckets:
                for ib in self.workload.inner_buckets:
                    prog = self._program(bb, ib)
                    args = self.workload.pad_batch([], bb, ib)
                    force_fetch(prog(*args))
                    n += 1
        self.warmed = True
        obs_log.info(
            "serve", "warmup complete", programs=n,
            batch_buckets=list(self.batch_buckets),
            inner_buckets=list(self.workload.inner_buckets),
        )
        return n

    # ------------------------------------------------------------------ #
    # Lifecycle
    # ------------------------------------------------------------------ #

    def start(self, warmup: bool = True) -> "ServingEngine":
        if self._thread is not None:
            raise RuntimeError("engine already started")
        if warmup:
            self.warmup()
        self._stop.clear()
        self._thread = threading.Thread(
            target=self._run, daemon=True, name=f"serve-{self.workload.name}"
        )
        # Flips only once the runner thread exists: the admin server
        # starts before warmup, and /healthz must read the whole warmup
        # window as "alive, not started yet" (200) — a liveness prober
        # seeing 503 there would kill the replica mid-compile.
        self.ever_started = True
        self._thread.start()
        return self

    def runner_alive(self) -> bool:
        """Liveness signal for ``/healthz``: the runner thread exists
        and is still draining (False before :meth:`start` and after
        :meth:`stop` or a runner death)."""
        t = self._thread
        return t is not None and t.is_alive()

    def stop(self, drain: bool = True, timeout_s: float = 30.0) -> None:
        """Close admission; optionally drain queued requests, then stop
        the runner."""
        self.queue.close()
        if not drain:
            self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout_s)
            self._thread = None

    def __enter__(self) -> "ServingEngine":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.stop()

    # ------------------------------------------------------------------ #
    # Client surface
    # ------------------------------------------------------------------ #

    def submit(self, payload: dict, tenant: str = DEFAULT_TENANT,
               trace_ctx: Optional[dict] = None) -> Request:
        """Admit one request (sheds with
        :class:`~distributed_sddmm_tpu.serve.queue.ShedError` when the
        queue is at depth). ``tenant`` must be a class declared at
        construction; the queue's weighted-fair scheduler isolates the
        classes from each other. ``trace_ctx`` is the decoded fleet
        trace context (``X-DSDDMM-Trace``) forwarded into the queue so
        the request's trace chain records its fleet parent."""
        from distributed_sddmm_tpu.serve.queue import ShedError

        wd = obs_watchdog.active()
        if wd is not None:
            # BEFORE admission: a strict-mode runaway alarm must shed
            # this request while it is still reject-able — admitting
            # first would execute (and ingest) a request whose client
            # was told it never got in.
            try:
                wd.observe_queue(self.queue.depth(), self.queue.max_depth)
            except NumericalFault:
                self.recorder.record_shed(tenant)
                obs_metrics.GLOBAL.add("serve_shed")
                raise ShedError(
                    "queue runaway (watchdog strict)",
                    retry_after_s=self.queue.max_wait_ms / 1e3,
                ) from None
        try:
            return self.queue.submit(self.workload.clamp(payload),
                                     tenant=tenant, trace_ctx=trace_ctx)
        except ShedError:
            self.recorder.record_shed(tenant)
            obs_metrics.GLOBAL.add("serve_shed")
            raise

    def serve_one(self, payload: dict, timeout_s: float = 30.0) -> dict:
        """Submit + wait (the synchronous convenience path)."""
        return self.submit(payload).result(timeout_s=timeout_s)

    def execute_now(self, payloads: list[dict]) -> list[dict]:
        """Synchronously execute payloads through the SAME pad/program
        path as the runner (no queue, no recorder) — the reference the
        batching-determinism tests compare batched replies against."""
        payloads = [self.workload.clamp(p) for p in payloads]
        replies: dict[int, dict] = {}
        for ib, idxs in self._group_by_inner(payloads).items():
            group = [payloads[i] for i in idxs]
            bb = bucket_for(len(group), self.batch_buckets)
            out = self._dispatch(group, bb, ib)
            for i, reply in zip(idxs, out):
                replies[i] = reply
        return [replies[i] for i in range(len(payloads))]

    # ------------------------------------------------------------------ #
    # Runner
    # ------------------------------------------------------------------ #

    def _run(self) -> None:
        while not self._stop.is_set():
            batch = self.queue.next_batch(timeout_s=0.25)
            if not batch:
                if self.queue.closed and self.queue.depth() == 0:
                    return
                continue
            try:
                self._serve_batch(batch)
            except Exception as e:  # noqa: BLE001 — the loop must survive
                obs_log.error(
                    "serve", "batch failed past every rung",
                    error=f"{type(e).__name__}: {e}",
                )
                for req in batch:
                    if not req.done():
                        req.set_error(RequestError(str(e)))

    def _group_by_inner(self, payloads: list[dict]) -> dict[int, list[int]]:
        """Indices grouped by inner bucket. Grouping (rather than padding
        the whole micro-batch to the largest member's bucket) is what
        makes a request's inner shape a function of its own payload —
        the determinism contract."""
        groups: dict[int, list[int]] = {}
        for i, p in enumerate(payloads):
            ib = bucket_for(
                self.workload.inner_size(p), self.workload.inner_buckets
            )
            groups.setdefault(ib, []).append(i)
        return groups

    def _serve_batch(self, batch: list[Request]) -> None:
        t_batch = clock.now()
        depth_now = self.queue.depth()
        payloads = [req.payload for req in batch]
        answered_idx: list[int] = []
        wd = obs_watchdog.active()

        for ib, idxs in self._group_by_inner(payloads).items():
            group = [payloads[i] for i in idxs]
            reqs = [batch[i] for i in idxs]
            bb = bucket_for(len(group), self.batch_buckets)
            self.recorder.record_batch(len(group), bb, depth_now)
            t0 = clock.now()
            for req in reqs:
                # Per GROUP, not per batch: groups dispatch sequentially,
                # and a later group's execute_s must not absorb an
                # earlier group's (possibly retried/degraded) dispatch.
                req.t_execute = t0
            with obs_trace.span(
                "serve:batch", workload=self.workload.name,
                batch=len(group), batch_bucket=bb, inner_bucket=ib,
                depth=depth_now,
                # The trace-context link: which requests this dispatch
                # carried — request_chains joins enqueue events, this
                # span and the reply events on these ids.
                req_ids=[r.req_id for r in reqs],
            ) as sp:
                try:
                    replies = self._dispatch(group, bb, ib, span=sp)
                    degraded = False
                except Exception as e:  # noqa: BLE001 — degrade rung
                    replies = self._degrade(group, e)
                    degraded = True
                    sp.set(degraded=True)
            for i, req, reply in zip(idxs, reqs, replies):
                if reply is None:  # serial fallback failed too
                    req.set_error(RequestError(
                        "no reply: compiled dispatch and serial fallback "
                        "both failed"
                    ))
                    continue
                req.degraded = degraded
                req.set_result(reply)
                answered_idx.append(i)
                if obs_trace.enabled():
                    # t_enqueue/t_reply are the request's own precise
                    # stamps in trace-relative time: the event's `t` is
                    # its emission instant, which can lag set_result by
                    # a scheduling delay once the client thread wakes.
                    fleet_attrs = {}
                    if req.fleet:
                        fleet_attrs = {
                            "fleet_req": req.fleet.get("req"),
                            "fleet_shard": req.fleet.get("shard"),
                            "fleet_span": req.fleet.get("span"),
                        }
                    obs_trace.event(
                        "serve:reply", req=req.req_id, degraded=degraded,
                        t_enqueue=obs_trace.rel_time(req.t_enqueue),
                        t_reply=obs_trace.rel_time(req.t_reply),
                        **{k: round(v, 6)
                           for k, v in req.stage_latencies_s().items()},
                        **fleet_attrs,
                    )
            self.served += len(group)
            mirror = self._mirror
            if (
                mirror is not None and not degraded
                and all(r is not None for r in replies)
            ):
                # AFTER the replies are out: mirroring must never delay
                # a reply, and a degraded group's serial-rung replies
                # are not the compiled programs' bits — shadow-compare
                # would flag the degrade, not the challenger.
                try:
                    mirror(group, replies, bb, ib)
                except Exception as e:  # noqa: BLE001 — best-effort tap
                    obs_log.warn("serve", "mirror hook failed",
                                 error=f"{type(e).__name__}: {e}")
            if wd is not None:
                try:
                    wd.observe(
                        f"serve:{self.workload.name}",
                        clock.now() - t0,
                    )
                except NumericalFault as alarm:
                    # Strict-mode spike/drift: the anomaly is recorded;
                    # serving's ladder response is shed/degrade upstream,
                    # not runner death.
                    obs_log.warn("serve", "watchdog alarm in runner",
                                 error=str(alarm))

        # Ingest + drain-rate hint, after replies are out the door.
        # ANSWERED payloads only, in admission (FIFO) order regardless of
        # group dispatch order: a request whose every rung failed got a
        # RequestError — training on traffic the client never received
        # an answer for would break the "served users appended" contract.
        if answered_idx:
            try:
                self.workload.ingest(
                    [payloads[i] for i in sorted(answered_idx)]
                )
            except Exception as e:  # noqa: BLE001 — ingest is best-effort
                obs_log.warn("serve", "online ingest failed",
                             error=f"{type(e).__name__}: {e}")
        dt = clock.now() - t_batch
        if dt > 0:
            inst = len(batch) / dt
            self.queue.drain_rate_hint = (
                0.8 * self.queue.drain_rate_hint + 0.2 * inst
                if self.queue.drain_rate_hint else inst
            )

    # ------------------------------------------------------------------ #
    # The resilience ladder around one compiled dispatch
    # ------------------------------------------------------------------ #

    def _dispatch(
        self, group: list[dict], batch_bucket: int, inner_bucket: int,
        span=None,
    ) -> list[dict]:
        from distributed_sddmm_tpu.resilience import guards
        from distributed_sddmm_tpu.resilience.retry import Backoff, retry_call
        from distributed_sddmm_tpu.utils.platform import force_fetch

        prog = self._program(batch_bucket, inner_bucket)
        t_pad0 = clock.now()
        args = self.workload.pad_batch(group, batch_bucket, inner_bucket)
        pad_s = clock.now() - t_pad0
        if span is not None:
            # The pad sub-segment of execute_s: how much of the dispatch
            # window went to bucket padding rather than the program.
            span.set(pad_s=round(pad_s, 9))

        def attempt():
            faults.maybe_raise(f"execute:{self.OP}")
            out = prog(*args)
            out = faults.corrupt_outputs(f"output:{self.OP}", out)
            force_fetch(out)
            if guards.enabled():
                # raise-mode trips the retry; repair-mode nan_to_nums.
                out = guards.guard_output(self.OP, out)
            return out

        def on_retry(i: int, err: BaseException) -> None:
            obs_metrics.GLOBAL.add("exec_retries")
            obs_trace.event("retry", op=self.OP, attempt=i,
                            error=type(err).__name__)

        out = retry_call(
            attempt,
            retries=self.exec_retries,
            timeout_s=self.exec_timeout_s,
            backoff=Backoff(base_s=0.02, max_delay_s=0.5),
            retry_on=(TimeoutError, MemoryError, NumericalFault,
                      faults.FaultError),
            label=f"execute:{self.OP}",
            on_retry=on_retry,
        )
        return self.workload.unpad(out, group)

    def _degrade(self, group: list[dict], cause: BaseException) -> list:
        """Final rung: per-request host-serial fallback. Requests whose
        fallback ALSO fails get a typed error (reply slot None here)."""
        self.degraded_batches += 1
        obs_metrics.GLOBAL.add("serve_degraded_batches")
        obs_trace.event(
            "serve_degraded", workload=self.workload.name,
            cause=type(cause).__name__, batch=len(group),
        )
        obs_log.warn(
            "serve", "batch degraded to serial fallback",
            cause=f"{type(cause).__name__}: {cause}", batch=len(group),
        )
        replies = []
        for payload in group:
            try:
                replies.append(self.workload.serial(payload))
            except Exception as e:  # noqa: BLE001 — per-request error
                replies.append(None)
                obs_log.error("serve", "serial fallback failed",
                              error=f"{type(e).__name__}: {e}")
        return replies

    # ------------------------------------------------------------------ #
    # Closed-loop tuning hooks (tuner/)
    # ------------------------------------------------------------------ #

    def attach_mirror(self, mirror) -> None:
        """Arm the request mirror: ``mirror(payloads, replies,
        batch_bucket, inner_bucket)`` is called by the runner for every
        answered, non-degraded group (the shadow session's ``offer``).
        One hook at a time — attaching over a live one replaces it."""
        self._mirror = mirror

    def detach_mirror(self) -> None:
        self._mirror = None

    def swap_ladder(self, cell_programs: dict, variant, key_fn=None) -> None:
        """Hot-swap the warm bucket ladder onto pre-warmed challenger
        programs — the tuner's promotion move.

        Atomic under the cache lock: an in-flight dispatch finishes on
        the incumbent program it already resolved; the next ``_program``
        lookup serves the challenger. No request is dropped and no
        request-path compile happens — ``cell_programs`` MUST cover
        every ladder cell and already be warmed (the shadow session
        compiles and executes each cell off-path before promotion; a
        partial ladder is refused here for exactly that reason). The
        workload's ``kernel_variant`` is restamped so later cache
        misses (there should be none) and the serve record key on the
        challenger's variant.
        """
        cells = {
            (bb, ib)
            for bb in self.batch_buckets
            for ib in self.workload.inner_buckets
        }
        missing = cells - set(cell_programs)
        if missing:
            raise ValueError(
                f"challenger ladder is missing cells {sorted(missing)}; "
                "promoting it would compile on the request path"
            )
        if variant is not None:
            # A variant id this code generation cannot reconstruct is
            # stale — it must be unpromotable no matter how it got here
            # (the shadow session already refuses it at construction).
            from distributed_sddmm_tpu import codegen

            codegen.variant_from_id(variant)
        if key_fn is None:
            key_fn = lambda bb, ib: self.program_key(  # noqa: E731
                bb, ib, variant=variant
            )
        keyed = {key_fn(bb, ib): prog
                 for (bb, ib), prog in cell_programs.items()}
        with self._cache_lock:
            self._cell_programs = {
                cell: cell_programs[cell] for cell in cells
            }
            self._programs = keyed
            self.workload.kernel_variant = variant
            self.ladder_swaps += 1
        obs_trace.event(
            "serve_ladder_swap", workload=self.workload.name,
            variant=variant, cells=len(cells),
        )
        obs_log.info(
            "serve", "bucket ladder hot-swapped",
            variant=variant, cells=len(cells), swaps=self.ladder_swaps,
        )

    def rebind_structure(self, *args, **kw) -> dict:
        """Bind a mutated structure into the live ladder (PR 20).

        Delegates to the workload's ``rebind_structure`` hook — the
        workload owns what "structure" means (the attention workload's
        context matrix, the fold-in workload's ratings matrix + model
        strategy) and performs the host-side rebind. On a fit (the new
        structure lands in the compiled capacity rungs) the existing
        ladder keeps serving untouched: structure rides in as program
        arguments with unchanged avals, so the change costs zero
        compiles and zero dropped requests. On a bucket spill the
        ladder's avals changed — the stale cells are dropped atomically
        and the ladder re-warms at the new capacity (store-warmed when
        a program store is bound), OFF the request path like any
        warmup. Returns the hook's report (``{"fit": bool, ...}``).
        """
        hook = getattr(self.workload, "rebind_structure", None)
        if hook is None:
            raise ValueError(
                f"workload {self.workload.name!r} has no structure "
                "rebind hook"
            )
        report = hook(*args, **kw)
        with self._cache_lock:
            self.structure_rebinds += 1
        if not report.get("fit", True):
            with self._cache_lock:
                self._cell_programs.clear()
                self._programs.clear()
                self.warmed = False
            self.warmup()
        obs_trace.event(
            "serve_structure_rebind", workload=self.workload.name,
            fit=bool(report.get("fit", True)),
        )
        return report

    # ------------------------------------------------------------------ #

    def stats(self) -> dict:
        with self._cache_lock:
            return {
                "programs": len(self._programs),
                "cache_hits": self.cache_hits,
                "cache_misses": self.cache_misses,
                "disk_hits": self.disk_hits,
                "live_compiles": self.live_compiles,
                "served": self.served,
                "degraded_batches": self.degraded_batches,
                "ladder_swaps": self.ladder_swaps,
                "structure_rebinds": self.structure_rebinds,
                "queue_shed": self.queue.shed_count,
            }
