"""Concrete serving endpoints: ALS fold-in top-k and GAT node scoring.

Both follow the same contract the engine batches against
(:class:`ServingWorkload`): a request **payload** is a small dict, the
per-request *inner size* (rated-item count, node count) is bucketed
independently of the batch dimension, and the compiled program for a
``(batch_bucket, inner_bucket)`` cell computes every request row
independently — the property the batching-determinism tests pin:
a request's reply must not depend on which other requests shared its
micro-batch, only on its own payload.

**ALS fold-in + top-k** (the paper's collaborative-filtering app, served):
a new user arrives with a handful of (item, rating) observations. Rather
than re-running distributed ALS, the user's factor vector is *folded in*
against the warm item factors B — solve the one-user ridge normal
equation ``(Bᵀ_obs B_obs + λI) x = Bᵀ_obs r`` (an R×R solve, the same
normal-equation structure the offline half-steps solve for all rows at
once) — then scored against every item and the top-k unseen items
returned. The served user's ratings row is appended to the live host
matrix via :meth:`HostCOO.append_rows` so the next offline retrain sees
the online traffic.

**GAT node scoring** (the paper's GNN app, served): the warm model's
forward pass is the expensive, whole-graph part; it runs once at engine
warmup and is refreshed out-of-band. A request asks for scores of a
node batch: gather the requested rows of the cached embeddings and
project them through a fixed scoring head — the gather/project half is
what latency-sensitive serving actually dispatches per request.
"""

from __future__ import annotations

import abc
import threading
from typing import Optional

import numpy as np

from distributed_sddmm_tpu.utils.coo import HostCOO

#: Default inner-size bucket ladders (powers of two keep the compiled
#: program count logarithmic in the supported range).
ALS_ITEM_BUCKETS = (8, 16, 32, 64)
GAT_NODE_BUCKETS = (1, 4, 16, 64)
ATTN_TOKEN_BUCKETS = (1, 4, 16, 64)

# Rung selection is the SHARED power-of-two bucketing rule
# (``utils/buckets.py``) — the same module the autotune fingerprint's
# npr_bucket and the codegen band selector use, so serving, plans and
# kernel banding bucket identically. Re-exported under the historical
# name (engine.py and tests import it from here).
from distributed_sddmm_tpu.utils.buckets import bucket_for  # noqa: E402,F401


def _model_kernel_variant(model) -> Optional[str]:
    """The warm model's codegen kernel-variant id (None = generic).

    Workload constructors default ``kernel_variant`` from here so a
    model built from a variant plan (``from_plan`` on skewed data)
    stamps its specialization into the warm ladder's program keys
    WITHOUT every caller having to thread it — the key-isolation
    invariant (a cache warmed under one specialization never answers
    for another) must hold by construction, not by caller diligence.
    Resolution is the SHARED rule bench records use
    (``parallel.base.realized_kernel_variant``) so records and serve
    keys always agree on a run's variant.
    """
    from distributed_sddmm_tpu.parallel.base import realized_kernel_variant

    return realized_kernel_variant(getattr(model, "d_ops", None))


def _model_wire(model_or_ops) -> Optional[str]:
    """The warm model's realized wire-precision policy LABEL (``bf16``,
    ``bf16.reduce=bf16``, ...), or None for the f32 identity wire —
    same by-construction key-isolation role as
    :func:`_model_kernel_variant`: a ladder warmed over bf16-wire
    strategy programs stamps ``w<label>`` into its keys
    (``programs/keys.serve_program_key``) so it can never answer for an
    f32-wire engine — and the label carries role overrides, so two
    numerically different bf16 policies never alias either. Accepts
    the model or the strategy itself (the attention workload holds
    ``d_ops`` directly)."""
    ops = getattr(model_or_ops, "d_ops", model_or_ops)
    policy = getattr(ops, "wire", None)
    if policy is None:
        return None
    label = policy.label
    return None if label == "f32" else label


def _chol_solve(gram, rhs):
    """Batched SPD solve via a hand-unrolled Cholesky (``gram`` is
    ``(b, R, R)``, ``rhs`` ``(b, R)``).

    Exists for bitwise batch-invariance, not speed: XLA:CPU lowers
    ``jnp.linalg.solve`` (and plain ``x @ B.T``) to LAPACK/Eigen calls
    whose accumulation order DEPENDS ON THE BATCH DIMENSION, so the same
    request solved in a batch of 1 vs 4 returns different last bits —
    exactly what the serving determinism contract forbids. This
    formulation uses only elementwise/broadcast ops and fixed-size
    last-axis reductions, which are batch-invariant (pinned by
    ``tests/test_serve.py``). Unrolls O(R) ops at trace time — fine for
    serving-scale R (tens), not for R in the thousands."""
    import jax.numpy as jnp

    R = gram.shape[-1]
    L = jnp.zeros_like(gram)
    for j in range(R):
        d = jnp.sqrt(
            gram[:, j, j] - jnp.sum(L[:, j, :j] * L[:, j, :j], axis=-1)
        )
        L = L.at[:, j, j].set(d)
        if j + 1 < R:
            off = (
                gram[:, j + 1:, j]
                - jnp.sum(
                    L[:, j + 1:, :j] * L[:, j, :j][:, None, :], axis=-1
                )
            ) / d[:, None]
            L = L.at[:, j + 1:, j].set(off)
    y = jnp.zeros_like(rhs)
    for j in range(R):
        y = y.at[:, j].set(
            (rhs[:, j] - jnp.sum(L[:, j, :j] * y[:, :j], axis=-1))
            / L[:, j, j]
        )
    x = jnp.zeros_like(rhs)
    for j in reversed(range(R)):
        x = x.at[:, j].set(
            (y[:, j] - jnp.sum(L[:, j + 1:, j] * x[:, j + 1:], axis=-1))
            / L[:, j, j]
        )
    return x


class ServingWorkload(abc.ABC):
    """What the engine needs from an endpoint. All array math that runs
    per-dispatch lives in :meth:`build_program`'s jitted closure; payload
    padding and reply slicing are host-side numpy."""

    #: Endpoint name (bench record ``app`` = ``serve-<name>``).
    name: str = "?"
    #: Inner-size ladder (rated items / requested nodes).
    inner_buckets: tuple[int, ...] = (1,)
    #: Codegen kernel-variant id of the warm model's plan (None = the
    #: generic kernel). Baked into the warm ladder's program keys
    #: (``programs/keys.serve_program_key``) so a cache warmed under one
    #: specialization can never answer for another.
    kernel_variant: Optional[str] = None
    #: Realized wire-precision policy name of the warm model's strategy
    #: (None = the f32 identity wire). Baked into the ladder's program
    #: keys as ``w<dtype>`` for the same isolation reason — and None
    #: appends nothing, so f32 keys stay byte-identical to PR 5-14.
    wire: Optional[str] = None

    @abc.abstractmethod
    def inner_size(self, payload: dict) -> int:
        """The payload's inner dimension, pre-bucketing."""

    @abc.abstractmethod
    def clamp(self, payload: dict) -> dict:
        """Payload admitted for execution (oversize payloads truncated to
        the largest inner bucket — admission must never grow the ladder)."""

    @abc.abstractmethod
    def build_program(self, batch_bucket: int, inner_bucket: int):
        """A jitted callable ``prog(*padded_args) -> outputs`` for one
        bucket cell. Called once per cell (the engine caches)."""

    @abc.abstractmethod
    def pad_batch(
        self, payloads: list[dict], batch_bucket: int, inner_bucket: int
    ) -> tuple:
        """Padded device-ready args for ``prog``; rows past
        ``len(payloads)`` are zero-masked."""

    @abc.abstractmethod
    def unpad(self, outputs, payloads: list[dict]) -> list[dict]:
        """Slice program outputs back into one reply per payload
        (host numpy)."""

    @abc.abstractmethod
    def serial(self, payload: dict) -> dict:
        """Single-request host-numpy fallback (the degrade rung: must
        not touch the accelerator)."""

    @abc.abstractmethod
    def oracle(self, payload: dict) -> dict:
        """Float64 reference reply for correctness checking."""

    @abc.abstractmethod
    def check_reply(self, payload: dict, reply: dict) -> bool:
        """True when ``reply`` is consistent with :meth:`oracle`."""

    @abc.abstractmethod
    def sample_payload(self, rng: np.random.Generator) -> dict:
        """A synthetic request (load generator + compile-ahead warmup)."""

    def ingest(self, payloads: list[dict]) -> None:
        """Optional online-ingest hook, called after a batch is served."""

    def program_params(self) -> str:
        """Workload constants BAKED INTO the traced program (beyond what
        the argument avals capture) — part of the persistent program-
        store key, or two configurations would alias one executable.
        Empty when every knob rides in as an argument."""
        return ""


# --------------------------------------------------------------------- #
# ALS: user fold-in + top-k recommendation
# --------------------------------------------------------------------- #


class ALSFoldInTopK(ServingWorkload):
    """Serve top-k recommendations for unseen users against warm item
    factors.

    ``model`` is a trained/warm
    :class:`~distributed_sddmm_tpu.models.als.DistributedALS`; its item
    factors are fetched once (global row order) and kept as the scoring
    matrix. ``S_live`` (defaults to the model's ``S_host``) receives
    each served user's ratings row via ``append_rows`` — the online
    half of the ingest story.

    Payload: ``{"items": int array, "ratings": float array}``.
    Reply:   ``{"items": int[k] (top-k unseen item ids, best first),
    "scores": float[k]}``.
    """

    name = "als"

    def __init__(
        self,
        model,
        k: int = 10,
        item_buckets: tuple[int, ...] = ALS_ITEM_BUCKETS,
        S_live: Optional[HostCOO] = None,
        ingest_rows: bool = True,
        ridge: float = 0.1,
        kernel_variant: Optional[str] = None,
    ):
        import jax.numpy as jnp

        self.kernel_variant = (
            kernel_variant if kernel_variant is not None
            else _model_kernel_variant(model)
        )
        self.wire = _model_wire(model)

        if model.B is None:
            raise ValueError(
                "ALSFoldInTopK needs a warm model (run initialize_embeddings"
                "/run_cg first, or use ServingEngine warmup)"
            )
        self.model = model
        self.k = int(k)
        self.inner_buckets = tuple(sorted(int(b) for b in item_buckets))
        d = model.d_ops
        self.N = d.N
        self.R = d.R
        # Deliberately STIFFER than the training ridge: a fold-in user
        # has fewer observations than factors (rank-deficient Gram), and
        # the training-scale 1e-6 leaves the f32 solve meaningless. The
        # floor keeps the one-user system conditioned; the training
        # ridge wins only if someone configured it even stiffer.
        self.ridge_lambda = max(float(model.ridge_lambda), float(ridge))
        # One host fetch; the serving programs take the factor matrix as
        # a plain argument so a refreshed B never invalidates the cache.
        self._B_host = np.ascontiguousarray(
            model.item_factors(), dtype=np.float32
        )
        self._B_dev = jnp.asarray(self._B_host)
        self.S_live = S_live if S_live is not None else model.S_host
        self.ingest_rows = bool(ingest_rows and self.S_live is not None)
        self._ingest_lock = threading.Lock()
        if self.k > self.N:
            raise ValueError(f"k={k} exceeds item count N={self.N}")

    # -- payload shaping ----------------------------------------------- #

    def inner_size(self, payload: dict) -> int:
        return int(len(payload["items"]))

    def clamp(self, payload: dict) -> dict:
        cap = self.inner_buckets[-1]
        if len(payload["items"]) <= cap:
            return payload
        return {
            "items": np.asarray(payload["items"])[:cap],
            "ratings": np.asarray(payload["ratings"])[:cap],
        }

    def sample_payload(self, rng: np.random.Generator) -> dict:
        n = int(min(1 + rng.poisson(4), self.inner_buckets[-1]))
        items = rng.choice(self.N, size=n, replace=False).astype(np.int64)
        return {
            "items": items,
            "ratings": rng.standard_normal(n).astype(np.float64),
        }

    def program_params(self) -> str:
        # k and the ridge are trace-time constants of fold_in_topk; the
        # factor matrix itself is an argument (shape covered by avals).
        return f"k{self.k}-l{self.ridge_lambda:g}"

    # -- device program ------------------------------------------------ #

    def build_program(self, batch_bucket: int, inner_bucket: int):
        import jax
        import jax.numpy as jnp

        lam = self.ridge_lambda
        k = self.k

        def fold_in_topk(B, idx, ratings, mask):
            # Per-row ridge normal equations against the observed item
            # factors (masked gather keeps padded slots inert). Every op
            # here is batch-dim-invariant by construction — see
            # _chol_solve for why lapack solve / plain gemm are not.
            rows = B[idx] * mask[..., None]                  # (b, L, R)
            gram = jnp.einsum("blr,bls->brs", rows, rows)
            gram = gram + lam * jnp.eye(B.shape[1], dtype=B.dtype)
            rhs = jnp.einsum("blr,bl->br", rows, ratings * mask)
            x = _chol_solve(gram, rhs)                       # (b, R)
            # Broadcast-sum, not x @ B.T: gemm accumulation order varies
            # with the batch dimension on XLA:CPU.
            scores = jnp.sum(x[:, None, :] * B[None, :, :], axis=-1)
            # Mask already-rated items out of the recommendation set.
            b = idx.shape[0]
            rated = jnp.zeros(scores.shape, dtype=mask.dtype)
            rated = rated.at[jnp.arange(b)[:, None], idx].max(mask)
            scores = jnp.where(rated > 0, -jnp.inf, scores)
            vals, ids = jax.lax.top_k(scores, k)
            return vals, ids

        return jax.jit(fold_in_topk)

    def pad_batch(
        self, payloads: list[dict], batch_bucket: int, inner_bucket: int
    ) -> tuple:
        b, L = batch_bucket, inner_bucket
        idx = np.zeros((b, L), dtype=np.int32)
        ratings = np.zeros((b, L), dtype=np.float32)
        mask = np.zeros((b, L), dtype=np.float32)
        for i, p in enumerate(payloads):
            n = len(p["items"])
            idx[i, :n] = p["items"]
            ratings[i, :n] = p["ratings"]
            mask[i, :n] = 1.0
        return (self._B_dev, idx, ratings, mask)

    def unpad(self, outputs, payloads: list[dict]) -> list[dict]:
        n = len(payloads)
        vals, ids = outputs
        vals = np.asarray(vals)[:n]
        ids = np.asarray(ids)[:n]
        return [
            {"items": ids[i].astype(np.int64), "scores": vals[i]}
            for i in range(n)
        ]

    # -- host paths ---------------------------------------------------- #

    def _scores_host(self, payload: dict, B: np.ndarray) -> np.ndarray:
        items = np.asarray(payload["items"], dtype=np.int64)
        ratings = np.asarray(payload["ratings"], dtype=B.dtype)
        rows = B[items]
        gram = rows.T @ rows + self.ridge_lambda * np.eye(
            B.shape[1], dtype=B.dtype
        )
        rhs = rows.T @ ratings
        x = np.linalg.solve(gram, rhs)
        scores = B @ x
        scores[items] = -np.inf
        return scores

    def serial(self, payload: dict) -> dict:
        """Degrade rung: same math, numpy float32, no accelerator."""
        scores = self._scores_host(payload, self._B_host)
        order = np.argsort(-scores, kind="stable")[: self.k]
        return {"items": order.astype(np.int64),
                "scores": scores[order].astype(np.float32)}

    def oracle(self, payload: dict) -> dict:
        scores = self._scores_host(
            payload, self._B_host.astype(np.float64)
        )
        order = np.argsort(-scores, kind="stable")[: self.k]
        return {"items": order.astype(np.int64), "scores": scores[order]}

    def check_reply(self, payload: dict, reply: dict) -> bool:
        """Reply is correct when every returned item scores (per the
        float64 oracle) at least as high as the oracle's k-th best minus
        float32 slack, and the returned scores agree with the oracle's
        scores for those same items. Rank-order between near-ties is NOT
        pinned — f32 vs f64 legitimately swaps ties."""
        oracle_scores = self._scores_host(
            payload, self._B_host.astype(np.float64)
        )
        scale = float(np.max(np.abs(oracle_scores[np.isfinite(oracle_scores)])))
        tol = 1e-3 * max(scale, 1.0)
        ids = np.asarray(reply["items"])
        got = np.asarray(reply["scores"], dtype=np.float64)
        kth = np.partition(oracle_scores, -self.k)[-self.k]
        if np.any(oracle_scores[ids] < kth - tol):
            return False
        return bool(np.all(np.abs(got - oracle_scores[ids]) <= tol))

    def ingest(self, payloads: list[dict]) -> None:
        """Fold the served users into the live ratings matrix: one new
        row per request (repair-mode sanitize — online traffic is
        untrusted by definition)."""
        if not self.ingest_rows:
            return
        with self._ingest_lock:
            self.S_live.append_rows(
                [np.asarray(p["items"], dtype=np.int64) for p in payloads],
                [np.asarray(p["ratings"], dtype=np.float64) for p in payloads],
                mode="repair",
            )

    # -- structure rebind ----------------------------------------------- #

    def rebind_structure(self, S: Optional[HostCOO] = None) -> dict:
        """Bind the ingest-grown ratings pattern into the model's
        distributed strategy (which must be a ``dynstruct.build``
        product — a plain strategy has no capacity rungs to rebind
        into). Defaults to ``S_live``, the matrix :meth:`ingest` grows.
        On a bucket spill the replacement strategy is re-pointed into
        the model, so training/serving handles stay valid either way.
        """
        from distributed_sddmm_tpu import dynstruct

        if S is None:
            S = self.S_live
        if S is None:
            raise ValueError("no live ratings matrix to rebind")
        with self._ingest_lock:
            update = dynstruct.rebind(self.model.d_ops, S)
            if update.spilled:
                self.model.d_ops = update.alg
        return {
            "fit": update.fit,
            "nnz": update.nnz_after,
            "row_cap": update.row_cap,
            "reason": update.reason,
        }


# --------------------------------------------------------------------- #
# Attention: token scoring over cached context embeddings
# --------------------------------------------------------------------- #


class AttentionTokenScore(ServingWorkload):
    """Score requested tokens by local attention over cached context.

    The expensive whole-sequence half — the fused block-sparse
    SDDMM → masked-softmax → SpMM pair — runs once at engine warmup
    (``build_attention_engine``) and its output rows are the cached
    context matrix ``K``. A request asks for scores of a token batch:
    per token ``i``, attend over its ±w sliding-window neighborhood of
    ``K`` with a numerically stable masked softmax and emit the
    attention-weighted value score through a fixed head (seeded, so
    replies are reproducible across processes).

    Every per-dispatch op is batch-dim-invariant BY CONSTRUCTION:
    gathers, elementwise math, and fixed-size LAST-AXIS max/sum
    reductions only — no gemm whose accumulation order depends on the
    batch dimension (the ``_chol_solve`` lesson) — so a reply is
    bit-identical across arrival order, micro-batch composition, batch
    bucket, and padding.

    Payload: ``{"tokens": int array}``.
    Reply:   ``{"tokens": int array, "scores": float array}``.
    """

    name = "attention"

    def __init__(
        self,
        context: np.ndarray,
        d_ops=None,
        window: Optional[int] = None,
        token_buckets: tuple[int, ...] = ATTN_TOKEN_BUCKETS,
        head_seed: int = 0,
        kernel_variant: Optional[str] = None,
        dynamic: bool = False,
    ):
        import os

        if kernel_variant is None and d_ops is not None:
            from distributed_sddmm_tpu.parallel.base import (
                realized_kernel_variant,
            )

            kernel_variant = realized_kernel_variant(d_ops)
        self.kernel_variant = kernel_variant
        self.wire = _model_wire(d_ops) if d_ops is not None else None
        self.d_ops = d_ops
        if window is None:
            window = int(os.environ.get("DSDDMM_ATTN_SERVE_WINDOW", "16"))
        if window < 0:
            raise ValueError(f"window must be >= 0, got {window}")
        self.window = int(window)
        self.inner_buckets = tuple(sorted(int(b) for b in token_buckets))
        self.dynamic = bool(dynamic)
        rng = np.random.default_rng(head_seed)
        self._w_host = (
            rng.standard_normal(context.shape[1]) / np.sqrt(context.shape[1])
        ).astype(np.float32)
        self._bind_context(np.ascontiguousarray(context, dtype=np.float32))

    def _bind_context(self, K: np.ndarray) -> None:
        """(Re)bind the cached context matrix. In dynamic mode ``K`` is
        padded up to the capacity rung ``ctx_cap`` (extra rows zero) and
        the real row count rides in as the runtime scalar ``n_valid`` —
        context growth within the rung rebinds without a retrace."""
        import jax.numpy as jnp

        self.n_ctx, self.R = K.shape
        if self.dynamic:
            from distributed_sddmm_tpu.utils.buckets import pow2_at_least

            self.ctx_cap = pow2_at_least(self.n_ctx + 1)
            pad = np.zeros((self.ctx_cap, self.R), dtype=np.float32)
            pad[: self.n_ctx] = K
            self._K_host = K
            self._K_pad = pad
            self._K_dev = jnp.asarray(pad)
            self._n_valid_dev = jnp.asarray(
                np.int32(self.n_ctx)
            )
        else:
            self.ctx_cap = self.n_ctx
            self._K_host = K
            self._K_dev = jnp.asarray(K)
        self._w_dev = jnp.asarray(self._w_host)

    # -- payload shaping ----------------------------------------------- #

    def inner_size(self, payload: dict) -> int:
        return int(len(payload["tokens"]))

    def clamp(self, payload: dict) -> dict:
        if self.dynamic and "mask" in payload:
            from distributed_sddmm_tpu import masks

            # Admission-time validation: a malformed or capacity-
            # exceeding spec is rejected here, before it can reach a
            # padded batch (the SLOSpec discipline — strict keys, loud
            # errors).
            masks.parse_dynamic_spec(
                payload["mask"],
                w_max=self.window,
                k_max=2 * self.window + 1,
            )
        cap = self.inner_buckets[-1]
        if len(payload["tokens"]) <= cap:
            return payload
        out = dict(payload)
        out["tokens"] = np.asarray(payload["tokens"])[:cap]
        return out

    def sample_payload(self, rng: np.random.Generator) -> dict:
        n = int(min(1 + rng.poisson(2), self.inner_buckets[-1]))
        out = {
            "tokens": rng.choice(
                self.n_ctx, size=n, replace=False
            ).astype(np.int64)
        }
        if self.dynamic:
            # Mask-churn traffic: every request narrows differently, and
            # none of it may retrace (the whole point of dynamic mode).
            pick = rng.integers(0, 3)
            if pick == 1:
                out["mask"] = f"window:{int(rng.integers(0, self.window + 1))}"
            elif pick == 2:
                out["mask"] = f"topk:{int(rng.integers(1, 2 * self.window + 2))}"
        return out

    def program_params(self) -> str:
        # The window width is a trace-time constant of the scoring
        # program; the context matrix and head vector ride in as
        # arguments (shapes covered by avals), so a refreshed context
        # never invalidates the ladder. Dynamic mode bakes the same
        # window as a CAPACITY and is a different program (runtime
        # n_valid/kind/param arguments), so it must not alias.
        return f"w{self.window}-dyn" if self.dynamic else f"w{self.window}"

    @property
    def capacity_segment(self) -> Optional[str]:
        """The serve-key capacity-bucket segment (None for static
        builds, whose keys must stay byte-identical): the window
        capacity and the context rung — everything the traced program's
        structure depends on that isn't an aval."""
        if not self.dynamic:
            return None
        return f"w{self.window}.n{self.ctx_cap}"

    # -- device program ------------------------------------------------ #

    def build_program(self, batch_bucket: int, inner_bucket: int):
        import jax
        import jax.numpy as jnp

        from distributed_sddmm_tpu.ops.kernels import ATTN_NEG

        w = self.window
        inv_sqrt_r = 1.0 / float(np.sqrt(self.R))

        if not self.dynamic:
            n_ctx = self.n_ctx

            def score(K, head, tokens, mask):
                # (b, L, 2w+1) sliding-window neighborhood, edge-clipped
                # via a validity mask (clip keeps the gather in range;
                # the mask keeps the softmax honest).
                offs = jnp.arange(-w, w + 1, dtype=jnp.int32)
                nb = tokens[..., None] + offs
                valid = (nb >= 0) & (nb < n_ctx)
                nb = jnp.clip(nb, 0, n_ctx - 1)
                q = K[tokens]                                  # (b, L, R)
                kn = K[nb]                                     # (b, L, W, R)
                logits = (
                    jnp.sum(q[..., None, :] * kn, axis=-1) * inv_sqrt_r
                )
                zsafe = jnp.where(
                    valid, logits, jnp.asarray(ATTN_NEG, K.dtype)
                )
                m = jnp.max(zsafe, axis=-1, keepdims=True)     # last-axis
                e = jnp.where(valid, jnp.exp(zsafe - m), 0.0)  # batch-inv
                d = jnp.sum(e, axis=-1)
                vals = jnp.sum(kn * head, axis=-1)             # (b, L, W)
                num = jnp.sum(e * vals, axis=-1)
                # The token itself is always in-window, so d > 0 at
                # every real row; padded rows divide by 1 and are
                # masked to 0.
                return num / jnp.where(d > 0, d, 1.0) * mask

            return jax.jit(score)

        ctx_cap = self.ctx_cap
        W = 2 * w + 1

        def score_dyn(K, n_valid, head, tokens, mask, kind, param):
            # Capacity-shaped gather: K is padded to the ctx_cap rung,
            # the real row count is the RUNTIME scalar n_valid, and the
            # per-request mask (kind 0 = window:<p>, kind 1 = topk:<p>)
            # narrows the fixed ±w neighborhood with data, never with a
            # trace constant — every op below is batch-dim-invariant
            # (gathers, elementwise, per-row last-axis sort/reductions).
            offs = jnp.arange(-w, w + 1, dtype=jnp.int32)
            nb = tokens[..., None] + offs
            valid = (nb >= 0) & (nb < n_valid)
            nb = jnp.clip(nb, 0, ctx_cap - 1)
            q = K[tokens]                                      # (b, L, R)
            kn = K[nb]                                         # (b, L, W, R)
            logits = jnp.sum(q[..., None, :] * kn, axis=-1) * inv_sqrt_r
            neg = jnp.asarray(ATTN_NEG, K.dtype)
            zsafe0 = jnp.where(valid, logits, neg)
            p = param[:, None, None]
            keep_window = jnp.abs(offs)[None, None, :] <= p
            # topk: per-row descending sort, threshold at the p-th
            # value; ties AT the threshold are all kept — deterministic
            # and order-free, unlike an argsort tie-break.
            sorted_desc = -jnp.sort(-zsafe0, axis=-1)
            kidx = jnp.clip(p, 1, W) - 1
            thr = jnp.take_along_axis(
                sorted_desc, jnp.broadcast_to(kidx, zsafe0.shape[:-1] + (1,)),
                axis=-1,
            )
            keep_topk = zsafe0 >= thr
            keep = jnp.where(kind[:, None, None] == 1, keep_topk, keep_window)
            valid = valid & keep
            zsafe = jnp.where(valid, zsafe0, neg)
            m = jnp.max(zsafe, axis=-1, keepdims=True)
            e = jnp.where(valid, jnp.exp(zsafe - m), 0.0)
            d = jnp.sum(e, axis=-1)
            vals = jnp.sum(kn * head, axis=-1)
            num = jnp.sum(e * vals, axis=-1)
            return num / jnp.where(d > 0, d, 1.0) * mask

        return jax.jit(score_dyn)

    def _mask_arrays(
        self, payloads: list[dict], b: int
    ) -> tuple[np.ndarray, np.ndarray]:
        from distributed_sddmm_tpu import masks

        kind = np.zeros(b, dtype=np.int32)
        param = np.full(b, self.window, dtype=np.int32)
        for i, p in enumerate(payloads):
            spec = p.get("mask")
            if spec is None:
                continue
            fam, val = masks.parse_dynamic_spec(
                spec, w_max=self.window, k_max=2 * self.window + 1
            )
            kind[i] = 1 if fam == "topk" else 0
            param[i] = val
        return kind, param

    def pad_batch(
        self, payloads: list[dict], batch_bucket: int, inner_bucket: int
    ) -> tuple:
        b, L = batch_bucket, inner_bucket
        tokens = np.zeros((b, L), dtype=np.int32)
        mask = np.zeros((b, L), dtype=np.float32)
        for i, p in enumerate(payloads):
            n = len(p["tokens"])
            tokens[i, :n] = p["tokens"]
            mask[i, :n] = 1.0
        if not self.dynamic:
            return (self._K_dev, self._w_dev, tokens, mask)
        kind, param = self._mask_arrays(payloads, b)
        return (
            self._K_dev, self._n_valid_dev, self._w_dev,
            tokens, mask, kind, param,
        )

    def unpad(self, outputs, payloads: list[dict]) -> list[dict]:
        scores = np.asarray(outputs)[: len(payloads)]
        return [
            {
                "tokens": np.asarray(p["tokens"], dtype=np.int64),
                "scores": scores[i][: len(p["tokens"])],
            }
            for i, p in enumerate(payloads)
        ]

    # -- host paths ---------------------------------------------------- #

    def _scores_host(self, payload: dict, K: np.ndarray) -> np.ndarray:
        from distributed_sddmm_tpu.ops.kernels import ATTN_NEG

        head = self._w_host.astype(K.dtype)
        tokens = np.asarray(payload["tokens"], dtype=np.int64)
        offs = np.arange(-self.window, self.window + 1, dtype=np.int64)
        nb = tokens[:, None] + offs
        valid = (nb >= 0) & (nb < self.n_ctx)
        nb = np.clip(nb, 0, self.n_ctx - 1)
        q = K[tokens]
        kn = K[nb]
        logits = np.sum(q[:, None, :] * kn, axis=-1) / np.sqrt(
            K.dtype.type(self.R)
        )
        zsafe = np.where(valid, logits, K.dtype.type(ATTN_NEG))
        if self.dynamic and payload.get("mask") is not None:
            from distributed_sddmm_tpu import masks

            fam, val = masks.parse_dynamic_spec(
                payload["mask"],
                w_max=self.window,
                k_max=2 * self.window + 1,
            )
            if fam == "window":
                valid = valid & (np.abs(offs)[None, :] <= val)
            else:
                sorted_desc = -np.sort(-zsafe, axis=-1)
                kidx = min(max(val, 1), offs.size) - 1
                thr = sorted_desc[:, kidx : kidx + 1]
                valid = valid & (zsafe >= thr)
            zsafe = np.where(valid, zsafe, K.dtype.type(ATTN_NEG))
        m = np.max(zsafe, axis=-1, keepdims=True)
        e = np.where(valid, np.exp(zsafe - m), 0.0).astype(K.dtype)
        d = np.sum(e, axis=-1)
        vals = np.sum(kn * head, axis=-1)
        return np.sum(e * vals, axis=-1) / np.where(d > 0, d, 1.0)

    # -- structure rebind ----------------------------------------------- #

    def rebind_structure(self, context: np.ndarray) -> dict:
        """Bind a grown/refreshed context matrix (``dynamic=True`` only).

        Growth within the ``ctx_cap`` rung rebinds in place: the padded
        device matrix and the runtime ``n_valid`` scalar change, the
        program avals do not — every compiled cell keeps serving
        (counted ``dynstruct_rebinds``). Growth past the rung spills:
        the capacity re-derives, the serve keys change through
        :attr:`capacity_segment`, and the engine re-warms the ladder
        (counted ``dynstruct_bucket_spills`` + ``structure_retraces``).
        """
        from distributed_sddmm_tpu.dynstruct import note_rebind

        if not self.dynamic:
            raise ValueError(
                "attention structure rebind needs dynamic=True (a static "
                "build bakes n_ctx into the traced program)"
            )
        K = np.ascontiguousarray(context, dtype=np.float32)
        if K.ndim != 2 or K.shape[1] != self.R:
            raise ValueError(
                f"context must be (n, {self.R}), got {K.shape}"
            )
        fit = K.shape[0] <= self.ctx_cap
        if fit:
            import jax.numpy as jnp

            self._K_host = K
            self.n_ctx = K.shape[0]
            self._K_pad[:] = 0.0
            self._K_pad[: self.n_ctx] = K
            self._K_dev = jnp.asarray(self._K_pad)
            self._n_valid_dev = jnp.asarray(np.int32(self.n_ctx))
        else:
            self._bind_context(K)
        note_rebind(fit)
        return {
            "fit": fit,
            "n_ctx": self.n_ctx,
            "ctx_cap": self.ctx_cap,
        }

    def serial(self, payload: dict) -> dict:
        tokens = np.asarray(payload["tokens"], dtype=np.int64)
        return {
            "tokens": tokens,
            "scores": self._scores_host(payload, self._K_host).astype(
                np.float32
            ),
        }

    def oracle(self, payload: dict) -> dict:
        tokens = np.asarray(payload["tokens"], dtype=np.int64)
        return {
            "tokens": tokens,
            "scores": self._scores_host(
                payload, self._K_host.astype(np.float64)
            ),
        }

    def check_reply(self, payload: dict, reply: dict) -> bool:
        want = self.oracle(payload)["scores"]
        got = np.asarray(reply["scores"], dtype=np.float64)[: len(want)]
        scale = max(float(np.max(np.abs(want))) if want.size else 0.0, 1.0)
        return bool(np.all(np.abs(got - want) <= 1e-3 * scale))


# --------------------------------------------------------------------- #
# GAT: node scoring over cached forward embeddings
# --------------------------------------------------------------------- #


class GATNodeScore(ServingWorkload):
    """Score requested nodes against the warm model's cached embeddings.

    ``refresh()`` runs the (whole-graph) forward pass and caches the
    final-layer embeddings in global row order; per-request serving is a
    gather + a fixed linear scoring head (seeded at construction so
    replies are reproducible across processes).

    Payload: ``{"nodes": int array}``.
    Reply:   ``{"nodes": int array, "scores": float array}`` (one scalar
    per requested node).
    """

    name = "gat"

    def __init__(
        self,
        model,
        node_buckets: tuple[int, ...] = GAT_NODE_BUCKETS,
        head_seed: int = 0,
        kernel_variant: Optional[str] = None,
    ):
        self.model = model
        self.kernel_variant = (
            kernel_variant if kernel_variant is not None
            else _model_kernel_variant(model)
        )
        self.wire = _model_wire(model)
        self.inner_buckets = tuple(sorted(int(b) for b in node_buckets))
        self.M = model.d_ops.M
        self._F = model.layers[-1].output_features
        # Fixed scoring head: embeddings -> scalar logit.
        rng = np.random.default_rng(head_seed)
        self._w_host = (
            rng.standard_normal(self._F) / np.sqrt(self._F)
        ).astype(np.float32)
        self._X_host: Optional[np.ndarray] = None
        self._X_dev = None
        self._w_dev = None
        self.refresh()

    def refresh(self) -> None:
        """Run the warm forward pass and cache the embeddings (call
        after a weight update; serving reads a consistent snapshot)."""
        import jax.numpy as jnp

        self._X_host = np.ascontiguousarray(
            self.model.node_embeddings(), dtype=np.float32
        )
        self._X_dev = jnp.asarray(self._X_host)
        self._w_dev = jnp.asarray(self._w_host)

    # -- payload shaping ----------------------------------------------- #

    def inner_size(self, payload: dict) -> int:
        return int(len(payload["nodes"]))

    def clamp(self, payload: dict) -> dict:
        cap = self.inner_buckets[-1]
        if len(payload["nodes"]) <= cap:
            return payload
        return {"nodes": np.asarray(payload["nodes"])[:cap]}

    def sample_payload(self, rng: np.random.Generator) -> dict:
        n = int(min(1 + rng.poisson(2), self.inner_buckets[-1]))
        return {
            "nodes": rng.choice(self.M, size=n, replace=False).astype(np.int64)
        }

    # -- device program ------------------------------------------------ #

    def build_program(self, batch_bucket: int, inner_bucket: int):
        import jax

        def score(X, w, nodes, mask):
            emb = X[nodes]                        # (b, L, F)
            return (emb @ w) * mask               # (b, L)

        return jax.jit(score)

    def pad_batch(
        self, payloads: list[dict], batch_bucket: int, inner_bucket: int
    ) -> tuple:
        b, L = batch_bucket, inner_bucket
        nodes = np.zeros((b, L), dtype=np.int32)
        mask = np.zeros((b, L), dtype=np.float32)
        for i, p in enumerate(payloads):
            n = len(p["nodes"])
            nodes[i, :n] = p["nodes"]
            mask[i, :n] = 1.0
        return (self._X_dev, self._w_dev, nodes, mask)

    def unpad(self, outputs, payloads: list[dict]) -> list[dict]:
        scores = np.asarray(outputs)[: len(payloads)]
        return [
            {
                "nodes": np.asarray(p["nodes"], dtype=np.int64),
                "scores": scores[i][: len(p["nodes"])],
            }
            for i, p in enumerate(payloads)
        ]

    # -- host paths ---------------------------------------------------- #

    def serial(self, payload: dict) -> dict:
        nodes = np.asarray(payload["nodes"], dtype=np.int64)
        scores = self._X_host[nodes] @ self._w_host
        return {"nodes": nodes, "scores": scores.astype(np.float32)}

    def oracle(self, payload: dict) -> dict:
        nodes = np.asarray(payload["nodes"], dtype=np.int64)
        scores = (
            self._X_host[nodes].astype(np.float64)
            @ self._w_host.astype(np.float64)
        )
        return {"nodes": nodes, "scores": scores}

    def check_reply(self, payload: dict, reply: dict) -> bool:
        want = self.oracle(payload)["scores"]
        got = np.asarray(reply["scores"], dtype=np.float64)[: len(want)]
        scale = max(float(np.max(np.abs(want))) if want.size else 0.0, 1.0)
        return bool(np.all(np.abs(got - want) <= 1e-3 * scale))
