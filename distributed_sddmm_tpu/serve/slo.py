"""SLO specs, per-request latency recording, and the open-loop load gen.

Three pieces, one contract:

* :class:`SLOSpec` — the target (``DSDDMM_SLO="p99_ms=250,err_rate=0.01"``
  or the ``--slo`` flag): latency percentiles in milliseconds plus an
  error-rate bound. :meth:`SLOSpec.check` turns an observed summary into
  a (possibly empty) list of violations.
* :class:`LatencyRecorder` — the measurement half: per-request stage
  latencies (enqueue→admit→execute→reply, straight off the
  :class:`~distributed_sddmm_tpu.serve.queue.Request` timeline), queue
  depth and batch occupancy samples, shed/error/degraded counts.
  Percentiles use the nearest-rank convention (p99 of 100 samples is the
  99th largest — no interpolation invents latencies nobody observed).
* :func:`run_load` — an **open-loop Poisson** load generator: arrival
  times are drawn ahead of time from a seeded exponential process and
  submissions happen at those instants regardless of completions (a
  closed loop self-throttles and hides capacity cliffs; open-loop is the
  honest way to ask "does this engine sustain λ req/s"). Every Nth reply
  is checked against the workload's float64 oracle.

The summary :func:`run_load` returns is the serving half of a bench
record: ``latency_ms`` percentiles, ``shed_count``, occupancy — the
fields ``bench serve`` persists to the run store and ``bench gate``
regresses on (``obs/regress.py`` serving axes).
"""

from __future__ import annotations

import dataclasses
import math
import os
import threading
import time  # time.sleep only; clocks go through obs.clock
from typing import Optional

import numpy as np

from distributed_sddmm_tpu.obs import clock
from distributed_sddmm_tpu.obs import log as obs_log
from distributed_sddmm_tpu.obs.telemetry import LatencyHistogram
from distributed_sddmm_tpu.serve.queue import (
    DEFAULT_TENANT, ShedError, TenantSpec,
)

_PCTS = (50, 95, 99)


def percentile(samples: list[float], pct: float) -> float | None:
    """Nearest-rank percentile (None on empty input)."""
    if not samples:
        return None
    ordered = sorted(samples)
    rank = max(1, math.ceil(pct / 100.0 * len(ordered)))
    return ordered[rank - 1]


@dataclasses.dataclass(frozen=True)
class SLOSpec:
    """Latency/error targets. Unset fields (None) are unconstrained."""

    p50_ms: float | None = None
    p95_ms: float | None = None
    p99_ms: float | None = None
    err_rate: float | None = None
    shed_rate: float | None = None

    _FIELDS = ("p50_ms", "p95_ms", "p99_ms", "err_rate", "shed_rate")

    @classmethod
    def parse(cls, spec: str | None) -> "SLOSpec":
        """``"p99_ms=250,err_rate=0.01"`` → SLOSpec. Unknown keys raise —
        a typo'd SLO that silently constrains nothing would make every
        run green."""
        if not spec:
            return cls()
        kw = {}
        for part in spec.split(","):
            part = part.strip()
            if not part:
                continue
            if "=" not in part:
                raise ValueError(f"SLO entry {part!r} is not key=value")
            k, v = part.split("=", 1)
            k = k.strip()
            if k not in cls._FIELDS:
                raise ValueError(
                    f"unknown SLO key {k!r}; expected one of {cls._FIELDS}"
                )
            kw[k] = float(v)
        return cls(**kw)

    @classmethod
    def from_env(cls) -> "SLOSpec":
        return cls.parse(os.environ.get("DSDDMM_SLO"))

    def to_dict(self) -> dict:
        return {
            k: v for k, v in dataclasses.asdict(self).items() if v is not None
        }

    def check(self, summary: dict) -> list[dict]:
        """Violations of this spec in a :meth:`LatencyRecorder.summary`
        (empty list = SLO met; unmeasured axes are not violations)."""
        out = []
        lat = summary.get("latency_ms") or {}
        for pct in _PCTS:
            want = getattr(self, f"p{pct}_ms")
            got = lat.get(f"p{pct}")
            if want is not None and got is not None and got > want:
                out.append({"axis": f"p{pct}_ms", "limit": want,
                            "observed": round(got, 3)})
        for axis in ("err_rate", "shed_rate"):
            want = getattr(self, axis)
            got = summary.get(axis)
            if want is not None and got is not None and got > want:
                out.append({"axis": axis, "limit": want,
                            "observed": round(got, 6)})
        return out

    def burn_rate(self, summary: dict) -> float | None:
        """Worst-axis error-budget burn rate for this spec over one
        recorder summary (None when no constrained axis is measurable).

        A ``pXX_ms`` target's budget is the ``(100-XX)%`` of requests
        allowed above it; the observed bad fraction comes from the
        summary's fixed-bucket ``request_hist`` so burn rates from
        different processes/windows aggregate the way the histograms
        do. ``err_rate``/``shed_rate`` budgets divide directly. 1.0 =
        burning exactly at budget; >1 = on course to violate.
        """
        rates = []
        hist = LatencyHistogram.from_dict(summary.get("request_hist"))
        if hist is not None and hist.total:
            for pct in _PCTS:
                want = getattr(self, f"p{pct}_ms")
                budget = 1.0 - pct / 100.0
                if want is None or budget <= 0:
                    continue
                rates.append(hist.fraction_above(want) / budget)
        for axis in ("err_rate", "shed_rate"):
            want = getattr(self, axis)
            got = summary.get(axis)
            if want and got is not None:
                rates.append(got / want)
        return round(max(rates), 4) if rates else None


def parse_tenants(spec: str | None) -> Optional[dict[str, TenantSpec]]:
    """``"premium:3:p99_ms=250,err_rate=0.01;batch:1"`` → tenant table.

    Grammar: ``;``-separated tenant clauses, each ``name[:weight[:slo]]``.
    The SLO sub-spec is the :meth:`SLOSpec.parse` grammar (commas inside
    it are why clauses join on ``;``). Weight defaults to 1.0. Returns
    None on an empty spec so callers fall back to single-tenant mode.
    """
    if not spec:
        return None
    out: dict[str, TenantSpec] = {}
    for part in spec.split(";"):
        part = part.strip()
        if not part:
            continue
        fields = part.split(":", 2)
        name = fields[0].strip()
        weight = 1.0
        if len(fields) > 1 and fields[1].strip():
            weight = float(fields[1])
        slo = None
        if len(fields) > 2 and fields[2].strip():
            slo = SLOSpec.parse(fields[2].strip())
        if name in out:
            raise ValueError(f"duplicate tenant {name!r} in spec")
        out[name] = TenantSpec(name=name, weight=weight, slo=slo)
    return out or None


def tenants_from_env() -> Optional[dict[str, TenantSpec]]:
    return parse_tenants(os.environ.get("DSDDMM_TENANTS"))


class LatencyRecorder:
    """Thread-safe accumulator for one serving session's observations."""

    def __init__(self):
        self._lock = threading.Lock()
        self._total_s: list[float] = []
        self._queue_s: list[float] = []
        self._batch_wait_s: list[float] = []
        self._execute_s: list[float] = []
        self._depth: list[int] = []
        self._occupancy: list[float] = []
        #: Fixed-bucket total-latency histogram — the mergeable view
        #: (sample-list percentiles above are exact but unmergeable).
        self.hist = LatencyHistogram()
        self.completed = 0
        self.errors = 0
        self.degraded = 0
        self.shed = 0
        #: Per-tenant breakdown (QoS axes). Cells appear lazily so the
        #: single-tenant path pays nothing and old summaries are stable.
        self._tenant_stats: dict[str, dict] = {}

    # -- feeding ------------------------------------------------------- #

    def _tenant_cell(self, tenant: str) -> dict:
        """Caller holds the lock."""
        cell = self._tenant_stats.get(tenant)
        if cell is None:
            cell = {"completed": 0, "errors": 0, "shed": 0,
                    "hist": LatencyHistogram()}
            self._tenant_stats[tenant] = cell
        return cell

    def record_reply(self, req) -> None:
        stages = req.stage_latencies_s()
        tenant = getattr(req, "tenant", DEFAULT_TENANT)
        with self._lock:
            self.completed += 1
            cell = self._tenant_cell(tenant)
            cell["completed"] += 1
            if req.degraded:
                self.degraded += 1
            if "total_s" in stages:
                self._total_s.append(stages["total_s"])
                self.hist.add(stages["total_s"] * 1e3)
                cell["hist"].add(stages["total_s"] * 1e3)
            if "queue_s" in stages:
                self._queue_s.append(stages["queue_s"])
            if "batch_wait_s" in stages:
                self._batch_wait_s.append(stages["batch_wait_s"])
            if "execute_s" in stages:
                self._execute_s.append(stages["execute_s"])

    def record_error(self, tenant: str = DEFAULT_TENANT) -> None:
        with self._lock:
            self.errors += 1
            self._tenant_cell(tenant)["errors"] += 1

    def record_shed(self, tenant: str = DEFAULT_TENANT) -> None:
        with self._lock:
            self.shed += 1
            self._tenant_cell(tenant)["shed"] += 1

    def record_batch(self, batch_size: int, bucket: int, depth: int) -> None:
        with self._lock:
            self._depth.append(depth)
            self._occupancy.append(batch_size / bucket if bucket else 0.0)

    # -- reporting ----------------------------------------------------- #

    @staticmethod
    def _pct_ms(samples: list[float]) -> dict:
        out = {}
        for pct in _PCTS:
            v = percentile(samples, pct)
            if v is not None:
                out[f"p{pct}"] = round(v * 1e3, 3)
        if samples:
            out["mean"] = round(sum(samples) / len(samples) * 1e3, 3)
            out["max"] = round(max(samples) * 1e3, 3)
        return out

    def summary(self) -> dict:
        with self._lock:
            total = list(self._total_s)
            queue = list(self._queue_s)
            batch_wait = list(self._batch_wait_s)
            execute = list(self._execute_s)
            depth = list(self._depth)
            occ = list(self._occupancy)
            completed, errors = self.completed, self.errors
            shed, degraded = self.shed, self.degraded
            hist = LatencyHistogram(self.hist.bounds_ms,
                                    list(self.hist.counts))
            tstats = {
                name: {"completed": c["completed"], "errors": c["errors"],
                       "shed": c["shed"],
                       "hist": LatencyHistogram(c["hist"].bounds_ms,
                                                list(c["hist"].counts))}
                for name, c in self._tenant_stats.items()
            }
        requests = completed + errors + shed
        out = {
            "requests": requests,
            "completed": completed,
            "errors": errors,
            "shed_count": shed,
            "degraded_count": degraded,
            "err_rate": errors / requests if requests else 0.0,
            "shed_rate": shed / requests if requests else 0.0,
            "latency_ms": self._pct_ms(total),
            "queue_ms": self._pct_ms(queue),
            "batch_wait_ms": self._pct_ms(batch_wait),
            "execute_ms": self._pct_ms(execute),
        }
        if hist.total:
            # The mergeable histogram view (bench-record fields the
            # runstore index lifts into hist_p* columns).
            out["request_hist"] = hist.to_dict()
            out["latency_hist_ms"] = hist.percentiles_ms()
        if occ:
            out["batch_occupancy"] = {
                "mean": round(sum(occ) / len(occ), 4),
                "p50": round(percentile(occ, 50), 4),
                "batches": len(occ),
            }
        if depth:
            out["queue_depth"] = {
                "mean": round(sum(depth) / len(depth), 2),
                "p95": percentile(depth, 95),
                "max": max(depth),
            }
        if set(tstats) - {DEFAULT_TENANT}:
            # Per-tenant QoS breakdown — only emitted once a named
            # tenant shows up, so single-tenant records keep their
            # pre-fleet shape byte for byte.
            out["tenant"] = {
                name: self._tenant_summary(cell)
                for name, cell in sorted(tstats.items())
            }
        return out

    @staticmethod
    def _tenant_summary(cell: dict) -> dict:
        t_req = cell["completed"] + cell["errors"] + cell["shed"]
        entry = {
            "requests": t_req,
            "completed": cell["completed"],
            "errors": cell["errors"],
            "shed_count": cell["shed"],
            "err_rate": cell["errors"] / t_req if t_req else 0.0,
            "shed_rate": cell["shed"] / t_req if t_req else 0.0,
        }
        if cell["hist"].total:
            entry["request_hist"] = cell["hist"].to_dict()
            entry["latency_hist_ms"] = cell["hist"].percentiles_ms()
        return entry


# --------------------------------------------------------------------- #
# Open-loop Poisson load generator
# --------------------------------------------------------------------- #


def run_load(
    engine,
    duration_s: float,
    rate_hz: float,
    seed: int = 0,
    oracle_every: int = 8,
    reply_timeout_s: float = 30.0,
    slo: Optional[SLOSpec] = None,
    tenants: Optional[dict[str, TenantSpec]] = None,
    honor_retry_after: bool = False,
) -> dict:
    """Drive ``engine`` with Poisson arrivals for ``duration_s`` seconds.

    Arrivals are precomputed (seeded exponential inter-arrival gaps at
    ``rate_hz``), submitted open-loop from this thread; each reply is
    collected on its own short-lived waiter thread (pruned as they
    finish) so a slow reply never delays the next arrival — arrival
    instants are absolute offsets from the run start, so thread-spawn
    cost cannot accumulate into schedule drift. Every
    ``oracle_every``-th completed request
    is checked against ``engine.workload.oracle`` (float64 reference);
    mismatches are counted and logged, never raised — the load gen's job
    is to measure, the caller's to judge.

    Returns the recorder summary extended with throughput, oracle-check
    results, and SLO violations (``slo`` defaults to the env spec).

    ``tenants`` (a :func:`parse_tenants` table) makes each arrival pick a
    tenant weighted by the spec weights; per-tenant burn rates land in
    ``summary["tenant"]``. ``honor_retry_after=True`` makes the client a
    good citizen: a shed whose :class:`ShedError` carries a positive
    ``retry_after_s`` opens a backoff window, and arrivals inside it are
    *deferred* (counted, never submitted) — the admission-control
    contract a fleet router relies on to actually relieve pressure.
    """
    slo = slo if slo is not None else SLOSpec.from_env()
    rec = engine.recorder
    rng = np.random.default_rng(seed)
    workload = engine.workload

    tenant_names: list[str] = list(tenants) if tenants else []
    tenant_probs = None
    if tenant_names:
        w = np.array([tenants[t].weight for t in tenant_names], dtype=float)
        tenant_probs = w / w.sum()

    n_expect = max(1, int(duration_s * rate_hz * 2))
    gaps = rng.exponential(1.0 / max(rate_hz, 1e-9), size=n_expect)
    arrivals = np.cumsum(gaps)
    arrivals = arrivals[arrivals < duration_s]

    oracle_checked = [0]
    oracle_failures = [0]
    waiters: list[threading.Thread] = []
    submitted = 0

    def wait_reply(req, check: bool):
        try:
            reply = req.result(timeout_s=reply_timeout_s)
        except ShedError:
            return  # already counted at submit
        except Exception as e:  # noqa: BLE001 — recorded, run continues
            rec.record_error()
            obs_log.warn("serve", "request failed", req=req.req_id,
                         error=f"{type(e).__name__}: {e}")
            return
        rec.record_reply(req)
        if check:
            oracle_checked[0] += 1
            if not workload.check_reply(req.payload, reply):
                oracle_failures[0] += 1
                obs_log.error("serve", "oracle mismatch", req=req.req_id)

    deferred = 0
    backoff_until = 0.0

    t0 = clock.now()
    for i, t_arr in enumerate(arrivals):
        delay = t0 + float(t_arr) - clock.now()
        if delay > 0:
            time.sleep(delay)
        if honor_retry_after and clock.now() < backoff_until:
            deferred += 1  # honoring the server's Retry-After hint
            continue
        payload = workload.sample_payload(rng)
        try:
            if tenant_names:
                tenant = tenant_names[
                    int(rng.choice(len(tenant_names), p=tenant_probs))
                ]
                req = engine.submit(payload, tenant=tenant)
            else:
                req = engine.submit(payload)
        except ShedError as e:
            # The engine's submit path recorded the shed.
            hint = float(getattr(e, "retry_after_s", 0.0) or 0.0)
            if honor_retry_after and hint > 0:
                backoff_until = clock.now() + hint
            continue
        submitted += 1
        w = threading.Thread(
            target=wait_reply,
            args=(req, oracle_every > 0 and i % oracle_every == 0),
            daemon=True, name=f"serve-wait-{req.req_id}",
        )
        w.start()
        waiters.append(w)
        if len(waiters) >= 256:  # prune finished waiters, bound the list
            waiters = [t for t in waiters if t.is_alive()]

    for w in waiters:
        w.join(reply_timeout_s)
    elapsed = clock.now() - t0

    summary = rec.summary()
    summary.update({
        "duration_s": round(elapsed, 3),
        "offered_rate_hz": rate_hz,
        "offered": int(len(arrivals)),
        "submitted": submitted,
        "throughput_rps": round(summary["completed"] / elapsed, 3)
        if elapsed > 0 else 0.0,
        "oracle_checked": oracle_checked[0],
        "oracle_failures": oracle_failures[0],
    })
    if honor_retry_after:
        summary["retry_after_deferred"] = deferred
    summary["slo"] = slo.to_dict()
    summary["slo_violations"] = slo.check(summary)
    # Error-budget burn rate (None when the spec constrains nothing):
    # the live-telemetry axis `bench gate` regresses run over run.
    summary["burn_rate"] = slo.burn_rate(summary)
    attach_tenant_slo(summary, tenants)
    return summary


def attach_tenant_slo(
    summary: dict, tenants: Optional[dict[str, TenantSpec]],
) -> dict:
    """Judge each declared tenant's sub-summary against its own SLO:
    ``summary["tenant"][name]`` gains ``slo``/``slo_violations``/
    ``burn_rate`` (the per-tenant ``serve:burn_rate:<name>`` gate axes)
    plus the scheduler weight. Declared-but-idle tenants get a zeroed
    cell so the record's tenant table always matches the declaration."""
    if not tenants:
        return summary
    tstats = summary.setdefault("tenant", {})
    for name, tspec in tenants.items():
        entry = tstats.setdefault(name, {
            "requests": 0, "completed": 0, "errors": 0,
            "shed_count": 0, "err_rate": 0.0, "shed_rate": 0.0,
        })
        if tspec.slo is not None:
            entry["slo"] = tspec.slo.to_dict()
            entry["slo_violations"] = tspec.slo.check(entry)
            entry["burn_rate"] = tspec.slo.burn_rate(entry)
        entry["weight"] = tspec.weight
    return summary
