"""The front router: one door, many replicas.

Zero-dependency, same stdlib-HTTP stance as ``obs/httpexp.py`` — in
fact the router's HTTP surface IS an :class:`~distributed_sddmm_tpu.
obs.httpexp.AdminServer` whose ``submit_fn`` is the routing decision:
``POST /submit`` routes, ``/snapshot`` serves the fleet topology,
``/healthz``/``/readyz`` make the router itself probeable. A shed
raised here (:class:`~distributed_sddmm_tpu.serve.queue.ShedError`)
leaves the building as the same 429 + ``Retry-After`` a replica's own
admission control produces — backpressure composes through the tiers.

Routing policy, in order:

1. **Structure-aware admission** (NeutronSparse, at request
   granularity): the request's inner size is bucketed against each
   replica's exported warm ladder (``/snapshot``'s ``buckets``); a
   request larger than every ready replica's largest warm rung is
   *pathological* — padding it into a batch would poison the batch, so
   it routes to the host-serial tier (``serial=true``, preferring a
   ``fallback``-role replica) instead.
2. **Health**: only replicas that are ready (``/readyz``), not
   draining, and recently polled are candidates.
3. **Drain, don't kill, burning replicas**: a replica whose SLO burn
   rate exceeds ``drain_burn`` stops receiving admissions but finishes
   its in-flight queue; it resumes when burn recovers below
   ``resume_burn`` (hysteresis — no flapping at the threshold).
4. **Least pressure**: among candidates, lowest (queue depth fraction,
   burn) wins.
5. **Failover**: a connection-level failure (killed replica) marks the
   replica not-ready and retries the SAME request on the next
   candidate — a chaos kill turns into a re-admission, never a
   silently dropped reply. A 429 from one replica tries the next; only
   when every candidate sheds does the router shed at the edge, with
   the largest ``Retry-After`` hint it saw.
"""

from __future__ import annotations

import os
import threading
import time
from typing import Callable, Optional

from distributed_sddmm_tpu.obs import log as obs_log
from distributed_sddmm_tpu.serve.queue import DEFAULT_TENANT, ShedError
from distributed_sddmm_tpu.utils.buckets import bucket_for


def _drain_burn_default() -> float:
    v = os.environ.get("DSDDMM_FLEET_DRAIN_BURN")
    return float(v) if v not in (None, "") else 1.0


class ReplicaState:
    """The router's cached view of one replica's exported signals."""

    def __init__(self, name: str, port: int, role: str = "serve"):
        self.name = name
        self.port = port
        self.role = role
        self.ready = False
        self.draining = False
        self.burn: Optional[float] = None
        self.depth_frac = 0.0
        self.inner_buckets: tuple = ()
        self.t_poll = 0.0
        self.errors = 0

    @property
    def inner_max(self) -> int:
        return max(self.inner_buckets) if self.inner_buckets else 0

    def describe(self) -> dict:
        return {
            "name": self.name, "port": self.port, "role": self.role,
            "ready": self.ready, "draining": self.draining,
            "burn": self.burn, "depth_frac": self.depth_frac,
            "inner_buckets": list(self.inner_buckets),
            "errors": self.errors,
        }


def _default_inner_size(payload: dict) -> int:
    """Workload-agnostic inner-size probe: the longest list-valued
    field. Matches ``inner_size`` for the shipped workloads (ALS items,
    GAT neighbor lists, attention windows) without importing them."""
    n = 1
    for v in payload.values():
        if isinstance(v, (list, tuple)):
            n = max(n, len(v))
        else:
            size = getattr(v, "shape", None)
            if size:
                n = max(n, int(size[0]))
    return n


class FleetRouter:
    """Balance, shed, drain, and structure-route over a replica pool.

    ``manager`` (a :class:`~distributed_sddmm_tpu.fleet.manager.
    FleetManager`) is the live endpoint source — respawns are picked up
    on the next poll tick. Tests can instead pass static ``endpoints``
    ``[(name, port, role), ...]``.
    """

    def __init__(
        self,
        manager=None,
        endpoints: Optional[list] = None,
        *,
        poll_interval_s: float = 0.25,
        drain_burn: Optional[float] = None,
        resume_frac: float = 0.8,
        request_timeout_s: float = 30.0,
        shed_retry_after_s: float = 1.0,
        inner_size_fn: Optional[Callable[[dict], int]] = None,
        port: int = 0,
    ):
        if manager is None and endpoints is None:
            raise ValueError("need a manager or static endpoints")
        self.manager = manager
        self.static_endpoints = endpoints
        self.poll_interval_s = float(poll_interval_s)
        self.drain_burn = (
            _drain_burn_default() if drain_burn is None
            else float(drain_burn)
        )
        self.resume_burn = self.drain_burn * float(resume_frac)
        self.request_timeout_s = float(request_timeout_s)
        self.shed_retry_after_s = float(shed_retry_after_s)
        self.inner_size_fn = inner_size_fn or _default_inner_size
        self._states: dict[str, ReplicaState] = {}
        self._lock = threading.Lock()
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._server = None
        self._port = int(port)
        self.stats = {
            "routed": 0, "failovers": 0, "serial_routed": 0,
            "edge_sheds": 0, "replica_sheds_seen": 0, "drains": 0,
        }

    # -- polling -------------------------------------------------------- #

    def _endpoints(self) -> list:
        if self.manager is not None:
            return [(r.name, r.port, r.role) for r in self.manager.replicas()]
        return list(self.static_endpoints)

    def poll_once(self) -> None:
        """One health sweep: refresh every replica's readiness, burn,
        depth, and ladder; apply the drain/resume hysteresis."""
        from distributed_sddmm_tpu.obs.httpexp import fetch_json

        seen = set()
        for name, port, role in self._endpoints():
            seen.add(name)
            with self._lock:
                st = self._states.get(name)
                if st is None or st.port != port:
                    # New replica, or a respawn on a fresh port — reset
                    # the cached view; it must re-prove readiness.
                    st = self._states[name] = ReplicaState(name, port, role)
            try:
                ready_body = fetch_json("127.0.0.1", port, "/readyz",
                                        timeout_s=1.0)
                snap = fetch_json("127.0.0.1", port, "/snapshot",
                                  timeout_s=1.0)
            except (OSError, ValueError):
                with self._lock:
                    st.ready = False
                    st.errors += 1
                continue
            with self._lock:
                st.ready = bool(ready_body.get("ready"))
                st.depth_frac = float(snap.get("depth_frac") or 0.0)
                st.burn = snap.get("burn_rate")
                buckets = snap.get("buckets") or {}
                st.inner_buckets = tuple(buckets.get("inner") or ())
                st.t_poll = time.monotonic()
                if st.burn is not None:
                    if not st.draining and st.burn > self.drain_burn:
                        st.draining = True
                        self.stats["drains"] += 1
                        obs_log.warn("fleet", "draining burning replica",
                                     name=name, burn=st.burn)
                    elif st.draining and st.burn <= self.resume_burn:
                        st.draining = False
                        obs_log.info("fleet", "replica resumed admissions",
                                     name=name, burn=st.burn)
        with self._lock:
            for gone in set(self._states) - seen:
                del self._states[gone]

    def _poll_loop(self) -> None:
        while not self._stop.is_set():
            try:
                self.poll_once()
            except Exception as e:  # noqa: BLE001 — the loop must survive
                obs_log.warn("fleet", "router poll failed",
                             error=f"{type(e).__name__}: {e}")
            self._stop.wait(self.poll_interval_s)

    # -- routing -------------------------------------------------------- #

    def states(self) -> list[ReplicaState]:
        with self._lock:
            return list(self._states.values())

    def _candidates(self, serial: bool) -> list[ReplicaState]:
        with self._lock:
            states = list(self._states.values())
        pool = [s for s in states if s.ready and not s.draining]
        if serial:
            # Host-serial tier: prefer dedicated fallback replicas, but
            # any ready replica can run the serial rung.
            fallback = [s for s in pool if s.role == "fallback"]
            pool = fallback or pool
        else:
            pool = [s for s in pool if s.role == "serve"]
        return sorted(pool, key=lambda s: (s.depth_frac, s.burn or 0.0,
                                           s.name))

    def route(self, payload: dict, tenant: str = DEFAULT_TENANT,
              serial: bool = False, timeout_s: Optional[float] = None
              ) -> dict:
        """The ``submit_fn`` contract: returns the reply dict, raises
        :class:`ShedError` (→ 429 + Retry-After at the edge) when no
        replica admits the request."""
        from distributed_sddmm_tpu.obs.httpexp import post_json

        timeout_s = self.request_timeout_s if timeout_s is None else timeout_s
        inner = self.inner_size_fn(payload)
        candidates = self._candidates(serial)
        if not serial and candidates:
            # Pathological outlier: larger than every candidate's
            # largest warm rung → host-serial tier, not a poisoned batch.
            fleet_max = max(s.inner_max for s in candidates)
            if fleet_max and inner > fleet_max:
                serial = True
                candidates = self._candidates(serial=True)
        if not candidates:
            self.stats["edge_sheds"] += 1
            raise ShedError("no ready replica",
                            retry_after_s=self.shed_retry_after_s)
        if not serial and len(candidates) > 1:
            # Bucket fit: among healthy candidates prefer those whose
            # warm ladder covers this inner size without clamping to
            # the top rung (bucket_for maps oversize onto the last
            # rung — correct, but it pads maximally).
            fitting = [s for s in candidates if s.inner_buckets
                       and bucket_for(inner, s.inner_buckets) >= inner]
            candidates = fitting or candidates

        shed_hint = 0.0
        saw_shed = False
        for st in candidates:
            body = {"payload": payload, "tenant": tenant,
                    "serial": serial, "timeout_s": timeout_s}
            try:
                code, decoded, headers = post_json(
                    "127.0.0.1", st.port, "/submit", body,
                    timeout_s=timeout_s,
                )
            except OSError as e:
                # Connection-level failure: the replica is gone (chaos
                # kill) or wedged. Mark it and FAIL OVER — the request
                # is re-admitted on the next candidate, not dropped.
                with self._lock:
                    st.ready = False
                    st.errors += 1
                self.stats["failovers"] += 1
                obs_log.warn("fleet", "replica unreachable; failing over",
                             name=st.name, error=f"{type(e).__name__}: {e}")
                continue
            if code == 200:
                with self._lock:
                    self.stats["routed"] += 1
                    if serial:
                        self.stats["serial_routed"] += 1
                return decoded.get("reply")
            if code == 429:
                saw_shed = True
                self.stats["replica_sheds_seen"] += 1
                hint = headers.get("Retry-After") or decoded.get(
                    "retry_after_s", 0.0
                )
                try:
                    shed_hint = max(shed_hint, float(hint))
                except (TypeError, ValueError):
                    pass
                continue  # another replica may have headroom
            raise RuntimeError(
                f"replica {st.name} answered {code}: "
                f"{decoded.get('error', decoded)}"
            )
        self.stats["edge_sheds"] += 1
        raise ShedError(
            "all replicas shed" if saw_shed else "no replica reachable",
            retry_after_s=shed_hint or self.shed_retry_after_s,
        )

    # -- the router's own HTTP surface ---------------------------------- #

    def topology(self) -> dict:
        """The ``/snapshot`` body: per-replica state + router counters
        (and the manager's spawn/loss ledger when attached)."""
        out = {
            "router": True,
            "replicas": [s.describe() for s in self.states()],
            "stats": dict(self.stats),
            "drain_burn": self.drain_burn,
        }
        if self.manager is not None:
            out["manager"] = self.manager.describe()
        return out

    @property
    def port(self) -> int:
        return self._server.port if self._server is not None else self._port

    def start(self) -> "FleetRouter":
        from distributed_sddmm_tpu.obs.httpexp import AdminServer

        if self._thread is not None:
            raise RuntimeError("router already started")
        self.poll_once()  # candidates exist before the first request
        self._stop.clear()
        self._thread = threading.Thread(
            target=self._poll_loop, daemon=True, name="fleet-router-poll",
        )
        self._thread.start()
        self._server = AdminServer(
            snapshot_fn=self.topology, submit_fn=self.route,
            port=self._port,
        ).start()
        obs_log.info("fleet", "router serving",
                     url=f"http://127.0.0.1:{self._server.port}")
        return self

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(5.0)
            self._thread = None
        if self._server is not None:
            self._server.stop()
            self._server = None

    def __enter__(self) -> "FleetRouter":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.stop()
