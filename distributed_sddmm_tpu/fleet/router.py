"""The front router: one door, many replicas.

Zero-dependency, same stdlib-HTTP stance as ``obs/httpexp.py`` — in
fact the router's HTTP surface IS an :class:`~distributed_sddmm_tpu.
obs.httpexp.AdminServer` whose ``submit_fn`` is the routing decision:
``POST /submit`` routes, ``/snapshot`` serves the fleet topology,
``/healthz``/``/readyz`` make the router itself probeable. A shed
raised here (:class:`~distributed_sddmm_tpu.serve.queue.ShedError`)
leaves the building as the same 429 + ``Retry-After`` a replica's own
admission control produces — backpressure composes through the tiers.

Routing policy, in order:

1. **Structure-aware admission** (NeutronSparse, at request
   granularity): the request's inner size is bucketed against each
   replica's exported warm ladder (``/snapshot``'s ``buckets``); a
   request larger than every ready replica's largest warm rung is
   *pathological* — padding it into a batch would poison the batch, so
   it routes to the host-serial tier (``serial=true``, preferring a
   ``fallback``-role replica) instead.
2. **Health**: only replicas that are ready (``/readyz``), not
   draining, recently polled, AND whose circuit breaker admits are
   candidates.
3. **Drain, don't kill, burning replicas**: a replica whose SLO burn
   rate exceeds ``drain_burn`` stops receiving admissions but finishes
   its in-flight queue; it resumes when burn recovers below
   ``resume_burn`` (hysteresis — no flapping at the threshold).
4. **Least pressure**: among candidates, lowest (queue depth fraction,
   burn) wins; half-open breakers sort last (probe traffic only).
5. **Failover**: a connection-level failure, a chaos-injected drop, or
   a malformed/undecodable reply body marks the replica and retries
   the SAME request on the next candidate — a fault turns into a
   re-admission, never a silently dropped reply or a client-facing
   500. A 429 from one replica tries the next; only when every
   candidate sheds does the router shed at the edge, with the largest
   ``Retry-After`` hint it saw.

Gray-failure hardening (PR 17) — crash faults fail fast, *gray* faults
need detectors:

* **Circuit breakers** (per replica): ``breaker_errs`` consecutive
  strikes (submit transport errors/timeouts, undecodable replies,
  failed health polls) → **open** — the replica stops receiving
  admissions, so a wedged runner no longer eats ``request_timeout_s``
  per request. After ``breaker_cooldown_s`` of quiet it goes
  **half-open** (probe traffic admitted, sorted last); one successful
  submit closes it, one failure re-opens it. Health polls never close
  a breaker — ``/readyz`` can lie (that is what makes the failure
  gray); only the submit path proves recovery.
* **Hedged requests**: when the primary attempt has not answered
  within a p95-derived hedge delay, the SAME request is re-submitted
  to the next ready replica and the first reply wins — safe because
  the serve layer's replies are bit-identical across replicas by
  construction. When both eventually land they are compared; a
  mismatch is a byzantine signal (counted, arbitrated, quarantined).
* **Sampled response audit**: a deterministic ``audit_frac`` fraction
  of requests is re-executed on a *different* replica before the
  reply leaves the router and compared bit-for-bit (the canary is
  never audited against itself — the comparator is always another
  process). On mismatch a third replica arbitrates: the odd replica
  out is quarantined (``quarantine_fn`` → ``FleetManager.
  quarantine``) and the majority reply is what the client receives —
  under audit, a byzantine replica cannot leak wrong bytes.
"""

from __future__ import annotations

import collections
import itertools
import json
import os
import threading
import time
from typing import Callable, Optional

from distributed_sddmm_tpu.obs import clock as obs_clock
from distributed_sddmm_tpu.obs import log as obs_log
from distributed_sddmm_tpu.obs import metrics as obs_metrics
from distributed_sddmm_tpu.obs import trace as obs_trace
from distributed_sddmm_tpu.serve.queue import DEFAULT_TENANT, ShedError
from distributed_sddmm_tpu.utils.buckets import bucket_for

#: Hedge delay floor when ``DSDDMM_FLEET_HEDGE`` is a bare enable.
DEFAULT_HEDGE_FLOOR_S = 0.25
#: Hedge delay ceiling — a hedge that waits longer than this is not
#: rescuing a tail, it is a second timeout.
HEDGE_CEIL_S = 2.0


def _drain_burn_default() -> float:
    v = os.environ.get("DSDDMM_FLEET_DRAIN_BURN")
    return float(v) if v not in (None, "") else 1.0


def _breaker_errs_default() -> int:
    v = os.environ.get("DSDDMM_FLEET_BREAKER_ERRS")
    return int(v) if v not in (None, "") else 3


def _breaker_cooldown_default() -> float:
    v = os.environ.get("DSDDMM_FLEET_BREAKER_COOLDOWN")
    return float(v) if v not in (None, "") else 2.0


def _audit_frac_default() -> float:
    v = os.environ.get("DSDDMM_FLEET_AUDIT_FRAC")
    return min(max(float(v), 0.0), 1.0) if v not in (None, "") else 0.0


def _trace_debug_default() -> int:
    """``DSDDMM_FLEET_TRACE_DEBUG``: how many recent fleet request
    chains the router keeps for ``/debug/requests``."""
    v = os.environ.get("DSDDMM_FLEET_TRACE_DEBUG")
    return int(v) if v not in (None, "") else 64


def _hedge_default() -> float:
    """``DSDDMM_FLEET_HEDGE``: off ('' / 0 / off), on with the default
    floor ('1' / 'on'), or a float hedge-delay floor in seconds."""
    v = (os.environ.get("DSDDMM_FLEET_HEDGE") or "").strip().lower()
    if v in ("", "0", "off", "false", "no"):
        return 0.0
    if v in ("1", "on", "true", "yes"):
        return DEFAULT_HEDGE_FLOOR_S
    return max(float(v), 0.0)


class ReplicaState:
    """The router's cached view of one replica's exported signals."""

    def __init__(self, name: str, port: int, role: str = "serve"):
        self.name = name
        self.port = port
        self.role = role
        self.ready = False
        self.draining = False
        self.burn: Optional[float] = None
        self.depth_frac = 0.0
        self.inner_buckets: tuple = ()
        self.t_poll = 0.0
        self.errors = 0
        #: Circuit breaker: closed → open (strike threshold) →
        #: half_open (cooldown) → closed (submit success).
        self.breaker = "closed"
        self.strikes = 0
        self.t_opened = 0.0
        self.breaker_opens = 0

    @property
    def inner_max(self) -> int:
        return max(self.inner_buckets) if self.inner_buckets else 0

    def describe(self) -> dict:
        return {
            "name": self.name, "port": self.port, "role": self.role,
            "ready": self.ready, "draining": self.draining,
            "burn": self.burn, "depth_frac": self.depth_frac,
            "inner_buckets": list(self.inner_buckets),
            "errors": self.errors,
            "breaker": self.breaker, "strikes": self.strikes,
            "breaker_opens": self.breaker_opens,
        }


def _default_inner_size(payload: dict) -> int:
    """Workload-agnostic inner-size probe: the longest list-valued
    field. Matches ``inner_size`` for the shipped workloads (ALS items,
    GAT neighbor lists, attention windows) without importing them."""
    n = 1
    for v in payload.values():
        if isinstance(v, (list, tuple)):
            n = max(n, len(v))
        else:
            size = getattr(v, "shape", None)
            if size:
                n = max(n, int(size[0]))
    return n


class FleetRouter:
    """Balance, shed, drain, and structure-route over a replica pool.

    ``manager`` (a :class:`~distributed_sddmm_tpu.fleet.manager.
    FleetManager`) is the live endpoint source — respawns are picked up
    on the next poll tick, and its :meth:`~distributed_sddmm_tpu.fleet.
    manager.FleetManager.quarantine` becomes the default
    ``quarantine_fn``. Tests can instead pass static ``endpoints``
    ``[(name, port, role), ...]``.
    """

    def __init__(
        self,
        manager=None,
        endpoints: Optional[list] = None,
        *,
        poll_interval_s: float = 0.25,
        drain_burn: Optional[float] = None,
        resume_frac: float = 0.8,
        request_timeout_s: float = 30.0,
        shed_retry_after_s: float = 1.0,
        inner_size_fn: Optional[Callable[[dict], int]] = None,
        port: int = 0,
        breaker_errs: Optional[int] = None,
        breaker_cooldown_s: Optional[float] = None,
        hedge_delay_s: Optional[float] = None,
        audit_frac: Optional[float] = None,
        quarantine_fn: Optional[Callable] = None,
    ):
        if manager is None and endpoints is None:
            raise ValueError("need a manager or static endpoints")
        self.manager = manager
        self.static_endpoints = endpoints
        self.poll_interval_s = float(poll_interval_s)
        self.drain_burn = (
            _drain_burn_default() if drain_burn is None
            else float(drain_burn)
        )
        self.resume_burn = self.drain_burn * float(resume_frac)
        self.request_timeout_s = float(request_timeout_s)
        self.shed_retry_after_s = float(shed_retry_after_s)
        self.inner_size_fn = inner_size_fn or _default_inner_size
        self.breaker_errs = (
            _breaker_errs_default() if breaker_errs is None
            else int(breaker_errs)
        )
        self.breaker_cooldown_s = (
            _breaker_cooldown_default() if breaker_cooldown_s is None
            else float(breaker_cooldown_s)
        )
        #: 0 disables hedging; > 0 is the hedge-delay floor (seconds).
        self.hedge_delay_s = (
            _hedge_default() if hedge_delay_s is None
            else max(float(hedge_delay_s), 0.0)
        )
        self.audit_frac = (
            _audit_frac_default() if audit_frac is None
            else min(max(float(audit_frac), 0.0), 1.0)
        )
        #: ``quarantine_fn(name, reason=..., evidence=...)`` — the
        #: byzantine verdict sink; defaults to the manager's.
        if quarantine_fn is None and manager is not None:
            quarantine_fn = manager.quarantine
        self.quarantine_fn = quarantine_fn
        #: Chaos wire-fault hook (``resilience/chaos.ChaosEngine``):
        #: called with the replica name before each wire attempt;
        #: returns None or {"drop": True} / {"delay_s": x}.
        self.fault_hook: Optional[Callable[[str], Optional[dict]]] = None
        self._states: dict[str, ReplicaState] = {}
        self._lock = threading.Lock()
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._server = None
        self._port = int(port)
        self._lat: collections.deque = collections.deque(maxlen=256)
        self._audit_seq = 0
        #: Fleet-level request ids: unique across router restarts (the
        #: prefix embeds pid + random salt) and monotonic within one.
        #: Minted even when tracing is off — replica logs stay
        #: correlatable by ``X-DSDDMM-Trace`` regardless.
        self._fleet_prefix = (
            f"fr{os.getpid():x}-{os.urandom(2).hex()}"
        )
        self._fleet_ids = itertools.count(1)
        #: Recent fleet request chains (attempt fan-out + routing
        #: annotations), served live at ``/debug/requests``.
        self._debug_chains: collections.deque = collections.deque(
            maxlen=_trace_debug_default()
        )
        #: Breaker transitions in arrival order (the chaos judge reads
        #: open events against the injected-fault timeline).
        self.breaker_events: list = []
        self.stats = {
            "routed": 0, "failovers": 0, "serial_routed": 0,
            "edge_sheds": 0, "replica_sheds_seen": 0, "drains": 0,
            "hedges": 0, "hedge_wins": 0, "audits": 0,
            "audit_mismatches": 0, "breaker_opens": 0,
            "quarantines": 0, "decode_failovers": 0,
        }

    # -- polling -------------------------------------------------------- #

    def _endpoints(self) -> list:
        if self.manager is not None:
            return [(r.name, r.port, r.role) for r in self.manager.replicas()]
        return list(self.static_endpoints)

    def poll_once(self) -> None:
        """One health sweep: refresh every replica's readiness, burn,
        depth, and ladder; apply the drain/resume hysteresis. A failed
        poll is a breaker strike — a wedged replica (SIGSTOP freezes
        its admin surface too) opens its breaker within
        ``breaker_errs`` ticks — but a SUCCESSFUL poll never closes
        one: readiness can lie while the submit path is dead (the
        partition case), so only a served request proves recovery."""
        from distributed_sddmm_tpu.obs.httpexp import fetch_json

        seen = set()
        for name, port, role in self._endpoints():
            seen.add(name)
            with self._lock:
                st = self._states.get(name)
                if st is None or st.port != port:
                    # New replica, or a respawn on a fresh port — reset
                    # the cached view; it must re-prove readiness.
                    st = self._states[name] = ReplicaState(name, port, role)
            try:
                ready_body = fetch_json("127.0.0.1", port, "/readyz",
                                        timeout_s=1.0)
                snap = fetch_json("127.0.0.1", port, "/snapshot",
                                  timeout_s=1.0)
            except (OSError, ValueError):
                with self._lock:
                    st.ready = False
                self._strike(st, "poll")
                continue
            with self._lock:
                st.ready = bool(ready_body.get("ready"))
                st.depth_frac = float(snap.get("depth_frac") or 0.0)
                st.burn = snap.get("burn_rate")
                buckets = snap.get("buckets") or {}
                st.inner_buckets = tuple(buckets.get("inner") or ())
                st.t_poll = time.monotonic()
                if st.burn is not None:
                    if not st.draining and st.burn > self.drain_burn:
                        st.draining = True
                        self.stats["drains"] += 1
                        obs_log.warn("fleet", "draining burning replica",
                                     name=name, burn=st.burn)
                    elif st.draining and st.burn <= self.resume_burn:
                        st.draining = False
                        obs_log.info("fleet", "replica resumed admissions",
                                     name=name, burn=st.burn)
        with self._lock:
            for gone in set(self._states) - seen:
                del self._states[gone]

    def _poll_loop(self) -> None:
        while not self._stop.is_set():
            try:
                self.poll_once()
            except Exception as e:  # noqa: BLE001 — the loop must survive
                obs_log.warn("fleet", "router poll failed",
                             error=f"{type(e).__name__}: {e}")
            self._stop.wait(self.poll_interval_s)

    # -- circuit breaker ------------------------------------------------ #

    def _strike(self, st: ReplicaState, where: str) -> None:
        """One consecutive-failure strike; opens the breaker at the
        threshold (instantly from half-open — a failed probe re-opens).
        While open, fresh strikes push the cooldown out: half-open
        probes wait for actual quiet."""
        opened = False
        now = time.monotonic()
        with self._lock:
            st.strikes += 1
            st.errors += 1
            if st.breaker == "half_open" or (
                st.breaker == "closed"
                and st.strikes >= self.breaker_errs
            ):
                st.breaker = "open"
                st.t_opened = now
                st.breaker_opens += 1
                self.stats["breaker_opens"] += 1
                self.breaker_events.append(
                    {"t": now, "name": st.name, "state": "open",
                     "where": where})
                opened = True
            elif st.breaker == "open":
                st.t_opened = now
        if opened:
            obs_metrics.GLOBAL.add("fleet_breaker_opens")
            obs_trace.event("fleet_breaker_open", replica=st.name,
                            where=where)
            obs_log.warn("fleet", "circuit breaker opened",
                         name=st.name, where=where, strikes=st.strikes)

    def _settle(self, st: ReplicaState) -> None:
        """A successful submit: the only evidence that closes a
        breaker (health polls are not proof — gray failures pass
        them)."""
        closed = False
        with self._lock:
            st.strikes = 0
            if st.breaker != "closed":
                st.breaker = "closed"
                self.breaker_events.append(
                    {"t": time.monotonic(), "name": st.name,
                     "state": "closed", "where": "submit"})
                closed = True
        if closed:
            obs_log.info("fleet", "circuit breaker closed", name=st.name)

    def _admits(self, st: ReplicaState, now: float) -> bool:
        """Breaker admission (call under ``self._lock``): closed and
        half-open admit; open flips to half-open after the cooldown."""
        if st.breaker == "open":
            if now - st.t_opened >= self.breaker_cooldown_s:
                st.breaker = "half_open"
                self.breaker_events.append(
                    {"t": now, "name": st.name, "state": "half_open",
                     "where": "cooldown"})
                return True
            return False
        return True

    # -- routing -------------------------------------------------------- #

    def states(self) -> list[ReplicaState]:
        with self._lock:
            return list(self._states.values())

    def _candidates(self, serial: bool) -> list[ReplicaState]:
        now = time.monotonic()
        with self._lock:
            pool = [s for s in self._states.values()
                    if s.ready and not s.draining and self._admits(s, now)]
        if serial:
            # Host-serial tier: prefer dedicated fallback replicas, but
            # any ready replica can run the serial rung.
            fallback = [s for s in pool if s.role == "fallback"]
            pool = fallback or pool
        else:
            pool = [s for s in pool if s.role == "serve"]
        # Half-open breakers last: probe traffic only reaches them when
        # the healthy pool is exhausted or as failover/hedge targets.
        return sorted(pool, key=lambda s: (s.breaker == "half_open",
                                           s.depth_frac, s.burn or 0.0,
                                           s.name))

    @staticmethod
    def _canon(reply) -> str:
        """Bit-for-bit comparison form: replies already crossed the
        wire as JSON, so the canonical dump IS the byte identity."""
        from distributed_sddmm_tpu.obs.httpexp import _json_default

        return json.dumps(reply, sort_keys=True, default=_json_default)

    @staticmethod
    def _note_attempt(rctx: Optional[dict], st: ReplicaState, kind: str,
                      ordinal: int, outcome: str,
                      lat_s: Optional[float] = None,
                      dropped: bool = False) -> None:
        """Append one attempt row to the request's debug chain (list
        append — safe from the hedge/audit side threads)."""
        chain = (rctx or {}).get("chain")
        if chain is None:
            return
        rec = {"replica": st.name, "kind": kind, "ordinal": ordinal,
               "outcome": outcome, "breaker": st.breaker,
               "depth_frac": st.depth_frac}
        if lat_s is not None:
            rec["lat_s"] = round(lat_s, 6)
        if dropped:
            rec["chaos_drop"] = True
        chain["attempts"].append(rec)

    def _submit_once(self, st: ReplicaState, body: dict,
                     timeout_s: float, rctx: Optional[dict] = None,
                     kind: str = "primary", ordinal: int = 0):
        """One wire attempt against one replica. Outcomes::

            ("ok", reply)          200 with a well-formed body
            ("shed", hint_s)       429 — replica admission shed
            ("error", reason)      transport failure, undecodable or
                                   malformed reply body, chaos drop —
                                   all strike the breaker and fail over
            ("http", code, detail) any other HTTP status

        The chaos ``fault_hook`` is consulted first: an active
        partition window turns the attempt into a local error (the
        wire is down for us, whatever the replica thinks), a slow
        window delays it.

        Tracing: every wire attempt is a ``fleet:attempt`` span
        annotated with the routing decision (replica, kind, ordinal,
        depth_frac, burn, breaker, bucket fit) and its fleet parent
        (``fleet_req``/``fleet_shard``/``fleet_span``), and the fleet
        context rides the ``X-DSDDMM-Trace`` header so the replica's
        own chain records this attempt's span as parent. The span is
        opened AFTER the chaos hook: an injected delay is not wire
        latency, and ``lat_s`` must agree with the span duration.
        """
        from distributed_sddmm_tpu.obs.httpexp import post_json

        hook = self.fault_hook
        if hook is not None:
            act = hook(st.name) or {}
            if act.get("delay_s"):
                time.sleep(float(act["delay_s"]))
            if act.get("drop"):
                self._strike(st, "chaos-drop")
                self._note_attempt(rctx, st, kind, ordinal, "error",
                                   dropped=True)
                return ("error", f"chaos partition: {st.name} dropped")
        attrs = {"replica": st.name, "kind": kind, "ordinal": ordinal,
                 "depth_frac": st.depth_frac, "burn": st.burn or 0.0,
                 "breaker": st.breaker}
        ctx = {"kind": kind, "ord": ordinal}
        if rctx is not None:
            ctx["req"] = rctx.get("req")
            ctx["shard"] = rctx.get("shard")
            attrs["fleet_req"] = rctx.get("req")
            if rctx.get("shard"):
                attrs["fleet_shard"] = rctx.get("shard")
            if rctx.get("span") is not None:
                # Cross-thread parent: hedge/audit attempts run on side
                # threads whose span stack is empty — the merge pass
                # re-parents on this attr, not the in-thread stack.
                attrs["fleet_span"] = rctx.get("span")
            inner = rctx.get("inner")
            if inner is not None and st.inner_buckets:
                attrs["bucket_fit"] = bool(
                    bucket_for(inner, st.inner_buckets) >= inner
                )
        with obs_trace.span("fleet:attempt", **attrs) as sp:
            ctx["span"] = getattr(sp, "id", None)
            hdr = {
                obs_trace.TRACE_HEADER: obs_trace.encode_fleet_ctx(ctx),
            }
            t_send = time.monotonic()
            try:
                code, decoded, headers = post_json(
                    "127.0.0.1", st.port, "/submit", body,
                    timeout_s=timeout_s, headers=hdr,
                )
            except OSError as e:
                # Connection-level failure: the replica is gone (chaos
                # kill) or wedged. Mark it — the caller fails over.
                with self._lock:
                    st.ready = False
                self._strike(st, "submit")
                sp.set(outcome="error", error_kind="transport")
                self._note_attempt(rctx, st, kind, ordinal, "error")
                return ("error", f"{type(e).__name__}: {e}")
            except ValueError as e:
                # 200 whose body does not decode as JSON: the replica is
                # answering garbage — replica failure, not client error.
                with self._lock:
                    self.stats["decode_failovers"] += 1
                self._strike(st, "decode")
                sp.set(outcome="error", error_kind="decode")
                self._note_attempt(rctx, st, kind, ordinal, "error")
                return ("error", f"undecodable reply body: {e}")
            if code == 200:
                try:
                    reply = decoded["reply"]
                except (TypeError, KeyError):
                    # Well-formed JSON, wrong shape — same verdict as an
                    # undecodable body: fail over, never a client 500.
                    with self._lock:
                        self.stats["decode_failovers"] += 1
                    self._strike(st, "decode")
                    sp.set(outcome="error", error_kind="decode")
                    self._note_attempt(rctx, st, kind, ordinal, "error")
                    return ("error", "malformed reply body: no 'reply' key")
                lat = time.monotonic() - t_send
                with self._lock:
                    self._lat.append(lat)
                self._settle(st)
                sp.set(outcome="ok", lat_s=round(lat, 9))
                self._note_attempt(rctx, st, kind, ordinal, "ok", lat)
                return ("ok", reply)
            if code == 429:
                hint = 0.0
                raw = headers.get("Retry-After") or (
                    decoded.get("retry_after_s", 0.0)
                    if isinstance(decoded, dict) else 0.0
                )
                try:
                    hint = float(raw)
                except (TypeError, ValueError):
                    pass
                sp.set(outcome="shed", retry_after_s=hint)
                self._note_attempt(rctx, st, kind, ordinal, "shed")
                return ("shed", hint)
            detail = (decoded.get("error", decoded)
                      if isinstance(decoded, dict) else decoded)
            sp.set(outcome="http", code=code)
            self._note_attempt(rctx, st, kind, ordinal, "http")
            return ("http", code, detail)

    # -- hedging -------------------------------------------------------- #

    def _hedge_delay(self) -> float:
        """The p95-derived hedge delay: 4× the observed p95 submit
        latency, floored at ``hedge_delay_s`` and capped — with no
        history yet, the floor alone. 0 when hedging is disabled."""
        if self.hedge_delay_s <= 0.0:
            return 0.0
        with self._lock:
            lats = sorted(self._lat)
        if len(lats) >= 8:
            p95 = lats[min(int(0.95 * (len(lats) - 1)), len(lats) - 1)]
            return max(self.hedge_delay_s, min(4.0 * p95, HEDGE_CEIL_S))
        return self.hedge_delay_s

    def _attempt(self, primary: ReplicaState, hedge_pool: list,
                 body: dict, timeout_s: float,
                 rctx: Optional[dict] = None, ordinal: int = 0):
        """Primary submit with an optional hedge: if the primary has
        not answered within the hedge delay, fire the same request at
        the next candidate and take the first success. Returns
        ``(outcome, server_name)``. When both land with replies they
        are compared (possibly after this returns) — a mismatch is a
        byzantine signal."""
        delay = self._hedge_delay() if hedge_pool else 0.0
        if delay <= 0.0:
            return self._submit_once(
                primary, body, timeout_s, rctx, kind="primary",
                ordinal=ordinal,
            ), primary.name

        cond = threading.Condition()
        arrivals: list = []  # (key, outcome) in completion order

        def run(key: str, st: ReplicaState, kind: str) -> None:
            out = self._submit_once(st, body, timeout_s, rctx,
                                    kind=kind, ordinal=ordinal)
            with cond:
                arrivals.append((key, out))
                cond.notify_all()

        threading.Thread(target=run, args=("p", primary, "primary"),
                         daemon=True, name="fleet-submit").start()
        with cond:
            cond.wait_for(lambda: arrivals, timeout=delay)
            early = arrivals[0] if arrivals else None
        if early is not None:
            # Primary answered (or failed fast) inside the delay — a
            # quick error is the failover loop's job, not a hedge's.
            return early[1], primary.name

        backup = hedge_pool[0]
        with self._lock:
            self.stats["hedges"] += 1
        obs_metrics.GLOBAL.add("fleet_hedges")
        obs_trace.event("fleet_hedge", primary=primary.name,
                        backup=backup.name,
                        fleet_req=(rctx or {}).get("req"))
        threading.Thread(target=run, args=("h", backup, "hedge"),
                         daemon=True, name="fleet-hedge").start()
        with cond:
            cond.wait_for(
                lambda: any(o[0] == "ok" for _, o in arrivals)
                or len(arrivals) == 2,
                timeout=timeout_s,
            )
            snapshot = list(arrivals)
        first_ok = next(((k, o) for k, o in snapshot if o[0] == "ok"),
                        None)
        self._compare_when_both_land(cond, arrivals, primary, backup,
                                     body, timeout_s, rctx)
        if first_ok is None:
            # Neither landed usable: report the primary's outcome when
            # it exists (keeps the failover loop's accounting honest).
            by_key = dict(snapshot)
            out = by_key.get("p") or by_key.get("h") or \
                ("error", "hedged attempt timed out")
            return out, primary.name
        key, out = first_ok
        if key == "h":
            with self._lock:
                self.stats["hedge_wins"] += 1
            obs_metrics.GLOBAL.add("fleet_hedge_wins")
        return out, (backup.name if key == "h" else primary.name)

    def _compare_when_both_land(self, cond, arrivals, primary, backup,
                                body, timeout_s,
                                rctx: Optional[dict] = None) -> None:
        """Both-land agreement check: when the loser eventually
        answers too, the two replies must be bit-identical. Runs on a
        side thread so the winning reply is never delayed."""

        def work() -> None:
            with cond:
                cond.wait_for(lambda: len(arrivals) == 2,
                              timeout=timeout_s)
                snapshot = dict(arrivals)
            p, h = snapshot.get("p"), snapshot.get("h")
            if not (p and h and p[0] == "ok" and h[0] == "ok"):
                return
            if self._canon(p[1]) == self._canon(h[1]):
                return
            self._byzantine(primary.name, p[1], backup.name, h[1],
                            body, timeout_s, where="hedge", rctx=rctx)

        threading.Thread(target=work, daemon=True,
                         name="fleet-hedge-compare").start()

    # -- audit / byzantine arbitration ---------------------------------- #

    def _audit_roll(self) -> bool:
        """Deterministic stride sampling: request ``n`` audits iff the
        integer part of ``n * frac`` advanced — exactly ``frac`` of
        requests, no RNG, reproducible run to run."""
        if self.audit_frac <= 0.0:
            return False
        with self._lock:
            self._audit_seq += 1
            n = self._audit_seq
        return int(n * self.audit_frac) > int((n - 1) * self.audit_frac)

    def _audit(self, server_name: str, reply, body: dict,
               timeout_s: float, candidates: list,
               rctx: Optional[dict] = None):
        """Synchronous sampled audit: re-execute on a DIFFERENT
        replica and compare bit-for-bit before the reply leaves the
        router. On mismatch, arbitration picks the majority reply —
        that is what the client gets — and the odd replica out is
        quarantined. Returns the reply to deliver."""
        pool = [s for s in candidates if s.name != server_name]
        if not pool:
            return reply  # nobody to compare against — audit skipped
        auditor = pool[0]
        with self._lock:
            self.stats["audits"] += 1
        out = self._submit_once(auditor, body, timeout_s, rctx,
                                kind="audit")
        chain = (rctx or {}).get("chain")
        if out[0] != "ok":
            return reply  # audit inconclusive; primary reply stands
        agree = self._canon(out[1]) == self._canon(reply)
        if chain is not None:
            chain["audit"] = {"auditor": auditor.name, "agree": agree}
        obs_trace.event("fleet_audit", auditor=auditor.name,
                        audited=server_name, agree=agree,
                        fleet_req=(rctx or {}).get("req"))
        if agree:
            return reply
        return self._byzantine(server_name, reply, auditor.name, out[1],
                               body, timeout_s, where="audit",
                               candidates=candidates, rctx=rctx)

    def _byzantine(self, name_a: str, reply_a, name_b: str, reply_b,
                   body: dict, timeout_s: float, where: str,
                   candidates: Optional[list] = None,
                   rctx: Optional[dict] = None):
        """Two replicas disagree bit-for-bit on the same request — one
        of them is lying. A third replica arbitrates: whichever side
        the tiebreak contradicts is quarantined, and the majority
        reply is returned. Without a tiebreak (2-replica fleet) the
        mismatch is counted and logged but nobody is quarantined — no
        quorum, no verdict."""
        with self._lock:
            self.stats["audit_mismatches"] += 1
        obs_metrics.GLOBAL.add("fleet_audit_mismatches")
        obs_trace.event("fleet_audit_mismatch", a=name_a, b=name_b,
                        where=where, fleet_req=(rctx or {}).get("req"))
        obs_log.warn("fleet", "byzantine reply mismatch",
                     a=name_a, b=name_b, where=where)
        chain = (rctx or {}).get("chain")
        if chain is not None:
            chain["mismatch"] = {"a": name_a, "b": name_b, "where": where}
        if candidates is None:
            candidates = self._candidates(serial=False)
        canon_a, canon_b = self._canon(reply_a), self._canon(reply_b)
        for tie in candidates:
            if tie.name in (name_a, name_b):
                continue
            out = self._submit_once(tie, body, timeout_s, rctx,
                                    kind="arbitrate")
            if out[0] != "ok":
                continue
            canon_t = self._canon(out[1])
            if canon_t == canon_a:
                liar, verdict = name_b, reply_a
            elif canon_t == canon_b:
                liar, verdict = name_a, reply_b
            else:
                obs_log.warn("fleet", "three-way reply disagreement; "
                             "no quorum", a=name_a, b=name_b,
                             tiebreak=tie.name)
                return reply_a
            if chain is not None:
                chain["verdict"] = {"liar": liar, "tiebreak": tie.name}
            self._quarantine(liar, where, evidence={
                "request_tenant": body.get("tenant"),
                "disagreed_with": [n for n in (name_a, name_b, tie.name)
                                   if n != liar],
                "where": where,
                "fleet_req": (rctx or {}).get("req"),
            })
            return verdict
        obs_log.warn("fleet", "byzantine mismatch with no tiebreak "
                     "replica — cannot arbitrate", a=name_a, b=name_b)
        return reply_a

    def _quarantine(self, name: str, where: str,
                    evidence: Optional[dict] = None) -> None:
        with self._lock:
            self.stats["quarantines"] += 1
        if self.quarantine_fn is None:
            obs_log.warn("fleet", "no quarantine sink; byzantine "
                         "replica stays in rotation", name=name)
            return
        try:
            self.quarantine_fn(
                name, reason=f"byzantine reply mismatch ({where})",
                evidence=evidence,
            )
        except Exception as e:  # noqa: BLE001 — verdict must not 500
            obs_log.warn("fleet", "quarantine failed", name=name,
                         error=f"{type(e).__name__}: {e}")

    # -- the routing decision ------------------------------------------- #

    def route(self, payload: dict, tenant: str = DEFAULT_TENANT,
              serial: bool = False, timeout_s: Optional[float] = None,
              trace_ctx: Optional[dict] = None) -> dict:
        """The ``submit_fn`` contract: returns the reply dict, raises
        :class:`ShedError` (→ 429 + Retry-After at the edge) when no
        replica admits the request.

        Every request is a ``fleet:request`` span plus a debug-chain
        entry (``/debug/requests``); each wire attempt below it is a
        ``fleet:attempt`` span carrying the routing decision.
        ``trace_ctx`` is an upstream fleet context decoded off the
        router's own front door — its request id is reused so chained
        routers stay one causal tree; otherwise the router mints one."""
        timeout_s = self.request_timeout_s if timeout_s is None else timeout_s
        fleet_req = (trace_ctx or {}).get("req") or (
            f"{self._fleet_prefix}-{next(self._fleet_ids)}"
        )
        chain = {"fleet_req": fleet_req, "tenant": tenant,
                 "t_epoch": obs_clock.epoch(), "attempts": [],
                 "outcome": "error"}
        t_route = time.monotonic()
        with obs_trace.span("fleet:request", fleet_req=fleet_req,
                            tenant=tenant) as sp:
            rctx = {"req": fleet_req, "shard": obs_trace.run_id(),
                    "span": getattr(sp, "id", None), "chain": chain,
                    "sp": sp}
            try:
                reply, server, serial_used = self._route_attempts(
                    payload, tenant, serial, timeout_s, rctx,
                )
            except ShedError as e:
                chain["outcome"] = "shed"
                chain["retry_after_s"] = round(e.retry_after_s, 6)
                sp.set(outcome="shed",
                       retry_after_s=round(e.retry_after_s, 6))
                raise
            except Exception as e:
                chain["error"] = f"{type(e).__name__}: {e}"
                sp.set(outcome="error")
                raise
            else:
                chain["outcome"] = "ok"
                chain["winner"] = server
                chain["serial"] = serial_used
                sp.set(outcome="ok", winner=server, serial=serial_used)
                return reply
            finally:
                chain["dur_s"] = round(time.monotonic() - t_route, 6)
                self._debug_chains.append(chain)

    def _route_attempts(self, payload: dict, tenant: str, serial: bool,
                        timeout_s: float, rctx: dict):
        """The routing decision proper: candidate selection, the
        failover loop, hedging and the sampled audit. Returns
        ``(reply, winner_name, serial_used)``."""
        inner = self.inner_size_fn(payload)
        rctx["inner"] = inner
        candidates = self._candidates(serial)
        if not serial and candidates:
            # Pathological outlier: larger than every candidate's
            # largest warm rung → host-serial tier, not a poisoned batch.
            fleet_max = max(s.inner_max for s in candidates)
            if fleet_max and inner > fleet_max:
                serial = True
                candidates = self._candidates(serial=True)
        if not candidates:
            self.stats["edge_sheds"] += 1
            raise ShedError("no ready replica",
                            retry_after_s=self.shed_retry_after_s)
        if not serial and len(candidates) > 1:
            # Bucket fit: among healthy candidates prefer those whose
            # warm ladder covers this inner size without clamping to
            # the top rung (bucket_for maps oversize onto the last
            # rung — correct, but it pads maximally).
            fitting = [s for s in candidates if s.inner_buckets
                       and bucket_for(inner, s.inner_buckets) >= inner]
            candidates = fitting or candidates

        rctx["serial"] = serial
        rctx["sp"].set(inner=inner)
        body = {"payload": payload, "tenant": tenant,
                "serial": serial, "timeout_s": timeout_s}
        shed_hint = 0.0
        saw_shed = False
        for i, st in enumerate(candidates):
            # The serial tier is the oracle rung — not bit-identical to
            # the batched path by design (float64), so neither hedging
            # nor audit applies to it.
            hedge_pool = [] if serial else candidates[i + 1:]
            out, server = self._attempt(st, hedge_pool, body, timeout_s,
                                        rctx, ordinal=i)
            if out[0] == "ok":
                reply = out[1]
                if not serial and self._audit_roll():
                    reply = self._audit(server, reply, body, timeout_s,
                                        candidates, rctx)
                with self._lock:
                    self.stats["routed"] += 1
                    if serial:
                        self.stats["serial_routed"] += 1
                return reply, server, serial
            if out[0] == "shed":
                saw_shed = True
                self.stats["replica_sheds_seen"] += 1
                shed_hint = max(shed_hint, out[1])
                continue  # another replica may have headroom
            if out[0] == "http":
                raise RuntimeError(
                    f"replica {server} answered {out[1]}: {out[2]}"
                )
            # ("error", ...): transport, decode, or chaos drop — the
            # request is re-admitted on the next candidate, not dropped.
            self.stats["failovers"] += 1
            obs_log.warn("fleet", "replica attempt failed; failing over",
                         name=server, error=out[1])
        self.stats["edge_sheds"] += 1
        raise ShedError(
            "all replicas shed" if saw_shed else "no replica reachable",
            retry_after_s=shed_hint or self.shed_retry_after_s,
        )

    # -- the router's own HTTP surface ---------------------------------- #

    def topology(self) -> dict:
        """The ``/snapshot`` body: per-replica state + router counters
        (and the manager's spawn/loss/quarantine ledger when
        attached)."""
        out = {
            "router": True,
            "replicas": [s.describe() for s in self.states()],
            "stats": dict(self.stats),
            "drain_burn": self.drain_burn,
            "breaker": {"errs": self.breaker_errs,
                        "cooldown_s": self.breaker_cooldown_s},
            "hedge_delay_s": self._hedge_delay(),
            "audit_frac": self.audit_frac,
            "breaker_events": list(self.breaker_events[-64:]),
        }
        if self.manager is not None:
            out["manager"] = self.manager.describe()
        return out

    def debug_chains(self) -> dict:
        """Live fleet request chains — the ``/debug/requests`` body on
        the router's own admin surface: one row per recent request with
        its attempt fan-out (primary/hedge/audit/arbitrate), outcomes,
        per-attempt routing annotations, and any audit/byzantine
        verdicts."""
        rows = list(self._debug_chains)
        return {
            "router": True,
            "capacity": self._debug_chains.maxlen,
            "complete": sum(1 for r in rows if r.get("outcome") == "ok"),
            "requests": rows,
            "stats": dict(self.stats),
        }

    @property
    def port(self) -> int:
        return self._server.port if self._server is not None else self._port

    def start(self) -> "FleetRouter":
        from distributed_sddmm_tpu.obs.httpexp import AdminServer

        if self._thread is not None:
            raise RuntimeError("router already started")
        self.poll_once()  # candidates exist before the first request
        self._stop.clear()
        self._thread = threading.Thread(
            target=self._poll_loop, daemon=True, name="fleet-router-poll",
        )
        self._thread.start()
        self._server = AdminServer(
            snapshot_fn=self.topology, submit_fn=self.route,
            debug_fn=self.debug_chains, port=self._port,
        ).start()
        obs_log.info("fleet", "router serving",
                     url=f"http://127.0.0.1:{self._server.port}")
        return self

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(5.0)
            self._thread = None
        if self._server is not None:
            self._server.stop()
            self._server = None

    def __enter__(self) -> "FleetRouter":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.stop()
