"""Telemetry-driven autoscaling over the replica pool.

The scaler reads the SAME snapshot stream everything else does
(:func:`~distributed_sddmm_tpu.obs.telemetry.engine_snapshot` — live
``/snapshot`` endpoints via :meth:`FleetManager.snapshots`, or sampler
JSONL lines replayed in tests) and makes exactly two moves:

* **Scale up** when pressure (queue depth fraction ≥ ``high_depth_frac``
  or SLO burn ≥ ``high_burn``) is *sustained* for ``sustain_ticks``
  consecutive observations — a single Poisson burst must not spawn a
  replica whose warmup outlives the burst.
* **Scale down** by drain-then-reap (never a kill: queued work finishes
  and the record is collected) after ``idle_ticks`` consecutive idle
  observations.

Both moves respect ``min_replicas``/``max_replicas`` bounds and a
``cooldown_s`` between actions, so decisions cannot oscillate faster
than replicas warm. The decision core (:meth:`AutoScaler.step`) is a
pure-ish synchronous function of the snapshot dict — tests drive it
with fabricated snapshots and a fake manager; :meth:`AutoScaler.start`
wraps it in the usual daemon-thread loop for live fleets.

Knobs (all ``DSDDMM_FLEET_*``, registered in ``utils/envreg.py``):
MIN/MAX bounds, HIGH_DEPTH/HIGH_BURN thresholds, IDLE_S idle window,
COOLDOWN seconds between actions.
"""

from __future__ import annotations

import dataclasses
import os
import threading
import time
from typing import Optional

from distributed_sddmm_tpu.obs import log as obs_log


def _cast(v, default, cast):
    return cast(v) if v not in (None, "") else default


@dataclasses.dataclass
class ScalerConfig:
    min_replicas: int = 1
    max_replicas: int = 4
    #: Pressure thresholds: either sustained past ``sustain_ticks``
    #: observations triggers a spawn.
    high_depth_frac: float = 0.7
    high_burn: float = 1.0
    #: Idle: every replica's depth fraction at or under this.
    idle_depth_frac: float = 0.05
    sustain_ticks: int = 3
    #: Idle observations before a drain (the interval_s multiplier —
    #: from_env derives it from DSDDMM_FLEET_IDLE_S).
    idle_ticks: int = 20
    cooldown_s: float = 5.0
    interval_s: float = 0.5

    @classmethod
    def from_env(cls) -> "ScalerConfig":
        interval_s = 0.5
        idle_s = _cast(os.environ.get("DSDDMM_FLEET_IDLE_S"), 10.0, float)
        return cls(
            min_replicas=_cast(os.environ.get("DSDDMM_FLEET_MIN"), 1, int),
            max_replicas=_cast(os.environ.get("DSDDMM_FLEET_MAX"), 4, int),
            high_depth_frac=_cast(
                os.environ.get("DSDDMM_FLEET_HIGH_DEPTH"), 0.7, float),
            high_burn=_cast(
                os.environ.get("DSDDMM_FLEET_HIGH_BURN"), 1.0, float),
            cooldown_s=_cast(
                os.environ.get("DSDDMM_FLEET_COOLDOWN"), 5.0, float),
            interval_s=interval_s,
            idle_ticks=max(1, int(idle_s / interval_s)),
        )


class AutoScaler:
    """Sustained-pressure spawn / sustained-idle drain over a
    :class:`~distributed_sddmm_tpu.fleet.manager.FleetManager`."""

    def __init__(self, manager, config: Optional[ScalerConfig] = None):
        self.manager = manager
        self.config = config or ScalerConfig.from_env()
        self._high_streak = 0
        self._idle_streak = 0
        self._last_action_t = float("-inf")
        #: Decision log for the fleet record: (t_monotonic, action, why).
        self.actions: list[dict] = []

    # -- the decision core ---------------------------------------------- #

    @staticmethod
    def _pressure(snap: dict) -> tuple[float, float]:
        depth = float(snap.get("depth_frac") or 0.0)
        burn = snap.get("burn_rate")
        return depth, float(burn) if burn is not None else 0.0

    def step(self, snapshots: dict, now: Optional[float] = None
             ) -> Optional[str]:
        """One observation → at most one action. ``snapshots`` is
        ``{replica_name: snapshot_dict_or_None}``; an unreachable
        replica (None) is treated as pressure — it is not absorbing
        load, whatever its queue claims. Returns ``"scale_up"``,
        ``"scale_down"``, or None."""
        cfg = self.config
        now = time.monotonic() if now is None else now
        live = self.manager.replicas(role="serve")
        n = len(live)
        snaps = [snapshots.get(r.name) for r in live]
        if not snaps:
            return None
        high = any(
            s is None
            or self._pressure(s)[0] >= cfg.high_depth_frac
            or self._pressure(s)[1] >= cfg.high_burn
            for s in snaps
        )
        idle = all(
            s is not None and self._pressure(s)[0] <= cfg.idle_depth_frac
            and self._pressure(s)[1] < cfg.high_burn
            for s in snaps
        )
        if high:
            self._high_streak += 1
            self._idle_streak = 0
        elif idle:
            self._idle_streak += 1
            self._high_streak = 0
        else:
            self._high_streak = 0
            self._idle_streak = 0

        if now - self._last_action_t < cfg.cooldown_s:
            return None
        if self._high_streak >= cfg.sustain_ticks and n < cfg.max_replicas:
            rep = self.manager.spawn(role="serve")
            self._note(now, "scale_up", replicas=n + 1, spawned=rep.name,
                       streak=self._high_streak)
            self._high_streak = 0
            self._last_action_t = now
            return "scale_up"
        if self._idle_streak >= cfg.idle_ticks and n > cfg.min_replicas:
            # Drain the newest non-tuner replica: the canary's shadow
            # state is the most expensive thing in the fleet to lose.
            victims = sorted(
                (r for r in live if not r.tuner),
                key=lambda r: r.t_spawn, reverse=True,
            )
            if not victims:
                return None
            self.manager.drain(victims[0].name)
            self._note(now, "scale_down", replicas=n - 1,
                       drained=victims[0].name, streak=self._idle_streak)
            self._idle_streak = 0
            self._last_action_t = now
            return "scale_down"
        return None

    def _note(self, now: float, action: str, **why) -> None:
        self.actions.append({"t": round(now, 3), "action": action, **why})
        obs_log.info("fleet", f"autoscaler {action}", **why)

    # -- live loop ------------------------------------------------------ #

    def start(self) -> "AutoScaler":
        self._stop = threading.Event()
        self._thread = threading.Thread(
            target=self._loop, daemon=True, name="fleet-scaler",
        )
        self._thread.start()
        return self

    def _loop(self) -> None:
        while not self._stop.is_set():
            try:
                self.step(self.manager.snapshots())
            except Exception as e:  # noqa: BLE001 — the loop must survive
                obs_log.warn("fleet", "scaler step failed",
                             error=f"{type(e).__name__}: {e}")
            self._stop.wait(self.config.interval_s)

    def stop(self) -> None:
        stop = getattr(self, "_stop", None)
        if stop is None:
            return
        stop.set()
        self._thread.join(5.0)
