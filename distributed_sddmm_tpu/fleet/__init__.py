"""Serving fleet: replica manager, front router, autoscaler.

One :class:`~distributed_sddmm_tpu.serve.engine.ServingEngine` is a
single queue and a single point of failure. This package turns the
serving layer into a *fleet*:

* :mod:`~distributed_sddmm_tpu.fleet.manager` — process-per-replica
  lifecycle: each replica is one ``bench serve --serve-http`` OS
  process with an injected ephemeral ``--admin-port``, spawned/reaped
  with the same hang-proof discipline as the elastic pod supervisor
  (``dist/elastic.py``) and warm-started from the shared ProgramStore
  so a replacement replica compiles nothing on the request path.
* :mod:`~distributed_sddmm_tpu.fleet.router` — a zero-dependency front
  router balancing on the signals the replicas already export
  (``/readyz`` readiness, SLO burn rate, queue depth), shedding at the
  edge with the ``Retry-After`` hint propagated from ``ShedError``,
  draining burning replicas instead of killing them, and routing by
  request *structure* (size buckets — the NeutronSparse admission idea
  at request granularity; pathological outliers go to the host-serial
  tier). PR 17 adds the gray-failure detectors: per-replica circuit
  breakers (a wedged replica stops eating the request timeout),
  hedged requests (p95-derived delay, first bit-identical reply
  wins), and a sampled cross-replica response audit whose mismatch
  verdict quarantines the byzantine replica.
* :mod:`~distributed_sddmm_tpu.fleet.scaler` — telemetry-driven
  autoscaling over the same ``/snapshot`` stream: spawn on sustained
  depth/burn pressure, drain-then-reap on sustained idle, min/max
  bounds and a cooldown.

Fleet-wide tuner discipline: exactly ONE replica runs the background
tuner (the canary); its promotion lands the winning plan in the shared
plan cache, and :meth:`FleetManager.rollout` rolls the rest of the
fleet onto it replica-by-replica (drain → respawn → warm-start onto
the cached winner) — the PR-12 closed loop with a blast-radius story.

``bench fleet`` (bench/cli.py) is the harness: an open-loop HTTP load
against the router under a seeded chaos schedule
(``resilience/chaos.py`` — kill/wedge/partition/slow/corrupt), pinning
replies bit-identical to a single-engine oracle, every gray fault
detected within a deadline, and availability above a floor throughout.
"""

from __future__ import annotations

from distributed_sddmm_tpu.fleet.manager import FleetManager, Replica
from distributed_sddmm_tpu.fleet.router import FleetRouter, ReplicaState
from distributed_sddmm_tpu.fleet.scaler import AutoScaler, ScalerConfig

__all__ = [
    "AutoScaler", "FleetManager", "FleetRouter", "Replica",
    "ReplicaState", "ScalerConfig",
]
