"""Process-per-replica fleet lifecycle.

A replica is ONE OS process serving one warm engine behind an admin
server (``bench serve --serve-http --admin-port <ephemeral>``). The
manager owns spawn/reap/replace:

* **Spawning is cheap by design**: every replica shares the process-
  wide ProgramStore directory, so a replacement's compile-ahead warmup
  resolves the whole bucket ladder from disk (``disk_hits``) instead of
  compiling — the acceptance bar is 0 request-path live compiles on a
  respawn.
* **Reaping reuses the elastic discipline** (``dist/elastic.py``):
  temp-file stdout/stderr (a chatty child must never block on a full
  pipe) and the last-JSON-line record convention — a drained replica's
  final stdout line is its serving record, collected into
  :attr:`FleetManager.records`.
* **Generations**: a replaced replica keeps its name and bumps its
  generation, mirroring the elastic supervisor's recovery-generation
  bookkeeping — fleet telemetry can tell "r1 gen 2" (respawned twice)
  from a fresh slot.
* **Tuner discipline**: exactly one replica (the first ``serve``-role
  spawn, by default) gets ``DSDDMM_TUNER=1`` overlaid — the canary that
  shadow-tests challengers. :meth:`rollout` then replaces the other
  replicas one at a time so their warmups pick the promoted plan out of
  the shared plan cache: canary → all, never the whole fleet at once.

The manager is deliberately transport-agnostic: it talks to replicas
only through their admin HTTP surface (``/healthz``, ``/readyz``,
``/snapshot``) and POSIX signals (SIGTERM = drain-and-exit-with-record,
SIGKILL = chaos, SIGSTOP/SIGCONT = gray-failure wedge).

Gray-failure lifecycle (PR 17):

* **Wedge** (:meth:`FleetManager.wedge`): SIGSTOP — the process is
  alive but answers nothing, the canonical gray fault. Every teardown
  path (``drain``/``stop_all``) SIGCONTs a wedged replica *first*: a
  stopped process cannot handle SIGTERM, so without the continue the
  drain would time out into a kill, lose the record, and — if the
  harness died before its timeout — leak a stopped ``bench serve``
  process forever.
* **Quarantine** (:meth:`FleetManager.quarantine`): a replica the
  router caught returning byzantine bytes (or whose breaker opened) is
  drained out of routing — excluded from :meth:`replicas` so the
  router drops it on the next poll — but kept ALIVE for autopsy; its
  flight record is dumped (``obs/flightrec``) and a warm replacement
  is spawned immediately. ``stop_all`` still drains it at teardown, so
  its serving record is collected like any other replica's.
"""

from __future__ import annotations

import os
import signal
import subprocess
import threading
import time
from typing import Callable, Optional

from distributed_sddmm_tpu.dist.elastic import (
    collect_output, free_port, last_json_line, spawn_process,
)
from distributed_sddmm_tpu.obs import log as obs_log


class Replica:
    """One managed replica process (live or just-reaped)."""

    def __init__(self, name: str, port: int, proc: subprocess.Popen,
                 role: str = "serve", generation: int = 0,
                 tuner: bool = False):
        self.name = name
        self.port = port
        self.proc = proc
        self.role = role
        self.generation = generation
        self.tuner = tuner
        self.t_spawn = time.monotonic()
        #: Filled at reap time: exit code and last-JSON-line record.
        self.rc: Optional[int] = None
        self.record: Optional[dict] = None
        #: SIGSTOPped by a chaos wedge (must be SIGCONTed on teardown).
        self.wedged = False
        #: Pulled from routing for autopsy (byzantine/breaker verdict).
        self.quarantined = False
        self.quarantine_reason: Optional[str] = None

    @property
    def alive(self) -> bool:
        return self.proc.poll() is None

    def describe(self) -> dict:
        return {
            "name": self.name, "port": self.port, "role": self.role,
            "generation": self.generation, "tuner": self.tuner,
            "alive": self.alive, "rc": self.rc,
            "wedged": self.wedged, "quarantined": self.quarantined,
            "quarantine_reason": self.quarantine_reason,
        }


class FleetManager:
    """Spawn, watch, replace, and drain a pool of serving replicas.

    ``replica_argv(name, port, role)`` builds one replica's full command
    line (``bench fleet`` points it at ``bench serve --serve-http``;
    tests point it at a cheap stub worker). ``env_overlay(name, port,
    role, tuner)`` returns extra environment for one replica — the
    manager itself only adds the tuner arming.
    """

    def __init__(
        self,
        replica_argv: Callable[[str, int, str], list],
        *,
        env_overlay: Optional[Callable] = None,
        cwd: Optional[str] = None,
        tuner_canary: bool = True,
    ):
        self.replica_argv = replica_argv
        self.env_overlay = env_overlay
        self.cwd = cwd
        #: Arm the background tuner on exactly one serve-role replica.
        self.tuner_canary = tuner_canary
        self._replicas: dict[str, Replica] = {}
        self._next_id = 0
        self._generation: dict[str, int] = {}
        #: Records collected from exited replicas (last JSON stdout
        #: line — the ``bench serve`` record), in reap order.
        self.records: list[dict] = []
        self.spawns = 0
        #: Replicas that died WITHOUT being asked (chaos kills, crashes).
        self.losses = 0
        #: Replicas pulled from routing on a byzantine/breaker verdict.
        self.quarantines = 0
        #: Quarantine verdicts in arrival order, monotonic-stamped —
        #: the chaos drill's detection-deadline judge reads this.
        self.quarantine_log: list[dict] = []
        self._quarantine_lock = threading.Lock()
        #: Trace shards harvested from replicas (reap/quarantine time):
        #: ``{"name", "generation", "pid", "path", "at"}`` rows, dedup'd
        #: by path. ``bench fleet`` merges these with the router's own
        #: trace into the fleet-wide causal tree.
        self.trace_shards: list[dict] = []

    def _harvest_shard(self, rep: Replica, at: str) -> None:
        """Record ``rep``'s per-process trace shard if the fleet run is
        traced. Replica tracers are line-buffered, so a shard is
        readable mid-flight (quarantine autopsy) and complete once the
        process exited (reap). Idempotent per path — a quarantined
        replica is harvested again at teardown without duplicating."""
        from distributed_sddmm_tpu.obs import trace as obs_trace

        shard_dir = obs_trace.shard_dir()
        if shard_dir is None:
            return
        path = obs_trace.find_shard(shard_dir, rep.proc.pid)
        if path is None:
            return
        if any(s["path"] == path for s in self.trace_shards):
            return
        self.trace_shards.append({
            "name": rep.name, "generation": rep.generation,
            "pid": rep.proc.pid, "path": path, "at": at,
        })
        obs_log.info("fleet", "trace shard harvested", name=rep.name,
                     at=at, path=path)

    # -- introspection -------------------------------------------------- #

    def replicas(self, role: Optional[str] = None,
                 include_quarantined: bool = False) -> list[Replica]:
        """Live routable replicas (optionally one role), spawn order.
        Quarantined replicas are alive but NOT routable — the router
        reads this list on every poll tick, so excluding them here IS
        the drain-out-of-routing mechanism."""
        return [r for r in self._replicas.values()
                if r.alive and (role is None or r.role == role)
                and (include_quarantined or not r.quarantined)]

    def get(self, name: str) -> Optional[Replica]:
        return self._replicas.get(name)

    def describe(self) -> dict:
        return {
            "replicas": [r.describe() for r in self._replicas.values()],
            "spawns": self.spawns,
            "losses": self.losses,
            "quarantines": self.quarantines,
            "records_collected": len(self.records),
            "trace_shards": len(self.trace_shards),
        }

    def _tuner_armed(self) -> bool:
        return any(r.tuner for r in self._replicas.values() if r.alive)

    # -- lifecycle ------------------------------------------------------ #

    def spawn(self, role: str = "serve", name: Optional[str] = None
              ) -> Replica:
        """Launch one replica on a fresh ephemeral admin port. A reused
        ``name`` (respawn) bumps that slot's generation; the tuner
        arms on the first serve-role replica only — one canary,
        never a fleet of independently-tuning engines."""
        if name is None:
            name = f"r{self._next_id}"
            self._next_id += 1
        generation = self._generation.get(name, -1) + 1
        self._generation[name] = generation
        port = free_port()
        tuner = (self.tuner_canary and role == "serve"
                 and not self._tuner_armed())
        env = dict(os.environ)
        if tuner:
            env["DSDDMM_TUNER"] = "1"
        else:
            env.pop("DSDDMM_TUNER", None)
        if self.env_overlay is not None:
            env.update(self.env_overlay(name, port, role, tuner) or {})
        proc = spawn_process(
            list(self.replica_argv(name, port, role)), env=env, cwd=self.cwd,
        )
        rep = Replica(name, port, proc, role=role, generation=generation,
                      tuner=tuner)
        self._replicas[name] = rep
        self.spawns += 1
        obs_log.info("fleet", "replica spawned", name=name, port=port,
                     role=role, generation=generation, tuner=tuner)
        return rep

    def wait_ready(self, timeout_s: float = 120.0,
                   names: Optional[list] = None) -> bool:
        """Poll each replica's ``/readyz`` until all are ready (True) or
        the deadline passes (False). A replica that *dies* while we
        wait fails fast — waiting out the full timeout on a corpse
        would hide a crash-on-boot as a timeout."""
        from distributed_sddmm_tpu.obs.httpexp import fetch_json

        want = names if names is not None else [
            r.name for r in self.replicas()
        ]
        deadline = time.monotonic() + timeout_s
        pending = set(want)
        while pending and time.monotonic() < deadline:
            for name in sorted(pending):
                rep = self._replicas.get(name)
                if rep is None or not rep.alive:
                    obs_log.warn("fleet", "replica died before ready",
                                 name=name)
                    return False
                try:
                    body = fetch_json("127.0.0.1", rep.port, "/readyz",
                                      timeout_s=1.0)
                except OSError:
                    continue  # not listening yet
                if body.get("ready"):
                    pending.discard(name)
            if pending:
                time.sleep(0.1)
        return not pending

    def _reap(self, rep: Replica, expected: bool) -> None:
        rep.proc.wait()
        out, err = collect_output(rep.proc)
        rep.rc = rep.proc.returncode
        rep.record = last_json_line(out)
        self._harvest_shard(rep, at="reap")
        if rep.record is not None:
            self.records.append(rep.record)
        if not expected:
            self.losses += 1
            obs_log.warn(
                "fleet", "replica lost", name=rep.name, rc=rep.rc,
                generation=rep.generation, stderr_tail=(err or "")[-300:],
            )
        else:
            obs_log.info("fleet", "replica reaped", name=rep.name,
                         rc=rep.rc, generation=rep.generation)

    def poll(self) -> list[Replica]:
        """Reap replicas that died on their own since the last poll;
        returns them (records collected, ``losses`` bumped)."""
        dead = [r for r in self._replicas.values()
                if r.rc is None and not r.alive]
        for rep in dead:
            self._reap(rep, expected=False)
        return dead

    def respawn_dead(self) -> list[Replica]:
        """The self-healing move: reap losses, then relaunch each under
        its old name (generation+1). The replacement's warmup resolves
        its ladder from the shared ProgramStore — disk hits, not
        request-path compiles."""
        replaced = []
        for rep in self.poll():
            replaced.append(self.spawn(role=rep.role, name=rep.name))
        return replaced

    def kill(self, name: str) -> None:
        """Chaos move: SIGKILL — no drain, no record, in-flight work
        dies with the process (the router's retry path owns it)."""
        rep = self._replicas.get(name)
        if rep is None or not rep.alive:
            raise ValueError(f"no live replica {name!r}")
        obs_log.warn("fleet", "replica killed (chaos)", name=name)
        rep.proc.kill()

    def wedge(self, name: str) -> None:
        """Gray-failure chaos move: SIGSTOP — the process stays alive
        (and holds its ports) but answers nothing. Reversed by
        :meth:`unwedge`; every teardown path SIGCONTs first."""
        rep = self._replicas.get(name)
        if rep is None or not rep.alive:
            raise ValueError(f"no live replica {name!r}")
        rep.proc.send_signal(signal.SIGSTOP)
        rep.wedged = True
        obs_log.warn("fleet", "replica wedged (chaos)", name=name)

    def unwedge(self, name: str) -> None:
        """SIGCONT a wedged replica. Idempotent; a no-op on a corpse."""
        rep = self._replicas.get(name)
        if rep is None:
            return
        if rep.alive and rep.wedged:
            rep.proc.send_signal(signal.SIGCONT)
            obs_log.info("fleet", "replica unwedged", name=name)
        rep.wedged = False

    def _continue_for_teardown(self, rep: Replica) -> None:
        """A SIGSTOPped process cannot handle SIGTERM — it would sit in
        the stopped state until the drain timeout killed it (record
        lost) or, if the harness died first, leak forever. SIGCONT
        before any teardown signal so the drain contract holds."""
        if rep.wedged and rep.alive:
            try:
                rep.proc.send_signal(signal.SIGCONT)
            except (OSError, ValueError):
                pass
            rep.wedged = False

    def quarantine(self, name: str, reason: str = "",
                   evidence: Optional[dict] = None,
                   respawn: bool = True) -> Optional[Replica]:
        """Byzantine/breaker verdict: pull ``name`` out of routing but
        keep it ALIVE for autopsy. Dumps a flight-record snapshot when
        the recorder is armed, bumps the quarantine ledger, and spawns
        a warm replacement (fresh name — the quarantined slot still
        exists). Returns the replacement (None when ``respawn`` is off
        or the replica was already quarantined/dead)."""
        from distributed_sddmm_tpu.obs import flightrec, metrics
        from distributed_sddmm_tpu.obs import trace as obs_trace

        with self._quarantine_lock:
            rep = self._replicas.get(name)
            if rep is None or not rep.alive or rep.quarantined:
                return None
            rep.quarantined = True
            rep.quarantine_reason = reason or "quarantined"
            self.quarantines += 1
            self.quarantine_log.append({
                "t": time.monotonic(), "name": name, "reason": reason,
                "generation": rep.generation,
            })
        metrics.GLOBAL.add("fleet_quarantines")
        obs_trace.event("fleet_quarantine", replica=name, reason=reason)
        self._harvest_shard(rep, at="quarantine")
        obs_log.warn("fleet", "replica quarantined", name=name,
                     reason=reason, generation=rep.generation)
        fr = flightrec.active()
        if fr is not None:
            fr.dump("fleet_quarantine", op="fleet", attrs={
                "name": name, "reason": reason,
                "generation": rep.generation, "role": rep.role,
                "evidence": evidence or {},
            })
        if not respawn:
            return None
        replacement = self.spawn(role=rep.role)
        obs_log.info("fleet", "quarantine replacement spawned",
                     quarantined=name, replacement=replacement.name)
        return replacement

    def drain(self, name: str, timeout_s: float = 60.0) -> Optional[dict]:
        """Graceful exit: SIGTERM → the replica closes admission, drains
        its queue, prints its record, exits 0. Returns the record."""
        rep = self._replicas.get(name)
        if rep is None or not rep.alive:
            raise ValueError(f"no live replica {name!r}")
        self._continue_for_teardown(rep)
        rep.proc.send_signal(signal.SIGTERM)
        try:
            rep.proc.wait(timeout_s)
        except subprocess.TimeoutExpired:
            obs_log.warn("fleet", "drain timed out; killing", name=name)
            rep.proc.kill()
        self._reap(rep, expected=True)
        return rep.record

    def stop_all(self, timeout_s: float = 60.0) -> list[dict]:
        """Drain every live replica (wedged ones are SIGCONTed first,
        quarantined ones included — their records still count); returns
        all collected records."""
        live = [r for r in self._replicas.values() if r.alive]
        for rep in live:
            self._continue_for_teardown(rep)
            rep.proc.send_signal(signal.SIGTERM)
        deadline = time.monotonic() + timeout_s
        for rep in live:
            remain = max(0.1, deadline - time.monotonic())
            try:
                rep.proc.wait(remain)
            except subprocess.TimeoutExpired:
                rep.proc.kill()
            self._reap(rep, expected=True)
        return list(self.records)

    # -- fleet-wide tuner rollout --------------------------------------- #

    def rollout(self, ready_timeout_s: float = 120.0) -> list[str]:
        """Canary → all: after the tuner replica promotes a challenger
        (its promotion stores the winning plan in the shared plan
        cache), replace every OTHER serve replica one at a time — drain,
        respawn under the same name, wait ready — so each replacement
        warms straight onto the winner. One replica's worth of capacity
        is out at any instant; a bad challenger is caught by the
        canary's shadow validation before this ever runs."""
        rolled = []
        targets = [r.name for r in self.replicas(role="serve")
                   if not r.tuner]
        for name in targets:
            role = self._replicas[name].role
            self.drain(name)
            self.spawn(role=role, name=name)
            if not self.wait_ready(ready_timeout_s, names=[name]):
                obs_log.warn("fleet", "rollout replacement not ready",
                             name=name)
                break
            rolled.append(name)
        obs_log.info("fleet", "rollout complete", replaced=rolled)
        return rolled

    # -- telemetry ------------------------------------------------------ #

    def snapshots(self) -> dict:
        """Live ``/snapshot`` per replica (None where unreachable) —
        the autoscaler's input stream."""
        from distributed_sddmm_tpu.obs.httpexp import fetch_json

        out = {}
        for rep in self.replicas():
            try:
                out[rep.name] = fetch_json("127.0.0.1", rep.port,
                                           "/snapshot", timeout_s=1.0)
            except (OSError, ValueError):
                out[rep.name] = None
        return out
