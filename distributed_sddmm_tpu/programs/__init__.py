"""Unified persistent AOT compiled-program store (PR 6).

``programs.keys``   — the one key grammar every compiled-program cache
uses (plan-routed strategy programs, serve bucket ladder, bench AOT).
``programs.store``  — the store itself: serialized-executable entries
under ``artifacts/programs/``, flock'd index, corrupt-entry eviction,
graceful fall-through to live compile.
"""

from distributed_sddmm_tpu.programs.keys import (  # noqa: F401
    bench_aot_key,
    parse_bench_key,
    parse_key,
    parse_plan_key,
    parse_serve_key,
    plan_program_key,
    safe_stem,
    serve_program_key,
    sig_for_args,
)
from distributed_sddmm_tpu.programs.store import (  # noqa: F401
    DEFAULT_ROOT,
    SCHEMA_VERSION,
    ProgramStore,
    StoredProgram,
    active,
    bind_strategy,
    chained_program,
    cost_log_len,
    disable,
    enable,
    matrix_content_key,
    stored,
    strategy_config_tag,
    xla_cost_summary,
)
