"""Persistent, fingerprint-keyed AOT compiled-program store.

Three compiled-program caches grew independently — the plan-routed
strategy programs (``autotune/plan.py``), the serve engine's bucket
ladder (``serve/engine.py``), and the bench AOT executables
(``bench/aot.py``) — each recompiling programs a previous run already
built. This module is the single store all three now read and write:
one directory (``artifacts/programs/`` by default) of serialized XLA
executables keyed by the shared grammar in ``programs/keys.py``
(problem shape + machine + code generation + aval signature), so a
serving cold start or a fresh worker process warms from disk instead of
compiling.

Durability discipline (the plan cache's, hardened by its corruption
suite):

* every write goes through ``utils/atomic.py`` (temp file +
  ``os.replace``; the resilience layer's write-fault hook applies),
* the summary ``index.json`` is derivative state behind the same
  advisory ``flock`` as the run store — corrupt or missing, it is
  rebuilt from the entry files, never trusted,
* a corrupt, truncated, schema-mismatched, foreign-key or
  wrong-backend entry reads as a **miss and is evicted**; the caller
  falls through to a live compile. The store is a pure accelerator —
  it can cost a compile, never an error,
* deserialization runs through ``compat.deserialize_and_load`` (the
  jax-generation shim), and any failure there also evicts and falls
  through.

Counters land in ``obs.metrics.GLOBAL``: ``program_store_hits`` (disk),
``program_store_misses`` (absent/evicted), ``live_compiles`` (an
executable was built in-process — the number a warmed cold start must
drive to zero). Since PR 7 the same facts are also trace *events* —
``program_store_hit`` / ``program_store_compile`` with the program key
(and compile seconds on the compile side) — so cold-start cost shows up
as tracereport phases, not just end-of-run counter deltas.

XLA cost cross-check: :meth:`ProgramStore.save` captures the compiled
executable's ``cost_analysis()`` FLOPs/bytes into the entry (and the
index row), and every save/load registers the numbers in a process-wide
cost log. :func:`xla_cost_summary` joins that log against the per-op
analytic counters (token-matching op names inside plan keys), giving
the tracereport/regress layers an *independent* FLOP column: the
analytic cost model and XLA's own accounting are maintained by
different parties, and their ratio drifting is how either one's bugs
surface (``obs/watchdog.py::check_xla_costs`` flags beyond-band
disagreement).

Activation mirrors the run store: ``DSDDMM_PROGRAMS`` = ``0``/``off``
disables, a path relocates, unset/``1`` selects the default root.
Unlike the run store (telemetry), the program store defaults ON — it is
a functional cache — but the test conftest vetoes it so CI cannot silt
``artifacts/``.
"""

from __future__ import annotations

import contextlib
import os
import pathlib
import pickle
import threading

from distributed_sddmm_tpu.programs import keys as keys_mod
from distributed_sddmm_tpu.utils.atomic import atomic_write_bytes, atomic_write_json

#: Entry payload schema generation; readers evict entries they cannot read.
SCHEMA_VERSION = 1

_REPO = pathlib.Path(__file__).resolve().parents[2]
DEFAULT_ROOT = _REPO / "artifacts" / "programs"


def _global_counters():
    from distributed_sddmm_tpu.obs import metrics as obs_metrics

    return obs_metrics.GLOBAL


# --------------------------------------------------------------------- #
# XLA cost capture (compiled.cost_analysis at compile/load time)
# --------------------------------------------------------------------- #

#: Process-wide append-only log of (key, cost) pairs, in resolution
#: order — callers snapshot ``cost_log_len()`` before a run and summon
#: ``xla_cost_summary(..., since=cursor)`` after, the same cursor
#: discipline the fault plan and watchdog events use.
_cost_log: list[tuple[str, dict]] = []
_cost_lock = threading.Lock()


def _cost_analysis(compiled) -> dict | None:
    """``{"flops", "bytes_accessed"}`` from an executable's own cost
    analysis, or None when this jax generation/backend exposes none.
    The numbers are XLA's accounting of the COMPILED program (padding
    and fusion included) — deliberately not the analytic model's."""
    try:
        cost = compiled.cost_analysis()
        if isinstance(cost, (list, tuple)):
            cost = cost[0] if cost else None
        if not isinstance(cost, dict):
            return None
        out = {}
        if cost.get("flops") is not None:
            out["flops"] = float(cost["flops"])
        if cost.get("bytes accessed") is not None:
            out["bytes_accessed"] = float(cost["bytes accessed"])
        return out or None
    except Exception:  # noqa: BLE001 — cost capture is best-effort
        return None


def register_cost(key: str, cost: dict | None) -> None:
    if not cost:
        return
    with _cost_lock:
        _cost_log.append((key, cost))


def cost_log_len() -> int:
    with _cost_lock:
        return len(_cost_log)


#: Metric-op → program-cache-key tokens (the strategy names its cached
#: programs "fused"/"sddmm"/"spmm"; app chains embed the metric name).
_OP_KEY_TOKENS = {
    "fusedSpMM": ("fused", "fused_twopass"),
    "fusedSpMMB": ("fused", "fused_twopass"),
    "fusedAttn": ("attn",), "fusedAttnB": ("attn",),
    "attnSoftmax": ("attn_softmax",),
    "sddmmA": ("sddmm",), "sddmmB": ("sddmm",),
    "spmmA": ("spmm",), "spmmB": ("spmm",),
}


def xla_cost_summary(ops, since: int = 0) -> dict | None:
    """Join the cost log against per-op analytic metrics ops.

    ``ops`` is an iterable of op names (typically a bench record's
    ``metrics`` keys). A logged key matches an op when one of the op's
    program-cache tokens appears as a ``-``/``:``-separated token of
    the key (plan keys embed the strategy's program-cache key, e.g.
    ``...-fused-False-none``; chained app keys embed the metric name
    itself, e.g. ``...-cgStep-A-...``). Returns ``{"programs": N,
    "ops": {op: {"flops_per_call", "bytes_per_call", "programs"}}}``
    averaging over matching programs (A/B-mode variants of one op
    legitimately differ), or None when nothing matched — records
    without the field simply lack the gate axis.
    """
    with _cost_lock:
        log = _cost_log[since:]
    if not log:
        return None
    out: dict[str, dict] = {}
    for op in ops:
        tokens = set(_OP_KEY_TOKENS.get(op, (op,)))
        flops, bytes_, n = 0.0, 0.0, 0
        for key, cost in log:
            if tokens & set(key.replace(":", "-").split("-")):
                n += 1
                flops += cost.get("flops", 0.0)
                bytes_ += cost.get("bytes_accessed", 0.0)
        if n and flops:
            out[op] = {
                "flops_per_call": flops / n,
                "bytes_per_call": bytes_ / n if bytes_ else None,
                "programs": n,
            }
    if not out:
        return None
    return {"programs": len(log), "ops": out}


def live_backend() -> str | None:
    """Platform of the default jax backend, initializing it if needed —
    the store's load path runs next to a compile, so a backend is
    already (or about to be) up; this is not the manifest's
    never-initialize context."""
    try:
        import jax

        return jax.default_backend()
    except Exception:  # noqa: BLE001 — no backend, no backend gate
        return None


class ProgramStore:
    """One directory of serialized executables plus a derived index.

    Layout::

        <root>/entries/<safe_stem(key)>.prog   pickled entry dict
        <root>/index.json                      summary rows (derived)

    An entry dict: ``{"schema", "key", "backend", "created_epoch",
    "meta", "payload"}`` where ``payload`` is
    ``jax.experimental.serialize_executable.serialize``'s
    ``(serialized, in_tree, out_tree)`` tuple.
    """

    def __init__(self, root: str | os.PathLike | None = None):
        self.root = pathlib.Path(root) if root else DEFAULT_ROOT
        self.entries_dir = self.root / "entries"
        self.index_path = self.root / "index.json"
        self._lock = threading.Lock()
        # Per-instance counters (tests + engine stats); the GLOBAL
        # counters aggregate across stores process-wide.
        self.hits = 0
        self.misses = 0
        self.live_compiles = 0

    def _path(self, key: str) -> pathlib.Path:
        return self.entries_dir / f"{keys_mod.safe_stem(key)}.prog"

    # ------------------------------------------------------------------ #
    # flock'd index (the run store's cross-process discipline)
    # ------------------------------------------------------------------ #

    @contextlib.contextmanager
    def _flock(self):
        try:
            import fcntl
        except ImportError:
            yield
            return
        self.root.mkdir(parents=True, exist_ok=True)
        # non-atomic-ok: flock target — the file's CONTENT is never read.
        with open(self.root / ".lock", "w") as fh:
            fcntl.flock(fh, fcntl.LOCK_EX)
            try:
                yield
            finally:
                fcntl.flock(fh, fcntl.LOCK_UN)

    def _read_index(self) -> list | None:
        import json

        try:
            rows = json.loads(self.index_path.read_text())
        except FileNotFoundError:
            return []
        except (OSError, ValueError):
            return None  # corrupt — rebuild
        if not isinstance(rows, list):
            return None
        return [r for r in rows if isinstance(r, dict) and r.get("key")]

    def _rebuild_index_locked(self) -> list:
        rows = []
        for f in sorted(self.entries_dir.glob("*.prog")):
            entry = self._read_entry_file(f)
            if entry is not None:
                rows.append(self._index_row(entry))
        atomic_write_json(self.index_path, rows)
        return rows

    @staticmethod
    def _index_row(entry: dict) -> dict:
        return {
            "key": entry.get("key"),
            "backend": entry.get("backend"),
            "created_epoch": entry.get("created_epoch"),
            "meta": entry.get("meta") or {},
            # XLA's own FLOPs/bytes for the executable (None on
            # pre-PR-7 entries and cost-less backends).
            "cost": entry.get("cost"),
        }

    def _update_index(self, entry: dict | None, drop_key: str | None = None):
        with self._flock():
            rows = self._read_index()
            if rows is None:
                rows = self._rebuild_index_locked()
            if drop_key is not None:
                rows = [r for r in rows if r.get("key") != drop_key]
            if entry is not None:
                rows = [r for r in rows if r.get("key") != entry.get("key")]
                rows.append(self._index_row(entry))
            rows.sort(key=lambda r: (r.get("created_epoch") or 0, r["key"]))
            atomic_write_json(self.index_path, rows)

    def index(self) -> list[dict]:
        with self._lock:
            rows = self._read_index()
            if rows is None:
                with self._flock():
                    rows = self._rebuild_index_locked()
            return rows

    # ------------------------------------------------------------------ #
    # Entry I/O
    # ------------------------------------------------------------------ #

    def _read_entry_file(self, path: pathlib.Path) -> dict | None:
        try:
            raw = path.read_bytes()
        except OSError:
            return None
        try:
            entry = pickle.loads(raw)
        except Exception:  # noqa: BLE001 — truncated/garbled pickle
            return None
        if not isinstance(entry, dict):
            return None
        if entry.get("schema") != SCHEMA_VERSION:
            return None
        return entry

    def evict(self, key: str) -> None:
        """Drop one entry (corruption, staleness); never raises."""
        try:
            os.unlink(self._path(key))
        except OSError:
            pass
        try:
            self._update_index(None, drop_key=key)
        except OSError:
            pass

    def load(self, key: str, *, backend: str | None = None, device=None):
        """The deserialized executable for ``key``, or None.

        Misses: absent file, unreadable/truncated pickle, schema or
        embedded-key mismatch (a renamed/copied entry must not answer
        for a foreign key), backend mismatch (an executable serialized
        for another platform cannot run here), or a deserialize failure
        — every non-absent miss also EVICTS the entry so the slot heals
        on the next save (backend mismatch excepted: a store shared
        between backends is legal, the entry is another platform's).
        Never raises for entry-content reasons.

        ``device`` pins deserialization to one device (the bench AOT
        re-homing path); default is the process's first device.
        """
        from distributed_sddmm_tpu import compat
        from distributed_sddmm_tpu.obs import log as obs_log

        path = self._path(key)
        entry = self._read_entry_file(path)
        if entry is None:
            if path.exists():
                self.evict(key)
            self._miss()
            return None
        if entry.get("key") != key:
            self.evict(key)
            self._miss()
            return None
        if backend is not None:
            want_backend = backend
        elif device is not None:
            want_backend = device.platform
        else:
            want_backend = live_backend()
        if want_backend is not None and entry.get("backend") != want_backend:
            self._miss()
            return None
        try:
            import jax

            serialized, in_tree, out_tree = entry["payload"]
            client = (
                device.client if device is not None
                else jax.devices()[0].client
            )
            loaded = compat.deserialize_and_load(
                serialized, in_tree, out_tree, backend=client,
                execution_devices=[device] if device is not None else None,
            )
        except Exception as e:  # noqa: BLE001 — any failure -> live compile
            obs_log.warn(
                "programs", "deserialize failed; evicting entry",
                key=key, error=f"{type(e).__name__}: {e}",
            )
            self.evict(key)
            self._miss()
            return None
        with self._lock:
            self.hits += 1
        _global_counters().add("program_store_hits")
        cost = entry.get("cost")
        register_cost(key, cost)
        # The counter's trace-event twin: disk warms are visible as
        # events in tracereport, not just end-of-run counter deltas.
        from distributed_sddmm_tpu.obs import trace as obs_trace

        if obs_trace.enabled():
            obs_trace.event(
                "program_store_hit", key=key,
                **({"xla_flops": cost["flops"]}
                   if cost and cost.get("flops") else {}),
            )
        return loaded

    def save(self, key: str, compiled, meta: dict | None = None,
             backend: str | None = None) -> bool:
        """Serialize + persist one compiled executable atomically.

        ``backend`` is the executable's TARGET platform; it defaults to
        the live backend but offline AOT compilers (a CPU-pinned process
        compiling for a TPU topology) must pass the target explicitly or
        the load-side backend gate would reject their own entries.

        Returns False (never raises) when this jax generation or
        executable cannot serialize — the store is an accelerator, and
        the caller already holds a working compiled program.
        """
        from distributed_sddmm_tpu.obs import clock
        from distributed_sddmm_tpu.obs import log as obs_log

        try:
            from jax.experimental import serialize_executable as se

            payload = se.serialize(compiled)
            cost = _cost_analysis(compiled)
            register_cost(key, cost)
            entry = {
                "schema": SCHEMA_VERSION,
                "key": key,
                "backend": backend if backend is not None else live_backend(),
                "created_epoch": clock.epoch(),
                "meta": dict(meta or {}),
                "cost": cost,
                "payload": payload,
            }
            atomic_write_bytes(self._path(key), pickle.dumps(entry))
            self._update_index(entry)
            return True
        except Exception as e:  # noqa: BLE001 — persistence is best-effort
            obs_log.warn(
                "programs", "serialize/store failed; entry not persisted",
                key=key, error=f"{type(e).__name__}: {e}",
            )
            return False

    # ------------------------------------------------------------------ #
    # The one call sites use
    # ------------------------------------------------------------------ #

    def get_or_compile(self, key: str, compile_fn, meta: dict | None = None):
        """(program, source): the deserialized entry (``"disk"``) or a
        live ``compile_fn()`` result (``"live"``, persisted for the next
        process). ``compile_fn`` must return a callable compiled
        executable (e.g. ``jit_fn.lower(*args).compile()``). Live
        compiles emit a ``program_store_compile`` trace event carrying
        the key and compile seconds, so cold-start cost shows up in
        tracereport phases rather than only as a counter delta."""
        from distributed_sddmm_tpu.obs import clock
        from distributed_sddmm_tpu.obs import trace as obs_trace

        prog = self.load(key)
        if prog is not None:
            return prog, "disk"
        t0 = clock.now()
        prog = compile_fn()
        compile_s = clock.now() - t0
        self._live()
        self.save(key, prog, meta=meta)
        if obs_trace.enabled():
            with _cost_lock:
                cost = dict(_cost_log[-1][1]) \
                    if _cost_log and _cost_log[-1][0] == key else None
            obs_trace.event(
                "program_store_compile", key=key,
                compile_s=round(compile_s, 6),
                **({"xla_flops": cost["flops"]}
                   if cost and cost.get("flops") else {}),
            )
        return prog, "live"

    def _miss(self) -> None:
        with self._lock:
            self.misses += 1
        _global_counters().add("program_store_misses")

    def _live(self) -> None:
        with self._lock:
            self.live_compiles += 1
        _global_counters().add("live_compiles")

    def stats(self) -> dict:
        with self._lock:
            return {
                "hits": self.hits,
                "misses": self.misses,
                "live_compiles": self.live_compiles,
            }


# --------------------------------------------------------------------- #
# Store-backed jit wrapper (the strategy/app integration point)
# --------------------------------------------------------------------- #


class StoredProgram:
    """Wrap a jitted function with store-backed resolution per aval
    signature.

    On a call with concrete arrays the argument signature selects a
    store key (``key_fn(sig)``); resolution tries the store first
    (disk hit), else AOT-compiles the jit via ``lower(*args).compile()``
    (live compile, persisted). Under a jax trace (the wrapped program is
    being inlined into a larger jitted program — the cgStep/gatLayer
    chains do exactly this) the wrapper steps aside and calls the jit
    directly: tracers have no buffers to load into.

    A disk-loaded executable that rejects a call (shape drift the key
    missed, donation/layout mismatch) permanently falls back to the jit
    for that signature — correctness never depends on the store.
    """

    def __init__(self, jit_fn, key_fn, store: "ProgramStore | None",
                 meta: dict | None = None, on_resolve=None):
        self._jit_fn = jit_fn
        self._key_fn = key_fn
        self._store = store
        self._meta = meta or {}
        self._on_resolve = on_resolve  # callback(source: "disk"|"live")
        self._resolved: dict[str, object] = {}
        self._lock = threading.Lock()

    def __call__(self, *args):
        import jax

        if self._store is None:
            return self._jit_fn(*args)
        # One traversal serves both the tracer check and the dispatch
        # key. The resolved-program cache is keyed on the raw
        # (shape, dtype) tuple — comparable in cost to jit's own cache
        # lookup — and the sha-based store signature is computed only on
        # the resolution miss, not per dispatch.
        shapes = []
        for x in jax.tree_util.tree_leaves(args):
            if isinstance(x, jax.core.Tracer):
                # Being inlined into a larger jitted program: step aside.
                return self._jit_fn(*args)
            shapes.append((getattr(x, "shape", ()),
                           str(getattr(x, "dtype", ""))))
        cache_key = tuple(shapes)
        prog = self._resolved.get(cache_key)
        if prog is None:
            sig = keys_mod.sig_for_args(jax.tree_util.tree_leaves(args))
            prog, src = self._store.get_or_compile(
                self._key_fn(sig),
                lambda: self._jit_fn.lower(*args).compile(),
                meta=self._meta,
            )
            with self._lock:
                self._resolved[cache_key] = prog
            if self._on_resolve is not None:
                self._on_resolve(src)
            if src == "disk":
                # A loaded executable must actually accept this call;
                # reject -> permanent jit fallback for the signature.
                try:
                    return prog(*args)
                except Exception as e:  # noqa: BLE001
                    from distributed_sddmm_tpu.obs import log as obs_log

                    obs_log.warn(
                        "programs",
                        "stored program rejected a call; jit fallback",
                        key=self._key_fn(sig),
                        error=f"{type(e).__name__}: {e}",
                    )
                    with self._lock:
                        self._resolved[cache_key] = self._jit_fn
                    self._store._live()
                    return self._jit_fn(*args)
        return prog(*args)

    # jit-API passthroughs some callers poke at.
    def lower(self, *args, **kw):
        return self._jit_fn.lower(*args, **kw)


def stored(jit_fn, key_fn, store: "ProgramStore | None" = None,
           meta: dict | None = None):
    """``StoredProgram`` over the active store (or ``store``); returns
    the jit unchanged when no store is active — zero overhead when the
    layer is disabled."""
    store = store if store is not None else active()
    if store is None:
        return jit_fn
    return StoredProgram(jit_fn, key_fn, store, meta=meta)


# --------------------------------------------------------------------- #
# Strategy binding (autotune Plan.instantiate's hook)
# --------------------------------------------------------------------- #


def strategy_config_tag(alg) -> str:
    """The strategy-configuration half of a program key.

    The problem fingerprint alone does NOT determine the compiled
    program: one fingerprint legitimately runs under several
    (algorithm, c, kernel) configurations — a heatmap sweep benchmarks
    every algorithm at every cell, and a re-measured plan can change its
    algorithm under an unchanged fingerprint — so the key must carry
    the configuration or entries would alias across them. Tile geometry
    and block shapes are already covered by the aval signature; this tag
    covers what avals cannot see: the strategy class, replication
    factor, the ring-build knobs (overlap fusion, rolled loops — same
    avals, different traced program), and the kernel knobs that reshape
    the traced program without changing argument shapes (precision,
    gather chunking, scatter form, batch step).
    """
    kern = alg.kernel
    cls = type(kern).__name__
    if getattr(kern, "variant_id", None):
        # BankedPallasKernel traces the SAME program family as the
        # generic PallasKernel (it falls through on generic tiles); the
        # realized variant in the op segment is what distinguishes
        # banked programs. Tagging the subclass name would fork a
        # guard-fallback build away from the generic entry it is
        # byte-identical to — and pre-PR-9 generic keys must not move.
        cls = "PallasKernel"
    bits = [type(alg).__name__, f"c{alg.c}", cls]
    if getattr(alg, "overlap", False):
        bits.append("ov")
    if not getattr(alg, "unroll", True):
        bits.append("rolled")
    # The codegen kernel variant is deliberately ABSENT here: the
    # per-op segment of the strategy's program-cache key carries the
    # REALIZED variant (base._program_cache_key), so a build that
    # guard-fell to the generic encoding shares the generic entry —
    # tagging the kernel's identity would fork a duplicate.
    for attr in ("precision", "gather_budget", "scatter_form",
                 "batch_step"):
        v = getattr(kern, attr, None)
        if v is not None:
            bits.append(f"{attr[:4]}{v}")
    return "-".join(bits)


def matrix_content_key(S) -> str:
    """Content digest of one sparse matrix (indices + values + shape).

    The strategy's shard_map programs take the tile arrays as
    *arguments*, so their store entries are content-generic — but the
    jit-chained app programs (``cgStep``, ``gatLayer``) trace through
    the raw-program accessors' closures and bake the concrete tile
    index/mask arrays into the executable as constants. Two matrices
    with identical coarse fingerprints (same M, N, nnz, R, p) would
    otherwise alias one chained entry and serve the wrong sparsity
    pattern; this digest keys them apart.
    """
    import hashlib

    import numpy as np

    h = hashlib.sha256()
    for arr in (S.rows, S.cols, S.vals):
        h.update(np.ascontiguousarray(arr).tobytes())
    h.update(f"{S.M}x{S.N}".encode())
    return h.hexdigest()[:12]


def bind_strategy(alg, fingerprint_key: str,
                  store: "ProgramStore | None" = None,
                  content_key: str | None = None) -> bool:
    """Install a program binder on a strategy: every shard_map program
    the strategy builds from now on resolves through the store under
    ``plan:<fingerprint_key>:<config>-<op>:<sig>`` keys. Returns False
    (no-op) when no store is active. Already-built programs are dropped
    so they rebuild through the binder — cheap: the jit wrappers
    re-trace only on their next call, which is when they would have
    compiled anyway.

    The binding facts land on ``alg._program_store_meta`` so the
    jit-chained app programs built ON TOP of the strategy (``cgStep``,
    ``gatLayer``) can resolve through the same store under the same
    fingerprint."""
    store = store if store is not None else active()
    if store is None or not fingerprint_key:
        return False
    backend = live_backend() or "unknown"
    cfg = strategy_config_tag(alg)
    # Pod identity is resolved ONCE at bind time (the worker's slot in
    # the pod cannot change mid-run): multi-process workers key their
    # per-process executables under a trailing ``dN.pK`` segment so a
    # worker warm-starts from exactly the entries its own slot wrote;
    # single-process binds append nothing and stay byte-identical to
    # the PR 6-13 grammar.
    dist = keys_mod.dist_segment()

    def binder(op_key: str, jit_fn):
        def key_fn(sig: str) -> str:
            return keys_mod.plan_program_key(
                fingerprint_key, f"{cfg}-{op_key}", sig, backend,
                dist=dist,
            )

        return StoredProgram(
            jit_fn, key_fn, store,
            meta={"fingerprint_key": fingerprint_key, "op": op_key,
                  "config": cfg, **({"dist": dist} if dist else {})},
        )

    alg.bind_program_store(binder)
    alg._program_store_meta = {
        "store": store, "fingerprint_key": fingerprint_key,
        "config": cfg, "backend": backend, "dist": dist,
        # Matrix-content digest (:func:`matrix_content_key`), consumed
        # by :func:`chained_program` — see there for why the chains
        # need it and the strategy programs do not.
        "content": content_key or "",
    }
    return True


def chained_program(alg, op: str, jit_fn):
    """Store-back one jit-chained APP program (cgStep, gatLayer) built
    over a bound strategy: resolves under the strategy's binding
    (fingerprint + config tag) PLUS the ``models/`` code generation —
    the chain bakes the app-side math (CG vector algebra, the GAT layer
    body) into the executable, which the plan-scope ``code_hash`` in
    the fingerprint deliberately does not cover. Returns ``jit_fn``
    unchanged when the strategy is unbound — the pre-store behavior,
    byte for byte."""
    meta = getattr(alg, "_program_store_meta", None)
    if not meta:
        return jit_fn
    if not meta.get("content"):
        # No content digest recorded at bind time: the chain would bake
        # this matrix's tile constants under a content-blind key — a
        # same-shape different-content matrix could then recall the
        # wrong sparsity pattern. Stay on the plain jit instead.
        return jit_fn
    from distributed_sddmm_tpu.autotune.fingerprint import models_code_hash

    op = f"{op}-m{models_code_hash()}-x{meta['content']}"

    def key_fn(sig: str) -> str:
        return keys_mod.plan_program_key(
            meta["fingerprint_key"], f"{meta['config']}-{op}", sig,
            meta["backend"], dist=meta.get("dist"),
        )

    return StoredProgram(
        jit_fn, key_fn, meta["store"],
        meta={"fingerprint_key": meta["fingerprint_key"], "op": op,
              "config": meta["config"],
              **({"dist": meta["dist"]} if meta.get("dist") else {})},
    )


# --------------------------------------------------------------------- #
# Module-level activation (env grammar shared with the run store)
# --------------------------------------------------------------------- #

_active: ProgramStore | None = None
_env_checked = False
_registry_lock = threading.Lock()


def default_root() -> pathlib.Path:
    from distributed_sddmm_tpu.obs.store import parse_env_spec

    _enabled, root = parse_env_spec(os.environ.get("DSDDMM_PROGRAMS"))
    return pathlib.Path(root) if root else DEFAULT_ROOT


def enable(root: str | os.PathLike | None = None) -> ProgramStore:
    """Activate the process-wide store (idempotent; an active store
    wins — same semantics as the run store and tracer)."""
    global _active, _env_checked
    with _registry_lock:
        _env_checked = True
        if _active is None:
            _active = ProgramStore(root)
        return _active


def disable() -> None:
    global _active, _env_checked
    with _registry_lock:
        _active = None
        _env_checked = True


def active() -> ProgramStore | None:
    """The active store, resolving ``DSDDMM_PROGRAMS`` on first query.
    Unlike the run store (telemetry, off unless asked), the program
    store is a functional cache and defaults ON at the default root;
    ``DSDDMM_PROGRAMS=0`` (the test conftest) vetoes it."""
    global _active, _env_checked
    if _env_checked:
        return _active
    with _registry_lock:
        if not _env_checked:
            _env_checked = True
            from distributed_sddmm_tpu.obs.store import parse_env_spec

            enabled, root = parse_env_spec(os.environ.get("DSDDMM_PROGRAMS"))
            if enabled:
                _active = ProgramStore(root)
    return _active
