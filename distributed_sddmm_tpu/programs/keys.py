"""Canonical program-store keys: one grammar for every compiled-program
cache.

Three look-alike fingerprint-key builders grew independently — the
autotune plan fingerprints (``autotune/fingerprint.py``), the serve
engine's bucket-ladder keys, and the bench AOT file stems — and the
program store unifies their *compiled-program* halves here so store keys
cannot silently diverge again. The design rules are the plan cache's
(``autotune/fingerprint.py`` module doc):

* a key is a pure function of (problem shape, machine, code generation):
  same inputs in two processes MUST produce the same key — cross-restart
  and cross-process reuse both depend on it;
* the code generation is baked INTO the key (``code_hash`` for programs
  shaped by ``ops/`` + ``parallel/``, ``serve_code_hash`` for serving
  programs): a new code generation is a new key, so a stale entry can
  never answer for new code;
* the **aval signature** (shapes + dtypes of the example arguments) is
  part of the key: compiled executables are shape-rigid, and two
  problems that share a fingerprint bucket can still disagree on padded
  tile geometry.

Keys are colon-joined printable segments (safe as file-name stems after
:func:`safe_stem`); every builder has a matching parser and the pair is
round-trip tested (``tests/test_program_keys.py``).

This module deliberately imports neither jax nor the strategy code —
keys must be computable in subprocesses and offline tooling (same
discipline as ``autotune/fingerprint.py``; the only jax touch-point,
:func:`sig_for_args`, duck-types on ``shape``/``dtype``).
"""

from __future__ import annotations

import hashlib
import re

_SEG_RE = re.compile(r"^[A-Za-z0-9._=+-]+$")


def _seg(value) -> str:
    """One key segment: printable and colon-free, or content-hashed."""
    s = str(value)
    if _SEG_RE.match(s):
        return s
    return "h" + hashlib.sha256(s.encode()).hexdigest()[:12]


def sig_for_args(args) -> str:
    """Short stable hash of the argument aval signature (shapes +
    dtypes, structure-order). Works on jax arrays, numpy arrays and
    ShapeDtypeStructs — anything with ``shape`` and ``dtype``."""
    parts = []
    for a in args:
        shape = tuple(getattr(a, "shape", ()))
        dtype = str(getattr(a, "dtype", type(a).__name__))
        parts.append(f"{shape}{dtype}")
    return hashlib.sha256(";".join(parts).encode()).hexdigest()[:10]


# --------------------------------------------------------------------- #
# Plan-routed strategy programs (autotune Plan.instantiate)
# --------------------------------------------------------------------- #


def plan_program_key(
    fingerprint_key: str,
    op: str,
    sig: str,
    backend: str,
    code: str | None = None,
) -> str:
    """Key for one compiled strategy program under an autotune plan.

    ``fingerprint_key`` is the plan fingerprint (problem + machine +
    code already hashed in); ``op`` names the strategy's program-cache
    key (op name, tile set, ablation mode); ``sig`` is
    :func:`sig_for_args` over the concrete call arguments. ``code``
    defaults to the live ``autotune.fingerprint.code_hash()`` — baked in
    even though the fingerprint already covers it, so a key parsed out
    of the store is self-describing about its generation.
    """
    if code is None:
        from distributed_sddmm_tpu.autotune.fingerprint import code_hash

        code = code_hash()
    return ":".join(
        ("plan", _seg(fingerprint_key), _seg(op), _seg(sig),
         _seg(backend), _seg(code))
    )


def parse_plan_key(key: str) -> dict | None:
    parts = key.split(":")
    if len(parts) != 6 or parts[0] != "plan":
        return None
    return dict(zip(
        ("family", "fingerprint_key", "op", "sig", "backend", "code_hash"),
        parts,
    ))


# --------------------------------------------------------------------- #
# Serving bucket-ladder programs (serve/engine.py)
# --------------------------------------------------------------------- #


def serve_program_key(
    workload: str,
    batch_bucket: int,
    inner_bucket: int,
    r,
    backend: str,
    code: str | None = None,
    params: str | None = None,
    sig: str | None = None,
    variant: str | None = None,
) -> str:
    """Cache key for one serving bucket cell — the grammar the engine
    has used since PR 5 (``serve:<workload>:b<bb>:i<ib>:r<R>:<backend>:
    <serve_code_hash>``), now owned here, with optional trailing
    segments the store appends: ``p<params>`` (workload constants the
    traced program bakes in — the fold-in top-k size and ridge, which
    change the executable without changing any argument shape),
    ``s<sig>`` (the aval signature, so a program compiled against one
    model's array shapes can never answer for another's) and
    ``v<variant>`` (the warm model's codegen kernel-variant id, PR 9 —
    a ladder warmed under one kernel specialization never answers for
    another; variant-less keys are byte-identical to the PR 5-8
    grammar, so existing stores keep hitting)."""
    if code is None:
        from distributed_sddmm_tpu.autotune.fingerprint import serve_code_hash

        code = serve_code_hash()
    key = (
        f"serve:{_seg(workload)}:b{int(batch_bucket)}:i{int(inner_bucket)}"
        f":r{_seg(r)}:{_seg(backend)}:{_seg(code)}"
    )
    if params:
        key += f":p{_seg(params)}"
    if sig:
        key += f":s{_seg(sig)}"
    if variant:
        key += f":v{_seg(variant)}"
    return key


def parse_serve_key(key: str) -> dict | None:
    parts = key.split(":")
    if not (7 <= len(parts) <= 10) or parts[0] != "serve":
        return None
    if not (parts[2].startswith("b") and parts[3].startswith("i")
            and parts[4].startswith("r")):
        return None
    out = {
        "family": "serve",
        "workload": parts[1],
        "batch_bucket": int(parts[2][1:]),
        "inner_bucket": int(parts[3][1:]),
        "r": parts[4][1:],
        "backend": parts[5],
        "code_hash": parts[6],
    }
    for extra in parts[7:]:
        if extra.startswith("p"):
            out["params"] = extra[1:]
        elif extra.startswith("s"):
            out["sig"] = extra[1:]
        elif extra.startswith("v"):
            out["variant"] = extra[1:]
        else:
            return None
    return out


# --------------------------------------------------------------------- #
# Bench AOT chain executables (bench/aot.py)
# --------------------------------------------------------------------- #


def bench_aot_key(stem: str, name: str, n: int, backend: str = "tpu") -> str:
    """Key for one serialized bench chain executable. ``stem`` is the
    config-describing cache-directory basename the offline compilers
    already derive (it embeds the code/knob hash — e.g.
    ``distgap_16_32_128_t5_<hash>``), ``name``/``n`` the program name
    and trip count that used to form the ``{name}_{n}.pkl`` file stem."""
    return ":".join(("bench", _seg(stem), _seg(name), str(int(n)),
                     _seg(backend)))


def parse_bench_key(key: str) -> dict | None:
    parts = key.split(":")
    if len(parts) != 5 or parts[0] != "bench":
        return None
    try:
        n = int(parts[3])
    except ValueError:
        return None
    return {"family": "bench", "stem": parts[1], "name": parts[2],
            "n": n, "backend": parts[4]}


# --------------------------------------------------------------------- #


def parse_key(key: str) -> dict | None:
    """Parse any store key; None when the grammar is unrecognized."""
    for parser in (parse_plan_key, parse_serve_key, parse_bench_key):
        out = parser(key)
        if out is not None:
            return out
    return None


def safe_stem(key: str) -> str:
    """Key -> file-name stem: colon separators become ``__``; anything
    else path-unsafe is hashed away by :func:`_seg` at build time. A
    trailing short hash of the FULL key disambiguates the (theoretical)
    collision of two keys mapping to one sanitized stem."""
    body = key.replace(":", "__")
    body = "".join(c if (c.isalnum() or c in "._=+-") else "_" for c in body)
    return f"{body[:140]}-{hashlib.sha256(key.encode()).hexdigest()[:8]}"
