"""Canonical program-store keys: one grammar for every compiled-program
cache.

Three look-alike fingerprint-key builders grew independently — the
autotune plan fingerprints (``autotune/fingerprint.py``), the serve
engine's bucket-ladder keys, and the bench AOT file stems — and the
program store unifies their *compiled-program* halves here so store keys
cannot silently diverge again. The design rules are the plan cache's
(``autotune/fingerprint.py`` module doc):

* a key is a pure function of (problem shape, machine, code generation):
  same inputs in two processes MUST produce the same key — cross-restart
  and cross-process reuse both depend on it;
* the code generation is baked INTO the key (``code_hash`` for programs
  shaped by ``ops/`` + ``parallel/``, ``serve_code_hash`` for serving
  programs): a new code generation is a new key, so a stale entry can
  never answer for new code;
* the **aval signature** (shapes + dtypes of the example arguments) is
  part of the key: compiled executables are shape-rigid, and two
  problems that share a fingerprint bucket can still disagree on padded
  tile geometry.

Keys are colon-joined printable segments (safe as file-name stems after
:func:`safe_stem`); every builder has a matching parser and the pair is
round-trip tested (``tests/test_program_keys.py``).

This module deliberately imports neither jax nor the strategy code —
keys must be computable in subprocesses and offline tooling (same
discipline as ``autotune/fingerprint.py``; the only jax touch-point,
:func:`sig_for_args`, duck-types on ``shape``/``dtype``).
"""

from __future__ import annotations

import hashlib
import re

_SEG_RE = re.compile(r"^[A-Za-z0-9._=+-]+$")


def _seg(value) -> str:
    """One key segment: printable and colon-free, or content-hashed."""
    s = str(value)
    if _SEG_RE.match(s):
        return s
    return "h" + hashlib.sha256(s.encode()).hexdigest()[:12]


def sig_for_args(args) -> str:
    """Short stable hash of the argument aval signature (shapes +
    dtypes, structure-order). Works on jax arrays, numpy arrays and
    ShapeDtypeStructs — anything with ``shape`` and ``dtype``."""
    parts = []
    for a in args:
        shape = tuple(getattr(a, "shape", ()))
        dtype = str(getattr(a, "dtype", type(a).__name__))
        parts.append(f"{shape}{dtype}")
    return hashlib.sha256(";".join(parts).encode()).hexdigest()[:10]


# --------------------------------------------------------------------- #
# Plan-routed strategy programs (autotune Plan.instantiate)
# --------------------------------------------------------------------- #


def dist_segment(num_processes: int | None = None,
                 process_index: int | None = None) -> str:
    """The multi-controller key segment: ``dN.pK`` for worker ``K`` of
    an ``N``-process pod, ``""`` single-process.

    Single-process keys stay byte-identical to the PR 6–13 grammar (no
    trailing segment at all), so every existing store keeps hitting. On
    a pod both halves matter: the compiled program has GLOBAL semantics
    shaped by the process count (collectives span hosts), and
    ``serialize_executable`` payloads are per-process (each worker's
    executable binds its own addressable devices) — worker K of an
    N-pod must only ever warm-start from entries worker K of an N-pod
    wrote. Defaults resolve from :func:`dist.init.pod_info`.
    """
    if num_processes is None:
        from distributed_sddmm_tpu.dist.init import pod_info

        ctx = pod_info()
        num_processes, process_index = ctx.num_processes, ctx.process_index
    if not num_processes or int(num_processes) <= 1:
        return ""
    if process_index is None:
        # Defaulting the slot would hand every caller 'dN.p0' — the
        # cross-worker store aliasing this segment exists to prevent
        # (same guard as pod_info's NPROCS-without-PROC_ID rule).
        raise ValueError(
            "dist_segment: multi-process segment needs an explicit "
            "process_index"
        )
    return f"d{int(num_processes)}.p{int(process_index)}"


def parse_dist_segment(seg: str) -> dict | None:
    """``dN.pK`` -> ``{"num_processes", "process_index"}`` (None when
    the segment is not dist-shaped)."""
    m = re.match(r"^d(\d+)\.p(\d+)$", seg)
    if not m:
        return None
    return {"num_processes": int(m.group(1)),
            "process_index": int(m.group(2))}


def plan_program_key(
    fingerprint_key: str,
    op: str,
    sig: str,
    backend: str,
    code: str | None = None,
    dist: str | None = None,
) -> str:
    """Key for one compiled strategy program under an autotune plan.

    ``fingerprint_key`` is the plan fingerprint (problem + machine +
    code already hashed in); ``op`` names the strategy's program-cache
    key (op name, tile set, ablation mode); ``sig`` is
    :func:`sig_for_args` over the concrete call arguments. ``code``
    defaults to the live ``autotune.fingerprint.code_hash()`` — baked in
    even though the fingerprint already covers it, so a key parsed out
    of the store is self-describing about its generation. ``dist`` is
    the :func:`dist_segment` of the compiling worker — appended only
    when multi-process (single-process keys are byte-identical to the
    pre-pod grammar), so a pod worker's per-process executables never
    alias single-controller entries or another worker's.
    """
    if code is None:
        from distributed_sddmm_tpu.autotune.fingerprint import code_hash

        code = code_hash()
    key = ":".join(
        ("plan", _seg(fingerprint_key), _seg(op), _seg(sig),
         _seg(backend), _seg(code))
    )
    if dist:
        key += f":{_seg(dist)}"
    return key


def parse_plan_key(key: str) -> dict | None:
    parts = key.split(":")
    if len(parts) not in (6, 7) or parts[0] != "plan":
        return None
    out = dict(zip(
        ("family", "fingerprint_key", "op", "sig", "backend", "code_hash"),
        parts[:6],
    ))
    if len(parts) == 7:
        dist = parse_dist_segment(parts[6])
        if dist is None:
            return None
        out["dist"] = parts[6]
        out.update(dist)
    return out


# --------------------------------------------------------------------- #
# Serving bucket-ladder programs (serve/engine.py)
# --------------------------------------------------------------------- #


def serve_program_key(
    workload: str,
    batch_bucket: int,
    inner_bucket: int,
    r,
    backend: str,
    code: str | None = None,
    params: str | None = None,
    sig: str | None = None,
    variant: str | None = None,
    wire: str | None = None,
    cap: str | None = None,
    dist: str | None = None,
) -> str:
    """Cache key for one serving bucket cell — the grammar the engine
    has used since PR 5 (``serve:<workload>:b<bb>:i<ib>:r<R>:<backend>:
    <serve_code_hash>``), now owned here, with optional trailing
    segments the store appends: ``p<params>`` (workload constants the
    traced program bakes in — the fold-in top-k size and ridge, which
    change the executable without changing any argument shape),
    ``s<sig>`` (the aval signature, so a program compiled against one
    model's array shapes can never answer for another's) and
    ``v<variant>`` (the warm model's codegen kernel-variant id, PR 9 —
    a ladder warmed under one kernel specialization never answers for
    another; variant-less keys are byte-identical to the PR 5-8
    grammar, so existing stores keep hitting) and ``w<wire>`` (PR 15 —
    the warm model's realized wire-precision policy: a ladder compiled
    with bf16 collectives must never answer for the f32 wire or vice
    versa; None and "f32" append nothing, so default keys — and every
    pre-PR-15 store — stay byte-identical). ``dist`` is the
    :func:`dist_segment` of the compiling worker (PR 14) — serving
    executables are per-process exactly like plan programs, so a pod
    worker's ladder entries must never answer for another slot's;
    single-process keys append nothing and stay byte-identical.
    ``cap`` (PR 20, ``dynstruct/``) is the capacity-bucket segment
    (``c<caps>``) of a dynamic-structure workload: the traced program is
    sized to pow2 capacity rungs, not the exact structure, so the rungs
    — not the pattern — identify it. Static workloads pass None and
    append nothing (old keys byte-identical); a bucketed key can never
    alias an exact-build key because only dyn builds carry the
    segment."""
    if code is None:
        from distributed_sddmm_tpu.autotune.fingerprint import serve_code_hash

        code = serve_code_hash()
    key = (
        f"serve:{_seg(workload)}:b{int(batch_bucket)}:i{int(inner_bucket)}"
        f":r{_seg(r)}:{_seg(backend)}:{_seg(code)}"
    )
    if params:
        key += f":p{_seg(params)}"
    if sig:
        key += f":s{_seg(sig)}"
    if variant:
        key += f":v{_seg(variant)}"
    if wire and wire != "f32":
        key += f":w{_seg(wire)}"
    if cap:
        key += f":c{_seg(cap)}"
    if dist:
        key += f":{_seg(dist)}"
    return key


def parse_serve_key(key: str) -> dict | None:
    parts = key.split(":")
    if not (7 <= len(parts) <= 13) or parts[0] != "serve":
        return None
    if not (parts[2].startswith("b") and parts[3].startswith("i")
            and parts[4].startswith("r")):
        return None
    out = {
        "family": "serve",
        "workload": parts[1],
        "batch_bucket": int(parts[2][1:]),
        "inner_bucket": int(parts[3][1:]),
        "r": parts[4][1:],
        "backend": parts[5],
        "code_hash": parts[6],
    }
    for extra in parts[7:]:
        dist = parse_dist_segment(extra)
        if dist is not None:
            out["dist"] = extra
            out.update(dist)
        elif extra.startswith("p"):
            out["params"] = extra[1:]
        elif extra.startswith("s"):
            out["sig"] = extra[1:]
        elif extra.startswith("v"):
            out["variant"] = extra[1:]
        elif extra.startswith("w"):
            out["wire"] = extra[1:]
        elif extra.startswith("c"):
            out["cap"] = extra[1:]
        else:
            return None
    return out


# --------------------------------------------------------------------- #
# Bench AOT chain executables (bench/aot.py)
# --------------------------------------------------------------------- #


def bench_aot_key(stem: str, name: str, n: int, backend: str = "tpu") -> str:
    """Key for one serialized bench chain executable. ``stem`` is the
    config-describing cache-directory basename the offline compilers
    already derive (it embeds the code/knob hash — e.g.
    ``distgap_16_32_128_t5_<hash>``), ``name``/``n`` the program name
    and trip count that used to form the ``{name}_{n}.pkl`` file stem."""
    return ":".join(("bench", _seg(stem), _seg(name), str(int(n)),
                     _seg(backend)))


def parse_bench_key(key: str) -> dict | None:
    parts = key.split(":")
    if len(parts) != 5 or parts[0] != "bench":
        return None
    try:
        n = int(parts[3])
    except ValueError:
        return None
    return {"family": "bench", "stem": parts[1], "name": parts[2],
            "n": n, "backend": parts[4]}


# --------------------------------------------------------------------- #


def parse_key(key: str) -> dict | None:
    """Parse any store key; None when the grammar is unrecognized."""
    for parser in (parse_plan_key, parse_serve_key, parse_bench_key):
        out = parser(key)
        if out is not None:
            return out
    return None


def safe_stem(key: str) -> str:
    """Key -> file-name stem: colon separators become ``__``; anything
    else path-unsafe is hashed away by :func:`_seg` at build time. A
    trailing short hash of the FULL key disambiguates the (theoretical)
    collision of two keys mapping to one sanitized stem."""
    body = key.replace(":", "__")
    body = "".join(c if (c.isalnum() or c in "._=+-") else "_" for c in body)
    return f"{body[:140]}-{hashlib.sha256(key.encode()).hexdigest()[:8]}"
