"""Offline structural gate for banked kernel codegen (PR 9).

``test_overlap_gate.py``-style evidence: the banked fused program is
AOT-compiled for a REAL TPU topology (``jax.experimental.topologies``,
no chips needed — the ``artifacts/multichip_hlo`` retarget pattern) and
the scheduled HLO is scanned for the band-specialized kernel bodies:
each band launches its own Pallas kernel, so the compiled module must
contain one ``tpu_custom_call`` per band per ring-loop body where the
generic kernel has exactly one. This turns "the specialized bodies
exist" from a CPU-interpreter observation into a banked Mosaic compile
artifact — and, run at R=1024, banks the R >= 1024 Pallas compile point
(ADVICE.md item 2: the XLA/Pallas crossover claim previously had no
Pallas artifact at R >= 1024 at all).

Environment note (same as the overlap gate): on machines without TPU
instance metadata export ``TPU_SKIP_MDS_QUERY=1`` before first
jax/libtpu init or the topology lookup stalls in metadata retries.
"""

from __future__ import annotations

import json
import re

import jax

#: One Pallas launch in compiled TPU HLO.
_PALLAS_CALL = re.compile(r'custom_call_target="tpu_custom_call"')


def count_pallas_calls(hlo: str) -> int:
    """Pallas (Mosaic) launch sites in one compiled-HLO text."""
    return len(_PALLAS_CALL.findall(hlo))


def banked_hlo_report(
    topology_name: str = "v5e:2x4",
    log_m: int = 12,
    edge_factor: int = 4,
    R: int = 1024,
    c: int = 1,
    unroll: bool = False,
    output_file: str | None = None,
) -> dict:
    """Compile the banked AND generic fused programs for a TPU topology
    and report the per-module Pallas launch counts plus band facts.

    Default ``unroll=False`` compiles the rolled ring, so the counts
    read directly as launches per loop body: the banked module must
    carry one per band, the generic exactly one. Defaults pin the
    R=1024 regime (``rl``) so the banked compile doubles as the
    R >= 1024 Pallas compile point.
    """
    from jax.experimental import topologies

    from distributed_sddmm_tpu.autotune.fingerprint import Problem
    from distributed_sddmm_tpu.codegen.kernel import BankedPallasKernel
    from distributed_sddmm_tpu.codegen.variants import select_variant
    from distributed_sddmm_tpu.common import MatMode
    from distributed_sddmm_tpu.ops.pallas_kernels import PallasKernel
    from distributed_sddmm_tpu.parallel.dense_shift_15d import DenseShift15D
    from distributed_sddmm_tpu.parallel.mesh import GridSpec, make_grid
    from distributed_sddmm_tpu.utils.coo import HostCOO

    devices = jax.devices()
    topo = topologies.get_topology_desc(
        platform="tpu", topology_name=topology_name
    )
    if len(topo.devices) < len(devices):
        raise ValueError(
            f"topology {topology_name} has {len(topo.devices)} < "
            f"{len(devices)} chips"
        )

    S = HostCOO.rmat(log_m=log_m, edge_factor=edge_factor, seed=0)
    problem = Problem.from_coo(S, R=R)
    variant = select_variant(problem)

    def compile_for(kernel):
        # Construct on the live (CPU test) mesh — tile ingest needs real
        # buffers — then retarget program construction at the TPU
        # topology mesh and AOT-compile with ShapeDtypeStruct operands.
        alg = DenseShift15D(
            S, R=R, c=c, fusion_approach=2, kernel=kernel, unroll=unroll
        )
        vals = alg.like_s_values(1.0)
        args = (
            alg.dummy_initialize(MatMode.A),
            alg.dummy_initialize(MatMode.B),
            *alg._tile_args(alg.S_tiles, vals),
        )
        g = alg.grid
        tpu_grid = make_grid(g.nr, g.nc, g.nh, adjacency=g.adjacency,
                             devices=list(topo.devices)[: alg.p])
        alg.grid = GridSpec(mesh=tpu_grid.mesh, nr=g.nr, nc=g.nc, nh=g.nh,
                            adjacency=g.adjacency)
        alg._programs.clear()
        mesh = alg.grid.mesh

        def sds_like(x):
            return jax.ShapeDtypeStruct(
                x.shape, x.dtype,
                sharding=jax.sharding.NamedSharding(mesh, x.sharding.spec),
            )

        prog = alg._program("fused", use_st=False)
        hlo = prog.lower(*(sds_like(a) for a in args)).compile().as_text()
        return alg, hlo

    banked_kernel = BankedPallasKernel(
        variant, precision="bf16", interpret=False
    )
    alg_b, hlo_banked = compile_for(banked_kernel)
    bands = alg_b.S_tiles.blk_bands or ()
    alg_g, hlo_generic = compile_for(
        PallasKernel(precision="bf16", interpret=False)
    )

    record = {
        "experiment": "codegen-banked-hlo",
        "topology": topology_name,
        "p": alg_b.p,
        "c": c,
        "M": S.M,
        "nnz": S.nnz,
        "R": R,
        "regime": variant.variant_id.rsplit(".", 1)[-1],
        "variant": variant.variant_id,
        "unrolled": bool(unroll),
        "bands": [
            {"body": b.body, "bm": b.bm, "bn": b.bn,
             "chunks": b.c1 - b.c0, "group": b.group}
            for b in bands
        ],
        "pad_lanes_generic": alg_g.S_tiles.blk_pad_lanes,
        "pad_lanes_banked": alg_b.S_tiles.blk_pad_lanes,
        "pallas_calls_banked": count_pallas_calls(hlo_banked),
        "pallas_calls_generic": count_pallas_calls(hlo_generic),
        "is_scheduled": "is_scheduled=true" in hlo_banked,
    }
    if output_file:
        # non-atomic-ok: append-only record stream (the -o contract).
        with open(output_file, "a") as f:
            f.write(json.dumps(record) + "\n")
    return record
