"""Offline structural gate for banked kernel codegen (PR 9).

``test_overlap_gate.py``-style evidence: the banked fused program is
AOT-compiled for a REAL TPU topology (``jax.experimental.topologies``,
no chips needed — the ``artifacts/multichip_hlo`` retarget pattern) and
the scheduled HLO is scanned for the band-specialized kernel bodies:
each band launches its own Pallas kernel, so the compiled module must
contain one ``tpu_custom_call`` per band per ring-loop body where the
generic kernel has exactly one. This turns "the specialized bodies
exist" from a CPU-interpreter observation into a banked Mosaic compile
artifact — and, run at R=1024, banks the R >= 1024 Pallas compile point
(ADVICE.md item 2: the XLA/Pallas crossover claim previously had no
Pallas artifact at R >= 1024 at all).

Environment note (same as the overlap gate): on machines without TPU
instance metadata export ``TPU_SKIP_MDS_QUERY=1`` before first
jax/libtpu init or the topology lookup stalls in metadata retries.
"""

from __future__ import annotations

import json
import re

import jax

#: One Pallas launch in compiled TPU HLO.
_PALLAS_CALL = re.compile(r'custom_call_target="tpu_custom_call"')


def count_pallas_calls(hlo: str) -> int:
    """Pallas (Mosaic) launch sites in one compiled-HLO text."""
    return len(_PALLAS_CALL.findall(hlo))


def _topology(topology_name: str, min_chips: int):
    """AOT topology lookup with the chip-count check both gates share."""
    from jax.experimental import topologies

    topo = topologies.get_topology_desc(
        platform="tpu", topology_name=topology_name
    )
    if len(topo.devices) < min_chips:
        raise ValueError(
            f"topology {topology_name} has {len(topo.devices)} < "
            f"{min_chips} chips"
        )
    return topo


def _aot_compile_ops(alg, args, topo, ops) -> dict:
    """Retarget one live-mesh strategy at the AOT topology and compile
    the named program ops with ShapeDtypeStruct operands.

    The strategy is constructed on the live (CPU test) mesh — tile
    ingest needs real buffers — then its grid is swapped for a mesh
    over the topology's AOT devices and the program cache cleared so
    every op re-traces against the TPU mesh. Returns ``{op: hlo_text}``.
    """
    from distributed_sddmm_tpu.parallel.mesh import GridSpec, make_grid

    g = alg.grid
    tpu_grid = make_grid(g.nr, g.nc, g.nh, adjacency=g.adjacency,
                         devices=list(topo.devices)[: alg.p])
    alg.grid = GridSpec(mesh=tpu_grid.mesh, nr=g.nr, nc=g.nc, nh=g.nh,
                        adjacency=g.adjacency)
    alg._programs.clear()
    mesh = alg.grid.mesh

    def sds_like(x):
        return jax.ShapeDtypeStruct(
            x.shape, x.dtype,
            sharding=jax.sharding.NamedSharding(mesh, x.sharding.spec),
        )

    sds = [sds_like(a) for a in args]
    return {
        op: alg._program(op, use_st=False).lower(*sds).compile().as_text()
        for op in ops
    }


def banked_hlo_report(
    topology_name: str = "v5e:2x4",
    log_m: int = 12,
    edge_factor: int = 4,
    R: int = 1024,
    c: int = 1,
    unroll: bool = False,
    output_file: str | None = None,
) -> dict:
    """Compile the banked AND generic fused programs for a TPU topology
    and report the per-module Pallas launch counts plus band facts.

    Default ``unroll=False`` compiles the rolled ring, so the counts
    read directly as launches per loop body: the banked module must
    carry one per band, the generic exactly one. Defaults pin the
    R=1024 regime (``rl``) so the banked compile doubles as the
    R >= 1024 Pallas compile point.
    """
    from distributed_sddmm_tpu.autotune.fingerprint import Problem
    from distributed_sddmm_tpu.codegen.kernel import BankedPallasKernel
    from distributed_sddmm_tpu.codegen.variants import select_variant
    from distributed_sddmm_tpu.common import MatMode
    from distributed_sddmm_tpu.ops.pallas_kernels import PallasKernel
    from distributed_sddmm_tpu.parallel.dense_shift_15d import DenseShift15D
    from distributed_sddmm_tpu.utils.coo import HostCOO

    topo = _topology(topology_name, len(jax.devices()))

    S = HostCOO.rmat(log_m=log_m, edge_factor=edge_factor, seed=0)
    problem = Problem.from_coo(S, R=R)
    variant = select_variant(problem)

    def compile_for(kernel):
        alg = DenseShift15D(
            S, R=R, c=c, fusion_approach=2, kernel=kernel, unroll=unroll
        )
        vals = alg.like_s_values(1.0)
        args = (
            alg.dummy_initialize(MatMode.A),
            alg.dummy_initialize(MatMode.B),
            *alg._tile_args(alg.S_tiles, vals),
        )
        hlo = _aot_compile_ops(alg, args, topo, ("fused",))["fused"]
        return alg, hlo

    banked_kernel = BankedPallasKernel(
        variant, precision="bf16", interpret=False
    )
    alg_b, hlo_banked = compile_for(banked_kernel)
    bands = alg_b.S_tiles.blk_bands or ()
    alg_g, hlo_generic = compile_for(
        PallasKernel(precision="bf16", interpret=False)
    )

    record = {
        "experiment": "codegen-banked-hlo",
        "topology": topology_name,
        "p": alg_b.p,
        "c": c,
        "M": S.M,
        "nnz": S.nnz,
        "R": R,
        "regime": variant.variant_id.rsplit(".", 1)[-1],
        "variant": variant.variant_id,
        "unrolled": bool(unroll),
        "bands": [
            {"body": b.body, "bm": b.bm, "bn": b.bn,
             "chunks": b.c1 - b.c0, "group": b.group}
            for b in bands
        ],
        "pad_lanes_generic": alg_g.S_tiles.blk_pad_lanes,
        "pad_lanes_banked": alg_b.S_tiles.blk_pad_lanes,
        "pallas_calls_banked": count_pallas_calls(hlo_banked),
        "pallas_calls_generic": count_pallas_calls(hlo_generic),
        "is_scheduled": "is_scheduled=true" in hlo_banked,
    }
    if output_file:
        # non-atomic-ok: append-only record stream (the -o contract).
        with open(output_file, "a") as f:
            f.write(json.dumps(record) + "\n")
    return record


def attention_hlo_report(
    topology_name: str = "v5e:2x4",
    log_m: int = 11,
    edge_factor: int = 4,
    R: int = 128,
    p: int = 2,
    unroll: bool = False,
    output_file: str | None = None,
) -> dict:
    """Compile the banked fused-ATTENTION program for a TPU topology and
    report the per-module Pallas launch counts vs the plain fused pair.

    The attention module must carry the masked-softmax epilogue as REAL
    Mosaic launches fused into the one compiled program: with the rolled
    ring (``unroll=False``) the SDDMM and SpMM passes contribute one
    launch per band per loop body exactly like ``banked_hlo_report``'s
    pair, and the epilogue adds ``2 × n_tiles × n_bands`` launches (one
    streaming reduce + one normalize per tile per band) — so the count
    delta over the twopass pair module is a structural proof the
    epilogue compiled into the banked v5e module, not an interpreter
    artifact. The graph-derived (skewed R-mat) mask keeps banking live;
    ``p=2`` keeps the ring small so the module is cheap to compile.
    """
    from distributed_sddmm_tpu import masks
    from distributed_sddmm_tpu.autotune.fingerprint import Problem
    from distributed_sddmm_tpu.codegen.kernel import BankedPallasKernel
    from distributed_sddmm_tpu.codegen.variants import select_variant
    from distributed_sddmm_tpu.common import MatMode
    from distributed_sddmm_tpu.parallel.dense_shift_15d import DenseShift15D
    from distributed_sddmm_tpu.utils.coo import HostCOO

    topo = _topology(topology_name, p)

    S = masks.graph_mask(
        HostCOO.rmat(log_m=log_m, edge_factor=edge_factor, seed=0)
    )
    variant = select_variant(Problem.from_coo(S, R=R))
    kernel = BankedPallasKernel(variant, precision="bf16", interpret=False)

    alg = DenseShift15D(
        S, R=R, c=1, fusion_approach=1, kernel=kernel, unroll=unroll,
        devices=jax.devices()[:p],
    )
    vals = alg.like_s_values(1.0)
    args = (
        alg.dummy_initialize(MatMode.A),
        alg.dummy_initialize(MatMode.B),
        *alg._tile_args(alg.S_tiles, vals),
    )
    hlos = _aot_compile_ops(alg, args, topo, ("attn", "fused_twopass"))
    hlo_attn = hlos["attn"]
    hlo_pair = hlos["fused_twopass"]
    bands = alg.S_tiles.blk_bands or ()
    n_tiles = alg.S_tiles.n_tiles
    attn_calls = count_pallas_calls(hlo_attn)
    pair_calls = count_pallas_calls(hlo_pair)

    record = {
        "experiment": "attention-hlo",
        "topology": topology_name,
        "p": alg.p,
        "M": S.M,
        "nnz": S.nnz,
        "R": R,
        "mask": "graph",
        "variant": variant.variant_id,
        "unrolled": bool(unroll),
        "n_tiles": n_tiles,
        "bands": [
            {"body": b.body, "bm": b.bm, "bn": b.bn,
             "chunks": b.c1 - b.c0, "group": b.group}
            for b in bands
        ],
        "pallas_calls_attn": attn_calls,
        "pallas_calls_pair": pair_calls,
        "epilogue_calls": attn_calls - pair_calls,
        "epilogue_calls_expected": 2 * n_tiles * len(bands),
        "is_scheduled": "is_scheduled=true" in hlo_attn,
    }
    if output_file:
        # non-atomic-ok: append-only record stream (the -o contract).
        with open(output_file, "a") as f:
            f.write(json.dumps(record) + "\n")
    return record
