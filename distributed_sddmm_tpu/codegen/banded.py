"""Row-banked chunk-list construction (the banked half of codegen).

The generic encoding (``ops/blocked.build_blocked``) packs every
(row block, col block) pair's nonzeros into 128-lane chunks — padding is
bounded per chunk, but a SHORT row scattered over many column blocks
drags one mostly-empty chunk per touched pair. Banking partitions each
tile's rows by nnz/row and builds one chunk list per band with
band-specific geometry: the short-row band uses a single full-width
column block (one chunk-rounding per row block, however many column
blocks its rows touch) while heavy rows keep the generic blocked walk.

The bands CONCATENATE into one combined chunk list per bucket, so the
flat value layout / ``scatter_index`` contract of ``parallel/sharding``
is unchanged — value vectors serve the XLA and banked-Pallas kernel
paths with zero relayout, exactly as for the generic encoding. Each
band is a contiguous chunk range ``[c0, c1)`` that the banked kernel
slices STATICALLY and launches with its own geometry and body
(``codegen/kernel.py``).

Accumulator correctness across bands: every band's chunk list covers
every row block of the shared padded frame (``build_blocked``
guarantees >= 1 chunk per (bucket, row block), zero + flush flags
included), so each band's launch produces a full-frame partial with
exact zeros outside its own rows; partials combine by addition
(``x + 0.0 == x`` bitwise for the nonzero rows).
"""

from __future__ import annotations

import dataclasses

import numpy as np

from distributed_sddmm_tpu.ops.blocked import (
    CHUNK, BlockedMeta, build_blocked, pad_chunk_count, pick_block,
    pad_frame, unpack_meta,
)
from distributed_sddmm_tpu.codegen.variants import KernelVariant
from distributed_sddmm_tpu.utils import buckets

#: Density target for auto-width (``block_cols=0``) bands: widen the
#: band's column blocks (power-of-two merges of generic blocks, up to
#: full tile width) until the band averages at least this many full
#: chunks per touched (bucket, row block, col block) pair — the point
#: where per-pair chunk rounding stops dominating the band's lanes.
DENSITY_TARGET_CHUNKS = 2


@dataclasses.dataclass(frozen=True)
class Band:
    """One resolved band: a static chunk range + geometry + body.

    Hashable on purpose — band tuples ride inside
    :class:`~distributed_sddmm_tpu.codegen.kernel.BankedTile` as static
    pytree metadata and inside jit static arguments.
    """

    c0: int            # first chunk of this band in the combined list
    c1: int            # one past the last chunk
    bm: int
    bn: int
    gr_blocks: int
    gc_blocks: int
    group: int
    body: str          # "walk" | "batched" | "single" (resolved)


@dataclasses.dataclass(frozen=True)
class BandedMeta:
    """Combined banked encoding; a drop-in for ``BlockedMeta`` plus the
    per-band descriptors. Field conventions match ``BlockedMeta`` (the
    combined arrays ARE band-concatenated ``BlockedMeta`` arrays)."""

    lr: np.ndarray         # [NB, C_tot, CHUNK] int32
    lc: np.ndarray         # [NB, C_tot, CHUNK] int32
    meta: np.ndarray       # [NB, C_tot] int32 (gr/gc relative to its band)
    host_to_chunk: np.ndarray
    pad_lane: np.ndarray   # [NB, C_tot, CHUNK] bool
    bands: tuple[Band, ...]
    rows_pad: int          # shared padded tile frame (all bands agree)
    cols_pad: int
    n_chunks: int          # C_tot

    # --- BlockedMeta-compatible geometry (the LAST surviving band's
    # blocks over the shared frame — the heavy band when it has
    # nonzeros — so ``gr_blocks * bm == rows_pad`` still holds for
    # every consumer of ``blk_geom``). ---

    @property
    def bm(self) -> int:
        return self.bands[-1].bm

    @property
    def bn(self) -> int:
        return self.bands[-1].bn

    @property
    def gr_blocks(self) -> int:
        return self.rows_pad // self.bm

    @property
    def gc_blocks(self) -> int:
        return self.cols_pad // self.bn

    @property
    def group(self) -> int:
        return self.bands[-1].group

    def global_rows(self) -> np.ndarray:
        """Tile-frame row index per chunk lane (pad lanes -> 0), band by
        band — each band's meta words decode against its own block
        size."""
        out = np.zeros(self.lr.shape, dtype=np.int32)
        for band in self.bands:
            gr, _, _, _ = unpack_meta(self.meta[:, band.c0:band.c1])
            rows = gr[:, :, None] * band.bm + self.lr[:, band.c0:band.c1]
            out[:, band.c0:band.c1] = rows
        return np.where(self.pad_lane, 0, out).astype(np.int32)

    def global_cols(self) -> np.ndarray:
        out = np.zeros(self.lc.shape, dtype=np.int32)
        for band in self.bands:
            _, gc, _, _ = unpack_meta(self.meta[:, band.c0:band.c1])
            cols = gc[:, :, None] * band.bn + self.lc[:, band.c0:band.c1]
            out[:, band.c0:band.c1] = cols
        return np.where(self.pad_lane, 0, out).astype(np.int32)


# THE counted waste metric the banked variants exist to shrink. Owned
# by ops/blocked.py (they measure any encoding, generic included, and
# core tiling must not depend on this specialization package);
# re-exported here because codegen is the metric's consumer of record.
from distributed_sddmm_tpu.ops.blocked import (  # noqa: F401
    padded_lane_count, padded_lane_frac,
)


def _single_step_provable(bmeta: BlockedMeta) -> bool:
    """True when EVERY (bucket, row block) group of the band spans
    exactly one ``group``-chunk grid step AND no trailing bucket-pad
    chunks exist — the precondition for the conditional-free
    direct-write body: each step then zeroes-and-flushes trivially,
    and an unconditional ``out_ref[:] = contribution`` per step can
    never overwrite a flushed block with a pad step's zeros.

    Because every group is a multiple of ``group`` chunks with at least
    one, ``C == gr_blocks * group`` forces every bucket to exactly
    ``group`` chunks per group with zero trailing pads."""
    return bmeta.n_chunks == bmeta.gr_blocks * bmeta.group


def build_banded(
    n_buckets: int,
    bucket: np.ndarray,
    local_r: np.ndarray,
    local_c: np.ndarray,
    tile_rows: int,
    tile_cols: int,
    variant: KernelVariant,
) -> BandedMeta:
    """Build the banked encoding for one variant.

    Same contract as :func:`ops.blocked.build_blocked` (same argument
    meanings, same flat-layout guarantees via ``host_to_chunk``), with
    rows partitioned into the variant's nnz/row bands first. Bands that
    receive no nonzeros are dropped (their chunk lists would be pure
    padding) — including the heavy band when every row is short; only a
    zero-nnz tile keeps the heavy band alone (so the encoding still
    zeroes every block). The LAST SURVIVING band supplies the
    ``BlockedMeta``-compat geometry (:class:`BandedMeta` properties).
    """
    bucket = np.asarray(bucket, dtype=np.int64)
    local_r = np.asarray(local_r, dtype=np.int64)
    local_c = np.asarray(local_c, dtype=np.int64)
    nnz = local_r.size
    specs = variant.bands

    # nnz per (bucket, tile-local row), spread back per nonzero.
    if nnz:
        key = bucket * max(tile_rows, 1) + local_r
        _, inv, cnt = np.unique(key, return_inverse=True, return_counts=True)
        row_nnz = cnt[inv]
    else:
        cnt = np.zeros(0, dtype=np.int64)
        row_nnz = np.zeros(0, dtype=np.int64)

    band_of = np.full(nnz, len(specs) - 1, dtype=np.int64)
    unassigned = np.ones(nnz, dtype=bool)
    for i, spec in enumerate(specs):
        if spec.npr_max is None:
            continue
        m = unassigned & (row_nnz <= spec.npr_max)
        band_of[m] = i
        unassigned &= ~m

    # Structured-mask degeneration guard: banding pays by splitting a
    # SKEWED degree distribution (power-law R-mat rows) so the short-row
    # majority stops paying one mostly-empty chunk per touched column
    # block. A near-UNIFORM distribution — sliding-window and other
    # structured attention masks, where only edge rows dip below the
    # interior degree — can STRADDLE a pow2 band threshold and split
    # near-identical rows across two full-frame chunk lists: double the
    # per-row-block chunk rounding for zero density win. When the max
    # populated row degree sits within 2x of the median (one octave of
    # the shared pow2 ladder — no band boundary separates meaningfully
    # different populations) AND the assignment actually split, collapse
    # every row into the band holding the most nonzeros: one chunk list
    # with that band's (density-targeted or generic) geometry instead of
    # a pathological split (ROADMAP item 5's "degenerate gracefully").
    # A uniform population that already lands in ONE band — e.g. all-
    # short degree-1 rows, where full-width banding is a real win — is
    # untouched; the realized band tuple (and its program-key digest)
    # honestly reports whatever was built.
    if (
        len(specs) > 1
        and cnt.size
        and cnt.max() <= 2 * np.median(cnt)
    ):
        per_band = np.bincount(band_of, minlength=len(specs))
        if (per_band > 0).sum() > 1:
            band_of[:] = int(per_band.argmax())

    # Drop empty bands (their chunk lists would be pure padding — one
    # pad chunk per row block per bucket); a zero-nnz tile set keeps
    # the heavy band alone so the encoding still zeroes every block.
    live = [i for i in range(len(specs)) if (band_of == i).any()]
    if not live:
        live = [len(specs) - 1]
    lut = np.full(len(specs), len(live) - 1, dtype=np.int64)
    lut[live] = np.arange(len(live))
    band_of = lut[band_of]
    specs = tuple(specs[i] for i in live)

    # Shared padded frame: every band's blocks must tile the SAME frame
    # (dense operands are prepped once per program). Row blocks are
    # powers of two, so padding to the largest makes every smaller one
    # divide evenly. Auto-width (block_cols=0) bands resolve against the
    # fixed bands' floor: their width is a MERGE of floor blocks chosen
    # from the band's actual nonzero density — constrained to widths
    # that tile cols_pad EXACTLY (halve the block count while it stays
    # even, else jump to one full-width block), because gcb_full =
    # cols_pad/bn_floor can be any integer and a non-divisor width
    # would give the band a different implied frame than the one the
    # dense operands are prepped to.
    from distributed_sddmm_tpu.ops import blocked as blocked_mod

    bms = [pick_block(tile_rows, s.block_rows) for s in specs]
    rows_pad = pad_frame(max(tile_rows, 1), max(bms))
    fixed = [pick_block(tile_cols, s.block_cols) for s in specs if s.block_cols]
    bn_floor = max(fixed) if fixed else pick_block(
        tile_cols, blocked_mod.DEFAULT_BLOCK_COLS
    )
    cols_pad = pad_frame(max(tile_cols, 1), bn_floor)
    gcb_full = cols_pad // bn_floor
    bns = []
    for i, s in enumerate(specs):
        if s.block_cols:
            bns.append(pick_block(tile_cols, s.block_cols))
            continue
        band_nnz = int((band_of == i).sum())
        grb = rows_pad // bms[i]
        gcb = gcb_full
        max_bn = s.max_block_cols or cols_pad
        while gcb > 1 and band_nnz < (
            n_buckets * grb * gcb * DENSITY_TARGET_CHUNKS * CHUNK
        ):
            nxt = gcb // 2 if gcb % 2 == 0 else 1
            if cols_pad // nxt > max_bn:
                break  # the VMEM cap (BandSpec.max_block_cols)
            gcb = nxt
        bns.append(cols_pad // gcb)

    parts: list[tuple[BlockedMeta, str, np.ndarray]] = []
    for i, spec in enumerate(specs):
        m = band_of == i
        bmeta = build_blocked(
            n_buckets, bucket[m], local_r[m], local_c[m],
            rows_pad, cols_pad,
            block_rows=bms[i], block_cols=bns[i], group=spec.group,
        )
        # Dyn-capacity builds (PR 20): pad each band's chunk count to a
        # pow2 rung BEFORE concatenation, so the Band (c0, c1) offsets —
        # static metadata in the traced program — are quantized and
        # survive pattern churn within the rung. Body resolution runs on
        # the padded meta: rung padding adds chunks per group, so
        # single-step is only provable against the realized count.
        cap = buckets.dyn_rung(bmeta.n_chunks, multiple=bmeta.group)
        if cap is not None and cap > bmeta.n_chunks:
            bmeta = pad_chunk_count(bmeta, cap)
        body = spec.body
        if body in ("batched", "single"):
            body = "single" if _single_step_provable(bmeta) else "batched"
        parts.append((bmeta, body, np.where(m)[0]))

    C_tot = sum(p[0].n_chunks for p in parts)
    host_to_chunk = np.empty(nnz, dtype=np.int64)
    bands: list[Band] = []
    c_off = 0
    for bmeta, body, idx in parts:
        C_k = bmeta.n_chunks
        b = bmeta.host_to_chunk // (C_k * CHUNK)
        within = bmeta.host_to_chunk % (C_k * CHUNK)
        host_to_chunk[idx] = b * (C_tot * CHUNK) + c_off * CHUNK + within
        bands.append(Band(
            c0=c_off, c1=c_off + C_k,
            bm=bmeta.bm, bn=bmeta.bn,
            gr_blocks=bmeta.gr_blocks, gc_blocks=bmeta.gc_blocks,
            group=bmeta.group, body=body,
        ))
        c_off += C_k

    return BandedMeta(
        lr=np.concatenate([p[0].lr for p in parts], axis=1),
        lc=np.concatenate([p[0].lc for p in parts], axis=1),
        meta=np.concatenate([p[0].meta for p in parts], axis=1),
        host_to_chunk=host_to_chunk,
        pad_lane=np.concatenate([p[0].pad_lane for p in parts], axis=1),
        bands=tuple(bands),
        rows_pad=rows_pad,
        cols_pad=cols_pad,
        n_chunks=C_tot,
    )
