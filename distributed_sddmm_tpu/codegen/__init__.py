"""Fingerprint-keyed specialized Pallas kernel codegen (PR 9).

The generic Pallas tile kernel (``ops/pallas_kernels.py``) is
one-shape-fits-all: one chunk geometry and one kernel body regardless of
shape, nnz/row skew, R, or dtype. JITSPMM and "Sparse GPU Kernels for
Deep Learning" (PAPERS.md) both show large wins from per-problem code
generation; this package is that idea applied to the autotune
fingerprint: ``get_plan()`` already knows (shape, npr_bucket, R, dtype),
so the fingerprint becomes the codegen key and each problem class gets a
specialized kernel variant instead of the generic one.

* ``codegen.variants`` — the variant space: row-band thresholds derived
  from the shared npr bucketing (``utils/buckets.py``), R-regime tile
  geometry (small-R / headline / R>=1024), and per-band kernel-body
  styles. Variant ids are stable, self-describing strings
  (``v1.rb<thr>.<regime>``) that round-trip through plan records and
  program-store keys.
* ``codegen.banded`` — row-banked chunk-list construction: each tile's
  rows are partitioned into nnz/row bands and one chunk list is built
  per band, so short rows stop paying long-row padding inside 128-lane
  chunks (measured by the counted padded-lane metric).
* ``codegen.kernel`` — :class:`BankedPallasKernel`: the drop-in
  ``LocalKernel`` that runs one specialized Pallas launch per band,
  with the band's body chosen at trace time in pure Python (no runtime
  branching inside any kernel).
* ``codegen.hlo`` — the offline structural gate: AOT-compile a banked
  program for a real TPU topology and assert the band-specialized
  bodies are present in the scheduled HLO (one ``tpu_custom_call`` per
  band per ring step), banking the R>=1024 compile point.

Variants register as autotune candidates (``autotune/candidates.py``),
are pruned by the cost model like every other candidate, compile through
the PR-6 ProgramStore with the variant id in the program key, and report
their variant through bench records, the runstore index and /metrics.
"""

from distributed_sddmm_tpu.codegen.variants import (  # noqa: F401
    BandSpec,
    KernelVariant,
    select_variant,
    variant_cost_factor,
    variant_from_id,
    variant_ids_for,
)
from distributed_sddmm_tpu.codegen.banded import (  # noqa: F401
    Band,
    BandedMeta,
    build_banded,
    padded_lane_count,
)
from distributed_sddmm_tpu.codegen.kernel import (  # noqa: F401
    BankedPallasKernel,
    BankedTile,
    make_banked_kernel,
)
