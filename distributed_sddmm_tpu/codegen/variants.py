"""The kernel-variant space and its fingerprint-keyed selector.

A :class:`KernelVariant` is everything the banked builder and kernel
need that the generic path does not carry: the nnz/row band split
(which rows go into the short-row chunk lists) and per-band chunk
geometry + body style. Variants are PURE FUNCTIONS of the autotune
fingerprint terms (npr_bucket, R, dtype) — two processes selecting for
the same problem MUST produce the same variant, for the same reason
fingerprints must agree (``autotune/fingerprint.py`` module doc): plan
records, program-store keys and bench records all carry the variant id
and must mean the same thing everywhere.

The id grammar is ``v1.rb<thr>.<regime>``:

* ``v1`` — variant-generation version. Any change to the geometry this
  module derives from an id MUST bump it: the id is baked into
  program-store keys, and a stale generation must miss-and-recompile,
  never alias (``codegen/`` is also part of ``code_hash`` for the same
  reason — belt and braces).
* ``rb<thr>`` — the short-row band threshold: rows with nnz <= thr go
  to the full-width short-row band. ``rb0`` = no banding (pure
  R-regime tiling specialization).
* ``<regime>`` — the R tiling regime: ``rs`` (R <= 64), ``rm`` (the
  headline 128-512 band), ``rl`` (R >= 1024, VMEM-bounded blocks).

Selection derives the threshold from the SHARED npr bucketing
(``utils/buckets.pow2_bucket``) so codegen bands exactly where the
fingerprint buckets.
"""

from __future__ import annotations

import dataclasses
import re

from distributed_sddmm_tpu.utils.buckets import pow2_bucket

#: Bump on ANY change to the geometry derived from a variant id (see
#: module doc — ids live inside program-store keys).
VARIANT_VERSION = 1

#: R-regime tile geometry: (heavy-band block_rows, block_cols, group).
#: ``rs``/``rm`` keep the measured headline blocks (KERNELS_TPU.jsonl:
#: (512, 512) wins at R=128); ``rl`` halves both so the [R, bm] f32
#: accumulator and dense windows stay VMEM-resident at R >= 1024
#: (512x1024 f32 = 2 MiB per operand before double buffering).
_REGIMES = {
    "rs": (512, 512, 4),
    "rm": (512, 512, 4),
    "rl": (256, 256, 2),
}

#: Widest column block an auto-width band may merge up to, per regime
#: (absolute lanes). Bounds the banked kernel's [R, bn] f32 dense
#: window to ~4 MiB so it stays VMEM-resident with double buffering —
#: unbounded merging on a full-width tile (SparseShift15D tiles carry
#: tile_cols = N_pad) would otherwise emit windows Mosaic cannot fit:
#: rs assumes R <= 64, rm R <= 512, rl R ~ 1024-2048.
_MAX_BAND_COLS = {
    "rs": 16384,
    "rm": 2048,
    "rl": 512,
}


def r_regime(R: int) -> str:
    """The R tiling regime name for an inner dimension."""
    if R <= 64:
        return "rs"
    if R < 1024:
        return "rm"
    return "rl"


@dataclasses.dataclass(frozen=True)
class BandSpec:
    """One row band's chunk-list geometry and kernel-body style.

    ``npr_max`` — rows with nnz <= npr_max belong to this band (None =
    the residual heavy band). ``block_cols=0`` means DENSITY-TARGETED
    width: the builder widens this band's column blocks (merging
    generic blocks, power-of-two steps up to full tile width) until the
    band's nonzeros average at least ~2 full chunks per touched
    (row block, col block) pair — short rows then stop paying one
    mostly-empty 128-lane chunk per touched column block. ``body`` is
    the requested kernel-body style; the builder may UPGRADE
    ``batched`` to ``single`` when the built metadata proves every
    row-block group spans exactly one grid step (same arithmetic, no
    scalar conditionals) — see ``codegen/banded.py``.
    """

    npr_max: int | None
    block_rows: int
    block_cols: int
    group: int
    body: str  # "walk" | "batched" | "single"
    #: Cap (absolute lanes) on the density-targeted width of an
    #: auto-width band — the VMEM bound (``_MAX_BAND_COLS``). 0 = fixed
    #: width, no merging. Derived from the variant id's regime, so id
    #: round-trips reconstruct it deterministically.
    max_block_cols: int = 0


@dataclasses.dataclass(frozen=True)
class KernelVariant:
    """A fully resolved specialization: id + band specs."""

    variant_id: str
    bands: tuple[BandSpec, ...]

    @property
    def banked(self) -> bool:
        return len(self.bands) > 1


def _bands_for(thr: int, regime: str) -> tuple[BandSpec, ...]:
    bm, bn, group = _REGIMES[regime]
    heavy = BandSpec(npr_max=None, block_rows=bm, block_cols=bn,
                     group=group, body="walk")
    if thr <= 0:
        return (heavy,)
    # Short band (rows at/below the fingerprint's npr bucket) and a mid
    # band one octave ladder up (<= 8x): both density-targeted
    # (block_cols=0), so each pays ~one chunk rounding per row block
    # instead of one per touched column block; group=1 avoids
    # deficit-pad chunks in sparse row blocks. Truly heavy rows keep the
    # measured headline geometry — their pairs are dense already, and
    # widening their gather windows would trade MXU work for nothing.
    # The short band requests the batched (lane-concatenated) body; the
    # builder upgrades it to the conditional-free single-step body when
    # provable. The mid band keeps the accumulator walk.
    cap = _MAX_BAND_COLS[regime]
    short = BandSpec(npr_max=thr, block_rows=bm, block_cols=0,
                     group=1, body="batched", max_block_cols=cap)
    mid = BandSpec(npr_max=8 * thr, block_rows=bm, block_cols=0,
                   group=1, body="walk", max_block_cols=cap)
    return (short, mid, heavy)


_ID_RE = re.compile(r"^v(\d+)\.rb(\d+)\.(rs|rm|rl)$")


def variant_from_id(variant_id: str) -> KernelVariant:
    """Reconstruct the variant a stable id names (plan records and
    program keys carry only the id). Unknown generations raise — a
    caller holding a ``v2`` id against ``v1`` code must fall back to
    generic, not guess geometry."""
    m = _ID_RE.match(variant_id)
    if not m:
        raise ValueError(f"unparseable kernel variant id {variant_id!r}")
    version, thr, regime = int(m.group(1)), int(m.group(2)), m.group(3)
    if version != VARIANT_VERSION:
        raise ValueError(
            f"kernel variant generation v{version} != current "
            f"v{VARIANT_VERSION} ({variant_id!r})"
        )
    return KernelVariant(
        variant_id=variant_id, bands=_bands_for(thr, regime)
    )


def select_variant(problem) -> KernelVariant:
    """The specialized variant for one autotune ``Problem``.

    The short-band threshold is the problem's npr bucket (the SAME
    power-of-two rounding the fingerprint uses): rows at or below the
    bucketed mean are "short" — in skewed (R-mat) degree distributions
    that is most rows, which is exactly the population paying the
    generic geometry's chunk-rounding tax. Very heavy buckets
    (npr_bucket >= 128) stop banding (rows fill chunks on their own)
    and keep only the R-regime tiling specialization.
    """
    thr = pow2_bucket(problem.nnz_per_row)
    if thr >= 128:
        thr = 0
    regime = r_regime(problem.R)
    vid = f"v{VARIANT_VERSION}.rb{thr}.{regime}"
    return KernelVariant(variant_id=vid, bands=_bands_for(thr, regime))


def variant_ids_for(problem) -> tuple[str, ...]:
    """Variant ids worth registering as autotune candidates for one
    problem (currently the single fingerprint-selected variant; the
    cost model and measured trials arbitrate against the generic
    kernel like any other candidate).

    A non-banked ``rs``/``rm`` variant is geometry-identical to the
    generic kernel (``_REGIMES`` keeps the measured headline blocks),
    so registering it would measure the same configuration twice and
    split byte-identical runs across gate baselines — skip it. The
    non-banked ``rl`` variant stays: its halved blocks are a real
    specialization."""
    v = select_variant(problem)
    if not v.banked and not v.variant_id.endswith(".rl"):
        return ()
    return (v.variant_id,)


def variant_cost_factor(problem, variant_id: str) -> float:
    """First-order multiplicative adjustment on the analytic pair time
    for a variant candidate, mirroring how the chunked XLA kernel is
    charged a 1.1x overhead: the model's flops term assumes zero
    padding, so the variant's relative worth is the ratio of estimated
    padded-lane overheads. Coarse by design — it orders what to
    MEASURE first; trials are the arbiter.
    """
    try:
        variant = variant_from_id(variant_id)
    except ValueError:
        return 1.0
    if not variant.banked:
        return 1.0
    waste_g = estimated_pad_frac(problem, banked=False)
    waste_b = estimated_pad_frac(problem, banked=True)
    factor = (1.0 + waste_b) / (1.0 + waste_g)
    return min(max(factor, 0.6), 1.1)


def estimated_pad_frac(problem, banked: bool) -> float:
    """Crude expected pad-lanes-per-real-lane for the generic vs banked
    encodings: every touched (row block, col block) pair rounds its
    chunk list up to CHUNK lanes (~CHUNK/2 expected waste); banking
    collapses the short rows' column-block dimension, leaving ~one
    rounding per row block."""
    from distributed_sddmm_tpu.ops import blocked

    bm = blocked.DEFAULT_BLOCK_ROWS
    bn = blocked.DEFAULT_BLOCK_COLS
    grb = max(-(-problem.M // bm), 1)
    gcb = max(-(-problem.N // bn), 1)
    nnz = max(problem.nnz, 1)
    if banked:
        # Density-targeted bands hold ~target chunks per touched pair;
        # the residual heavy rows' pairs are dense. ~a few roundings
        # per row block survive.
        pairs = grb * 6
    else:
        # Expected touched pairs under a uniform scatter, capped by nnz.
        cells = grb * gcb
        import math

        pairs = cells * (1.0 - math.exp(-nnz / cells))
    pairs = min(pairs, nnz)
    return (pairs * blocked.CHUNK / 2.0) / nnz
