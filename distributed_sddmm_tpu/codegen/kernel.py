"""The banked Pallas kernel: one specialized launch per row band.

:class:`BankedPallasKernel` is a drop-in for
:class:`~distributed_sddmm_tpu.ops.pallas_kernels.PallasKernel` — same
flat protocol, same tile-level entry points — that consumes the banked
encoding (``codegen/banded.py``) when the tile set carries it. Each
band is a STATIC chunk range with its own geometry and body style, so
the per-band specialization is pure-Python trace-time dispatch: the
emitted program contains one Pallas launch per band (visible as one
``tpu_custom_call`` each in compiled HLO — what the structural gate
counts) and no runtime branching inside any kernel.

Per-band numerics: the SDDMM mid values are per-nonzero (band chunk
ranges concatenate back into the flat layout); SpMM/fused dense
partials are full-frame per band (every band's chunk list zeroes and
flushes every row block) and combine by addition — each output row has
real contributions in exactly one band, zeros elsewhere.

When handed a plain ``BlockedTile`` (tile sets built without banding —
the replicated 2.5D layout, degenerate block grids), every entry point
falls through to the generic superclass path, so the banked kernel is
safe to bind anywhere the generic one is.
"""

from __future__ import annotations

import dataclasses

import jax
import jax.numpy as jnp

from distributed_sddmm_tpu.codegen.banded import Band
from distributed_sddmm_tpu.codegen.variants import (
    KernelVariant, variant_from_id,
)
from distributed_sddmm_tpu.ops.kernels import attn_merge_stats
from distributed_sddmm_tpu.ops.pallas_kernels import (
    PallasKernel, _attn_call, _fused_op, _sddmm_op, _spmm_op,
)


@jax.tree_util.register_dataclass
@dataclasses.dataclass
class BankedTile:
    """Per-(device, tile) banked chunk-list view.

    The three arrays are the COMBINED (band-concatenated) chunk list —
    the same arrays a :class:`BlockedTile` would hold — and ``bands``
    carries the static per-band ranges/geometry the kernel slices by.
    """

    lr: jax.Array        # [C_tot, CHUNK] int32
    lc: jax.Array        # [C_tot, CHUNK] int32
    meta: jax.Array      # [C_tot] int32 (gr/gc relative to each band)
    bands: tuple = dataclasses.field(
        metadata=dict(static=True), default=()
    )  # tuple[Band, ...]
    rows_pad: int = dataclasses.field(metadata=dict(static=True), default=0)
    cols_pad: int = dataclasses.field(metadata=dict(static=True), default=0)

    @property
    def n_chunks(self) -> int:
        return self.lr.shape[0]


class BankedPallasKernel(PallasKernel):
    """Fingerprint-specialized Pallas kernel (one launch per row band).

    ``variant`` is a :class:`~distributed_sddmm_tpu.codegen.variants.
    KernelVariant` or its stable id string; the id is what plan
    records, program keys and bench records carry.
    """

    def __init__(
        self,
        variant: KernelVariant | str,
        precision: str | None = None,
        interpret: bool | None = None,
        scatter_form: str | None = None,
        batch_step: bool | None = None,
    ):
        super().__init__(
            precision=precision, interpret=interpret,
            scatter_form=scatter_form, batch_step=batch_step,
        )
        if isinstance(variant, str):
            variant = variant_from_id(variant)
        self.variant = variant
        self.variant_id = variant.variant_id

    # ------------------------------------------------------------------ #
    # Banded tile-level entry points
    # ------------------------------------------------------------------ #

    def _band_geom(self, band: Band) -> tuple:
        batch = band.body in ("batched", "single")
        single = band.body == "single"
        return (
            band.bm, band.bn, band.gr_blocks, band.gc_blocks, band.group,
            self.interpret, self.scatter_form, batch, single,
        )

    def _band_slices(self, blk: BankedTile, band: Band):
        return (
            blk.meta[band.c0:band.c1],
            blk.lr[band.c0:band.c1],
            blk.lc[band.c0:band.c1],
        )

    def sddmm_tile_t(self, blk, vals, at, bt, out_dtype):
        if not isinstance(blk, BankedTile):
            return super().sddmm_tile_t(blk, vals, at, bt, out_dtype)
        sv = self._chunk_vals(blk, vals)
        mids = []
        for band in blk.bands:
            meta, lr, lc = self._band_slices(blk, band)
            mid = _sddmm_op(
                self._band_geom(band), meta, lr, lc,
                sv[band.c0:band.c1], at, bt,
            )
            mids.append(mid.reshape(-1))
        return jnp.concatenate(mids).astype(out_dtype)

    def spmm_tile_t(self, blk, vals, bt):
        if not isinstance(blk, BankedTile):
            return super().spmm_tile_t(blk, vals, bt)
        sv = self._chunk_vals(blk, vals)
        outT = None
        for band in blk.bands:
            meta, lr, lc = self._band_slices(blk, band)
            o = _spmm_op(
                self._band_geom(band), meta, lr, lc,
                sv[band.c0:band.c1], bt,
            )
            outT = o if outT is None else outT + o
        return outT

    def fused_tile_t(self, blk, vals, at, bt, out_dtype):
        if not isinstance(blk, BankedTile):
            return super().fused_tile_t(blk, vals, at, bt, out_dtype)
        sv = self._chunk_vals(blk, vals)
        outT, mids = None, []
        for band in blk.bands:
            meta, lr, lc = self._band_slices(blk, band)
            o, mid = _fused_op(
                self._band_geom(band), meta, lr, lc,
                sv[band.c0:band.c1], at, bt,
            )
            outT = o if outT is None else outT + o
            mids.append(mid.reshape(-1))
        return outT, jnp.concatenate(mids).astype(out_dtype)

    # -------------- masked-softmax attention epilogue ----------------- #
    #
    # Per-band launches over the shared rows_pad frame: every band's
    # chunk list covers every row block (>= 1 chunk each, flags
    # included), so each band's (m, d) is a full-frame PARTIAL with
    # ATTN_NEG/0 at rows it does not own, and partials merge by the
    # online-softmax rule exactly like tiles do. Bands whose metadata
    # proved the single-step property get the provably-one-pass reduce
    # body (no scratch, no flags).

    def attn_stats_tile_t(self, blk, gate_vals, logit_vals):
        if not isinstance(blk, BankedTile):
            return super().attn_stats_tile_t(blk, gate_vals, logit_vals)
        gv = self._chunk_vals(blk, gate_vals)
        zv = self._chunk_vals(blk, logit_vals)
        stats = []
        for band in blk.bands:
            meta, lr, _ = self._band_slices(blk, band)
            stats.append(_attn_call(
                meta, lr, gv[band.c0:band.c1], zv[band.c0:band.c1],
                None, None, op="attn_reduce", bm=band.bm,
                gr_blocks=band.gr_blocks, group=band.group,
                interpret=self.interpret,
                single_step=band.body == "single",
            ))
        return attn_merge_stats(stats)

    def attn_norm_tile_t(self, blk, gate_vals, logit_vals, m, d, out_dtype):
        if not isinstance(blk, BankedTile):
            return super().attn_norm_tile_t(
                blk, gate_vals, logit_vals, m, d, out_dtype
            )
        gv = self._chunk_vals(blk, gate_vals)
        zv = self._chunk_vals(blk, logit_vals)
        probs = []
        for band in blk.bands:
            meta, lr, _ = self._band_slices(blk, band)
            p = _attn_call(
                meta, lr, gv[band.c0:band.c1], zv[band.c0:band.c1],
                m, d, op="attn_norm", bm=band.bm,
                gr_blocks=band.gr_blocks, group=band.group,
                interpret=self.interpret,
            )
            probs.append(p.reshape(-1))
        return jnp.concatenate(probs).astype(out_dtype)


def make_banked_kernel(variant: KernelVariant | str, **kw) -> BankedPallasKernel:
    """Factory used by ``autotune/measure._build_kernel`` for variant
    candidates (and by anything holding only a variant id)."""
    return BankedPallasKernel(variant, **kw)
