"""The pod runner: one process per host, bench CLI semantics unchanged.

Promoted from ``scripts/run_pod.py`` (the script is now a thin wrapper)
so the pod wiring is a package capability:

* coordinator resolution + ``jax.distributed`` init via
  :mod:`distributed_sddmm_tpu.dist.init` (explicit flags > the
  ``DSDDMM_DIST_*`` env knobs > Cloud TPU auto-discovery);
* **per-worker admin surface**: ``DSDDMM_POD_ADMIN_BASE=P`` gives
  worker ``k`` its own ``/metrics``/``/healthz`` endpoint on port
  ``P + k`` (injected as ``--admin-port`` when the forwarded command is
  ``serve`` and none was passed);
* **per-worker trace shards**: a file-valued ``DSDDMM_TRACE`` is
  rewritten to its sibling ``.shards/`` directory before any worker
  traces, so each process writes its own shard (the PR 7 layout
  ``bench trace-merge`` consumes) instead of fighting over one file;
* **pod timeline merge**: worker 0 offset-aligns every shard back into
  one trace after the run (``DSDDMM_POD_TRACE_MERGE=0`` opts out).

Run THIS on every host of the pod, e.g. with::

    gcloud compute tpus tpu-vm ssh $TPU_NAME --worker=all \\
      --command="cd ~/distributed_sddmm_tpu && python scripts/run_pod.py \\
                 er 20 32 15d_fusion2 128 4 -o results.jsonl"
"""

from __future__ import annotations

import argparse
import os
import pathlib
from typing import Optional


def build_parser() -> argparse.ArgumentParser:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--coordinator", default=None,
                    help="host:port (omit on Cloud TPU: auto-discovered; "
                    "DSDDMM_DIST_COORDINATOR is the env equivalent)")
    ap.add_argument("--num-processes", type=int, default=None)
    ap.add_argument("--process-id", type=int, default=None)
    ap.add_argument("--dry-run", action="store_true",
                    help="print the resolved initialize()/bench invocation "
                    "and exit (testable without a pod)")
    ap.add_argument("bench_args", nargs=argparse.REMAINDER,
                    help="arguments forwarded to distributed_sddmm_tpu.bench")
    return ap


def _trace_shard_candidate() -> Optional[pathlib.Path]:
    """The shard directory the current ``DSDDMM_TRACE`` value implies
    (pure function of the env, no mutation): a ``.jsonl`` file spec
    maps to its ``.shards/`` sibling, a non-flag path IS the directory
    (the trace layer mkdirs it on first write), flag/off specs have
    none."""
    from distributed_sddmm_tpu.obs.trace import FLAG_VALUES

    spec = os.environ.get("DSDDMM_TRACE")
    if not spec or spec in FLAG_VALUES:
        return None
    p = pathlib.Path(spec)
    return p.with_suffix(".shards") if p.suffix == ".jsonl" else p


def _shardify_trace_env() -> Optional[pathlib.Path]:
    """Rewrite a file-valued ``DSDDMM_TRACE`` to its ``.shards/``
    sibling (every worker computes the same rewrite — pure function of
    the env), returning the shard dir for the end-of-run merge.
    Directory specs already shard naturally (per-process run-id files)
    and pass through unmutated."""
    shards = _trace_shard_candidate()
    if shards is None:
        return None
    if pathlib.Path(os.environ["DSDDMM_TRACE"]).suffix == ".jsonl":
        os.environ["DSDDMM_TRACE"] = str(shards)
    return shards


def _inject_admin_port(bench_args: list, process_index: int) -> list:
    base = os.environ.get("DSDDMM_POD_ADMIN_BASE")
    if (
        not base or int(base) <= 0
        or bench_args[:1] != ["serve"]
        or any(a == "--admin-port" or a.startswith("--admin-port=")
               for a in bench_args)
    ):
        return bench_args
    return [*bench_args, "--admin-port", str(int(base) + process_index)]


def main(argv=None) -> int:
    ap = build_parser()
    args = ap.parse_args(argv)

    from distributed_sddmm_tpu.dist.init import initialize, resolve_init_kwargs

    try:
        init_kwargs = resolve_init_kwargs(
            args.coordinator, args.num_processes, args.process_id
        )
    except ValueError as e:
        ap.error(str(e))
    if args.dry_run:
        # Validate the forwarded bench arguments parse, without touching
        # any backend or coordinator.
        from distributed_sddmm_tpu.bench.cli import build_parser as bench_parser

        bench_parser().parse_args(args.bench_args)
        print(  # cli-output
            f"dry-run ok: initialize({init_kwargs}) -> bench {args.bench_args}"
        )
        return 0

    # Snapshot prior-run shards BEFORE joining the init rendezvous: no
    # peer can write a trace until every worker (this one included) has
    # passed initialize, so everything in the dir now is a previous
    # run's — glob later and a fast peer's fresh shard would be
    # misclassified as stale.
    pre_shard_dir = _trace_shard_candidate()
    pre_existing = (
        {str(f) for f in pre_shard_dir.glob("*.jsonl")}
        if pre_shard_dir is not None and pre_shard_dir.is_dir() else set()
    )
    ctx = initialize(args.coordinator, args.num_processes, args.process_id)

    import jax

    if ctx.process_index == 0:
        print(  # cli-output
            f"pod up: {ctx.num_processes} hosts, "
            f"{jax.device_count()} chips ({jax.local_device_count()}/host)"
        )
    shard_dir = _shardify_trace_env() if ctx.is_multi_host else None
    bench_args = _inject_admin_port(list(args.bench_args), ctx.process_index)

    from distributed_sddmm_tpu.bench.cli import main as bench_main

    rc = bench_main(bench_args)

    if (
        shard_dir is not None
        and ctx.process_index == 0
        and os.environ.get("DSDDMM_POD_TRACE_MERGE", "1") not in ("0", "off")
    ):
        # Best-effort pod-timeline merge: a failed merge (straggler
        # shard mid-write) must not fail the run — the shards remain
        # and `bench trace-merge` re-runs offline.
        try:
            from distributed_sddmm_tpu.obs import trace as obs_trace
            from distributed_sddmm_tpu.obs import tracemerge

            obs_trace.disable()  # flush our own shard first
            # A merge over fewer shards than workers would SUCCEED on
            # an incomplete timeline and read as complete — wait for
            # every worker's shard to appear (they flush at exit;
            # stragglers get a bounded grace window), else leave the
            # shards for an offline `bench trace-merge`.
            import time

            def _this_runs_shards():
                return [
                    f for f in tracemerge.discover(shard_dir)
                    if str(f) not in pre_existing
                ]

            deadline = time.monotonic() + 30.0
            shards = _this_runs_shards()
            while (
                len(shards) < ctx.num_processes
                and time.monotonic() < deadline
            ):
                time.sleep(0.25)
                shards = _this_runs_shards()
            if len(shards) < ctx.num_processes:
                raise RuntimeError(
                    f"only {len(shards)} of {ctx.num_processes} worker "
                    "shards present; merge deferred to `bench "
                    "trace-merge`"
                )
            out, merged = tracemerge.write_merged(shards)
            print(f"pod trace merged: {out} "  # cli-output
                  f"({len(merged['begin']['shards'])} shards)")
        except Exception as e:  # noqa: BLE001
            from distributed_sddmm_tpu.obs import log as obs_log

            obs_log.warn("dist", "pod trace merge skipped",
                         error=f"{type(e).__name__}: {e}")
    return rc
