"""Offline structural gate for cross-host program structure.

This container's jax 0.4.x CPU backend rejects cross-process
``device_put``, so the multi-controller path cannot EXECUTE here — but
the repo's banking discipline (``codegen/hlo.py`` retarget pattern)
still proves the program *structure*: the fused SDDMM→SpMM pair is
AOT-compiled for a REAL 2-host v5e topology
(``jax.experimental.topologies``, no chips needed) and the compiled
HLO is scanned for collectives whose replica groups **span the host
boundary** — the property that makes the program a genuine multi-host
program rather than p copies of a local one. The committed
``MULTIHOST_HLO.json`` is this probe's banked record
(``tests/test_multihost_gate.py``).

Partition-id → host mapping: jit over a ``NamedSharding`` derives its
device assignment from the mesh's flat device order, so partition ``i``
executes on ``mesh.devices.flat[i]`` and its host is that device's
``process_index``. The report carries the whole mapping
(``device_processes``) so the committed record is self-describing.

Environment note (same as every other gate): on machines without TPU
instance metadata export ``TPU_SKIP_MDS_QUERY=1`` before first
jax/libtpu init or the topology lookup stalls in metadata retries.
"""

from __future__ import annotations

import json
import re

#: Collective ops whose attributes carry partition groups.
_COLLECTIVE_OPS = (
    "collective-permute-start", "collective-permute",
    "all-gather-start", "all-gather",
    "all-reduce-start", "all-reduce",
    "reduce-scatter", "all-to-all",
)

_PAIRS_RE = re.compile(r"source_target_pairs=\{((?:\{\d+,\d+\},?)+)\}")
_GROUPS_RE = re.compile(r"replica_groups=\{((?:\{[\d,]+\},?)*)\}")
_PAIR_RE = re.compile(r"\{(\d+),(\d+)\}")


def _groups_on_line(line: str) -> list[list[int]] | None:
    """Partition groups named on one HLO line: explicit
    ``source_target_pairs`` (each pair is a 2-group) or explicit
    ``replica_groups`` braces. None when the line carries neither (or
    an iota-form group this scanner does not decode — callers count
    those as unparsed rather than guessing)."""
    m = _PAIRS_RE.search(line)
    if m:
        return [[int(a), int(b)] for a, b in _PAIR_RE.findall(m.group(1))]
    m = _GROUPS_RE.search(line)
    if m:
        groups = []
        for grp in re.findall(r"\{([\d,]+)\}", m.group(1)):
            groups.append([int(x) for x in grp.split(",") if x])
        # ``replica_groups={}`` is HLO's implicit ONE-group-of-ALL form
        # (e.g. a global all-reduce) — every participant in one group,
        # not "no groups"; the caller substitutes the full device list.
        return groups if groups else [[]]
    if "replica_groups=[" in line:
        return None  # iota form — report as unparsed
    return None


def scan_cross_host(hlo: str, device_processes: list[int]) -> dict:
    """Scan compiled HLO for collectives and classify each by whether
    any of its partition groups spans two processes.

    ``device_processes[i]`` is the host (process index) of partition
    ``i``. Returns per-op counts plus the total
    ``cross_host_collectives`` the gate asserts on, and
    ``unparsed_group_lines`` (collective lines whose group syntax the
    scanner does not decode — nonzero means the gate's evidence is
    incomplete and the committed record must say so).
    """
    per_op: dict[str, dict] = {}
    unparsed = 0
    for line in hlo.splitlines():
        op = next((o for o in _COLLECTIVE_OPS if f" {o}(" in line
                   or line.lstrip().startswith(f"%{o}")
                   or f"= {o}" in line or f"{o}(" in line), None)
        if op is None:
            continue
        # -start/-done pairs: count the start only (the done names no
        # groups); plain "collective-permute" matches before "-start"
        # is tried, so normalize on the base op name.
        base = op.replace("-start", "")
        if "-done(" in line:
            continue
        groups = _groups_on_line(line)
        if groups is None:
            if "replica_groups=[" in line:
                unparsed += 1
            continue
        # [[]] is the implicit all-participants group (see
        # _groups_on_line): it spans exactly the processes of the whole
        # device list.
        groups = [
            grp if grp else list(range(len(device_processes)))
            for grp in groups
        ]
        entry = per_op.setdefault(
            base, {"count": 0, "cross_host": 0, "groups": None}
        )
        entry["count"] += 1
        cross = any(
            len({device_processes[i] for i in grp}) > 1 for grp in groups
        )
        if cross:
            entry["cross_host"] += 1
        if entry["groups"] is None:
            entry["groups"] = groups
    return {
        "per_op": per_op,
        "cross_host_collectives": sum(
            e["cross_host"] for e in per_op.values()
        ),
        "unparsed_group_lines": unparsed,
    }


def multihost_hlo_report(
    topology_name: str = "v5e:2x4",
    log_m: int = 11,
    edge_factor: int = 4,
    R: int = 128,
    c: int = 2,
    output_file: str | None = None,
) -> dict:
    """Compile the fused-pair program for a 2-host v5e topology and
    report which collectives cross the host boundary.

    ``c=2`` puts the replication axis (all-gather + reduce-scatter)
    across the 4×2 grid's fast dimension; with the topology's host-major
    device order that is exactly the axis whose replica groups pair one
    device per host — the cross-host evidence. The rows ring
    (collective-permute) stays intra-host at this shape, which the
    report records too: the gate asserts both that cross-host
    collectives exist AND that the boundary landed where the layout
    math says it should.
    """
    import jax

    from distributed_sddmm_tpu.codegen.hlo import _aot_compile_ops, _topology
    from distributed_sddmm_tpu.common import MatMode
    from distributed_sddmm_tpu.parallel.dense_shift_15d import DenseShift15D
    from distributed_sddmm_tpu.parallel.mesh import process_spans
    from distributed_sddmm_tpu.utils.coo import HostCOO

    topo = _topology(topology_name, len(jax.devices()))

    S = HostCOO.rmat(log_m=log_m, edge_factor=edge_factor, seed=0)
    alg = DenseShift15D(S, R=R, c=c, fusion_approach=2)
    vals = alg.like_s_values(1.0)
    args = (
        alg.dummy_initialize(MatMode.A),
        alg.dummy_initialize(MatMode.B),
        *alg._tile_args(alg.S_tiles, vals),
    )
    hlo = _aot_compile_ops(alg, args, topo, ("fused",))["fused"]
    # Partition i executes on mesh.devices.flat[i] (module doc).
    device_processes = [
        int(d.process_index) for d in alg.grid.mesh.devices.flat
    ]
    scan = scan_cross_host(hlo, device_processes)
    record = {
        "experiment": "multihost-hlo",
        "topology": topology_name,
        "p": alg.p,
        "c": c,
        "n_hosts": len(set(device_processes)),
        "M": S.M,
        "nnz": S.nnz,
        "R": R,
        "device_processes": device_processes,
        "axis_spans_hosts": process_spans(alg.grid),
        "collectives": scan["per_op"],
        "cross_host_collectives": scan["cross_host_collectives"],
        "unparsed_group_lines": scan["unparsed_group_lines"],
        "is_scheduled": "is_scheduled=true" in hlo,
    }
    if output_file:
        # non-atomic-ok: append-only record stream (the -o contract).
        with open(output_file, "a") as f:
            f.write(json.dumps(record) + "\n")
    return record
