"""Elastic pod membership on the resilience layer.

A lost worker must turn into **checkpoint scan-back recovery at reduced
p**, not a dead run. This module is the controller-of-controllers: an
:class:`ElasticSupervisor` launches one OS process per pod slot,
watches for deaths (the resilience layer's ``kill`` faults exit with
``faults.KILL_EXIT_CODE``; real crashes exit nonzero or die on a
signal), and on loss relaunches the survivors' work as a new
*generation* at reduced process count. Recovery workers resume from the
shared :class:`~distributed_sddmm_tpu.resilience.checkpoint.
CheckpointStore` via its scan-back ladder — the supervisor passes no
state, only identity: generation number, new ``p``, and which fixed
data shards each worker now owns.

Shard-vs-worker split: the DATA partition is fixed at the original pod
size (``nshards``), independent of the live worker count — worker ``w``
of a ``live_p``-worker generation owns shards ``{s : s % live_p == w}``.
A 2-worker run that loses worker 1 recovers as a 1-worker generation
owning both shards, resuming shard 1 from whatever step its dead owner
last checkpointed (scan-back) and shard 0 from its own completed
checkpoints — the final state is bit-identical to an uninterrupted run
because the checkpoint store round-trips float bits and the per-shard
step programs are deterministic.

Fault plans and recovery: firing is a pure function of (seed, spec,
site, call#) *per process*, so a relaunched worker would re-trigger the
very kill that felled its predecessor. Recovery generations therefore
drop ``DSDDMM_FAULTS`` by default (``drop_faults_on_recovery``) — the
semantic being modeled is "the faulty host left the pod", not "the
fault chases the work".
"""

from __future__ import annotations

import dataclasses
import json
import os
import socket
import subprocess
import sys
import time
from typing import Callable, Optional

from distributed_sddmm_tpu.obs import log as obs_log
from distributed_sddmm_tpu.resilience.faults import KILL_EXIT_CODE


def free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def spawn_process(argv: list, env: Optional[dict] = None,
                  cwd: Optional[str] = None) -> subprocess.Popen:
    """Spawn one supervised child with temp-file stdout/stderr.

    Temp files, not PIPEs: supervisors here do not drain output until
    exit, and a chatty worker (``DSDDMM_LOG=debug`` writes structured
    logs to stderr) would fill a ~64KB pipe buffer, block in write(),
    and read as hung/lost. Pair with :func:`collect_output`. Shared by
    :class:`ElasticSupervisor` and the fleet manager
    (``fleet/manager.py``) so both spawn paths have the same hang-proof
    discipline.
    """
    import tempfile

    out_f = tempfile.TemporaryFile(mode="w+")
    err_f = tempfile.TemporaryFile(mode="w+")
    proc = subprocess.Popen(
        argv, stdout=out_f, stderr=err_f, text=True, env=env, cwd=cwd,
    )
    proc._elastic_out, proc._elastic_err = out_f, err_f
    return proc


def collect_output(proc: subprocess.Popen) -> tuple[str, str]:
    """Read back (and close) a :func:`spawn_process` child's captured
    stdout/stderr. Call once, after exit."""
    out = err = ""
    for fh, slot in ((proc._elastic_out, "out"), (proc._elastic_err, "err")):
        try:
            fh.seek(0)
            text = fh.read()
        finally:
            fh.close()
        if slot == "out":
            out = text
        else:
            err = text
    return out, err


def last_json_line(text: str) -> Optional[dict]:
    """The worker-record convention: a child's result is the LAST line
    of stdout that parses as JSON (banners/log noise above it are
    ignored). None when no line parses."""
    for line in reversed((text or "").strip().splitlines()):
        try:
            doc = json.loads(line)
        except ValueError:
            continue
        if isinstance(doc, dict):
            return doc
    return None


@dataclasses.dataclass
class GenerationResult:
    """One generation's outcome.

    ``lost`` holds workers that died on their OWN (fault kill, crash);
    ``reaped`` holds survivors the supervisor killed after the grace
    window (blocked on a barrier their dead peer never reached, or a
    generation timeout). Only ``lost`` shrinks the next generation's
    ``p`` — a reaped worker's host is healthy and must stay in the pod.
    """

    generation: int
    live_p: int
    returncodes: list
    records: list  # last-JSON-line per worker (None when unparsable)
    lost: list    # workers that died on their own
    reaped: list = dataclasses.field(default_factory=list)

    @property
    def ok(self) -> bool:
        return (not self.lost and not self.reaped
                and all(rc == 0 for rc in self.returncodes))


@dataclasses.dataclass
class ElasticResult:
    generations: list
    #: True only when a WORKER LOSS drove a reduced-p recovery
    #: generation — a pure-timeout retry at unchanged p is not a
    #: recovery (no membership change happened).
    recovered: bool

    @property
    def ok(self) -> bool:
        return bool(self.generations) and self.generations[-1].ok

    @property
    def records(self) -> list:
        return self.generations[-1].records if self.generations else []


class ElasticSupervisor:
    """Launch, watch, and elastically relaunch a pod's worker processes.

    ``worker_argv(generation, live_p, worker, port)`` builds one
    worker's command line (the test drill points it at
    ``tests/_mp_worker.py --elastic``; a real pod points it at
    ``scripts/run_pod.py``). ``worker_env(generation, live_p, worker)``
    overlays per-worker environment — the hook that aims a ``kill``
    fault at ONE worker instead of the whole (deterministically
    identical) fleet.
    """

    def __init__(
        self,
        worker_argv: Callable[[int, int, int, int], list],
        nprocs: int,
        *,
        worker_env: Optional[Callable[[int, int, int], dict]] = None,
        max_recoveries: int = 1,
        generation_timeout_s: float = 300.0,
        grace_s: float = 10.0,
        drop_faults_on_recovery: bool = True,
        on_loss: Optional[Callable[[GenerationResult], None]] = None,
        cwd: Optional[str] = None,
    ):
        if nprocs < 1:
            raise ValueError("nprocs must be >= 1")
        self.worker_argv = worker_argv
        self.nprocs = nprocs
        self.worker_env = worker_env
        self.max_recoveries = max_recoveries
        self.generation_timeout_s = generation_timeout_s
        self.grace_s = grace_s
        self.drop_faults_on_recovery = drop_faults_on_recovery
        #: Called with the failed GenerationResult before the recovery
        #: generation launches — the re-provisioning hook (and the test
        #: drill's lever for corrupting a checkpoint pointer so recovery
        #: demonstrably rides the scan-back ladder).
        self.on_loss = on_loss
        self.cwd = cwd

    # ------------------------------------------------------------------ #

    def _spawn(self, generation: int, live_p: int) -> list:
        port = free_port()
        procs = []
        for w in range(live_p):
            env = dict(os.environ)
            if generation > 0 and self.drop_faults_on_recovery:
                env.pop("DSDDMM_FAULTS", None)
            if self.worker_env is not None:
                env.update(self.worker_env(generation, live_p, w))
            procs.append(spawn_process(
                [sys.executable, *self.worker_argv(
                    generation, live_p, w, port
                )],
                env=env, cwd=self.cwd,
            ))
        return procs

    def _watch(self, procs: list, generation: int, live_p: int
               ) -> GenerationResult:
        """Wait for the generation, detecting a death promptly: once any
        worker exits nonzero, survivors get ``grace_s`` to finish (their
        local work may be complete) and are then killed — a worker
        blocked on a barrier its dead peer will never reach must not
        stall recovery for the full generation timeout."""
        deadline = time.monotonic() + self.generation_timeout_s
        death_seen_at = None
        reaped: set = set()
        while True:
            rcs = [p.poll() for p in procs]
            if all(rc is not None for rc in rcs):
                break
            now = time.monotonic()
            if death_seen_at is None and any(
                rc is not None and rc != 0 for rc in rcs
            ):
                death_seen_at = now
            if now > deadline or (
                death_seen_at is not None and now > death_seen_at + self.grace_s
            ):
                for w, p in enumerate(procs):
                    if p.poll() is None:
                        reaped.add(w)
                        p.kill()
            time.sleep(0.05)
        records, rcs = [], []
        lost = []
        for w, p in enumerate(procs):
            p.wait()
            out, err = collect_output(p)
            rc = p.returncode
            rcs.append(rc)
            records.append(last_json_line(out))
            if rc != 0 and w not in reaped:
                lost.append(w)
                obs_log.warn(
                    "elastic", "worker lost",
                    generation=generation, worker=w, rc=rc,
                    killed=rc == KILL_EXIT_CODE,
                    stderr_tail=(err or "")[-300:],
                )
            elif w in reaped:
                obs_log.warn(
                    "elastic", "survivor reaped (blocked past grace)",
                    generation=generation, worker=w, rc=rc,
                )
        return GenerationResult(
            generation=generation, live_p=live_p, returncodes=rcs,
            records=records, lost=lost, reaped=sorted(reaped),
        )

    def run(self) -> ElasticResult:
        """Run to completion or exhaustion: generation 0 at full
        ``nprocs``; each loss spawns the next generation at
        ``live_p - len(lost)`` (floor 1) until a generation completes
        clean or ``max_recoveries`` is spent."""
        generations = []
        live_p = self.nprocs
        for generation in range(self.max_recoveries + 1):
            from distributed_sddmm_tpu.obs import trace as obs_trace

            obs_trace.event(
                "elastic:generation", generation=generation, live_p=live_p,
            )
            result = self._watch(
                self._spawn(generation, live_p), generation, live_p
            )
            generations.append(result)
            if result.ok:
                break
            if self.on_loss is not None:
                self.on_loss(result)
            if generation >= self.max_recoveries:
                # Recoveries exhausted — no further generation launches;
                # logging "recovering" here would claim one is in flight.
                break
            # Only SELF-dead workers shrink p: a reaped survivor's host
            # is healthy and rejoins the next generation (a pure
            # timeout, everyone reaped, retries at the same p).
            live_p = max(live_p - len(result.lost), 1)
            obs_log.warn(
                "elastic",
                "recovering at reduced p" if result.lost
                else "retrying at unchanged p after stall",
                generation=generation + 1, live_p=live_p,
                lost=result.lost, reaped=result.reaped,
            )
        return ElasticResult(
            generations=generations,
            recovered=any(g.lost for g in generations[:-1]),
        )


def run_elastic(worker_argv, nprocs: int, **kw) -> ElasticResult:
    """One-call form of :class:`ElasticSupervisor`."""
    return ElasticSupervisor(worker_argv, nprocs, **kw).run()
